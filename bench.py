"""Headline benchmark: 1-D complex FFT, N = 2^20, single TPU chip.

Measures the framework's flagship path (the composed two-kernel Pallas
pi-FFT on the shared (R, Q, 128) layout, pi-layout output — gather
excluded exactly as the reference excludes it from timing) against TWO
baselines on this host and prints ONE JSON line:

    {"metric": ..., "value": GFLOP/s, "unit": ...,
     "vs_baseline": ..., "vs_xla_fft": ..., "xla_fft_ms": ...}

* vs_baseline — wall-clock speedup over the native C backend at the same
  N (BASELINE.md north star: >= 10x; GFLOP/s uses the standard
  5 N log2 N FFT flop count).
* vs_xla_fft — wall-clock speedup over `jnp.fft.fft` ON THE SAME CHIP at
  the same N: the strongest same-hardware comparison (XLA's own FFT is
  the production alternative a user would otherwise call).

Measurement method: loop-slope (utils/timing.py) — on the axon TPU relay
block_until_ready is not a real barrier, so the FFT is iterated K times
inside one jitted fori_loop ending in a scalar fetch, at two K values;
the per-FFT time is the slope and the ~100 ms relay overhead cancels.
On hardware where block_until_ready is honest the same method simply
measures with less noise.
"""

import json
import sys

import numpy as np

N = 1 << 20


def measure_tpu_ms() -> float:
    import jax
    import jax.numpy as jnp

    from cs87project_msolano2_tpu.ops.pallas_fft import (
        fft_pi_layout_pallas2,
        fft_pi_layout_pallas_fused,
        fft_pi_layout_pallas_mf,
        fft_pi_layout_pallas_rql,
    )
    from cs87project_msolano2_tpu.utils.timing import loop_slope_ms

    # (impl, tile_or_R, cb, tail): rql = the retiling-free (R, Q, 128)
    # composed path (tile_or_R = tile).  tail=256 moves two VPU stage
    # traversals onto the MXU as a 2x2-blocked 256-point DIF matmul; the
    # tail matmul runs in SPLIT3 precision (3-pass bf16 error split,
    # rel err ~4e-6 — pallas_fft.SPLIT3), which round-4 measurements
    # showed cuts the tile pass by ~2x vs Precision.HIGHEST (XLA's
    # 6-pass f32 emulation was the single largest cost in the whole
    # transform).  rql fastest measured with split3: 0.081-0.092 ms at
    # tile=2^16 cb=2^12..13 (~1180-1300 GF), rel_err 3.9e-06 vs numpy.
    #
    # The matmul-funnel path (fft_pi_layout_pallas_mf) is NOT in the
    # config list: round 3's mf configs OOM'd scoped VMEM on hardware
    # (24.12M vs the 16M limit); round 4 fixed it with the separable
    # A/B2 twiddle factorization (dft_funnel_factors) and a VMEM guard,
    # but the surviving lowerable shape (R=128, cb=1024 — Mosaic stack
    # intermediates force 1 MB blocks) measures 0.108 ms (split3) vs
    # rql's 0.089 ms at N=2^20: correct and supported (tests/
    # test_pallas.py), just not the headline.
    # (the tile plan keeps radix-8 stages off sub-2-row slabs: an 8-way
    # interleave of 1-row slabs measured 3x slower than finishing the
    # last pre-tail levels radix-4 — with that guard tail=128 measures
    # ~0.085 ms, on par with tail=256)
    # fused = the round-5 single-pallas_call path (VMEM scratch carries
    # the transform between the long-range and tile phases, so the rql
    # intermediate's ~16 MB HBM round trip never happens — see
    # _fused_fft_kernel); its cb slot holds qb (columns per phase-A
    # step).
    # measured 2026-07-31 (v5e, same-session comparisons): fused t16
    # qb32 unaliased = 78.8-79.3 us (1323-1331 GF) vs rql t16 = 91-98 us
    # in the same sessions — but that config sits AT the 16 MB
    # scoped-VMEM cliff and compiles nondeterministically (16.70-16.72M
    # observed), hence the aliased variant (reliable, 94-98 us) and rql
    # as fallbacks; smaller-tile fused variants measured strictly slower
    # (t15 qb32 = 109 us, t14 = 167 us).
    configs = (
        ("fused", 1 << 16, 32, 256),
        ("fused-alias", 1 << 16, 32, 256),
        ("fused-alias", 1 << 16, 64, 256),
        ("rql", 1 << 16, 1 << 13, 256),
        ("rql", 1 << 16, 1 << 12, 256),
        ("rql", 1 << 15, 1 << 13, 256),
        ("rql", 1 << 16, 1 << 13, 128),
        ("two-kernel", 1 << 16, 1 << 14, 128),
    )

    key = jax.random.PRNGKey(0)
    xr = jax.random.normal(key, (N,), jnp.float32)
    xi = jax.random.normal(jax.random.fold_in(key, 1), (N,), jnp.float32)

    inv_rn = np.float32(1.0 / np.sqrt(N))  # keep loop iterates in range
    best = float("inf")
    for impl, tile, cb, tail in configs:
        try:
            def body(c, impl=impl, t=tile, cb=cb, tail=tail):
                if impl.startswith("fused"):
                    yr, yi = fft_pi_layout_pallas_fused(
                        c[0], c[1], tile=t, qb=cb, tail=tail,
                        alias_io=impl.endswith("alias"))
                elif impl == "mf":
                    yr, yi = fft_pi_layout_pallas_mf(
                        c[0], c[1], R=t, cb=cb, tail=tail)
                elif impl == "rql":
                    yr, yi = fft_pi_layout_pallas_rql(
                        c[0], c[1], tile=t, cb=cb, tail=tail)
                else:
                    yr, yi = fft_pi_layout_pallas2(c[0], c[1], tile=t, cb=cb)
                return yr * inv_rn, yi * inv_rn

            ms = loop_slope_ms(body, (xr, xi), k1=64, k2=1024, reps=5,
                               min_delta_ms=100.0, cache=False)
            best = min(best, ms)
        except Exception as e:  # a config failing to compile is not fatal
            print(f"# {impl} tile={tile} cb={cb} tail={tail} failed: "
                  f"{type(e).__name__}", file=sys.stderr)
    if not np.isfinite(best):
        raise RuntimeError("no benchmark configuration compiled")
    return best


def measure_xla_fft_ms():
    """jnp.fft.fft on the same chip at the same N — the same-hardware
    comparison VERDICT.md round 2 demanded.  The loop body carries
    complex state (no per-iteration plane split/merge) so only the FFT
    itself plus one scaling is timed — the same epilogue the Pallas body
    pays.  Falls back to the unrolled slope if the FFT custom-call
    cannot lower inside a fori_loop; returns None (metric omitted) if it
    cannot be measured at all rather than losing the other results."""
    import jax
    import jax.numpy as jnp

    from cs87project_msolano2_tpu.utils.timing import (
        loop_slope_ms,
        unrolled_slope_ms,
    )

    key = jax.random.PRNGKey(2)
    xr = jax.random.normal(key, (N,), jnp.float32)
    xi = jax.random.normal(jax.random.fold_in(key, 1), (N,), jnp.float32)
    inv_rn = np.complex64(1.0 / np.sqrt(N))

    # The relay cannot pass complex64 across the program ABI (eager
    # complex ops, complex program inputs, and complex While carries are
    # all Unimplemented), so the loop body must carry float planes and
    # pay a complex-merge + re/im-split every iteration.  That epilogue
    # is NOT the XLA FFT's cost — charging it would overstate our
    # speedup — so it is measured separately with the same method (the
    # identical elementwise chain minus the fft) and subtracted.
    inv = np.float32(inv_rn.real)

    def body_fft(c):
        y = jnp.fft.fft(c[0] + 1j * c[1])
        return jnp.real(y) * inv, jnp.imag(y) * inv

    def body_epilogue(c):
        y = c[0] + 1j * c[1]
        return jnp.real(y) * inv, jnp.imag(y) * inv

    try:
        raw = loop_slope_ms(body_fft, (xr, xi), k1=64, k2=1024, reps=5,
                            min_delta_ms=100.0, cache=False)
    except Exception as e:
        # some backends cannot lower the FFT custom-call inside a While
        # body — statically unroll instead (modest k2: program size and
        # remote-compile time grow linearly with the unroll)
        print(f"# xla fft under fori_loop failed ({type(e).__name__}); "
              "trying unrolled slope", file=sys.stderr)
        try:
            raw = unrolled_slope_ms(body_fft, (xr, xi), k1=8, k2=64,
                                    reps=7, min_delta_ms=20.0, max_k=256,
                                    cache=False)
        except Exception as e2:
            print(f"# xla fft not measurable on this backend "
                  f"({type(e2).__name__}); omitting vs_xla_fft",
                  file=sys.stderr)
            return None
    try:
        epilogue = loop_slope_ms(body_epilogue, (xr, xi), k1=64, k2=1024,
                                 reps=5, min_delta_ms=40.0, cache=False)
    except Exception as e:
        print(f"# epilogue not resolvable ({type(e).__name__}); "
              "vs_xla_fft conservatively uncorrected", file=sys.stderr)
        epilogue = 0.0
    # the epilogue is a small fraction of the FFT; if its measurement
    # came back implausibly large (relay noise), don't let it eat the
    # result — cap the correction at half the raw time
    return max(raw - epilogue, raw * 0.5)


def measure_large_n_ms() -> dict:
    """Large-n reach rows (the reference's pthreads analysis goes to
    n=2^24): rql wall time at 2^22 and 2^24 with the VMEM-aware default
    cb.  Best-effort — a failure drops the fields, not the bench."""
    import jax
    import jax.numpy as jnp

    from cs87project_msolano2_tpu.ops.pallas_fft import fft_pi_layout_pallas_rql
    from cs87project_msolano2_tpu.utils.timing import loop_slope_ms

    out = {}
    for logn in (22, 24):
        nn = 1 << logn
        try:
            key = jax.random.PRNGKey(3)
            xr = jax.random.normal(key, (nn,), jnp.float32)
            xi = jax.random.normal(jax.random.fold_in(key, 1), (nn,),
                                   jnp.float32)
            inv = np.float32(1.0 / np.sqrt(nn))

            def body(c):
                yr, yi = fft_pi_layout_pallas_rql(c[0], c[1], tile=1 << 16,
                                                  tail=256)
                return yr * inv, yi * inv

            ms = loop_slope_ms(body, (xr, xi), k1=16, k2=256, reps=5,
                               min_delta_ms=100.0, cache=False)
            out[f"n2^{logn}_ms"] = round(ms, 4)
            out[f"n2^{logn}_gflops"] = round(
                5.0 * nn * np.log2(nn) / (ms * 1e-3) / 1e9, 1)
        except Exception as e:
            print(f"# large-n 2^{logn} not measured: {type(e).__name__}",
                  file=sys.stderr)
    return out


def measure_c_baseline_ms() -> float:
    from cs87project_msolano2_tpu.backends.cpu import num_cores
    from cs87project_msolano2_tpu.backends.registry import get_backend
    from cs87project_msolano2_tpu.cli import make_input

    p = 1
    while p * 2 <= num_cores():
        p *= 2
    x = make_input(N, seed=0)
    return get_backend("cpu").run(x, p, reps=3).total_ms


def main() -> int:
    tpu_ms = measure_tpu_ms()
    xla_ms = measure_xla_fft_ms()
    large = measure_large_n_ms()
    c_ms = measure_c_baseline_ms()
    gflops = 5.0 * N * np.log2(N) / (tpu_ms * 1e-3) / 1e9
    record = {
        "metric": "fft1d_n2^20_complex64_gflops",
        "value": round(gflops, 1),
        "unit": "GFLOP/s",
        "vs_baseline": round(c_ms / tpu_ms, 1),
    }
    if xla_ms is not None:
        record["vs_xla_fft"] = round(xla_ms / tpu_ms, 2)
        record["xla_fft_ms"] = round(xla_ms, 4)
    record.update(large)
    print(json.dumps(record))
    return 0


if __name__ == "__main__":
    sys.exit(main())
