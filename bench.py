"""Headline benchmark: 1-D complex FFT, N = 2^20, single TPU chip.

Measures the framework's flagship path (XLA long-range stages + Pallas
VMEM tile kernel, pi layout — gather excluded exactly as the reference
excludes it from timing) against the native C baseline running on this
host, and prints ONE JSON line:

    {"metric": ..., "value": GFLOP/s, "unit": ..., "vs_baseline": speedup}

vs_baseline is wall-clock speedup over the C backend at the same N
(BASELINE.md north star: >= 10x; GFLOP/s uses the standard 5 N log2 N
FFT flop count).
"""

import json
import sys
import time

import numpy as np

N = 1 << 20
TILES = (1 << 14, 1 << 15, 1 << 16)
REPS = 10


def measure_tpu_ms() -> float:
    import jax
    import jax.numpy as jnp

    from cs87project_msolano2_tpu.ops.pallas_fft import fft_pi_layout_pallas

    rng = np.random.default_rng(0)
    xr = jax.device_put(jnp.asarray(rng.standard_normal(N).astype(np.float32)))
    xi = jax.device_put(jnp.asarray(rng.standard_normal(N).astype(np.float32)))

    best = float("inf")
    for tile in TILES:
        try:
            f = jax.jit(lambda a, b, t=tile: fft_pi_layout_pallas(a, b, tile=t))
            jax.block_until_ready(f(xr, xi))  # compile + warm
            for _ in range(REPS):
                t0 = time.perf_counter()
                jax.block_until_ready(f(xr, xi))
                best = min(best, (time.perf_counter() - t0) * 1e3)
        except Exception as e:  # a tile config failing to compile is not fatal
            print(f"# tile={tile} failed: {type(e).__name__}", file=sys.stderr)
    if not np.isfinite(best):
        raise RuntimeError("no tile configuration compiled")
    return best


def measure_c_baseline_ms() -> float:
    from cs87project_msolano2_tpu.backends.cpu import num_cores
    from cs87project_msolano2_tpu.backends.registry import get_backend
    from cs87project_msolano2_tpu.cli import make_input

    p = 1
    while p * 2 <= num_cores():
        p *= 2
    x = make_input(N, seed=0)
    return get_backend("cpu").run(x, p, reps=3).total_ms


def main() -> int:
    tpu_ms = measure_tpu_ms()
    c_ms = measure_c_baseline_ms()
    gflops = 5.0 * N * np.log2(N) / (tpu_ms * 1e-3) / 1e9
    print(
        json.dumps(
            {
                "metric": "fft1d_n2^20_complex64_gflops",
                "value": round(gflops, 1),
                "unit": "GFLOP/s",
                "vs_baseline": round(c_ms / tpu_ms, 1),
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
