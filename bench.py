"""Headline benchmark: 1-D complex FFT, N = 2^20, single TPU chip.

Measures the framework's flagship path (pi-layout output — gather
excluded exactly as the reference excludes it from timing) against TWO
baselines on this host and prints ONE JSON line:

    {"metric": ..., "value": GFLOP/s, "unit": ...,
     "vs_baseline": ..., "vs_xla_fft": ..., "xla_fft_ms": ..., "plan": ...}

* vs_baseline — wall-clock speedup over the native C backend at the same
  N (BASELINE.md north star: >= 10x; GFLOP/s uses the standard
  5 N log2 N FFT flop count).
* vs_xla_fft — wall-clock speedup over `jnp.fft.fft` ON THE SAME CHIP at
  the same N: the strongest same-hardware comparison (XLA's own FFT is
  the production alternative a user would otherwise call).

Kernel selection goes through the plan subsystem
(cs87project_msolano2_tpu.plans): `plans.tune` races the shared
candidate ladder (plans/ladder.py — the single source of truth this file
used to own) ONCE per (device kind, n, layout) key and persists the
winner, so a warm session reaches its first timed FFT on a cache hit
with no re-race; this file just tunes-or-loads and reports the winning
plan.

Measurement method: loop-slope (utils/timing.py) — on the axon TPU relay
block_until_ready is not a real barrier, so the FFT is iterated K times
inside one jitted fori_loop ending in a scalar fetch, at two K values;
the per-FFT time is the slope and the ~100 ms relay overhead cancels.
On hardware where block_until_ready is honest the same method simply
measures with less noise.
"""

import json
import sys

import numpy as np

N = 1 << 20


def measure_tpu_ms() -> tuple:
    """(ms, plan) for the flagship key, via the plans subsystem's shared
    measurement policy (tuned-race ms reused, cached plans re-timed with
    the tuner's own timer, a non-compiling cached winner re-raced)."""
    from cs87project_msolano2_tpu import plans

    return plans.measured_ms(plans.make_key(N, layout="pi"))


def measure_xla_fft_ms():
    """jnp.fft.fft on the same chip at the same N — the same-hardware
    comparison VERDICT.md round 2 demanded.  The loop body carries
    complex state (no per-iteration plane split/merge) so only the FFT
    itself plus one scaling is timed — the same epilogue the Pallas body
    pays.  Falls back to the unrolled slope if the FFT custom-call
    cannot lower inside a fori_loop; returns None (metric omitted) if it
    cannot be measured at all rather than losing the other results."""
    import jax
    import jax.numpy as jnp

    from cs87project_msolano2_tpu.utils.timing import (
        loop_slope_ms,
        unrolled_slope_ms,
    )

    key = jax.random.PRNGKey(2)
    xr = jax.random.normal(key, (N,), jnp.float32)
    xi = jax.random.normal(jax.random.fold_in(key, 1), (N,), jnp.float32)
    inv_rn = np.complex64(1.0 / np.sqrt(N))

    # The relay cannot pass complex64 across the program ABI (eager
    # complex ops, complex program inputs, and complex While carries are
    # all Unimplemented), so the loop body must carry float planes and
    # pay a complex-merge + re/im-split every iteration.  That epilogue
    # is NOT the XLA FFT's cost — charging it would overstate our
    # speedup — so it is measured separately with the same method (the
    # identical elementwise chain minus the fft) and subtracted.
    inv = np.float32(inv_rn.real)

    def body_fft(c):
        y = jnp.fft.fft(c[0] + 1j * c[1])
        return jnp.real(y) * inv, jnp.imag(y) * inv

    def body_epilogue(c):
        y = c[0] + 1j * c[1]
        return jnp.real(y) * inv, jnp.imag(y) * inv

    try:
        raw = loop_slope_ms(body_fft, (xr, xi), k1=64, k2=1024, reps=5,
                            min_delta_ms=100.0, cache=False)
    except Exception as e:
        # some backends cannot lower the FFT custom-call inside a While
        # body — statically unroll instead (modest k2: program size and
        # remote-compile time grow linearly with the unroll)
        print(f"# xla fft under fori_loop failed ({type(e).__name__}); "
              "trying unrolled slope", file=sys.stderr)
        try:
            raw = unrolled_slope_ms(body_fft, (xr, xi), k1=8, k2=64,
                                    reps=7, min_delta_ms=20.0, max_k=256,
                                    cache=False)
        except Exception as e2:
            print(f"# xla fft not measurable on this backend "
                  f"({type(e2).__name__}); omitting vs_xla_fft",
                  file=sys.stderr)
            return None
    try:
        epilogue = loop_slope_ms(body_epilogue, (xr, xi), k1=64, k2=1024,
                                 reps=5, min_delta_ms=40.0, cache=False)
    except Exception as e:
        print(f"# epilogue not resolvable ({type(e).__name__}); "
              "vs_xla_fft conservatively uncorrected", file=sys.stderr)
        epilogue = 0.0
    # the epilogue is a small fraction of the FFT; if its measurement
    # came back implausibly large (relay noise), don't let it eat the
    # result — cap the correction at half the raw time
    return max(raw - epilogue, raw * 0.5)


def measure_large_n_ms() -> dict:
    """Large-n reach rows (the reference's pthreads analysis goes to
    n=2^24): per-key plans at 2^22 and 2^24 — each n gets the plan tuned
    (or statically chosen) for ITS key, not the flagship's shape.
    Best-effort — a failure drops the fields, not the bench."""
    from cs87project_msolano2_tpu import plans

    out = {}
    for logn in (22, 24):
        nn = 1 << logn
        try:
            ms, _ = plans.measured_ms(plans.make_key(nn, layout="pi"))
            out[f"n2^{logn}_ms"] = round(ms, 4)
            out[f"n2^{logn}_gflops"] = round(
                5.0 * nn * np.log2(nn) / (ms * 1e-3) / 1e9, 1)
        except Exception as e:
            print(f"# large-n 2^{logn} not measured: {type(e).__name__}",
                  file=sys.stderr)
    return out


def measure_c_baseline_ms() -> float:
    from cs87project_msolano2_tpu.backends.cpu import num_cores
    from cs87project_msolano2_tpu.backends.registry import get_backend
    from cs87project_msolano2_tpu.cli import make_input

    p = 1
    while p * 2 <= num_cores():
        p *= 2
    x = make_input(N, seed=0)
    return get_backend("cpu").run(x, p, reps=3).total_ms


def main() -> int:
    tpu_ms, plan = measure_tpu_ms()
    xla_ms = measure_xla_fft_ms()
    large = measure_large_n_ms()
    c_ms = measure_c_baseline_ms()
    gflops = 5.0 * N * np.log2(N) / (tpu_ms * 1e-3) / 1e9
    record = {
        "metric": "fft1d_n2^20_complex64_gflops",
        "value": round(gflops, 1),
        "unit": "GFLOP/s",
        "vs_baseline": round(c_ms / tpu_ms, 1),
        "plan": plan.describe(),
    }
    if xla_ms is not None:
        record["vs_xla_fft"] = round(xla_ms / tpu_ms, 2)
        record["xla_fft_ms"] = round(xla_ms, 4)
    record.update(large)
    print(json.dumps(record))
    return 0


if __name__ == "__main__":
    sys.exit(main())
