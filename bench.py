"""Headline benchmark: 1-D complex FFT, N = 2^20, single TPU chip.

Measures the framework's flagship path (pi-layout output — gather
excluded exactly as the reference excludes it from timing) against TWO
baselines on this host and prints ONE JSON line:

    {"metric": ..., "value": GFLOP/s, "unit": ...,
     "vs_baseline": ..., "vs_xla_fft": ..., "xla_fft_ms": ..., "plan": ...,
     "roofline_util": ...,
     "n2^22_ms": ..., "n2^22_gflops": ..., "n2^22_vs_xla": ...,
     "n2^22_roofline_util": ..., "n2^24_...": ...}

* vs_baseline — wall-clock speedup over the native C backend at the same
  N (BASELINE.md north star: >= 10x; GFLOP/s uses the standard
  5 N log2 N FFT flop count).
* vs_xla_fft — wall-clock speedup over `jnp.fft.fft` ON THE SAME CHIP at
  the same N: the strongest same-hardware comparison (XLA's own FFT is
  the production alternative a user would otherwise call).  Reported for
  the flagship AND for every large-n row, so the large-n falloff is
  compared against what XLA manages at that same n.
* roofline_util — achieved fraction of the HBM roofline charging the
  minimum 16 B/element traffic (utils/roofline.py): each row also
  carries its plan-declared ceiling (1/(1+carry passes): 1.0 carry-free
  fused, ~0.5 fourstep/rql, ~0.33 the two-carry sixstep) and
  util_of_ceiling — how closely the path approaches ITS cap, the
  launch/retiling/serialization overhead the single-pass pipelines
  remove and the >= 0.8 acceptance figure that tracks the large-n
  falloff (and its fix) release over release.
* rfft2^K_* — the half-spectrum real-input row beside every c2c
  large-n row (docs/REAL.md): GFLOP/s on the 2.5 n log2 n real count,
  the domain-aware roofline_util (the r2c floor is 8 B/element — half
  of c2c), and the METERED pifft_hbm_bytes_total delta the
  `make rfft-smoke` gate asserts is exactly half the c2c cell's at
  equal n.

Kernel selection goes through the plan subsystem
(cs87project_msolano2_tpu.plans): `plans.tune` races the shared
candidate ladder (plans/ladder.py — the single source of truth this file
used to own) ONCE per (device kind, n, layout) key and persists the
winner, so a warm session reaches its first timed FFT on a cache hit
with no re-race; this file just tunes-or-loads and reports the winning
plan.  Large-n rows each tune THEIR key — above the documented
crossover (plans.ladder.FOURSTEP_MIN_N) the ladder leads with the
fourstep entries.

Measurement method: loop-slope (utils/timing.py) — on the axon TPU relay
block_until_ready is not a real barrier, so the FFT is iterated K times
inside one jitted fori_loop ending in a scalar fetch, at two K values;
the per-FFT time is the slope and the ~100 ms relay overhead cancels.
On hardware where block_until_ready is honest the same method simply
measures with less noise.

Resilience (docs/RESILIENCE.md): every measurement runs under the
resilience subsystem's discipline — faults are CLASSIFIED
(resilience.classify), TRANSIENT ones retried with backoff, and
CAPACITY/PERMANENT kernel faults ride the plan degradation chain, so a
dead kernel demotes (fourstep -> rql -> jnp.fft -> numpy) instead of
killing the bench; a degraded row is tagged ``degraded: true`` and its
plan record carries the demotion trail.  ``--journal``/``--resume`` add
atomic per-cell JSONL checkpointing: a preempted bench re-run with
``--resume`` recomputes only the cells the kill took, byte-identical
semantics for the rest.

``--smoke`` (CI): run the whole reporting pipeline at toy sizes with
single-shot timing so the entry point cannot silently rot offline.  The
numbers are meaningless (interpret mode); the JSON shape, the plan
resolution, and every measurement seam are real.  ``make bench-chaos``
runs it with ``PIFFT_FAULT=tube:capacity:1.0`` and asserts the
degradation chain carried the run to rc=0 with a recorded demotion.

Observability (docs/OBSERVABILITY.md): ``--events PATH`` arms the
structured event stream (JSONL sink) — every cell runs under a named
span with a funnel/tube phase probe nested inside it, plan-cache /
retry / demotion activity is counted, the final metrics snapshot is
appended as the last event, and the JSON record carries the ``run`` id
every event shares.  ``--trace-out PATH`` additionally writes the
run's spans as Chrome trace JSON (Perfetto-loadable);
``pifft obs {summary, export, validate}`` post-processes the events
file.  Without the flags (and without ``PIFFT_OBS*`` in the
environment) the whole layer is a no-op.  ``make bench-smoke-obs`` is
the CI gate over all of this.
"""

import argparse
import sys

import numpy as np

N = 1 << 20

# the reference's pthreads analysis reaches n=2^24; the rows continue
# through 2^27 — the HBM-resident range the hierarchical sixstep path
# exists to keep flat (the old ladder silently fell back to the
# two-trip rql plan from 2^25, where fourstep's smallest column block
# misses VMEM — docs/KERNELS.md)
LARGE_LOGNS = (22, 24, 25, 26, 27)

SMOKE_N = 1 << 12
SMOKE_LARGE_LOGNS = (13,)

# the heterogeneous-backend rows (docs/BACKENDS.md): the same pi-layout
# c2c shape planned under explicit gpu / cpu-native plan-key tokens, at
# BOUNDED n in every tier — the gpu rung runs the portable Pallas rows
# kernel in interpret mode on non-GPU hosts, so these rows exist to
# keep the cross-backend plumbing (per-backend cache tokens, per-backend
# roofline ceilings, the analyze loader's backend axis) exercised, not
# to publish hero numbers
BACKEND_ROW_LOGNS = (8, 10)
BACKEND_ROW_BACKENDS = ("gpu", "cpu-native")
BACKEND_ROW_PREFIX = {"gpu": "gpu", "cpu-native": "cpun"}

# --serve-load: offered loads (requests/s) per served shape, open-loop
# (serve/loadgen.py); the smoke tier is sized to finish in CI seconds
SERVE_LOAD_NS = (1 << 16,)
SERVE_LOAD_RPS = (100.0, 500.0)
SERVE_LOAD_DURATION_S = 2.0
SMOKE_SERVE_LOAD_NS = (1 << 10,)
SMOKE_SERVE_LOAD_RPS = (80.0, 320.0)
SMOKE_SERVE_LOAD_DURATION_S = 0.25

# the wire replay tier (serve/loadgen.py run_wire_load): per-dialect
# rows over a REAL socket, same offered load for both protocols so the
# JSON-vs-binary p99 delta is apples to apples — the wire-smoke gate
# asserts binary < json on these rows
WIRE_LOAD_N = 1 << 16
WIRE_LOAD_RPS = (200.0,)
WIRE_LOAD_DURATION_S = 2.0
WIRE_LOAD_PROCESSES = ("uniform", "diurnal", "bursty", "heavytail")
SMOKE_WIRE_LOAD_N = 1 << 12
SMOKE_WIRE_LOAD_RPS = (120.0,)
SMOKE_WIRE_LOAD_DURATION_S = 0.4
SMOKE_WIRE_LOAD_PROCESSES = ("uniform", "bursty")


def _retry(fn, *args, smoke: bool = False, label: str = ""):
    """Shared TRANSIENT-retry wrapper (resilience.with_retry policy):
    real runs get the 30/60/120 s relay-recovery ladder, smoke runs a
    fast one so CI never sleeps on an injected blip.  CAPACITY and
    PERMANENT faults pass straight through — repetition cannot fix
    them; classification at the call site decides what can."""
    from cs87project_msolano2_tpu.resilience import (
        FAST_POLICY,
        call_with_retry,
    )

    policy = FAST_POLICY if smoke else None
    return call_with_retry(fn, *args, policy=policy, label=label)


def _smoke_ms(fn, *args) -> float:
    """Single-shot wall time for --smoke: exercises the exact callable
    the real path would measure, with none of the loop-slope cost.
    Interpret-mode numbers mean nothing; only the plumbing is under
    test."""
    import jax

    from cs87project_msolano2_tpu.utils.timing import time_ms

    ms, _ = time_ms(jax.jit(fn), *args, reps=2, warmup=1)
    return ms


def measure_tpu_ms(n: int = N, smoke: bool = False) -> tuple:
    """(ms, plan) for an n-point pi-layout key, via the plans
    subsystem's shared measurement policy (tuned-race ms reused, cached
    plans re-timed with the tuner's own timer, a non-compiling cached
    winner re-raced).  TRANSIENT faults retry here; kernel CAPACITY/
    PERMANENT faults degrade inside the plan executor and surface as
    ``plan.degraded``."""
    from cs87project_msolano2_tpu import plans
    from cs87project_msolano2_tpu.resilience import maybe_fault

    key = plans.make_key(n, layout="pi")
    if smoke:
        import jax
        import jax.numpy as jnp

        plan = plans.get_plan(key)
        k0 = jax.random.PRNGKey(0)
        xr = jax.random.normal(k0, (n,), jnp.float32)
        xi = jax.random.normal(jax.random.fold_in(k0, 1), (n,), jnp.float32)

        def run_smoke():
            maybe_fault("bench")  # resilience injection site
            return _smoke_ms(plan.fn, xr, xi)

        return _retry(run_smoke, smoke=True,
                      label=f"flagship smoke n={n}"), plan

    def run():
        maybe_fault("bench")  # resilience injection site
        return plans.measured_ms(key)

    return _retry(run, label=f"measured_ms n={n}")


def measure_xla_fft_ms(n: int = N, smoke: bool = False):
    """jnp.fft.fft on the same chip at the same n — the same-hardware
    comparison VERDICT.md round 2 demanded.  The loop body carries
    complex state (no per-iteration plane split/merge) so only the FFT
    itself plus one scaling is timed — the same epilogue the Pallas body
    pays.  Falls back to the unrolled slope if the FFT custom-call
    cannot lower inside a fori_loop; returns None (metric omitted) if it
    cannot be measured at all rather than losing the other results.
    Failures are classified (resilience taxonomy) so the diagnostic
    says WHICH recovery applies, and transient ones were already
    retried before any fallback fires."""
    import jax
    import jax.numpy as jnp

    from cs87project_msolano2_tpu.plans import warn
    from cs87project_msolano2_tpu.resilience import classify, maybe_fault
    from cs87project_msolano2_tpu.utils.timing import (
        loop_slope_ms,
        unrolled_slope_ms,
    )

    key = jax.random.PRNGKey(2)
    xr = jax.random.normal(key, (n,), jnp.float32)
    xi = jax.random.normal(jax.random.fold_in(key, 1), (n,), jnp.float32)
    inv_rn = np.complex64(1.0 / np.sqrt(n))

    # The relay cannot pass complex64 across the program ABI (eager
    # complex ops, complex program inputs, and complex While carries are
    # all Unimplemented), so the loop body must carry float planes and
    # pay a complex-merge + re/im-split every iteration.  That epilogue
    # is NOT the XLA FFT's cost — charging it would overstate our
    # speedup — so it is measured separately with the same method (the
    # identical elementwise chain minus the fft) and subtracted.
    inv = np.float32(inv_rn.real)

    def body_fft(c):
        y = jnp.fft.fft(c[0] + 1j * c[1])
        return jnp.real(y) * inv, jnp.imag(y) * inv

    def body_epilogue(c):
        y = c[0] + 1j * c[1]
        return jnp.real(y) * inv, jnp.imag(y) * inv

    if smoke:
        def run_smoke():
            maybe_fault("bench")  # resilience injection site
            return _smoke_ms(body_fft, (xr, xi))

        return _retry(run_smoke, smoke=True, label=f"xla smoke n={n}")

    def run_loop_slope():
        maybe_fault("bench")  # resilience injection site
        return loop_slope_ms(body_fft, (xr, xi), k1=64, k2=1024, reps=5,
                             min_delta_ms=100.0, cache=False)

    try:
        raw = _retry(run_loop_slope, label=f"xla fft n={n}")
    except Exception as e:
        # some backends cannot lower the FFT custom-call inside a While
        # body — statically unroll instead (modest k2: program size and
        # remote-compile time grow linearly with the unroll)
        warn(f"xla fft n={n} under fori_loop failed ({classify(e).value} "
             f"{type(e).__name__}); trying unrolled slope")
        try:
            raw = unrolled_slope_ms(body_fft, (xr, xi), k1=8, k2=64,
                                    reps=7, min_delta_ms=20.0, max_k=256,
                                    cache=False)
        except Exception as e2:
            warn(f"xla fft n={n} not measurable on this backend "
                 f"({classify(e2).value} {type(e2).__name__}); omitting "
                 f"vs_xla_fft")
            return None
    try:
        epilogue = loop_slope_ms(body_epilogue, (xr, xi), k1=64, k2=1024,
                                 reps=5, min_delta_ms=40.0, cache=False)
    except Exception as e:
        warn(f"xla epilogue n={n} not resolvable ({classify(e).value} "
             f"{type(e).__name__}); vs_xla_fft conservatively uncorrected")
        epilogue = 0.0
    # the epilogue is a small fraction of the FFT; if its measurement
    # came back implausibly large (relay noise), don't let it eat the
    # result — cap the correction at half the raw time
    return max(raw - epilogue, raw * 0.5)


def _metered_hbm_delta(fn) -> tuple:
    """(result, bytes) of calling `fn` (a roofline_utilization
    closure): the pifft_hbm_bytes_total delta the call charged —
    0 while the obs subsystem is disarmed (the meter is a no-op
    there).  The rfft-smoke gate asserts the r2c delta is exactly
    half the c2c one at equal n, FROM THE METER, not from the
    formula that feeds it."""
    from cs87project_msolano2_tpu.obs import metrics

    before = metrics.counter_value("pifft_hbm_bytes_total")
    out = fn()
    return out, int(metrics.counter_value("pifft_hbm_bytes_total")
                    - before)


def measure_rfft_ms(n: int, smoke: bool = False) -> tuple:
    """(ms, plan) for an n-point half-spectrum r2c key (docs/REAL.md):
    natural order — the Hermitian merge IS the r2c contract, so unlike
    the pi-layout c2c rows there is no gather to exclude.  The plan
    rides the tuned c2c choice at n/2, so a warmed c2c trajectory
    serves these rows with no extra race."""
    from cs87project_msolano2_tpu import plans
    from cs87project_msolano2_tpu.resilience import maybe_fault

    key = plans.make_key(n, layout="natural", domain="r2c")
    if smoke:
        import jax
        import jax.numpy as jnp

        plan = plans.get_plan(key)
        k0 = jax.random.PRNGKey(5)
        xr = jax.random.normal(k0, (n,), jnp.float32)
        xi = jnp.zeros((n,), jnp.float32)

        def run_smoke():
            maybe_fault("bench")  # resilience injection site
            return _smoke_ms(plan.fn, xr, xi)

        return _retry(run_smoke, smoke=True,
                      label=f"rfft smoke n={n}"), plan

    def run():
        maybe_fault("bench")  # resilience injection site
        return plans.measured_ms(key)

    return _retry(run, label=f"rfft measured_ms n={n}")


def _row_fields(tag: str, nn: int, ms: float, plan,
                domain: str = "c2c", flops_per: float = 5.0) -> dict:
    """The row-measurement scaffolding every reach-row kind shares
    (c2c, rfft, precision-mode): ms, GFLOP/s on the given flop count,
    the plan description, the degraded flag, the carry-pass-aware
    ceiling of the variant that actually SERVED (a demoted row is
    judged by its rung's carries, not the dead winner's), and the
    METERED domain-/dtype-aware roofline figures — the bytes charged
    come from the plan's own storage width (Plan.storage_bytes), so a
    bf16 cell meters half and an escape-rung demotion meters fp32."""
    from cs87project_msolano2_tpu.utils.roofline import (
        plan_carry_passes,
        roofline_ceiling,
        roofline_utilization,
    )

    out = {f"{tag}_ms": round(ms, 4),
           f"{tag}_gflops": round(
               flops_per * nn * np.log2(nn) / (ms * 1e-3) / 1e9, 1),
           f"{tag}_plan": plan.describe()}
    if plan.degraded:
        out[f"{tag}_degraded"] = True
    served = plan.demotions[-1]["to"] if plan.degraded else plan.variant
    passes = plan_carry_passes(served)
    ceil = roofline_ceiling(passes)
    if ceil is not None:
        out[f"{tag}_carry_passes"] = passes
        out[f"{tag}_roofline_ceiling"] = round(ceil, 3)
    util, hbm_bytes = _metered_hbm_delta(
        lambda: roofline_utilization(nn, ms, plan.key.device_kind,
                                     passes or 0, domain=domain,
                                     storage_bytes=plan.storage_bytes(),
                                     backend=getattr(plan.key, "backend",
                                                     "tpu")))
    if hbm_bytes:
        # the METERED plan-declared traffic this cell charged — the
        # raw material of the rfft-smoke and precision-smoke
        # bytes-halved assertions
        out[f"{tag}_hbm_bytes"] = hbm_bytes
    if util is not None:
        out[f"{tag}_roofline_util"] = round(util, 3)
        if ceil:
            # the acceptance figure: how close the path runs to ITS
            # own carry-pass-aware cap (target >= 0.8 per row)
            out[f"{tag}_util_of_ceiling"] = round(util / ceil, 3)
    return out


def measure_rfft_row(logn: int, smoke: bool = False) -> dict:
    """One half-spectrum reach row, side by side with the c2c row at
    the same n: GFLOP/s on the standard real-input count
    (2.5 n log2 n — half the c2c flops, matching the halved spectrum),
    the domain-aware roofline utilization (the r2c floor is 8 B/elem),
    and the METERED HBM-bytes delta — the enforced, not asserted, half
    of the bytes the c2c cell moved.  Smoke rows additionally record
    the parity error vs numpy.fft.rfft (the correctness tests cover
    the ladder; this keeps the CI gate self-contained)."""
    from cs87project_msolano2_tpu import plans
    from cs87project_msolano2_tpu.resilience import classify

    nn = 1 << logn
    tag = f"rfft2^{logn}"
    try:
        ms, plan = measure_rfft_ms(nn, smoke=smoke)
    except Exception as e:
        plans.warn(f"rfft 2^{logn} not measured "
                   f"({classify(e).value} {type(e).__name__}: "
                   f"{str(e)[:200]})")
        return {}
    out = _row_fields(tag, nn, ms, plan, domain="r2c", flops_per=2.5)
    out[f"{tag}_domain"] = "r2c"
    if smoke:
        from cs87project_msolano2_tpu.models.real import rfft

        rng = np.random.default_rng(6)
        x = rng.standard_normal(nn).astype(np.float32)
        ref = np.fft.rfft(x.astype(np.float64))
        err = float(np.max(np.abs(np.asarray(rfft(x)) - ref))
                    / np.max(np.abs(ref)))
        out[f"{tag}_parity_relerr"] = err
    return out


def measure_conv_row(logn: int, smoke: bool = False) -> dict:
    """One fused spectral-convolution reach row (docs/APPS.md) beside
    the transform rows at the same n: the served circular conv
    primitive — rfft(x) · cached-kernel-spectrum, irfft, all on
    device — timed through its jitted fused pipeline, with the
    METERED HBM-bytes delta the `make apps-smoke` gate holds at the
    FUSED floor (an unfused host round-trip charges visibly more),
    and the op-aware roofline utilization.  GFLOP/s uses the real-
    transform count of what the timed pipeline RUNS — one rfft + one
    irfft, 2 x 2.5 n log2 n (the kernel spectrum is cached, the
    repeated-filtering serving reality).  Smoke rows record the
    parity error vs the numpy oracle."""
    from cs87project_msolano2_tpu import plans
    from cs87project_msolano2_tpu.apps.spectral import (
        _fused_circular,
        kernel_spectrum,
        numpy_oracle,
    )
    from cs87project_msolano2_tpu.resilience import classify, maybe_fault
    from cs87project_msolano2_tpu.utils.roofline import (
        charge_spectral_traffic,
        spectral_roofline_utilization,
    )

    import jax.numpy as jnp

    nn = 1 << logn
    tag = f"conv2^{logn}"
    rng = np.random.default_rng(9)
    x = rng.standard_normal(nn).astype(np.float32)
    k = rng.standard_normal(129).astype(np.float32)
    try:
        kr, ki = kernel_spectrum(k, nn)
        fused = _fused_circular("conv", nn, None)
        xp = jnp.asarray(x)

        def run_cell():
            maybe_fault("bench")  # resilience injection site
            return _smoke_ms(fused, xp, kr, ki) if smoke else \
                _timed_op_ms(fused, xp, kr, ki)

        ms = _retry(run_cell, smoke=smoke, label=f"conv n={nn}")
    except Exception as e:
        plans.warn(f"conv 2^{logn} not measured "
                   f"({classify(e).value} {type(e).__name__}: "
                   f"{str(e)[:200]})")
        return {}
    # the timed pipeline runs TWO transforms (the kernel spectrum is
    # cached — the repeated-filtering serving reality): one rfft of
    # the signal + one irfft, 2 x 2.5 n log2 n real-transform flops
    out = {f"{tag}_ms": round(ms, 4),
           f"{tag}_gflops": round(
               2 * 2.5 * nn * np.log2(nn) / (ms * 1e-3) / 1e9, 1),
           f"{tag}_op": "conv"}
    _, hbm = _metered_hbm_delta(
        lambda: charge_spectral_traffic("conv", nn))
    if hbm:
        out[f"{tag}_hbm_bytes"] = hbm
    key = plans.make_key(nn, layout="natural", domain="r2c")
    util = spectral_roofline_utilization("conv", nn, ms,
                                         key.device_kind,
                                         backend=key.backend)
    if util is not None:
        out[f"{tag}_roofline_util"] = round(util, 3)
    if smoke:
        y = np.asarray(fused(xp, kr, ki))
        ref = numpy_oracle("conv", x.astype(np.float64),
                           np.pad(k, (0, nn - k.shape[0]))
                           .astype(np.float64), nn)
        out[f"{tag}_parity_relerr"] = float(
            np.max(np.abs(y - ref)) / np.max(np.abs(ref)))
    return out


def measure_conv_np_row(smoke: bool = False) -> dict:
    """The any-length fftconv row (docs/PLANS.md "Arbitrary n"): the
    fused circular-conv pipeline at the NON-pow2 transform length
    `cheapest_length` actually picks for a 3·2^18-sample signal
    (3·2^8 in smoke), beside the pad-to-pow2 control's metered
    charge at next_pow2 of the same linear length.  The
    bluestein-smoke bytes gate asserts `{tag}_hbm_bytes` is
    STRICTLY below `{tag}_pow2_hbm_bytes` — the pad-to-pow2 tax,
    read FROM THE METER, not from the formula that feeds it.
    Smoke rows record parity vs the numpy oracle at the mixed-radix
    length."""
    from cs87project_msolano2_tpu import plans
    from cs87project_msolano2_tpu.apps.spectral import (
        _fused_circular,
        cheapest_length,
        kernel_spectrum,
        numpy_oracle,
    )
    from cs87project_msolano2_tpu.ops.anylen import next_pow2
    from cs87project_msolano2_tpu.resilience import classify, maybe_fault
    from cs87project_msolano2_tpu.utils.roofline import (
        charge_spectral_traffic,
        spectral_roofline_utilization,
    )

    import jax.numpy as jnp

    # signal sized so the linear length la+lv-1 lands exactly on
    # 3·2^k: cheapest_length keeps it (odd part 3), the pow2 control
    # must pad 33% further to 2^(k+2)
    lv = 129
    la = (3 * (1 << 8) if smoke else 3 * (1 << 18)) - (lv - 1)
    nn = cheapest_length(la + lv - 1)
    pow2_n = next_pow2(la + lv - 1)
    tag = f"conv_np{nn}"
    rng = np.random.default_rng(11)
    x = rng.standard_normal(nn).astype(np.float32)
    k = rng.standard_normal(lv).astype(np.float32)
    try:
        kr, ki = kernel_spectrum(k, nn)
        fused = _fused_circular("conv", nn, None)
        xp = jnp.asarray(x)

        def run_cell():
            maybe_fault("bench")  # resilience injection site
            return _smoke_ms(fused, xp, kr, ki) if smoke else \
                _timed_op_ms(fused, xp, kr, ki)

        ms = _retry(run_cell, smoke=smoke, label=f"conv_np n={nn}")
    except Exception as e:
        plans.warn(f"conv_np {nn} not measured "
                   f"({classify(e).value} {type(e).__name__}: "
                   f"{str(e)[:200]})")
        return {}
    out = {f"{tag}_ms": round(ms, 4),
           f"{tag}_gflops": round(
               2 * 2.5 * nn * np.log2(nn) / (ms * 1e-3) / 1e9, 1),
           f"{tag}_op": "conv"}
    _, hbm = _metered_hbm_delta(
        lambda: charge_spectral_traffic("conv", nn))
    # the pad-to-pow2 control: what the SAME op would have charged
    # at next_pow2 — the tax this row exists to show is gone
    _, hbm_pow2 = _metered_hbm_delta(
        lambda: charge_spectral_traffic("conv", pow2_n))
    if hbm:
        out[f"{tag}_hbm_bytes"] = hbm
    if hbm_pow2:
        out[f"{tag}_pow2_hbm_bytes"] = hbm_pow2
    key = plans.make_key(nn, layout="natural", domain="r2c")
    util = spectral_roofline_utilization("conv", nn, ms,
                                         key.device_kind,
                                         backend=key.backend)
    if util is not None:
        out[f"{tag}_roofline_util"] = round(util, 3)
    if smoke:
        y = np.asarray(fused(xp, kr, ki))
        ref = numpy_oracle("conv", x.astype(np.float64),
                           np.pad(k, (0, nn - k.shape[0]))
                           .astype(np.float64), nn)
        out[f"{tag}_parity_relerr"] = float(
            np.max(np.abs(y - ref)) / np.max(np.abs(ref)))
    return out


def measure_os_row(logn: int, smoke: bool = False) -> dict:
    """One overlap-save streaming-convolution row (docs/APPS.md): a
    signal 4x the block convolved through ONE cached plan pair at
    block = 2^logn, reporting the row set's chunk-count and
    overlap-waste columns — the two sides of the block-size trade the
    tuned `block` axis races — plus wall time and the metered
    per-chunk traffic.  Rows past stream.py's raced-candidate ceiling
    (MAX_BLOCK) are SKIPPED with a diagnostic rather than silently
    measured at a capped block the row tag would misname.  Smoke rows
    record np.convolve parity."""
    from cs87project_msolano2_tpu import plans
    from cs87project_msolano2_tpu.apps.stream import (
        MAX_BLOCK,
        chunk_count,
        overlap_save,
        overlap_waste,
    )
    from cs87project_msolano2_tpu.obs.spans import clock
    from cs87project_msolano2_tpu.resilience import classify, maybe_fault

    # the os2^K tag IS the block size (analyze/loader parses it that
    # way): past stream.py's raced-candidate ceiling the row is
    # skipped, never silently measured at a capped block the tag
    # would misname (the hardware rows' 2^22..2^27 n land here)
    if (1 << logn) > MAX_BLOCK:
        plans.warn(f"overlap-save 2^{logn} skipped: block past the "
                   f"raced-candidate ceiling MAX_BLOCK="
                   f"2^{MAX_BLOCK.bit_length() - 1} "
                   f"(docs/APPS.md block-size tuning)")
        return {}
    block = 1 << logn
    m = 129
    n_signal = 4 * block
    tag = f"os2^{logn}"
    rng = np.random.default_rng(10)
    x = rng.standard_normal(n_signal).astype(np.float32)
    k = rng.standard_normal(m).astype(np.float32)
    try:
        def run_cell():
            maybe_fault("bench")  # resilience injection site
            t0 = clock()
            y = overlap_save(x, k, block=block)
            return (clock() - t0) * 1e3, y

        (ms, y), hbm = _metered_hbm_delta(
            lambda: _retry(run_cell, smoke=smoke,
                           label=f"overlap-save block={block}"))
    except Exception as e:
        plans.warn(f"overlap-save 2^{logn} not measured "
                   f"({classify(e).value} {type(e).__name__}: "
                   f"{str(e)[:200]})")
        return {}
    out = {f"{tag}_ms": round(ms, 4),
           f"{tag}_block": block,
           f"{tag}_signal_n": n_signal,
           f"{tag}_chunks": chunk_count(n_signal, m, block),
           f"{tag}_overlap_waste": round(overlap_waste(block, m), 4),
           f"{tag}_op": "conv"}
    if hbm:
        out[f"{tag}_hbm_bytes"] = hbm
    if smoke:
        ref = np.convolve(x.astype(np.float64), k.astype(np.float64),
                          "full")
        out[f"{tag}_parity_relerr"] = float(
            np.max(np.abs(y - ref)) / np.max(np.abs(ref)))
    return out


def _timed_op_ms(fn, *args) -> float:
    """Wall time of one compiled fused-op invocation (median of 5 —
    the ops are whole pipelines, not single kernels; the loop-slope
    discipline belongs to the transforms the pipeline is built
    from)."""
    from cs87project_msolano2_tpu.utils.timing import time_ms

    ms, _ = time_ms(fn, *args, reps=5, warmup=2)
    return ms


def measure_precision_ms(n: int, mode: str, smoke: bool = False) -> tuple:
    """(ms, plan) for an n-point pi-layout key at precision `mode`
    (docs/PRECISION.md) — the flagship measurement path with the
    precision axis pinned, so a bf16-storage cell rides the same
    tuning/cache/degradation machinery as its fp32 sibling."""
    from cs87project_msolano2_tpu import plans
    from cs87project_msolano2_tpu.resilience import maybe_fault

    key = plans.make_key(n, layout="pi", precision=mode)
    if smoke:
        import jax
        import jax.numpy as jnp

        plan = plans.get_plan(key)
        k0 = jax.random.PRNGKey(7)
        xr = jax.random.normal(k0, (n,), jnp.float32)
        xi = jax.random.normal(jax.random.fold_in(k0, 1), (n,),
                               jnp.float32)

        def run_smoke():
            maybe_fault("bench")  # resilience injection site
            return _smoke_ms(plan.fn, xr, xi)

        return _retry(run_smoke, smoke=True,
                      label=f"{mode} smoke n={n}"), plan

    def run():
        maybe_fault("bench")  # resilience injection site
        return plans.measured_ms(key)

    return _retry(run, label=f"{mode} measured_ms n={n}")


def measure_precision_row(logn: int, mode: str = "bf16",
                          smoke: bool = False) -> dict:
    """One precision-mode row beside the split3 c2c row at the same n
    (docs/PRECISION.md): GFLOP/s on the standard count, the
    dtype-aware roofline utilization (bf16 storage floors at
    8 B/element — half of fp32), and the METERED HBM-bytes delta the
    `make precision-smoke` gate asserts is exactly half the fp32
    cell's at equal n.  Smoke rows additionally record the parity
    error vs numpy, which the gate asserts within the MODE's budget —
    the bytes-halving must never be bought with a blown contract."""
    from cs87project_msolano2_tpu import plans
    from cs87project_msolano2_tpu.resilience import classify

    nn = 1 << logn
    tag = f"{mode}_2^{logn}"
    try:
        ms, plan = measure_precision_ms(nn, mode, smoke=smoke)
    except Exception as e:
        plans.warn(f"{mode} 2^{logn} not measured "
                   f"({classify(e).value} {type(e).__name__}: "
                   f"{str(e)[:200]})")
        return {}
    out = _row_fields(tag, nn, ms, plan)
    out[f"{tag}_precision"] = plan.effective_precision()
    if smoke:
        from cs87project_msolano2_tpu.ops.precision import rel_err
        from cs87project_msolano2_tpu.utils.verify import (
            pi_layout_to_natural,
        )

        rng = np.random.default_rng(8)
        xr = rng.standard_normal(nn).astype(np.float32)
        xi = rng.standard_normal(nn).astype(np.float32)
        yr, yi = plan.execute(xr, xi)
        got = pi_layout_to_natural(np.asarray(yr)
                                   + 1j * np.asarray(yi))
        ref = np.fft.fft(xr.astype(np.complex128)
                         + 1j * xi.astype(np.complex128))
        out[f"{tag}_parity_relerr"] = rel_err(got.real, got.imag,
                                              ref.real, ref.imag)
    return out


def measure_large_n_row(logn: int, smoke: bool = False) -> dict:
    """One large-n reach row (the reference's pthreads analysis goes to
    n=2^24): the per-key plan at 2^logn — each n gets the plan tuned
    (or statically chosen) for ITS key, not the flagship's shape — with
    the same-chip XLA comparison and the HBM-roofline utilization
    recorded, so the large-n falloff is tracked release over release.
    Best-effort — a failed row drops its fields, not the bench, and
    says so through plans.warn with the fault's classification
    (greppable `# ` diagnostics, the PIF501 discipline).  A row whose
    plan demoted mid-measurement is tagged ``<tag>_degraded``."""
    from cs87project_msolano2_tpu import plans
    from cs87project_msolano2_tpu.resilience import classify

    nn = 1 << logn
    tag = f"n2^{logn}"
    try:
        ms, plan = measure_tpu_ms(nn, smoke=smoke)
    except Exception as e:
        plans.warn(f"large-n 2^{logn} not measured "
                   f"({classify(e).value} {type(e).__name__}: "
                   f"{str(e)[:200]})")
        return {}
    out = _row_fields(tag, nn, ms, plan)
    try:
        xla_ms = measure_xla_fft_ms(nn, smoke=smoke)
    except Exception as e:
        plans.warn(f"large-n 2^{logn} xla comparison failed "
                   f"({classify(e).value} {type(e).__name__}: "
                   f"{str(e)[:200]})")
        xla_ms = None
    if xla_ms is not None:
        out[f"{tag}_vs_xla"] = round(xla_ms / ms, 2)
    return out


def measure_backend_row(logn: int, backend: str,
                        smoke: bool = False) -> dict:
    """One heterogeneous-backend reach row (docs/BACKENDS.md): the same
    pi-layout c2c shape the n2^K rows measure, planned under an
    EXPLICIT backend plan-key token — ``gpu`` rows serve the portable
    Pallas rows kernel (interpret mode on non-GPU hosts, which is why
    these rows stay at BACKEND_ROW_LOGNS in every tier), ``cpu-native``
    rows serve the ctypes pthreads harness when libpifft.so is present
    and its numpy stand-in (ONE plans.warn) when it is not.  Timing is
    single-shot: the row's value is the exercised plumbing — the
    per-backend cache token, the backend-aware roofline ceiling, and
    the gpu2^K_* / cpun2^K_* names the analyze loader maps back onto
    Sample.backend — not the number.  Best-effort like every reach
    row: a failed cell drops its fields, not the bench."""
    import jax
    import jax.numpy as jnp

    from cs87project_msolano2_tpu import plans
    from cs87project_msolano2_tpu.resilience import classify, maybe_fault
    from cs87project_msolano2_tpu.utils.roofline import (
        backend_peak_bytes_per_s,
        roofline_utilization,
    )

    nn = 1 << logn
    tag = f"{BACKEND_ROW_PREFIX[backend]}2^{logn}"
    try:
        key = plans.make_key(nn, layout="pi", backend=backend)
        plan = plans.get_plan(key)
        k0 = jax.random.PRNGKey(13)
        xr = jax.random.normal(k0, (nn,), jnp.float32)
        xi = jax.random.normal(jax.random.fold_in(k0, 1), (nn,),
                               jnp.float32)

        def run_cell():
            maybe_fault("bench")  # resilience injection site
            return _smoke_ms(plan.fn, xr, xi)

        ms = _retry(run_cell, smoke=True,
                    label=f"{backend} row n={nn}")
    except Exception as e:
        plans.warn(f"{backend} 2^{logn} not measured "
                   f"({classify(e).value} {type(e).__name__}: "
                   f"{str(e)[:200]})")
        return {}
    out = {f"{tag}_ms": round(ms, 4),
           f"{tag}_gflops": round(
               5.0 * nn * np.log2(nn) / (ms * 1e-3) / 1e9, 3),
           f"{tag}_plan": plan.describe(),
           f"{tag}_backend": backend}
    if plan.degraded:
        out[f"{tag}_degraded"] = True
    # the ceiling this row reads against is its OWN backend's — the
    # `make backend-smoke` gate asserts the gpu and cpu-native rows
    # carry DISTINCT peaks (the whole point of rule PIF122)
    peak = backend_peak_bytes_per_s(backend, key.device_kind)
    if peak is not None:
        out[f"{tag}_peak_gbps"] = round(peak / 1e9, 1)
    util = roofline_utilization(nn, ms, key.device_kind, 0,
                                backend=backend)
    if util is not None:
        out[f"{tag}_roofline_util"] = round(util, 6)
    if smoke:
        from cs87project_msolano2_tpu.utils.verify import (
            pi_layout_to_natural,
        )

        yr, yi = plan.execute(np.asarray(xr), np.asarray(xi))
        got = pi_layout_to_natural(np.asarray(yr) + 1j * np.asarray(yi))
        ref = np.fft.fft(np.asarray(xr, np.complex128)
                         + 1j * np.asarray(xi, np.complex128))
        out[f"{tag}_parity_relerr"] = float(
            np.max(np.abs(got - ref)) / np.max(np.abs(ref)))
    return out


def measure_large_n_ms(logns=LARGE_LOGNS, smoke: bool = False) -> dict:
    """All large-n rows (kept as the non-journaled entry point; the
    journaled path in main() checkpoints per row)."""
    out = {}
    for logn in logns:
        out.update(measure_large_n_row(logn, smoke=smoke))
    return out


def _phase_probe(n: int) -> None:
    """One small funnel/tube decomposition run under the current cell
    span, so the trace carries named, NESTED funnel/tube phase spans
    (and XProf TraceAnnotations) for this cell.  Observability
    structure only — never timed, never part of any measurement — and
    sized down (the phase spans record their own probe shape; the cell
    span carries the real n) so the probe stays trivial next to the
    measurement it decorates.  A no-op unless --events/--trace-out (or
    PIFFT_OBS*) armed the obs subsystem."""
    from cs87project_msolano2_tpu import obs

    if not obs.enabled():
        return
    from cs87project_msolano2_tpu.models.pi_fft import pi_fft_pi_layout

    # the probe kernel is pi-layout (pow2-only): round any-length
    # cell ns (conv_np*) down to the nearest power of two
    pn = min(n, 1 << 12)
    pn = 1 << (pn.bit_length() - 1)
    rng = np.random.default_rng(0)
    xr = rng.standard_normal(pn).astype(np.float32)
    xi = rng.standard_normal(pn).astype(np.float32)
    pi_fft_pi_layout(xr, xi, min(8, pn))


def measure_c_baseline_ms() -> float:
    from cs87project_msolano2_tpu.backends.cpu import num_cores
    from cs87project_msolano2_tpu.backends.registry import get_backend
    from cs87project_msolano2_tpu.cli import make_input

    p = 1
    while p * 2 <= num_cores():
        p *= 2
    x = make_input(N, seed=0)
    return get_backend("cpu").run(x, p, reps=3).total_ms


def serve_load_main(args) -> int:
    """``--serve-load``: the serving SLO suite (docs/SERVING.md).

    Runs the open-loop load generator (serve/loadgen.py) against an
    in-process dispatcher warmed for the load shapes, one cell per
    (shape, offered rps), and emits ONE BENCH-round JSON line whose
    headline is the worst completed p99; the full row set (offered
    load, achieved throughput, p50/p99 with the queue-wait vs compute
    split, rejections, degradations) rides in ``serve_load``.  A cell
    that saturates (backpressure rejections, admission degradation, or
    injected ``PIFFT_FAULT=serve:*`` chaos) is REPORTED, not fatal:
    the record tags ``degraded`` and the run exits 0 — the resilience
    contract."""
    import asyncio

    from cs87project_msolano2_tpu import obs
    from cs87project_msolano2_tpu.analyze.records import (
        emit_record,
        env_fingerprint,
    )
    from cs87project_msolano2_tpu.serve import (
        Dispatcher,
        ServeConfig,
        ShapeSpec,
    )
    from cs87project_msolano2_tpu.serve.loadgen import run_offered_load

    from cs87project_msolano2_tpu.serve import protocol as serve_protocol
    from cs87project_msolano2_tpu.serve.loadgen import run_wire_load

    smoke = args.smoke
    ns = tuple(SMOKE_SERVE_LOAD_NS if smoke else SERVE_LOAD_NS)
    rps_list = tuple(args.load_rps
                     or (SMOKE_SERVE_LOAD_RPS if smoke
                         else SERVE_LOAD_RPS))
    duration = args.load_duration or (
        SMOKE_SERVE_LOAD_DURATION_S if smoke else SERVE_LOAD_DURATION_S)
    wire_n = SMOKE_WIRE_LOAD_N if smoke else WIRE_LOAD_N
    wire_rps = SMOKE_WIRE_LOAD_RPS if smoke else WIRE_LOAD_RPS
    wire_duration = SMOKE_WIRE_LOAD_DURATION_S if smoke \
        else WIRE_LOAD_DURATION_S
    wire_processes = SMOKE_WIRE_LOAD_PROCESSES if smoke \
        else WIRE_LOAD_PROCESSES
    # the replay population: mixed op/priority/tenant over the wire
    # shape — the front door must multiplex classes, not just shapes
    population = [
        (3.0, {"n": wire_n}),
        (1.0, {"n": wire_n, "op": "conv", "priority": "high",
               "tenant": "batch"}),
    ]
    cfg = ServeConfig(max_batch=8, max_wait_ms=1.0, queue_depth=32)
    specs = [ShapeSpec(n=n) for n in ns]
    if wire_n not in ns:
        specs.append(ShapeSpec(n=wire_n))
    specs.append(ShapeSpec(n=wire_n, op="conv"))
    rows = []
    tails_by_protocol = {}

    async def run_all():
        async with Dispatcher(cfg, specs) as d:
            for n in ns:
                for rps in rps_list:
                    row = await run_offered_load(d, n, rps, duration)
                    # the classic cells drive the dispatcher directly
                    # — no wire at all; say so instead of letting the
                    # loader's "json" backfill claim otherwise
                    row["protocol"] = "inproc"
                    rows.append(row)
            # ---- the wire replay tier: same dispatcher, REAL socket,
            # one row set per dialect at the same offered load
            import numpy as _np

            for _w, _spec in population:
                # pay each replay group's trace/compile cost BEFORE
                # the measured schedule opens (the warmup pass every
                # SLO run owes itself — the cells measure the wire,
                # not XLA)
                _rng = _np.random.default_rng(0)
                _xr = _rng.standard_normal(
                    _spec["n"]).astype(_np.float32)
                await d.submit(_xr, _np.zeros_like(_xr)
                               if _spec.get("op", "fft") != "fft"
                               else _xr.copy(),
                               op=_spec.get("op", "fft"))
            server = await asyncio.start_server(
                lambda r, w: serve_protocol.handle_connection(d, r, w),
                "127.0.0.1", 0)
            port = server.sockets[0].getsockname()[1]
            try:
                for proto in ("json", "binary"):
                    mark = len(obs.events.snapshot()) \
                        if obs.enabled() else 0
                    for process in wire_processes:
                        for rps in wire_rps:
                            rows.append(await run_wire_load(
                                "127.0.0.1", port, proto, population,
                                rps, wire_duration, process=process,
                                seed=17))
                    if obs.enabled():
                        from cs87project_msolano2_tpu.analyze.loader \
                            import tail_attribution
                        # attribution over THIS dialect's event slice:
                        # the per-protocol p99 owner the wire-smoke
                        # gate reads (binary must not blame the queue/
                        # parse phase)
                        sliced = tail_attribution(
                            obs.events.snapshot()[mark:])
                        if sliced:
                            tails_by_protocol[proto] = {
                                label: {
                                    "p99_owner": r["p99_owner"],
                                    "p99_ms": r["p99_ms"],
                                    "p99_queue_share":
                                        r["p99_queue_share"],
                                    "p99_window_share":
                                        r["p99_window_share"],
                                    "p99_compute_share":
                                        r["p99_compute_share"]}
                                for label, r in sliced.items()}
            finally:
                server.close()
                await server.wait_closed()

    asyncio.run(run_all())

    # latency fields are always present (stable row schema); a cell
    # with no completions reports them None
    completed = [r for r in rows if r.get("p99_ms") is not None]
    record = {
        "metric": "serve_slo_p99_ms",
        "value": max((r["p99_ms"] for r in completed), default=None),
        "unit": "ms",
        "serve_load": rows,
        # the comparability key `analyze gate` groups rounds by: a
        # smoke SLO row must never read as a hardware regression
        "env": env_fingerprint(smoke=smoke),
    }
    if smoke:
        record["smoke"] = True
    if any(r["degraded"] or r["failed"] for r in rows):
        record["degraded"] = True
    if obs.enabled():
        record["run"] = obs.run_id()
        from cs87project_msolano2_tpu.analyze.loader import (
            tail_attribution,
        )
        from cs87project_msolano2_tpu.obs import export, metrics

        # the trace-derived tail-attribution table (docs/ANALYSIS.md):
        # the serve trace plane ran under this load, so the record can
        # say WHICH PHASE owned each shape's p99 — the span-level
        # sequel to the funnel/tube shares
        tails = tail_attribution(obs.snapshot())
        if tails:
            record["serve_tail_attribution"] = {
                label: {"p99_owner": row["p99_owner"],
                        "p99_ms": row["p99_ms"],
                        "p99_queue_share": row["p99_queue_share"],
                        "p99_window_share": row["p99_window_share"],
                        "p99_compute_share": row["p99_compute_share"]}
                for label, row in tails.items()}
        if tails_by_protocol:
            record["serve_tail_attribution_by_protocol"] = \
                tails_by_protocol
        if obs.events.dropped():
            # an overflowed buffer means the attribution above is
            # partial: say so in the record, not just the summary
            record["obs_dropped_events"] = obs.events.dropped()
        obs.emit("env", **record["env"])
        obs.emit("metrics", snapshot=metrics.snapshot())
        obs.flush()
        if args.trace_out:
            export.write_chrome_trace(args.trace_out)
    emit_record(record)
    return 0


def serve_mesh_main(args) -> int:
    """``--serve-mesh``: the mesh chaos SLO row set (docs/SERVING.md).

    Drives the open-loop chaos load (serve/loadgen.py,
    ``run_mesh_chaos_load``) against a warmed virtual device mesh with
    a MID-RUN DEVICE KILL, and emits ONE BENCH-round JSON line whose
    headline is the post-kill p99 and whose ``serve_mesh`` row set
    carries per-device utilization plus the pre/post-kill p99 split —
    the rows ``analyze.loader`` parses so ``pifft analyze gate`` can
    hold a floor on post-kill p99 across rounds.  The kill is the
    point: the record tags ``degraded`` and exits 0 — re-routing under
    failure is the behavior being measured, not an error."""
    import asyncio

    from cs87project_msolano2_tpu import obs
    from cs87project_msolano2_tpu.analyze.records import (
        emit_record,
        env_fingerprint,
    )
    from cs87project_msolano2_tpu.serve import MeshConfig, MeshDispatcher
    from cs87project_msolano2_tpu.serve.cli import MESH_SMOKE_SPECS
    from cs87project_msolano2_tpu.serve.loadgen import (
        mesh_report_rows,
        run_mesh_chaos_load,
    )

    smoke = args.smoke
    rps = (args.load_rps or [120.0 if smoke else 400.0])[0]
    duration = args.load_duration or (1.2 if smoke else 5.0)
    cfg = MeshConfig(devices=8, max_batch=2, max_wait_ms=5.0,
                     queue_depth=64)
    specs = list(MESH_SMOKE_SPECS)

    async def run():
        async with MeshDispatcher(cfg, specs) as mesh:
            return await run_mesh_chaos_load(mesh, specs, rps=rps,
                                             duration_s=duration,
                                             kill_at_frac=0.5)

    report = asyncio.run(run())
    rows = mesh_report_rows(report)
    record = {
        "metric": "serve_mesh_p99_post_kill_ms",
        "value": report["p99_post_kill_ms"],
        "unit": "ms",
        "serve_mesh": rows,
        "env": env_fingerprint(smoke=smoke),
    }
    if smoke:
        record["smoke"] = True
    if report["failover_tagged"] or report["failed"] \
            or report["degraded"]:
        record["degraded"] = True
    if report["problems"]:
        # a wrong ANSWER (unlike a killed device) is a real failure:
        # report it in the record and the exit code
        record["problems"] = report["problems"]
    if obs.enabled():
        record["run"] = obs.run_id()
        from cs87project_msolano2_tpu.obs import export, metrics

        obs.emit("env", **record["env"])
        obs.emit("metrics", snapshot=metrics.snapshot())
        obs.flush()
        if args.trace_out:
            export.write_chrome_trace(args.trace_out)
    emit_record(record)
    return 1 if report["problems"] else 0


def measure_sixstep_smoke(n: int) -> dict:
    """--smoke only: one interpret-safe cell through the hierarchical
    sixstep kernel with forced parameters (the static ladder serves
    sixstep from 2^25 — far past interpret reach), so CI exercises the
    recursive-carry kernel, its plan executor, and its degradation
    wiring end to end.  The timing is meaningless; the plumbing, the
    plan description, and the carry-pass-aware roofline fields are
    real."""
    import jax
    import jax.numpy as jnp

    from cs87project_msolano2_tpu import plans
    from cs87project_msolano2_tpu.plans.core import Plan
    from cs87project_msolano2_tpu.resilience import maybe_fault
    from cs87project_msolano2_tpu.utils.roofline import (
        plan_carry_passes,
        roofline_ceiling,
    )

    key = plans.make_key(n, layout="pi")
    plan = Plan(key=key, variant="sixstep",
                params={"tile": n >> 2, "r2": 2, "tail": 128},
                source="static")
    k0 = jax.random.PRNGKey(3)
    xr = jax.random.normal(k0, (n,), jnp.float32)
    xi = jax.random.normal(jax.random.fold_in(k0, 1), (n,), jnp.float32)

    def run_smoke():
        maybe_fault("bench")  # resilience injection site
        return _smoke_ms(plan.fn, xr, xi)

    ms = _retry(run_smoke, smoke=True, label=f"sixstep smoke n={n}")
    out = {"sixstep_smoke_n": n, "sixstep_smoke_ms": round(ms, 4),
           "sixstep_smoke_plan": plan.describe()}
    # like the large-n rows: the ceiling belongs to the variant that
    # SERVED (a chaos-demoted cell is judged by its rung's carries)
    served = plan.demotions[-1]["to"] if plan.degraded else plan.variant
    ceil = roofline_ceiling(plan_carry_passes(served))
    if ceil is not None:
        out["sixstep_smoke_roofline_ceiling"] = round(ceil, 3)
    if plan.degraded:
        out["sixstep_smoke_degraded"] = True
    return out


def main(argv=None) -> int:
    from cs87project_msolano2_tpu import plans
    from cs87project_msolano2_tpu.utils.roofline import (
        plan_carry_passes,
        roofline_ceiling,
        roofline_utilization,
    )

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="toy sizes + single-shot timing: exercise the "
                         "whole reporting pipeline offline (CI rot "
                         "check; numbers are meaningless)")
    ap.add_argument("--journal", default=None, metavar="PATH",
                    help="checkpoint each measurement cell to an atomic "
                         "JSONL journal (docs/RESILIENCE.md)")
    ap.add_argument("--resume", action="store_true",
                    help="skip cells already completed in the journal "
                         "(default journal: bench-journal.jsonl); a "
                         "killed bench re-run this way recomputes only "
                         "what the kill took")
    ap.add_argument("--events", default=None, metavar="PATH",
                    help="write the structured observability event "
                         "stream (JSONL) to PATH and tag the record "
                         "with the run id (docs/OBSERVABILITY.md)")
    ap.add_argument("--trace-out", default=None, metavar="PATH",
                    help="write the run's spans as Chrome trace JSON "
                         "(open in Perfetto / chrome://tracing)")
    ap.add_argument("--serve-load", action="store_true",
                    help="run the serving SLO suite instead of the "
                         "kernel bench: open-loop offered load against "
                         "the serve/ dispatcher, p50/p99 + throughput "
                         "per (shape, rps) cell (docs/SERVING.md)")
    ap.add_argument("--load-rps", type=float, nargs="*", default=None,
                    help="serve-load: offered loads in requests/s "
                         "(default: the tier's standard ladder)")
    ap.add_argument("--load-duration", type=float, default=None,
                    metavar="S", help="serve-load: seconds per cell")
    ap.add_argument("--serve-mesh", action="store_true",
                    help="run the mesh chaos SLO suite: open-loop "
                         "load over a virtual 8-device mesh with a "
                         "mid-run device kill; emits the serve_mesh "
                         "row set (per-device utilization, "
                         "pre/post-kill p99 — docs/SERVING.md)")
    args = ap.parse_args(argv)

    from cs87project_msolano2_tpu import obs

    if args.events:
        obs.enable(events_path=args.events)
    elif args.trace_out and not obs.enabled():
        obs.enable()

    if args.serve_load:
        return serve_load_main(args)
    if args.serve_mesh:
        return serve_mesh_main(args)

    n = SMOKE_N if args.smoke else N
    logns = SMOKE_LARGE_LOGNS if args.smoke else LARGE_LOGNS

    journal = None
    if args.journal or args.resume:
        from cs87project_msolano2_tpu.resilience import Journal

        journal = Journal(args.journal or "bench-journal.jsonl")
        if args.resume:
            journal.load()
        else:
            # a fresh (non-resumed) run must not inherit stale cells
            journal.reset()
        # cells are keyed by name ("flagship", ...), so the journal
        # carries its run configuration and --resume refuses a
        # mismatch (Journal.guard_config, shared with the harness
        # sweeps): resuming a full-N bench from a smoke journal would
        # splice toy numbers into the headline record
        try:
            journal.guard_config(
                {"n": n, "logns": list(logns), "smoke": bool(args.smoke)},
                label="bench")
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2

    def cell(name, compute, probe_n=None):
        """compute() -> JSON-safe payload dict, checkpointed per cell.
        An EMPTY payload (a row whose measurement failed outright) is
        never journaled: --resume must re-measure it, not canonize the
        failure as a completed cell.  Each computed cell runs under a
        named observability span (with a nested funnel/tube phase probe
        for transform cells) and lands in the event stream — no-ops
        while the obs subsystem is disabled."""
        if journal is not None and journal.has(name):
            rec = dict(journal.get(name))
            rec.pop("cell", None)
            plans.warn(f"bench --resume: cell {name} loaded from journal "
                       f"(not re-measured)")
            obs.emit("bench_cell_loaded", cell={"name": name})
            return rec
        with obs.span("cell", cell={"name": name, "n": probe_n or n}):
            if probe_n is not None:
                _phase_probe(probe_n)
            out = compute()
        if journal is not None and out:
            journal.record(name, out)
        obs.emit("bench_cell", cell={"name": name},
                 ok=bool(out), **(out if out else {}))
        return out

    def flagship_cell():
        tpu_ms, plan = measure_tpu_ms(n, smoke=args.smoke)
        out = {"tpu_ms": tpu_ms, "plan": plan.describe(),
               "device_kind": plan.key.device_kind,
               "backend": getattr(plan.key, "backend", "tpu")}
        if plan.degraded:
            out["degraded"] = True
        return out

    def xla_cell():
        # a None measurement is a FAILED cell, not a completed one with
        # value None: return {} so cell() leaves it out of the journal
        # and --resume re-measures it once the blip passes
        ms = measure_xla_fft_ms(n, smoke=args.smoke)
        return {} if ms is None else {"xla_ms": ms}

    flagship = cell("flagship", flagship_cell, probe_n=n)
    xla = cell("xla", xla_cell)
    large = {}
    degraded_rows = False
    for logn in logns:
        row = cell(f"n2^{logn}",
                   lambda logn=logn: measure_large_n_row(
                       logn, smoke=args.smoke),
                   probe_n=1 << logn)
        degraded_rows |= bool(row.get(f"n2^{logn}_degraded"))
        large.update(row)
        # the half-spectrum row at the SAME n, right after its c2c
        # sibling: GFLOP/s + roofline_util side by side, and the
        # metered HBM-bytes delta the rfft-smoke gate asserts is
        # exactly half the c2c cell's (docs/REAL.md)
        rrow = cell(f"rfft2^{logn}",
                    lambda logn=logn: measure_rfft_row(
                        logn, smoke=args.smoke),
                    probe_n=1 << logn)
        degraded_rows |= bool(rrow.get(f"rfft2^{logn}_degraded"))
        large.update(rrow)
        # the bf16-storage row at the SAME n, beside its fp32-storage
        # siblings: GFLOP/s + dtype-aware roofline side by side, and
        # the metered HBM-bytes delta the precision-smoke gate asserts
        # is exactly half the split3 cell's (docs/PRECISION.md)
        prow = cell(f"bf16_2^{logn}",
                    lambda logn=logn: measure_precision_row(
                        logn, "bf16", smoke=args.smoke),
                    probe_n=1 << logn)
        degraded_rows |= bool(prow.get(f"bf16_2^{logn}_degraded"))
        large.update(prow)
        # the spectral-op rows at the SAME n (docs/APPS.md): the fused
        # conv cell whose metered HBM delta the apps-smoke gate holds
        # at the fused floor, and the overlap-save streaming cell with
        # its chunk-count / overlap-waste columns
        large.update(cell(f"conv2^{logn}",
                          lambda logn=logn: measure_conv_row(
                              logn, smoke=args.smoke),
                          probe_n=1 << logn))
        large.update(cell(f"os2^{logn}",
                          lambda logn=logn: measure_os_row(
                              logn, smoke=args.smoke),
                          probe_n=1 << logn))
    # the any-length conv row (docs/PLANS.md "Arbitrary n"): fused
    # circular conv at the non-pow2 length cheapest_length picks,
    # with the pad-to-pow2 control's metered charge beside it — the
    # bluestein-smoke bytes gate reads both columns off this row
    large.update(cell("conv_np",
                      lambda: measure_conv_np_row(smoke=args.smoke),
                      probe_n=3 * (1 << (8 if args.smoke else 18))))
    # the heterogeneous-backend rows (docs/BACKENDS.md): bounded n in
    # EVERY tier — the gpu rung interprets on non-GPU hosts and the
    # cpu-native rung is a correctness/plumbing rail, so hero sizes
    # would measure the harness, not the backend
    for logn in BACKEND_ROW_LOGNS:
        for bk in BACKEND_ROW_BACKENDS:
            btag = f"{BACKEND_ROW_PREFIX[bk]}2^{logn}"
            brow = cell(btag,
                        lambda logn=logn, bk=bk: measure_backend_row(
                            logn, bk, smoke=args.smoke),
                        probe_n=1 << logn)
            degraded_rows |= bool(brow.get(f"{btag}_degraded"))
            large.update(brow)
    if args.smoke:
        # the interpret-safe sixstep cell (docs/KERNELS.md): rides only
        # in smoke mode — on hardware the 2^25..2^27 rows above exercise
        # the real thing
        six = cell("sixstep_smoke",
                   lambda: measure_sixstep_smoke(SMOKE_N),
                   probe_n=SMOKE_N)
        degraded_rows |= bool(six.get("sixstep_smoke_degraded"))
        large.update(six)
        # the C baseline runs at the FULL flagship N (the native
        # harness is not parameterized here): in smoke mode that is
        # both expensive and an apples-to-oranges ratio against the
        # toy-n TPU time — omit vs_baseline rather than publish it
        c_ms = None
    else:
        c_ms = cell("c_baseline",
                    lambda: {"c_ms": measure_c_baseline_ms()})["c_ms"]

    from cs87project_msolano2_tpu.analyze.records import (
        emit_record,
        env_fingerprint,
    )

    tpu_ms = flagship["tpu_ms"]
    xla_ms = xla.get("xla_ms")
    gflops = 5.0 * n * np.log2(n) / (tpu_ms * 1e-3) / 1e9
    record = {
        "metric": f"fft1d_n2^{n.bit_length() - 1}_complex64_gflops",
        "value": round(gflops, 1),
        "unit": "GFLOP/s",
        "plan": flagship["plan"],
        # the environment fingerprint: the comparability key the
        # regression gate groups rounds by (docs/ANALYSIS.md) — a smoke
        # round must refuse comparison against a hardware round instead
        # of reading as a throughput cliff.  The device kind is the one
        # that actually served the flagship measurement.
        "env": env_fingerprint(smoke=bool(args.smoke),
                               device_kind=flagship.get("device_kind")),
    }
    if args.smoke:
        record["smoke"] = True
    if flagship.get("degraded") or degraded_rows:
        # a demoted plan anywhere taints the whole line: never let a
        # degraded run read as a healthy number (docs/RESILIENCE.md)
        record["degraded"] = True
    if c_ms is not None:
        record["vs_baseline"] = round(c_ms / tpu_ms, 1)
    pd = flagship["plan"]
    served = pd.get("demoted_to") or pd["variant"]
    passes = plan_carry_passes(served)
    ceil = roofline_ceiling(passes)
    if ceil is not None:
        record["roofline_ceiling"] = round(ceil, 3)
    util = roofline_utilization(n, tpu_ms, flagship["device_kind"],
                                passes or 0,
                                backend=flagship.get("backend", "tpu"))
    if util is not None:
        record["roofline_util"] = round(util, 3)
        if ceil:
            record["util_of_ceiling"] = round(util / ceil, 3)
    if xla_ms is not None:
        record["vs_xla_fft"] = round(xla_ms / tpu_ms, 2)
        record["xla_fft_ms"] = round(xla_ms, 4)
    record.update(large)
    if obs.enabled():
        # the run id ties this record to every event/span/metric the
        # run emitted; the metrics snapshot is the stream's last word,
        # and the env event fingerprints the stream for the analyze
        # loader exactly as record["env"] fingerprints the record
        record["run"] = obs.run_id()
        from cs87project_msolano2_tpu.obs import export, metrics

        obs.emit("env", **record["env"])
        obs.emit("metrics", snapshot=metrics.snapshot())
        obs.flush()
        if args.trace_out:
            export.write_chrome_trace(args.trace_out)
            plans.warn(f"chrome trace written to {args.trace_out} "
                       f"(open in Perfetto)")
    emit_record(record)
    return 0


if __name__ == "__main__":
    sys.exit(main())
