"""Headline benchmark: 1-D complex FFT, N = 2^20, single TPU chip.

Measures the framework's flagship path (pi-layout output — gather
excluded exactly as the reference excludes it from timing) against TWO
baselines on this host and prints ONE JSON line:

    {"metric": ..., "value": GFLOP/s, "unit": ...,
     "vs_baseline": ..., "vs_xla_fft": ..., "xla_fft_ms": ..., "plan": ...,
     "roofline_util": ...,
     "n2^22_ms": ..., "n2^22_gflops": ..., "n2^22_vs_xla": ...,
     "n2^22_roofline_util": ..., "n2^24_...": ...}

* vs_baseline — wall-clock speedup over the native C backend at the same
  N (BASELINE.md north star: >= 10x; GFLOP/s uses the standard
  5 N log2 N FFT flop count).
* vs_xla_fft — wall-clock speedup over `jnp.fft.fft` ON THE SAME CHIP at
  the same N: the strongest same-hardware comparison (XLA's own FFT is
  the production alternative a user would otherwise call).  Reported for
  the flagship AND for every large-n row, so the large-n falloff is
  compared against what XLA manages at that same n.
* roofline_util — achieved fraction of the HBM roofline charging the
  minimum 16 B/element traffic (utils/roofline.py): carry-free paths
  (fused, n <= 2^20) top out at 1.0; any materialized-intermediate
  design — the fourstep HBM carry included — is bandwidth-capped at
  ~0.5, and how closely a path approaches ITS cap measures the
  launch/retiling/serialization overhead the single-pass pipeline
  removes — the figure that tracks the large-n falloff (and its fix)
  release over release.

Kernel selection goes through the plan subsystem
(cs87project_msolano2_tpu.plans): `plans.tune` races the shared
candidate ladder (plans/ladder.py — the single source of truth this file
used to own) ONCE per (device kind, n, layout) key and persists the
winner, so a warm session reaches its first timed FFT on a cache hit
with no re-race; this file just tunes-or-loads and reports the winning
plan.  Large-n rows each tune THEIR key — above the documented
crossover (plans.ladder.FOURSTEP_MIN_N) the ladder leads with the
fourstep entries.

Measurement method: loop-slope (utils/timing.py) — on the axon TPU relay
block_until_ready is not a real barrier, so the FFT is iterated K times
inside one jitted fori_loop ending in a scalar fetch, at two K values;
the per-FFT time is the slope and the ~100 ms relay overhead cancels.
On hardware where block_until_ready is honest the same method simply
measures with less noise.

``--smoke`` (CI): run the whole reporting pipeline at toy sizes with
single-shot timing so the entry point cannot silently rot offline.  The
numbers are meaningless (interpret mode); the JSON shape, the plan
resolution, and every measurement seam are real.
"""

import argparse
import json
import sys

import numpy as np

N = 1 << 20

# the reference's pthreads analysis reaches n=2^24; these rows track the
# large-n falloff the fourstep path exists to close
LARGE_LOGNS = (22, 24)

SMOKE_N = 1 << 12
SMOKE_LARGE_LOGNS = (13,)


def _smoke_ms(fn, *args) -> float:
    """Single-shot wall time for --smoke: exercises the exact callable
    the real path would measure, with none of the loop-slope cost.
    Interpret-mode numbers mean nothing; only the plumbing is under
    test."""
    import jax

    from cs87project_msolano2_tpu.utils.timing import time_ms

    ms, _ = time_ms(jax.jit(fn), *args, reps=2, warmup=1)
    return ms


def measure_tpu_ms(n: int = N, smoke: bool = False) -> tuple:
    """(ms, plan) for an n-point pi-layout key, via the plans
    subsystem's shared measurement policy (tuned-race ms reused, cached
    plans re-timed with the tuner's own timer, a non-compiling cached
    winner re-raced)."""
    from cs87project_msolano2_tpu import plans

    key = plans.make_key(n, layout="pi")
    if smoke:
        import jax
        import jax.numpy as jnp

        plan = plans.get_plan(key)
        k0 = jax.random.PRNGKey(0)
        xr = jax.random.normal(k0, (n,), jnp.float32)
        xi = jax.random.normal(jax.random.fold_in(k0, 1), (n,), jnp.float32)
        return _smoke_ms(plan.fn, xr, xi), plan
    return plans.measured_ms(key)


def measure_xla_fft_ms(n: int = N, smoke: bool = False):
    """jnp.fft.fft on the same chip at the same n — the same-hardware
    comparison VERDICT.md round 2 demanded.  The loop body carries
    complex state (no per-iteration plane split/merge) so only the FFT
    itself plus one scaling is timed — the same epilogue the Pallas body
    pays.  Falls back to the unrolled slope if the FFT custom-call
    cannot lower inside a fori_loop; returns None (metric omitted) if it
    cannot be measured at all rather than losing the other results."""
    import jax
    import jax.numpy as jnp

    from cs87project_msolano2_tpu.plans import warn
    from cs87project_msolano2_tpu.utils.timing import (
        loop_slope_ms,
        unrolled_slope_ms,
    )

    key = jax.random.PRNGKey(2)
    xr = jax.random.normal(key, (n,), jnp.float32)
    xi = jax.random.normal(jax.random.fold_in(key, 1), (n,), jnp.float32)
    inv_rn = np.complex64(1.0 / np.sqrt(n))

    # The relay cannot pass complex64 across the program ABI (eager
    # complex ops, complex program inputs, and complex While carries are
    # all Unimplemented), so the loop body must carry float planes and
    # pay a complex-merge + re/im-split every iteration.  That epilogue
    # is NOT the XLA FFT's cost — charging it would overstate our
    # speedup — so it is measured separately with the same method (the
    # identical elementwise chain minus the fft) and subtracted.
    inv = np.float32(inv_rn.real)

    def body_fft(c):
        y = jnp.fft.fft(c[0] + 1j * c[1])
        return jnp.real(y) * inv, jnp.imag(y) * inv

    def body_epilogue(c):
        y = c[0] + 1j * c[1]
        return jnp.real(y) * inv, jnp.imag(y) * inv

    if smoke:
        return _smoke_ms(body_fft, (xr, xi))

    try:
        raw = loop_slope_ms(body_fft, (xr, xi), k1=64, k2=1024, reps=5,
                            min_delta_ms=100.0, cache=False)
    except Exception as e:
        # some backends cannot lower the FFT custom-call inside a While
        # body — statically unroll instead (modest k2: program size and
        # remote-compile time grow linearly with the unroll)
        warn(f"xla fft n={n} under fori_loop failed ({type(e).__name__}); "
             f"trying unrolled slope")
        try:
            raw = unrolled_slope_ms(body_fft, (xr, xi), k1=8, k2=64,
                                    reps=7, min_delta_ms=20.0, max_k=256,
                                    cache=False)
        except Exception as e2:
            warn(f"xla fft n={n} not measurable on this backend "
                 f"({type(e2).__name__}); omitting vs_xla_fft")
            return None
    try:
        epilogue = loop_slope_ms(body_epilogue, (xr, xi), k1=64, k2=1024,
                                 reps=5, min_delta_ms=40.0, cache=False)
    except Exception as e:
        warn(f"xla epilogue n={n} not resolvable ({type(e).__name__}); "
             f"vs_xla_fft conservatively uncorrected")
        epilogue = 0.0
    # the epilogue is a small fraction of the FFT; if its measurement
    # came back implausibly large (relay noise), don't let it eat the
    # result — cap the correction at half the raw time
    return max(raw - epilogue, raw * 0.5)


def measure_large_n_ms(logns=LARGE_LOGNS, smoke: bool = False) -> dict:
    """Large-n reach rows (the reference's pthreads analysis goes to
    n=2^24): per-key plans at each 2^logn — each n gets the plan tuned
    (or statically chosen) for ITS key, not the flagship's shape — with
    the same-chip XLA comparison and the HBM-roofline utilization
    recorded PER ROW, so the large-n falloff is tracked release over
    release.  Best-effort — a failed row drops its fields, not the
    bench, and says so through plans.warn (greppable `# ` diagnostics,
    the PIF501 discipline)."""
    from cs87project_msolano2_tpu import plans
    from cs87project_msolano2_tpu.utils.roofline import roofline_utilization

    out = {}
    for logn in logns:
        nn = 1 << logn
        tag = f"n2^{logn}"
        try:
            ms, plan = measure_tpu_ms(nn, smoke=smoke)
        except Exception as e:
            plans.warn(f"large-n 2^{logn} not measured "
                       f"({type(e).__name__}: {str(e)[:200]})")
            continue
        out[f"{tag}_ms"] = round(ms, 4)
        out[f"{tag}_gflops"] = round(
            5.0 * nn * np.log2(nn) / (ms * 1e-3) / 1e9, 1)
        out[f"{tag}_plan"] = plan.describe()
        util = roofline_utilization(nn, ms, plan.key.device_kind)
        if util is not None:
            out[f"{tag}_roofline_util"] = round(util, 3)
        try:
            xla_ms = measure_xla_fft_ms(nn, smoke=smoke)
        except Exception as e:
            plans.warn(f"large-n 2^{logn} xla comparison failed "
                       f"({type(e).__name__}: {str(e)[:200]})")
            xla_ms = None
        if xla_ms is not None:
            out[f"{tag}_vs_xla"] = round(xla_ms / ms, 2)
    return out


def measure_c_baseline_ms() -> float:
    from cs87project_msolano2_tpu.backends.cpu import num_cores
    from cs87project_msolano2_tpu.backends.registry import get_backend
    from cs87project_msolano2_tpu.cli import make_input

    p = 1
    while p * 2 <= num_cores():
        p *= 2
    x = make_input(N, seed=0)
    return get_backend("cpu").run(x, p, reps=3).total_ms


def main(argv=None) -> int:
    from cs87project_msolano2_tpu import plans
    from cs87project_msolano2_tpu.utils.roofline import roofline_utilization

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="toy sizes + single-shot timing: exercise the "
                         "whole reporting pipeline offline (CI rot "
                         "check; numbers are meaningless)")
    args = ap.parse_args(argv)

    n = SMOKE_N if args.smoke else N
    logns = SMOKE_LARGE_LOGNS if args.smoke else LARGE_LOGNS

    tpu_ms, plan = measure_tpu_ms(n, smoke=args.smoke)
    xla_ms = measure_xla_fft_ms(n, smoke=args.smoke)
    large = measure_large_n_ms(logns, smoke=args.smoke)
    if args.smoke:
        # the C baseline runs at the FULL flagship N (the native
        # harness is not parameterized here): in smoke mode that is
        # both expensive and an apples-to-oranges ratio against the
        # toy-n TPU time — omit vs_baseline rather than publish it
        c_ms = None
    else:
        c_ms = measure_c_baseline_ms()
    gflops = 5.0 * n * np.log2(n) / (tpu_ms * 1e-3) / 1e9
    record = {
        "metric": f"fft1d_n2^{n.bit_length() - 1}_complex64_gflops",
        "value": round(gflops, 1),
        "unit": "GFLOP/s",
        "plan": plan.describe(),
    }
    if args.smoke:
        record["smoke"] = True
    if c_ms is not None:
        record["vs_baseline"] = round(c_ms / tpu_ms, 1)
    util = roofline_utilization(n, tpu_ms, plan.key.device_kind)
    if util is not None:
        record["roofline_util"] = round(util, 3)
    if xla_ms is not None:
        record["vs_xla_fft"] = round(xla_ms / tpu_ms, 2)
        record["xla_fft_ms"] = round(xla_ms, 4)
    record.update(large)
    print(json.dumps(record))
    return 0


if __name__ == "__main__":
    sys.exit(main())
