"""Headline benchmark: 1-D complex FFT, N = 2^20, single TPU chip.

Measures the framework's flagship path (XLA long-range stages + Pallas
VMEM tile kernel, pi layout — gather excluded exactly as the reference
excludes it from timing) against the native C baseline on this host, and
prints ONE JSON line:

    {"metric": ..., "value": GFLOP/s, "unit": ..., "vs_baseline": speedup}

vs_baseline is wall-clock speedup over the C backend at the same N
(BASELINE.md north star: >= 10x; GFLOP/s uses the standard 5 N log2 N
FFT flop count).

Measurement method: loop-slope (utils/timing.py) — on the axon TPU relay
block_until_ready is not a real barrier, so the FFT is iterated K times
inside one jitted fori_loop ending in a scalar fetch, at two K values;
the per-FFT time is the slope and the ~100 ms relay overhead cancels.
On hardware where block_until_ready is honest the same method simply
measures with less noise.
"""

import json
import sys

import numpy as np

N = 1 << 20
# (impl, tile, cb): two-kernel first (fastest measured: ~0.11 ms at
# tile=2^16 cb=2^14 = ~930 GFLOP/s), hybrid as fallback configs
CONFIGS = (
    ("two-kernel", 1 << 16, 1 << 14),
    ("two-kernel", 1 << 16, 1 << 16),
    ("hybrid", 1 << 16, None),
    ("hybrid", 1 << 15, None),
)


def measure_tpu_ms() -> float:
    import jax
    import jax.numpy as jnp

    from cs87project_msolano2_tpu.ops.pallas_fft import (
        fft_pi_layout_pallas,
        fft_pi_layout_pallas2,
    )
    from cs87project_msolano2_tpu.utils.timing import loop_slope_ms

    key = jax.random.PRNGKey(0)
    xr = jax.random.normal(key, (N,), jnp.float32)
    xi = jax.random.normal(jax.random.fold_in(key, 1), (N,), jnp.float32)

    inv_rn = np.float32(1.0 / np.sqrt(N))  # keep loop iterates in range
    best = float("inf")
    for impl, tile, cb in CONFIGS:
        try:
            def body(c, impl=impl, t=tile, cb=cb):
                if impl == "two-kernel":
                    yr, yi = fft_pi_layout_pallas2(c[0], c[1], tile=t, cb=cb)
                else:
                    yr, yi = fft_pi_layout_pallas(c[0], c[1], tile=t)
                return yr * inv_rn, yi * inv_rn

            ms = loop_slope_ms(body, (xr, xi), k1=32, k2=512, reps=3)
            best = min(best, ms)
        except Exception as e:  # a config failing to compile is not fatal
            print(f"# {impl} tile={tile} cb={cb} failed: {type(e).__name__}",
                  file=sys.stderr)
    if not np.isfinite(best):
        raise RuntimeError("no benchmark configuration compiled")
    return best


def measure_c_baseline_ms() -> float:
    from cs87project_msolano2_tpu.backends.cpu import num_cores
    from cs87project_msolano2_tpu.backends.registry import get_backend
    from cs87project_msolano2_tpu.cli import make_input

    p = 1
    while p * 2 <= num_cores():
        p *= 2
    x = make_input(N, seed=0)
    return get_backend("cpu").run(x, p, reps=3).total_ms


def main() -> int:
    tpu_ms = measure_tpu_ms()
    c_ms = measure_c_baseline_ms()
    gflops = 5.0 * N * np.log2(N) / (tpu_ms * 1e-3) / 1e9
    print(
        json.dumps(
            {
                "metric": "fft1d_n2^20_complex64_gflops",
                "value": round(gflops, 1),
                "unit": "GFLOP/s",
                "vs_baseline": round(c_ms / tpu_ms, 1),
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
