#!/usr/bin/env python3
"""Experiment harness (L4): sweep (T reps x n grid x p grid) per backend,
append TSV rows, estimate remaining time, optionally cross-verify.

Parity with the reference drivers (cpu/pthreads/run-experiments-and-
analyze-results:27-69, gpu/cuda/run-experiments:15-73) plus what they
lacked: resume (completed (n, p, rep) cells are skipped, journaled in
an atomic per-cell JSONL next to the append-only TSV — the reference's
interrupted sweeps kept completed rows, we also skip re-running them,
and a kill that truncates the TSV's last line can no longer lose the
sweep's place), per-config cross-backend verification, and a
--backend list so one sweep drives the dual-backend agreement story.

Fault discipline (docs/RESILIENCE.md): every cell runs under the shared
``resilience.with_retry`` policy — TRANSIENT infrastructure faults
(relay drops, worker restarts) retry on the 30/60/120 s backoff ladder
exactly as the old local ``run_with_retry`` did, while CAPACITY and
PERMANENT faults (and ValueError's cell-infeasibility contract, which
classifies PERMANENT) re-raise immediately: an OOM retried three times
is three OOMs and twenty minutes of sweep lost.

Observability (docs/OBSERVABILITY.md): every cell runs under a
``sweep_cell`` span; ``--events PATH`` (or ``PIFFT_OBS_EVENTS``) arms
the structured event stream — one event per completed/skipped cell,
progress events carrying the remaining-time estimate (computed from
the completed-cell span durations, the reference harness's ETA
feature), and a final metrics snapshot.  Disarmed, the layer is a
no-op.

TSV contract: `n  p  total_ms  funnel_ms  tube_ms` (5 columns, exactly
the reference's …pthreads.c:487-491), one file per backend.
"""

from __future__ import annotations

import argparse
import os
import sys
from collections import Counter

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np  # noqa: E402

from cs87project_msolano2_tpu import obs  # noqa: E402
from cs87project_msolano2_tpu.backends.registry import get_backend  # noqa: E402
from cs87project_msolano2_tpu.cli import make_input  # noqa: E402
from cs87project_msolano2_tpu.obs.spans import clock  # noqa: E402
from cs87project_msolano2_tpu.resilience import (  # noqa: E402
    Journal,
    classify,
    call_with_retry,
    maybe_fault,
)
from cs87project_msolano2_tpu.utils.timing import (  # noqa: E402
    reset_program_warm_state,
)
from cs87project_msolano2_tpu.utils.verify import (  # noqa: E402
    pi_layout_to_natural,
    rel_err,
)


def parse_grid(spec: str) -> list[int]:
    """'1024,2048' or '1024..8192' (powers-of-two range, inclusive)."""
    if ".." in spec:
        lo, hi = (int(v) for v in spec.split(".."))
        out = []
        v = lo
        while v <= hi:
            out.append(v)
            v *= 2
        return out
    return [int(v) for v in spec.split(",")]


def result_path(outdir: str, backend: str,
                oversubscribe: bool = False, full: bool = False) -> str:
    """Oversubscribed sweeps get a DISTINCT file: mixing p<=cores rows
    (per-processor regime) and p>cores rows (serialized regime) in one
    TSV across resumes would leave no single law that fits it.  The
    `-oversub-` stem also auto-selects the serialized model in
    analyze_results.model_for / the awk fallback.  `full` marks the
    reference-style deep-replication dataset (…-results-full.tsv, cf.
    the reference's 256-rep …-results-full.csv)."""
    stem = f"{backend}-oversub" if oversubscribe else backend
    tail = "-results-full.tsv" if full else "-results.tsv"
    return os.path.join(outdir, f"fourier-parallel-pi-{stem}{tail}")


def journal_for(path: str) -> Journal:
    """The per-cell JSONL journal riding next to a sweep TSV."""
    return Journal(f"{path}.journal.jsonl")


def done_counts(path: str, journal: Journal | None = None) -> Counter:
    """(n, p) -> completed replication count.

    The TSV scan (pre-journal sweeps) and the JSONL journal are merged
    per-cell by max: a TSV written before the journal existed still
    resumes, and a kill that truncated the TSV's final line cannot
    erase a rep the fsynced journal already committed."""
    done: Counter = Counter()
    if os.path.exists(path):
        with open(path) as fh:
            for line in fh:
                parts = line.rstrip("\n").split("\t")
                if len(parts) in (5, 6) and parts[0].isdigit():
                    done[(int(parts[0]), int(parts[1]))] += 1
    if journal is not None:
        from_journal: Counter = Counter()
        for cell_id in journal.load():
            parts = cell_id.split(":")
            if len(parts) == 3 and parts[0].isdigit() and parts[1].isdigit():
                from_journal[(int(parts[0]), int(parts[1]))] += 1
        for cell_key, count in from_journal.items():
            done[cell_key] = max(done[cell_key], count)
    return done


def grid_cells(backend_name: str, ns: list[int], ps: list[int],
               oversubscribe: bool = False, for_verification: bool = False):
    """Returns (backend, cells, oversubscribed).

    `oversubscribed` is True only when the flag was given AND the p-grid
    actually exceeds capacity: on a host whose cores cover the whole
    grid the rows run genuinely in parallel (per-processor regime), and
    routing them to the serialized-model -oversub- TSV would fit the
    wrong law against correct data.

    `for_verification` keeps mid-regime p (1 < p <= cores) in an
    oversubscribed grid: the drop below exists to keep the TIMING file
    regime-pure, but correctness does not depend on the timing regime,
    so the verify pass must cover every cell the user asked for."""
    backend = get_backend(backend_name)
    cap = backend.capacity()
    oversubscribed = (oversubscribe and cap is not None
                      and any(p > cap for p in ps))
    if oversubscribed:
        # Deliberately run more virtual processors than real cores (the
        # reference's probe-and-clip would refuse): with all cores busy,
        # wall time tracks the SUM of per-processor work — the
        # `serialized` law model in analysis/analyze_results.py — which
        # still verifies the funnel/tube complexity, just not speedup.
        # Keep the file regime-pure: rows with 1 < p <= cap run genuinely
        # in parallel (time ~ total/p, not ~ total/cap) and would break
        # the single-beta serialized fit, so they are dropped here — a
        # separate normal (capacity-clipped) sweep covers them.  p = 1
        # stays: both laws coincide there and the speedup table needs it.
        if not for_verification:
            mixed = [p for p in ps if 1 < p <= cap]
            if mixed:
                print(f"# {backend_name}: dropping mid-regime p {mixed} "
                      "from the oversubscribed sweep (they run truly "
                      "parallel; sweep them without --oversubscribe)",
                      file=sys.stderr)
            ps = [p for p in ps if p == 1 or p > cap]
            print(f"# {backend_name}: capacity {cap} OVERSUBSCRIBED — "
                  f"p-grid {ps}; rows go to the -oversub- TSV, which the "
                  "analysis auto-maps to the serialized law model",
                  file=sys.stderr)
        else:
            print(f"# {backend_name}: capacity {cap} oversubscribed — "
                  f"verifying the FULL p-grid {ps} (no rows are written)",
                  file=sys.stderr)
        cap = None
    ps_eff = [p for p in ps if cap is None or p <= cap]
    if len(ps_eff) < len(ps):
        print(f"# {backend_name}: capacity {cap} clips p-grid to {ps_eff}",
              file=sys.stderr)
    cells = [(n, p) for n in ns for p in ps_eff if p <= n]
    return backend, cells, oversubscribed


def _on_retry(exc: BaseException, attempt: int, pause: float) -> None:
    """Between-retry hook for the shared policy: the relay that just
    dropped likely lost its compiled programs too, so reset the slope
    cache's warm-skip flags — no post-reconnect recompile may land
    inside a timed window."""
    nreset = reset_program_warm_state()
    print(f"# {classify(exc).value} backend error ({type(exc).__name__}: "
          f"{str(exc)[:120]}); retry {attempt} in {pause:.0f}s"
          + (f" (re-warming {nreset} cached timing programs)"
             if nreset else ""), file=sys.stderr)


def run_cell(backend, x, p, fetch: bool = False, timers: bool = True):
    """backend.run under the shared resilience retry policy.

    The old local ``run_with_retry`` (4 attempts, 30/60/120 s backoff,
    ValueError passthrough) is now the DEFAULT ``resilience.RetryPolicy``
    plus classification: TRANSIENT infrastructure faults earn the
    backoff ladder (observed relay drops and >60 s worker restarts),
    CAPACITY/PERMANENT — including ValueError's cell-infeasibility
    contract — re-raise on first failure.  The append-only TSV and the
    fsynced journal keep completed rows either way.
    """

    def attempt():
        maybe_fault("harness")  # resilience injection site
        return backend.run(x, p, fetch=fetch, timers=timers)

    return call_with_retry(attempt, on_retry=_on_retry,
                           label=f"cell n={x.shape[-1]} p={p}")


def sweep(backend_name: str, ns: list[int], ps: list[int], reps: int,
          outdir: str, resume: bool, seed: int,
          oversubscribe: bool = False, full: bool = False) -> str:
    """Timing pass: append TSV rows, NO result fetches (on remote
    accelerators the first device->host transfer permanently inflates
    per-dispatch latency — see Backend.run; verification is a separate
    pass that runs after ALL timing)."""
    os.makedirs(outdir, exist_ok=True)
    backend, cells, oversubscribed = grid_cells(
        backend_name, ns, ps, oversubscribe)
    path = result_path(outdir, backend_name, oversubscribed, full)
    journal = journal_for(path)
    if not os.path.exists(path):
        # a rotated/deleted TSV invalidates the sidecar: the journal may
        # only ever claim cells whose data exists, so a redone sweep
        # must not skip cells an old journal remembers
        journal.reset()
    done = done_counts(path, journal) if resume else Counter()

    todo = sum(max(reps - done[c], 0) for c in cells)
    # completed-cell wall durations (the sweep_cell spans' own clock,
    # obs.spans.clock — the sanctioned progress/ETA clock, PIF106);
    # feeds the remaining-time estimate below.  Display only, never a
    # measurement (row timings come from the backend's loop-slope
    # timers).
    cell_s: list = []
    completed = 0

    with open(path, "a") as fh:
        for n, p in cells:
            x = make_input(n, seed)
            for rep in range(done[(n, p)], reps):
                cell_id = {"n": n, "p": p, "rep": rep}
                t0 = clock()
                try:
                    with obs.span("sweep_cell", cell=cell_id,
                                  backend=backend_name):
                        res = run_cell(backend, x, p)
                except ValueError as e:
                    # per-(n, p) infeasibility (e.g. einsum's p*n cap) is
                    # a property of the cell, not an error of the sweep
                    print(f"# {backend_name} n={n} p={p} skipped: {e}",
                          file=sys.stderr)
                    obs.emit("sweep_cell_skipped", cell=cell_id,
                             backend=backend_name, reason=str(e)[:200])
                    todo -= reps - rep
                    break
                # degraded = loop-slope fell back to dispatch-inclusive
                # timing (relay noise floor); mark the row so the analysis
                # can exclude it instead of fitting ~100 ms of relay bias
                mark = "\tDEGRADED" if getattr(res, "degraded", False) else ""
                fh.write(f"{n}\t{p}\t{res.total_ms:.6f}\t{res.funnel_ms:.6f}"
                         f"\t{res.tube_ms:.6f}{mark}\n")
                fh.flush()
                # fsync the TSV row BEFORE the (itself fsynced) journal
                # claim: the journal may only ever claim cells whose
                # data exists, even across a host crash — a flushed-but-
                # unsynced row could die in the page cache after the
                # journal line already survived
                os.fsync(fh.fileno())
                journal.record(f"{n}:{p}:{rep}",
                               {"total_ms": res.total_ms})
                cell_s.append(clock() - t0)
                obs.emit("sweep_cell", cell=cell_id, backend=backend_name,
                         total_ms=res.total_ms, funnel_ms=res.funnel_ms,
                         tube_ms=res.tube_ms,
                         degraded=bool(getattr(res, "degraded", False)),
                         dur_s=round(cell_s[-1], 6))
                completed += 1
                if completed % 10 == 0 or completed == todo:
                    # remaining time from the completed-cell durations
                    # (the reference harness's ETA feature, SURVEY.md
                    # H4): mean completed cell x cells left
                    eta = sum(cell_s) / len(cell_s) * (todo - completed)
                    print(f"# {backend_name} {completed}/{todo} "
                          f"(n={n} p={p}) eta {eta:5.0f}s", file=sys.stderr)
                    obs.emit("sweep_progress", backend=backend_name,
                             completed=completed, todo=todo,
                             eta_s=round(eta, 1))
    return path


def verify_pass(backend_name: str, ns: list[int], ps: list[int],
                seed: int, oversubscribe: bool = False) -> None:
    """Correctness pass: one fetched run per cell, checked against numpy.
    Covers the FULL p-grid even under --oversubscribe (the timing pass
    drops mid-regime p to keep the TSV regime-pure; verification has no
    such constraint)."""
    backend, cells, _ = grid_cells(backend_name, ns, ps, oversubscribe,
                                   for_verification=True)
    skipped = 0
    for n, p in cells:
        x = make_input(n, seed)
        ref = np.fft.fft(x.astype(np.complex128))
        try:
            # timers=False: verification needs the output, not another
            # loop-slope pass — re-timing every verified cell measured
            # ~20+ min of a big-n sweep's wall clock on the relay
            res = run_cell(backend, x, p, fetch=True, timers=False)
        except ValueError as e:
            print(f"# {backend_name} n={n} p={p} verify skipped: {e}",
                  file=sys.stderr)
            skipped += 1
            continue
        err = rel_err(pi_layout_to_natural(res.out), ref)
        if err > 1e-5:
            raise AssertionError(
                f"{backend_name} n={n} p={p}: rel err {err:.2e}"
            )
    print(f"# {backend_name}: verified {len(cells) - skipped}/{len(cells)} "
          f"cells vs numpy fft ({skipped} skipped)", file=sys.stderr)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--backends", default="serial",
                    help="comma-separated backend list")
    ap.add_argument("--n-grid", default="1024..8192",
                    help="'a,b,c' or 'lo..hi' powers of two")
    ap.add_argument("--p-grid", default="1..32")
    ap.add_argument("-T", "--reps", type=int, default=10,
                    help="replications per cell (reference default)")
    ap.add_argument("--out", default=os.path.join(REPO, "results"))
    ap.add_argument("--no-resume", action="store_true",
                    help="re-run cells already present in the TSV")
    ap.add_argument("--verify", action="store_true",
                    help="check every config against numpy's FFT")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--oversubscribe", action="store_true",
                    help="run p > capacity anyway (serialized-law regime; "
                         "see grid_cells)")
    ap.add_argument("--full", action="store_true",
                    help="write the deep-replication …-results-full.tsv "
                         "(reference parity: gpu/cuda …-results-full.csv)")
    ap.add_argument("--events", default=None, metavar="PATH",
                    help="write the structured observability event "
                         "stream (per-cell events, progress/ETA, the "
                         "final metrics snapshot) to a JSONL file — "
                         "docs/OBSERVABILITY.md")
    args = ap.parse_args(argv)

    if args.events:
        obs.enable(events_path=args.events)
        # fingerprint the stream so the analyze loader knows which
        # environment these sweep cells are comparable within
        # (docs/ANALYSIS.md)
        from cs87project_msolano2_tpu.analyze.records import (
            env_fingerprint,
        )

        obs.emit("env", **env_fingerprint())

    ns = parse_grid(args.n_grid)
    ps = parse_grid(args.p_grid)
    backends = [b.strip() for b in args.backends.split(",")]
    # ALL timing before ANY verification fetch (see sweep docstring)
    for b in backends:
        path = sweep(b, ns, ps, args.reps, args.out,
                     not args.no_resume, args.seed, args.oversubscribe,
                     args.full)
        print(path)
    if args.verify:
        for b in backends:
            verify_pass(b, ns, ps, args.seed, args.oversubscribe)
    if obs.enabled():
        from cs87project_msolano2_tpu.obs import metrics

        obs.emit("metrics", snapshot=metrics.snapshot())
        obs.flush()
    return 0


if __name__ == "__main__":
    sys.exit(main())
