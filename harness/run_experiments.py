#!/usr/bin/env python3
"""Experiment harness (L4): sweep (T reps x n grid x p grid) per backend,
append TSV rows, estimate remaining time, optionally cross-verify.

Parity with the reference drivers (cpu/pthreads/run-experiments-and-
analyze-results:27-69, gpu/cuda/run-experiments:15-73) plus what they
lacked: resume (append-only TSV is scanned and completed (n, p) cells are
skipped — the reference's interrupted sweeps kept completed rows, we also
skip re-running them), per-config cross-backend verification, and a
--backend list so one sweep drives the dual-backend agreement story.

TSV contract: `n  p  total_ms  funnel_ms  tube_ms` (5 columns, exactly
the reference's …pthreads.c:487-491), one file per backend.
"""

from __future__ import annotations

import argparse
import os
import sys
import time
from collections import Counter

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np  # noqa: E402

from cs87project_msolano2_tpu.backends.registry import get_backend  # noqa: E402
from cs87project_msolano2_tpu.cli import make_input  # noqa: E402
from cs87project_msolano2_tpu.utils.timing import (  # noqa: E402
    reset_program_warm_state,
)
from cs87project_msolano2_tpu.utils.verify import (  # noqa: E402
    pi_layout_to_natural,
    rel_err,
)


def parse_grid(spec: str) -> list[int]:
    """'1024,2048' or '1024..8192' (powers-of-two range, inclusive)."""
    if ".." in spec:
        lo, hi = (int(v) for v in spec.split(".."))
        out = []
        v = lo
        while v <= hi:
            out.append(v)
            v *= 2
        return out
    return [int(v) for v in spec.split(",")]


def result_path(outdir: str, backend: str,
                oversubscribe: bool = False, full: bool = False) -> str:
    """Oversubscribed sweeps get a DISTINCT file: mixing p<=cores rows
    (per-processor regime) and p>cores rows (serialized regime) in one
    TSV across resumes would leave no single law that fits it.  The
    `-oversub-` stem also auto-selects the serialized model in
    analyze_results.model_for / the awk fallback.  `full` marks the
    reference-style deep-replication dataset (…-results-full.tsv, cf.
    the reference's 256-rep …-results-full.csv)."""
    stem = f"{backend}-oversub" if oversubscribe else backend
    tail = "-results-full.tsv" if full else "-results.tsv"
    return os.path.join(outdir, f"fourier-parallel-pi-{stem}{tail}")


def done_counts(path: str) -> Counter:
    """(n, p) -> completed replication count, from an existing TSV."""
    done: Counter = Counter()
    if os.path.exists(path):
        with open(path) as fh:
            for line in fh:
                parts = line.rstrip("\n").split("\t")
                if len(parts) in (5, 6) and parts[0].isdigit():
                    done[(int(parts[0]), int(parts[1]))] += 1
    return done


def grid_cells(backend_name: str, ns: list[int], ps: list[int],
               oversubscribe: bool = False, for_verification: bool = False):
    """Returns (backend, cells, oversubscribed).

    `oversubscribed` is True only when the flag was given AND the p-grid
    actually exceeds capacity: on a host whose cores cover the whole
    grid the rows run genuinely in parallel (per-processor regime), and
    routing them to the serialized-model -oversub- TSV would fit the
    wrong law against correct data.

    `for_verification` keeps mid-regime p (1 < p <= cores) in an
    oversubscribed grid: the drop below exists to keep the TIMING file
    regime-pure, but correctness does not depend on the timing regime,
    so the verify pass must cover every cell the user asked for."""
    backend = get_backend(backend_name)
    cap = backend.capacity()
    oversubscribed = (oversubscribe and cap is not None
                      and any(p > cap for p in ps))
    if oversubscribed:
        # Deliberately run more virtual processors than real cores (the
        # reference's probe-and-clip would refuse): with all cores busy,
        # wall time tracks the SUM of per-processor work — the
        # `serialized` law model in analysis/analyze_results.py — which
        # still verifies the funnel/tube complexity, just not speedup.
        # Keep the file regime-pure: rows with 1 < p <= cap run genuinely
        # in parallel (time ~ total/p, not ~ total/cap) and would break
        # the single-beta serialized fit, so they are dropped here — a
        # separate normal (capacity-clipped) sweep covers them.  p = 1
        # stays: both laws coincide there and the speedup table needs it.
        if not for_verification:
            mixed = [p for p in ps if 1 < p <= cap]
            if mixed:
                print(f"# {backend_name}: dropping mid-regime p {mixed} "
                      "from the oversubscribed sweep (they run truly "
                      "parallel; sweep them without --oversubscribe)",
                      file=sys.stderr)
            ps = [p for p in ps if p == 1 or p > cap]
            print(f"# {backend_name}: capacity {cap} OVERSUBSCRIBED — "
                  f"p-grid {ps}; rows go to the -oversub- TSV, which the "
                  "analysis auto-maps to the serialized law model",
                  file=sys.stderr)
        else:
            print(f"# {backend_name}: capacity {cap} oversubscribed — "
                  f"verifying the FULL p-grid {ps} (no rows are written)",
                  file=sys.stderr)
        cap = None
    ps_eff = [p for p in ps if cap is None or p <= cap]
    if len(ps_eff) < len(ps):
        print(f"# {backend_name}: capacity {cap} clips p-grid to {ps_eff}",
              file=sys.stderr)
    cells = [(n, p) for n in ns for p in ps_eff if p <= n]
    return backend, cells, oversubscribed


def run_with_retry(backend, x, p, attempts: int = 4, pause_s: float = 30.0,
                   fetch: bool = False, timers: bool = True):
    """backend.run with retries on transient infrastructure errors.

    Remote-accelerator relays drop connections under long sweeps
    (observed: 'remote_compile: response body closed' mid-sweep, killing
    hours of remaining grid), and a crashed TPU worker process takes
    over a minute to come back (observed: UNAVAILABLE for >60 s after a
    worker kill) — hence exponential backoff (30, 60, 120 s).
    ValueError (cell infeasibility) passes through untouched; anything
    else is retried, then re-raised — the append-only TSV keeps
    completed rows either way.
    """
    for attempt in range(attempts):
        try:
            return backend.run(x, p, fetch=fetch, timers=timers)
        except ValueError:
            raise
        except Exception as e:
            if attempt == attempts - 1:
                raise
            # the relay that just dropped likely lost its compiled
            # programs too: reset the slope cache's warm-skip flags so
            # no post-reconnect recompile lands inside a timed window
            nreset = reset_program_warm_state()
            pause = pause_s * (2 ** attempt)
            print(f"# transient backend error ({type(e).__name__}: "
                  f"{str(e)[:120]}); retry {attempt + 1}/{attempts - 1} "
                  f"in {pause:.0f}s"
                  + (f" (re-warming {nreset} cached timing programs)"
                     if nreset else ""), file=sys.stderr)
            time.sleep(pause)


def sweep(backend_name: str, ns: list[int], ps: list[int], reps: int,
          outdir: str, resume: bool, seed: int,
          oversubscribe: bool = False, full: bool = False) -> str:
    """Timing pass: append TSV rows, NO result fetches (on remote
    accelerators the first device->host transfer permanently inflates
    per-dispatch latency — see Backend.run; verification is a separate
    pass that runs after ALL timing)."""
    os.makedirs(outdir, exist_ok=True)
    backend, cells, oversubscribed = grid_cells(
        backend_name, ns, ps, oversubscribe)
    path = result_path(outdir, backend_name, oversubscribed, full)
    done = done_counts(path) if resume else Counter()

    todo = sum(max(reps - done[c], 0) for c in cells)
    # ETA display only — not a measurement (row timings come from the
    # backend's own loop-slope timers)
    t_start = time.perf_counter()  # pifft: noqa[PIF102]
    completed = 0

    with open(path, "a") as fh:
        for n, p in cells:
            x = make_input(n, seed)
            for rep in range(done[(n, p)], reps):
                try:
                    res = run_with_retry(backend, x, p)
                except ValueError as e:
                    # per-(n, p) infeasibility (e.g. einsum's p*n cap) is
                    # a property of the cell, not an error of the sweep
                    print(f"# {backend_name} n={n} p={p} skipped: {e}",
                          file=sys.stderr)
                    todo -= reps - rep
                    break
                # degraded = loop-slope fell back to dispatch-inclusive
                # timing (relay noise floor); mark the row so the analysis
                # can exclude it instead of fitting ~100 ms of relay bias
                mark = "\tDEGRADED" if getattr(res, "degraded", False) else ""
                fh.write(f"{n}\t{p}\t{res.total_ms:.6f}\t{res.funnel_ms:.6f}"
                         f"\t{res.tube_ms:.6f}{mark}\n")
                fh.flush()
                completed += 1
                if completed % 10 == 0 or completed == todo:
                    # pifft ETA only, see t_start note above
                    elapsed = time.perf_counter() - t_start  # pifft: noqa[PIF102]
                    eta = elapsed / completed * (todo - completed)
                    print(f"# {backend_name} {completed}/{todo} "
                          f"(n={n} p={p}) eta {eta:5.0f}s", file=sys.stderr)
    return path


def verify_pass(backend_name: str, ns: list[int], ps: list[int],
                seed: int, oversubscribe: bool = False) -> None:
    """Correctness pass: one fetched run per cell, checked against numpy.
    Covers the FULL p-grid even under --oversubscribe (the timing pass
    drops mid-regime p to keep the TSV regime-pure; verification has no
    such constraint)."""
    backend, cells, _ = grid_cells(backend_name, ns, ps, oversubscribe,
                                   for_verification=True)
    skipped = 0
    for n, p in cells:
        x = make_input(n, seed)
        ref = np.fft.fft(x.astype(np.complex128))
        try:
            # timers=False: verification needs the output, not another
            # loop-slope pass — re-timing every verified cell measured
            # ~20+ min of a big-n sweep's wall clock on the relay
            res = run_with_retry(backend, x, p, fetch=True, timers=False)
        except ValueError as e:
            print(f"# {backend_name} n={n} p={p} verify skipped: {e}",
                  file=sys.stderr)
            skipped += 1
            continue
        err = rel_err(pi_layout_to_natural(res.out), ref)
        if err > 1e-5:
            raise AssertionError(
                f"{backend_name} n={n} p={p}: rel err {err:.2e}"
            )
    print(f"# {backend_name}: verified {len(cells) - skipped}/{len(cells)} "
          f"cells vs numpy fft ({skipped} skipped)", file=sys.stderr)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--backends", default="serial",
                    help="comma-separated backend list")
    ap.add_argument("--n-grid", default="1024..8192",
                    help="'a,b,c' or 'lo..hi' powers of two")
    ap.add_argument("--p-grid", default="1..32")
    ap.add_argument("-T", "--reps", type=int, default=10,
                    help="replications per cell (reference default)")
    ap.add_argument("--out", default=os.path.join(REPO, "results"))
    ap.add_argument("--no-resume", action="store_true",
                    help="re-run cells already present in the TSV")
    ap.add_argument("--verify", action="store_true",
                    help="check every config against numpy's FFT")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--oversubscribe", action="store_true",
                    help="run p > capacity anyway (serialized-law regime; "
                         "see grid_cells)")
    ap.add_argument("--full", action="store_true",
                    help="write the deep-replication …-results-full.tsv "
                         "(reference parity: gpu/cuda …-results-full.csv)")
    args = ap.parse_args(argv)

    ns = parse_grid(args.n_grid)
    ps = parse_grid(args.p_grid)
    backends = [b.strip() for b in args.backends.split(",")]
    # ALL timing before ANY verification fetch (see sweep docstring)
    for b in backends:
        path = sweep(b, ns, ps, args.reps, args.out,
                     not args.no_resume, args.seed, args.oversubscribe,
                     args.full)
        print(path)
    if args.verify:
        for b in backends:
            verify_pass(b, ns, ps, args.seed, args.oversubscribe)
    return 0


if __name__ == "__main__":
    sys.exit(main())
