#!/usr/bin/env python3
"""Multi-chip speedup dataset: per-device timing of the sharded pi-FFT.

The reference's headline evidence is measured speedup under the
communication-free decomposition (13.4x on GPU, 21.4x on Xeon Phi —
BASELINE.md).  This repo's multi-chip analogue is parallel/pi_shard.py:
each device of a p-mesh runs ONE funnel chain plus its local tube, with
machine-checked zero collectives in the compiled HLO
(tests/test_parallel.py::test_pi_fft_sharded_is_collective_free).

Because the computation is communication-free, device i's wall time on
a real p-device mesh IS the wall time of its shard-local program — the
devices never wait on each other.  This script therefore times the
shard-local body (models.pi_fft.funnel_single + tube, exactly what
pi_fft_sharded's device_fn runs) as a single-device jit per (n, p) and
records per-processor phase times in the reference TSV contract.  The
same modeling argument the reference itself makes: "because processors
share nothing after init, distributed behavior is fully represented by
P independent threads in one address space" (SURVEY.md §4).  What it
does NOT capture is per-device dispatch overhead on a real pod (~us
scale, constant in n) — the law fit, which regresses against n-scaled
work terms, is insensitive to it.

Before timing, the script cross-checks the REAL 8-virtual-device mesh:
pi_fft_sharded on a CPU mesh must equal the single-device pi-FFT bit
for bit (the dryrun recipe, __graft_entry__.dryrun_multichip).

Output: datasets/fourier-parallel-pi-sharded-results.tsv
(n  p  total_ms  funnel_ms  tube_ms — per-DEVICE times; analysis model:
per-processor, auto-selected since the filename matches no on-chip or
serialized backend pattern).

Resume discipline (docs/RESILIENCE.md, docs/MULTICHIP.md): per-cell
completion is journaled to an fsynced JSONL sidecar next to the
append-only TSV (the same kill-safe contract bench.py and
run_experiments.py carry) — a sweep killed mid-cell (or mid-STALL: the
r05 failure mode) restarts from the last completed cell, re-running
nothing, and the supervised collective cross-check's degrade trail is
preserved across resumes instead of re-risking the wedge.
"""

from __future__ import annotations

import argparse
import os
import sys
from collections import Counter

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=8"
)
os.environ["JAX_PLATFORMS"] = "cpu"

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from harness.run_experiments import (  # noqa: E402
    done_counts,
    journal_for,
    parse_grid,
)

from cs87project_msolano2_tpu import obs  # noqa: E402
from cs87project_msolano2_tpu.models.pi_fft import (  # noqa: E402
    funnel_single,
    tube,
    tube_scan,
)
from cs87project_msolano2_tpu.ops.twiddle import twiddle_tables  # noqa: E402
from cs87project_msolano2_tpu.utils.timing import time_ms  # noqa: E402

# past this segment length the unrolled tube's XLA compile time blows up
# (backends/jax_backend.py::SCAN_MIN_N) — use the stage-scan tube.
# IMPORTANT: every cell of one sweep must use the SAME tube
# implementation — the scan tube carries per-stage overhead the
# unrolled tube doesn't, and a grid that mixes them puts the extra cost
# only in the small-p cells, inflating empirical speedup (observed:
# 104x "speedup" at n=2^17 p=32 when the p=1 baseline alone used the
# scan tube).  The default grid (n <= 2^17 = the reference's Xeon Phi
# maximum) stays below this threshold everywhere.
SCAN_MIN_S = 1 << 18


def mesh_crosscheck(n: int = 1 << 12) -> None:
    """The real virtual-device mesh must reproduce the single-device
    pi-FFT exactly (same recipe as the driver's dryrun_multichip)."""
    from jax.sharding import Mesh

    from cs87project_msolano2_tpu.models.pi_fft import pi_fft_pi_layout
    from cs87project_msolano2_tpu.parallel.pi_shard import pi_fft_sharded

    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual CPU devices, got {devs}"
    mesh = Mesh(np.array(devs[:8]), ("p",))
    rng = np.random.default_rng(0)
    xr = jnp.asarray(rng.standard_normal(n).astype(np.float32))
    xi = jnp.asarray(rng.standard_normal(n).astype(np.float32))
    sr, si = pi_fft_sharded(xr, xi, mesh)
    rr, ri = pi_fft_pi_layout(xr, xi, 8)
    err = max(
        float(jnp.max(jnp.abs(sr - rr.reshape(-1)))),
        float(jnp.max(jnp.abs(si - ri.reshape(-1)))),
    )
    scale = float(jnp.max(jnp.abs(rr)))
    assert err / scale < 1e-6, f"mesh cross-check failed: {err / scale:.2e}"
    print(f"# 8-device mesh cross-check ok (n={n}, rel err "
          f"{err / scale:.1e})", file=sys.stderr)


def collective_crosscheck(journal, n: int = 64):
    """The SUPERVISED collective cross-check: run the all_to_all 2-D
    FFT on the real 8-device mesh through the self-healing entry
    (collective supervision + consensus + the communication-free
    escape, docs/MULTICHIP.md) and journal what happened — including
    the degrade trail, so a sweep that escaped (a wedged rendezvous on
    this host, an injected stall in CI) says so on EVERY later resume
    instead of the r05 pattern of a completed run with a buried hang.
    A journaled cell is not re-run: the trail is PRESERVED."""
    prior = journal.get("collective_crosscheck")
    if prior is not None:
        trail = prior.get("trail") or []
        print(f"# collective cross-check preserved from journal "
              f"(degraded={bool(prior.get('degraded'))}"
              + (f", trail={[t.get('to') for t in trail]}" if trail
                 else "") + ")", file=sys.stderr)
        return prior
    from jax.sharding import Mesh

    from cs87project_msolano2_tpu.parallel import fft2_sharded_resilient

    devs = jax.devices()
    mesh = Mesh(np.array(devs[:8]), ("p",))
    rng = np.random.default_rng(1)
    x = (rng.standard_normal((n, n))
         + 1j * rng.standard_normal((n, n))).astype(np.complex64)
    y, report = fft2_sharded_resilient(x, mesh)
    ref = np.fft.fft2(x.astype(np.complex128))
    err = float(np.max(np.abs(np.asarray(y) - ref)) / np.max(np.abs(ref)))
    assert err < 1e-5, f"collective cross-check failed: rel err {err:.2e}"
    rec = journal.record("collective_crosscheck",
                         {**report.to_record(), "rel_err": err})
    print(f"# collective cross-check ok (supervised all_to_all, "
          f"degraded={report.degraded}"
          + (f", escaped via {[t.get('to') for t in report.trail]}"
             if report.trail else "") + f", rel err {err:.1e})",
          file=sys.stderr)
    return rec


def device_fns(n: int, p: int):
    """jitted shard-local phases for device 0 of a p-mesh (all devices
    do identical-shape work — funnel_single's chain length log2(p) and
    the tube's segment n/p do not depend on the device index)."""
    tables = twiddle_tables(n)
    s = n // p
    tube_f = tube_scan if s >= SCAN_MIN_S else tube

    @jax.jit
    def funnel_f(xr, xi):
        return funnel_single(xr, xi, 0, p, tables)

    @jax.jit
    def tube_only(fr, fi):
        if tube_f is tube:
            return tube_f(fr, fi, n, p, tables)
        return tube_f(fr, fi, n, p)

    @jax.jit
    def full(xr, xi):
        fr, fi = funnel_single(xr, xi, 0, p, tables)
        if tube_f is tube:
            return tube_f(fr, fi, n, p, tables)
        return tube_f(fr, fi, n, p)

    return funnel_f, tube_only, full


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--n-grid", default="2048..131072",
                    help="default matches the reference Phi sweep "
                         "(xeonphi run-experiments: n=16384..131072 plus "
                         "the smaller committed grid)")
    ap.add_argument("--p-grid", default="1..32")
    ap.add_argument("-T", "--reps", type=int, default=10)
    ap.add_argument("--out", default=os.path.join(REPO, "datasets"))
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--full", action="store_true",
                    help="write the reference-style deep-replication "
                         "dataset (…-results-full.tsv, cf. the "
                         "reference's 256-rep …-results-full.csv) "
                         "instead of the standard 10-rep file")
    ap.add_argument("--no-resume", action="store_true",
                    help="start a FRESH dataset: rotate the TSV and "
                         "journal and re-run every cell (default: "
                         "resume — a killed sweep restarts from the "
                         "last completed cell)")
    ap.add_argument("--events", default=None, metavar="PATH",
                    help="write the structured observability event "
                         "stream to a JSONL file "
                         "(docs/OBSERVABILITY.md)")
    args = ap.parse_args(argv)

    if args.events:
        obs.enable(events_path=args.events)

    mesh_crosscheck()

    os.makedirs(args.out, exist_ok=True)
    stem = "full" if args.full else ""
    path = os.path.join(
        args.out,
        f"fourier-parallel-pi-sharded-results{'-' + stem if stem else ''}.tsv",
    )
    # the kill-safe per-cell resume discipline bench.py gained in PR 4
    # (docs/RESILIENCE.md): an fsynced JSONL journal rides next to the
    # append-only TSV, merged per-cell by max with the TSV scan, so a
    # kill mid-cell (or mid-stall) loses at most the cell it took —
    # never the sweep's place, never the degrade trail
    journal = journal_for(path)
    if not os.path.exists(path):
        # a rotated/deleted TSV invalidates the sidecar: the journal
        # may only ever claim cells whose data exists
        journal.reset()
    resume = not args.no_resume
    if not resume:
        # a fresh run starts a fresh DATASET: the TSV is append-only,
        # so leaving it would splice two runs' timings into one
        # per-cell replication count — remove both it and the journal
        # (whose rep-keyed cells would otherwise claim rows of a file
        # that no longer matches them)
        if os.path.exists(path):
            os.remove(path)
        journal.reset()
    journal.guard_config({"dataset": "sharded", "full": bool(args.full)})
    collective_crosscheck(journal)
    done = done_counts(path, journal) if resume else Counter()

    ns = parse_grid(args.n_grid)
    ps = parse_grid(args.p_grid)
    cells = [(n, p) for n in ns for p in ps if p <= n]
    rng = np.random.default_rng(args.seed)

    with open(path, "a") as fh:
        for n, p in cells:
            start_rep = done[(n, p)]
            todo = args.reps - start_rep
            if todo <= 0:
                continue
            xr = jnp.asarray(rng.standard_normal(n).astype(np.float32))
            xi = jnp.asarray(rng.standard_normal(n).astype(np.float32))
            funnel_f, tube_only, full = device_fns(n, p)
            for rep in range(start_rep, args.reps):
                cell_id = {"n": n, "p": p, "rep": rep}
                with obs.span("sweep_cell", cell=cell_id,
                              backend="sharded"):
                    # phase timers compose: total := funnel + tube, the
                    # reference's nested-timer contract (jax_backend.run)
                    if p == 1:
                        funnel_ms = 0.0  # empty chain, log2(1) stages
                        fr, fi = funnel_f(xr, xi)
                    else:
                        funnel_ms, (fr, fi) = time_ms(funnel_f, xr, xi,
                                                      reps=3)
                    tube_ms, _ = time_ms(tube_only, fr, fi, reps=3)
                fh.write(f"{n}\t{p}\t{funnel_ms + tube_ms:.6f}"
                         f"\t{funnel_ms:.6f}\t{tube_ms:.6f}\n")
                fh.flush()
                # fsync the TSV row BEFORE the (itself fsynced) journal
                # claim, like run_experiments.sweep: the journal may
                # only ever claim cells whose data exists, even across
                # a host crash
                os.fsync(fh.fileno())
                journal.record(f"{n}:{p}:{rep}",
                               {"total_ms": funnel_ms + tube_ms})
                obs.emit("sweep_cell", cell=cell_id, backend="sharded",
                         total_ms=funnel_ms + tube_ms,
                         funnel_ms=funnel_ms, tube_ms=tube_ms)
            print(f"# sharded n={n} p={p} done", file=sys.stderr)
    if obs.enabled():
        from cs87project_msolano2_tpu.obs import metrics

        obs.emit("metrics", snapshot=metrics.snapshot())
        obs.flush()
    print(path)
    return 0


if __name__ == "__main__":
    sys.exit(main())
