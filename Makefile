# Top-level Makefile — target parity with the reference build discipline
# (cpu/pthreads/Makefile:16-46: all / clean / recompile /
# run-experiments-and-analyze-results / replicate), one level up from the
# native core's own Makefile.

# analyze-datasets uses pipefail, which /bin/sh (dash) lacks
SHELL := /bin/bash

.PHONY: all clean recompile test bench replicate \
        run-experiments run-experiments-and-analyze-results analyze \
        analyze-datasets

all:
	$(MAKE) -C cs87project_msolano2_tpu/native all

clean:
	$(MAKE) -C cs87project_msolano2_tpu/native clean
	rm -rf results

recompile: clean all

test: all
	python3 -m pytest tests/ -q

run-experiments: all
	./harness/run-experiments

analyze:
	./analysis/analyze-results results/fourier-parallel-pi-*-results.tsv

# regenerate the COMMITTED datasets' analysis artifacts (D2 parity:
# law-fit log + per-n figures) from the committed TSVs
analyze-datasets:
	set -o pipefail; \
	python3 analysis/analyze_results.py datasets/fourier-parallel-pi-*.tsv \
	  --allow-fail=-jax-unrolled- --allow-fail=-jax-results \
	  --plots datasets | tee datasets/pifft-sweep-results-analysis.out
	python3 analysis/analyze_results_full.py datasets/fourier-parallel-pi-*.tsv \
	  --out datasets

run-experiments-and-analyze-results: run-experiments analyze

bench: all
	python3 bench.py

# the reference's one-command replication entry (make replicate)
replicate: recompile run-experiments-and-analyze-results
