# Top-level Makefile — target parity with the reference build discipline
# (cpu/pthreads/Makefile:16-46: all / clean / recompile /
# run-experiments-and-analyze-results / replicate), one level up from the
# native core's own Makefile.

# analyze-datasets uses pipefail, which /bin/sh (dash) lacks
SHELL := /bin/bash

.PHONY: all clean recompile test bench bench-smoke bench-smoke-obs \
        bench-chaos serve-smoke serve-slo serve-mesh-smoke wire-smoke \
        rfft-smoke precision-smoke apps-smoke bluestein-smoke \
        multichip-smoke fleet-smoke backend-smoke \
        obs-live-smoke replicate run-experiments \
        run-experiments-and-analyze-results analyze analyze-datasets \
        analyze-smoke check check-stats lint

all:
	$(MAKE) -C cs87project_msolano2_tpu/native all

clean:
	$(MAKE) -C cs87project_msolano2_tpu/native clean
	rm -rf results

recompile: clean all

test: all
	python3 -m pytest tests/ -q

run-experiments: all
	./harness/run-experiments

analyze:
	./analysis/analyze-results results/fourier-parallel-pi-*-results.tsv

# regenerate the COMMITTED datasets' analysis artifacts (D2 parity:
# law-fit log + per-n figures) from the committed TSVs
analyze-datasets:
	set -o pipefail; \
	python3 analysis/analyze_results.py datasets/fourier-parallel-pi-*.tsv \
	  --allow-fail=-jax-unrolled- --allow-fail=-jax-results \
	  --plots datasets | tee datasets/pifft-sweep-results-analysis.out
	python3 analysis/analyze_results_full.py datasets/fourier-parallel-pi-*.tsv \
	  --out datasets

run-experiments-and-analyze-results: run-experiments analyze

# the CI statistical-verification check (docs/ANALYSIS.md): the
# perf-regression gate over the COMMITTED BENCH trajectory (it must
# pass — a significant unbaselined throughput regression fails CI with
# a named metric and a p-value), the loader/change-point report over
# the same rounds, and a law-fit round trip on the self-test table
# (the fit must recover known coefficients and exit 0)
analyze-smoke:
	set -o pipefail; \
	python3 -m cs87project_msolano2_tpu.cli analyze gate BENCH_r*.json \
	  --baseline perf-baseline.json \
	  | tee /tmp/pifft-analyze-gate.out && \
	python3 -m cs87project_msolano2_tpu.cli analyze report \
	  --bench BENCH_r*.json --json \
	  | python3 -c "import json, sys; r = json.load(sys.stdin); \
	  assert r['rounds'] and r['skipped_pairs'], r; \
	  assert r['change_points'], r; \
	  print('# analyze report ok: %d rounds, %d incomparable pair(s), %d change-point(s)' \
	        % (len(r['rounds']), len(r['skipped_pairs']), len(r['change_points'])))" && \
	python3 -c "from cs87project_msolano2_tpu.analyze.lawfit import write_demo_tsv; \
	  write_demo_tsv('/tmp/pifft-analyze-demo.tsv')" && \
	python3 -m cs87project_msolano2_tpu.cli analyze fit \
	  /tmp/pifft-analyze-demo.tsv --json \
	  | python3 -c "import json, sys; r = json.load(sys.stdin); \
	  rep = r['/tmp/pifft-analyze-demo.tsv']; \
	  assert rep['total']['holds'] is True, rep['total']; \
	  beta = rep['funnel']['beta']; lo, hi = rep['funnel']['ci95']['funnel']; \
	  assert abs(beta - 2e-6) / 2e-6 < 0.05, beta; \
	  assert lo < beta < hi, (lo, beta, hi); \
	  print('# analyze fit ok: law holds, funnel beta %g (true 2e-6), CI [%g, %g]' % (beta, lo, hi))"

bench: all
	python3 bench.py

# the CI rot check: whole reporting pipeline at toy sizes, offline —
# including one interpret-safe cell through the hierarchical sixstep
# kernel (docs/KERNELS.md), asserted tagged with its plan and its
# carry-pass-aware roofline ceiling (~0.33: two HBM carries)
bench-smoke:
	set -o pipefail; \
	PIFFT_PLAN_CACHE=off python3 bench.py --smoke \
	  | tee /tmp/pifft-bench-smoke.json && \
	python3 -c "import json; r = json.load(open('/tmp/pifft-bench-smoke.json')); \
	  assert r['sixstep_smoke_plan']['variant'] == 'sixstep', r; \
	  assert abs(r['sixstep_smoke_roofline_ceiling'] - 1/3.0) < 1e-2, r; \
	  assert r['n2^13_roofline_ceiling'] == 1.0, r; \
	  print('# bench smoke ok: sixstep cell %s ms, ceiling %s' \
	        % (r['sixstep_smoke_ms'], r['sixstep_smoke_roofline_ceiling']))"

# the CI observability check (docs/OBSERVABILITY.md): the same smoke
# run with the event stream armed — every emitted event must validate
# against the schema, the Chrome export must load, and the summary must
# report nonzero plan-cache activity (the counters are actually wired,
# not just declared)
bench-smoke-obs:
	set -o pipefail; \
	PIFFT_PLAN_CACHE=off python3 bench.py --smoke \
	  --events /tmp/pifft-obs-events.jsonl \
	  --trace-out /tmp/pifft-obs-trace.json \
	  | tee /tmp/pifft-bench-obs.json && \
	python3 -m cs87project_msolano2_tpu.cli obs validate \
	  --events /tmp/pifft-obs-events.jsonl && \
	python3 -m cs87project_msolano2_tpu.cli obs summary \
	  --events /tmp/pifft-obs-events.jsonl --json \
	  | python3 -c "import json, sys; \
	s = json.load(sys.stdin); c = s['metrics']['counters']; \
	act = sum(v for k, v in c.items() if k.startswith('pifft_plan_cache_')); \
	assert act > 0, c; \
	rec = json.load(open('/tmp/pifft-bench-obs.json')); \
	assert rec.get('run') in s['runs'], (rec.get('run'), s['runs']); \
	assert rec['sixstep_smoke_plan']['variant'] == 'sixstep', rec; \
	json.load(open('/tmp/pifft-obs-trace.json')); \
	print('# obs smoke ok: %d events, plan-cache activity %g, run %s, sixstep cell tagged' \
	      % (s['event_count'], act, rec['run']))"

# the CI chaos check (docs/RESILIENCE.md): with every kernel entry
# dying of an injected CAPACITY fault, the degradation chain must carry
# the bench to rc=0 with the record tagged degraded and at least one
# demotion on the plan — the end-to-end resilience guarantee
bench-chaos:
	set -o pipefail; \
	PIFFT_PLAN_CACHE=off PIFFT_FAULT=tube:capacity:1.0 \
	  python3 bench.py --smoke | tee /tmp/pifft-bench-chaos.json && \
	python3 -c "import json; r = json.load(open('/tmp/pifft-bench-chaos.json')); \
	  assert r.get('degraded') is True, r; \
	  assert r['plan'].get('demotions'), r['plan']; \
	  print('# chaos smoke ok: rc=0, degraded tagged, demotion recorded')"

# the CI serving check (docs/SERVING.md): an in-process dispatcher on
# CPU is hit with concurrent mixed-shape requests; the command fails
# unless coalescing happened (k same-shape requests -> strictly fewer
# kernel invocations, read from the obs counters), every response
# verifies against numpy, every event is schema-valid, and the
# per-shape p50/p99 queue-wait + compute table is reportable
serve-smoke:
	PIFFT_PLAN_CACHE=off python3 -m cs87project_msolano2_tpu.cli \
	  serve --smoke

# the serving SLO suite (BENCH-round format: offered load, achieved
# throughput, p50/p99 with the queue-wait vs compute split per cell);
# smoke-sized here — drop --smoke for the real tier on hardware
serve-slo:
	PIFFT_PLAN_CACHE=off python3 bench.py --serve-load --smoke

# the CI mesh-serving check (docs/SERVING.md, mesh section): a virtual
# 8-device CPU mesh under open-loop load with a MID-RUN DEVICE KILL
# (the device<K> injection site) and a journaled warm-handoff drain.
# The in-process gate fails unless zero requests were dropped, every
# response verifies against numpy, re-routed requests carry a
# failover:* trail, consensus ran before the re-route, shape affinity
# held (asserted from the placement counter), utilization stayed in
# the spread bound, the pre/post-kill p99 pair is recorded, and the
# drained device's successor serves without re-tuning.  The bench run
# then emits the serve_mesh row set (per-device utilization + the p99
# split) in the BENCH round format analyze/loader parses.
serve-mesh-smoke:
	set -o pipefail; \
	PIFFT_PLAN_CACHE=off python3 -m cs87project_msolano2_tpu.cli \
	  serve --mesh-smoke && \
	PIFFT_PLAN_CACHE=off python3 bench.py --serve-mesh --smoke \
	  | tee /tmp/pifft-serve-mesh.json && \
	python3 -c "import json; r = json.load(open('/tmp/pifft-serve-mesh.json')); \
	  rows = r['serve_mesh']; \
	  kill = [x for x in rows if x.get('row') == 'kill'][0]; \
	  assert kill['failed'] == 0, kill; \
	  assert kill['failover_tagged'] >= 1, kill; \
	  assert kill['p99_pre_kill_ms'] is not None, kill; \
	  assert kill['p99_post_kill_ms'] is not None, kill; \
	  devs = [x for x in rows if x.get('row') == 'device']; \
	  assert len(devs) == 8 and sum(1 for d in devs if d['served'] > 0) >= 6, devs; \
	  assert r['metric'] == 'serve_mesh_p99_post_kill_ms', r['metric']; \
	  print('# serve mesh rows ok: kill on %s, p99 %s -> %s ms, %d devices served' \
	        % (kill['killed_device'], kill['p99_pre_kill_ms'], \
	           kill['p99_post_kill_ms'], sum(1 for d in devs if d['served'] > 0)))"

# the CI wire check (docs/SERVING.md, "The wire"): (1) the in-process
# wire smoke — both dialects served over a real socket with the planes
# BYTE-IDENTICAL to the direct dispatcher result, the host-copy meter
# charging ZERO on the binary float32 path (and nonzero on JSON — the
# meter discriminates), the shm lane and streaming reassembly
# round-tripping bit-identically, an unsupported HELLO version falling
# back to the JSON dialect with the serve_wire_fallback event, and a
# malformed header closing with serve_conn_lost, never a hang; (2) the
# trace-driven replay SLO run — at EQUAL offered load per (process,
# rps) cell, the binary dialect's p99 must beat JSON's, and the
# per-protocol tail attribution must show the parse-driven tail GONE:
# every binary label's p99 sits strictly below every JSON label's
# (an order of magnitude in practice — what remains of the binary
# tail is millisecond-scale batching wait, not seconds of queue/parse)
wire-smoke:
	set -o pipefail; \
	PIFFT_PLAN_CACHE=off python3 -m cs87project_msolano2_tpu.cli \
	  serve --wire-smoke --json | tee /tmp/pifft-wire-smoke.json && \
	python3 -c "import json; r = json.load(open('/tmp/pifft-wire-smoke.json')); \
	  assert r['ok'] and not r['problems'], r; \
	  assert r['binary_host_copy_delta'] == 0, r; \
	  assert r['json_host_copy_delta'] > 0, r; \
	  print('# wire smoke ok: binary copies 0 B, json copies %d B' \
	        % r['json_host_copy_delta'])" && \
	PIFFT_PLAN_CACHE=off python3 bench.py --serve-load --smoke \
	  --events /tmp/pifft-wire-events.jsonl \
	  | tee /tmp/pifft-wire-slo.json && \
	python3 -c "import json; r = json.load(open('/tmp/pifft-wire-slo.json')); \
	  rows = r['serve_load']; \
	  cell = lambda p: {(x['process'], x['offered_rps']): x['p99_ms'] \
	                    for x in rows if x.get('protocol') == p \
	                    and x.get('p99_ms') is not None}; \
	  jsn, bin_ = cell('json'), cell('binary'); \
	  matched = sorted(set(jsn) & set(bin_)); \
	  assert matched, (sorted(jsn), sorted(bin_)); \
	  slow = {k: (bin_[k], jsn[k]) for k in matched if bin_[k] >= jsn[k]}; \
	  assert not slow, slow; \
	  tails = r['serve_tail_attribution_by_protocol']; \
	  bt = max(v['p99_ms'] for v in tails['binary'].values()); \
	  jt = min(v['p99_ms'] for v in tails['json'].values()); \
	  assert bt < jt, (bt, jt); \
	  print('# wire replay ok: binary p99 beats json in %d/%d cells (best %0.1fx), worst binary tail %.1f ms vs best json %.1f ms' \
	        % (len(matched), len(matched), \
	           max(jsn[k] / bin_[k] for k in matched), bt, jt))"

# the CI half-spectrum check (docs/REAL.md): rfft parity vs numpy
# across sizes, then the bench smoke with the obs meter armed — the
# METERED pifft_hbm_bytes_total delta of the r2c cell must be EXACTLY
# half the c2c cell's at equal n (the tentpole win, enforced from the
# meter, not the formula that feeds it) — then a serve smoke over a
# mixed c2c/r2c shape file (the r2c burst coalesces into half-width
# kernel invocations, responses verified vs numpy.fft.rfft, zero
# schema-invalid events)
rfft-smoke:
	set -o pipefail; \
	PIFFT_PLAN_CACHE=off python3 -c "import numpy as np; \
	from cs87project_msolano2_tpu.models.real import rfft, irfft; \
	rng = np.random.default_rng(0); \
	errs = {}; \
	[errs.__setitem__(n, float(np.max(np.abs(np.asarray(rfft(x)) - np.fft.rfft(x.astype(np.float64)))) / np.max(np.abs(np.fft.rfft(x.astype(np.float64)))))) \
	 for n in (1 << 10, 1 << 12, 1 << 14) \
	 for x in [rng.standard_normal(n).astype(np.float32)]]; \
	assert all(e <= 1e-5 for e in errs.values()), errs; \
	x = rng.standard_normal(1 << 12).astype(np.float32); \
	rt = float(np.max(np.abs(np.asarray(irfft(rfft(x))) - x))); \
	assert rt <= 1e-4, rt; \
	print('# rfft parity ok: ' + ', '.join('n=%d %.2e' % kv for kv in sorted(errs.items())))" && \
	PIFFT_PLAN_CACHE=off python3 bench.py --smoke \
	  --events /tmp/pifft-rfft-events.jsonl \
	  | tee /tmp/pifft-rfft-smoke.json && \
	python3 -c "import json; r = json.load(open('/tmp/pifft-rfft-smoke.json')); \
	  c2c = r['n2^13_hbm_bytes']; r2c = r['rfft2^13_hbm_bytes']; \
	  assert r2c * 2 == c2c, (r2c, c2c); \
	  assert r['rfft2^13_parity_relerr'] <= 1e-5, r; \
	  assert r['rfft2^13_domain'] == 'r2c', r; \
	  print('# rfft bytes-halved ok: metered r2c %d B == c2c %d B / 2 at n=2^13' % (r2c, c2c))" && \
	printf '{"n": 1024, "domain": "r2c"}\n{"n": 1024}\n{"n": 2048}\n' \
	  > /tmp/pifft-rfft-shapes.jsonl && \
	PIFFT_PLAN_CACHE=off python3 -m cs87project_msolano2_tpu.cli \
	  serve --smoke --shapes /tmp/pifft-rfft-shapes.jsonl

# the CI mixed-precision check (docs/PRECISION.md): (1) numerical
# parity within each mode's committed error budget at 2^10..2^14 vs
# the float64 reference; (2) the bench smoke with the obs meter armed
# — the METERED pifft_hbm_bytes_total delta of the bf16-storage cell
# must be EXACTLY half the fp32-storage (split3) cell's at equal n,
# with the bf16 parity error inside its budget (the bytes-halving is
# enforced from the meter AND never bought with a blown contract);
# (3) an INJECTED budget violation (PIFFT_PRECISION_BUDGET=0) must
# walk the serve plan UP the degrade chain to fp32 with degraded:true
# tagged on the plan and the serve response; (4) a serve smoke over a
# mixed-precision shape file (bf16 + split3 groups coalesce
# separately, responses verified within each mode's budget)
precision-smoke:
	set -o pipefail; \
	PIFFT_PLAN_CACHE=off python3 -c "import numpy as np; \
	from cs87project_msolano2_tpu import plans; \
	from cs87project_msolano2_tpu.ops.precision import error_budget, rel_err; \
	rng = np.random.default_rng(0); \
	errs = {}; \
	[errs.__setitem__((m, n), rel_err(*(lambda yr, yi: (np.asarray(yr), np.asarray(yi)))(*plans.plan(n, layout='natural', precision=m).execute(xr, xi)), np.fft.fft(xr.astype(np.complex128) + 1j * xi.astype(np.complex128)).real, np.fft.fft(xr.astype(np.complex128) + 1j * xi.astype(np.complex128)).imag)) \
	 for m in ('split3', 'highest', 'default', 'fp32', 'bf16') \
	 for n in (1 << 10, 1 << 12, 1 << 14) \
	 for xr in [rng.standard_normal(n).astype(np.float32)] \
	 for xi in [rng.standard_normal(n).astype(np.float32)]]; \
	bad = {k: (e, error_budget(k[0])) for k, e in errs.items() if e > error_budget(k[0])}; \
	assert not bad, bad; \
	print('# precision parity ok: ' + ', '.join('%s@%d %.1e<=%.0e' % (m, n, e, error_budget(m)) for (m, n), e in sorted(errs.items())))" && \
	PIFFT_PLAN_CACHE=off python3 bench.py --smoke \
	  --events /tmp/pifft-precision-events.jsonl \
	  | tee /tmp/pifft-precision-smoke.json && \
	python3 -c "import json; \
	from cs87project_msolano2_tpu.ops.precision import error_budget; \
	r = json.load(open('/tmp/pifft-precision-smoke.json')); \
	bf16 = r['bf16_2^13_hbm_bytes']; fp32 = r['n2^13_hbm_bytes']; \
	assert bf16 * 2 == fp32, (bf16, fp32); \
	assert r['bf16_2^13_parity_relerr'] <= error_budget('bf16'), r; \
	assert r['bf16_2^13_precision'] == 'bf16', r; \
	print('# precision bytes-halved ok: metered bf16 %d B == fp32 %d B / 2 at n=2^13 (parity %.1e)' % (bf16, fp32, r['bf16_2^13_parity_relerr']))" && \
	PIFFT_PLAN_CACHE=off PIFFT_PRECISION_BUDGET=0 \
	  python3 -m cs87project_msolano2_tpu.serve.precision_smoke && \
	printf '{"n": 1024, "precision": "bf16"}\n{"n": 1024}\n{"n": 2048, "precision": "bf16"}\n' \
	  > /tmp/pifft-precision-shapes.jsonl && \
	PIFFT_PLAN_CACHE=off python3 -m cs87project_msolano2_tpu.cli \
	  serve --smoke --shapes /tmp/pifft-precision-shapes.jsonl

# the CI spectral-operation check (docs/APPS.md): per-op gates —
# conv: fftconv/overlap-save parity vs the numpy oracles at
# 2^10..2^14 (block sweep: block == signal, block > signal,
# non-divisible tails), the METERED fusion gate (the
# pifft_hbm_bytes_total delta of a fused conv must sit at the op's
# fused roofline floor while the deliberately unfused host-round-trip
# control exceeds it — the gate discriminates), and one conv request
# served END TO END over the socket protocol (op-tagged GroupKey,
# coalescing from the obs counters, a fault-injected request
# degrade-tagged, the op-tagged SLO row present); corr: correlate
# parity incl. the conjugation mattering; solve: the PDE family
# (3-D Poisson, Helmholtz const+variable, the exact heat step)
apps-smoke:
	PIFFT_PLAN_CACHE=off python3 -m cs87project_msolano2_tpu.cli \
	  apps conv --smoke
	PIFFT_PLAN_CACHE=off python3 -m cs87project_msolano2_tpu.cli \
	  apps corr --smoke
	PIFFT_PLAN_CACHE=off python3 -m cs87project_msolano2_tpu.cli \
	  apps solve --smoke

# the CI any-length check (docs/PLANS.md, "Arbitrary n"): (1) parity
# vs numpy across the variant matrix — primes (7 via the mixedradix
# matmul, 127 and 8191 via Rader), composites (720 and 3072
# mixed-radix, 999 Bluestein) and n=2, forward AND inverse, c2c AND
# r2c/c2r — with the static router's variant choices asserted; (2)
# the bench smoke with the obs meter armed — the conv_np* row's
# METERED pifft_hbm_bytes_total delta at the cheapest mixed-radix
# conv length must sit STRICTLY below the pad-to-pow2 control's
# charge at next_pow2 of the same linear length (the pad-to-pow2 tax,
# enforced from the meter, not the formula that feeds it); (3) an
# injected CAPACITY fault at the anylen site must walk a non-pow2
# plan PAST the pow2-only kernel rungs (their feasibility probes
# refuse) to the jnp-fft escape with degraded:true and the demotion
# recorded — results stay numpy-correct on the rung; (4) n=1000 c2c
# + r2c requests served over the real socket protocol on a
# mixed-radix PLAN (not a degrade rung), numpy parity asserted
bluestein-smoke:
	set -o pipefail; \
	PIFFT_PLAN_CACHE=off python3 -c "import numpy as np; \
	from cs87project_msolano2_tpu import plans; \
	from cs87project_msolano2_tpu.models.real import rfft_planes_fast, irfft_planes_fast; \
	rng = np.random.default_rng(0); \
	ns = (2, 7, 127, 720, 999, 3072, 8191); \
	rel = lambda got, ref: float(np.max(np.abs(got - ref)) / np.max(np.abs(ref))); \
	asc = lambda t: np.asarray(t[0]) + 1j * np.asarray(t[1]); \
	errs = {}; vars_ = {}; \
	[(errs.__setitem__(('c2c', n), rel(asc(y), ref)), \
	  errs.__setitem__(('ic2c', n), rel(asc(p.execute_inverse(np.asarray(y[0]), np.asarray(y[1]))), xr + 1j * xi)), \
	  vars_.__setitem__(n, p.variant)) \
	 for n in ns \
	 for xr in [rng.standard_normal(n).astype(np.float32)] \
	 for xi in [rng.standard_normal(n).astype(np.float32)] \
	 for p in [plans.plan(n, layout='natural')] \
	 for y in [p.execute(xr, xi)] \
	 for ref in [np.fft.fft(xr.astype(np.complex128) + 1j * xi.astype(np.complex128))]]; \
	[(errs.__setitem__(('r2c', n), rel(asc(h), np.fft.rfft(x.astype(np.float64)))), \
	  errs.__setitem__(('c2r', n), rel(np.asarray(irfft_planes_fast(np.asarray(h[0]), np.asarray(h[1]), n=n)), x.astype(np.float64)))) \
	 for n in ns \
	 for x in [rng.standard_normal(n).astype(np.float32)] \
	 for h in [rfft_planes_fast(x)]]; \
	bad = {k: e for k, e in errs.items() if e > (1e-4 if k[0] in ('ic2c', 'c2r') else 1e-5)}; \
	assert not bad, bad; \
	assert vars_[127] == 'rader' and vars_[8191] == 'rader', vars_; \
	assert vars_[7] == vars_[720] == vars_[3072] == 'mixedradix', vars_; \
	assert vars_[999] == 'bluestein', vars_; \
	print('# anylen parity ok: ' + ', '.join('n=%d %s %.1e' % (n, vars_.get(n, 'ladder'), errs[('c2c', n)]) for n in ns) + ' (fwd+inv, c2c+r2c)')" && \
	PIFFT_PLAN_CACHE=off python3 bench.py --smoke \
	  --events /tmp/pifft-anylen-events.jsonl \
	  | tee /tmp/pifft-anylen-smoke.json && \
	python3 -c "import json; r = json.load(open('/tmp/pifft-anylen-smoke.json')); \
	  got = r['conv_np768_hbm_bytes']; ctrl = r['conv_np768_pow2_hbm_bytes']; \
	  assert got < ctrl, (got, ctrl); \
	  assert r['conv_np768_parity_relerr'] <= 1e-5, r; \
	  print('# anylen bytes gate ok: metered conv at n=768 moves %d B, pad-to-pow2 control %d B (%.0f%% tax gone, parity %.1e)' \
	        % (got, ctrl, 100.0 * (1 - got / ctrl), r['conv_np768_parity_relerr']))" && \
	PIFFT_PLAN_CACHE=off PIFFT_FAULT=anylen:capacity:1.0 \
	  python3 -c "import numpy as np; \
	from cs87project_msolano2_tpu import plans; \
	rng = np.random.default_rng(0); n = 999; \
	xr = rng.standard_normal(n).astype(np.float32); \
	xi = rng.standard_normal(n).astype(np.float32); \
	p = plans.plan(n, layout='natural'); \
	y = p.execute(xr, xi); \
	ref = np.fft.fft(xr.astype(np.complex128) + 1j * xi.astype(np.complex128)); \
	err = float(np.max(np.abs(np.asarray(y[0]) + 1j * np.asarray(y[1]) - ref)) / np.max(np.abs(ref))); \
	assert p.degraded, 'walk never tagged the plan degraded'; \
	assert p.demotions and p.demotions[-1]['to'] == 'jnp-fft', p.demotions; \
	assert err <= 1e-5, err; \
	print('# anylen degrade ok: injected capacity fault walked %s -> jnp-fft at n=%d, degraded tagged, parity %.1e' \
	      % (p.demotions[-1]['from'], n, err))" && \
	PIFFT_PLAN_CACHE=off python3 -m cs87project_msolano2_tpu.serve.anylen_smoke

# the CI multichip check (docs/MULTICHIP.md): the four sharding
# dryruns on a forced 8-device CPU host platform (incl. the asserted
# collective_recovered window), then the injected-stall recovery loop —
# a stalled supervised all_to_all must abort, reach fallback consensus,
# escape to the communication-free pi-path, and produce a result
# bit-identical to the healthy run, with every event schema-valid
multichip-smoke:
	JAX_PLATFORMS=cpu PIFFT_PLAN_CACHE=off \
	  python3 -c "import __graft_entry__ as g; g.dryrun_multichip(8)"
	JAX_PLATFORMS=cpu PIFFT_PLAN_CACHE=off \
	  XLA_FLAGS="--xla_force_host_platform_device_count=8" \
	  python3 -m cs87project_msolano2_tpu.cli multichip smoke

# the closed fleet loop, end-to-end on CPU (docs/FLEET.md): healthy
# traffic captures drift baselines from the LIVE /slo reservoir (no
# drift flagged); the `shifted` arrival process + an injected device
# stall forces a Mann-Whitney drift verdict; the canary race promotes
# a faster plan into the shared store under journal epoch 1 and live
# p99 RECOVERS after the stall clears; an injected promote-site fault
# rolls back to a BYTE-IDENTICAL store with the schema'd
# fleet_rollback demotion; and a restarted empty-spec mesh prewarms
# every previously-hot GroupKey from the drain-persisted arrival
# model (zero tuning events after restart).  The smoke asserts each
# transition internally and self-provisions a throwaway plan-cache
# dir; the tail re-asserts the summary it printed.
fleet-smoke:
	set -o pipefail; \
	JAX_PLATFORMS=cpu \
	  python3 -m cs87project_msolano2_tpu.fleet.smoke \
	  | tee /tmp/pifft-fleet-smoke.json && \
	python3 -c "import json; r = json.load(open('/tmp/pifft-fleet-smoke.json')); \
	  assert r['ok'], r; p = r['phases']; \
	  assert any(f['drifted'] for f in p['B']['drift']), p['B']; \
	  c = p['C']['outcome']; \
	  assert c['promoted'] and not c['rolled_back'] and c['epoch'] == 1, c; \
	  assert p['C']['recovered_p99_ms'] < p['C']['drifted_p99_ms'], p['C']; \
	  d = p['D']['outcome']; \
	  assert d['rolled_back'] and not d['promoted'], d; \
	  assert p['E']['prewarmed'], p['E']; \
	  assert r['events']['fleet'] == sorted(['fleet_canary', 'fleet_drift', 'fleet_prewarm', 'fleet_promote', 'fleet_rollback']), r['events']; \
	  print('# fleet loop ok: drift -> promote (epoch %d) -> recover -> rollback -> prewarm %s' % (c['epoch'], p['E']['prewarmed']))"

# the CI heterogeneous-backend check (docs/BACKENDS.md): the plan-key
# backend axis end to end on a CPU-only host — schema-5 tokens with
# per-backend cached winners and v4 refusal, `pifft hw probe` typed
# inventory, distinct per-backend roofline ceilings, a two-tag virtual
# mesh whose mid-run kill fails over ACROSS the backend boundary
# (failover:backend:<tag> trail, zero drops), and the gpu / cpu-native
# bench rows parsed back through the analyze loader's backend axis.
# Self-provisions a throwaway plan cache; the tail re-asserts the
# summary it printed.
backend-smoke:
	set -o pipefail; \
	JAX_PLATFORMS=cpu \
	  python3 -m cs87project_msolano2_tpu.hw.smoke \
	  | tee /tmp/pifft-backend-smoke.json && \
	python3 -c "import json; r = json.load(open('/tmp/pifft-backend-smoke.json')); \
	  assert r['ok'], r; p = r['phases']; \
	  assert p['A']['gpu_variant'].startswith('gpu'), p['A']; \
	  assert p['B']['backend'] in ('tpu', 'gpu', 'cpu-interpret', 'cpu-native'), p['B']; \
	  gbps = (p['C']['gpu_gbps'], p['C']['dram_gbps'], p['C']['tpu_v4_gbps']); \
	  assert len(set(gbps)) == 3, p['C']; \
	  assert p['D']['crossed'] >= 1 and p['D']['gpu_parity_relerr'] < 1e-4, p['D']; \
	  assert set(p['E']['backends']) >= {'gpu', 'cpu-native', 'tpu'}, p['E']; \
	  assert r['events']['failover'] >= 1, r['events']; \
	  print('# backend plane ok: %s probe, %d cross-backend reroutes, bench rows %s' % (p['B']['backend'], p['D']['crossed'], ','.join(p['E']['backends'])))"

# the CI live-telemetry check (docs/OBSERVABILITY.md, "The live
# plane"): end-to-end request tracing + the streaming endpoints + the
# burn-rate SLO loop, all asserted in one process — a no-trace socket
# request gets a MINTED trace whose queue/window/compute children sum
# (±5%) to the SLO row's total with every hop parented correctly, a
# client-supplied trace id round-trips, the coalescing burst's batch
# span carries links == coalesced request count, /metrics + /healthz
# answer DURING load (and /slo reports the sliding window), a mid-run
# device kill yields a failover span under the SAME trace, injected
# serve-path latency fires a schema'd slo_alert that demotes the next
# admission to the jnp rung tagged slo:* + degraded:true and RESOLVES
# when the injection stops, the disabled path adds zero events, and
# zero schema-invalid events overall.  The serve-load bench run then
# proves the trace-derived tail-attribution table rides the record.
obs-live-smoke:
	set -o pipefail; \
	JAX_PLATFORMS=cpu PIFFT_PLAN_CACHE=off \
	  python3 -m cs87project_msolano2_tpu.serve.live_smoke && \
	JAX_PLATFORMS=cpu PIFFT_PLAN_CACHE=off python3 bench.py \
	  --serve-load --smoke --events /tmp/pifft-live-events.jsonl \
	  | tee /tmp/pifft-live-slo.json && \
	python3 -c "import json; r = json.load(open('/tmp/pifft-live-slo.json')); \
	  tails = r['serve_tail_attribution']; \
	  assert tails, r.keys(); \
	  row = next(iter(tails.values())); \
	  assert row['p99_owner'] in ('queue', 'window', 'compute'), row; \
	  shares = row['p99_queue_share'] + row['p99_window_share'] + row['p99_compute_share']; \
	  assert abs(shares - 1.0) < 0.01, row; \
	  print('# tail attribution ok: ' + ', '.join('%s p99 owned by %s' % (k, v['p99_owner']) for k, v in tails.items()))" && \
	python3 -m cs87project_msolano2_tpu.cli analyze report \
	  --events /tmp/pifft-live-events.jsonl --json \
	  | python3 -c "import json, sys; r = json.load(sys.stdin); \
	  assert r.get('tail_attribution'), list(r); \
	  print('# analyze tail table ok: %d shape(s)' % len(r['tail_attribution']))"

# project static analysis (check/ subsystem, docs/CHECKS.md): the
# timing/retrace/Mosaic/plan-key invariants as AST rules, gated on the
# committed baseline so only NEW violations fail
check:
	python3 -m cs87project_msolano2_tpu.cli check \
	  --baseline check-baseline.json

# the same run with the per-phase/per-rule wall-time table and the
# summary-cache hit counts — what to reach for when the CI 60s guard
# trips (docs/CHECKS.md, "--stats")
check-stats:
	python3 -m cs87project_msolano2_tpu.cli check \
	  --baseline check-baseline.json --stats

# lint = ruff (general Python hygiene; skipped with a notice where the
# environment lacks it) + pifft check (project invariants).  Both always
# run so one pass reports every finding; the exit status aggregates.
lint:
	@status=0; \
	python3 -m cs87project_msolano2_tpu.cli check \
	  --baseline check-baseline.json || status=1; \
	if command -v ruff >/dev/null 2>&1; then \
	  ruff check . || status=1; \
	else \
	  echo "# ruff not installed; skipping (pip install ruff)"; \
	fi; \
	exit $$status

# the reference's one-command replication entry (make replicate)
replicate: recompile run-experiments-and-analyze-results
