#!/usr/bin/env python3
"""Statistical analysis (L5) — THIN SHIM over the package module.

The law-fitting core (two-coefficient zero-intercept fit, latency
floor, significance + per-cell prediction gate) lives in
``cs87project_msolano2_tpu.analyze.lawfit`` — the single source of
truth shared by this script, ``analyze_results_full.py``, and ``pifft
analyze`` (docs/ANALYSIS.md).  This file keeps the historical TSV
entry point (`analysis/analyze-results` dispatches here, the awk
fallback mirrors the criterion) and re-exports the fitting API under
its old names so existing callers and tests are unaffected.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from cs87project_msolano2_tpu.analyze.lawfit import (  # noqa: E402,F401
    FLOOR_MODELS,
    LOG2_GATE,
    MODELS,
    NATIVE_TIMED,
    ON_CHIP_BACKENDS,
    SERIALIZED_BACKENDS,
    analyze,
    analyze_table,
    fit_laws,
    has_floor_for,
    laws,
    load_tsv,
    ls_fit,
    model_for,
    plot_results,
    predicted_total,
    prediction_gate,
    script_main as main,
    t_sf,
    zero_intercept_fit,
)

if __name__ == "__main__":
    sys.exit(main())
