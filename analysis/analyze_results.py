#!/usr/bin/env python3
"""Statistical analysis (L5): does the measured time obey the predicted
complexity law?

The reference's R scripts (cpu/pthreads/analyze-results.R:23-157) fit
    total ~ 0 + I(funnel_law + tube_law)     (zero-intercept regression)
with funnel_law = n(p-1)/p and tube_law = (n/p) log2(n/p), report the
significance of the fit, and plot empirical + fitted speedup.  This is
the project's integration test: "the implementation scales as designed".

This is a from-scratch Python port of that *discipline* (R is absent in
the image): zero-intercept OLS per phase, t-statistic and its tail
probability (scipy if present, else a normal approximation), empirical
and fitted speedup tables, and optional matplotlib PDFs mirroring the
reference's per-n figure layout.  The awk fallback (analyze-results.awk)
covers machines without numpy, keeping the reference's R -> awk fallback
philosophy (gpu/cuda/analyze-results:26-36).
"""

from __future__ import annotations

import argparse
import math
import os
import sys

import numpy as np


def t_sf(t: float, df: int) -> float:
    """P(T > t) for Student's t; scipy when available, else normal tail."""
    try:
        from scipy import stats

        return float(stats.t.sf(t, df))
    except Exception:
        return 0.5 * math.erfc(t / math.sqrt(2.0))


def load_tsv(path: str) -> tuple[np.ndarray, int]:
    """Returns (rows, n_degraded).  Rows carrying the harness's DEGRADED
    marker (6th column: loop-slope fell back to dispatch-inclusive wall
    time) are excluded from the fit — they carry ~100 ms of relay
    overhead that is not device time."""
    rows, degraded = [], 0
    with open(path) as fh:
        for line in fh:
            parts = line.strip().split("\t")
            if len(parts) in (5, 6) and parts[0] and parts[0][0].isdigit():
                if len(parts) == 6:
                    if parts[5] != "DEGRADED":
                        raise SystemExit(
                            f"{path}: unknown row marker {parts[5]!r} "
                            "(only DEGRADED is defined) — refusing to fit "
                            "data of unknown provenance"
                        )
                    degraded += 1
                    continue
                rows.append([float(v) for v in parts])
    if not rows:
        raise SystemExit(f"no usable data rows in {path}")
    return np.asarray(rows), degraded  # n p total funnel tube


# Which complexity law governs each phase depends on WHERE the p virtual
# processors run:
#  * per-processor (the reference's law, analyze-results.R:35-37): each
#    of p real cores runs its own chain, so time tracks the per-processor
#    work — funnel n(p-1)/p, tube (n/p)log2(n/p).
#  * on-chip (single-accelerator butterfly backends jax/pallas): ALL p
#    virtual processors are materialized as rows of one array on one
#    chip, whose throughput is fixed — time tracks the TOTAL work, p x
#    the per-processor law: funnel n(p-1) (the paper's redundant
#    replication made explicit), tube n*log2(n/p) (each stage touches all
#    n elements regardless of p).  On a real multi-chip mesh each device
#    runs only its own chain (parallel/pi_shard.py), recovering the
#    per-processor law.
#  * einsum-dense (the einsum backend): the same phases expressed as
#    dense contractions predict DIFFERENT complexity — funnel is the
#    (p, p, s)-coefficient einsum, Theta(p*n) ~ n(p-1) total work (0 at
#    p=1, where the funnel is empty); the tube is a dense s-point DFT
#    matrix per segment, Theta(p*s^2) = n^2/p.  Fitting the butterfly
#    law to a dense implementation would test the wrong hypothesis.
#  * serialized (CPU backends running all p virtual processors on fewer
#    real cores: the `serial` backend by construction, and any backend
#    swept with --oversubscribe, which the harness writes to a distinct
#    `-oversub-` file so the regime is visible in the filename): wall
#    time (total_ms) is the SUM over processors — the same total-work
#    laws as on-chip — but the funnel/tube COLUMNS are still processor
#    0's per-processor timers (native/pifft_backends.c:62-67), so the
#    phase fits keep the per-processor laws.  See fit_laws().
MODELS = ("per-processor", "on-chip", "einsum-dense", "serialized")
ON_CHIP_BACKENDS = ("jax", "pallas")
SERIALIZED_BACKENDS = ("serial",)


def model_for(path: str, requested: str = "auto") -> str:
    if requested != "auto":
        return requested
    base = os.path.basename(path)
    if "-oversub-" in base:  # harness --oversubscribe output (any backend)
        return "serialized"
    if "-einsum-" in base:
        return "einsum-dense"
    if any(f"-{b}-" in base for b in ON_CHIP_BACKENDS):
        return "on-chip"
    if any(f"-{b}-" in base for b in SERIALIZED_BACKENDS):
        return "serialized"
    return "per-processor"


def laws(n: np.ndarray, p: np.ndarray,
         model: str = "per-processor") -> tuple[np.ndarray, np.ndarray]:
    s = n / p
    log_s = np.where(s > 1, np.log2(np.maximum(s, 2)), 0.0)
    if model in ("on-chip", "serialized"):
        return n * (p - 1), n * log_s
    if model == "einsum-dense":
        return n * (p - 1), n * n / p
    return n * (p - 1) / p, s * log_s


def fit_laws(n: np.ndarray, p: np.ndarray,
             model: str) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Per-COLUMN regressors (total_x, funnel_x, tube_x).

    The serialized model is hybrid: total_ms sums over the p virtual
    processors run back-to-back (total-work laws), but the funnel/tube
    columns are processor 0's own phase timers
    (native/pifft_backends.c:62-67) and obey the per-processor laws —
    fitting them against total-work laws is off by a factor of p (the
    round-3 advisor measured tube R^2 0.999 -> 0.69 from exactly that).
    Every other model times all three columns in the same regime."""
    fl, tl = laws(n, p, model)
    if model == "serialized":
        pfl, ptl = laws(n, p, "per-processor")
        return fl + tl, pfl, ptl
    return fl + tl, fl, tl


def predicted_total(report: dict, n: np.ndarray, p: np.ndarray,
                    model: str) -> np.ndarray:
    """Fitted-law total time at (n, p), for speedup tables and figures.

    Serialized: the phase betas predict processor-0's phases, not the
    summed wall time, so the total fit's single beta applies to the
    total-work law.  Other models: the reference's two-coefficient
    prediction beta_f*funnel_law + beta_t*tube_law."""
    fl, tl = laws(n, p, model)
    if model == "serialized":
        return report["total"]["beta"] * (fl + tl)
    return report["funnel"]["beta"] * fl + report["tube"]["beta"] * tl


def zero_intercept_fit(x: np.ndarray, y: np.ndarray):
    """y ~ 0 + beta*x: returns (beta, r2, tstat, alpha, df)."""
    sxx = float(np.sum(x * x))
    if sxx == 0:
        return 0.0, 0.0, 0.0, 1.0, 0
    beta = float(np.sum(x * y)) / sxx
    resid = y - beta * x
    df = max(len(y) - 1, 1)
    sigma2 = float(np.sum(resid * resid)) / df
    se = math.sqrt(sigma2 / sxx) if sigma2 > 0 else 0.0
    tstat = beta / se if se > 0 else float("inf")
    ss_tot = float(np.sum(y * y))  # zero-intercept R^2 convention
    r2 = 1.0 - float(np.sum(resid * resid)) / ss_tot if ss_tot > 0 else 0.0
    alpha = t_sf(tstat, df) if math.isfinite(tstat) else 0.0
    return beta, r2, tstat, alpha, df


def analyze(path: str, alpha_level: float = 0.01, plot_dir: str | None = None,
            model: str = "auto"):
    data, degraded = load_tsv(path)
    model = model_for(path, model)
    n, p, total, funnel, tube = data.T
    total_law, funnel_law, tube_law = fit_laws(n, p, model)

    report = {"model": model}
    print(f"== {os.path.basename(path)}: {len(n)} runs, "
          f"n in {sorted(int(v) for v in set(n))}, "
          f"p in {sorted(int(v) for v in set(p))}, "
          f"law model: {model} ==")
    if degraded:
        print(f"# excluded {degraded} DEGRADED rows "
              "(dispatch-inclusive fallback timing)")
    for name, y, x in (
        ("total", total, total_law),
        ("funnel", funnel, funnel_law),
        ("tube", tube, tube_law),
    ):
        if not np.any(x):
            # Degenerate grid: the law is identically zero here (e.g. a
            # p=1-only sweep, where funnel_law = n(p-1)/p = 0 — this
            # container's pthreads capacity is 1 core).  The hypothesis
            # "time scales as the law" is vacuously satisfied iff the
            # measured phase time is also ~0; there is nothing to regress.
            negligible = float(np.mean(y)) <= 1e-3 * float(np.mean(total))
            verdict = "Yes (vacuous: law = 0 on this grid)" if negligible \
                else "No"
            print(f"{name:>6}: law = 0 over the whole grid; measured mean "
                  f"{float(np.mean(y)):.3e} ms  law holds: {verdict}")
            report[name] = dict(beta=0.0, r2=0.0, t=0.0, alpha=1.0,
                                holds=negligible)
            continue
        beta, r2, tstat, a, df = zero_intercept_fit(x, y)
        holds = a < alpha_level and beta > 0
        verdict = "Yes" if holds else "No"
        frac = float(np.mean(y)) / max(float(np.mean(total)), 1e-30)
        if not holds and name != "total" and frac < 0.01:
            # A phase that is a sub-percent sliver of the total sits at
            # the timing floor — its measurements are noise, and neither
            # law acceptance nor rejection is supportable (e.g. the
            # einsum funnel, Theta(n*p) work next to a Theta(n^2/p)
            # tube: ratio n/p^2, thousands at these grids).  The
            # reference never hits this (its funnel is a large share of
            # total); report it as untestable rather than failing.
            # record the distinct value "untestable" (truthy, so the
            # law-gate consumers pass) rather than True, keeping a
            # broken near-zero timer distinguishable from a real pass
            holds = "untestable"
            verdict = (f"untestable (phase is {frac * 100:.2g}% of "
                       "total — below the timing floor)")
        print(f"{name:>6}: time ~ {beta:.3e} * law   R^2={r2:.4f}  "
              f"t={tstat:.1f} (df={df})  alpha={a:.3e}  "
              f"law holds: {verdict}")
        report[name] = dict(beta=beta, r2=r2, t=tstat, alpha=a,
                            holds=holds)

    # speedup tables (reference: empirical + fitted, per n)
    print("\nspeedup (empirical vs fitted-law):")
    for nn in sorted(set(n.astype(int))):
        sel1 = (n == nn) & (p == 1)
        if not sel1.any():
            continue
        t1 = float(np.mean(total[sel1]))
        t1_law = predicted_total(
            report, np.array([float(nn)]), np.array([1.0]), model)[0]
        for pp in sorted(set(p[n == nn].astype(int))):
            sel = (n == nn) & (p == pp)
            tp = float(np.mean(total[sel]))
            tp_law = predicted_total(
                report, np.array([float(nn)]), np.array([float(pp)]), model)[0]
            fitted = t1_law / max(tp_law, 1e-30)
            print(f"  n={nn:>9} p={pp:>4}: {t1 / tp:7.2f}x  "
                  f"(law predicts {float(fitted):7.2f}x)")

    if plot_dir:
        try:
            plot_results(data, report, plot_dir, os.path.basename(path))
        except Exception as e:  # plots are best-effort, like the awk path
            print(f"# plotting skipped: {type(e).__name__}: {e}",
                  file=sys.stderr)
    return report


def plot_results(data, report, plot_dir: str, stem: str):
    """Per-n PDF: speedup scatter + fitted curve, stacked phase times —
    mirroring the reference figure layout (analyze-results.R:119-151)."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    os.makedirs(plot_dir, exist_ok=True)
    n, p, total, funnel, tube = data.T
    model = report.get("model", "per-processor")

    for nn in sorted(set(n.astype(int))):
        sel1 = (n == nn) & (p == 1)
        if not sel1.any():
            continue
        t1 = float(np.mean(total[sel1]))
        ps = np.array(sorted(set(p[n == nn].astype(int))))
        emp = np.array([t1 / float(np.mean(total[(n == nn) & (p == pp)]))
                        for pp in ps])
        grid = np.array([2**k for k in range(0, int(np.log2(ps.max())) + 1)])
        fit = predicted_total(
            report, np.array([float(nn)]), np.array([1.0]), model
        )[0] / np.maximum(
            predicted_total(report, np.full_like(grid, nn, dtype=float),
                            grid.astype(float), model), 1e-30)

        fig, (ax1, ax2) = plt.subplots(1, 2, figsize=(9, 3.6))
        ax1.plot(ps, emp, "o", label="measured")
        ax1.plot(grid, fit, "-", label="fitted law")
        ax1.set_xscale("log", base=2)
        ax1.set_xlabel("p")
        ax1.set_ylabel("speedup")
        ax1.set_title(f"n = {nn}")
        ax1.legend()

        fmean = [float(np.mean(funnel[(n == nn) & (p == pp)])) for pp in ps]
        tmean = [float(np.mean(tube[(n == nn) & (p == pp)])) for pp in ps]
        ax2.bar([str(v) for v in ps], fmean, label="funnel")
        ax2.bar([str(v) for v in ps], tmean, bottom=fmean, label="tube")
        ax2.set_xlabel("p")
        ax2.set_ylabel("phase time (ms)")
        ax2.legend()
        fig.tight_layout()
        out = os.path.join(plot_dir, f"{stem}-n{nn}.pdf")
        fig.savefig(out)
        plt.close(fig)
        print(f"# wrote {out}", file=sys.stderr)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("tsv", nargs="+")
    ap.add_argument("--alpha", type=float, default=0.01)
    ap.add_argument("--plots", default=None,
                    help="directory for per-n PDF figures")
    ap.add_argument("--model", default="auto",
                    choices=("auto",) + MODELS,
                    help="complexity-law model; auto picks einsum-dense "
                         "for the einsum backend, on-chip for the other "
                         "single-accelerator backends (jax/pallas), and "
                         "per-processor otherwise")
    args = ap.parse_args(argv)
    ok = True
    for path in args.tsv:
        report = analyze(path, args.alpha, args.plots, args.model)
        ok &= report["total"]["holds"]
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
