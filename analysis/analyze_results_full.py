#!/usr/bin/env python3
"""Paper-figure analysis (A3 parity) — THIN SHIM over the package
module.

The two-panel publication figure (speedup vs p, one curve per n; phase
shares) and the zero-intercept summary block live in
``cs87project_msolano2_tpu.analyze.figures``; this file keeps the
historical entry point:

    python3 analysis/analyze_results_full.py datasets/*.tsv --out datasets
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from cs87project_msolano2_tpu.analyze.figures import (  # noqa: E402,F401
    figure,
    summary,
)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("tsv", nargs="+")
    ap.add_argument("--out", default=".")
    args = ap.parse_args(argv)
    os.makedirs(args.out, exist_ok=True)
    for path in args.tsv:
        summary(path)
        figure(path, args.out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
