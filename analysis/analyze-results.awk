# analyze-results.awk — limited law-fit analysis for machines without
# python/numpy (the reference keeps an awk fallback for machines without R,
# gpu/cuda/analyze-results.awk — this is a fresh implementation of the same
# idea, upgraded in round 5 to the falsifiable criterion of
# analyze_results.py):
#
#   * the TOTAL time is fitted against BOTH phase laws with separate
#     coefficients (a single beta on the summed law cannot fail against
#     monotone data when the two phases' constants differ by orders of
#     magnitude — the round-4 einsum sweep proved it);
#   * measurements that ride a JAX dispatch pipeline (filenames -jax-,
#     -pallas-, -einsum-, -sharded-) get a latency-FLOOR column; a
#     fitted floor that is negative or exceeds 2x the smallest cell
#     mean is least squares absorbing misfit, and is dropped;
#   * acceptance = significance of every MATERIAL (>=5% share) law
#     coefficient AND the per-cell prediction gate
#     median |log(measured/predicted)| < log 2.
#
# Law model selection mirrors analyze_results.py::model_for.  Rows
# marked DEGRADED (6th column) are excluded.  Only the TOTAL time is
# fitted here; the python analysis's per-phase fits have no awk
# counterpart.
#
# Input: 5- or 6-column TSV  n  p  total_ms  funnel_ms  tube_ms  [DEGRADED]
# Usage: awk -f analyze-results.awk results.tsv

function log2(v) { return log(v) / log(2) }

function funnel_law(n, p) {
    if (model == "einsum-dense" || model == "on-chip" || model == "serialized")
        return n * (p - 1)
    return n * (p - 1) / p
}

function tube_law(n, p,    s, lg) {
    s = n / p
    lg = (s > 1) ? log2(s) : 0
    if (model == "einsum-dense")
        return s * s            # MXU absorbs the batch: per-processor work
    if (model == "on-chip" || model == "serialized")
        return n * lg
    return s * lg
}

# upper normal tail via Abramowitz-Stegun 7.1.26 erfc approximation
function normal_sf(z,    t, y) {
    if (z < 0) return 1 - normal_sf(-z)
    if (z > 12) return 1e-30
    t = 1.0 / (1.0 + 0.3275911 * z / sqrt(2))
    y = t * (0.254829592 + t * (-0.284496736 + t * (1.421413741 \
        + t * (-1.453152027 + t * 1.061405429)))) * exp(-z * z / 2)
    return y / 2
}

function abs(v) { return v < 0 ? -v : v }

# Solve the k x k normal equations A beta = b by Gaussian elimination
# with partial pivoting; result in sol[1..k].  Returns 0 on a singular
# system.
function solve(k, A, b, sol,    i, j, l, piv, t) {
    for (i = 1; i <= k; i++)
        for (j = 1; j <= k; j++) M[i, j] = A[i, j]
    for (i = 1; i <= k; i++) v[i] = b[i]
    for (i = 1; i <= k; i++) {
        piv = i
        for (l = i + 1; l <= k; l++)
            if (abs(M[l, i]) > abs(M[piv, i])) piv = l
        if (M[piv, i] == 0) return 0
        if (piv != i) {
            for (j = 1; j <= k; j++) { t = M[i, j]; M[i, j] = M[piv, j]; M[piv, j] = t }
            t = v[i]; v[i] = v[piv]; v[piv] = t
        }
        for (l = i + 1; l <= k; l++) {
            t = M[l, i] / M[i, i]
            for (j = i; j <= k; j++) M[l, j] -= t * M[i, j]
            v[l] -= t * v[i]
        }
    }
    for (i = k; i >= 1; i--) {
        t = v[i]
        for (j = i + 1; j <= k; j++) t -= M[i, j] * sol[j]
        sol[i] = t / M[i, i]
    }
    return 1
}

# Fit y ~ X[.,1..k] over m rows (globals X, Y); fills beta[], se[],
# r2g, ssrg.  Columns are RMS-normalized for conditioning.
function fit(k,    i, j, l, s, A, b, sol, yh, ssr, syy, sigma2, Ainv) {
    for (j = 1; j <= k; j++) {
        s = 0
        for (i = 1; i <= m; i++) s += X[i, j] * X[i, j]
        scale[j] = sqrt(s / m); if (scale[j] == 0) scale[j] = 1e-30
    }
    for (j = 1; j <= k; j++)
        for (l = 1; l <= k; l++) {
            s = 0
            for (i = 1; i <= m; i++)
                s += (X[i, j] / scale[j]) * (X[i, l] / scale[l])
            A[j, l] = s
        }
    for (j = 1; j <= k; j++) {
        s = 0
        for (i = 1; i <= m; i++) s += (X[i, j] / scale[j]) * Y[i]
        b[j] = s
    }
    if (!solve(k, A, b, sol)) return 0
    ssr = 0; syy = 0
    for (i = 1; i <= m; i++) {
        yh = 0
        for (j = 1; j <= k; j++) yh += sol[j] * X[i, j] / scale[j]
        pred[i] = yh
        ssr += (Y[i] - yh) * (Y[i] - yh)
        syy += Y[i] * Y[i]
    }
    sigma2 = ssr / (m > k ? m - k : 1)
    # se via the inverse normal matrix diagonal: re-solve k unit systems
    for (j = 1; j <= k; j++) {
        for (l = 1; l <= k; l++) e[l] = (l == j) ? 1 : 0
        if (!solve(k, A, e, Ainvcol)) return 0
        se[j] = sqrt((sigma2 * Ainvcol[j] > 0) ? sigma2 * Ainvcol[j] : 0)
    }
    for (j = 1; j <= k; j++) { beta[j] = sol[j] / scale[j]; sen[j] = sol[j]; seu[j] = se[j] }
    r2g = (syy > 0) ? 1 - ssr / syy : 0
    return 1
}

FNR == 1 {
    base = FILENAME
    sub(/.*\//, "", base)      # basename, mirroring model_for()
    newmodel = (force_model != "") ? force_model : \
               (base ~ /-oversub-/) ? "serialized" : \
               (base ~ /-einsum-/) ? "einsum-dense" : \
               (base ~ /-jax-scan-/) ? "per-processor" : \
               (base ~ /-(jax|pallas)-/) ? "on-chip" : \
               (base ~ /-serial-/) ? "serialized" : "per-processor"
    if (model != "" && newmodel != model) mixed = 1
    model = newmodel
    # floor column: jax-dispatch-timed files (mirrors has_floor_for)
    floorfile = (base ~ /-(serial|pthreads)-/) ? 0 : \
                (model == "on-chip" || model == "einsum-dense" || \
                 base ~ /-sharded-/ || base ~ /-jax-scan-/) ? 1 : 0
}

$1 ~ /^[0-9]+$/ && NF == 6 && $6 == "DEGRADED" { degraded += 1; next }

# unknown 6th-column markers: refuse, like load_tsv does
$1 ~ /^[0-9]+$/ && NF == 6 { badmarker = $6; exit 1 }

$1 ~ /^[0-9]+$/ && NF == 5 {
    m += 1
    N[m] = $1; P[m] = $2; Y[m] = $3
    key = $1 "|" $2
    cnt[key] += 1; sum[key] += $3
    if (!($1 in seen_n)) { seen_n[$1] = 1; ns[++nn] = $1 }
    if ($2 > maxp) maxp = $2
}

END {
    if (badmarker != "") {
        printf "error: unknown row marker '%s' (only DEGRADED is defined) — refusing to fit\n", badmarker
        exit 1
    }
    if (mixed) {
        print "error: input files select different law models — analyze them separately"
        exit 1
    }
    if (m < 4) { print "error: not enough data"; exit 1 }

    # columns: funnel law (if not identically 0), tube law, floor (maybe)
    kf = 0; kt = 0
    for (i = 1; i <= m; i++) if (funnel_law(N[i], P[i]) != 0) kf = 1
    ncol = 0
    if (kf) { ncol += 1; colname[ncol] = "funnel" }
    ncol += 1; colname[ncol] = "tube"
    if (floorfile) { ncol += 1; colname[ncol] = "floor" }
    for (i = 1; i <= m; i++) {
        j = 0
        if (kf) { j += 1; X[i, j] = funnel_law(N[i], P[i]) }
        j += 1; X[i, j] = tube_law(N[i], P[i])
        if (floorfile) { j += 1; X[i, j] = 1 }
    }
    if (!fit(ncol)) { print "error: singular fit"; exit 1 }

    # floor sanity: must be positive and <= 2x the smallest cell mean
    if (floorfile) {
        minmean = 1e300
        for (key in cnt) if (sum[key] / cnt[key] < minmean) minmean = sum[key] / cnt[key]
        if (beta[ncol] < 0 || beta[ncol] > 2 * minmean) {
            ncol -= 1
            for (i = 1; i <= m; i++) delete X[i, ncol + 1]
            floorfile = 0
            if (!fit(ncol)) { print "error: singular fit"; exit 1 }
        }
    }
    # negligible-negative law column: drop the funnel column and refit
    ymean = 0; for (i = 1; i <= m; i++) ymean += Y[i]; ymean /= m
    if (kf) {
        share = 0
        for (i = 1; i <= m; i++) share += beta[1] * X[i, 1]
        share = share / m / ymean
        if (beta[1] < 0 && share > -0.01) {
            for (i = 1; i <= m; i++) {
                for (j = 1; j < ncol; j++) X[i, j] = X[i, j + 1]
                delete X[i, ncol]
            }
            for (j = 1; j < ncol; j++) colname[j] = colname[j + 1]
            ncol -= 1; kf = 0
            if (!fit(ncol)) { print "error: singular fit"; exit 1 }
        }
    }

    # significance of material (>=5% share) law coefficients
    signif = 1; nmajor = 0
    for (j = 1; j <= ncol; j++) {
        if (colname[j] == "floor") continue
        share = 0
        for (i = 1; i <= m; i++) share += beta[j] * X[i, j]
        share = share / m / ymean
        tj = (seu[j] > 0) ? sen[j] / seu[j] : 1e9
        aj = normal_sf(tj)
        tstat[j] = tj; alpha[j] = aj
        if (share >= 0.05 || share <= -0.05) {
            nmajor += 1
            if (!(aj < 0.01 && beta[j] > 0)) signif = 0
        }
    }
    if (nmajor == 0) signif = 0

    # prediction gate: median |log(measured/predicted)| < log 2
    maxy = 0
    for (i = 1; i <= m; i++) if (Y[i] > maxy) maxy = Y[i]
    ng = 0; gatefail = 0
    for (i = 1; i <= m; i++) {
        if (pred[i] <= 0) {
            if (Y[i] > 1e-3 * maxy) gatefail = 1
            continue
        }
        if (Y[i] > 0) { ng += 1; errs[ng] = abs(log(Y[i] / pred[i])) }
    }
    # insertion sort for the median (plain awk has no asort)
    for (i = 2; i <= ng; i++) {
        t = errs[i]; j = i - 1
        while (j >= 1 && errs[j] > t) { errs[j + 1] = errs[j]; j -= 1 }
        errs[j + 1] = t
    }
    mederr = (ng == 0) ? 0 : (ng % 2 ? errs[(ng + 1) / 2] : \
             (errs[ng / 2] + errs[ng / 2 + 1]) / 2)
    if (gatefail) mederr = 1e9
    gate_ok = (!gatefail && mederr < log(2))

    printf "limited analysis (awk fallback; install numpy for the full one)\n"
    printf "law model: %s%s\n", model, (floorfile ? " + latency floor" : "")
    if (degraded > 0)
        printf "excluded %d DEGRADED rows (dispatch-inclusive timing)\n", degraded
    printf "runs: %d   fit: total_ms ~", m
    for (j = 1; j <= ncol; j++)
        printf " %s %s=%.3e", (j > 1 ? " +" : ""), colname[j], beta[j]
    printf "   R^2=%.4f\n", r2g
    for (j = 1; j <= ncol; j++)
        if (colname[j] != "floor")
            printf "  %s: t=%.1f alpha~%.2e\n", colname[j], tstat[j], alpha[j]
    printf "prediction gate: med|log err|=%.3f (< %.3f: %s)\n", \
        (mederr > 1e8 ? 999 : mederr), log(2), (gate_ok ? "ok" : "FAIL")
    printf "law holds: %s\n", ((signif && gate_ok) ? "Yes" : "No")
    printf "\navg total_ms at max p per n (measured vs fitted):\n"
    for (i = 1; i <= nn; i++) {
        n = ns[i]; key = n "|" maxp
        if (key in cnt) {
            yh = 0; j = 0
            if (kf) { j += 1; yh += beta[j] * funnel_law(n, maxp) }
            j += 1; yh += beta[j] * tube_law(n, maxp)
            if (floorfile) yh += beta[j + 1]
            printf "  n=%9d p=%d: %10.3f ms  (law: %10.3f ms)\n", \
                n, maxp, sum[key] / cnt[key], yh
        }
    }
}
