# analyze-results.awk — limited law-fit analysis for machines without
# python/numpy (the reference keeps an awk fallback for machines without R,
# gpu/cuda/analyze-results.awk — this is a fresh implementation of the same
# idea: zero-intercept least squares of total time against the predicted
# complexity law, a t-statistic for the slope, and a normal-tail
# significance approximation).
#
# Law model selection mirrors analyze_results.py::model_for: the einsum
# backend (-einsum-) gets the einsum-dense law (funnel n(p-1), tube
# n^2/p — dense contractions), other single-accelerator backends
# (-jax-/-pallas-) the on-chip law (funnel n(p-1), tube n*log2(n/p) —
# all p virtual processors on one chip, time tracks total work), and
# everything else the reference's per-processor law.  Rows marked
# DEGRADED (6th column: dispatch-inclusive fallback timing) are
# excluded, as in the python analysis.  Only the TOTAL time is fitted
# here; the python analysis's per-phase fits (and its negligible-phase
# "untestable" rule) have no awk counterpart.
#
# Input: 5- or 6-column TSV  n  p  total_ms  funnel_ms  tube_ms  [DEGRADED]
# Usage: awk -f analyze-results.awk results.tsv

function log2(v) { return log(v) / log(2) }

# law(n, p) under the selected model
function law(n, p,    s, lg) {
    s = n / p
    lg = (s > 1) ? log2(s) : 0
    if (model == "einsum-dense")
        return n * (p - 1) + n * n / p
    if (model == "on-chip" || model == "serialized")
        return n * (p - 1) + n * lg
    return n * (p - 1) / p + s * lg
}

# upper normal tail via Abramowitz-Stegun 7.1.26 erfc approximation
function normal_sf(z,    t, y) {
    if (z > 12) return 1e-30
    t = 1.0 / (1.0 + 0.3275911 * z / sqrt(2))
    y = t * (0.254829592 + t * (-0.284496736 + t * (1.421413741 \
        + t * (-1.453152027 + t * 1.061405429)))) * exp(-z * z / 2)
    return y / 2
}

FNR == 1 {
    base = FILENAME
    sub(/.*\//, "", base)      # basename, mirroring model_for()
    newmodel = (force_model != "") ? force_model : \
               (base ~ /-oversub-/) ? "serialized" : \
               (base ~ /-einsum-/) ? "einsum-dense" : \
               (base ~ /-(jax|pallas)-/) ? "on-chip" : \
               (base ~ /-serial-/) ? "serialized" : "per-processor"
    if (model != "" && newmodel != model) mixed = 1
    model = newmodel
}

$1 ~ /^[0-9]+$/ && NF == 6 && $6 == "DEGRADED" { degraded += 1; next }

# unknown 6th-column markers: refuse, like load_tsv does
$1 ~ /^[0-9]+$/ && NF == 6 { badmarker = $6; exit 1 }

$1 ~ /^[0-9]+$/ && NF == 5 {
    x = law($1, $2); y = $3
    sxx += x * x; sxy += x * y; syy += y * y
    m += 1
    key = $1 "|" $2
    cnt[key] += 1; sum[key] += y
    if (!($1 in seen_n)) { seen_n[$1] = 1; ns[++nn] = $1 }
    if ($2 > maxp) maxp = $2
}

END {
    if (badmarker != "") {
        printf "error: unknown row marker '%s' (only DEGRADED is defined) — refusing to fit\n", badmarker
        exit 1
    }
    if (mixed) {
        print "error: input files select different law models — analyze them separately"
        exit 1
    }
    if (m < 2 || sxx == 0) { print "error: not enough data"; exit 1 }
    beta = sxy / sxx
    ssr = syy - beta * sxy           # sum of squared residuals (zero-intercept)
    if (ssr < 0) ssr = 0
    df = m - 1
    se = sqrt(ssr / df / sxx)
    t = (se > 0) ? beta / se : 1e9
    alpha = normal_sf(t)
    r2 = (syy > 0) ? 1 - ssr / syy : 0

    printf "limited analysis (awk fallback; install numpy for the full one)\n"
    printf "law model: %s\n", model
    if (degraded > 0)
        printf "excluded %d DEGRADED rows (dispatch-inclusive timing)\n", degraded
    printf "runs: %d   fit: total_ms ~ %.3e * law   R^2=%.4f  t=%.1f  alpha~%.2e\n", \
        m, beta, r2, t, alpha
    printf "law holds: %s\n", (alpha < 0.01 && beta > 0) ? "Yes" : "No"
    printf "\navg total_ms at max p per n (measured vs beta*law):\n"
    for (i = 1; i <= nn; i++) {
        n = ns[i]; key = n "|" maxp
        if (key in cnt)
            printf "  n=%9d p=%d: %10.3f ms  (law: %10.3f ms)\n", \
                n, maxp, sum[key] / cnt[key], beta * law(n, maxp)
    }
}
