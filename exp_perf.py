"""Round-3 perf experiments, part 10: composed rql with the 256-point
MXU tail (one fewer VPU traversal) x cb tuning, plus accuracy check."""

import sys

import numpy as np

import jax
import jax.numpy as jnp

from cs87project_msolano2_tpu.ops.pallas_fft import fft_pi_layout_pallas_rql
from cs87project_msolano2_tpu.utils.timing import loop_slope_ms

N = 1 << 20
K1, K2, REPS = 64, 1024, 5


def gf(ms):
    return 5.0 * N * np.log2(N) / (ms * 1e-3) / 1e9


def main():
    key = jax.random.PRNGKey(0)
    xr = jax.random.normal(key, (N,), jnp.float32)
    xi = jax.random.normal(jax.random.fold_in(key, 1), (N,), jnp.float32)
    inv = np.float32(1.0 / np.sqrt(N))

    def rql(c, tile, cb, tail):
        yr, yi = fft_pi_layout_pallas_rql(c[0], c[1], tile=tile, cb=cb,
                                          tail=tail)
        return yr * inv, yi * inv

    cases = [
        ("t16 cb13 tail128", lambda c: rql(c, 1 << 16, 1 << 13, 128)),
        ("t16 cb13 tail256", lambda c: rql(c, 1 << 16, 1 << 13, 256)),
        ("t16 cb11 tail256", lambda c: rql(c, 1 << 16, 1 << 11, 256)),
        ("t16 cb12 tail256", lambda c: rql(c, 1 << 16, 1 << 12, 256)),
        ("t15 cb13 tail256", lambda c: rql(c, 1 << 15, 1 << 13, 256)),
        ("t16 cb13 tail512", lambda c: rql(c, 1 << 16, 1 << 13, 512)),
    ]
    for rnd in range(3):
        for name, body in cases:
            try:
                ms = loop_slope_ms(body, (xr, xi), k1=K1, k2=K2, reps=REPS,
                                   min_delta_ms=100.0)
                print(f"[{rnd}] {name}: {ms:.4f} ms  ({gf(ms):.0f} GF)",
                      flush=True)
            except Exception as e:
                print(f"[{rnd}] {name}: FAILED {type(e).__name__}", flush=True)

    # accuracy at bench shape (fetches — last)
    rng = np.random.default_rng(0)
    hxr = rng.standard_normal(N).astype(np.float32)
    hxi = rng.standard_normal(N).astype(np.float32)
    ref = np.fft.fft(hxr.astype(np.complex128) + 1j * hxi)
    from cs87project_msolano2_tpu.ops.bits import bit_reverse_indices
    idx = bit_reverse_indices(N)
    scale = np.max(np.abs(ref))
    for tail in (128, 256, 512):
        yr, yi = jax.jit(
            lambda a, b, t=tail: fft_pi_layout_pallas_rql(
                a, b, tile=1 << 16, cb=1 << 13, tail=t)
        )(hxr, hxi)
        y = np.asarray(yr).astype(np.complex128) + 1j * np.asarray(yi)
        err = np.max(np.abs(y[idx] - ref)) / scale
        print(f"tail={tail}: rel_err {err:.2e}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
