"""Round-3 perf experiments, part 11: four-step matmul funnel (mf) vs
the rql composed path at N=2^20 — R sweep x cb tuning, plus accuracy.

mf runs the first log2(R) stages as one R-point DFT matmul + twiddle
grid (ops/pallas_fft.py::dft_funnel_matrices); larger R moves more
levels onto the MXU and shrinks the tile kernel's VPU stage count, at
R^2-growing matmul flops.  The expected sweet spot is R in {128, 256}.
"""

import sys

import numpy as np

import jax
import jax.numpy as jnp

from cs87project_msolano2_tpu.ops.pallas_fft import (
    fft_pi_layout_pallas_mf,
    fft_pi_layout_pallas_rql,
)
from cs87project_msolano2_tpu.utils.timing import loop_slope_ms

N = 1 << 20
K1, K2, REPS = 64, 1024, 5


def gf(ms):
    return 5.0 * N * np.log2(N) / (ms * 1e-3) / 1e9


def main():
    key = jax.random.PRNGKey(0)
    xr = jax.random.normal(key, (N,), jnp.float32)
    xi = jax.random.normal(jax.random.fold_in(key, 1), (N,), jnp.float32)
    inv = np.float32(1.0 / np.sqrt(N))

    def rql(c, tile, cb, tail):
        yr, yi = fft_pi_layout_pallas_rql(c[0], c[1], tile=tile, cb=cb,
                                          tail=tail)
        return yr * inv, yi * inv

    def mf(c, R, cb, tail):
        yr, yi = fft_pi_layout_pallas_mf(c[0], c[1], R=R, cb=cb, tail=tail)
        return yr * inv, yi * inv

    cases = [
        ("rql t16 cb13 tail256", lambda c: rql(c, 1 << 16, 1 << 13, 256)),
        ("mf R128 cb13 tail256", lambda c: mf(c, 128, 1 << 13, 256)),
        ("mf R128 cb12 tail256", lambda c: mf(c, 128, 1 << 12, 256)),
        ("mf R256 cb12 tail256", lambda c: mf(c, 256, 1 << 12, 256)),
        ("mf R256 cb12 tail512", lambda c: mf(c, 256, 1 << 12, 512)),
        ("mf R64  cb13 tail256", lambda c: mf(c, 64, 1 << 13, 256)),
    ]
    for rnd in range(3):
        for name, body in cases:
            try:
                ms = loop_slope_ms(body, (xr, xi), k1=K1, k2=K2, reps=REPS,
                                   min_delta_ms=100.0)
                print(f"[{rnd}] {name}: {ms:.4f} ms  ({gf(ms):.0f} GF)",
                      flush=True)
            except Exception as e:
                print(f"[{rnd}] {name}: FAILED {type(e).__name__}: "
                      f"{str(e)[:100]}", flush=True)

    # accuracy at bench shape (fetches — last)
    rng = np.random.default_rng(0)
    hxr = rng.standard_normal(N).astype(np.float32)
    hxi = rng.standard_normal(N).astype(np.float32)
    ref = np.fft.fft(hxr.astype(np.complex128) + 1j * hxi)
    from cs87project_msolano2_tpu.ops.bits import bit_reverse_indices
    idx = bit_reverse_indices(N)
    scale = np.max(np.abs(ref))
    for R in (128, 256):
        yr, yi = jax.jit(
            lambda a, b, r=R: fft_pi_layout_pallas_mf(
                a, b, R=r, cb=1 << 12, tail=256)
        )(hxr, hxi)
        y = np.asarray(yr).astype(np.complex128) + 1j * np.asarray(yi)
        err = np.max(np.abs(y[idx] - ref)) / scale
        print(f"mf R={R}: rel_err {err:.2e}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
