"""Perf experiments: four-step matmul funnel (mf) vs the rql composed
path at N=2^20 — R sweep x cb tuning, plus accuracy.

mf runs the first log2(R) stages as one R-point DFT matmul + twiddle
grid (ops/pallas_fft.py::dft_funnel_matrices); larger R moves more
levels onto the MXU and shrinks the tile kernel's VPU stage count, at
R^2-growing matmul flops.

Round-4 update: the round-3 configs (cb = 2^12..2^13) OOM'd scoped
VMEM on hardware; after the separable-twiddle fix the lowerable shapes
are bounded by _mf_vmem_bytes (~22 block-planes of stack + io), so the
sweep now covers the feasible region: R=128 cb<=1024, R=64 cb<=2048.
Measured (round 4): mf best 0.149 ms / 706 GF (R=128 cb=1024 tail=128)
vs rql 0.103 ms / 1017 GF — the VMEM-forced 1 MB blocks cap mf's
pipeline, so rql keeps the headline.
"""

import sys

import numpy as np

import jax
import jax.numpy as jnp

from cs87project_msolano2_tpu.ops.pallas_fft import (
    fft_pi_layout_pallas_mf,
    fft_pi_layout_pallas_rql,
)
from cs87project_msolano2_tpu.utils.timing import loop_slope_ms

N = 1 << 20
K1, K2, REPS = 64, 1024, 5


def gf(ms):
    return 5.0 * N * np.log2(N) / (ms * 1e-3) / 1e9


def main():
    key = jax.random.PRNGKey(0)
    xr = jax.random.normal(key, (N,), jnp.float32)
    xi = jax.random.normal(jax.random.fold_in(key, 1), (N,), jnp.float32)
    inv = np.float32(1.0 / np.sqrt(N))

    def rql(c, tile, cb, tail):
        yr, yi = fft_pi_layout_pallas_rql(c[0], c[1], tile=tile, cb=cb,
                                          tail=tail)
        return yr * inv, yi * inv

    def mf(c, R, cb, tail):
        yr, yi = fft_pi_layout_pallas_mf(c[0], c[1], R=R, cb=cb, tail=tail)
        return yr * inv, yi * inv

    cases = [
        ("rql t16 cb13 tail256", lambda c: rql(c, 1 << 16, 1 << 13, 256)),
        ("mf R128 cb10 tail128", lambda c: mf(c, 128, 1 << 10, 128)),
        ("mf R128 cb10 tail256", lambda c: mf(c, 128, 1 << 10, 256)),
        ("mf R64  cb11 tail128", lambda c: mf(c, 64, 1 << 11, 128)),
        ("mf R64  cb11 tail256", lambda c: mf(c, 64, 1 << 11, 256)),
    ]
    for rnd in range(3):
        for name, body in cases:
            try:
                ms = loop_slope_ms(body, (xr, xi), k1=K1, k2=K2, reps=REPS,
                                   min_delta_ms=100.0)
                print(f"[{rnd}] {name}: {ms:.4f} ms  ({gf(ms):.0f} GF)",
                      flush=True)
            except Exception as e:
                from cs87project_msolano2_tpu.resilience import classify

                print(f"[{rnd}] {name}: FAILED {classify(e).value} "
                      f"{type(e).__name__}: {str(e)[:100]}", flush=True)

    # accuracy at bench shape (fetches — last)
    rng = np.random.default_rng(0)
    hxr = rng.standard_normal(N).astype(np.float32)
    hxi = rng.standard_normal(N).astype(np.float32)
    ref = np.fft.fft(hxr.astype(np.complex128) + 1j * hxi)
    from cs87project_msolano2_tpu.ops.bits import bit_reverse_indices
    idx = bit_reverse_indices(N)
    scale = np.max(np.abs(ref))
    for R in (64, 128):
        # one-shot accuracy call per R (each R is a distinct program
        # traced exactly once, nothing to reuse across iterations)
        yr, yi = jax.jit(  # pifft: noqa[PIF202]: one jit per radix config is deliberate — the sweep compares compiled programs, not cache hits
            lambda a, b, r=R: fft_pi_layout_pallas_mf(
                a, b, R=r, tail=256)  # cb=None: auto-picked feasible block
        )(hxr, hxi)
        y = np.asarray(yr).astype(np.complex128) + 1j * np.asarray(yi)
        err = np.max(np.abs(y[idx] - ref)) / scale
        print(f"mf R={R}: rel_err {err:.2e}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
