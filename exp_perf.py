"""Round-3 perf experiments, part 5: the rql composed path vs pallas2,
high-precision slope.  Timing first, fetches last."""

import sys

import numpy as np

import jax
import jax.numpy as jnp

from cs87project_msolano2_tpu.ops.pallas_fft import (
    fft_pi_layout_pallas2,
    fft_pi_layout_pallas_rql,
)
from cs87project_msolano2_tpu.utils.timing import loop_slope_ms

N = 1 << 20
K1, K2, REPS = 64, 2048, 5


def gf(ms):
    return 5.0 * N * np.log2(N) / (ms * 1e-3) / 1e9


def main():
    # XLA FFT availability probe (compile-only shapes, tiny)
    try:
        x = jnp.asarray(np.ones(1024, np.complex64))
        _ = jax.jit(jnp.fft.fft)(x)
        print("jnp.fft.fft: compiles on this backend", flush=True)
    except Exception as e:
        print(f"jnp.fft.fft: UNAVAILABLE ({type(e).__name__})", flush=True)

    key = jax.random.PRNGKey(0)
    xr = jax.random.normal(key, (N,), jnp.float32)
    xi = jax.random.normal(jax.random.fold_in(key, 1), (N,), jnp.float32)
    inv = np.float32(1.0 / np.sqrt(N))

    def rql(c, tile, cb):
        yr, yi = fft_pi_layout_pallas_rql(c[0], c[1], tile=tile, cb=cb)
        return yr * inv, yi * inv

    def p2(c, tile, cb):
        yr, yi = fft_pi_layout_pallas2(c[0], c[1], tile=tile, cb=cb,
                                       separable=True)
        return yr * inv, yi * inv

    cases = [
        ("rql t16 cb13", lambda c: rql(c, 1 << 16, 1 << 13)),
        ("rql t17 cb14", lambda c: rql(c, 1 << 17, 1 << 14)),
        ("rql t16 cb14", lambda c: rql(c, 1 << 16, 1 << 14)),
        ("p2  t16 cb13", lambda c: p2(c, 1 << 16, 1 << 13)),
        ("rql t18 cb14", lambda c: rql(c, 1 << 18, 1 << 14)),
    ]
    for rnd in range(2):
        for name, body in cases:
            try:
                ms = loop_slope_ms(body, (xr, xi), k1=K1, k2=K2, reps=REPS,
                                   min_delta_ms=150.0)
                print(f"[{rnd}] {name}: {ms:.4f} ms  ({gf(ms):.0f} GF)",
                      flush=True)
            except Exception as e:
                print(f"[{rnd}] {name}: FAILED {type(e).__name__}", flush=True)

    # correctness at bench shape (fetch — last)
    rng = np.random.default_rng(0)
    hxr = rng.standard_normal(N).astype(np.float32)
    hxi = rng.standard_normal(N).astype(np.float32)
    ref = np.fft.fft(hxr.astype(np.complex128) + 1j * hxi)
    from cs87project_msolano2_tpu.ops.bits import bit_reverse_indices
    idx = bit_reverse_indices(N)
    for tile, cb in ((1 << 16, 1 << 13), (1 << 17, 1 << 14)):
        yr, yi = jax.jit(
            lambda a, b, t=tile, c=cb: fft_pi_layout_pallas_rql(
                a, b, tile=t, cb=c)
        )(hxr, hxi)
        y = np.asarray(yr).astype(np.complex128) + 1j * np.asarray(yi)
        err = np.max(np.abs(y[idx] - ref)) / np.max(np.abs(ref))
        print(f"rql t{int(np.log2(tile))}: rel_err {err:.2e}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
