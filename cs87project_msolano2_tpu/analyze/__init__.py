"""``analyze/`` — the statistical verification layer as a package
(docs/ANALYSIS.md).

The reference's capstone (SURVEY items 5-6) is hypothesis testing that
measured runtimes fit the paper's complexity law
Theta(n(p-1)/p) + Theta((n/p) log2(n/p)) plus fitted speedup figures.
This package re-expresses that discipline over every measurement
artifact the framework produces — harness TSVs, BENCH_r\\*.json round
records, and the obs event/span JSONL — and turns the BENCH trajectory
into an *enforced invariant*: ``pifft analyze gate`` fails CI with a
named metric and a p-value on a statistically significant throughput
regression, instead of a human noticing a smaller number in a JSON
tail.

Modules:

* :mod:`.lawfit` — the two-coefficient zero-intercept law fit, latency
  floor, significance + per-cell prediction gate (the single source of
  truth ``analysis/analyze_results.py`` now shims), extended with
  confidence intervals and per-cell residual reporting.
* :mod:`.loader` — one typed sample table over all three measurement
  sources, each round/stream stamped with an environment fingerprint so
  only comparable rounds are ever compared.
* :mod:`.phases` — funnel/tube phase attribution computed directly from
  nested obs span durations (spans as a first-class measurement source,
  docs/OBSERVABILITY.md), feeding the same two-law fit as TSV columns.
* :mod:`.regress` — the nonparametric regression detector (Mann-Whitney
  over replications, calibrated scalar fallback), change-point summary,
  and the committed perf-baseline gate.
* :mod:`.records` — the schema'd record emission helpers bench/harness
  metric output goes through (check rule PIF109).
* :mod:`.cli` — ``pifft analyze {fit, report, gate}``.
"""

from .lawfit import (  # noqa: F401
    analyze,
    analyze_table,
    fit_laws,
    laws,
    model_for,
    prediction_gate,
    zero_intercept_fit,
)
from .loader import Fingerprint, SampleTable, load_bench_round  # noqa: F401
from .regress import detect_regressions, gate_rounds  # noqa: F401
