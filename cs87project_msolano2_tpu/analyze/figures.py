"""Paper-figure rendering (A3 parity): the reference's
``analyze-results-full.R`` renders the publication figures — speedup vs
p with one curve per n, and per-stage time shares — from the large
committed datasets.  This module is the single source of truth the
standalone ``analysis/analyze_results_full.py`` script now shims.

:func:`figure` produces the same two-panel figure per dataset, all
n-values overlaid, plus :func:`summary`'s text block, from our TSV
contract.  Figures are best-effort: a machine without matplotlib gets
the summary and a notice, never a crash (the reference's R -> awk
fallback philosophy).
"""

from __future__ import annotations

import os
import sys

import numpy as np

from .lawfit import fit_laws, load_tsv, model_for, zero_intercept_fit

__all__ = ["figure", "summary"]


def figure(path: str, outdir: str):
    try:
        import matplotlib

        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
    except Exception as e:
        print(f"# matplotlib unavailable, no figures: {e}", file=sys.stderr)
        return None

    data, _ = load_tsv(path)
    n, p, total, funnel, tube = data.T
    stem = os.path.splitext(os.path.basename(path))[0]

    fig, (ax1, ax2) = plt.subplots(1, 2, figsize=(10, 4))
    for nn in sorted(set(n.astype(int))):
        sel1 = (n == nn) & (p == 1)
        if not sel1.any():
            continue
        t1 = float(np.mean(total[sel1]))
        ps = np.array(sorted(set(p[n == nn].astype(int))))
        emp = np.array([t1 / float(np.mean(total[(n == nn) & (p == pp)]))
                        for pp in ps])
        ax1.plot(ps, emp, "o-", label=f"n=2^{int(np.log2(nn))}")
    ax1.set_xscale("log", base=2)
    ax1.set_xlabel("processors p")
    ax1.set_ylabel("speedup over p=1")
    ax1.set_title("empirical speedup")
    ax1.legend(fontsize=7)

    # per-stage share of total at each p (aggregated over n)
    ps = np.array(sorted(set(p.astype(int))))
    fshare, tshare = [], []
    for pp in ps:
        sel = p == pp
        tot = float(np.sum(funnel[sel]) + np.sum(tube[sel]))
        fshare.append(float(np.sum(funnel[sel])) / tot if tot else 0.0)
        tshare.append(float(np.sum(tube[sel])) / tot if tot else 0.0)
    xs = [str(v) for v in ps]
    ax2.bar(xs, fshare, label="funnel share")
    ax2.bar(xs, tshare, bottom=fshare, label="tube share")
    ax2.set_xlabel("processors p")
    ax2.set_ylabel("share of per-processor time")
    ax2.set_title("phase breakdown (funnel grows with p, as the law says)")
    ax2.legend(fontsize=8)

    fig.suptitle(stem)
    fig.tight_layout()
    out = os.path.join(outdir, f"{stem}-figures.pdf")
    fig.savefig(out)
    print(f"# wrote {out}", file=sys.stderr)
    return out


def summary(path: str) -> None:
    data, _ = load_tsv(path)
    n, p, total, funnel, tube = data.T
    model = model_for(path)
    # fit_laws: per-COLUMN regressors (serialized is hybrid — the phase
    # columns are processor-0 timers, see lawfit.fit_laws)
    _, funnel_law, tube_law = fit_laws(n, p, model)
    print(f"== {os.path.basename(path)} (law model: {model}) ==")
    for name, y, x in (("funnel", funnel, funnel_law),
                       ("tube", tube, tube_law)):
        beta, r2, t, a, df = zero_intercept_fit(x, y)
        print(f"  {name}: beta={beta:.3e} R^2={r2:.4f} t={t:.1f} alpha={a:.2e}")
