"""Schema'd metric-record emission (the PIF109 sanctioned layer).

``bench.py`` and the harness print ONE JSON line per run — the line the
driver commits as ``BENCH_r*.json`` and ``pifft analyze gate`` later
fits laws over.  An ad-hoc ``json.dumps`` at the emission site can ship
a record missing the ``metric``/``value``/``unit`` envelope or the
environment fingerprint, and the gate then either refuses the round or
— worse — compares a smoke round against hardware.  Every metric
emission therefore goes through this module (check rule PIF109,
docs/CHECKS.md): :func:`emit_record` validates the envelope, stamps
nothing silently, and is the ONE ``json.dumps`` call site on the
bench/harness metric path.

The **environment fingerprint** (:func:`env_fingerprint`) is the
comparability key the regression gate groups rounds by: accelerator
platform, device kind, the smoke flag, and the git revision when one
is resolvable.  Two rounds whose fingerprints are incompatible
(:meth:`.loader.Fingerprint.compatible`) are never compared — a CPU
smoke round "regressing" from a TPU hardware round is not a verdict,
it is a category error.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
from typing import Optional

__all__ = ["dump_json", "dump_record", "emit_record", "env_fingerprint",
           "validate_record"]

#: bump when the record envelope changes incompatibly
RECORD_SCHEMA_VERSION = 1

_REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))


def _git_rev() -> Optional[str]:
    """Short git revision of the repo this package lives in, or None
    (detached artifact dirs, sdist installs, missing git)."""
    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"], cwd=_REPO_ROOT,
            capture_output=True, text=True, timeout=5)
    except (OSError, subprocess.SubprocessError):
        return None
    rev = out.stdout.strip()
    return rev if out.returncode == 0 and rev else None


def env_fingerprint(smoke: bool = False,
                    device_kind: Optional[str] = None) -> dict:
    """The environment fingerprint stamped on every emitted round
    record (and mirrored as an ``env`` obs event by armed runs):
    ``{"platform", "device_kind", "smoke", "git_rev"}``.  ``platform``
    is the jax backend actually serving this process (axon/tpu/cpu/...)
    or None where jax is absent; ``git_rev`` is best-effort."""
    platform = None
    try:
        import jax

        platform = str(jax.default_backend())
    except (ImportError, RuntimeError):
        # jax absent or no backend initializable: the fingerprint is
        # still valid, with the platform honestly unknown
        platform = None
    fp = {"platform": platform, "device_kind": device_kind,
          "smoke": bool(smoke)}
    rev = _git_rev()
    if rev:
        fp["git_rev"] = rev
    return fp


def validate_record(rec) -> list:
    """Problems with a metric record's envelope (empty = valid): it
    must be a JSON-safe object carrying ``metric`` (str), ``value``
    (number or None — a failed headline is explicit, never absent) and
    ``unit`` (str)."""
    problems = []
    if not isinstance(rec, dict):
        return [f"record is {type(rec).__name__}, not an object"]
    if not isinstance(rec.get("metric"), str) or not rec.get("metric"):
        problems.append("missing/empty 'metric' name")
    if "value" not in rec:
        problems.append("missing 'value' (a failed measurement is an "
                        "explicit null, not an absent key)")
    elif (rec["value"] is not None
          and not isinstance(rec["value"], (int, float))) \
            or isinstance(rec["value"], bool):
        problems.append(f"'value' is {type(rec['value']).__name__}, "
                        "not a number")
    if not isinstance(rec.get("unit"), str) or not rec.get("unit"):
        problems.append("missing/empty 'unit'")
    env = rec.get("env")
    if env is not None:
        if not isinstance(env, dict):
            problems.append(f"'env' is {type(env).__name__}, not a "
                            "fingerprint object")
        elif "smoke" not in env:
            problems.append("'env' fingerprint lacks the 'smoke' flag")
    try:
        json.dumps(rec)
    except (TypeError, ValueError) as e:
        problems.append(f"record is not JSON-serializable: {e}")
    return problems


def dump_record(rec: dict) -> str:
    """The validated one-line JSON form of a metric record; raises
    ``ValueError`` naming every envelope problem rather than emitting a
    record the gate would refuse later."""
    problems = validate_record(rec)
    if problems:
        raise ValueError("refusing to emit a malformed metric record: "
                         + "; ".join(problems))
    return json.dumps(rec)


def emit_record(rec: dict, stream=None) -> dict:
    """Validate and print one metric record (the bench/harness emission
    path); returns the record."""
    print(dump_record(rec), file=stream if stream is not None
          else sys.stdout)
    return rec


def _json_default(o):
    """numpy scalars (betas, p-values) degrade to floats, anything
    else to its repr — CLI output must never crash on a report field."""
    try:
        return float(o)
    except (TypeError, ValueError):
        return str(o)


def dump_json(obj, indent: int = 1) -> str:
    """Pretty JSON for analyze CLI output (reports, gate verdicts) —
    kept here so the analyze/bench/harness surface has exactly one
    serialization module (PIF109)."""
    return json.dumps(obj, indent=indent, sort_keys=True,
                      default=_json_default)
