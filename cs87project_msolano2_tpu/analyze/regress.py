"""The regression detector: nonparametric comparison of each metric
between successive comparable BENCH rounds, a change-point summary over
the whole trajectory, and the committed perf-baseline gate
(docs/ANALYSIS.md).

**What counts as a regression.**  Every metric has a *better*
direction inferred from its name (GFLOP/s and ``vs_*`` speedups go up;
``*_ms`` latencies and SLO percentiles go down; anything unclassifiable
is skipped, never guessed).  A candidate regression is a step between
two *fingerprint-compatible* successive rounds that moves in the worse
direction; it becomes significant only when BOTH hold:

* the relative change exceeds the practical threshold (default 10% —
  below that the verdict would be about measurement noise, not the
  code), and
* the statistical test rejects "no change" at ``alpha``:

  - **replicated metrics** (a round recording a list of values per
    metric) get a one-sided Mann-Whitney U test — rank-based, no
    normality assumption, exactly the "bootstrap or Mann-Whitney over
    replications" discipline the reference's R scripts apply to their
    replication columns;
  - **scalar metrics** (the committed BENCH_r01..r06 records carry one
    value per metric) get a calibrated z-score: the trajectory's own
    step-to-step |log change| distribution (median/MAD, robust to the
    very outlier under test) estimates the round-to-round noise scale,
    with a floor so a 2-round history cannot claim perfect precision.
    The resulting p-value is honest about what a single number can
    support — a noisy trajectory widens its own tolerance instead of
    producing bogus verdicts.

**The gate.**  ``pifft analyze gate`` compares detected regressions
against the committed ``perf-baseline.json`` exactly as ``pifft
check`` compares findings against ``check-baseline.json``: accepted
(documented) regressions pass, NEW ones fail CI with the metric name,
the round pair, and the p-value; baseline entries no longer observed
are reported as fixed so the file can shrink.
"""

from __future__ import annotations

import dataclasses
import json
import math
from typing import Iterable, Optional

import numpy as np

from .loader import BenchRound

__all__ = ["LiveVerdict", "Regression", "GateResult", "change_points",
           "compare_pair", "detect_regressions", "direction_of",
           "gate_rounds", "live_improved", "live_regressed",
           "load_perf_baseline", "mann_whitney", "write_perf_baseline"]

#: default practical-significance threshold (relative change in the
#: worse direction below this is never flagged, whatever its p-value)
DEFAULT_THRESHOLD = 0.10

#: default statistical-significance level
DEFAULT_ALPHA = 0.05

#: the scalar calibration can never claim the trajectory is quieter
#: than this (log-change units): a short or lucky history must not
#: make a 6% wobble "significant"
SIGMA_FLOOR = 0.05

#: minimum median relative change for a replicated-metric flag — a
#: Mann-Whitney p below alpha with a sub-noise median shift is a
#: distribution-shape verdict, not a throughput regression
REPLICATED_MIN_CHANGE = 0.05


def direction_of(metric: str) -> Optional[str]:
    """"higher" (is better) / "lower" / None (not a perf metric —
    plan descriptions, counts, round bookkeeping — skipped)."""
    name = metric.lower()
    if "gflops" in name:
        return "higher"
    if name.startswith("vs_") or "_vs_" in name or name.endswith("_vs_xla"):
        return "higher"
    if "roofline" in name or "util" in name:
        return "higher"
    if name.endswith("_ms") or "_ms_" in name or "p99" in name \
            or "p50" in name:
        return "lower"
    return None


def _norm_sf(z: float) -> float:
    """P(Z > z), standard normal."""
    return 0.5 * math.erfc(z / math.sqrt(2.0))


def mann_whitney(a, b) -> tuple:
    """One-sided Mann-Whitney U: (u_statistic, p) for H1 "values in
    ``b`` tend to be SMALLER than values in ``a``" (caller orients the
    worse direction).  Normal approximation with tie correction —
    adequate at bench replication depths (>= ~5 per side), and scipy-
    free so the gate runs anywhere the loader does."""
    a = np.asarray(a, dtype=float)
    b = np.asarray(b, dtype=float)
    na, nb = len(a), len(b)
    if na == 0 or nb == 0:
        return 0.0, 1.0
    pooled = np.concatenate([a, b])
    order = np.argsort(pooled, kind="mergesort")
    ranks = np.empty(len(pooled))
    # midranks for ties
    sorted_vals = pooled[order]
    i = 0
    while i < len(sorted_vals):
        j = i
        while j + 1 < len(sorted_vals) and sorted_vals[j + 1] == \
                sorted_vals[i]:
            j += 1
        ranks[order[i:j + 1]] = 0.5 * (i + j) + 1.0
        i = j + 1
    ra = float(np.sum(ranks[:na]))
    u_a = ra - na * (na + 1) / 2.0        # large when a ranks high
    mean_u = na * nb / 2.0
    # tie-corrected variance
    _, counts = np.unique(pooled, return_counts=True)
    tie_term = float(np.sum(counts**3 - counts))
    nn = na + nb
    var_u = na * nb / 12.0 * ((nn + 1) - tie_term / (nn * (nn - 1))) \
        if nn > 1 else 0.0
    if var_u <= 0:
        return u_a, 1.0
    # H1: b smaller than a  <=>  a's ranks high  <=>  u_a large
    z = (u_a - mean_u - 0.5) / math.sqrt(var_u)
    return u_a, _norm_sf(z)


@dataclasses.dataclass
class LiveVerdict:
    """One live-population comparison (the fleet loop's drift and
    canary gates — docs/FLEET.md).  ``significant`` applies the same
    two-part contract as the replicated bench gate: the one-sided
    Mann-Whitney p must clear ``alpha`` AND the median shift must
    clear the practical floor, so a distribution-shape wobble never
    drives a promotion or a rollback."""

    significant: bool
    p_value: float
    med_change: float         # median(b)/median(a) - 1, signed
    test: str                 # "mann-whitney" | "insufficient"
    samples: tuple            # (len(a), len(b))

    def to_json(self) -> dict:
        return {"significant": self.significant,
                "p_value": round(self.p_value, 6),
                "med_change": round(self.med_change, 6),
                "test": self.test,
                "samples": list(self.samples)}


def _live_compare(a, b, h1: str, alpha: float,
                  min_change: float) -> LiveVerdict:
    a = [float(v) for v in a]
    b = [float(v) for v in b]
    med_a = float(np.median(a)) if a else 0.0
    med_b = float(np.median(b)) if b else 0.0
    med_change = (med_b / med_a - 1.0) if med_a > 0 else 0.0
    # the same >= 5-per-side floor as compare_pair: below it the normal
    # approximation is anticonservative, so the verdict is "not enough
    # evidence", never a guess
    if len(a) < 5 or len(b) < 5:
        return LiveVerdict(False, 1.0, med_change, "insufficient",
                           (len(a), len(b)))
    if h1 == "larger":          # H1: b larger than a (regression)
        _, p = mann_whitney([-v for v in a], [-v for v in b])
        shifted = med_change > min_change
    else:                       # H1: b smaller than a (improvement)
        _, p = mann_whitney(a, b)
        shifted = -med_change > min_change
    return LiveVerdict(bool(p < alpha and shifted), float(p),
                       med_change, "mann-whitney", (len(a), len(b)))


def live_regressed(baseline, live, alpha: float = DEFAULT_ALPHA,
                   min_change: float = REPLICATED_MIN_CHANGE) \
        -> LiveVerdict:
    """Has this lower-better LIVE latency population drifted worse
    than its healthy baseline?  One-sided Mann-Whitney, H1 = "live
    tends larger" — the fleet drift detector's calibrated verdict
    (the same orientation compare_pair applies to lower-better bench
    metrics), never an ad-hoc threshold."""
    return _live_compare(baseline, live, "larger", alpha, min_change)


def live_improved(live, candidate, alpha: float = DEFAULT_ALPHA,
                  min_change: float = REPLICATED_MIN_CHANGE) \
        -> LiveVerdict:
    """Does the canary candidate beat the live population?  One-sided
    Mann-Whitney, H1 = "candidate tends smaller" — the promotion gate:
    a winner is promoted into the shared plan cache only on a
    significant verdict here (docs/FLEET.md)."""
    return _live_compare(live, candidate, "smaller", alpha, min_change)


@dataclasses.dataclass
class Regression:
    """One flagged (or candidate) worse-direction step."""

    metric: str
    from_round: int
    to_round: int
    prev: float
    cur: float
    change: float             # relative, signed in raw units
    p_value: float
    test: str                 # "mann-whitney" | "scalar-z"
    significant: bool
    direction: str

    def key(self) -> tuple:
        """Baseline identity, like a check finding's (rule, path,
        message) key: metric + the round pair."""
        return (self.metric, self.from_round, self.to_round)

    def describe(self) -> str:
        arrow = f"{self.prev:g} -> {self.cur:g}"
        return (f"{self.metric}: r{self.from_round:02d}->"
                f"r{self.to_round:02d} {arrow} "
                f"({self.change * 100:+.1f}%, worse; p={self.p_value:.3g},"
                f" {self.test})")


def _rep_mean(val) -> float:
    return float(np.mean(val)) if isinstance(val, list) else float(val)


def _trajectory_sigma(rounds: list, exclude: Optional[tuple] = None) \
        -> float:
    """The scalar-comparison noise scale: robust spread of every
    |log change| between successive comparable rounds, over every
    directional metric — the trajectory's own empirical round-to-round
    volatility.  ``exclude`` drops one (from_index, to_index) pair:
    the step under test must not calibrate its own tolerance, or a
    large injected regression widens sigma until it excuses itself
    (leave-one-pair-out)."""
    changes = []
    for prev, cur in _comparable_pairs(rounds):
        if exclude is not None and (prev.index, cur.index) == exclude:
            continue
        for metric in set(prev.metrics) & set(cur.metrics):
            if direction_of(metric) is None:
                continue
            a, b = _rep_mean(prev.metrics[metric]), \
                _rep_mean(cur.metrics[metric])
            if a > 0 and b > 0:
                changes.append(abs(math.log(b / a)))
    if len(changes) < 4:
        return SIGMA_FLOOR
    # the MAD-from-zero estimator: under X ~ N(0, sigma),
    # median(|X|) = 0.6745 sigma.  Genuine improvements in the history
    # inflate the estimate — a volatile trajectory honestly widens its
    # own tolerance rather than producing confident verdicts single
    # numbers cannot support.
    return max(1.4826 * float(np.median(np.asarray(changes))),
               SIGMA_FLOOR)


def _comparable_pairs(rounds: list) -> list:
    out = []
    for prev, cur in zip(rounds, rounds[1:]):
        ok, _ = prev.fingerprint.compatible(cur.fingerprint)
        if ok:
            out.append((prev, cur))
    return out


def compare_pair(prev: BenchRound, cur: BenchRound, sigma: float,
                 alpha: float = DEFAULT_ALPHA,
                 threshold: float = DEFAULT_THRESHOLD) -> list:
    """Every worse-direction step between two comparable rounds (the
    caller has already checked fingerprints), each carrying its
    p-value; ``significant`` is set per the module contract."""
    out = []
    for metric in sorted(set(prev.metrics) & set(cur.metrics)):
        worse = direction_of(metric)
        if worse is None:
            continue
        pv, cv = prev.metrics[metric], cur.metrics[metric]
        a, b = _rep_mean(pv), _rep_mean(cv)
        if a <= 0 or b <= 0:
            continue
        change = (b - a) / a
        regressed = change < 0 if worse == "higher" else change > 0
        if not regressed:
            continue
        # >= 5 per side: below that the normal approximation is
        # anticonservative (3v3 complete separation approximates to
        # p=0.04 where the exact test's floor is 1/C(6,3)=0.05 — a
        # verdict the test cannot actually produce); thinner
        # replication falls back to the calibrated scalar path
        replicated = isinstance(pv, list) and isinstance(cv, list) \
            and len(pv) >= 5 and len(cv) >= 5
        if replicated:
            # orient so H1 = "cur is worse": for higher-better metrics
            # worse means cur smaller than prev
            if worse == "higher":
                _, p = mann_whitney(pv, cv)
            else:
                _, p = mann_whitney([-v for v in pv], [-v for v in cv])
            med_change = abs(float(np.median(cv)) / float(np.median(pv))
                             - 1.0)
            significant = p < alpha and med_change > REPLICATED_MIN_CHANGE
            test = "mann-whitney"
        else:
            z = abs(math.log(b / a)) / max(sigma, 1e-9)
            p = _norm_sf(z)
            significant = p < alpha and abs(change) > threshold
            test = "scalar-z"
        out.append(Regression(
            metric=metric, from_round=prev.index, to_round=cur.index,
            prev=round(a, 6), cur=round(b, 6), change=round(change, 6),
            p_value=float(p), test=test, significant=significant,
            direction=worse))
    return out


def detect_regressions(rounds: list, alpha: float = DEFAULT_ALPHA,
                       threshold: float = DEFAULT_THRESHOLD) -> tuple:
    """(significant_regressions, all_candidates, skipped_pairs) over a
    trajectory of rounds (trajectory order).  ``skipped_pairs`` names
    every successive pair the fingerprint check refused, with the
    reason — the gate REPORTS a cross-environment step, it never
    compares across one."""
    skipped = []
    for prev, cur in zip(rounds, rounds[1:]):
        ok, reason = prev.fingerprint.compatible(cur.fingerprint)
        if not ok:
            skipped.append({
                "from_round": prev.index, "to_round": cur.index,
                "reason": reason,
                "from": prev.fingerprint.describe(),
                "to": cur.fingerprint.describe(),
            })
    candidates = []
    for prev, cur in _comparable_pairs(rounds):
        sigma = _trajectory_sigma(rounds,
                                  exclude=(prev.index, cur.index))
        candidates.extend(compare_pair(prev, cur, sigma, alpha, threshold))
    return [r for r in candidates if r.significant], candidates, skipped


def change_points(rounds: list) -> dict:
    """Per-metric largest |log change| step across the comparable
    trajectory — the "where did this metric's story change" summary
    (a single-change-point estimator; improvements count too, so the
    fourstep landing shows up next to any regression)."""
    out: dict = {}
    for prev, cur in _comparable_pairs(rounds):
        for metric in set(prev.metrics) & set(cur.metrics):
            if direction_of(metric) is None:
                continue
            a, b = _rep_mean(prev.metrics[metric]), \
                _rep_mean(cur.metrics[metric])
            if a <= 0 or b <= 0:
                continue
            step = abs(math.log(b / a))
            best = out.get(metric)
            if best is None or step > best["abs_log_change"]:
                out[metric] = {
                    "from_round": prev.index, "to_round": cur.index,
                    "prev": round(a, 6), "cur": round(b, 6),
                    "change": round((b - a) / a, 6),
                    "abs_log_change": round(step, 6),
                }
    return out


# ------------------------------------------------------------- baseline


def load_perf_baseline(path: str) -> list:
    """Accepted-regression keys from a committed perf baseline.
    Raises ValueError on a structurally wrong document (the CLI turns
    that into a usage error, like the check baseline loader)."""
    with open(path, encoding="utf-8") as fh:
        doc = json.load(fh)
    if not isinstance(doc, dict) or \
            not isinstance(doc.get("accepted", []), list):
        raise ValueError("perf baseline is not an {accepted: [...]} "
                         "document")
    out = []
    for rec in doc.get("accepted", []):
        out.append((str(rec["metric"]), int(rec["from_round"]),
                    int(rec["to_round"])))
    return out


def write_perf_baseline(path: str, regressions: Iterable[Regression],
                        note: str = "") -> str:
    doc = {
        "schema": 1,
        "note": note or ("accepted (documented) perf regressions: the "
                         "gate fails only on regressions NOT listed "
                         "here — the perf twin of check-baseline.json"),
        "accepted": [
            {"metric": r.metric, "from_round": r.from_round,
             "to_round": r.to_round,
             "change": r.change, "p_value": round(r.p_value, 6)}
            for r in regressions
        ],
    }
    from .records import dump_json

    with open(path, "w", encoding="utf-8") as fh:
        fh.write(dump_json(doc) + "\n")
    return path


@dataclasses.dataclass
class GateResult:
    """The gate verdict: ``ok`` iff no NEW significant regression."""

    ok: bool
    new: list                 # significant, not in baseline
    accepted: list            # significant, grandfathered
    fixed: list               # baseline keys no longer observed
    candidates: list          # every worse-direction step (diagnostics)
    skipped_pairs: list
    rounds: list

    def to_json(self) -> dict:
        return {
            "ok": self.ok,
            "new": [dataclasses.asdict(r) for r in self.new],
            "accepted": [dataclasses.asdict(r) for r in self.accepted],
            "fixed": [{"metric": m, "from_round": a, "to_round": b}
                      for (m, a, b) in self.fixed],
            "candidates": [dataclasses.asdict(r)
                           for r in self.candidates],
            "skipped_pairs": self.skipped_pairs,
            "rounds": [
                {"index": r.index, "path": r.path,
                 "fingerprint": r.fingerprint.describe(),
                 "metrics": len(r.metrics)}
                for r in self.rounds
            ],
            "change_points": change_points(self.rounds),
        }


def gate_rounds(rounds: list, baseline: Optional[list] = None,
                alpha: float = DEFAULT_ALPHA,
                threshold: float = DEFAULT_THRESHOLD) -> GateResult:
    """The CI gate: detect, split against the baseline, verdict."""
    significant, candidates, skipped = detect_regressions(
        rounds, alpha, threshold)
    accepted_keys = set(baseline or [])
    new = [r for r in significant if r.key() not in accepted_keys]
    accepted = [r for r in significant if r.key() in accepted_keys]
    observed = {r.key() for r in significant}
    fixed = sorted(k for k in accepted_keys if k not in observed)
    return GateResult(ok=not new, new=new, accepted=accepted,
                      fixed=fixed, candidates=candidates,
                      skipped_pairs=skipped, rounds=rounds)
