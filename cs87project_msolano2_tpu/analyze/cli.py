"""``pifft analyze {fit, report, gate}`` (docs/ANALYSIS.md).

* ``fit`` — the law fit over harness TSVs and/or the phase spans of an
  obs event stream: two-coefficient zero-intercept regression,
  significance + per-cell prediction gate, confidence intervals,
  residuals, optional matplotlib speedup/residual figures.  Exit 0 iff
  every fitted law holds (``--allow-fail`` inverts per file, keeping
  documented negative results falsifying).
* ``report`` — the loader inventory: samples per source, rounds with
  environment fingerprints, span-vs-TSV phase shares, and the
  change-point summary over the BENCH trajectory.
* ``gate`` — the statistical perf-regression gate over BENCH_r*.json
  (docs/ANALYSIS.md: Mann-Whitney over replications, calibrated
  scalar fallback, fingerprint-gated comparability, committed
  perf-baseline).  Exit 0 = no new significant regression; 1 = at
  least one, each named with its p-value; 2 = usage.
"""

from __future__ import annotations

import argparse
import sys

from . import lawfit, phases, regress
from .loader import build_table, load_bench_rounds, tail_attribution
from .records import dump_json

__all__ = ["analyze_main"]


def _fit_main(args) -> int:
    reports = {}
    ok = True
    for path in args.tsv:
        try:
            rep = lawfit.analyze(path, args.alpha, args.plots,
                                 args.model, verbose=not args.json)
        except SystemExit as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        reports[path] = rep
        expected_fail = any(sub in path for sub in args.allow_fail)
        holds = bool(rep["total"]["holds"])
        if expected_fail:
            if holds:
                print(f"# {path}: documented law violation PASSED the "
                      "fit — criterion lost its teeth", file=sys.stderr)
                ok = False
        else:
            ok &= holds
    if args.events:
        from ..obs.events import load_events

        try:
            records, dropped = load_events(args.events)
        except OSError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        rows = phases.phase_rows_from_events(records)
        if len(rows) == 0:
            print(f"error: {args.events} carries no paired funnel/tube "
                  "phase spans (arm the run with --events and a phase "
                  "probe — docs/OBSERVABILITY.md)", file=sys.stderr)
            return 2
        model = args.model if args.model != "auto" else "per-processor"
        rep = lawfit.analyze_table(
            rows, model, alpha_level=args.alpha,
            # span durations ride the same dispatch pipeline the TSV
            # timers do: dispatch-piped models keep their floor column
            # (docs/OBSERVABILITY.md promises exactly this)
            has_floor=model in lawfit.FLOOR_MODELS,
            label=f"{args.events} (span-derived)",
            verbose=not args.json)
        if dropped:
            print(f"# {args.events}: {dropped} corrupt line(s) skipped",
                  file=sys.stderr)
        reports[args.events] = rep
        ok &= bool(rep["total"]["holds"])
    if not reports:
        print("error: nothing to fit (give TSVs and/or --events)",
              file=sys.stderr)
        return 2
    if args.json:
        print(dump_json(reports))
    return 0 if ok else 1


def _report_main(args) -> int:
    if not (args.tsv or args.bench or args.events):
        print("error: nothing to report (give TSVs, --bench and/or "
              "--events)", file=sys.stderr)
        return 2
    try:
        table = build_table(tsv_paths=args.tsv, bench_paths=args.bench,
                            events_paths=args.events)
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    doc = table.summary()
    # phase shares per derivation, cross-checkable cell by cell
    shares = {}
    tsv_rows = table.phase_rows("tsv")
    if len(tsv_rows):
        shares["tsv"] = {f"n={n} p={p}": v for (n, p), v in
                         phases.phase_shares_from_rows(tsv_rows).items()}
    obs_rows = table.phase_rows("obs")
    if len(obs_rows):
        shares["obs"] = {f"n={n} p={p}": v for (n, p), v in
                         phases.phase_shares_from_rows(obs_rows).items()}
    if shares:
        doc["phase_shares"] = shares
    if args.events:
        # the trace-derived tail-attribution table (loader.py,
        # docs/ANALYSIS.md): which phase owns the p99, straight from
        # the serve trace plane's span trees
        from ..obs.events import load_events

        tails = {}
        for path in args.events:
            try:
                records, _dropped = load_events(path)
            except OSError:
                continue  # build_table already reported unreadables
            tails.update(tail_attribution(records))
        if tails:
            doc["tail_attribution"] = tails
    if table.rounds:
        doc["change_points"] = regress.change_points(table.rounds)
        _, _, skipped = regress.detect_regressions(table.rounds)
        doc["skipped_pairs"] = skipped
        doc["comparable_pairs"] = (len(table.rounds) - 1 - len(skipped)
                                   if len(table.rounds) > 1 else 0)
    if args.json:
        print(dump_json(doc))
        return 0
    print(f"samples: {doc['samples']} "
          + " ".join(f"{k}={v}" for k, v in
                     sorted(doc["by_source"].items())))
    for rnd in doc["rounds"]:
        print(f"  round r{rnd['index']:02d}  {rnd['path']:<18} "
              f"{rnd['metrics']:>3} metric(s)  [{rnd['fingerprint']}]")
    for pair in doc.get("skipped_pairs", []):
        print(f"  incomparable r{pair['from_round']:02d}->"
              f"r{pair['to_round']:02d}: {pair['reason']}")
    for src, cells in shares.items():
        print(f"phase shares ({src}-derived):")
        for cell, v in cells.items():
            print(f"  {cell:<18} funnel {v['funnel']:.3f}  "
                  f"tube {v['tube']:.3f}  ({v['runs']} run(s))")
    tails = doc.get("tail_attribution") or {}
    if tails:
        print("tail attribution (trace-derived; which phase owns "
              "the p99):")
        for label, row in tails.items():
            print(f"  {label:<30} p50 {row['p50_ms']:.3f} ms  "
                  f"p99 {row['p99_ms']:.3f} ms  owner "
                  f"{row['p99_owner']} "
                  f"(q {row['p99_queue_share']:.2f} / "
                  f"w {row['p99_window_share']:.2f} / "
                  f"c {row['p99_compute_share']:.2f}; "
                  f"{row['requests']} traced)")
    for metric, cp in sorted(doc.get("change_points", {}).items()):
        print(f"change-point {metric}: r{cp['from_round']:02d}->"
              f"r{cp['to_round']:02d} {cp['prev']:g} -> {cp['cur']:g} "
              f"({cp['change'] * 100:+.1f}%)")
    return 0


def _gate_main(args) -> int:
    try:
        rounds = load_bench_rounds(args.bench)
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if len(rounds) < 2:
        print(f"error: a trajectory gate needs >= 2 rounds "
              f"(got {len(rounds)})", file=sys.stderr)
        return 2
    baseline = None
    if args.baseline:
        try:
            baseline = regress.load_perf_baseline(args.baseline)
        except (OSError, ValueError, KeyError) as e:
            print(f"error: unusable perf baseline {args.baseline}: {e}",
                  file=sys.stderr)
            return 2
    result = regress.gate_rounds(rounds, baseline, alpha=args.alpha,
                                 threshold=args.threshold)
    if args.write_baseline:
        path = regress.write_perf_baseline(
            args.write_baseline, result.new + result.accepted)
        print(f"wrote {len(result.new) + len(result.accepted)} accepted "
              f"regression(s) to {path}")
        return 0
    if args.json:
        print(dump_json(result.to_json()))
        return 0 if result.ok else 1
    for rnd in result.rounds:
        print(f"# round r{rnd.index:02d}  "
              f"[{rnd.fingerprint.describe()}]  "
              f"{len(rnd.metrics)} metric(s)")
    for pair in result.skipped_pairs:
        print(f"# skipped r{pair['from_round']:02d}->"
              f"r{pair['to_round']:02d}: incomparable environments "
              f"({pair['reason']})")
    for r in result.accepted:
        print(f"# accepted (baselined): {r.describe()}")
    for key in result.fixed:
        print(f"# fixed: baseline entry {key[0]} "
              f"r{key[1]:02d}->r{key[2]:02d} no longer observed — "
              "shrink the baseline")
    insig = [r for r in result.candidates if not r.significant]
    if insig:
        print(f"# {len(insig)} worse-direction step(s) below "
              "significance (noise-compatible)")
    if result.new:
        for r in result.new:
            print(f"REGRESSION {r.describe()}")
        print(f"analyze gate: {len(result.new)} new significant "
              f"regression(s) — FAIL")
        return 1
    pairs = len(result.rounds) - 1 - len(result.skipped_pairs)
    print(f"analyze gate: ok ({len(result.rounds)} rounds, "
          f"{pairs} comparable pair(s), "
          f"{len(result.candidates)} candidate step(s), 0 new "
          "significant regressions)")
    return 0


def analyze_main(argv) -> int:
    ap = argparse.ArgumentParser(
        prog="cs87project_msolano2_tpu analyze",
        description="statistical verification: law fitting over "
                    "TSV/span measurements, loader inventory, and the "
                    "perf-regression gate over the BENCH trajectory "
                    "(docs/ANALYSIS.md)",
    )
    sub = ap.add_subparsers(dest="action", required=True)

    fit = sub.add_parser("fit", help="fit the complexity laws")
    fit.add_argument("tsv", nargs="*", help="harness TSV file(s)")
    fit.add_argument("--events", default=None, metavar="FILE",
                     help="also fit the funnel/tube phase spans of an "
                          "obs event stream (span-derived table)")
    fit.add_argument("--alpha", type=float, default=0.01)
    fit.add_argument("--model", default="auto",
                     choices=("auto",) + lawfit.MODELS)
    fit.add_argument("--plots", default=None, metavar="DIR",
                     help="write per-n speedup/phase PDF figures")
    fit.add_argument("--allow-fail", action="append", default=[],
                     help="path substring whose total-fit FAILURE is "
                          "expected (documented negative results)")
    fit.add_argument("--json", action="store_true")

    report = sub.add_parser("report", help="loader inventory + phase "
                                           "attribution + change points")
    report.add_argument("tsv", nargs="*", help="harness TSV file(s)")
    report.add_argument("--bench", nargs="*", default=[], metavar="FILE",
                        help="BENCH round record(s)")
    report.add_argument("--events", nargs="*", default=[],
                        metavar="FILE", help="obs event stream(s)")
    report.add_argument("--json", action="store_true")

    gate = sub.add_parser("gate", help="the statistical perf-regression "
                                       "gate over BENCH rounds")
    gate.add_argument("bench", nargs="+", help="BENCH_r*.json trajectory")
    gate.add_argument("--baseline", default=None, metavar="FILE",
                      help="committed perf baseline (accepted "
                           "regressions; the perf twin of "
                           "check-baseline.json)")
    gate.add_argument("--alpha", type=float,
                      default=regress.DEFAULT_ALPHA)
    gate.add_argument("--threshold", type=float,
                      default=regress.DEFAULT_THRESHOLD,
                      help="practical-significance floor (relative "
                           "change in the worse direction)")
    gate.add_argument("--write-baseline", default=None, metavar="FILE",
                      help="record the currently significant "
                           "regressions as accepted and exit 0")
    gate.add_argument("--json", action="store_true")

    args = ap.parse_args(argv)
    if args.action == "fit":
        return _fit_main(args)
    if args.action == "report":
        return _report_main(args)
    return _gate_main(args)
