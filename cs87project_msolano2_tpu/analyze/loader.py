"""The loader layer: one typed sample table over every measurement
artifact the framework produces (docs/ANALYSIS.md).

Three sources, one schema:

* **harness TSVs** — ``n p total_ms funnel_ms tube_ms [DEGRADED]``
  rows (the reference contract) become three phase samples per row;
* **BENCH round records** — the driver-committed ``BENCH_r*.json``
  files (``{"n": round, "cmd", "rc", "tail", "parsed": {...}}``)
  become one :class:`BenchRound` each: every numeric field of
  ``parsed`` is a metric (a list of numbers is a *replicated* metric
  and earns the real Mann-Whitney test in :mod:`.regress`), and the
  round carries an environment :class:`Fingerprint`;
* **obs event streams** — the JSONL a run wrote with ``--events``:
  funnel/tube span durations become phase samples (spans as a
  first-class measurement source, docs/OBSERVABILITY.md — the
  attribution logic lives in :mod:`.phases`), and a ``kind="env"``
  event fingerprints the whole stream.

**Fingerprints** gate comparability: rounds measured on different
platforms, device kinds, or smoke tiers are never compared
(``analyze gate`` reports the skipped pair instead of producing a
bogus verdict).  Committed rounds predating the ``env`` stamp
(BENCH_r01-r06) are backfilled tolerantly: the smoke flag from the
parsed record, the platform from the jax platform banner in the
captured ``tail`` — and any field that cannot be recovered stays
``None``, which :meth:`Fingerprint.compatible` treats as "unknown,
do not refuse on this field alone".
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
from typing import Optional

import numpy as np

__all__ = ["BenchRound", "Fingerprint", "Sample", "SampleTable",
           "load_bench_round", "load_bench_rounds", "load_obs_samples",
           "load_tsv_samples", "build_table", "tail_attribution"]

#: the jax platform banner the relay prints into captured bench tails —
#: the backfill source for pre-``env`` committed rounds
_PLATFORM_BANNER = re.compile(r"Platform '([A-Za-z0-9_]+)' is")


@dataclasses.dataclass(frozen=True)
class Fingerprint:
    """The environment identity of one measurement round/stream."""

    platform: Optional[str] = None
    device_kind: Optional[str] = None
    smoke: bool = False
    git_rev: Optional[str] = None

    @classmethod
    def from_env(cls, env: Optional[dict],
                 smoke: Optional[bool] = None) -> "Fingerprint":
        env = env or {}
        return cls(platform=env.get("platform"),
                   device_kind=env.get("device_kind"),
                   smoke=bool(env.get("smoke", smoke or False)),
                   git_rev=env.get("git_rev"))

    def compatible(self, other: "Fingerprint") -> tuple:
        """(ok, reason): whether metrics measured under ``self`` may be
        compared against ``other``.  The smoke flag always decides
        (it is never unknown); platform/device_kind refuse only when
        BOTH sides are known and differ — a backfilled None means
        "unrecoverable", not "different"."""
        if self.smoke != other.smoke:
            return False, "smoke tier vs hardware tier"
        for field in ("platform", "device_kind"):
            a, b = getattr(self, field), getattr(other, field)
            if a is not None and b is not None and a != b:
                return False, f"{field} {a!r} vs {b!r}"
        return True, ""

    def describe(self) -> str:
        parts = [f"platform={self.platform or '?'}"]
        if self.device_kind:
            parts.append(f"device={self.device_kind}")
        parts.append("smoke" if self.smoke else "hardware")
        if self.git_rev:
            parts.append(f"@{self.git_rev}")
        return " ".join(parts)


@dataclasses.dataclass(frozen=True)
class Sample:
    """One measured value with its full context — the table row every
    source is normalized into.  ``domain`` tags the transform family
    (docs/REAL.md): half-spectrum rows ("rfft2^K_*" bench metrics)
    carry "r2c"; every record that predates the domain field —
    including the committed BENCH_r01-r06 trajectory — backfills the
    "c2c" default, so old artifacts keep parsing unchanged.
    ``precision`` tags the plan precision mode the same way
    (docs/PRECISION.md): precision-mode rows ("bf16_2^K_*" metrics)
    carry their mode, and every record that predates the precision
    axis — the whole committed r01-r06 trajectory — backfills
    "split3", the mode those rounds actually ran.  ``op`` tags the
    served spectral operation (docs/APPS.md): op rows ("conv2^K_*",
    "corr2^K_*", "solve2^K_*", and the overlap-save "os2^K_*" set)
    carry their op, and every record that predates the op axis —
    the whole committed BENCH_r01-r06 trajectory — backfills "fft",
    the only op those rounds served.  ``protocol`` tags the wire
    dialect a serve-load sample was measured over (docs/SERVING.md
    "The wire"): per-protocol ``serve_load`` rows carry "json" /
    "binary" (or "inproc" for the direct-dispatcher cells), and every
    record that predates the protocol axis backfills "json", the only
    dialect the front door spoke before the framed wire landed."""

    source: str               # "tsv" | "bench" | "obs"
    metric: str               # "total_ms", "funnel_ms", "n2^24_gflops", ...
    value: float
    n: Optional[int] = None
    p: Optional[int] = None
    rep: Optional[int] = None
    round_index: Optional[int] = None
    fingerprint: Optional[Fingerprint] = None
    degraded: bool = False
    domain: str = "c2c"
    precision: str = "split3"
    op: str = "fft"
    #: mesh-serving rows (docs/SERVING.md): per-device ``serve_mesh``
    #: samples carry the device id they were measured on; every other
    #: sample (and every pre-mesh committed round) stays None
    device: Optional[str] = None
    protocol: str = "json"
    #: the backend plan axis (docs/BACKENDS.md): per-backend rows
    #: (``gpu2^K_*``, ``cpun2^K_*``) carry their tag, and every record
    #: that predates the axis — the whole committed BENCH_r01-r06
    #: trajectory — backfills "tpu", the only family those rounds ran
    backend: str = "tpu"


@dataclasses.dataclass
class BenchRound:
    """One committed BENCH round record, normalized."""

    index: int
    path: str
    metrics: dict            # name -> float | list[float] (replications)
    fingerprint: Fingerprint
    rc: Optional[int] = None
    note: Optional[str] = None
    #: the raw ``serve_mesh`` row set when the round carries one
    #: (``bench.py --serve-mesh`` — docs/SERVING.md): per-device
    #: utilization rows plus the kill row; empty for every other round
    serve_mesh_rows: list = dataclasses.field(default_factory=list)
    #: the raw ``serve_load`` row set when the round carries one
    #: (``bench.py --serve-load`` — docs/SERVING.md): one SLO cell per
    #: (protocol, arrival process, offered rps); empty otherwise
    serve_load_rows: list = dataclasses.field(default_factory=list)

    def metric_names(self) -> list:
        return sorted(self.metrics)


class SampleTable:
    """The merged table: samples from every ingested source plus the
    bench rounds in trajectory order."""

    def __init__(self):
        self.samples: list = []
        self.rounds: list = []

    def add(self, samples) -> "SampleTable":
        self.samples.extend(samples)
        return self

    def filter(self, **fields) -> list:
        out = self.samples
        for key, want in fields.items():
            out = [s for s in out if getattr(s, key) == want]
        return out

    def metrics(self) -> list:
        return sorted({s.metric for s in self.samples})

    def phase_rows(self, source: str = "tsv") -> np.ndarray:
        """``n p total funnel tube`` rows (the lawfit contract) from
        this table's phase samples of one source, pairing the k-th
        total/funnel/tube samples per (n, p) cell by rep index.
        DEGRADED samples are excluded, exactly as the TSV fit excludes
        the marked rows."""
        cells: dict = {}
        for s in self.samples:
            if s.source != source or s.degraded or s.n is None:
                continue
            if s.metric in ("total_ms", "funnel_ms", "tube_ms"):
                cells.setdefault((s.n, s.p, s.rep), {})[s.metric] = s.value
        rows = []
        for (n, p, _rep), vals in sorted(cells.items()):
            if "funnel_ms" not in vals or "tube_ms" not in vals:
                continue
            total = vals.get("total_ms",
                             vals["funnel_ms"] + vals["tube_ms"])
            rows.append([n, p, total, vals["funnel_ms"], vals["tube_ms"]])
        return np.asarray(rows) if rows else np.empty((0, 5))

    def summary(self) -> dict:
        by_source: dict = {}
        for s in self.samples:
            by_source[s.source] = by_source.get(s.source, 0) + 1
        return {
            "samples": len(self.samples),
            "by_source": by_source,
            "metrics": self.metrics(),
            "rounds": [
                {"index": r.index, "path": os.path.basename(r.path),
                 "rc": r.rc, "metrics": len(r.metrics),
                 "fingerprint": r.fingerprint.describe()}
                for r in self.rounds
            ],
        }


# ----------------------------------------------------------- TSV source


def load_tsv_samples(path: str,
                     fingerprint: Optional[Fingerprint] = None) -> list:
    """Phase samples from one harness TSV.  DEGRADED rows are kept but
    flagged (the fit path drops them; the loader is an inventory, not a
    filter).  An UNKNOWN 6th-column marker raises — the same provenance
    refusal the fit's own reader enforces (lawfit.load_tsv): data of
    unknown provenance must not silently enter shares/cross-checks the
    fit path would refuse."""
    samples = []
    reps: dict = {}
    with open(path) as fh:
        for line in fh:
            parts = line.rstrip("\n").split("\t")
            if len(parts) not in (5, 6) or not parts[0] \
                    or not parts[0][0].isdigit():
                continue
            if len(parts) == 6 and parts[5] != "DEGRADED":
                raise ValueError(
                    f"{path}: unknown row marker {parts[5]!r} (only "
                    "DEGRADED is defined) — refusing to ingest data of "
                    "unknown provenance")
            degraded = len(parts) == 6
            n, p = int(parts[0]), int(parts[1])
            rep = reps[(n, p)] = reps.get((n, p), -1) + 1
            for metric, raw in zip(("total_ms", "funnel_ms", "tube_ms"),
                                   parts[2:5], strict=True):
                samples.append(Sample(
                    source="tsv", metric=metric, value=float(raw),
                    n=n, p=p, rep=rep, fingerprint=fingerprint,
                    degraded=degraded))
    return samples


# --------------------------------------------------------- BENCH source

#: parsed-record keys that are structure, not metrics
_NON_METRIC_KEYS = frozenset(("metric", "unit", "smoke", "degraded",
                              "run", "env", "note"))


def _numeric(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def _mesh_device_utils(mesh_rows) -> list:
    """``(device, utilization)`` pairs from a serve_mesh row set —
    THE one filter both the replicated metric (values) and the
    device-tagged samples (ids) are derived from, so they can never
    fall out of step and mis-attribute a device's utilization."""
    return [(r.get("device"), float(r["utilization"]))
            for r in mesh_rows
            if r.get("row") == "device"
            and _numeric(r.get("utilization"))]


def _round_index(doc: dict, path: str) -> int:
    idx = doc.get("n")
    if isinstance(idx, int):
        return idx
    m = re.search(r"_r(\d+)", os.path.basename(path))
    return int(m.group(1)) if m else 0


def load_bench_round(path: str) -> BenchRound:
    """One BENCH_r*.json file -> a normalized :class:`BenchRound`.

    Accepts both the driver's committed wrapper (``{"n", "cmd", "rc",
    "tail", "parsed"}``) and a bare record (one JSON line from
    ``bench.py`` itself).  Every numeric ``parsed`` field is a metric;
    the headline ``value`` is renamed to the record's ``metric`` name;
    a list of numbers is kept whole as a replicated metric."""
    with open(path, encoding="utf-8") as fh:
        doc = json.load(fh)
    parsed = doc.get("parsed") if isinstance(doc.get("parsed"), dict) \
        else doc
    metrics: dict = {}
    for key, val in parsed.items():
        if key in _NON_METRIC_KEYS:
            continue
        if key == "value":
            name = parsed.get("metric")
            if isinstance(name, str) and name and _numeric(val):
                metrics[name] = float(val)
            continue
        if _numeric(val):
            metrics[key] = float(val)
        elif isinstance(val, list) and val and all(_numeric(v)
                                                  for v in val):
            metrics[key] = [float(v) for v in val]
    # the serve_mesh row set (docs/SERVING.md): per-device utilization
    # becomes ONE replicated metric (the balance distribution) and the
    # kill row's p99 split becomes scalar metrics — the fields a
    # future `analyze gate` holds floors on (post-kill p99)
    mesh_rows = parsed.get("serve_mesh")
    mesh_rows = [r for r in mesh_rows if isinstance(r, dict)] \
        if isinstance(mesh_rows, list) else []
    utils = _mesh_device_utils(mesh_rows)
    if utils:
        metrics["serve_mesh_utilization"] = [u for _d, u in utils]
    for r in mesh_rows:
        if r.get("row") != "kill":
            continue
        for key in ("p99_pre_kill_ms", "p99_post_kill_ms"):
            if _numeric(r.get(key)):
                metrics[f"serve_mesh_{key}"] = float(r[key])
    # the serve_load row set (docs/SERVING.md "The wire"): the worst
    # p99 per wire dialect becomes a scalar metric, so the trajectory
    # (and a future `analyze gate` floor) can hold the binary dialect
    # to its parse-tax-free tail directly; rows predating the protocol
    # axis backfill "json", the only dialect the front door spoke then
    load_rows = parsed.get("serve_load")
    load_rows = [r for r in load_rows if isinstance(r, dict)] \
        if isinstance(load_rows, list) else []
    by_proto: dict = {}
    for r in load_rows:
        if _numeric(r.get("p99_ms")):
            proto = r.get("protocol") or "json"
            by_proto.setdefault(proto, []).append(float(r["p99_ms"]))
    for proto, p99s in by_proto.items():
        metrics[f"serve_load_{proto}_p99_ms"] = max(p99s)
    # fingerprint: the stamped env when present, else backfill from the
    # record's smoke flag and the platform banner in the captured tail
    env = parsed.get("env") if isinstance(parsed.get("env"), dict) \
        else None
    fp = Fingerprint.from_env(env, smoke=bool(parsed.get("smoke", False)))
    if env is None:
        tail = doc.get("tail") if isinstance(doc.get("tail"), str) else ""
        m = _PLATFORM_BANNER.search(tail)
        if m:
            fp = dataclasses.replace(fp, platform=m.group(1))
    return BenchRound(index=_round_index(doc, path), path=path,
                      metrics=metrics, fingerprint=fp,
                      rc=doc.get("rc") if isinstance(doc.get("rc"), int)
                      else None,
                      note=doc.get("note") if isinstance(doc.get("note"),
                                                         str) else None,
                      serve_mesh_rows=mesh_rows,
                      serve_load_rows=load_rows)


def load_bench_rounds(paths) -> list:
    """Rounds sorted into trajectory order (by round index, then
    filename, so ties from hand-built files stay deterministic)."""
    rounds = [load_bench_round(p) for p in paths]
    rounds.sort(key=lambda r: (r.index, os.path.basename(r.path)))
    return rounds


_LOGN_METRIC = re.compile(r"^n2\^(\d+)_")
_RFFT_METRIC = re.compile(r"^rfft2\^(\d+)_")
#: exact-n row prefixes (docs/PLANS.md "Arbitrary n"): non-pow2 cells
#: carry the exact length (``n1000_``, ``rfft1000_``, ``conv_np768_``)
#: — the ``n2^K`` forms above stay for pow2 cells, so every committed
#: round parses unchanged.  NOTE the pow2 patterns cannot collide with
#: these: ``n2^13_`` fails ``^n(\d+)_`` on the ``^`` character.
_EXACTN_METRIC = re.compile(r"^n(\d+)_")
_RFFT_EXACTN_METRIC = re.compile(r"^rfft(\d+)_")
_OP_EXACTN_METRIC = re.compile(r"^(conv|corr|solve|os)_np(\d+)_")
#: precision-mode row prefixes (docs/PRECISION.md): bench emits one
#: row set per raced storage mode beside the split3 cells — the mode
#: rides the metric name exactly as the domain does for rfft rows
_PRECISION_METRIC = re.compile(
    r"^(bf16|fp32|highest|default)_2\^(\d+)_")
#: spectral-op row prefixes (docs/APPS.md): the conv/corr/solve cells
#: plus the overlap-save streaming set ("os" = streaming conv; its
#: 2^K is the BLOCK size, the row's tuned chunk length)
_OP_METRIC = re.compile(r"^(conv|corr|solve|os)2\^(\d+)_")
_OP_PREFIX = {"conv": "conv", "corr": "corr", "solve": "solve",
              "os": "conv"}
#: per-protocol serve-load scalars (docs/SERVING.md "The wire"): the
#: dialect rides the metric name exactly as the op does for op rows
_SERVE_LOAD_METRIC = re.compile(r"^serve_load_([a-z0-9]+)_p99_ms$")
#: per-backend row prefixes (docs/BACKENDS.md): bench emits one row
#: set per non-default backend beside the TPU cells — ``gpu2^K_*``
#: (backend "gpu") and ``cpun2^K_*`` (backend "cpu-native"); the tag
#: rides the metric name exactly as the precision mode does, and no
#: prefix collides with the existing patterns (``n``/``rfft``/op
#: names/``bf16`` etc. share no leading token with ``gpu``/``cpun``)
_BACKEND_METRIC = re.compile(r"^(gpu|cpun)2\^(\d+)_")
_BACKEND_PREFIX = {"gpu": "gpu", "cpun": "cpu-native"}


def bench_samples(rnd: BenchRound) -> list:
    """A round's metrics as flat samples (n parsed from the ``n2^K_``
    row prefix where one exists; ``rfft2^K_`` rows parse the same n
    and tag ``domain="r2c"``; ``bf16_2^K_`` (and any other
    precision-mode prefix) rows parse the same n and tag their
    ``precision``; ``conv2^K_`` / ``corr2^K_`` / ``solve2^K_`` /
    ``os2^K_`` op rows (docs/APPS.md) tag ``op`` — everything else,
    including every pre-domain / pre-precision / pre-op committed
    round (BENCH_r01-r06), backfills "c2c" / "split3" / "fft";
    replicated metrics flatten with rep indices)."""
    out = []
    for name, val in rnd.metrics.items():
        if name == "serve_mesh_utilization":
            # per-device rows: keep the device identity on each sample
            # (the replicated metric itself still feeds the gate) —
            # ids and values come from the SAME pair list, so they
            # cannot skew against each other
            pairs = _mesh_device_utils(rnd.serve_mesh_rows)
            for rep, (device, v) in enumerate(pairs):
                out.append(Sample(
                    source="bench", metric=name, value=v, rep=rep,
                    round_index=rnd.index,
                    fingerprint=rnd.fingerprint, device=device))
            continue
        sl = _SERVE_LOAD_METRIC.match(name)
        if sl is not None:
            # the per-dialect SLO scalar keeps its dialect on the
            # sample, so `analyze` can filter binary vs json tails
            # without re-parsing metric names
            out.append(Sample(
                source="bench", metric=name, value=val,
                round_index=rnd.index, fingerprint=rnd.fingerprint,
                protocol=sl.group(1)))
            continue
        domain = "c2c"
        precision = "split3"
        op = "fft"
        backend = "tpu"
        m = _LOGN_METRIC.match(name)
        if m is None:
            m = _RFFT_METRIC.match(name)
            if m is not None:
                domain = "r2c"
        n = (1 << int(m.group(1))) if m else None
        if n is None:
            # exact-n (non-pow2) cells — docs/PLANS.md "Arbitrary n"
            em = _EXACTN_METRIC.match(name)
            if em is None:
                em = _RFFT_EXACTN_METRIC.match(name)
                if em is not None:
                    domain = "r2c"
            if em is not None:
                m = em
                n = int(em.group(1))
        if m is None:
            pm = _PRECISION_METRIC.match(name)
            if pm is not None:
                precision = pm.group(1)
                n = 1 << int(pm.group(2))
        if m is None and n is None:
            om = _OP_METRIC.match(name)
            if om is not None:
                op = _OP_PREFIX[om.group(1)]
                domain = "r2c"  # the ops ride the half-spectrum path
                n = 1 << int(om.group(2))
            else:
                om = _OP_EXACTN_METRIC.match(name)
                if om is not None:
                    op = _OP_PREFIX[om.group(1)]
                    domain = "r2c"
                    n = int(om.group(2))
        if m is None and n is None:
            bm = _BACKEND_METRIC.match(name)
            if bm is not None:
                backend = _BACKEND_PREFIX[bm.group(1)]
                n = 1 << int(bm.group(2))
        values = val if isinstance(val, list) else [val]
        for rep, v in enumerate(values):
            out.append(Sample(
                source="bench", metric=name, value=v, n=n,
                rep=rep if isinstance(val, list) else None,
                round_index=rnd.index, fingerprint=rnd.fingerprint,
                domain=domain, precision=precision, op=op,
                backend=backend))
    # per-cell serve_load rows (docs/SERVING.md "The wire"): one
    # sample per (protocol, process, rps) SLO cell, dialect-tagged —
    # rows predating the protocol axis backfill "json"
    for rep, r in enumerate(rnd.serve_load_rows):
        if not _numeric(r.get("p99_ms")):
            continue
        out.append(Sample(
            source="bench", metric="serve_load_p99_ms",
            value=float(r["p99_ms"]),
            n=r["n"] if isinstance(r.get("n"), int) else None,
            rep=rep, round_index=rnd.index,
            fingerprint=rnd.fingerprint,
            protocol=r.get("protocol") or "json"))
    return out


# ----------------------------------------------------------- obs source


def load_obs_samples(path: str) -> tuple:
    """(samples, fingerprint, dropped_lines) from an obs event-stream
    JSONL: every funnel/tube span becomes a phase sample keyed by its
    cell identity, and a ``kind="env"`` event (bench/harness emit one
    when armed) fingerprints the stream.  The reader tolerates the
    half-written tail a kill leaves (the journal discipline) — a
    truncated final line is counted, not fatal."""
    from ..obs.events import load_events
    from .phases import phase_samples_from_events

    records, dropped = load_events(path)
    fp = None
    for rec in records:
        if rec.get("kind") == "env" and isinstance(rec.get("payload"),
                                                   dict):
            fp = Fingerprint.from_env(rec["payload"])
    samples = phase_samples_from_events(records, fingerprint=fp)
    return samples, fp, dropped


# ----------------------------------------------- trace tail attribution

#: the request-tree phase children the serve trace plane emits
#: (obs/trace.py): queue (submit->dequeue), window (dequeue->batch
#: execution), compute (the kernel seconds)
_TRACE_PHASES = ("queue", "window", "compute")


def tail_attribution(records, q: float = 99.0) -> dict:
    """WHICH PHASE OWNS THE TAIL: the span-level sequel to the
    funnel/tube shares (docs/ANALYSIS.md).

    Reassembles every ``serve_request`` span tree in an obs event
    stream (the serve trace plane, obs/trace.py) by trace id, then per
    shape label compares the MEDIAN request's phase split against the
    p-th-percentile request's: the row names the phase that owns the
    tail request's latency (``p99_owner``) and carries both splits, so
    "the p99 is queue wait, not kernel" is a table lookup instead of a
    spelunking session.  Requests whose tree is incomplete (sampled-out
    children, kill-truncated stream) are skipped, not guessed at."""
    from ..obs.export import spans_from_events
    from ..utils.stats import percentile_nearest_rank

    spans = spans_from_events(records)
    roots: dict = {}       # (trace, sid) -> root span
    children: dict = {}    # (trace, parent_sid) -> {phase: dur_s}
    for sp in spans:
        trace = sp.get("trace")
        if not trace:
            continue
        if sp.get("name") == "serve_request" and sp.get("sid"):
            if not (sp.get("args") or {}).get("shed"):
                roots[(trace, sp["sid"])] = sp
        elif sp.get("name") in _TRACE_PHASES and sp.get("parent_sid"):
            bucket = children.setdefault((trace, sp["parent_sid"]), {})
            bucket[sp["name"]] = float(sp.get("dur_s", 0.0))
    requests: dict = {}    # label -> [(total_s, {phase: dur_s})]
    for key, root in roots.items():
        phases = children.get(key)
        if not phases or any(p not in phases for p in _TRACE_PHASES):
            continue
        label = (root.get("args") or {}).get("shape", "?")
        total = sum(phases[p] for p in _TRACE_PHASES)
        requests.setdefault(label, []).append((total, phases))
    out = {}
    for label, rows in sorted(requests.items()):
        totals = sorted(t for t, _ in rows)
        p50 = percentile_nearest_rank(totals, 50)
        p_tail = percentile_nearest_rank(totals, q)
        # the ACTUAL tail request (nearest rank: it happened), not an
        # interpolated phantom — its split is the attribution
        tail_total, tail_phases = min(
            (r for r in rows if r[0] >= p_tail), key=lambda r: r[0])
        med_total, med_phases = min(
            (r for r in rows if r[0] >= p50), key=lambda r: r[0])
        row = {
            "requests": len(rows),
            "p50_ms": round(p50 * 1e3, 4),
            f"p{q:g}_ms": round(p_tail * 1e3, 4),
        }
        for name, total, phases in (("p50", med_total, med_phases),
                                    (f"p{q:g}", tail_total,
                                     tail_phases)):
            for phase in _TRACE_PHASES:
                row[f"{name}_{phase}_ms"] = round(
                    phases[phase] * 1e3, 4)
                row[f"{name}_{phase}_share"] = round(
                    phases[phase] / total, 4) if total > 0 else 0.0
        row[f"p{q:g}_owner"] = max(
            _TRACE_PHASES, key=lambda p: tail_phases[p])
        out[label] = row
    return out


# -------------------------------------------------------------- merging


def build_table(tsv_paths=(), bench_paths=(), events_paths=()) \
        -> SampleTable:
    """Ingest every named artifact into one table."""
    table = SampleTable()
    for path in tsv_paths:
        table.add(load_tsv_samples(path))
    if bench_paths:
        table.rounds = load_bench_rounds(bench_paths)
        for rnd in table.rounds:
            table.add(bench_samples(rnd))
    for path in events_paths:
        samples, _fp, _dropped = load_obs_samples(path)
        table.add(samples)
    return table
