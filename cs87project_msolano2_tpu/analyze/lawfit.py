"""Law fitting (the reference's L5 statistical verification): does the
measured time obey the predicted complexity law?

This module is the single source of truth the standalone scripts
``analysis/analyze_results.py`` / ``analysis/analyze_results_full.py``
now shim (docs/ANALYSIS.md).  The reference's R scripts
(cpu/pthreads/analyze-results.R:23-157) fit

    total ~ 0 + I(funnel_law + tube_law)     (zero-intercept regression)

with funnel_law = n(p-1)/p and tube_law = (n/p) log2(n/p), report the
significance of the fit, and plot empirical + fitted speedup.  This is
the project's integration test: "the implementation scales as designed".

The port is FALSIFIABLE (round 5 hardened it — the reference's
single-beta significance test cannot reject any positively-correlated
data):

* the TOTAL is fitted against BOTH phase laws with separate
  coefficients (the two phases' constants differ by ~800x in some
  regimes here; the reference's hardware kept them comparable);
* measurements riding a JAX dispatch pipeline carry a latency-FLOOR
  column (with a physical sanity bound — see :func:`analyze_table`);
* acceptance requires, besides significance of every material
  coefficient, the per-cell PREDICTION GATE
  median |log(measured/predicted)| < log 2 — the fitted law must
  predict the typical cell within 2x, not merely correlate.

Package-era extensions (ISSUE 9): every fit reports per-coefficient
95% confidence intervals and per-(n, p)-cell residuals
(``report["cells"]``), and :func:`analyze_table` accepts an in-memory
sample table so span-derived phase times (:mod:`.phases`) feed the
same fit as TSV columns.

t-statistics use scipy when present, else a normal approximation;
empirical and fitted speedup tables and optional matplotlib PDFs mirror
the reference's per-n figure layout.  The awk fallback
(analyze-results.awk) implements the same criterion for machines
without numpy, keeping the reference's R -> awk fallback philosophy.
"""

from __future__ import annotations

import math
import os
import sys

import numpy as np

__all__ = [
    "FLOOR_MODELS", "LOG2_GATE", "MODELS", "NATIVE_TIMED",
    "ON_CHIP_BACKENDS", "SERIALIZED_BACKENDS", "analyze", "analyze_table",
    "demo_table", "fit_laws", "has_floor_for", "laws", "load_tsv",
    "ls_fit", "model_for", "plot_results", "prediction_gate",
    "predicted_total", "script_main", "t_ppf", "t_sf", "write_demo_tsv",
    "zero_intercept_fit",
]


def t_sf(t: float, df: int) -> float:
    """P(T > t) for Student's t; scipy when available, else normal tail."""
    try:
        from scipy import stats

        return float(stats.t.sf(t, df))
    except ImportError:
        return 0.5 * math.erfc(t / math.sqrt(2.0))


def t_ppf(q: float, df: int) -> float:
    """Upper-tail critical value: t with P(T > t) = q (for confidence
    intervals).  scipy when available, else bisection on :func:`t_sf`'s
    normal-tail fallback — both sides of the fallback agree, so the
    reported interval is internally consistent either way."""
    try:
        from scipy import stats

        return float(stats.t.isf(q, df))
    except ImportError:
        lo, hi = 0.0, 50.0
        for _ in range(80):
            mid = 0.5 * (lo + hi)
            if t_sf(mid, df) > q:
                lo = mid
            else:
                hi = mid
        return 0.5 * (lo + hi)


def load_tsv(path: str) -> tuple:
    """Returns (rows, n_degraded).  Rows carrying the harness's DEGRADED
    marker (6th column: loop-slope fell back to dispatch-inclusive wall
    time) are excluded from the fit — they carry ~100 ms of relay
    overhead that is not device time."""
    rows, degraded = [], 0
    with open(path) as fh:
        for line in fh:
            parts = line.strip().split("\t")
            if len(parts) in (5, 6) and parts[0] and parts[0][0].isdigit():
                if len(parts) == 6:
                    if parts[5] != "DEGRADED":
                        raise SystemExit(
                            f"{path}: unknown row marker {parts[5]!r} "
                            "(only DEGRADED is defined) — refusing to fit "
                            "data of unknown provenance"
                        )
                    degraded += 1
                    continue
                rows.append([float(v) for v in parts])
    if not rows:
        raise SystemExit(f"no usable data rows in {path}")
    return np.asarray(rows), degraded  # n p total funnel tube


# Which complexity law governs each phase depends on WHERE the p virtual
# processors run:
#  * per-processor (the reference's law, analyze-results.R:35-37): each
#    of p real cores runs its own chain, so time tracks the per-processor
#    work — funnel n(p-1)/p, tube (n/p)log2(n/p).
#  * on-chip (single-accelerator butterfly backends jax/pallas): ALL p
#    virtual processors are materialized as rows of one array on one
#    chip, whose throughput is fixed — time tracks the TOTAL work, p x
#    the per-processor law: funnel n(p-1) (the paper's redundant
#    replication made explicit), tube n*log2(n/p) (each stage touches all
#    n elements regardless of p).  On a real multi-chip mesh each device
#    runs only its own chain (parallel/pi_shard.py), recovering the
#    per-processor law.
#  * einsum-dense (the einsum backend): the same phases expressed as
#    dense contractions predict DIFFERENT complexity — funnel is the
#    (p, p, s)-coefficient einsum, Theta(p*n) ~ n(p-1) total work (0 at
#    p=1, where the funnel is empty); the tube is a dense s-point DFT
#    matrix per segment — s^2 per processor, with the batch dimension
#    absorbed by the MXU (see laws()).  Fitting the butterfly law to a
#    dense implementation would test the wrong hypothesis.
#  * serialized (CPU backends running all p virtual processors on fewer
#    real cores: the `serial` backend by construction, and any backend
#    swept with --oversubscribe, which the harness writes to a distinct
#    `-oversub-` file so the regime is visible in the filename): wall
#    time (total_ms) is the SUM over processors — the same total-work
#    laws as on-chip — but the funnel/tube COLUMNS are still processor
#    0's per-processor timers (native/pifft_backends.c:62-67), so the
#    phase fits keep the per-processor laws.  See fit_laws().
MODELS = ("per-processor", "on-chip", "einsum-dense", "serialized")
ON_CHIP_BACKENDS = ("jax", "pallas")
SERIALIZED_BACKENDS = ("serial",)


def model_for(path: str, requested: str = "auto") -> str:
    if requested != "auto":
        return requested
    base = os.path.basename(path)
    if "-oversub-" in base:  # harness --oversubscribe output (any backend)
        return "serialized"
    if "-einsum-" in base:
        return "einsum-dense"
    if "-jax-scan-" in base:
        # measured (round 5): the constant-geometry scan tube's stage
        # ops carry a leading p dimension the VPU absorbs — at fixed n
        # its time falls ~2x per p-doubling, the PER-PROCESSOR law, not
        # the total-work law (same mechanism as the einsum s^2 tube:
        # the chip is unsaturated by one chain, so the p virtual
        # processors run physically in parallel on the vector units).
        # The pallas backend, whose sequential grid programs DO
        # saturate the chip, keeps the total-work on-chip model below.
        return "per-processor"
    if any(f"-{b}-" in base for b in ON_CHIP_BACKENDS):
        return "on-chip"
    if any(f"-{b}-" in base for b in SERIALIZED_BACKENDS):
        return "serialized"
    return "per-processor"


def laws(n: np.ndarray, p: np.ndarray,
         model: str = "per-processor") -> tuple:
    s = n / p
    log_s = np.where(s > 1, np.log2(np.maximum(s, 2)), 0.0)
    if model in ("on-chip", "serialized"):
        return n * (p - 1), n * log_s
    if model == "einsum-dense":
        # tube = a (p, s, s) batched dense matvec on the MXU.  TOTAL
        # flops are p*s^2 = n^2/p, but the committed sweeps show time
        # constant along fixed s and falling 4x per p-doubling — the
        # chip absorbs the batch dimension (matvec leaves the MXU's
        # lanes idle; batching fills them for free), so wall time
        # tracks the PER-PROCESSOR dense work s^2 = n^2/p^2.  The
        # round-4 criterion couldn't reject the total-work guess
        # (894x measured vs "predicts 32x" while printing Yes); the
        # falsifiable fit did, and this is the hardware-honest law.
        return n * (p - 1), s * s
    return n * (p - 1) / p, s * log_s


def fit_laws(n: np.ndarray, p: np.ndarray, model: str) -> tuple:
    """Per-COLUMN regressors ((total_funnel_x, total_tube_x), funnel_x,
    tube_x).

    The total is fitted against BOTH phase laws with separate
    coefficients (round-4 verdict: the single-beta summed-law fit
    cannot fail against monotone data — the einsum sweep's funnel and
    tube constants differ by ~800x, and one beta split the difference
    while the speedup table showed 894x measured vs "predicts 32x").
    The reference could get away with one beta because its hardware had
    comparable phase constants (analyze-results.R:46-50 fits the sum);
    this framework's regimes don't.

    The serialized model is hybrid: total_ms sums over the p virtual
    processors run back-to-back (total-work laws), but the funnel/tube
    columns are processor 0's own phase timers
    (native/pifft_backends.c:62-67) and obey the per-processor laws —
    fitting them against total-work laws is off by a factor of p (the
    round-3 advisor measured tube R^2 0.999 -> 0.69 from exactly that).
    Every other model times all three columns in the same regime."""
    fl, tl = laws(n, p, model)
    if model == "serialized":
        pfl, ptl = laws(n, p, "per-processor")
        return (fl, tl), pfl, ptl
    return (fl, tl), fl, tl


# Measurements that ride a JAX dispatch pipeline carry a per-run
# latency FLOOR: a 2^14-point transform does not run 64x faster than a
# 2^20-point one on hardware both underutilize (round-4 verdict: the
# jax total fit was R^2=0.40 purely from this floor).  The fit includes
# a constant column for them.  That is an implementation property, not
# a law-model property: the per-device `-sharded-` dataset is
# per-processor-law data timed through jitted jax calls (dispatch
# ~tens of us), while the native-C-timed sweeps (serial, pthreads)
# read the reference's floor-free form.
FLOOR_MODELS = ("on-chip", "einsum-dense")
NATIVE_TIMED = ("-serial-", "-pthreads-")


def has_floor_for(path: str, model: str) -> bool:
    base = os.path.basename(path)
    if any(tag in base for tag in NATIVE_TIMED):
        return False
    return (model in FLOOR_MODELS or "-sharded-" in base
            or "-jax-scan-" in base)


def _ls_fit_full(y: np.ndarray, cols: list) -> tuple:
    """Least squares y ~ sum_i beta_i * cols_i (no implicit intercept);
    returns (betas, r2, tstats, alphas, df, ses) in the caller's units.

    Columns are RMS-normalized internally (law columns span ~1e9 in
    raw units next to a unit floor column; the raw normal equations'
    conditioning produced garbage standard errors).  R^2 keeps the
    zero-intercept convention (1 - SSR / sum(y^2)) so values stay
    comparable with earlier rounds' logs and the reference's R output.
    """
    scales = np.array([max(float(np.sqrt(np.mean(c * c))), 1e-30)
                       for c in cols])
    X = np.column_stack([c / s for c, s in zip(cols, scales, strict=True)])
    betas_n, *_ = np.linalg.lstsq(X, y, rcond=None)
    resid = y - X @ betas_n
    df = max(len(y) - X.shape[1], 1)
    sigma2 = float(resid @ resid) / df
    xtx_inv = np.linalg.pinv(X.T @ X)
    ses = np.sqrt(np.maximum(sigma2 * np.diag(xtx_inv), 0.0))
    tstats = np.where(ses > 0, betas_n / np.where(ses > 0, ses, 1.0), np.inf)
    alphas = np.array([t_sf(float(t), df) if math.isfinite(t) else 0.0
                       for t in tstats])
    ss_tot = float(y @ y)
    r2 = 1.0 - float(resid @ resid) / ss_tot if ss_tot > 0 else 0.0
    return betas_n / scales, r2, tstats, alphas, df, ses / scales


def ls_fit(y: np.ndarray, cols: list):
    """(betas, r2, tstats, alphas, df) — the historical 5-tuple form
    (see :func:`_ls_fit_full` for the standard errors)."""
    betas, r2, tstats, alphas, df, _ = _ls_fit_full(y, cols)
    return betas, r2, tstats, alphas, df


LOG2_GATE = math.log(2.0)


def prediction_gate(y: np.ndarray, yhat: np.ndarray) -> tuple:
    """Per-cell prediction-error gate: median |log(measured/predicted)|
    must be < log 2 (i.e. the fitted law predicts the TYPICAL cell
    within 2x).  Significance alone cannot catch a law that mispredicts
    per-cell behavior by 30x while correlating with it (round-4
    verdict, the einsum speedup table).  Returns (ok, median_abs_log).

    Cells where the law predicts <= 0: a correct zero (the phase is
    empty there — e.g. funnel at p=1 — and the measurement agrees) is
    skipped; a nonpositive prediction against a real measurement fails
    the gate outright."""
    tiny = 1e-3 * float(np.max(y)) if np.max(y) > 0 else 0.0
    bad = (yhat <= 0) & (y > tiny)
    if bad.any():
        return False, float("inf")
    both = (yhat > 0) & (y > 0)
    if not both.any():
        return True, 0.0
    err = float(np.median(np.abs(np.log(y[both] / yhat[both]))))
    return err < LOG2_GATE, err


def predicted_total(report: dict, n: np.ndarray, p: np.ndarray,
                    model: str) -> np.ndarray:
    """Fitted-law total time at (n, p), for speedup tables and figures:
    the TOTAL fit's own coefficients beta_f*funnel_law + beta_t*tube_law
    (+ the latency floor where the model carries one)."""
    fl, tl = laws(n, p, model)
    t = report["total"]
    return (t.get("beta_f", 0.0) * fl + t.get("beta_t", 0.0) * tl
            + t.get("floor", 0.0))


def zero_intercept_fit(x: np.ndarray, y: np.ndarray):
    """y ~ 0 + beta*x: returns (beta, r2, tstat, alpha, df).  The
    reference's single-regressor form, kept for the phase fits of
    floor-free models."""
    betas, r2, tstats, alphas, df = ls_fit(y, [x])
    return float(betas[0]), r2, float(tstats[0]), float(alphas[0]), df


def _cell_residuals(n: np.ndarray, p: np.ndarray, y: np.ndarray,
                    yhat: np.ndarray) -> list:
    """Per-(n, p)-cell residual records for the fitted quantity:
    measured mean, predicted mean, and the log ratio the prediction
    gate medians over — the 'which cell is the law missing' diagnostic
    the round-4 verdict wanted next to a bare med|log err|."""
    out = []
    for nn in sorted(set(n.astype(int))):
        for pp in sorted(set(p[n == nn].astype(int))):
            sel = (n == nn) & (p == pp)
            meas = float(np.mean(y[sel]))
            pred = float(np.mean(yhat[sel]))
            rec = {"n": nn, "p": pp, "measured": round(meas, 6),
                   "predicted": round(pred, 6), "reps": int(sel.sum())}
            if meas > 0 and pred > 0:
                rec["log_ratio"] = round(math.log(meas / pred), 4)
            out.append(rec)
    return out


def analyze_table(data: np.ndarray, model: str,
                  alpha_level: float = 0.01, has_floor: bool = False,
                  label: str = "<table>", degraded: int = 0,
                  verbose: bool = True) -> dict:
    """The law fit over an in-memory sample table (rows of
    ``n p total funnel tube``, the TSV contract) — the single fitting
    core behind :func:`analyze` (files) and :mod:`.phases`
    (span-derived tables).  Returns the report dict; ``verbose=False``
    suppresses the human log for library callers."""
    say = print if verbose else (lambda *a, **k: None)
    n, p, total, funnel, tube = data.T
    (tfl, ttl), funnel_law, tube_law = fit_laws(n, p, model)

    report = {"model": model}
    say(f"== {label}: {len(n)} runs, "
        f"n in {sorted(int(v) for v in set(n))}, "
        f"p in {sorted(int(v) for v in set(p))}, "
        f"law model: {model}"
        f"{' + latency floor' if has_floor else ''} ==")
    if degraded:
        say(f"# excluded {degraded} DEGRADED rows "
            "(dispatch-inclusive fallback timing)")
    for name, y, xcols, colnames in (
        ("total", total, [tfl, ttl], ["funnel", "tube"]),
        ("funnel", funnel, [funnel_law], ["funnel"]),
        ("tube", tube, [tube_law], ["tube"]),
    ):
        kept = [(c, nm) for c, nm in zip(xcols, colnames, strict=True)
                if np.any(c)]
        if not kept:
            # Degenerate grid: the law is identically zero here (e.g. a
            # p=1-only sweep, where funnel_law = n(p-1)/p = 0 — this
            # container's pthreads capacity is 1 core).  The hypothesis
            # "time scales as the law" is vacuously satisfied iff the
            # measured phase time is also ~0; there is nothing to regress.
            negligible = float(np.mean(y)) <= 1e-3 * float(np.mean(total))
            verdict = "Yes (vacuous: law = 0 on this grid)" if negligible \
                else "No"
            say(f"{name:>6}: law = 0 over the whole grid; measured mean "
                f"{float(np.mean(y)):.3e} ms  law holds: {verdict}")
            report[name] = dict(beta=0.0, beta_f=0.0, beta_t=0.0, floor=0.0,
                                r2=0.0, t=0.0, alpha=1.0, med_log_err=0.0,
                                signif=negligible, holds=negligible,
                                ci95={})
            continue

        def fit(cols, names):
            betas, r2, tstats, alphas, df, ses = _ls_fit_full(y, cols)
            return list(betas), r2, list(tstats), list(alphas), df, \
                list(names), list(ses)

        cols = [c for c, _ in kept]
        names = [nm for _, nm in kept]
        if has_floor:
            # the floor rides each DISPATCHED run: the total always
            # dispatches, but a phase whose law is 0 at a cell (funnel
            # at p=1) never runs there — its floor column is the
            # law-positive indicator, not all-ones
            if name == "total":
                fc = np.ones_like(y)
            else:
                fc = (cols[0] > 0).astype(float)
            if np.any(fc):
                cols = cols + [fc]
                names = names + ["floor"]
        betas, r2, tstats, alphas, df, names, ses = fit(cols, names)
        # floor sanity: the dispatch floor is a LOWER-bound component of
        # every dispatched run, so the fitted value can never exceed the
        # smallest dispatched cell's mean (2x margin for noise).  A
        # "floor" beyond that — or a negative one — is least squares
        # using the constant column to absorb model misfit in the
        # large cells (observed: an "82 ms floor" on the einsum sweep,
        # 300x its smallest cell); drop the column and refit.
        if "floor" in names:
            fi = names.index("floor")
            disp = cols[fi] > 0
            cell_means = [float(np.mean(y[disp & (n == nn) & (p == pp)]))
                          for nn in set(n[disp]) for pp in set(p[disp])
                          if ((n == nn) & (p == pp) & disp).any()]
            bound = 2.0 * min(cell_means) if cell_means else 0.0
            if betas[fi] < 0 or betas[fi] > bound:
                cols.pop(fi)
                betas, r2, tstats, alphas, df, names, ses = fit(
                    cols, [nm for nm in names if nm != "floor"])
        # a law column whose fitted contribution is a negligible share
        # of the measurement is noise to this fit: a negative or
        # insignificant coefficient there says nothing about the law
        # (the einsum funnel is ~0.1% of total next to the Theta(n^2/p)
        # tube).  Drop negative-negligible columns; exempt
        # positive-negligible ones from the significance requirement.
        ymean = max(float(np.mean(y)), 1e-30)
        while True:
            shares = {nm: float(np.mean(b * c)) / ymean
                      for nm, b, c in zip(names, betas, cols, strict=True)}
            drop = [nm for nm in names if nm != "floor"
                    and betas[names.index(nm)] < 0 and shares[nm] > -0.01]
            if not drop:
                break
            i = names.index(drop[0])
            cols.pop(i)
            remaining = names[:i] + names[i + 1:]
            if not remaining:
                names = []
                break  # nothing left to fit (corrupt data reached here)
            betas, r2, tstats, alphas, df, names, ses = fit(cols, remaining)
        # significance is demanded only of coefficients that carry a
        # material share (>= 5%) of the fitted quantity: a term that
        # explains 1-2% of a noisy measurement can be real physics with
        # t < 2.6, and failing the whole law on it tests noise, not the
        # law.  The prediction gate still covers the total behavior.
        law_ix = [i for i, nm in enumerate(names) if nm != "floor"]
        major = [i for i in law_ix if abs(shares[names[i]]) >= 0.05]
        signif = bool(major) and all(
            alphas[i] < alpha_level and betas[i] > 0 for i in major)
        yhat = (np.column_stack(cols) @ np.asarray(betas)
                if names else np.zeros_like(y))
        gate_ok, med_err = prediction_gate(y, yhat)
        holds = signif and gate_ok
        verdict = ("Yes" if holds else
                   f"No ({'prediction gate' if signif else 'significance'})")
        frac = float(np.mean(y)) / max(float(np.mean(total)), 1e-30)
        if not holds and name != "total" and frac < 0.01:
            # A phase that is a sub-percent sliver of the total sits at
            # the timing floor — its measurements are noise, and neither
            # law acceptance nor rejection is supportable (e.g. the
            # einsum funnel, Theta(n*p) work next to a Theta(n^2/p)
            # tube: ratio n/p^2, thousands at these grids).  The
            # reference never hits this (its funnel is a large share of
            # total); report it as untestable rather than failing.
            # record the distinct value "untestable" (truthy, so the
            # law-gate consumers pass) rather than True, keeping a
            # broken near-zero timer distinguishable from a real pass
            holds = "untestable"
            verdict = (f"untestable (phase is {frac * 100:.2g}% of "
                       "total — below the timing floor)")
        # 95% confidence intervals per retained coefficient (t-critical
        # at the fit's residual df) — the package-era extension: a beta
        # without an interval cannot anchor a cross-round comparison
        tcrit = t_ppf(0.025, df)
        ci95 = {nm: (round(betas[i] - tcrit * ses[i], 12),
                     round(betas[i] + tcrit * ses[i], 12))
                for i, nm in enumerate(names)}
        terms = "  ".join(
            f"{nm}={betas[i]:.3e}(t={tstats[i]:.1f},a={alphas[i]:.1e})"
            for i, nm in enumerate(names))
        say(f"{name:>6}: {terms}   R^2={r2:.4f} (df={df})  "
            f"med|log err|={med_err:.3f} (gate {LOG2_GATE:.3f})  "
            f"law holds: {verdict}")
        get = lambda nm: (betas[names.index(nm)] if nm in names else 0.0)
        first_law = names[law_ix[0]] if law_ix else None
        report[name] = dict(
            beta=get(first_law) if first_law else 0.0,
            beta_f=get("funnel"), beta_t=get("tube"), floor=get("floor"),
            r2=r2,
            t=min((float(tstats[i]) for i in law_ix), default=0.0),
            alpha=max((float(alphas[i]) for i in major), default=1.0)
            if major else min((float(alphas[i]) for i in law_ix),
                              default=1.0),
            med_log_err=med_err, signif=signif, holds=holds, ci95=ci95)
        if name == "total":
            report["cells"] = _cell_residuals(n, p, y, yhat)

    # speedup tables (reference: empirical + fitted, per n)
    say("\nspeedup (empirical vs fitted-law):")
    for nn in sorted(set(n.astype(int))):
        sel1 = (n == nn) & (p == 1)
        if not sel1.any():
            continue
        t1 = float(np.mean(total[sel1]))
        t1_law = predicted_total(
            report, np.array([float(nn)]), np.array([1.0]), model)[0]
        for pp in sorted(set(p[n == nn].astype(int))):
            sel = (n == nn) & (p == pp)
            tp = float(np.mean(total[sel]))
            tp_law = predicted_total(
                report, np.array([float(nn)]), np.array([float(pp)]),
                model)[0]
            fitted = t1_law / max(tp_law, 1e-30)
            say(f"  n={nn:>9} p={pp:>4}: {t1 / tp:7.2f}x  "
                f"(law predicts {float(fitted):7.2f}x)")
    return report


def analyze(path: str, alpha_level: float = 0.01, plot_dir=None,
            model: str = "auto", verbose: bool = True):
    """The file entry point: load a harness TSV, pick the law model
    from the filename, fit, optionally render the per-n figures."""
    data, degraded = load_tsv(path)
    model = model_for(path, model)
    report = analyze_table(
        data, model, alpha_level=alpha_level,
        has_floor=has_floor_for(path, model),
        label=os.path.basename(path), degraded=degraded, verbose=verbose)
    if plot_dir:
        try:
            plot_results(data, report, plot_dir, os.path.basename(path))
        except Exception as e:  # plots are best-effort, like the awk path
            print(f"# plotting skipped: {type(e).__name__}: {e}",
                  file=sys.stderr)
    return report


def plot_results(data, report, plot_dir: str, stem: str):
    """Per-n PDF: speedup scatter + fitted curve, stacked phase times —
    mirroring the reference figure layout (analyze-results.R:119-151)."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    os.makedirs(plot_dir, exist_ok=True)
    n, p, total, funnel, tube = data.T
    model = report.get("model", "per-processor")

    for nn in sorted(set(n.astype(int))):
        sel1 = (n == nn) & (p == 1)
        if not sel1.any():
            continue
        t1 = float(np.mean(total[sel1]))
        ps = np.array(sorted(set(p[n == nn].astype(int))))
        emp = np.array([t1 / float(np.mean(total[(n == nn) & (p == pp)]))
                        for pp in ps])
        grid = np.array([2**k for k in range(0, int(np.log2(ps.max())) + 1)])
        fit = predicted_total(
            report, np.array([float(nn)]), np.array([1.0]), model
        )[0] / np.maximum(
            predicted_total(report, np.full_like(grid, nn, dtype=float),
                            grid.astype(float), model), 1e-30)

        fig, (ax1, ax2) = plt.subplots(1, 2, figsize=(9, 3.6))
        ax1.plot(ps, emp, "o", label="measured")
        ax1.plot(grid, fit, "-", label="fitted law")
        ax1.set_xscale("log", base=2)
        ax1.set_xlabel("p")
        ax1.set_ylabel("speedup")
        ax1.set_title(f"n = {nn}")
        ax1.legend()

        fmean = [float(np.mean(funnel[(n == nn) & (p == pp)])) for pp in ps]
        tmean = [float(np.mean(tube[(n == nn) & (p == pp)])) for pp in ps]
        ax2.bar([str(v) for v in ps], fmean, label="funnel")
        ax2.bar([str(v) for v in ps], tmean, bottom=fmean, label="tube")
        ax2.set_xlabel("p")
        ax2.set_ylabel("phase time (ms)")
        ax2.legend()
        fig.tight_layout()
        out = os.path.join(plot_dir, f"{stem}-n{nn}.pdf")
        fig.savefig(out)
        plt.close(fig)
        print(f"# wrote {out}", file=sys.stderr)


def demo_table(model: str = "per-processor", seed: int = 0,
               beta_f: float = 2e-6, beta_t: float = 3e-6,
               noise: float = 0.05,
               ns=(1024, 4096, 16384), ps=(1, 2, 4, 8, 16),
               reps: int = 5) -> np.ndarray:
    """A law-obeying synthetic sample table (rows ``n p total funnel
    tube``) — the self-test generator behind ``make analyze-smoke`` and
    the fit-recovery tests: the fit must recover ``beta_f``/``beta_t``
    from this data, and reject data that does not come from the law."""
    rng = np.random.default_rng(seed)
    rows = []
    for n in ns:
        for p in ps:
            fl, tl = laws(np.array([float(n)]), np.array([float(p)]), model)
            for _ in range(reps):
                eps = 1.0 + noise * rng.standard_normal()
                fm = beta_f * fl[0] * eps
                tm = beta_t * tl[0] * eps
                rows.append([n, p, fm + tm, fm, tm])
    return np.asarray(rows)


def write_demo_tsv(path: str, **kwargs) -> str:
    """:func:`demo_table` in the harness TSV contract, for CLI smoke."""
    data = demo_table(**kwargs)
    with open(path, "w") as fh:
        for n, p, total, fm, tm in data:
            fh.write(f"{int(n)}\t{int(p)}\t{total:.6f}\t{fm:.6f}"
                     f"\t{tm:.6f}\n")
    return path


def script_main(argv=None) -> int:
    """The ``analysis/analyze_results.py`` entry point (shimmed)."""
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("tsv", nargs="+")
    ap.add_argument("--alpha", type=float, default=0.01)
    ap.add_argument("--plots", default=None,
                    help="directory for per-n PDF figures")
    ap.add_argument("--model", default="auto",
                    choices=("auto",) + MODELS,
                    help="complexity-law model; auto picks einsum-dense "
                         "for the einsum backend, on-chip for the other "
                         "single-accelerator backends (jax/pallas), and "
                         "per-processor otherwise")
    ap.add_argument("--allow-fail", action="append", default=[],
                    help="filename substring whose total-fit FAILURE is "
                         "expected (documented negative results, e.g. "
                         "-jax-unrolled-); such a file failing keeps the "
                         "exit code 0, and PASSING flips it to 1 — the "
                         "criterion must keep its teeth")
    args = ap.parse_args(argv)
    ok = True
    for path in args.tsv:
        report = analyze(path, args.alpha, args.plots, args.model)
        expected_fail = any(sub in os.path.basename(path)
                            for sub in args.allow_fail)
        if expected_fail:
            if report["total"]["holds"]:
                print(f"# {os.path.basename(path)}: documented law "
                      "violation PASSED the fit — criterion lost its "
                      "teeth", file=sys.stderr)
                ok = False
            continue
        ok &= bool(report["total"]["holds"])
    return 0 if ok else 1
