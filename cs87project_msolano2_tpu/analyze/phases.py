"""Phase attribution from obs spans (docs/OBSERVABILITY.md,
docs/ANALYSIS.md): funnel/tube time computed directly from the nested
span durations a run emitted, instead of from TSV columns.

``models/pi_fft.py`` wraps its two algorithm phases in named spans —
``funnel`` (the replicated accumulation) and ``tube`` (the segment-
local chains) — each carrying its cell identity ``{"n": .., "p": ..}``.
A run armed with ``--events`` therefore already contains a complete
phase-time decomposition of every transform it executed; this module
turns that stream into the same ``n p total funnel tube`` sample rows
the harness TSVs carry, so the two-law fit (:mod:`.lawfit`) can run on
*measured per-phase span times* with no TSV in the loop, and the two
derivations can be cross-checked against each other
(:func:`phase_shares` over either source; the tests assert agreement
on identical synthetic runs).

Span caveat (the spans-module contract): a span duration is a
host-side wall interval — on an async dispatch pipeline it is NOT a
device measurement unless the span closed over an explicit sync.  The
pi-FFT phase spans wrap eager numpy/jit-blocking phase code, where
wall time IS phase time; attribution from spans around un-synced
dispatches would attribute launch time, which is why the fit keeps the
latency-floor column for on-chip models either way.
"""

from __future__ import annotations

from typing import Iterable, Optional

import numpy as np

from ..obs.export import spans_from_events

__all__ = ["PHASE_SPAN_NAMES", "phase_rows_from_events",
           "phase_samples_from_events", "phase_shares",
           "phase_shares_from_events", "phase_shares_from_rows"]

#: the span names that ARE the algorithm's phase decomposition
PHASE_SPAN_NAMES = ("funnel", "tube")


def _phase_pairs(records: Iterable[dict]) -> dict:
    """(n, p) -> list of {"funnel_ms": .., "tube_ms": ..} per executed
    transform, pairing the k-th funnel span with the k-th tube span of
    the same cell (one transform emits exactly one of each, in order;
    seq order within the stream preserves that pairing)."""
    per_cell: dict = {}
    for sp in spans_from_events(records):
        name = sp.get("name")
        if name not in PHASE_SPAN_NAMES:
            continue
        cell = sp.get("cell") or {}
        n, p = cell.get("n"), cell.get("p")
        if not isinstance(n, int) or not isinstance(p, int):
            continue
        runs = per_cell.setdefault((n, p), [])
        key = f"{name}_ms"
        # first run still missing this phase gets it; else a new run
        target = next((r for r in runs if key not in r), None)
        if target is None:
            target = {}
            runs.append(target)
        target[key] = float(sp.get("dur_s", 0.0)) * 1e3
    return per_cell


def phase_rows_from_events(records: Iterable[dict]) -> np.ndarray:
    """``n p total funnel tube`` rows (the lawfit/TSV contract) from an
    event stream's phase spans; total is the phase sum (the TSV total
    column is also funnel+tube for every backend without a separate
    total timer).  Incomplete pairs (a run killed between its funnel
    and tube span) are dropped, like the journal reader drops a
    half-written tail."""
    rows = []
    for (n, p), runs in sorted(_phase_pairs(records).items()):
        for run in runs:
            if "funnel_ms" not in run or "tube_ms" not in run:
                continue
            rows.append([n, p, run["funnel_ms"] + run["tube_ms"],
                         run["funnel_ms"], run["tube_ms"]])
    return np.asarray(rows) if rows else np.empty((0, 5))


def phase_samples_from_events(records: Iterable[dict],
                              fingerprint=None) -> list:
    """The same pairing as :func:`phase_rows_from_events`, as loader
    samples (source ``"obs"``) so the merged table can fit or
    cross-check them."""
    from .loader import Sample

    out = []
    for (n, p), runs in sorted(_phase_pairs(records).items()):
        for rep, run in enumerate(runs):
            if "funnel_ms" not in run or "tube_ms" not in run:
                continue
            for metric in ("funnel_ms", "tube_ms"):
                out.append(Sample(source="obs", metric=metric,
                                  value=run[metric], n=n, p=p, rep=rep,
                                  fingerprint=fingerprint))
            out.append(Sample(source="obs", metric="total_ms",
                              value=run["funnel_ms"] + run["tube_ms"],
                              n=n, p=p, rep=rep, fingerprint=fingerprint))
    return out


def phase_shares_from_rows(rows: np.ndarray) -> dict:
    """(n, p) -> {"funnel": share, "tube": share, "runs": k} from
    ``n p total funnel tube`` rows (either derivation).  Shares are of
    the phase SUM — the decomposition the paper's law speaks about —
    so the TSV- and span-derived values are directly comparable even
    where a TSV total column carries overhead outside both phases."""
    out: dict = {}
    if len(rows) == 0:
        return out
    n, p, _total, funnel, tube = np.asarray(rows).T
    for nn in sorted(set(n.astype(int))):
        for pp in sorted(set(p[n == nn].astype(int))):
            sel = (n == nn) & (p == pp)
            f = float(np.sum(funnel[sel]))
            t = float(np.sum(tube[sel]))
            tot = f + t
            out[(int(nn), int(pp))] = {
                "funnel": f / tot if tot else 0.0,
                "tube": t / tot if tot else 0.0,
                "runs": int(sel.sum()),
            }
    return out


def phase_shares_from_events(records: Iterable[dict]) -> dict:
    return phase_shares_from_rows(phase_rows_from_events(records))


def phase_shares(source, tsv_path: Optional[str] = None) -> dict:
    """Dispatch helper: an events-record list, a span-rows array, or a
    TSV path (via ``tsv_path=``) — all land in the same share table."""
    if tsv_path is not None:
        from .lawfit import load_tsv

        data, _ = load_tsv(tsv_path)
        return phase_shares_from_rows(data)
    if isinstance(source, np.ndarray):
        return phase_shares_from_rows(source)
    return phase_shares_from_events(source)
