"""The spectral operation suite (docs/APPS.md): production traffic
buys *operations* — filtering, correlation, PDE solves — not bare
transforms, and this package turns the tuned plan ladder into exactly
those:

* :mod:`.spectral` — fused spectral convolution / cross-correlation
  (one rfft of each operand, a pointwise half-spectrum multiply ON
  DEVICE, one irfft — composed from the planned executors so the
  intermediate never materializes on host) with a kernel-spectrum
  cache, plus the op executors and numpy oracles the serving layer's
  op-tagged groups ride;
* :mod:`.stream` — overlap-save / overlap-add block convolution for
  signals longer than any transform: ONE cached plan pair per chunk
  shape, a plan-chosen (autotune-raced) block size, an eager array
  API, a generator/push API serve can drain incrementally, and a
  journaled kill-safe variant;
* :mod:`.pde` — the spectral solver family generalizing
  ``parallel/poisson3d.py``: one spectral pipeline parameterized by
  its multiplier (Poisson, constant- and variable-coefficient
  Helmholtz, an exact spectral time-stepper), single-device and
  slab-sharded.

Every op has a roofline minimum-traffic model
(``utils.roofline.spectral_min_hbm_bytes``) charged through the same
``pifft_hbm_bytes_total`` meter the transforms use, so the fused-op
win is enforced by ``make apps-smoke`` from the meter, not asserted
in prose — an implementation that round-trips the half-spectrum
through host trips the gate (and check rule PIF116 flags it
statically).
"""

from __future__ import annotations

from .spectral import (  # noqa: F401
    OPS,
    fftconv,
    fftcorr,
    kernel_spectrum,
    numpy_oracle,
    op_executor,
    solve_spectral_1d,
)
from .stream import (  # noqa: F401
    OverlapSave,
    choose_block,
    overlap_add,
    overlap_save,
    overlap_save_journaled,
    overlap_save_stream,
)
