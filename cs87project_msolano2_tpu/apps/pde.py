"""The spectral PDE solver family (docs/APPS.md): ONE spectral
pipeline — forward FFT every axis through the plan subsystem, apply a
REAL spectral multiplier, invert — parameterized by the multiplier,
so Poisson (``parallel/poisson3d.py``'s pipeline, now a thin shim
over this module), constant- and variable-coefficient Helmholtz, and
an exact spectral time-stepper are one code path instead of four.

All spectral arithmetic stays on split re/im float32 planes (the
TPU-native representation the whole kernel family uses): every
multiplier here is real, so the planes never recombine and the
pipeline is loop-compatible on every backend.  Kernel dispatch is the
per-axis-shape plan discipline: each axis pass fetches the plan for
ITS shape's key (the ``poisson3d`` rule, unchanged).

The sharded 3-D slab pipeline (:func:`solve_spectral_sharded`) is the
poisson3d dataflow verbatim — two ``all_to_all`` transposes through
the sanctioned ``parallel.collectives`` funnel (PIF108) — with the
Poisson multiplier generalized to any real symbol; the collective-free
escape path (``parallel/escape.py``) replays the same per-block
pipeline, so the bit-parity contract between primary and escape is
untouched.

Multipliers are declared as ``symbol(ksq) -> multiplier array``
callables over the squared wavenumber grid:

    poisson:    -1/|k|^2, zero mode -> 0 (the mean-free solution)
    helmholtz:  1/(alpha + |k|^2)   for (alpha - lap) u = f
    heat step:  exp(-nu |k|^2 t)    (the EXACT integrator of
                                     u_t = nu lap u — unconditionally
                                     stable at any dt)

Variable-coefficient Helmholtz has no diagonal symbol; it is solved
by the classic fixed-point split alpha = mean + fluctuation, each
iteration one constant-coefficient spectral solve — the whole family
still rides the one pipeline.
"""

from __future__ import annotations

from typing import Callable

import numpy as np

import jax.numpy as jnp

from .. import plans
from ..obs import metrics
from ..obs.spans import span
from ..utils.roofline import charge_spectral_traffic


def wavenumbers(m: int) -> np.ndarray:
    """Integer wavenumbers for an m-point periodic axis (fftfreq * m)
    — the poisson3d helper, now owned here."""
    k = np.arange(m)
    k[k > m // 2] -= m
    return k.astype(np.float32)


def fft_axis(vr, vi, ax: int, inverse: bool):
    """One planned FFT pass over axis `ax` of split planes: moveaxis
    to the trailing transform axis, fetch the plan for THIS shape's
    key, execute, move back — the per-axis-shape discipline every
    consumer of the pipeline shares (poisson3d's ``_fft_axis``)."""
    vr = jnp.moveaxis(vr, ax, -1)
    vi = jnp.moveaxis(vi, ax, -1)
    plan = plans.plan_for(vr.shape)
    if inverse:
        yr, yi = plan.execute_inverse(vr, vi)
    else:
        yr, yi = plan.execute(vr, vi)
    return jnp.moveaxis(yr, -1, ax), jnp.moveaxis(yi, -1, ax)


# ------------------------------------------------------- multipliers


def poisson_multiplier(ksq):
    """-1/|k|^2 with the zero mode -> 0: the mean-free solution of
    lap(u) = f.  EXACTLY the poisson3d expression — the sharded shim
    and the collective-free escape replay must stay bit-identical."""
    return jnp.where(ksq > 0, -1.0 / jnp.maximum(ksq, 1e-30), 0.0)


def helmholtz_multiplier(alpha: float) -> Callable:
    """1/(alpha + |k|^2): the symbol of (alpha - lap) u = f, alpha >
    0 (at alpha = 0 the zero mode is singular — use Poisson)."""
    if alpha <= 0:
        raise ValueError(f"helmholtz alpha={alpha} must be > 0 "
                         f"(alpha=0 is the Poisson problem)")
    a = np.float32(alpha)

    def mult(ksq):
        return 1.0 / (a + ksq)

    return mult


def heat_multiplier(nu: float, t: float) -> Callable:
    """exp(-nu |k|^2 t): the exact solution operator of the periodic
    heat equation u_t = nu lap(u) over time t."""

    def mult(ksq):
        return jnp.exp(-np.float32(nu) * ksq * np.float32(t))

    return mult


def _ksq_grid(shape: tuple) -> np.ndarray:
    """|k|^2 over the full grid (host-built float32, like the twiddle
    discipline)."""
    ksq = np.zeros(shape, np.float32)
    for ax, m in enumerate(shape):
        k = wavenumbers(m).astype(np.float64) ** 2
        expand = [1] * len(shape)
        expand[ax] = m
        ksq = ksq + k.reshape(expand).astype(np.float32)
    return ksq


# -------------------------------------------------- full-grid solves


def solve_spectral(f, multiplier: Callable):
    """The single-device family pipeline: real field `f` (any ndim,
    every axis a power of two) -> forward FFT every axis through the
    plan ladder, multiply by the REAL ``multiplier(ksq)``, invert
    every axis.  Returns the real solution (the imaginary plane of a
    real-input/real-symbol pipeline is roundoff and dropped)."""
    f = jnp.asarray(f, jnp.float32)
    shape = tuple(int(s) for s in f.shape)
    gr, gi = f, jnp.zeros_like(f)
    with span("spectral_solve", cell={"op": "solve",
                                      "n": int(np.prod(shape))}):
        for ax in range(len(shape)):
            gr, gi = fft_axis(gr, gi, ax, False)
        m = multiplier(jnp.asarray(_ksq_grid(shape)))
        gr, gi = gr * m, gi * m
        for ax in range(len(shape)):
            gr, gi = fft_axis(gr, gi, ax, True)
        metrics.inc("pifft_apps_ops_total", op="solve")
        charge_spectral_traffic("solve", int(np.prod(shape)))
    return gr


def poisson_solve(f):
    """lap(u) = f on the periodic grid, zero-mean — the full-grid
    form of poisson3d's slab solve, any ndim."""
    return solve_spectral(f, poisson_multiplier)


def helmholtz_solve(f, alpha: float):
    """(alpha - lap) u = f on the periodic grid, alpha > 0."""
    return solve_spectral(f, helmholtz_multiplier(alpha))


def helmholtz_solve_variable(f, alpha_field, iters: int = 40,
                             tol: float = 1e-6):
    """(alpha(x) - lap) u = f with a VARIABLE coefficient: no diagonal
    spectral symbol exists, so split alpha = mean + fluctuation and
    iterate the classic fixed point

        u_{j+1} = S_mean( f - (alpha - mean) u_j )

    where each S_mean is one constant-coefficient spectral solve —
    convergent while the fluctuation stays under the mean (a
    diagonally-dominant split; the iteration count and residual are
    reported, and a non-converged exit WARNS rather than lying).
    Returns the solution field."""
    f = jnp.asarray(f, jnp.float32)
    alpha_field = jnp.asarray(alpha_field, jnp.float32)
    if alpha_field.shape != f.shape:
        raise ValueError(f"alpha field shape {alpha_field.shape} != "
                         f"rhs shape {f.shape}")
    abar = float(jnp.mean(alpha_field))
    if abar <= 0:
        raise ValueError(f"mean(alpha)={abar} must be > 0")
    fluct = alpha_field - np.float32(abar)
    mult = helmholtz_multiplier(abar)
    u = solve_spectral(f, mult)
    err = np.inf
    for _ in range(iters):
        u_next = solve_spectral(f - fluct * u, mult)
        err = float(jnp.max(jnp.abs(u_next - u))
                    / jnp.maximum(jnp.max(jnp.abs(u_next)), 1e-30))
        u = u_next
        if err <= tol:
            break
    if err > tol:
        # a bare array cannot carry a degrade tag: the never-silent
        # rule is served by the warn, the event, and the counter — a
        # monitoring stack sees the non-convergence even though the
        # caller's array looks like any other
        metrics.inc("pifft_apps_solve_nonconverged_total")
        plans.warn(f"variable-coefficient helmholtz did not converge "
                   f"in {iters} iteration(s) (rel step {err:.2e} > "
                   f"{tol:.0e}); returning the best iterate — treat "
                   f"as degraded")
    return u


def spectral_step(u0, nu: float, dt: float, steps: int = 1):
    """March the periodic heat equation u_t = nu lap(u) by `steps`
    steps of `dt` with the EXACT spectral integrator (one pipeline,
    the one-step symbol raised to the step count — unconditionally
    stable, error is the transform roundoff)."""
    if steps < 1:
        raise ValueError(f"steps={steps} must be >= 1")
    return solve_spectral(u0, heat_multiplier(nu, dt * steps))


# ------------------------------------------------- sharded 3-D slabs


def solve_spectral_sharded(f, mesh, axis: str = "p",
                           multiplier: Callable = poisson_multiplier):
    """The slab-decomposed 3-D family pipeline (BASELINE.json config 5
    dataflow, lifted verbatim from ``parallel/poisson3d.py``): per
    slab local FFTs over axes 1-2, one all_to_all transpose to
    localize axis 0, FFT over axis 0, the REAL spectral `multiplier`
    on the (n1, n2/p, n3) block, then the inverted pipeline — two ICI
    transposes per solve, both through the sanctioned
    ``parallel.collectives`` funnel (PIF108).  `f` real (n1, n2, n3)
    sharded on axis 0; returns real u, same sharding."""
    from jax.sharding import PartitionSpec as P

    import jax

    from ..parallel.collectives import all_to_all as _a2a
    from ..utils.compat import shard_map

    p = mesh.shape[axis]
    n1, n2, n3 = f.shape
    k1 = wavenumbers(n1)
    k2 = wavenumbers(n2)
    k3 = wavenumbers(n3)

    def a2a(v, split_axis, concat_axis):
        return _a2a(v, axis, split_axis, concat_axis)

    def device_fn(fb):  # (n1/p, n2, n3) real
        gr, gi = fb, jnp.zeros_like(fb)
        gr, gi = fft_axis(gr, gi, 2, False)
        gr, gi = fft_axis(gr, gi, 1, False)
        # localize axis 0: (n1/p, n2, n3) -> (n1, n2/p, n3)
        gr, gi = a2a(gr, 1, 0), a2a(gi, 1, 0)
        gr, gi = fft_axis(gr, gi, 0, False)

        # the spectral multiplier on the (n1, n2/p, n3) block — REAL,
        # so planes never recombine; the k2 slice is this device's
        i = jax.lax.axis_index(axis)
        k2_loc = jax.lax.dynamic_slice_in_dim(
            jnp.asarray(k2), i * (n2 // p), n2 // p
        )
        ksq = (
            jnp.asarray(k1)[:, None, None] ** 2
            + k2_loc[None, :, None] ** 2
            + jnp.asarray(k3)[None, None, :] ** 2
        )
        inv = multiplier(ksq)
        gr, gi = gr * inv, gi * inv

        gr, gi = fft_axis(gr, gi, 0, True)
        gr, gi = a2a(gr, 0, 1), a2a(gi, 0, 1)
        gr, gi = fft_axis(gr, gi, 1, True)
        gr, gi = fft_axis(gr, gi, 2, True)
        return gr

    fn = shard_map(
        device_fn, mesh=mesh, in_specs=(P(axis, None, None),),
        out_specs=P(axis, None, None),
        # check=False (vma checking off): the Pallas HLO interpreter
        # (CPU test path) cannot carry varying-manual-axes through its
        # grid while-loop (jax hlo_interpreter.py; the error text
        # itself prescribes this workaround).  With the checker off
        # HERE, the kernels' vma declarations (_out_struct/_pvary_like
        # in ops) are inert on this entry point — they exist to keep
        # EXTERNAL check_vma=True embeddings of these kernels working,
        # not to protect this path.
        check=False,
    )
    return fn(f)


def helmholtz_solve_sharded(f, mesh, axis: str = "p",
                            alpha: float = 1.0):
    """(alpha - lap) u = f on the sharded 3-D slab pipeline — the
    first sibling Poisson gained from the family refactor: same two
    transposes, same per-shard plans, a different symbol."""
    return solve_spectral_sharded(f, mesh, axis,
                                  helmholtz_multiplier(alpha))


def spectral_step_sharded(u0, mesh, axis: str = "p",
                          nu: float = 1.0, dt: float = 1e-3,
                          steps: int = 1):
    """The exact heat step on the sharded slab pipeline."""
    if steps < 1:
        raise ValueError(f"steps={steps} must be >= 1")
    return solve_spectral_sharded(u0, mesh, axis,
                                  heat_multiplier(nu, dt * steps))
