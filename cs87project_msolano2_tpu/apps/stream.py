"""Overlap-save / overlap-add streaming convolution (docs/APPS.md).

A signal longer than any transform — or one that has not finished
ARRIVING — is served by the classic block-convolution identities
(Oppenheim & Schafer): chunk the input, convolve each chunk against
the kernel through ONE cached plan pair at the block length, and
stitch.  Overlap-save slides a ``block``-long window by
``L = block - (m-1)`` samples and keeps the L circularly-valid
outputs per chunk; overlap-add convolves disjoint L-chunks to
``L+m-1`` and adds the overhangs.  Both reuse one compiled fused
pipeline (one r2c plan, one c2r plan, the cached kernel spectrum) for
EVERY chunk — the per-chunk cost is a dispatch, not a trace.

The block size is a tuned axis: a big block amortizes the transform
(cost ~ block·log2(block) per chunk) but the last chunk wastes its
padding, a small block wastes ``(m-1)/block`` of every transform on
overlap.  :func:`choose_block` minimizes the analytic total;
:func:`tune_block` RACES the candidate blocks with real timings on
tunable devices (the autotune discipline — every candidate's fate is
reported) and falls back to the analytic choice offline, exactly like
``plans.tune_or_static``.

Three front doors:

* :func:`overlap_save` / :func:`overlap_add` — eager
  ``numpy.convolve(x, k, "full")`` parity for arbitrary lengths;
* :func:`overlap_save_stream` / :class:`OverlapSave` — the
  generator/push API: feed chunks as they arrive, drain outputs
  incrementally (what a served streaming op drains);
* :func:`overlap_save_journaled` — the kill-safe variant on the
  resilience journal: each chunk's output is checkpointed atomically,
  a re-run resumes at the first chunk the kill took.
"""

from __future__ import annotations

import math
from typing import Iterable, Optional

import numpy as np

import jax.numpy as jnp

from ..obs import metrics
from ..obs.spans import span
from ..utils.roofline import charge_spectral_traffic
from .spectral import _fused_circular, kernel_spectrum, next_pow2

#: hard cap on raced/chosen block sizes (2^18 keeps every candidate
#: inside the carry-free plan regime on current devices)
MAX_BLOCK = 1 << 18


def overlap_waste(block: int, m: int) -> float:
    """Fraction of each transform spent re-computing the overlap:
    (m-1)/block — the bench ``os2^K_overlap_waste`` column."""
    return (m - 1) / block


def chunk_count(n: int, m: int, block: int) -> int:
    """Chunks an n-sample signal needs at this block size (full-
    convolution output, n+m-1 samples) — the ``os2^K_chunks``
    column."""
    step = block - (m - 1)
    return max(1, -(-(n + m - 1) // step))


def block_candidates(m: int, n: Optional[int] = None) -> list:
    """The raced block-size ladder for an m-tap kernel: powers of two
    AND the 3·2^j mixed sizes between them (the any-length ladder
    serves those as one-level mixed-radix plans — docs/PLANS.md
    "Arbitrary n" — so the block race is no longer locked to octave
    steps; a half-octave 1.5·2^j block can win where the pow2 above
    wastes overlap and the one below multiplies chunks), from the
    smallest useful block (>= 2·(m-1), so at least half of every
    transform is new samples) up to MAX_BLOCK — truncated one size
    past the whole padded signal when `n` is known (a block bigger
    than the signal is a single-chunk transform; racing ten of them
    is pure waste)."""
    lo = max(2 * (m - 1), 2)
    cands = []
    b = next_pow2(lo)
    while b <= MAX_BLOCK:
        half = 3 * b // 4  # 1.5x the previous pow2: 3*2^(j-2)
        if lo <= half < b and half % 2 == 0:
            cands.append(half)
            if n is not None and half >= n + m - 1:
                break
        cands.append(b)
        if n is not None and b >= n + m - 1:
            break
        b *= 2
    return cands


def block_cost(block: int, m: int, n: Optional[int] = None) -> float:
    """Analytic cost of serving at this block size: chunk count times
    the O(block log block) transform work when the signal length is
    known, per-useful-output-sample transform work otherwise — the
    FFT-cost-vs-overlap-waste trade the block axis tunes."""
    step = block - (m - 1)
    if step < 1:
        return math.inf
    per_chunk = block * math.log2(block)
    if n is None:
        return per_chunk / step
    return chunk_count(n, m, block) * per_chunk


def choose_block(m: int, n: Optional[int] = None) -> int:
    """The analytic block choice: argmin of :func:`block_cost` over
    the candidate ladder — the offline policy (and the seed ordering
    of :func:`tune_block`'s race)."""
    cands = block_candidates(m, n)
    return min(cands, key=lambda b: block_cost(b, m, n))


def tune_block(m: int, n: Optional[int] = None,
               reps: int = 3, verbose: bool = False) -> int:
    """The RACED block choice: on a tunable device, time one fused
    chunk convolution per candidate block (the plan ladder's
    loop-discipline timer is overkill for a whole-op race; best-of
    `reps` wall time suffices at these sizes) and pick the lowest
    measured per-useful-sample cost; offline, serve the analytic
    choice — the ``tune_or_static`` policy applied to the block
    axis.  Every candidate's fate lands in the
    ``pifft_apps_block_race_total`` counter and, with `verbose`, on
    stderr."""
    from .. import plans

    cands = block_candidates(m, n)
    if len(cands) == 1 or not plans.device_is_tunable():
        return choose_block(m, n)
    from ..resilience import FaultKind, classify
    from ..utils.timing import time_ms

    rng = np.random.default_rng(0)
    k = rng.standard_normal(m).astype(np.float32)
    best, best_cost = None, math.inf
    for block in cands:
        kr, ki = kernel_spectrum(k, block)
        # _fused_circular returns the jitted (and cached) pipeline:
        # every candidate's compiled program is reused by the serving
        # path that follows the race
        fused = _fused_circular("conv", block, None)
        xp = jnp.asarray(rng.standard_normal(block).astype(np.float32))
        try:
            ms, _ = time_ms(fused, xp, kr, ki, reps=reps, warmup=1)
        except Exception as e:
            kind = classify(e)
            if kind is FaultKind.TRANSIENT:
                raise  # the moment failed, not the block: retry layers own it
            metrics.inc("pifft_apps_block_race_total",
                        block=str(block), fate="rejected")
            plans.warn(f"block race: block={block} rejected "
                       f"({kind.value} {type(e).__name__}: "
                       f"{str(e)[:120]})")
            continue
        cost = ms * chunk_count(n, m, block) if n is not None \
            else ms / (block - (m - 1))
        won = cost < best_cost
        metrics.inc("pifft_apps_block_race_total", block=str(block),
                    fate="timed")
        if verbose:
            plans.warn(f"block race: block={block} {ms:.4f} ms/chunk "
                       f"cost={cost:.6f}{' <- best' if won else ''}")
        if won:
            best, best_cost = block, cost
    return best if best is not None else choose_block(m, n)


# ------------------------------------------------------ the push API


class OverlapSave:
    """Streaming overlap-save convolver: push input chunks of ANY
    size, drain full-convolution output incrementally.

        conv = OverlapSave(k, block=4096)
        for piece in arriving_signal:
            out.append(conv.push(piece))   # maybe-empty arrays
        out.append(conv.flush())           # the tail

    ``concatenate(out) == np.convolve(signal, k, "full")``.  ONE plan
    pair (r2c + c2r at ``block``) and one cached kernel spectrum
    serve every chunk; per-chunk work under an obs span, per-chunk
    traffic on the meter."""

    def __init__(self, k, block: Optional[int] = None,
                 precision: Optional[str] = None):
        self.k = np.ascontiguousarray(np.asarray(k, np.float32))
        if self.k.ndim != 1 or self.k.shape[0] < 1:
            raise ValueError(f"kernel must be a non-empty 1-D array, "
                             f"got shape {self.k.shape}")
        self.m = self.k.shape[0]
        self.block = int(block) if block is not None \
            else choose_block(self.m)
        if self.block < 2 or self.block % 2:
            raise ValueError(f"block={self.block} must be an even "
                             f"length >= 2 (the r2c pack trick needs "
                             f"the even/odd split; any even length is "
                             f"a ladder plan — docs/PLANS.md)")
        if self.block < self.m:
            raise ValueError(f"block={self.block} < kernel length "
                             f"{self.m}: no valid outputs per chunk")
        self.step = self.block - (self.m - 1)
        self.precision = precision
        self._kr, self._ki = kernel_spectrum(self.k, self.block,
                                             precision)
        self._fused = _fused_circular("conv", self.block, precision)
        #: the saved overlap: the last m-1 input samples (zeros before
        #: the signal starts — the textbook prefix)
        self._tail = np.zeros(self.m - 1, np.float32)
        self._buffer = np.zeros(0, np.float32)
        self._consumed = 0      # input samples fully processed
        self.chunks = 0         # fused invocations so far

    def _convolve_block(self, seg: np.ndarray) -> np.ndarray:
        """One fused circular conv of a block-length window; returns
        the step valid output samples."""
        with span("overlap_save_chunk",
                  cell={"op": "conv", "n": self.block},
                  chunk=self.chunks):
            y = self._fused(jnp.asarray(seg), self._kr, self._ki)
            metrics.inc("pifft_apps_ops_total", op="conv")
            charge_spectral_traffic("conv", self.block)
        self.chunks += 1
        return np.asarray(y)[self.m - 1:]

    def push(self, chunk) -> np.ndarray:
        """Feed more signal; returns every output sample that is now
        final (possibly empty).  Outputs arrive in order; sample i of
        the concatenated stream is ``np.convolve(x, k, 'full')[i]``."""
        chunk = np.asarray(chunk, np.float32).reshape(-1)
        self._buffer = np.concatenate([self._buffer, chunk])
        out = []
        while self._buffer.shape[0] >= self.step:
            head, self._buffer = (self._buffer[:self.step],
                                  self._buffer[self.step:])
            seg = np.concatenate([self._tail, head])
            out.append(self._convolve_block(seg))
            self._tail = seg[self.step:]
            self._consumed += self.step
        return np.concatenate(out) if out \
            else np.zeros(0, np.float32)

    def flush(self) -> np.ndarray:
        """Close the stream: convolve the zero-padded remainder and
        return the final output samples (the convolution tail).  The
        convolver is spent afterwards."""
        pending = self._buffer.shape[0]
        # total output owed is n + m - 1; push emitted one sample per
        # consumed input sample, so the tail owes the rest
        want = pending + self.m - 1
        out = []
        emitted = 0
        while emitted < want:
            head = np.zeros(self.step, np.float32)
            head[:self._buffer.shape[0]] = self._buffer
            self._buffer = np.zeros(0, np.float32)
            seg = np.concatenate([self._tail, head])
            out.append(self._convolve_block(seg))
            self._tail = seg[self.step:]
            emitted += self.step
        y = np.concatenate(out) if out else np.zeros(0, np.float32)
        return y[:want]


def overlap_save_stream(chunks: Iterable, k,
                        block: Optional[int] = None,
                        precision: Optional[str] = None):
    """Generator form of :class:`OverlapSave`: yields maybe-empty
    output arrays as input chunks arrive, then the tail — the shape a
    served streaming op drains incrementally."""
    conv = OverlapSave(k, block=block, precision=precision)
    for chunk in chunks:
        y = conv.push(chunk)
        if y.size:
            yield y
    tail = conv.flush()
    if tail.size:
        yield tail


# ------------------------------------------------------ the eager API


def overlap_save(x, k, block: Optional[int] = None,
                 precision: Optional[str] = None) -> np.ndarray:
    """``np.convolve(x, k, "full")`` for arbitrary signal lengths via
    overlap-save block convolution: ONE cached plan pair at `block`
    serves every chunk (block defaults to the analytic
    :func:`choose_block` choice)."""
    x = np.asarray(x, np.float32).reshape(-1)
    conv = OverlapSave(k, block=block, precision=precision)
    head = conv.push(x)
    tail = conv.flush()
    return np.concatenate([head, tail])


def overlap_add(x, k, block: Optional[int] = None,
                precision: Optional[str] = None) -> np.ndarray:
    """``np.convolve(x, k, "full")`` via overlap-ADD: disjoint
    L-sample chunks each convolved to L+m-1 outputs (zero-padded into
    one block-length fused circular conv), overhangs summed.  Same
    plan reuse, different stitching — the pair every DSP text
    teaches, both offered so the bench can race them."""
    x = np.asarray(x, np.float32).reshape(-1)
    k = np.ascontiguousarray(np.asarray(k, np.float32))
    m = k.shape[0]
    block = int(block) if block is not None else choose_block(m)
    if block < 2 or block % 2:
        raise ValueError(f"block={block} must be an even length >= 2")
    step = block - (m - 1)
    if step < 1:
        raise ValueError(f"block={block} < kernel length {m}")
    n = x.shape[0]
    kr, ki = kernel_spectrum(k, block, precision)
    fused = _fused_circular("conv", block, precision)
    y = np.zeros(n + m - 1, np.float32)
    for start in range(0, max(n, 1), step):
        seg = np.zeros(block, np.float32)
        piece = x[start:start + step]
        seg[:piece.shape[0]] = piece
        with span("overlap_add_chunk", cell={"op": "conv", "n": block},
                  chunk=start // step):
            yc = np.asarray(fused(jnp.asarray(seg), kr, ki))
            metrics.inc("pifft_apps_ops_total", op="conv")
            charge_spectral_traffic("conv", block)
        hi = min(start + block, y.shape[0])
        y[start:hi] += yc[:hi - start]
    return y


# -------------------------------------------------- journaled resume


def overlap_save_journaled(x, k, journal_path: str,
                           block: Optional[int] = None,
                           precision: Optional[str] = None) -> tuple:
    """Kill-safe overlap-save: each chunk's valid outputs are
    checkpointed to the resilience journal (atomic fsynced JSONL —
    docs/RESILIENCE.md) before the next chunk runs, and a re-run with
    the same journal resumes at the first chunk the kill took —
    recomputing ONLY those, byte-identical for the rest.  The journal
    is configuration-guarded (``Journal.guard_config``): resuming
    with a different signal/kernel/block refuses instead of splicing.

    Returns ``(y, computed_chunks)`` — the full convolution and how
    many chunks actually ran this time (a clean resume of a finished
    journal computes zero)."""
    from ..resilience.journal import Journal

    from .spectral import _kernel_hash

    x = np.asarray(x, np.float32).reshape(-1)
    k = np.ascontiguousarray(np.asarray(k, np.float32))
    m = k.shape[0]
    block = int(block) if block is not None else choose_block(m, x.shape[0])
    conv = OverlapSave(k, block=block, precision=precision)
    total = chunk_count(x.shape[0], m, block)
    journal = Journal(journal_path)
    # the kernel HASH rides the guard: a resume with a different
    # same-length kernel must refuse, not splice mixed-kernel chunks
    journal.guard_config(
        {"n": int(x.shape[0]), "m": int(m), "block": int(block),
         "kernel": _kernel_hash(k),
         "x_sum": float(np.float32(x.sum()))},
        label="overlap-save")
    xp = np.concatenate([x, np.zeros(total * conv.step - x.shape[0],
                                     np.float32)])
    pieces, computed = [], 0
    for i in range(total):
        cell = f"os:{i}"
        rec = journal.get(cell)
        head = xp[i * conv.step:(i + 1) * conv.step]
        if rec is not None:
            pieces.append(np.asarray(rec["y"], np.float32))
            # the overlap memory must advance even over skipped
            # chunks, so the first recomputed chunk sees the right
            # saved samples
            seg = np.concatenate([conv._tail, head])
            conv._tail = seg[conv.step:]
            continue
        y = conv._convolve_block(np.concatenate([conv._tail, head]))
        conv._tail = np.concatenate([conv._tail, head])[conv.step:]
        journal.record(cell, {"y": [float(v) for v in y]})
        pieces.append(y)
        computed += 1
    y = np.concatenate(pieces)[: x.shape[0] + m - 1]
    return y, computed
