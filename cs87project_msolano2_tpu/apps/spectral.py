"""Fused spectral convolution / correlation on the plan ladder
(docs/APPS.md).

The convolution theorem makes filtering three transforms and one
elementwise multiply — and on a memory-bound kernel family the whole
game is keeping that multiply ON DEVICE, in the half-spectrum, between
the paired transforms:

    y = irfft( rfft(x) · rfft(k) )          (conv)
    c = irfft( rfft(x) · conj(rfft(k)) )    (corr)

Everything here composes the EXISTING planned executors
(``plans.plan_for(..., domain="r2c"/"c2r")`` — docs/REAL.md): the
forward and inverse plans' traceable ``fn``s are fused into one jitted
callable, so the half-spectrum intermediate lives in device memory for
exactly the life of the pointwise multiply and never round-trips
through host (check rule PIF116 watches for the round trip; the
``make apps-smoke`` meter gate catches it dynamically).  Repeated
filtering with the same kernel pays ONE forward transform: the kernel
spectrum is cached per (kernel hash, n, domain, precision).

Linear-convolution semantics (``numpy.convolve`` /
``numpy.correlate`` parity) ride on the circular core by padding to
the next even power of two >= len(x)+len(k)-1 and slicing the mode's
window — the classic identity, with the padded length chosen from the
plan ladder's domain.  The circular core itself is also the SERVED
primitive: an op-tagged serve group (``op="conv"|"corr"|"solve"``,
docs/SERVING.md) coalesces requests into one batched fused invocation
through :func:`op_executor`, with ``jnp-fft`` and ``numpy-ref``
degradation rungs that speak each op natively — a fallback that
quietly served a bare transform would be a wrong answer merely tagged
degraded.
"""

from __future__ import annotations

import hashlib
import threading
from typing import Callable, Optional

import numpy as np

import jax.numpy as jnp

from .. import plans
from ..obs import metrics
from ..obs.spans import span
from ..utils.roofline import SPECTRAL_OPS as OPS
from ..utils.roofline import charge_spectral_traffic


def check_op(op: str) -> str:
    """Validate an op name, returning it; raises ``ValueError`` naming
    the vocabulary — the one refusal every op-accepting surface
    (shapes files, the wire, the CLI) routes through so an unknown op
    is a structured error, never a silently-warmed bare FFT."""
    if op not in OPS:
        raise ValueError(f"op={op!r} not in {OPS} (docs/APPS.md)")
    return op


def next_pow2(v: int) -> int:
    """Smallest power of two >= max(v, 2) (the plan ladder's domain —
    real-domain keys additionally need even n, which >= 2 gives)."""
    n = 2
    while n < v:
        n *= 2
    return n


#: odd parts the conv length chooser considers: 2^a * odd for these
#: odd factors all have cheap mixed-radix plans (one small-matmul
#: four-step split — ops.anylen), so the chooser can land well under
#: the next power of two without ever picking a chirp-padded length
_CHEAP_ODD_PARTS = (1, 3, 5, 9, 15)


def cheapest_length(v: int) -> int:
    """The cheapest feasible transform length >= v for the linear
    conv/corr pipeline — the end of the pad-to-pow2 tax (docs/APPS.md):
    spectral traffic scales linearly with n, so the cheapest length is
    simply the SMALLEST even n >= v whose plan is efficient.  With the
    any-length ladder that is the smallest ``odd * 2^a`` over the
    mixed-radix-cheap odd parts — at v = 3*2^18 + 1 the old
    ``next_pow2`` paid 2^20 (a 1.33x tax in bytes and time); this
    picks 5*2^16 = 327680 (1.25x denser coverage caps the worst-case
    tax at ~12.5%, odd part 9 vs 8).  Power-of-two v returns v
    unchanged, so every existing pow2 call site is untouched."""
    best = next_pow2(v)
    for odd in _CHEAP_ODD_PARTS[1:]:
        m = odd * 2  # even, so the r2c pack trick always applies
        while m < v:
            m *= 2
        if m < best:
            best = m
    return best


def _mul_half_spectrum(ar, ai, br, bi, conj: bool):
    """(a · b) or (a · conj(b)) on split half-spectrum planes."""
    if conj:
        return ar * br + ai * bi, ai * br - ar * bi
    return ar * br - ai * bi, ar * bi + ai * br


def poisson_multiplier_1d(n: int) -> np.ndarray:
    """The 1-D periodic Poisson symbol on the n//2+1 half-spectrum
    bins: u'' = f on [0, 2*pi) -> u_hat = -f_hat / k^2, zero mode -> 0
    (the mean-free solution — the served ``solve`` op's contract)."""
    k = np.arange(n // 2 + 1, dtype=np.float64)
    with np.errstate(divide="ignore"):
        m = np.where(k > 0, -1.0 / np.maximum(k * k, 1e-30), 0.0)
    return m.astype(np.float32)


# ------------------------------------------------ kernel-spectrum cache

_KSPEC_LOCK = threading.Lock()
_KSPEC_CACHE: dict = {}

#: bound on cached kernel spectra: per-request distinct kernels at
#: serving rates must not grow device memory without limit — past the
#: bound the least-recently-USED entry is evicted (dict insertion
#: order; hits re-append)
KSPEC_CACHE_MAX = 64


def _kernel_hash(k: np.ndarray) -> str:
    return hashlib.sha1(np.ascontiguousarray(k, np.float32)
                        .tobytes()).hexdigest()


def kernel_spectrum(k, n: int, precision: Optional[str] = None) -> tuple:
    """The half-spectrum planes of `k` zero-padded to `n`, through the
    r2c plan at n — cached per (kernel hash, n, domain, precision) so
    repeated filtering with one kernel pays ONE forward transform
    (the ``pifft_apps_kspec_cache_total`` counter says which).  The
    returned planes are device arrays; they never leave the device on
    the fused path."""
    k = np.ascontiguousarray(np.asarray(k, np.float32))
    if k.ndim != 1 or not 1 <= k.shape[0] <= n:
        raise ValueError(f"kernel must be 1-D with 1 <= len <= n={n}, "
                         f"got shape {k.shape}")
    ck = (_kernel_hash(k), n, "r2c", precision or "split3")
    with _KSPEC_LOCK:
        hit = _KSPEC_CACHE.pop(ck, None)
        if hit is not None:
            _KSPEC_CACHE[ck] = hit  # re-append: LRU recency
    if hit is not None:
        metrics.inc("pifft_apps_kspec_cache_total", result="hit")
        return hit
    metrics.inc("pifft_apps_kspec_cache_total", result="miss")
    kp = np.zeros(n, np.float32)
    kp[: k.shape[0]] = k
    rfft_plan = plans.plan_for((n,), layout="natural",
                               precision=precision, domain="r2c")
    kr, ki = rfft_plan.execute(jnp.asarray(kp), jnp.zeros(n, jnp.float32))
    with _KSPEC_LOCK:
        _KSPEC_CACHE[ck] = (kr, ki)
        while len(_KSPEC_CACHE) > KSPEC_CACHE_MAX:
            _KSPEC_CACHE.pop(next(iter(_KSPEC_CACHE)))
    return kr, ki


def kernel_spectrum_cache_clear() -> None:
    """Drop the cached kernel spectra (tests, memory pressure)."""
    with _KSPEC_LOCK:
        _KSPEC_CACHE.clear()


# ------------------------------------------------- fused circular core

#: jitted fused callables per (op, batch, n, precision, rung) — one
#: compiled program per served shape, the serving-rate discipline
#: (PIF2xx) the batcher applies to bare transforms
_FUSED_LOCK = threading.Lock()
_FUSED_CACHE: dict = {}


def _build_fused(op: str, batch: tuple, n: int,
                 precision: Optional[str]) -> tuple:
    """(traceable run(xr, xi) -> (yr, yi), forward plan) for one op at
    the transform length n over `batch` leading dims: rfft of each
    operand, the pointwise half-spectrum multiply, irfft — all inside
    ONE traced function, so the spectrum never leaves the device.

    conv/corr: ``xr`` is the signal plane(s), ``xi`` the kernel
    plane(s) (both real — the op rides the half-spectrum domain).
    solve: ``xr`` is the field, ``xi`` ignored; the multiplier is the
    1-D periodic Poisson symbol (the served solve contract; the
    richer family lives in :mod:`.pde`)."""
    shape = tuple(batch) + (n,)
    fwd = plans.plan_for(shape, layout="natural", precision=precision,
                         domain="r2c")
    # serve at the forward plan's EFFECTIVE mode: a precision
    # promotion (resilience.degrade.promote_precision) lands in the
    # plan's params, and the rebuilt fused executor must pick it up
    # for BOTH directions
    eff = fwd.effective_precision()
    inv = plans.plan_for(shape, layout="natural", precision=eff,
                         domain="c2r")
    if op == "solve":
        mult = jnp.asarray(poisson_multiplier_1d(n))

        def run(xr, xi):
            del xi  # the field is real by declaration
            ar, ai = fwd.fn(xr, jnp.zeros_like(xr))
            yr, yi = inv.fn(ar * mult, ai * mult)
            return yr, yi

        return run, fwd
    conj = op == "corr"

    def run(xr, xi):  # xr = signal plane(s), xi = kernel plane(s)
        zeros = jnp.zeros_like(xr)
        ar, ai = fwd.fn(xr, zeros)
        br, bi = fwd.fn(xi, zeros)
        pr, pi = _mul_half_spectrum(ar, ai, br, bi, conj)
        yr, yi = inv.fn(pr, pi)
        return yr, yi

    return run, fwd


def op_executor(op: str, batch: tuple, n: int,
                precision: Optional[str] = None,
                rung: Optional[str] = None) -> tuple:
    """(callable, plan) serving one op-tagged group (docs/SERVING.md):
    the fused planned pipeline by default, or a degradation rung that
    speaks the OP natively — ``jnp-fft`` via ``jnp.fft.rfft/irfft``,
    ``numpy-ref`` via a ``pure_callback`` numpy pipeline — so a
    fallback stays the same operation, just slower.  The returned
    plan is the forward r2c plan (the variant/degradation identity
    the batch outcome reports)."""
    check_op(op)
    if op == "fft":
        raise ValueError("op='fft' is the plain transform — it is "
                         "served by the plan executor, not an op "
                         "pipeline")
    shape = tuple(batch) + (n,)
    fwd_plan = plans.plan_for(shape, layout="natural",
                              precision=precision, domain="r2c")
    if rung is None:
        run, plan = _build_fused(op, tuple(batch), n, precision)
        return run, plan
    if rung == "jnp-fft":
        if op == "solve":
            mult = jnp.asarray(poisson_multiplier_1d(n))

            def jnp_solve_run(xr, xi):
                del xi
                s = jnp.fft.rfft(xr.astype(jnp.float32), axis=-1)
                y = jnp.fft.irfft(s * mult, n=n, axis=-1)
                yr = y.astype(jnp.float32)
                return yr, jnp.zeros_like(yr)

            return jnp_solve_run, fwd_plan
        conj = op == "corr"

        def jnp_conv_run(xr, xi):
            a = jnp.fft.rfft(xr.astype(jnp.float32), axis=-1)
            b = jnp.fft.rfft(xi.astype(jnp.float32), axis=-1)
            if conj:
                b = jnp.conj(b)
            y = jnp.fft.irfft(a * b, n=n, axis=-1)
            yr = y.astype(jnp.float32)
            return yr, jnp.zeros_like(yr)

        return jnp_conv_run, fwd_plan
    if rung == "numpy-ref":
        import jax

        out_shape = shape

        def host_op(ar, ai):
            yr = numpy_oracle(op, np.asarray(ar), np.asarray(ai), n)
            yr = yr.astype(np.float32)
            return yr, np.zeros_like(yr)

        out_struct = (jax.ShapeDtypeStruct(out_shape, np.float32),
                      jax.ShapeDtypeStruct(out_shape, np.float32))

        def numpy_run(xr, xi):
            return jax.pure_callback(host_op, out_struct, xr, xi)

        return numpy_run, fwd_plan
    raise ValueError(f"unknown op rung {rung!r}")


def numpy_oracle(op: str, xr, xi, n: int) -> np.ndarray:
    """The float64 numpy reference of one CIRCULAR op at n — the
    oracle the serve smokes, the precision contract sampling, and
    ``make apps-smoke`` verify against.  ``xr``/``xi`` follow the op's
    served plane contract (signal/kernel for conv+corr; field/ignored
    for solve); trailing axis is the transform axis."""
    check_op(op)
    x64 = np.asarray(xr, np.float64)
    if op == "solve":
        return np.fft.irfft(
            np.fft.rfft(x64, axis=-1)
            * poisson_multiplier_1d(n).astype(np.float64),
            n=n, axis=-1)
    k64 = np.asarray(xi, np.float64)
    spec = np.fft.rfft(x64, axis=-1) * (
        np.conj(np.fft.rfft(k64, axis=-1)) if op == "corr"
        else np.fft.rfft(k64, axis=-1))
    return np.fft.irfft(spec, n=n, axis=-1)


def _fused_circular(op: str, n: int,
                    precision: Optional[str]) -> Callable:
    """The jitted single-signal fused circular pipeline at n, cached —
    conv/corr against a PRE-TRANSFORMED kernel spectrum (the cache's
    planes ride as arguments so one compiled program serves every
    kernel)."""
    import jax

    ck = (op, n, precision or "split3")
    with _FUSED_LOCK:
        hit = _FUSED_CACHE.get(ck)
    if hit is not None:
        return hit
    shape = (n,)
    fwd = plans.plan_for(shape, layout="natural", precision=precision,
                         domain="r2c")
    inv = plans.plan_for(shape, layout="natural",
                         precision=fwd.effective_precision(),
                         domain="c2r")
    conj = op == "corr"

    def run(xp, kr, ki):
        ar, ai = fwd.fn(xp, jnp.zeros_like(xp))
        pr, pi = _mul_half_spectrum(ar, ai, kr, ki, conj)
        yr, _ = inv.fn(pr, pi)
        return yr

    fn = jax.jit(run)
    with _FUSED_LOCK:
        _FUSED_CACHE[ck] = fn
    return fn


def circular_conv(x, k, op: str = "conv",
                  precision: Optional[str] = None,
                  n: Optional[int] = None) -> np.ndarray:
    """Circular convolution (or correlation, ``op="corr"``) of real
    `x` with real `k` at ANY length ``n >= 2`` (default: len(x)) —
    the fused served primitive.  Non-pow2 lengths ride the any-length
    plan ladder (docs/PLANS.md "Arbitrary n") through the same fused
    pipeline.  The kernel spectrum comes from the cache; the
    half-spectrum product never leaves the device."""
    if op not in ("conv", "corr"):
        raise ValueError(f"circular_conv serves conv/corr, not {op!r}")
    x = np.ascontiguousarray(np.asarray(x, np.float32))
    if x.ndim != 1:
        raise ValueError(f"signal must be 1-D, got shape {x.shape}")
    n = int(n) if n is not None else x.shape[0]
    if n < 2:
        raise ValueError(f"circular length n={n} must be >= 2")
    if x.shape[0] > n:
        raise ValueError(f"signal of {x.shape[0]} exceeds n={n}")
    kr, ki = kernel_spectrum(k, n, precision)
    xp = np.zeros(n, np.float32)
    xp[: x.shape[0]] = x
    fused = _fused_circular(op, n, precision)
    with span("spectral_op", cell={"op": op, "n": n}):
        y = fused(jnp.asarray(xp), kr, ki)
        metrics.inc("pifft_apps_ops_total", op=op)
        charge_spectral_traffic(op, n)
    return np.asarray(y)


def _mode_slice(full: np.ndarray, la: int, lv: int, mode: str,
                op: str) -> np.ndarray:
    """Slice a full linear conv/corr (length la+lv-1) into numpy's
    mode windows.  ``same`` follows numpy: length max(la, lv),
    centered — with correlate's swapped-operand convention honored
    (``numpy.correlate(a, v)`` with len(v) > len(a) computes the
    reversed correlate(v, a), which shifts the same-window start by
    one when the shorter length is even)."""
    if mode == "full":
        return full
    if mode == "same":
        out_len = max(la, lv)
        if op == "corr" and lv > la:
            # reversed-swap centering: reverse(corr(v, a, same)) in
            # full_av coordinates starts at (la-1) - (la-1)//2
            start = (la - 1) - (la - 1) // 2
        else:
            start = (min(la, lv) - 1) // 2
        return full[start:start + out_len]
    if mode == "valid":
        out_len = max(la, lv) - min(la, lv) + 1
        start = min(la, lv) - 1
        return full[start:start + out_len]
    raise ValueError(f"mode={mode!r} not in ('full', 'same', 'valid')")


def fftconv(x, k, mode: str = "full",
            precision: Optional[str] = None) -> np.ndarray:
    """Linear convolution of real 1-D `x` with real 1-D `k` via the
    fused spectral pipeline — ``numpy.convolve(x, k, mode)`` parity,
    at O(n log n): pad to the CHEAPEST feasible length >=
    len(x)+len(k)-1 (cheapest_length — not next-pow2; the any-length
    ladder killed that tax), run the fused circular core (one cached
    kernel transform, the pointwise multiply on device), slice the
    mode window."""
    x = np.asarray(x, np.float32)
    k = np.asarray(k, np.float32)
    la, lv = x.shape[-1], k.shape[-1]
    n = cheapest_length(la + lv - 1)
    full = circular_conv(x, k, "conv", precision, n)[: la + lv - 1]
    return _mode_slice(full, la, lv, mode, "conv")


def fftcorr(x, k, mode: str = "full",
            precision: Optional[str] = None) -> np.ndarray:
    """Cross-correlation of real 1-D `x` with real 1-D `k` —
    ``numpy.correlate(x, k, mode)`` parity via the conjugated kernel
    spectrum (one rfft each, conj-multiply on device, one irfft).
    The negative lags live at the top of the circular buffer; the
    full window re-assembles them in numpy's order."""
    x = np.asarray(x, np.float32)
    k = np.asarray(k, np.float32)
    la, lv = x.shape[-1], k.shape[-1]
    n = cheapest_length(la + lv - 1)
    circ = circular_conv(x, k, "corr", precision, n)
    # full output lag t - (lv-1), t = 0..la+lv-2: negative lags wrap
    full = np.concatenate([circ[n - (lv - 1):], circ[:la]]) \
        if lv > 1 else circ[:la]
    return _mode_slice(full, la, lv, mode, "corr")


def solve_spectral_1d(f, precision: Optional[str] = None) -> np.ndarray:
    """The served 1-D periodic Poisson solve (op="solve"): u'' = f on
    [0, 2*pi), mean-free — one fused rfft·symbol·irfft pipeline.  The
    full solver family (3-D, Helmholtz, time-stepping) lives in
    :mod:`.pde`."""
    import jax

    f = np.ascontiguousarray(np.asarray(f, np.float32))
    n = f.shape[-1]
    ck = ("solve", (), n, precision or "split3")
    with _FUSED_LOCK:
        fn = _FUSED_CACHE.get(ck)
    if fn is None:
        run, _plan = _build_fused("solve", (), n, precision)
        fn = jax.jit(run)
        with _FUSED_LOCK:
            _FUSED_CACHE[ck] = fn
    with span("spectral_op", cell={"op": "solve", "n": n}):
        yr, _ = fn(jnp.asarray(f), jnp.zeros(n, jnp.float32))
        metrics.inc("pifft_apps_ops_total", op="solve")
        charge_spectral_traffic("solve", n)
    return np.asarray(yr)


def fftconv_unfused(x, k, mode: str = "full",
                    precision: Optional[str] = None) -> np.ndarray:
    """The DELIBERATELY UNFUSED control for the ``make apps-smoke``
    meter gate (docs/APPS.md): same math as :func:`fftconv`, but the
    half-spectrum product round-trips through HOST between the paired
    transforms — exactly the anti-pattern the fused path exists to
    kill, charged honestly as one extra spectrum round trip so the
    metered delta EXCEEDS the fused floor and the gate discriminates.
    It also deliberately keeps the OLD next-pow2 padding, so it
    doubles as the pad-to-pow2 control for the bluestein-smoke bytes
    gate (the bench ``conv_np*`` row) at non-pow2 signal lengths.
    Never serve this; it exists so the gates have a failing side."""
    from ..models.real import irfft_planes_fast, rfft_planes_fast

    x = np.asarray(x, np.float32)
    k = np.asarray(k, np.float32)
    la, lv = x.shape[-1], k.shape[-1]
    n = next_pow2(la + lv - 1)
    xp = np.zeros(n, np.float32)
    xp[:la] = x
    kp = np.zeros(n, np.float32)
    kp[:lv] = k
    ar, ai = rfft_planes_fast(jnp.asarray(xp), precision=precision)
    br, bi = rfft_planes_fast(jnp.asarray(kp), precision=precision)
    # the host round trip between the transforms — the PIF116 finding
    # shape, suppressed here because being the gate's failing control
    # is this function's entire purpose
    har, hai, hbr, hbi = (np.asarray(ar), np.asarray(ai), np.asarray(br), np.asarray(bi))  # pifft: noqa[PIF116]: the metered-fusion gate's deliberately unfused control — the host round trip IS the point
    pr = har * hbr - hai * hbi
    pi = har * hbi + hai * hbr
    yr = irfft_planes_fast(jnp.asarray(pr.astype(np.float32)),
                           jnp.asarray(pi.astype(np.float32)), n=n,
                           precision=precision)
    metrics.inc("pifft_apps_ops_total", op="conv")
    charge_spectral_traffic("conv", n, host_round_trips=1)
    full = np.asarray(yr)[: la + lv - 1]
    return _mode_slice(full, la, lv, mode, "conv")
