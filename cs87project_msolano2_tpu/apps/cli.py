"""`pifft apps {conv,corr,solve}` — the spectral operation suite's
front door and its CI smokes (docs/APPS.md).

``--smoke`` is the ``make apps-smoke`` gate, one op per invocation:

* **conv**: (1) ``fftconv`` / overlap-save parity vs the
  numpy/scipy-class oracles at 2^10..2^14 (block sweep included);
  (2) the METERED fusion gate — the ``pifft_hbm_bytes_total`` delta
  of a fused conv must sit within tolerance of the op's fused
  roofline floor while the deliberately UNFUSED control (a host
  round-trip between the transforms) exceeds it, so the gate
  actually discriminates; (3) a conv request served END TO END over
  the socket protocol — op-tagged GroupKey, coalescing asserted from
  the obs counters, a fault-injected request degrade-tagged on its
  fallback rung, the op-tagged SLO row present, every event
  schema-valid.
* **corr**: ``fftcorr`` vs ``numpy.correlate`` across modes plus the
  circular oracle, and the conjugation actually mattering (corr !=
  conv on asymmetric kernels).
* **solve**: the solver family — 1-D served solve vs its oracle,
  3-D Poisson vs the spectral reference, constant- and
  variable-coefficient Helmholtz residuals, the exact heat step —
  and the poisson3d shim still matching the family (one pipeline,
  not two).

Without ``--smoke`` the subcommand runs a small demo of the op and
prints the result summary (a quick by-hand check, not a gate).
"""

from __future__ import annotations

import argparse
import json
import sys

import numpy as np

#: parity tolerance for the float32 fused pipelines vs float64 oracles
TOL = 1e-4

#: metered-fusion gate tolerance: the fused cell must charge within
#: this factor of the fused floor (the charge IS the op's declared
#: model, so this is slack for future carry accounting, not noise)
FUSED_TOL = 1.05

SMOKE_LOGNS = (10, 12, 14)


def _parity_problems(op: str) -> list:
    """Oracle-parity sweep for one op at the smoke sizes."""
    from .spectral import fftconv, fftcorr, numpy_oracle
    from .stream import overlap_add, overlap_save

    problems = []
    rng = np.random.default_rng(0)
    for logn in SMOKE_LOGNS:
        n = 1 << logn
        x = rng.standard_normal(n).astype(np.float32)
        k = rng.standard_normal(33).astype(np.float32)
        if op == "solve":
            from .spectral import solve_spectral_1d

            got = solve_spectral_1d(x)
            ref = numpy_oracle("solve", x.astype(np.float64), None, n)
            err = float(np.max(np.abs(got - ref))
                        / max(np.max(np.abs(ref)), 1e-30))
            if err > TOL:
                problems.append(f"solve n=2^{logn}: rel err {err:.2e} "
                                f"> {TOL:.0e} vs spectral oracle")
            continue
        fn = fftconv if op == "conv" else fftcorr
        oracle = np.convolve if op == "conv" else np.correlate
        for mode in ("full", "same", "valid"):
            got = fn(x, k, mode)
            ref = oracle(x.astype(np.float64), k.astype(np.float64),
                         mode)
            err = float(np.max(np.abs(got - ref))
                        / np.max(np.abs(ref)))
            if err > TOL:
                problems.append(f"{op} n=2^{logn} mode={mode}: rel "
                                f"err {err:.2e} > {TOL:.0e} vs "
                                f"numpy.{oracle.__name__}")
        if op == "conv" and logn == SMOKE_LOGNS[0]:
            # the streaming path across block sizes, including block
            # == padded signal, block > signal, non-divisible tails
            ref = np.convolve(x.astype(np.float64),
                              k.astype(np.float64), "full")
            for block in (64, 256, n, 2 * n):
                for stitcher, name in ((overlap_save, "overlap-save"),
                                       (overlap_add, "overlap-add")):
                    y = stitcher(x, k, block=block)
                    err = float(np.max(np.abs(y - ref))
                                / np.max(np.abs(ref)))
                    if err > TOL:
                        problems.append(
                            f"{name} block={block}: rel err "
                            f"{err:.2e} > {TOL:.0e}")
    if op == "corr":
        # the conjugation must matter: an asymmetric kernel's corr
        # and conv differ — a sign bug that served conv for corr
        # would otherwise sail through symmetric-ish noise
        x = rng.standard_normal(256).astype(np.float32)
        k = np.zeros(9, np.float32)
        k[1] = 1.0
        from .spectral import fftconv as _conv

        if np.allclose(fftcorr(x, k, "full"), _conv(x, k, "full"),
                       atol=1e-3):
            problems.append("corr == conv on an asymmetric kernel — "
                            "the conjugation is not applied")
    return problems


def _fusion_gate_problems() -> list:
    """The metered-fusion gate (docs/APPS.md): read the
    pifft_hbm_bytes_total delta for a fused conv and the unfused
    control FROM THE METER and hold the fused one at the floor."""
    from .. import obs
    from ..obs import metrics
    from ..utils.roofline import spectral_min_hbm_bytes
    from .spectral import fftconv, fftconv_unfused

    problems = []
    owned = not obs.enabled()
    if owned:
        obs.enable()
    try:
        rng = np.random.default_rng(1)
        x = rng.standard_normal((1 << 12) - 32).astype(np.float32)
        k = rng.standard_normal(33).astype(np.float32)
        n_pad = 1 << 12  # cheapest_length(len(x) + len(k) - 1): the
        # lengths are chosen so the sum is exactly 2^12 — both the
        # fused path and the next-pow2 unfused control land on the
        # same n, and the gate compares like with like

        def delta(fn):
            before = metrics.counter_value("pifft_hbm_bytes_total")
            y = fn(x, k)
            return y, int(metrics.counter_value(
                "pifft_hbm_bytes_total") - before)

        y_fused, fused_bytes = delta(fftconv)
        y_unfused, unfused_bytes = delta(fftconv_unfused)
        floor = spectral_min_hbm_bytes("conv", n_pad)
        gate = int(floor * FUSED_TOL)
        if not fused_bytes:
            problems.append("fused conv charged ZERO metered bytes — "
                            "the op meter is not wired")
        elif fused_bytes > gate:
            problems.append(
                f"fused conv metered {fused_bytes} B > fused floor "
                f"{floor} B x {FUSED_TOL} — the pipeline is moving "
                f"more than the fused model (a host round trip?)")
        if unfused_bytes <= gate:
            problems.append(
                f"UNFUSED control metered {unfused_bytes} B <= the "
                f"gate bound {gate} B — the gate does not "
                f"discriminate")
        if not np.allclose(y_fused, y_unfused, atol=1e-3):
            problems.append("fused and unfused conv disagree — the "
                            "control is not computing the same thing")
    finally:
        if owned:
            obs.disable()
    return problems


def _served_conv_problems() -> list:
    """A conv request served end to end through the SOCKET protocol
    (acceptance: op-tagged GroupKey, coalesced, degrade-tagged on
    fallback, visible in SLO rows, schema-valid events)."""
    import asyncio

    from .. import obs
    from ..obs import events as obs_events
    from ..obs import metrics
    from ..resilience import inject
    from ..serve import Dispatcher, ServeConfig
    from ..serve.batcher import GroupKey
    from ..serve.protocol import handle_connection, request_over_socket
    from ..serve.shapes import ShapeSpec
    from .spectral import numpy_oracle

    problems = []
    owned = not obs.enabled()
    if owned:
        obs.enable()
    n = 1 << 10
    k_burst = 6
    rng = np.random.default_rng(2)
    spec = ShapeSpec(n=n, op="conv")
    label = GroupKey(n=n, domain="r2c", op="conv").label()
    inputs = [(rng.standard_normal(n).astype(np.float32),
               rng.standard_normal(n).astype(np.float32))
              for _ in range(k_burst)]

    async def main():
        d = Dispatcher(ServeConfig(max_wait_ms=25.0), [spec])
        await asyncio.get_running_loop().run_in_executor(None, d.warm)
        server = await asyncio.start_server(
            lambda r, w: handle_connection(d, r, w), "127.0.0.1", 0)
        port = server.sockets[0].getsockname()[1]
        replies = await asyncio.gather(*[
            request_over_socket("127.0.0.1", port, xr, xi, op="conv")
            for xr, xi in inputs])
        # one more, with the serve site armed: the batch must fall to
        # a rung that still speaks conv, degrade-tagged on the wire
        with inject("serve", "capacity", count=1):
            degraded = await request_over_socket(
                "127.0.0.1", port, inputs[0][0], inputs[0][1],
                op="conv")
        server.close()
        await server.wait_closed()
        await d.close()
        return d, replies, degraded

    try:
        d, replies, degraded = asyncio.run(main())
        for (xr, xi), rep in zip(inputs, replies):
            if not rep.get("ok"):
                problems.append(f"served conv failed: {rep}")
                break
            ref = numpy_oracle("conv", xr.astype(np.float64),
                               xi.astype(np.float64), n)
            err = float(np.max(np.abs(np.asarray(rep["yr"]) - ref))
                        / np.max(np.abs(ref)))
            if err > TOL:
                problems.append(f"served conv wrong: rel err "
                                f"{err:.2e} > {TOL:.0e}")
                break
        batches = int(metrics.counter_value(
            "pifft_serve_batches_total", shape=label))
        if not (0 < batches < k_burst):
            problems.append(
                f"no coalescing: {k_burst} concurrent conv requests "
                f"-> {batches} invocation(s) on group {label!r}")
        if not degraded.get("ok"):
            problems.append(f"fault-injected conv request FAILED "
                            f"instead of degrading: {degraded}")
        elif not degraded.get("degraded") or not degraded.get("degrade"):
            problems.append(
                f"fault-injected conv served UNTAGGED "
                f"(degraded={degraded.get('degraded')}, "
                f"trail={degraded.get('degrade')})")
        if label not in d.stats.summary():
            problems.append(f"op-tagged SLO row {label!r} missing "
                            f"from {sorted(d.stats.summary())}")
        ops_served = metrics.counter_value("pifft_serve_ops_total",
                                           op="conv")
        if ops_served < k_burst:
            problems.append(f"pifft_serve_ops_total{{op=conv}} = "
                            f"{ops_served} < {k_burst}")
        bad = [p for rec in obs_events.snapshot()
               for p in obs_events.validate_event(rec)]
        if bad:
            problems.append(f"{len(bad)} schema-invalid event(s): "
                            f"{bad[:3]}")
    finally:
        if owned:
            obs.disable()
    return problems


def _solve_family_problems() -> list:
    """The pde family beyond the served 1-D solve: 3-D Poisson vs the
    spectral reference, the poisson3d-shim equivalence, Helmholtz
    residuals (constant and variable coefficient), the exact heat
    step."""
    from .pde import (
        helmholtz_solve,
        helmholtz_solve_variable,
        poisson_solve,
        spectral_step,
    )

    problems = []
    rng = np.random.default_rng(3)
    f = rng.standard_normal((16, 16, 32)).astype(np.float32)
    f -= f.mean()
    axes = [np.fft.fftfreq(m) * m for m in f.shape]
    ksq = (axes[0][:, None, None] ** 2 + axes[1][None, :, None] ** 2
           + axes[2][None, None, :] ** 2)

    def spectral_ref(mult):
        return np.real(np.fft.ifftn(np.fft.fftn(f.astype(np.float64))
                                    * mult))

    u = np.asarray(poisson_solve(f))
    with np.errstate(divide="ignore"):
        m_poi = np.where(ksq > 0, -1.0 / np.maximum(ksq, 1e-30), 0.0)
    err = float(np.max(np.abs(u - spectral_ref(m_poi))))
    if err > TOL:
        problems.append(f"3-D poisson: abs err {err:.2e} > {TOL:.0e}")
    uh = np.asarray(helmholtz_solve(f, 2.5))
    err = float(np.max(np.abs(uh - spectral_ref(1.0 / (2.5 + ksq)))))
    if err > TOL:
        problems.append(f"helmholtz alpha=2.5: abs err {err:.2e}")
    us = np.asarray(spectral_step(f, nu=0.05, dt=0.02, steps=4))
    err = float(np.max(np.abs(
        us - spectral_ref(np.exp(-0.05 * ksq * 0.08)))))
    if err > TOL:
        problems.append(f"heat step: abs err {err:.2e}")
    alpha = (2.0 + 0.5 * np.cos(
        np.linspace(0, 2 * np.pi, 16))[:, None, None]
        * np.ones_like(f)).astype(np.float32)
    uv = np.asarray(helmholtz_solve_variable(f, alpha, iters=60))
    lap = np.real(np.fft.ifftn(np.fft.fftn(uv.astype(np.float64))
                               * (-ksq)))
    res = float(np.max(np.abs(alpha * uv - lap - f))
                / np.max(np.abs(f)))
    if res > 1e-3:
        problems.append(f"variable helmholtz residual {res:.2e} > "
                        f"1e-3 — the fixed point did not converge")
    return problems


def apps_main(argv) -> int:
    ap = argparse.ArgumentParser(
        prog="cs87project_msolano2_tpu apps",
        description="the spectral operation suite: fused conv/corr, "
                    "streaming overlap-save, the spectral PDE family "
                    "(docs/APPS.md)",
    )
    ap.add_argument("op", choices=("conv", "corr", "solve"))
    ap.add_argument("--smoke", action="store_true",
                    help="the make apps-smoke CI gate for this op: "
                         "oracle parity, the metered fusion gate "
                         "(conv), a served socket round trip (conv)")
    ap.add_argument("-n", type=int, default=1 << 12,
                    help="demo size (no --smoke)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report")
    args = ap.parse_args(argv)

    if not args.smoke:
        return _demo(args)

    problems = _parity_problems(args.op)
    checks = [f"{args.op} oracle parity at "
              + ",".join(f"2^{g}" for g in SMOKE_LOGNS)]
    if args.op == "conv":
        problems += _fusion_gate_problems()
        checks.append("metered fusion gate (fused floor vs unfused "
                      "control)")
        problems += _served_conv_problems()
        checks.append("served socket conv (op-tagged, coalesced, "
                      "degrade-tagged)")
    if args.op == "solve":
        problems += _solve_family_problems()
        checks.append("pde family (3-D poisson, helmholtz, variable "
                      "helmholtz, heat step)")

    if args.json:
        print(json.dumps({"op": args.op, "ok": not problems,
                          "checks": checks, "problems": problems},
                         indent=1, sort_keys=True))
    else:
        for p in problems:
            print(f"# FAIL: {p}", file=sys.stderr)
    if problems:
        return 1
    print(f"# apps {args.op} smoke ok ({'; '.join(checks)})",
          file=sys.stderr)
    return 0


def _demo(args) -> int:
    """The no-smoke path: run the op once and summarize."""
    rng = np.random.default_rng(0)
    n = args.n
    if args.op == "solve":
        from .spectral import solve_spectral_1d

        f = rng.standard_normal(n).astype(np.float32)
        u = solve_spectral_1d(f)
        print(f"solve: n={n} |u|_max={np.max(np.abs(u)):.4f} "
              f"mean={u.mean():.2e} (mean-free)")
        return 0
    from .spectral import fftconv, fftcorr
    from .stream import choose_block

    x = rng.standard_normal(n).astype(np.float32)
    k = rng.standard_normal(65).astype(np.float32)
    fn = fftconv if args.op == "conv" else fftcorr
    y = fn(x, k)
    print(f"{args.op}: n={n} m=65 -> {y.shape[0]} samples, "
          f"|y|_max={np.max(np.abs(y)):.4f}; streaming block choice "
          f"for m=65: {choose_block(65, n)}")
    return 0
