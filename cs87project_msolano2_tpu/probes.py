"""Capacity probes (L3) — the reference ships standalone probe executables
(how-many-cpu-cores, cpu/pthreads/how-many-cpu-cores.c:19-32, and
how-many-concurrent-blocks, gpu/cuda/how-many-concurrent-blocks.cu:34-176)
whose output the harness uses to clip its p-sweep.  TPU equivalents:

    python -m cs87project_msolano2_tpu.probes            # device count
    python -m cs87project_msolano2_tpu.probes -v         # verbose, like the
                                                         # reference's -v
    python -m cs87project_msolano2_tpu.probes --cores    # CPU cores (native)
"""

from __future__ import annotations

import argparse
import sys


def how_many_tpu_devices(verbose: bool = False) -> int:
    import jax

    devs = jax.devices()
    if verbose:
        for d in devs:
            print(f"device {d.id}: {d.device_kind} "
                  f"(platform {d.platform}, process {d.process_index})")
        print(f"addressable: {jax.local_device_count()}, "
              f"global: {jax.device_count()}, "
              f"processes: {jax.process_count()}")
    return len(devs)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description="capacity probes")
    ap.add_argument("-v", action="store_true", help="verbose device info")
    ap.add_argument("--cores", action="store_true",
                    help="print CPU core count (native probe) instead")
    args = ap.parse_args(argv)
    if args.cores:
        from .backends.cpu import num_cores

        print(num_cores())
        return 0
    print(how_many_tpu_devices(args.v))
    return 0


if __name__ == "__main__":
    sys.exit(main())
