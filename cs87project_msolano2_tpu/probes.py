"""DEPRECATED shim: the capacity probes moved into the hardware plane —
import from ``cs87project_msolano2_tpu.hw.inventory`` instead, which
unifies the device-count/core probes with the typed
:class:`~cs87project_msolano2_tpu.hw.inventory.DeviceInventory`
(docs/BACKENDS.md).

Kept so existing callers and the documented module invocation

    python -m cs87project_msolano2_tpu.probes [-v] [--cores]

keep working; new code should not import this path."""

from __future__ import annotations

import sys
import warnings

from .hw.inventory import how_many_tpu_devices, main  # noqa: F401

warnings.warn(
    "cs87project_msolano2_tpu.probes moved to "
    "cs87project_msolano2_tpu.hw.inventory; this shim will be removed",
    DeprecationWarning,
    stacklevel=2,
)

if __name__ == "__main__":
    sys.exit(main())
