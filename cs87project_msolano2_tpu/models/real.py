"""Half-spectrum real-input transforms: rfft / irfft on the pi-FFT
plan ladder (docs/REAL.md).

A length-n real signal carries half the information of a length-n
complex one, and its spectrum is Hermitian (X[n-k] = conj(X[k])), so
only the n//2+1 leading bins are worth computing, moving, or serving.
The classic pack trick turns the whole r2c transform into ONE c2c
transform at HALF the length plus an O(n) elementwise post-pass:

    z[k]  = x[2k] + i·x[2k+1]            (m = n/2 complex points)
    Z     = FFT_m(z)                      (the existing tuned c2c plan)
    A[k]  = (Z[k] + conj(Z[m-k])) / 2     (spectrum of even samples)
    B[k]  = (Z[k] - conj(Z[m-k])) / 2i    (spectrum of odd samples)
    X[k]  = A[k] + W^k · B[k],  W = e^{-2πi/n},  k = 0..m

The inverse (c2r) runs the same algebra backwards — split X into
(A, B), rebuild Z = A + i·B, one c2c inverse at m, deinterleave.

Everything here is expressed on split float32 planes (the TPU-native
representation the whole kernel family uses), and NONE of it is a new
Pallas kernel: the heavy lifting is the c2c plan at n/2 — which means
an r2c transform inherits the entire ladder (fused / fourstep /
sixstep), the autotuner, the plan cache, the degradation chain, and
the obs spans for free, while moving HALF the HBM bytes of the c2c
transform at the same n (utils/roofline.py charges it exactly that).

Dispatch goes through the plan subsystem with ``domain="r2c"`` /
``"c2r"`` keys (plans.core.PlanKey): ``plans.plan_for(shape,
domain="r2c")`` resolves the half-length c2c choice and
``plan.execute`` runs pack → kernel → merge as one traceable
executor.  The r2c executor keeps the uniform ``(xr, xi) -> (yr, yi)``
plane contract; its ``xi`` operand is ignored (the input is real by
declaration) and the c2r output's ``yi`` plane is zeros.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp


def _half_twiddles(n: int) -> tuple:
    """(cos, sin) of 2πk/n for k = 0..n/2 — the W^k factors of the
    Hermitian merge/split, built host-side in float64 and cast once
    (same discipline as ops.twiddle: trig error must not ride the
    kernel's error budget)."""
    k = np.arange(n // 2 + 1, dtype=np.float64)
    ang = 2.0 * np.pi * k / float(n)
    return (jnp.asarray(np.cos(ang), jnp.float32),
            jnp.asarray(np.sin(ang), jnp.float32))


def pack_real_planes(xr):
    """Deinterleave a real signal (..., n) into the packed complex
    planes (..., n/2): z[k] = x[2k] + i·x[2k+1]."""
    return xr[..., 0::2], xr[..., 1::2]


def unpack_real_planes(zr, zi):
    """Inverse of :func:`pack_real_planes`: interleave (..., m) planes
    back into the real signal (..., 2m)."""
    return jnp.stack([zr, zi], axis=-1).reshape(
        zr.shape[:-1] + (2 * zr.shape[-1],))


def hermitian_merge(zr, zi, n: int):
    """The O(n) r2c post-pass: packed-FFT planes (..., m) in natural
    order -> half-spectrum planes (..., m+1), m = n/2."""
    m = n // 2
    idx = jnp.arange(m + 1) % m          # Z[k], k = 0..m (Z[m] = Z[0])
    rev = (m - jnp.arange(m + 1)) % m    # Z[m-k]
    zr_k, zi_k = jnp.take(zr, idx, axis=-1), jnp.take(zi, idx, axis=-1)
    zr_r, zi_r = jnp.take(zr, rev, axis=-1), jnp.take(zi, rev, axis=-1)
    ar, ai = 0.5 * (zr_k + zr_r), 0.5 * (zi_k - zi_r)
    br, bi = 0.5 * (zi_k + zi_r), -0.5 * (zr_k - zr_r)
    c, s = _half_twiddles(n)
    return ar + c * br + s * bi, ai + c * bi - s * br


def hermitian_split(xr, xi, n: int):
    """The O(n) c2r pre-pass: half-spectrum planes (..., m+1) ->
    packed planes Z = A + i·B of length m, ready for one c2c inverse.
    Only the leading m entries of the (A, B) algebra are needed."""
    m = n // 2
    rev = m - jnp.arange(m)              # X[m-k], k = 0..m-1
    xr_k, xi_k = xr[..., :m], xi[..., :m]
    xr_r, xi_r = jnp.take(xr, rev, axis=-1), jnp.take(xi, rev, axis=-1)
    ar, ai = 0.5 * (xr_k + xr_r), 0.5 * (xi_k - xi_r)
    # W^k B[k] = (X[k] - conj(X[m-k])) / 2; undo the twiddle with W^-k
    tr, ti = 0.5 * (xr_k - xr_r), 0.5 * (xi_k + xi_r)
    c, s = _half_twiddles(n)
    c, s = c[:m], s[:m]
    br, bi = c * tr - s * ti, c * ti + s * tr
    # Z = A + i·B
    return ar - bi, ai + br


def rfft_executor(c2c_fn, n: int):
    """Wrap a natural-order c2c executor at n/2 into the r2c executor
    at n: (xr, xi) -> half-spectrum planes (..., n/2+1).  ``xi`` is
    ignored — an r2c plan's input is real by declaration."""

    def run(xr, xi):
        del xi  # real by declaration (domain="r2c")
        zr, zi = pack_real_planes(xr)
        zr, zi = c2c_fn(zr, zi)
        return hermitian_merge(zr, zi, n)

    return run


def irfft_executor(c2c_fn, n: int):
    """Wrap a natural-order c2c executor at n/2 into the c2r executor
    at n: half-spectrum planes (..., n/2+1) -> (real signal, zeros).
    The inverse c2c rides the conj trick on the same forward
    executor, so the rung/variant serving the forward serves the
    inverse too."""
    m = n // 2
    inv_m = np.float32(1.0 / m)

    def run(xr, xi):
        zr, zi = hermitian_split(xr, xi, n)
        wr, wi = c2c_fn(zr, -zi)          # IFFT_m = conj∘FFT_m∘conj / m
        yr = unpack_real_planes(wr * inv_m, -wi * inv_m)
        return yr, jnp.zeros_like(yr)

    return run


def rfft(x, precision: str | None = None, plan=None):
    """1-D real-input DFT over the trailing axis: real in, the n//2+1
    leading (non-redundant) complex bins out — ``numpy.fft.rfft``
    semantics on the plan ladder.  Any n >= 2 is served: even n rides
    the packed half-length c2c trick below; odd n a direct any-length
    plan (docs/PLANS.md, "Arbitrary n").

    Dispatches through a ``domain="r2c"`` plan (docs/REAL.md): the
    packed c2c transform at n/2 runs whatever variant the ladder
    tuned for THAT key, so rfft inherits the kernel family and the
    resilience chain with half the HBM traffic of ``fft`` at the same
    n.  `plan` pins an explicit r2c plan; `precision` picks the kernel
    precision mode exactly as in :func:`.fft.fft`.
    """
    x = jnp.asarray(x)
    if jnp.iscomplexobj(x):
        raise ValueError("rfft input must be real (the half-spectrum "
                         "contract); use fft for complex input")
    xr = x.astype(jnp.float32)
    if plan is None:
        from .. import plans

        plan = plans.plan_for(xr.shape, layout="natural",
                              precision=precision, domain="r2c")
    yr, yi = plan.execute(xr, jnp.zeros_like(xr))
    from .fft import jax_complex

    return jax_complex(yr, yi)


def irfft(x, precision: str | None = None, plan=None):
    """Inverse of :func:`rfft`: n//2+1 half-spectrum bins in, the
    length-n real signal out (``numpy.fft.irfft`` semantics; n is
    inferred as 2·(bins-1) — even by construction; pass `n` to
    :func:`irfft_planes_fast` (or pin an explicit c2r `plan`) to
    recover an odd-length signal)."""
    x = jnp.asarray(x)
    if not jnp.iscomplexobj(x):
        x = x.astype(jnp.complex64)
    n = 2 * (x.shape[-1] - 1)
    if n < 2:
        raise ValueError(f"irfft needs >= 2 half-spectrum bins, got "
                         f"shape {x.shape}")
    xr = jnp.real(x).astype(jnp.float32)
    xi = jnp.imag(x).astype(jnp.float32)
    if plan is None:
        from .. import plans

        plan = plans.plan_for(xr.shape[:-1] + (n,), layout="natural",
                              precision=precision, domain="c2r")
    yr, _ = plan.execute(xr, xi)
    return yr


def rfft_planes_fast(xr, plan=None, precision: str | None = None):
    """Plane-level r2c through the plan subsystem — the hot-path form
    (cf. fft_planes_fast): real plane(s) in, half-spectrum (yr, yi)
    planes out."""
    if plan is None:
        from .. import plans

        plan = plans.plan_for(xr.shape, layout="natural",
                              precision=precision, domain="r2c")
    return plan.execute(xr, jnp.zeros_like(xr))


def irfft_planes_fast(xr, xi, n: int | None = None, plan=None,
                      precision: str | None = None):
    """Plane-level c2r: half-spectrum planes (..., m+1) in, the real
    signal plane (..., n) out (n defaults to 2·(m+1-1))."""
    n = n if n is not None else 2 * (xr.shape[-1] - 1)
    if plan is None:
        from .. import plans

        plan = plans.plan_for(xr.shape[:-1] + (n,), layout="natural",
                              precision=precision, domain="c2r")
    yr, _ = plan.execute(xr, xi)
    return yr
