"""Direct DFT as a (vmap'd) complex einsum — the north star's second
expression of the no-communication property.

Every output bin is an independent partial sum X[k] = sum_j x[j] W^(jk):
no bin needs any other bin, so processor Pi can compute exactly its own
pi-layout segment of bins with one einsum against its replicated input —
zero communication, now in dense-matmul form, which is the formulation
the MXU natively wants (BASELINE.json north_star; config 1 is the N=1024
float64 CPU reference run of this model).

Two tiers live here:

* the O(n^2)-memory oracles ``dft_direct`` / ``dft_direct_pi`` (guarded
  by MAX_N — small-n correctness references, config 1);
* the PHASED einsum model — ``funnel_einsum_planes`` /
  ``tube_einsum_planes`` / ``pi_dft_einsum_planes`` — the full third
  backend (`-b einsum`).  It has the same funnel/tube structure as the
  butterfly backends, resting on the polyphase identity (verified in
  tests):  funnel(pi, j) = sum_m x[m*s+j] * W_n^{rev(pi)*(m*s+j)} — the
  funnel IS a (p, p, s)-coefficient einsum against the blocked input —
  and the tube is the segment-local DIF matrix  B[k, j] =
  W_s^{rev_s(k)*j},  generated blockwise on the fly inside a ``lax.scan``
  (exact integer angle indices, MXU contraction), so memory stays
  O(block * s) at any n.  Phase timers are honest on both phases —
  reference parity with the Xeon Phi backend's full phased run
  (…openmp.c:291-441).
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax.numpy as jnp
import numpy as np

from ..ops.bits import bit_reverse_indices

MAX_N = 1 << 13  # W is n^2 complex entries; 8192^2 * 8 B = 512 MB


def _einsum_f32(spec: str, a, b):
    """jnp.einsum pinned to Precision.HIGHEST (XLA's full f32 matmul
    emulation on the MXU).

    The MXU's DEFAULT single-pass bf16 einsum measures ~2e-3 relative
    error on these dense contractions — the first on-chip einsum verify
    failed exactly there — and the 3-pass bf16 error split (the pallas
    tail's SPLIT3) has a ~2^-16 operand-representation floor that put
    the p=1 identity funnel at 5e-5, over the 1e-5 bound.  Unlike the
    pallas tail (where HIGHEST was the single largest cost in the whole
    transform), the einsum phases are twiddle-GATHER-bound on the
    accelerator (~34 GB gather vs ~0.2 s of even-HIGHEST MXU work per
    blocked tube application at s=2^16; measured timing shift between
    precision modes < 10%, within run noise), so full precision is the
    right trade here."""
    import jax

    return jnp.einsum(spec, a, b, precision=jax.lax.Precision.HIGHEST)


# funnel coefficient planes hold p*n floats x2; 2^24 = 128 MB — beyond
# that the (n, p) combination is out of the einsum backend's capacity
COEF_MAX_ENTRIES = 1 << 24
# full-period twiddle tables are m floats x2 (host f64 trig, f32 stored)
FULL_TABLE_MAX = 1 << 20


@lru_cache(maxsize=16)
def dft_matrix(n: int, dtype=np.complex64) -> np.ndarray:
    """W[k, j] = exp(-2 pi i j k / n), float64 trig then cast."""
    if n > MAX_N:
        raise ValueError(f"direct DFT capped at n={MAX_N} (O(n^2) memory)")
    k = np.arange(n)
    return np.exp(-2j * np.pi * np.outer(k, k) / n).astype(dtype)


def dft_direct(x, dtype=np.complex64):
    """X = W @ x over the trailing axis (natural order).

    dtype=np.complex128 is BASELINE.json config 1 (the N=1024 float64 CPU
    reference run) and is computed with numpy on the host — JAX defaults
    to 32-bit and this path is an oracle, not a device hot path."""
    if dtype == np.complex128:
        x = np.asarray(x, dtype=np.complex128)
        return np.einsum("kj,...j->...k", dft_matrix(x.shape[-1], dtype), x)
    import jax

    x = jnp.asarray(x)
    n = x.shape[-1]
    w = jnp.asarray(dft_matrix(n, dtype))
    return jnp.einsum("kj,...j->...k", w, x.astype(w.dtype),
                      precision=jax.lax.Precision.HIGHEST)


def dft_direct_pi(x, p: int = 1, dtype=np.complex64):
    """The pi-decomposed einsum: processor Pi computes only the bins of
    its pi-layout segment.  Returns the pi-layout result (..., n) —
    identical layout to the butterfly models', so the whole verification
    stack applies unchanged.

    Internally a vmap-style batched einsum: W's rows are gathered into
    (p, n/p, n) so row block Pi holds exactly Pi's bins — each block's
    contraction touches only the (replicated) input.
    """
    import jax

    x = jnp.asarray(x)
    n = x.shape[-1]
    w = dft_matrix(n, dtype)[bit_reverse_indices(n)]  # pi-layout bin order
    w_blocks = jnp.asarray(w.reshape(p, n // p, n))
    y = jnp.einsum("psj,...j->...ps", w_blocks, x.astype(w_blocks.dtype),
                   precision=jax.lax.Precision.HIGHEST)
    return y.reshape(*x.shape[:-1], n)


@lru_cache(maxsize=8)
def full_twiddle(m: int) -> tuple[np.ndarray, np.ndarray]:
    """(wr, wi) full-period table W_m^j = exp(-2*pi*i*j/m), j in [0, m)."""
    if m > FULL_TABLE_MAX:
        raise ValueError(f"full twiddle table capped at m={FULL_TABLE_MAX}")
    j = np.arange(m, dtype=np.float64)
    ang = -2.0 * np.pi * j / m
    return np.cos(ang).astype(np.float32), np.sin(ang).astype(np.float32)


@lru_cache(maxsize=8)
def funnel_coeff_planes(n: int, p: int) -> tuple[np.ndarray, np.ndarray]:
    """C[pi, m, j] = W_n^{rev(pi) * (m*s + j)} as (p, p, s) float32 planes.

    The funnel's linear map in closed form (polyphase identity, module
    docstring).  Exact integer angle indices, float64 host trig.
    """
    if p * n > COEF_MAX_ENTRIES:
        raise ValueError(
            f"einsum funnel coefficients need p*n <= {COEF_MAX_ENTRIES} "
            f"(got p={p}, n={n})"
        )
    rev = bit_reverse_indices(p).astype(np.int64)
    i = np.arange(n, dtype=np.int64)
    idx = (rev[:, None] * i[None, :]) % n  # (p, n), exact in int64
    wr, wi = full_twiddle(n)
    s = n // p
    return wr[idx].reshape(p, p, s), wi[idx].reshape(p, p, s)


def funnel_einsum_planes(xr, xi, p: int):
    """Funnel phase as one coefficient-tensor einsum.

    xr/xi: (..., n) -> (..., p, s) pi-layout funnel planes — numerically
    the same map as models.pi_fft.funnel (tests assert < 1e-5), computed
    as four real contractions against the replicated blocked input.
    """
    n = xr.shape[-1]
    cr, ci = (jnp.asarray(t) for t in funnel_coeff_planes(n, p))
    xbr = xr.reshape(*xr.shape[:-1], p, n // p)
    xbi = xi.reshape(*xi.shape[:-1], p, n // p)
    spec = "pmj,...mj->...pj"
    yr = _einsum_f32(spec, cr, xbr) - _einsum_f32(spec, ci, xbi)
    yi = _einsum_f32(spec, cr, xbi) + _einsum_f32(spec, ci, xbr)
    return yr, yi


def _tube_rows_apply(sr, si, kb, s: int):
    """Shared core of the scan tube and the host-blocked tube: generate
    the DIF-matrix rows for output indices `kb` (already bit-reversed)
    and contract them against the (..., s) planes.

    Angle index (kb * j) mod s is computed with wrapping int32
    multiplies — exact because s is a power of two, so the low bits of
    the wrapped product ARE the mod — then gathered from the full-period
    table; the four real '...j,kj->...k' einsums are the complex
    contraction, MXU work.  Returns (..., len(kb)) planes."""
    wr_t, wi_t = (jnp.asarray(t) for t in full_twiddle(s))
    j = jnp.arange(s, dtype=jnp.int32)
    idx = (kb[:, None] * j[None, :]) & jnp.int32(s - 1)
    wr, wi = wr_t[idx], wi_t[idx]
    spec = "...j,kj->...k"
    yr = _einsum_f32(spec, sr, wr) - _einsum_f32(spec, si, wi)
    yi = _einsum_f32(spec, sr, wi) + _einsum_f32(spec, si, wr)
    return yr, yi


def _tube_rows_scan(sr, si, kb, s: int, block: int | None = None):
    """_tube_rows_apply streamed over row sub-blocks of `kb` with a
    lax.scan, keeping the materialized (block, s) twiddle gather at
    ~2^22 entries regardless of how many output rows are requested.
    Returns (..., len(kb)) planes."""
    import jax

    nrows = kb.shape[0]
    if block is None:
        block = max(min(nrows, (1 << 22) // s), 1)
    if block >= nrows:
        return _tube_rows_apply(sr, si, kb, s)
    if nrows % block:
        raise ValueError(
            f"tube block={block} must divide the {nrows} requested rows "
            "(auto-chosen blocks are powers of two and always do)"
        )

    def step(carry, kb_blk):
        return carry, _tube_rows_apply(sr, si, kb_blk, s)

    _, (yrs, yis) = jax.lax.scan(step, None, kb.reshape(nrows // block, block))
    # (nsteps, ..., block) -> (..., nrows): blocks are consecutive rows
    yr = jnp.moveaxis(yrs, 0, -2).reshape(*sr.shape[:-1], nrows)
    yi = jnp.moveaxis(yis, 0, -2).reshape(*si.shape[:-1], nrows)
    return yr, yi


def tube_einsum_planes(sr, si, n: int, p: int, block: int | None = None):
    """Tube phase as a blockwise dense einsum: per-segment s-point DIF
    matrix B[k, j] = W_s^{rev_s(k) * j} applied over the trailing axis.

    sr/si: (..., s) -> (..., s).  B rows are generated on the fly inside
    a lax.scan over output-row blocks (_tube_rows_scan).  Memory
    O(block * s) at any n; the contraction itself is MXU work.
    """
    s = sr.shape[-1]
    if s == 1:
        return sr, si
    revk = jnp.asarray(bit_reverse_indices(s).astype(np.int32))
    return _tube_rows_scan(sr, si, revk, s, block)


def tube_einsum_block(sr, si, k0, n: int, p: int, kblock: int):
    """One host-driven slice of the dense tube: output rows
    [k0, k0 + kblock) of every segment's s-point DIF.

    The blockwise-scan tube (tube_einsum_planes) is ONE device program
    whose total twiddle-gather traffic is Theta(s^2) — past s = 2^14
    that exceeds the relay's single-program budget and crashes the TPU
    worker (see backends/jax_backend.py::EINSUM_TUBE_MAX_S).  Splitting
    across MULTIPLE programs lifts the capacity: each call does
    Theta(kblock * s) work, and `k0` is a TRACED scalar so one compiled
    program serves every block of a segment length (s // kblock host
    calls per application, not s // kblock compiles).

    sr/si: (..., s) planes -> (..., kblock) planes of rows k0..k0+kblock.

    Internally streamed by _tube_rows_scan so the materialized twiddle
    gather stays at ~2^22 entries: kblock bounds the program's TOTAL
    work for the relay budget, while the scan bounds its PEAK memory
    (at s=2^15 a one-shot gather would be 2^28-entry/1 GB tensors).
    """
    import jax

    s = sr.shape[-1]
    revk_all = jnp.asarray(bit_reverse_indices(s).astype(np.int32))
    kb = jax.lax.dynamic_slice(revk_all, (k0,), (kblock,))
    return _tube_rows_scan(sr, si, kb, s)


def tube_einsum_planes_hostblocked(sr, si, n: int, p: int, kblock: int,
                                   block_fn=None):
    """Full dense tube as a HOST loop over tube_einsum_block programs —
    the capacity-lifting path for segments too long for one relay
    program.  Each iteration dispatches the same compiled block program
    at a different k0; results concatenate along the row axis (blocks
    are consecutive bit-reversed-order output rows, exactly the scan
    tube's layout).  `block_fn` lets the backend pass a jitted
    tube_einsum_block."""
    s = sr.shape[-1]
    if s % kblock:
        raise ValueError(f"kblock={kblock} must divide s={s}")
    if block_fn is None:
        block_fn = partial(tube_einsum_block, n=n, p=p, kblock=kblock)
    parts = [block_fn(sr, si, k0) for k0 in range(0, s, kblock)]
    yr = jnp.concatenate([pr for pr, _ in parts], axis=-1)
    yi = jnp.concatenate([pi_ for _, pi_ in parts], axis=-1)
    return yr, yi


def pi_dft_einsum_planes(xr, xi, p: int):
    """Full phased einsum pi-DFT: funnel einsum then tube einsum, output
    in pi layout — layout-identical to the butterfly models, so the whole
    verification stack applies unchanged."""
    n = xr.shape[-1]
    fr, fi = funnel_einsum_planes(xr, xi, p)
    tr, ti = tube_einsum_planes(fr, fi, n, p)
    return (
        tr.reshape(*xr.shape[:-1], n),
        ti.reshape(*xi.shape[:-1], n),
    )


def dft_direct_pi_planes(xr, xi, p: int = 1):
    """dft_direct_pi on split float32 planes — all-float einsums (four
    real contractions), so it composes with lax loops on backends whose
    While lowering lacks complex support (the axon relay)."""
    n = xr.shape[-1]
    w = dft_matrix(n, np.complex64)[bit_reverse_indices(n)].reshape(p, n // p, n)
    wr = jnp.asarray(np.ascontiguousarray(w.real))
    wi = jnp.asarray(np.ascontiguousarray(w.imag))
    spec = "psj,...j->...ps"
    yr = _einsum_f32(spec, wr, xr) - _einsum_f32(spec, wi, xi)
    yi = _einsum_f32(spec, wr, xi) + _einsum_f32(spec, wi, xr)
    return (
        yr.reshape(*xr.shape[:-1], n),
        yi.reshape(*xi.shape[:-1], n),
    )
