"""Direct DFT as a (vmap'd) complex einsum — the north star's second
expression of the no-communication property.

Every output bin is an independent partial sum X[k] = sum_j x[j] W^(jk):
no bin needs any other bin, so processor Pi can compute exactly its own
pi-layout segment of bins with one einsum against its replicated input —
zero communication, now in dense-matmul form, which is the formulation
the MXU natively wants (BASELINE.json north_star; config 1 is the N=1024
float64 CPU reference run of this model).

Quadratic in n, so it is an oracle / small-n model, not the hot path:
`capacity`-style guard at MAX_N (the O(n log n) butterfly models take
over beyond it).
"""

from __future__ import annotations

from functools import lru_cache

import jax.numpy as jnp
import numpy as np

from ..ops.bits import bit_reverse_indices

MAX_N = 1 << 13  # W is n^2 complex entries; 8192^2 * 8 B = 512 MB


@lru_cache(maxsize=16)
def dft_matrix(n: int, dtype=np.complex64) -> np.ndarray:
    """W[k, j] = exp(-2 pi i j k / n), float64 trig then cast."""
    if n > MAX_N:
        raise ValueError(f"direct DFT capped at n={MAX_N} (O(n^2) memory)")
    k = np.arange(n)
    return np.exp(-2j * np.pi * np.outer(k, k) / n).astype(dtype)


def dft_direct(x, dtype=np.complex64):
    """X = W @ x over the trailing axis (natural order).

    dtype=np.complex128 is BASELINE.json config 1 (the N=1024 float64 CPU
    reference run) and is computed with numpy on the host — JAX defaults
    to 32-bit and this path is an oracle, not a device hot path."""
    if dtype == np.complex128:
        x = np.asarray(x, dtype=np.complex128)
        return np.einsum("kj,...j->...k", dft_matrix(x.shape[-1], dtype), x)
    x = jnp.asarray(x)
    n = x.shape[-1]
    w = jnp.asarray(dft_matrix(n, dtype))
    return jnp.einsum("kj,...j->...k", w, x.astype(w.dtype))


def dft_direct_pi(x, p: int = 1, dtype=np.complex64):
    """The pi-decomposed einsum: processor Pi computes only the bins of
    its pi-layout segment.  Returns the pi-layout result (..., n) —
    identical layout to the butterfly models', so the whole verification
    stack applies unchanged.

    Internally a vmap-style batched einsum: W's rows are gathered into
    (p, n/p, n) so row block Pi holds exactly Pi's bins — each block's
    contraction touches only the (replicated) input.
    """
    x = jnp.asarray(x)
    n = x.shape[-1]
    w = dft_matrix(n, dtype)[bit_reverse_indices(n)]  # pi-layout bin order
    w_blocks = jnp.asarray(w.reshape(p, n // p, n))
    y = jnp.einsum("psj,...j->...ps", w_blocks, x.astype(w_blocks.dtype))
    return y.reshape(*x.shape[:-1], n)


def dft_direct_pi_planes(xr, xi, p: int = 1):
    """dft_direct_pi on split float32 planes — all-float einsums (four
    real contractions), so it composes with lax loops on backends whose
    While lowering lacks complex support (the axon relay)."""
    n = xr.shape[-1]
    w = dft_matrix(n, np.complex64)[bit_reverse_indices(n)].reshape(p, n // p, n)
    wr = jnp.asarray(np.ascontiguousarray(w.real))
    wi = jnp.asarray(np.ascontiguousarray(w.imag))
    yr = jnp.einsum("psj,...j->...ps", wr, xr) - jnp.einsum(
        "psj,...j->...ps", wi, xi
    )
    yi = jnp.einsum("psj,...j->...ps", wr, xi) + jnp.einsum(
        "psj,...j->...ps", wi, xr
    )
    return (
        yr.reshape(*xr.shape[:-1], n),
        yi.reshape(*xi.shape[:-1], n),
    )
