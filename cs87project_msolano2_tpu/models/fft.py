"""Natural-order FFT APIs on top of the pi decomposition.

These are the user-facing transforms (complex64 in, complex64 out, natural
frequency order) — what ``jnp.fft`` users reach for, built on the same
funnel/tube stages the benchmarks measure.  The bit-reversal gather lives
here, at the API boundary, never inside the timed phases.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..ops.bits import bit_reverse_indices
from .pi_fft import pi_fft_pi_layout


def fft(x, p: int = 1, tables=None):
    """1-D DFT over the trailing axis (complex in/out, natural order).

    `p` chooses the virtual-processor decomposition; the result is
    p-invariant (that is the paper's claim, and tests assert it).  At
    the default p=1 with a kernel-eligible shape the transform runs on
    the Pallas tile kernel (fft_planes_fast); an explicit p keeps the
    stage-by-stage pi decomposition so the virtual-processor structure
    stays inspectable.
    """
    x = jnp.asarray(x)
    if not jnp.iscomplexobj(x):
        x = x.astype(jnp.complex64)
    n = x.shape[-1]
    xr = jnp.real(x).astype(jnp.float32)
    xi = jnp.imag(x).astype(jnp.float32)
    if p == 1 and tables is None and _pallas_rows_ok(xr.shape):
        from ..ops.pallas_fft import fft_rows_pallas

        yr, yi = fft_rows_pallas(xr, xi)
        return jax_complex(yr, yi)
    yr, yi = pi_fft_pi_layout(xr, xi, p, tables)
    idx = jnp.asarray(bit_reverse_indices(n))
    yr = jnp.take(yr, idx, axis=-1)
    yi = jnp.take(yi, idx, axis=-1)
    return jax_complex(yr, yi)


def ifft(x, p: int = 1, tables=None):
    """Inverse DFT via conjugation: ifft(x) = conj(fft(conj(x))) / n."""
    x = jnp.asarray(x)
    n = x.shape[-1]
    return jnp.conj(fft(jnp.conj(x), p, tables)) / n


def fft2(x, p: int = 1):
    """2-D DFT over the trailing two axes via row then column 1-D passes."""
    y = fft(x, p)
    y = jnp.swapaxes(y, -1, -2)
    y = fft(y, p)
    return jnp.swapaxes(y, -1, -2)


def fftn(x, axes=None, p: int = 1):
    """N-D DFT over `axes` (default: all) via successive 1-D passes."""
    x = jnp.asarray(x)
    if axes is None:
        axes = range(x.ndim)
    y = x
    for ax in axes:
        y = jnp.moveaxis(fft(jnp.moveaxis(y, ax, -1), p), -1, ax)
    return y


def jax_complex(re, im):
    return re.astype(jnp.complex64) + 1j * im.astype(jnp.complex64)


def fft_planes(xr, xi, p: int = 1, tables=None):
    """Natural-order DFT on split re/im float32 planes (trailing axis).

    The plane-level core the complex `fft` wraps.  Exposed because (a)
    float planes are the TPU-native representation end-to-end, and (b)
    the axon relay's While-loop lowering lacks complex support, so
    anything that must run inside `lax.fori_loop` (loop-slope timing,
    iterative solvers) uses these.
    """
    n = xr.shape[-1]
    yr, yi = pi_fft_pi_layout(xr, xi, p, tables)
    idx = jnp.asarray(bit_reverse_indices(n))
    return jnp.take(yr, idx, axis=-1), jnp.take(yi, idx, axis=-1)


def ifft_planes(xr, xi, p: int = 1, tables=None):
    """Inverse DFT on planes: conj trick, all-float."""
    n = xr.shape[-1]
    yr, yi = fft_planes(xr, -xi, p, tables)
    return yr / n, -yi / n


def _pallas_rows_ok(shape) -> bool:
    import math

    from ..ops.pallas_fft import rows_plan_feasible

    n = shape[-1]
    return rows_plan_feasible(math.prod(shape[:-1]) or 1, n)


def fft_planes_fast(xr, xi, natural: bool = True):
    """fft_planes with the batched Pallas tile kernel on the hot path.

    The parallel configs (batched / 2-D / Poisson) previously ran
    unrolled jnp stages plus a bit-reverse gather per pass — ~10x under
    the flagship kernel (VERDICT r4 item 2).  Any stack of
    power-of-two rows 128..2^16 long goes through ops.pallas_fft.
    fft_rows_pallas (each row one in-VMEM DIF); other shapes fall back
    to the jnp path.  `natural=False` returns pi layout (per-row
    bit-reversed), skipping the gather pass for pipelines that don't
    need ordering — only valid on the kernel path, so it requires a
    kernel-eligible n.
    """
    if _pallas_rows_ok(xr.shape):
        from ..ops.pallas_fft import fft_rows_pallas

        return fft_rows_pallas(xr, xi, natural=natural)
    if not natural:
        raise ValueError(
            f"pi-layout output requires a kernel-eligible shape "
            f"(power-of-two trailing axis 128..65536 with a Mosaic-legal "
            f"row grouping), got {xr.shape}")
    return fft_planes(xr, xi)


def ifft_planes_fast(xr, xi):
    """Inverse of fft_planes_fast (conj trick, same dispatch)."""
    n = xr.shape[-1]
    yr, yi = fft_planes_fast(xr, -xi)
    return yr / n, -yi / n
