"""Natural-order FFT APIs on top of the pi decomposition.

These are the user-facing transforms (complex64 in, complex64 out, natural
frequency order) — what ``jnp.fft`` users reach for, built on the same
funnel/tube stages the benchmarks measure.  The bit-reversal gather lives
here, at the API boundary, never inside the timed phases.  Real inputs
have a cheaper door: :mod:`.real` (``rfft``/``irfft``) computes only the
non-redundant half-spectrum and moves half the HBM bytes
(docs/REAL.md).

Dispatch goes through the plan subsystem (:mod:`..plans`):
``plans.plan_for(shape)`` resolves the kernel variant + parameters for
this (device kind, n, batch, layout, precision) key — a cached tuned
winner when one exists, measured-good static defaults otherwise — and
``plan.execute`` is the single dispatch point.  There is no per-call
variant retry anywhere on this path.

Precision (the documented escape hatch — previously the only opt-out
from the kernel's bf16-split tail was an undocumented ``tables=``
workaround):

* ``precision=None`` / ``"split3"`` — the default error-compensated
  3-pass bf16 tail, rel err ~4e-6 (ops.pallas_fft.SPLIT3);
* ``"highest"`` — XLA's 6-pass f32 emulation on the MXU tail (~2x the
  tile-pass cost, bit-tighter accuracy);
* ``"fp32"`` — the all-float32 jnp stage path: no MXU tail at all, full
  f32 end to end (what ``fft`` always did before the kernel dispatch).
"""

from __future__ import annotations

import jax.numpy as jnp

from ..ops.bits import bit_reverse_indices
from .pi_fft import pi_fft_pi_layout


def fft(x, p: int = 1, tables=None, plan=None, precision: str | None = None):
    """1-D DFT over the trailing axis (complex in/out, natural order).

    `p` chooses the virtual-processor decomposition; the result is
    p-invariant (that is the paper's claim, and tests assert it).  At
    the default p=1 the transform dispatches through the plan subsystem
    (``plans.plan_for``): the Pallas kernel family on kernel-eligible
    shapes, the jnp stage path elsewhere.  An explicit `p` (or a
    `tables` override) keeps the stage-by-stage pi decomposition so the
    virtual-processor structure stays inspectable.

    `plan` pins an explicit ``plans.Plan``; `precision` picks the
    kernel precision mode ("split3" default / "highest" / "fp32" — see
    module docstring).  Both apply to the p=1 plan path only.
    """
    x = jnp.asarray(x)
    if not jnp.iscomplexobj(x):
        x = x.astype(jnp.complex64)
    xr = jnp.real(x).astype(jnp.float32)
    xi = jnp.imag(x).astype(jnp.float32)
    if p == 1 and tables is None:
        from .. import plans

        pl = plan if plan is not None else plans.plan_for(
            xr.shape, layout="natural", precision=precision)
        yr, yi = pl.execute(xr, xi)
        return jax_complex(yr, yi)
    n = x.shape[-1]
    yr, yi = pi_fft_pi_layout(xr, xi, p, tables)
    idx = jnp.asarray(bit_reverse_indices(n))
    yr = jnp.take(yr, idx, axis=-1)
    yi = jnp.take(yi, idx, axis=-1)
    return jax_complex(yr, yi)


def ifft(x, p: int = 1, tables=None, plan=None,
         precision: str | None = None):
    """Inverse DFT via conjugation: ifft(x) = conj(fft(conj(x))) / n."""
    x = jnp.asarray(x)
    n = x.shape[-1]
    return jnp.conj(fft(jnp.conj(x), p, tables, plan, precision)) / n


def fft2(x, p: int = 1, precision: str | None = None):
    """2-D DFT over the trailing two axes via row then column 1-D passes.
    Each pass resolves its own per-shape plan (the two axes may differ),
    so large axes pick up the large-n kernel family automatically."""
    y = fft(x, p, precision=precision)
    y = jnp.swapaxes(y, -1, -2)
    y = fft(y, p, precision=precision)
    return jnp.swapaxes(y, -1, -2)


def fftn(x, axes=None, p: int = 1, precision: str | None = None):
    """N-D DFT over `axes` (default: all) via successive 1-D passes."""
    x = jnp.asarray(x)
    if axes is None:
        axes = range(x.ndim)
    y = x
    for ax in axes:
        y = jnp.moveaxis(
            fft(jnp.moveaxis(y, ax, -1), p, precision=precision), -1, ax)
    return y


def jax_complex(re, im):
    return re.astype(jnp.complex64) + 1j * im.astype(jnp.complex64)


def fft_planes(xr, xi, p: int = 1, tables=None):
    """Natural-order DFT on split re/im float32 planes (trailing axis).

    The all-float32 jnp stage core — the plan subsystem's "jnp" variant
    and the ``precision="fp32"`` escape hatch.  Exposed because (a)
    float planes are the TPU-native representation end-to-end, and (b)
    the axon relay's While-loop lowering lacks complex support, so
    anything that must run inside `lax.fori_loop` (loop-slope timing,
    iterative solvers) uses these.
    """
    n = xr.shape[-1]
    yr, yi = pi_fft_pi_layout(xr, xi, p, tables)
    idx = jnp.asarray(bit_reverse_indices(n))
    return jnp.take(yr, idx, axis=-1), jnp.take(yi, idx, axis=-1)


def ifft_planes(xr, xi, p: int = 1, tables=None):
    """Inverse DFT on planes: conj trick, all-float."""
    n = xr.shape[-1]
    yr, yi = fft_planes(xr, -xi, p, tables)
    return yr / n, -yi / n


def fft_planes_fast(xr, xi, natural: bool = True, plan=None,
                    precision: str | None = None):
    """Plane-level FFT through the plan subsystem — the hot path the
    parallel configs (batched / 2-D / Poisson) build on.

    The plan for this shape's key picks the kernel: any stack of
    power-of-two rows 128..2^16 long runs ops.pallas_fft.fft_rows_pallas
    (each row one in-VMEM DIF), large 1-D transforms the composed
    whole-FFT paths on hardware, everything else the jnp stage path.
    `natural=False` returns pi layout (per-row bit-reversed), skipping
    the gather pass for pipelines that don't need ordering — only valid
    on a kernel path, so it requires a kernel-eligible shape.
    """
    if plan is None:
        from .. import plans

        plan = plans.plan_for(
            xr.shape, layout="natural" if natural else "pi",
            precision=precision)
    return plan.execute(xr, xi)


def ifft_planes_fast(xr, xi, plan=None, precision: str | None = None):
    """Inverse of fft_planes_fast (conj trick, same plan dispatch)."""
    if plan is None:
        from .. import plans

        plan = plans.plan_for(xr.shape, layout="natural",
                              precision=precision)
    return plan.execute_inverse(xr, xi)
