"""L2 transforms: the pi-FFT decomposition and the natural-order FFT APIs."""

from .pi_fft import funnel, tube, pi_fft_pi_layout  # noqa: F401
from .fft import fft, ifft, fft2, fftn  # noqa: F401
from .real import irfft, rfft  # noqa: F401
