"""The pi-FFT: the communication-free funnel/tube decomposition as pure,
jittable JAX functions.

Semantics (identical to the native core, see native/pifft_core.c and the
reference algorithm …pthreads.c:388-512): for N = 2^m inputs and P = 2^k
virtual processors,

* ``funnel``: log2(P) replicated half-butterfly stages.  Processor Pi
  keeps, at stage i, the half of its current working set selected by bit
  (k-1-i) of Pi, halving the working set N -> N/2 -> ... -> N/P.  Here
  all P processors are materialized as rows of one array, so the funnel
  is a dense (P, len) computation — on one TPU core this expresses the
  paper's *redundant-compute-instead-of-communication* trade literally;
  across chips the same code runs with a scalar Pi per device
  (parallel/pi_shard.py) and needs no collectives at all.
* ``tube``: log2(N/P) full DIF stages confined to each row's segment.

The concatenation of the P segments is the global DIF output = the DFT in
bit-reversed index order ("pi layout").  Unscrambling is a separate
``jnp.take`` gather, kept off the hot path exactly like the reference's
test-mode-only gather (…pthreads.c:496-499).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..obs.spans import span as _span
from ..ops.bits import ilog2
from ..ops.butterfly import stage_full, stage_half


def _tables_for(n, tables):
    if tables is None:
        from ..ops.twiddle import twiddle_tables

        return twiddle_tables(n)
    return tables


def funnel(xr, xi, p, tables=None):
    """Replicated funnel phase.  xr/xi: (..., n) -> (..., p, n // p).

    The phase runs under an observability span (``annotate=True`` also
    names it via ``jax.profiler.TraceAnnotation``, so a captured XProf
    trace shows "funnel" as a named region); when the obs subsystem is
    disabled the span is a shared no-op.  Under jit the span covers
    TRACE time, not device time — docs/OBSERVABILITY.md."""
    n = xr.shape[-1]
    with _span("funnel", cell={"n": n, "p": p}, annotate=True):
        k = ilog2(p)
        tables = _tables_for(n, tables)
        cr = jnp.broadcast_to(xr[..., None, :], (*xr.shape[:-1], p, n))
        ci = jnp.broadcast_to(xi[..., None, :], (*xi.shape[:-1], p, n))
        pis = jnp.arange(p, dtype=jnp.int32)[:, None]  # (p, 1)
        for i in range(k):
            wr, wi = tables[i]
            bottom = (pis >> (k - 1 - i)) & 1
            cr, ci = stage_half(cr, ci, jnp.asarray(wr), jnp.asarray(wi),
                                bottom)
        return cr, ci


def funnel_single(xr, xi, pi, p, tables=None):
    """Funnel for ONE processor with traced scalar id `pi` (shard_map path).

    xr/xi: (..., n) -> (..., n // p).  Identical math to `funnel` but the
    half choice is a scalar select, so each device touches only its own
    shrinking chain — zero inter-device communication.
    """
    n = xr.shape[-1]
    k = ilog2(p)
    tables = _tables_for(n, tables)
    cr, ci = xr, xi
    pi = jnp.asarray(pi, dtype=jnp.int32)
    for i in range(k):
        wr, wi = tables[i]
        bottom = (pi >> (k - 1 - i)) & 1
        cr, ci = stage_half(cr, ci, jnp.asarray(wr), jnp.asarray(wi), bottom)
    return cr, ci


def tube(sr, si, n, p, tables=None):
    """Segment-local tube phase: full DIF FFT over the trailing axis.

    sr/si: (..., s) with s = n // p; the trailing axis is one processor's
    segment.  Twiddle levels continue where the funnel stopped (level
    log2(p) of the n-point plan — segment-local butterflies of an n-point
    transform use the same tables as a standalone s-point transform, which
    is why zero communication works).
    """
    with _span("tube", cell={"n": n, "p": p}, annotate=True):
        k = ilog2(p)
        s = sr.shape[-1]
        tables = _tables_for(n, tables)
        for i in range(ilog2(s)):
            wr, wi = tables[k + i]
            sr, si = stage_full(sr, si, jnp.asarray(wr), jnp.asarray(wi))
        return sr, si


def resolve_tube_plan(shape, plan=None, precision=None,
                      min_segment=None):
    """THE tube-plan resolution, shared by :func:`tube_planned` and the
    sharded paths (parallel/pi_shard.py) so the fallback policy exists
    once: an explicit Plan passes through, ``False`` pins the jnp tube,
    None resolves per `shape` — returning None (jnp tube) when the
    segment is at or below `min_segment`, when the plan layer has no
    kernel for the shape (non-eligible batch/row geometry raises
    ValueError), or when it would serve the jnp variant (no pi-layout
    jnp path exists).

    Resolution itself sits under the resilience discipline: a CAPACITY
    or PERMANENT fault while resolving (injection site ``resolve``, or
    a plan layer dying on a real backend) DEGRADES to the jnp tube with
    a ``plans.warn`` diagnostic instead of killing the sharded caller;
    TRANSIENT faults re-raise for the retry layer.  Kernel faults
    during plan EXECUTION are handled further down, by the plan's own
    degradation chain (resilience.degrade)."""
    if plan is False:
        return None
    if plan is not None:
        return plan
    if min_segment is not None and shape[-1] <= min_segment:
        return None
    from .. import plans
    from ..resilience import FaultKind, classify, maybe_fault

    try:
        maybe_fault("resolve")
        resolved = plans.plan_for(shape, layout="pi", precision=precision)
    except ValueError:
        return None
    except Exception as e:
        kind = classify(e)
        if kind is FaultKind.TRANSIENT:
            raise
        plans.warn(f"tube-plan resolution for shape {tuple(shape)} "
                   f"DEGRADED to the jnp tube ({kind.value}: "
                   f"{type(e).__name__}: {str(e)[:200]})")
        return None
    return None if resolved.variant == "jnp" else resolved


def tube_planned(sr, si, n, p, plan=None, precision=None):
    """Tube phase through the plan subsystem.

    A segment's tube IS a standalone s-point pi-layout transform: the
    n-plan levels k.. coincide exactly with a fresh s-plan's levels 0..
    (W_{n>>(k+l)} = W_{s>>l} — see ``tube``), so the per-shard-shape
    plan applies, including the large-n carry kernels (fourstep at
    s > 2^20, the hierarchical sixstep at s >= 2^25 — docs/KERNELS.md)
    where the unrolled jnp tube costs minutes of compile.
    Falls back to the jnp ``tube`` whenever :func:`resolve_tube_plan`
    serves no kernel plan."""
    plan = resolve_tube_plan(sr.shape, plan, precision)
    if plan is None:
        return tube(sr, si, n, p)
    with _span("tube", cell={"n": n, "p": p, "variant": plan.variant},
               annotate=True):
        return plan.execute(sr, si)


def pi_fft_pi_layout(xr, xi, p, tables=None):
    """Full pi-FFT, output in pi layout.  xr/xi: (..., n) -> (..., n)."""
    n = xr.shape[-1]
    tables = _tables_for(n, tables)
    fr, fi = funnel(xr, xi, p, tables)
    tr, ti = tube(fr, fi, n, p, tables)
    return tr.reshape(*xr.shape[:-1], n), ti.reshape(*xi.shape[:-1], n)


def fft_stages_scan(xr, xi):
    """All log2(m) DIF stages over the trailing axis as ONE
    ``lax.fori_loop`` — the compile-time answer to the unrolled stages.

    The unrolled ``tube`` emits log2(m) reshape+stack stages into the HLO
    graph, and XLA compile time grows with the graph (minutes at n=2^20).
    Here the graph holds exactly one stage body, so the body must have
    the same static shape at every traced level l.  That is the **Pease
    constant-geometry FFT**: every stage pairs the two contiguous halves
    (a, b) = (x[:m/2], x[m/2:]) — static slices — computes the butterfly
    (a + b, (a - b) * w_l), and writes the results perfectly shuffled
    (interleaved).  With stage-l twiddles w_l[pos] =
    W_m^{(pos >> l) << l}, the final array equals the standard DIF
    output (pi layout / bit-reversed order) with NO extra permutation —
    verified element-exact against the unrolled stages in tests.

    TPU notes: no gathers anywhere (an earlier XOR-partner formulation
    spent 15 ns/element in gathers); the shuffle is a static
    stack+reshape; twiddles are computed per stage by vectorized cos/sin
    of exactly representable angles (k <= m/2 < 2^24 is exact in f32),
    trading one VPU transcendental pass for what would otherwise be an
    (levels, m/2) baked table (84 MB at m=2^20) or a gather.
    """
    import jax

    m = xr.shape[-1]
    levels = ilog2(m)
    if levels == 0:
        return xr, xi
    h = m // 2
    pos = jnp.arange(h, dtype=jnp.int32)
    shape = xr.shape

    def stage(l, c):
        cr, ci = c
        ar, br = cr[..., :h], cr[..., h:]
        ai, bi = ci[..., :h], ci[..., h:]
        k = (pos >> l) << l
        ang = k.astype(jnp.float32) * jnp.float32(-2.0 * np.pi / m)
        wr, wi = jnp.cos(ang), jnp.sin(ang)
        tr, ti = ar + br, ai + bi
        dr, di = ar - br, ai - bi
        ur = dr * wr - di * wi
        ui = dr * wi + di * wr
        yr = jnp.stack((tr, ur), axis=-1).reshape(shape)
        yi = jnp.stack((ti, ui), axis=-1).reshape(shape)
        return yr, yi

    return jax.lax.fori_loop(0, levels, stage, (xr, xi))


def tube_scan(sr, si, n, p):
    """Tube phase as a fori_loop: segment-local s-point DIF over the
    trailing axis.  Mathematically identical to ``tube`` (the n-plan
    levels k.. equal a standalone s-point plan, see ``tube``); compiles
    in O(1) stages instead of O(log s)."""
    with _span("tube", cell={"n": n, "p": p}, annotate=True):
        return fft_stages_scan(sr, si)


def pi_fft_pi_layout_scan(xr, xi, p, tables=None):
    """pi-FFT with the unrolled funnel (log2 p stages, always small) and
    the fori_loop tube — the n=2^20-reachable path for the jax backend."""
    n = xr.shape[-1]
    fr, fi = funnel(xr, xi, p, _tables_for(n, tables))
    tr, ti = tube_scan(fr, fi, n, p)
    return tr.reshape(*xr.shape[:-1], n), ti.reshape(*xi.shape[:-1], n)
