"""The communication-free escape path (docs/MULTICHIP.md): when a
supervised collective wedges, re-plan the sharded 2-D FFT / Poisson
dataflow onto the paper's pi-layout decomposition — funnel-style
replicated input, per-chip local work, one final host-side reorder —
and complete the run instead of hanging it.

The escape reproduces the all_to_all paths' arithmetic EXACTLY, it only
re-plans the data movement: every 1-D transform runs through the same
per-shard-shape plan on the same values (the all_to_all path's
per-device blocks become per-chip loop iterations over the replicated
input — the paper's redundant-compute-instead-of-communication trade,
…cuda.cu's broadcast-into-every-scratchpad made literal), so results
are BIT-IDENTICAL to the primary path (asserted by
tests/test_multichip_recovery.py) and the compiled HLO contains zero
collective ops (same machine check as the sharded pi-FFT's
collective-free test).  What is spent is p-fold redundant compute on
the phases that previously communicated — the escape completes a run,
it does not win a benchmark, and every escape is recorded as a
``collective_free`` demotion in the degrade trail
(resilience.degrade.note_collective_escape).

Recovery loop (the resilient entry points in fft2d.py / poisson3d.py):

1. the primary all_to_all path runs under
   ``resilience.supervise_collective`` — heartbeats per deadline, abort
   past the wait budget;
2. on :class:`CollectiveAborted` / :class:`CollectiveTimeout` (or when
   a device has been reported unhealthy, which skips the doomed attempt
   entirely) all hosts agree on the fallback epoch first
   (``multihost.agree_on_fallback`` — one host's escape must not strand
   the others in the next rendezvous), then
3. the escape body runs, the demotion is recorded, and the caller gets
   the same values the primary path would have produced.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from .. import plans
from ..resilience import (
    CollectiveAborted,
    CollectiveTimeout,
    FaultKind,
    classify,
    supervise_collective,
)
from ..resilience.degrade import note_collective_escape
from ..utils.compat import shard_map

# ------------------------------------------------------ device health
#
# "a device is reported unhealthy" is the OTHER trigger for the escape
# (ISSUE: a stall is detected in-band; an unhealthy device is reported
# out-of-band — by the operator, a prior aborted region, or platform
# health checks).  The registry is process-local; the consensus step
# keeps hosts from acting on it unilaterally.

_UNHEALTHY: dict = {}


def report_unhealthy(device, reason: str) -> None:
    """Report a device unhealthy: subsequent resilient sharded calls on
    a mesh containing it skip the doomed collective attempt and take
    the escape path directly."""
    _UNHEALTHY[str(device)] = str(reason)
    from ..obs import events
    from ..plans.core import warn

    events.emit("device_unhealthy", device=str(device),
                reason=str(reason)[:200])
    warn(f"device {device} reported unhealthy ({reason}); resilient "
         f"sharded paths will escape to collective_free")


def clear_unhealthy() -> None:
    _UNHEALTHY.clear()


def unhealthy_in(mesh) -> dict:
    """The unhealthy-device reports that apply to `mesh`."""
    devs = {str(d) for d in np.asarray(mesh.devices).ravel()}
    return {d: r for d, r in _UNHEALTHY.items() if d in devs}


# ------------------------------------------------------- escape bodies


def _fft2_escape_fn(mesh, axis: str, inverse: bool, R: int, C: int):
    """The escape's sharded body for an (R, C) transform — exposed so
    tests can lower it and machine-check the compiled HLO is
    collective-free (the same check the sharded pi-FFT carries)."""
    p = mesh.shape[axis]
    row_plan = plans.plan_for((R // p, C))
    col_plan = plans.plan_for((C // p, R))

    def run(plan, br, bi):
        if inverse:
            return plan.execute_inverse(br, bi)
        return plan.execute(br, bi)

    def device_fn(br, bi):  # (R, C) planes, replicated
        # row pass: per row-block j, EXACTLY the primary path's
        # per-device row transform (same plan, same block) — the
        # redundancy buys zero communication
        rp = R // p
        rows = [run(row_plan, br[j * rp:(j + 1) * rp],
                    bi[j * rp:(j + 1) * rp]) for j in range(p)]
        yr = jnp.concatenate([r[0] for r in rows], axis=0)
        yi = jnp.concatenate([r[1] for r in rows], axis=0)
        # this chip's column block (a local dynamic slice of the
        # replicated intermediate — the transpose that used to be an
        # all_to_all rendezvous)
        i = jax.lax.axis_index(axis)
        cp = C // p
        yr = jax.lax.dynamic_slice_in_dim(yr, i * cp, cp, axis=1)
        yi = jax.lax.dynamic_slice_in_dim(yi, i * cp, cp, axis=1)
        cr, ci = run(col_plan, jnp.swapaxes(yr, 0, 1),
                     jnp.swapaxes(yi, 0, 1))
        return jnp.swapaxes(cr, 0, 1), jnp.swapaxes(ci, 0, 1)

    return shard_map(
        device_fn, mesh=mesh,
        in_specs=(P(None, None), P(None, None)),
        out_specs=(P(None, axis), P(None, axis)),
        # check=False: same Pallas-HLO-interpreter workaround as the
        # primary path (parallel/fft2d.py)
        check=False,
    )


def fft2_collective_free_planes(xr, xi, mesh, axis: str = "p",
                                inverse: bool = False):
    """2-D FFT on (R, C) re/im planes with ZERO collectives — the
    escape body for ``fft2_sharded_planes``.

    Dataflow: the input is staged to the host and fed back replicated
    (the funnel trade: every chip holds the whole problem).  Each chip
    runs the row pass for ALL p row blocks through the SAME per-shard
    row plan the primary path uses (p-fold redundant, bit-identical
    values), transposes locally, slices ITS column block, and runs the
    same per-shard column plan.  One final host-side reorder lands the
    result in the primary path's row-sharded contract.  R and C must
    be divisible by the axis size."""
    xr = np.asarray(xr, dtype=np.float32)  # host staging (no collective)
    xi = np.asarray(xi, dtype=np.float32)
    R, C = xr.shape
    fn = _fft2_escape_fn(mesh, axis, inverse, R, C)
    # under jit, like the primary path: XLA compiles the shared
    # per-block stage arithmetic bit-identically across programs ONLY
    # jit-to-jit (eager dispatch rounds differently) — and bit-parity
    # with the primary path is this module's contract
    yr, yi = jax.jit(fn)(xr, xi)
    # the one final host-side reorder: land in the primary path's
    # row-sharded contract without any device collective
    out = NamedSharding(mesh, P(axis, None))
    return (jax.device_put(np.asarray(yr), out),
            jax.device_put(np.asarray(yi), out))


def fft2_collective_free(x, mesh, axis: str = "p",
                         inverse: bool = False):
    """Complex-API wrapper over :func:`fft2_collective_free_planes`."""
    from ..models.fft import jax_complex

    x = jnp.asarray(x)
    yr, yi = fft2_collective_free_planes(
        jnp.real(x).astype(jnp.float32), jnp.imag(x).astype(jnp.float32),
        mesh, axis, inverse,
    )
    return jax_complex(yr, yi)


def _poisson_escape_fn(mesh, axis: str, n1: int, n2: int, n3: int):
    """The Poisson escape's sharded body — exposed for the compiled-HLO
    collective-free machine check (see :func:`_fft2_escape_fn`)."""
    from .poisson3d import _fft_axis, _wavenumbers

    p = mesh.shape[axis]
    k1 = _wavenumbers(n1)
    k2 = _wavenumbers(n2)
    k3 = _wavenumbers(n3)
    s1, s2 = n1 // p, n2 // p

    def device_fn(fb):  # (n1, n2, n3) real, replicated
        # phase 1, per slab j: the primary path's per-device forward
        # FFTs over axes 1-2 (identical plan keys and values)
        blocks = []
        for j in range(p):
            gr = fb[j * s1:(j + 1) * s1]
            gi = jnp.zeros_like(gr)
            gr, gi = _fft_axis(gr, gi, 2, False)
            gr, gi = _fft_axis(gr, gi, 1, False)
            blocks.append((gr, gi))
        gr = jnp.concatenate([b[0] for b in blocks], axis=0)
        gi = jnp.concatenate([b[1] for b in blocks], axis=0)
        # phase 2, per n2-block j: the primary path's post-transpose
        # axis-0 transform + spectral multiplier + inverse (the
        # multiplier slice is block j's — the same values the a2a path
        # computes on device j)
        cols = []
        for j in range(p):
            hr = gr[:, j * s2:(j + 1) * s2]
            hi = gi[:, j * s2:(j + 1) * s2]
            hr, hi = _fft_axis(hr, hi, 0, False)
            k2_loc = jnp.asarray(k2)[j * s2:(j + 1) * s2]
            ksq = (
                jnp.asarray(k1)[:, None, None] ** 2
                + k2_loc[None, :, None] ** 2
                + jnp.asarray(k3)[None, None, :] ** 2
            )
            inv = jnp.where(ksq > 0, -1.0 / jnp.maximum(ksq, 1e-30), 0.0)
            hr, hi = hr * inv, hi * inv
            hr, hi = _fft_axis(hr, hi, 0, True)
            cols.append((hr, hi))
        gr = jnp.concatenate([c[0] for c in cols], axis=1)
        gi = jnp.concatenate([c[1] for c in cols], axis=1)
        # phase 3: THIS chip's slab only — the output is slab-sharded
        # exactly like the primary path's
        i = jax.lax.axis_index(axis)
        gr = jax.lax.dynamic_slice_in_dim(gr, i * s1, s1, axis=0)
        gi = jax.lax.dynamic_slice_in_dim(gi, i * s1, s1, axis=0)
        gr, gi = _fft_axis(gr, gi, 1, True)
        gr, gi = _fft_axis(gr, gi, 2, True)
        return gr

    return shard_map(
        device_fn, mesh=mesh, in_specs=(P(None, None, None),),
        out_specs=P(axis, None, None),
        check=False,  # see fft2_collective_free_planes
    )


def poisson_solve_collective_free(f, mesh, axis: str = "p"):
    """Slab Poisson solve with ZERO collectives — the escape body for
    ``poisson_solve_sharded``.

    Every phase of the primary path's per-device pipeline is replayed
    as a loop over the corresponding blocks of the replicated input
    (same plan keys, same multiplier slices — bit-identical values);
    each chip then keeps only ITS slab for the final inverse passes, so
    the output lands directly in the primary path's slab-sharded
    contract."""
    f = np.asarray(f, dtype=np.float32)  # host staging (no collective)
    n1, n2, n3 = f.shape
    fn = _poisson_escape_fn(mesh, axis, n1, n2, n3)
    # jit for bit-parity with the jitted primary (see the 2-D path)
    return jax.jit(fn)(f)


# --------------------------------------------------- the recovery loop


@dataclasses.dataclass
class ShardedRunReport:
    """What the resilient sharded entry points did: whether the run
    ``escaped`` to the collective-free path (``degraded`` mirrors it —
    the performance contract changed, the values did not), the
    supervisor's deadline-wait count, the consensus ``epoch`` (None
    when no escape happened), and the demotion ``trail``."""

    label: str
    escaped: bool = False
    degraded: bool = False
    waits: int = 0
    epoch: Optional[int] = None
    trail: list = dataclasses.field(default_factory=list)

    def to_record(self) -> dict:
        return {"label": self.label, "escaped": self.escaped,
                "degraded": self.degraded, "waits": self.waits,
                "epoch": self.epoch, "trail": list(self.trail)}


def run_with_escape(primary: Callable, escape: Callable, label: str,
                    mesh, tagged_plans=(),
                    deadline_s: float | None = None,
                    abort_waits: Optional[int] = None,
                    supervise: bool = True):
    """THE recovery loop (module docstring): supervise `primary`; on a
    wedged or doomed collective, reach consensus, record the
    ``collective_free`` demotion (tagging `tagged_plans` like any other
    demotion), and run `escape`.  Returns ``(value,
    ShardedRunReport)``.

    Faults that are NOT collective stalls propagate unchanged — a
    capacity fault inside the primary body belongs to the plan
    degradation chain, not to the transport escape."""
    from .multihost import agree_on_fallback

    report = ShardedRunReport(label)
    unhealthy = unhealthy_in(mesh)
    if unhealthy:
        exc: BaseException = CollectiveTimeout(
            f"{label}: device(s) reported unhealthy before dispatch: "
            + "; ".join(f"{d} ({r})" for d, r in unhealthy.items()))
    else:
        if not supervise:
            return primary(), report
        try:
            value, sup = supervise_collective(
                primary, label, deadline_s=deadline_s,
                abort_waits=abort_waits)
            report.waits = sup.fired
            return value, report
        except (CollectiveAborted, CollectiveTimeout) as e:
            exc = e
            sup = getattr(e, "report", None)
            if sup is not None:
                report.waits = sup.fired
    # all hosts agree on the fallback epoch BEFORE anyone switches —
    # one host escaping alone would strand the rest in the next
    # rendezvous (docs/MULTICHIP.md, consensus protocol)
    report.epoch = agree_on_fallback(label, reason=str(exc)[:200],
                                     deadline_s=deadline_s)
    kind = classify(exc)
    if kind is None:  # pragma: no cover — classify always returns
        kind = FaultKind.TRANSIENT
    report.trail.append(
        note_collective_escape(label, exc, kind, plans=tagged_plans))
    report.escaped = True
    report.degraded = True
    value = escape()
    from ..obs import events

    events.emit("collective_escape_completed", label=label,
                epoch=report.epoch, waits=report.waits)
    return value, report
