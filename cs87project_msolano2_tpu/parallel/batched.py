"""Batched FFT, pure data parallelism over the mesh: BASELINE.json config 3
("Batched 1D FFT, batch x N over TPU cores").  Each device transforms its
own batch shard locally — like the pi funnel, this needs no collectives;
it is the honest multi-chip analogue of the paper's claim for the batched
workload."""

from __future__ import annotations

import jax
from jax import shard_map
from jax.sharding import PartitionSpec as P

from ..models.fft import fft, ifft


def fft_batched_sharded(x, mesh, axis: str = "data", inverse: bool = False):
    """1-D FFT along the trailing axis of complex (B, n), batch-sharded
    over `axis`.  Natural frequency order output, same sharding."""
    f = ifft if inverse else fft

    fn = shard_map(
        lambda xb: f(xb),
        mesh=mesh,
        in_specs=(P(axis, None),),
        out_specs=P(axis, None),
    )
    return fn(x)


def jit_fft_batched(mesh, axis: str = "data"):
    import functools

    return jax.jit(functools.partial(fft_batched_sharded, mesh=mesh, axis=axis))
