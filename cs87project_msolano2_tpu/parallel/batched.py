"""Batched FFT, pure data parallelism over the mesh: BASELINE.json config 3
("Batched 1D FFT, batch x N over TPU cores").  Each device transforms its
own batch shard locally — like the pi funnel, this needs no collectives;
it is the honest multi-chip analogue of the paper's claim for the batched
workload.  Plane-level variant exposed for loop-compatible timing.

Kernel dispatch: one plan is fetched for the PER-SHARD shape (the shape
each device actually transforms — tile/tail tuned for that key, not for
the flagship's), and ``plan.execute`` runs inside the shard_map body.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .. import plans
from ..models.fft import jax_complex
from ..utils.compat import shard_map


def fft_batched_planes(xr, xi, mesh, axis: str = "data",
                       inverse: bool = False, natural: bool = True,
                       precision: str | None = None,
                       domain: str = "c2c"):
    """1-D FFT along the trailing axis of (B, n) re/im planes,
    batch-sharded over `axis`.  Natural order by default, same
    sharding; `natural=False` returns pi layout (per-row bit-reversed,
    forward only — the kernel-native order with the gather left off,
    mirroring the flagship bench contract).  `precision` picks the
    kernel precision mode for the per-shard plan (split3 default /
    highest / fp32 — see models.fft).  `domain` picks c2c (default) or
    the half-spectrum real planes (docs/REAL.md): "r2c" takes real
    (B, n) planes (xi ignored) and returns (B, n//2+1) half-spectrum
    shards; "c2r" the reverse — the per-shard plan still rides the
    tuned c2c kernel at n/2, per shard, with no collectives."""
    if domain != "c2c":
        if inverse:
            raise ValueError("inverse is the c2c conj trick; use "
                             "domain='c2r' for the real inverse")
        if not natural:
            raise ValueError(f"domain={domain!r} requires natural "
                             f"layout (the half-spectrum has no pi "
                             f"order)")
    nshards = mesh.shape[axis]
    if domain == "c2r":
        # the signal-side length the plan is keyed by (input planes
        # carry n//2+1 half-spectrum bins per row)
        n_signal = 2 * (xr.shape[-1] - 1)
        local = (xr.shape[0] // nshards,) + tuple(xr.shape[1:-1]) \
            + (n_signal,)
    else:
        local = (xr.shape[0] // nshards,) + tuple(xr.shape[1:])
    plan = plans.plan_for(
        local, layout="natural" if (natural or inverse) else "pi",
        precision=precision, domain=domain)

    def device_fn(br, bi):
        if inverse:
            return plan.execute_inverse(br, bi)
        return plan.execute(br, bi)

    fn = shard_map(
        device_fn,
        mesh=mesh,
        in_specs=(P(axis, None), P(axis, None)),
        out_specs=(P(axis, None), P(axis, None)),
        # check=False (vma checking off): the Pallas HLO interpreter
        # (CPU test path) cannot carry varying-manual-axes through its
        # grid while-loop (jax hlo_interpreter.py; the error text itself
        # prescribes this workaround).  With the checker off HERE, the
        # kernels' vma declarations (_out_struct/_pvary_like in ops) are
        # inert on this entry point — they exist to keep EXTERNAL
        # check_vma=True embeddings of these kernels working, not to
        # protect this path.
        check=False,
    )
    return fn(xr, xi)


def fft_batched_sharded(x, mesh, axis: str = "data", inverse: bool = False):
    """Complex-API wrapper over fft_batched_planes."""
    x = jnp.asarray(x)
    yr, yi = fft_batched_planes(
        jnp.real(x).astype(jnp.float32), jnp.imag(x).astype(jnp.float32),
        mesh, axis, inverse,
    )
    return jax_complex(yr, yi)


def rfft_batched_sharded(x, mesh, axis: str = "data"):
    """Real-input half-spectrum wrapper over fft_batched_planes: real
    (B, n) in, complex (B, n//2+1) out, batch-sharded, each shard's
    packed c2c kernel local to its device (docs/REAL.md)."""
    xr = jnp.real(jnp.asarray(x)).astype(jnp.float32)
    yr, yi = fft_batched_planes(xr, jnp.zeros_like(xr), mesh, axis,
                                domain="r2c")
    return jax_complex(yr, yi)


def jit_fft_batched(mesh, axis: str = "data"):
    return jax.jit(functools.partial(fft_batched_sharded, mesh=mesh, axis=axis))
