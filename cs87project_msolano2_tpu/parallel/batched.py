"""Batched FFT, pure data parallelism over the mesh: BASELINE.json config 3
("Batched 1D FFT, batch x N over TPU cores").  Each device transforms its
own batch shard locally — like the pi funnel, this needs no collectives;
it is the honest multi-chip analogue of the paper's claim for the batched
workload.  Plane-level variant exposed for loop-compatible timing."""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import PartitionSpec as P

from ..models.fft import fft_planes, ifft_planes, jax_complex


def fft_batched_planes(xr, xi, mesh, axis: str = "data",
                       inverse: bool = False):
    """1-D FFT along the trailing axis of (B, n) re/im planes,
    batch-sharded over `axis`.  Natural order, same sharding."""
    f = ifft_planes if inverse else fft_planes

    fn = shard_map(
        lambda br, bi: f(br, bi),
        mesh=mesh,
        in_specs=(P(axis, None), P(axis, None)),
        out_specs=(P(axis, None), P(axis, None)),
    )
    return fn(xr, xi)


def fft_batched_sharded(x, mesh, axis: str = "data", inverse: bool = False):
    """Complex-API wrapper over fft_batched_planes."""
    x = jnp.asarray(x)
    yr, yi = fft_batched_planes(
        jnp.real(x).astype(jnp.float32), jnp.imag(x).astype(jnp.float32),
        mesh, axis, inverse,
    )
    return jax_complex(yr, yi)


def jit_fft_batched(mesh, axis: str = "data"):
    return jax.jit(functools.partial(fft_batched_sharded, mesh=mesh, axis=axis))
