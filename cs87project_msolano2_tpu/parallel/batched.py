"""Batched FFT, pure data parallelism over the mesh: BASELINE.json config 3
("Batched 1D FFT, batch x N over TPU cores").  Each device transforms its
own batch shard locally — like the pi funnel, this needs no collectives;
it is the honest multi-chip analogue of the paper's claim for the batched
workload.  Plane-level variant exposed for loop-compatible timing."""

from __future__ import annotations

import functools
from functools import partial

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import PartitionSpec as P

from ..models.fft import fft_planes_fast, ifft_planes_fast, jax_complex


def fft_batched_planes(xr, xi, mesh, axis: str = "data",
                       inverse: bool = False, natural: bool = True):
    """1-D FFT along the trailing axis of (B, n) re/im planes,
    batch-sharded over `axis`.  Natural order by default, same
    sharding; `natural=False` returns pi layout (per-row bit-reversed,
    forward only — the kernel-native order with the gather left off,
    mirroring the flagship bench contract)."""
    if inverse:
        f = ifft_planes_fast
    else:
        f = partial(fft_planes_fast, natural=natural)

    fn = shard_map(
        lambda br, bi: f(br, bi),
        mesh=mesh,
        in_specs=(P(axis, None), P(axis, None)),
        out_specs=(P(axis, None), P(axis, None)),
        # check_vma=False: the Pallas HLO interpreter (CPU test path)
        # cannot carry varying-manual-axes through its grid while-loop
        # (jax hlo_interpreter.py; the error text itself prescribes this
        # workaround).  The kernel operands/outputs still declare vma
        # for the compiled path (_out_struct/_pvary_like in ops).
        check_vma=False,
    )
    return fn(xr, xi)


def fft_batched_sharded(x, mesh, axis: str = "data", inverse: bool = False):
    """Complex-API wrapper over fft_batched_planes."""
    x = jnp.asarray(x)
    yr, yi = fft_batched_planes(
        jnp.real(x).astype(jnp.float32), jnp.imag(x).astype(jnp.float32),
        mesh, axis, inverse,
    )
    return jax_complex(yr, yi)


def jit_fft_batched(mesh, axis: str = "data"):
    return jax.jit(functools.partial(fft_batched_sharded, mesh=mesh, axis=axis))
