"""Multi-chip layer: meshes, the zero-collective sharded pi-FFT, DP-batched
FFT, the all_to_all 2-D FFT / 3-D Poisson configs — and their
self-healing entries (collective supervision + the communication-free
escape path + multihost fallback consensus, docs/MULTICHIP.md)."""

from .mesh import how_many_devices, make_mesh, make_mesh2d  # noqa: F401
from .pi_shard import pi_fft_sharded, pi_fft_sharded_batched  # noqa: F401
from .batched import fft_batched_sharded  # noqa: F401
from .fft2d import fft2_sharded, fft2_sharded_resilient  # noqa: F401
from .poisson3d import (  # noqa: F401
    poisson_solve_sharded,
    poisson_solve_sharded_resilient,
)
from .batched import fft_batched_planes  # noqa: F401
from .fft2d import fft2_sharded_planes  # noqa: F401
from .escape import (  # noqa: F401
    ShardedRunReport,
    clear_unhealthy,
    fft2_collective_free,
    fft2_collective_free_planes,
    poisson_solve_collective_free,
    report_unhealthy,
    run_with_escape,
)
from .multihost import agree_on_fallback  # noqa: F401
