"""Multi-chip layer: meshes, the zero-collective sharded pi-FFT, DP-batched
FFT, and the all_to_all 2-D FFT / 3-D Poisson configs."""

from .mesh import how_many_devices, make_mesh, make_mesh2d  # noqa: F401
from .pi_shard import pi_fft_sharded, pi_fft_sharded_batched  # noqa: F401
from .batched import fft_batched_sharded  # noqa: F401
from .fft2d import fft2_sharded  # noqa: F401
from .poisson3d import poisson_solve_sharded  # noqa: F401
from .batched import fft_batched_planes  # noqa: F401
from .fft2d import fft2_sharded_planes  # noqa: F401
