"""3-D spectral Poisson solver with slab decomposition (BASELINE.json
config 5): solve lap(u) = f on a periodic [0, 2*pi)^3 grid.

Slabs are sharded along axis 0.  Per slab: local FFT over axes 1-2, one
all_to_all transpose to localize axis 0, FFT over axis 0, multiply by
-1/|k|^2 (zero mode -> 0: the mean-free solution), then invert the
pipeline.  Two ICI transposes per solve — the textbook slab pattern."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import shard_map
from jax.sharding import PartitionSpec as P

from ..models.fft import fft, ifft


def _wavenumbers(m: int) -> np.ndarray:
    """Integer wavenumbers for an m-point periodic axis (fftfreq * m)."""
    k = np.arange(m)
    k[k > m // 2] -= m
    return k.astype(np.float32)


def _fft_axis(x, ax: int, inverse: bool):
    f = ifft if inverse else fft
    return jnp.moveaxis(f(jnp.moveaxis(x, ax, -1)), -1, ax)


def poisson_solve_sharded(f, mesh, axis: str = "p"):
    """u with lap(u) = f, zero-mean; f real (n1, n2, n3) sharded on axis 0.

    Returns real u, same sharding.  n1 and n2 must be divisible by the
    mesh axis size.
    """
    p = mesh.shape[axis]
    n1, n2, n3 = f.shape
    k1 = jnp.asarray(_wavenumbers(n1))
    k2 = jnp.asarray(_wavenumbers(n2))
    k3 = jnp.asarray(_wavenumbers(n3))

    def device_fn(fb):  # (n1/p, n2, n3)
        g = fb.astype(jnp.complex64)
        g = _fft_axis(g, 2, False)
        g = _fft_axis(g, 1, False)
        # localize axis 0: (n1/p, n2, n3) -> (n1, n2/p, n3)
        g = jax.lax.all_to_all(g, axis, split_axis=1, concat_axis=0,
                               tiled=True)
        g = _fft_axis(g, 0, False)

        # spectral inverse Laplacian on the (n1, n2/p, n3) block
        i = jax.lax.axis_index(axis)
        k2_loc = jax.lax.dynamic_slice_in_dim(k2, i * (n2 // p), n2 // p)
        ksq = (
            k1[:, None, None] ** 2
            + k2_loc[None, :, None] ** 2
            + k3[None, None, :] ** 2
        )
        inv = jnp.where(ksq > 0, -1.0 / jnp.maximum(ksq, 1e-30), 0.0)
        g = g * inv.astype(jnp.complex64)

        g = _fft_axis(g, 0, True)
        g = jax.lax.all_to_all(g, axis, split_axis=0, concat_axis=1,
                               tiled=True)
        g = _fft_axis(g, 1, True)
        g = _fft_axis(g, 2, True)
        return jnp.real(g)

    fn = shard_map(
        device_fn, mesh=mesh, in_specs=(P(axis, None, None),),
        out_specs=P(axis, None, None),
    )
    return fn(f)
