"""3-D spectral Poisson solver with slab decomposition (BASELINE.json
config 5): solve lap(u) = f on a periodic [0, 2*pi)^3 grid.

THIN SHIM (docs/APPS.md): the slab pipeline — per-slab local FFTs
over axes 1-2, one all_to_all transpose to localize axis 0, the
axis-0 FFT, a real spectral multiplier, the inverted pipeline — now
lives in :mod:`..apps.pde` as :func:`~..apps.pde.solve_spectral_sharded`,
parameterized by its multiplier so Poisson, Helmholtz, and the
spectral time-stepper are ONE code path.  This module keeps the
Poisson names (and the private helpers ``parallel/escape.py``'s
collective-free replay imports) bound to the family with the Poisson
symbol — same plan keys, same multiplier expression, bit-identical
results; existing callers and tests are untouched.

:func:`poisson_solve_sharded_resilient` still adds the supervision/
consensus/escape recovery loop (docs/MULTICHIP.md).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _wavenumbers(m: int):
    """Integer wavenumbers for an m-point periodic axis (fftfreq * m)
    — re-exported from the family (escape.py's replay imports it
    here)."""
    from ..apps.pde import wavenumbers

    return wavenumbers(m)


def _fft_axis(vr, vi, ax: int, inverse: bool):
    """One planned FFT pass over `ax` — the family's per-axis-shape
    dispatch (escape.py's replay imports it here)."""
    from ..apps.pde import fft_axis

    return fft_axis(vr, vi, ax, inverse)


def poisson_solve_sharded(f, mesh, axis: str = "p"):
    """u with lap(u) = f, zero-mean; f real (n1, n2, n3) sharded on
    axis 0.  Returns real u, same sharding.  n1 and n2 must be
    divisible by the mesh axis size.  Dispatches through the spectral
    solver family (apps/pde.py) with the Poisson multiplier — the
    identical dataflow this module used to own."""
    from ..apps.pde import poisson_multiplier, solve_spectral_sharded

    return solve_spectral_sharded(f, mesh, axis, poisson_multiplier)


def poisson_solve_sharded_resilient(f, mesh, axis: str = "p",
                                    deadline_s: float | None = None,
                                    abort_waits: int | None = None):
    """Self-healing slab Poisson solve: the two-transpose all_to_all
    pipeline under collective supervision, escaping to the
    communication-free pi-path when a transpose wedges or a mesh
    device is unhealthy (docs/MULTICHIP.md).  Returns ``(u,
    ShardedRunReport)`` — `u` is bit-identical either way."""
    from .escape import poisson_solve_collective_free, run_with_escape

    f = jnp.asarray(f)
    n1, n2, n3 = f.shape
    p = mesh.shape[axis]

    def primary():
        from ..utils.timing import block

        # jitted like the escape body (bit-parity: parallel/escape.py);
        # block(): the supervised region must contain the transposes'
        # completion, not just their dispatch
        return block(
            jax.jit(lambda v: poisson_solve_sharded(v, mesh, axis))(f))

    def escape():
        return poisson_solve_collective_free(f, mesh, axis)

    return run_with_escape(
        primary, escape,
        f"poisson3d all_to_all ({n1}x{n2}x{n3}, p={p})", mesh,
        deadline_s=deadline_s, abort_waits=abort_waits)
