"""3-D spectral Poisson solver with slab decomposition (BASELINE.json
config 5): solve lap(u) = f on a periodic [0, 2*pi)^3 grid.

Slabs are sharded along axis 0.  Per slab: local FFT over axes 1-2, one
all_to_all transpose to localize axis 0, FFT over axis 0, multiply by
-1/|k|^2 (zero mode -> 0: the mean-free solution), then invert the
pipeline.  Two ICI transposes per solve — the textbook slab pattern —
both dispatched through the sanctioned ``parallel.collectives`` funnel
(PIF108); :func:`poisson_solve_sharded_resilient` adds the
supervision/consensus/escape recovery loop (docs/MULTICHIP.md).

All spectral arithmetic runs on split re/im float32 planes: the
multiplier is real, so the whole pipeline is float ops — TPU-native and
loop-compatible (the axon relay cannot lower complex in While bodies).

Kernel dispatch: every axis pass transforms a different per-shard shape
((n1/p, n2) rows of n3, (n1/p, n3) rows of n2, (n2/p, n3) rows of n1…),
and each fetches the plan for ITS shape's key — no shared module-level
tile/cb defaults.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from .. import plans
from ..utils.compat import shard_map
from .collectives import all_to_all as _a2a


def _wavenumbers(m: int) -> np.ndarray:
    """Integer wavenumbers for an m-point periodic axis (fftfreq * m)."""
    k = np.arange(m)
    k[k > m // 2] -= m
    return k.astype(np.float32)


def _fft_axis(vr, vi, ax: int, inverse: bool):
    vr = jnp.moveaxis(vr, ax, -1)
    vi = jnp.moveaxis(vi, ax, -1)
    plan = plans.plan_for(vr.shape)
    if inverse:
        yr, yi = plan.execute_inverse(vr, vi)
    else:
        yr, yi = plan.execute(vr, vi)
    return jnp.moveaxis(yr, -1, ax), jnp.moveaxis(yi, -1, ax)


def poisson_solve_sharded(f, mesh, axis: str = "p"):
    """u with lap(u) = f, zero-mean; f real (n1, n2, n3) sharded on axis 0.

    Returns real u, same sharding.  n1 and n2 must be divisible by the
    mesh axis size.
    """
    p = mesh.shape[axis]
    n1, n2, n3 = f.shape
    k1 = _wavenumbers(n1)
    k2 = _wavenumbers(n2)
    k3 = _wavenumbers(n3)

    def a2a(v, split_axis, concat_axis):
        return _a2a(v, axis, split_axis, concat_axis)

    def device_fn(fb):  # (n1/p, n2, n3) real
        gr, gi = fb, jnp.zeros_like(fb)
        gr, gi = _fft_axis(gr, gi, 2, False)
        gr, gi = _fft_axis(gr, gi, 1, False)
        # localize axis 0: (n1/p, n2, n3) -> (n1, n2/p, n3)
        gr, gi = a2a(gr, 1, 0), a2a(gi, 1, 0)
        gr, gi = _fft_axis(gr, gi, 0, False)

        # spectral inverse Laplacian on the (n1, n2/p, n3) block —
        # a REAL multiplier, so planes never recombine
        i = jax.lax.axis_index(axis)
        k2_loc = jax.lax.dynamic_slice_in_dim(
            jnp.asarray(k2), i * (n2 // p), n2 // p
        )
        ksq = (
            jnp.asarray(k1)[:, None, None] ** 2
            + k2_loc[None, :, None] ** 2
            + jnp.asarray(k3)[None, None, :] ** 2
        )
        inv = jnp.where(ksq > 0, -1.0 / jnp.maximum(ksq, 1e-30), 0.0)
        gr, gi = gr * inv, gi * inv

        gr, gi = _fft_axis(gr, gi, 0, True)
        gr, gi = a2a(gr, 0, 1), a2a(gi, 0, 1)
        gr, gi = _fft_axis(gr, gi, 1, True)
        gr, gi = _fft_axis(gr, gi, 2, True)
        return gr

    fn = shard_map(
        device_fn, mesh=mesh, in_specs=(P(axis, None, None),),
        out_specs=P(axis, None, None),
        # check=False (vma checking off): the Pallas HLO interpreter
        # (CPU test path) cannot carry varying-manual-axes through its
        # grid while-loop (jax hlo_interpreter.py; the error text itself
        # prescribes this workaround).  With the checker off HERE, the
        # kernels' vma declarations (_out_struct/_pvary_like in ops) are
        # inert on this entry point — they exist to keep EXTERNAL
        # check_vma=True embeddings of these kernels working, not to
        # protect this path.
        check=False,
    )
    return fn(f)


def poisson_solve_sharded_resilient(f, mesh, axis: str = "p",
                                    deadline_s: float | None = None,
                                    abort_waits: int | None = None):
    """Self-healing slab Poisson solve: the two-transpose all_to_all
    pipeline under collective supervision, escaping to the
    communication-free pi-path when a transpose wedges or a mesh
    device is unhealthy (docs/MULTICHIP.md).  Returns ``(u,
    ShardedRunReport)`` — `u` is bit-identical either way."""
    from .escape import poisson_solve_collective_free, run_with_escape

    f = jnp.asarray(f)
    n1, n2, n3 = f.shape
    p = mesh.shape[axis]

    def primary():
        from ..utils.timing import block

        # jitted like the escape body (bit-parity: parallel/escape.py);
        # block(): the supervised region must contain the transposes'
        # completion, not just their dispatch
        return block(
            jax.jit(lambda v: poisson_solve_sharded(v, mesh, axis))(f))

    def escape():
        return poisson_solve_collective_free(f, mesh, axis)

    return run_with_escape(
        primary, escape,
        f"poisson3d all_to_all ({n1}x{n2}x{n3}, p={p})", mesh,
        deadline_s=deadline_s, abort_waits=abort_waits)
