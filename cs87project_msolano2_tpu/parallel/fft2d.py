"""Distributed 2-D FFT over ICI (BASELINE.json config 4): row FFTs local,
`lax.all_to_all` transpose, column FFTs local, transpose back.

This is the one place the framework genuinely needs communication — the
2-D transform's data dependencies span both axes — and per SURVEY.md §2.3
it uses the XLA collective over ICI (tiled all_to_all), not a
point-to-point port of anything in the reference (which has no multi-node
path at all).

Internals run on split re/im float32 planes (the TPU-native
representation; also required because the axon relay cannot lower
complex64 inside While loops); complex64 only at the API edge.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import PartitionSpec as P

from ..models.fft import fft_planes_fast, ifft_planes_fast, jax_complex


def _a2a(v, axis, split_axis, concat_axis):
    return jax.lax.all_to_all(v, axis, split_axis=split_axis,
                              concat_axis=concat_axis, tiled=True)


def fft2_sharded_planes(xr, xi, mesh, axis: str = "p",
                        inverse: bool = False):
    """2-D FFT on (R, C) re/im planes, rows sharded over the mesh axis.
    Returns planes with the same sharding.  R and C must be divisible by
    the axis size."""
    f = ifft_planes_fast if inverse else fft_planes_fast

    def device_fn(br, bi):  # (R/p, C) planes
        yr, yi = f(br, bi)  # row transforms
        # ICI transpose: (R/p, C) -> (R, C/p)
        yr, yi = _a2a(yr, axis, 1, 0), _a2a(yi, axis, 1, 0)
        # column transforms (axis 0 now fully local)
        cr, ci = f(jnp.swapaxes(yr, 0, 1), jnp.swapaxes(yi, 0, 1))
        yr, yi = jnp.swapaxes(cr, 0, 1), jnp.swapaxes(ci, 0, 1)
        # transpose back: (R, C/p) -> (R/p, C)
        return _a2a(yr, axis, 0, 1), _a2a(yi, axis, 0, 1)

    fn = shard_map(
        device_fn, mesh=mesh,
        in_specs=(P(axis, None), P(axis, None)),
        out_specs=(P(axis, None), P(axis, None)),
        # check_vma=False: the Pallas HLO interpreter (CPU test path)
        # cannot carry varying-manual-axes through its grid while-loop
        # (jax hlo_interpreter.py; the error text itself prescribes this
        # workaround).  The kernel operands/outputs still declare vma
        # for the compiled path (_out_struct/_pvary_like in ops).
        check_vma=False,
    )
    return fn(xr, xi)


def fft2_sharded(x, mesh, axis: str = "p", inverse: bool = False):
    """Complex-API wrapper over fft2_sharded_planes."""
    x = jnp.asarray(x)
    yr, yi = fft2_sharded_planes(
        jnp.real(x).astype(jnp.float32), jnp.imag(x).astype(jnp.float32),
        mesh, axis, inverse,
    )
    return jax_complex(yr, yi)
