"""Distributed 2-D FFT over ICI (BASELINE.json config 4): row FFTs local,
`lax.all_to_all` transpose, column FFTs local, transpose back.

This is the one place the framework genuinely needs communication — the
2-D transform's data dependencies span both axes — and per SURVEY.md §2.3
it uses the XLA collective over ICI (tiled all_to_all), not a
point-to-point port of anything in the reference (which has no multi-node
path at all).  The collective is dispatched through the sanctioned
``parallel.collectives`` funnel (check rule PIF108), and
:func:`fft2_sharded_resilient` wraps the whole path in the self-healing
loop — collective supervision, fallback consensus, and the
communication-free escape (docs/MULTICHIP.md) — so the MULTICHIP_r05
wedge completes instead of hanging.

Internals run on split re/im float32 planes (the TPU-native
representation; also required because the axon relay cannot lower
complex64 inside While loops); complex64 only at the API edge.

Kernel dispatch: the row and column passes transform DIFFERENT per-shard
shapes — (R/p, C) rows before the transpose, (C/p, R) columns after —
so each pass fetches its own plan for its own key instead of sharing one
module-level default.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .. import plans
from ..models.fft import jax_complex
from ..utils.compat import shard_map
from .collectives import all_to_all as _a2a


def fft2_sharded_planes(xr, xi, mesh, axis: str = "p",
                        inverse: bool = False):
    """2-D FFT on (R, C) re/im planes, rows sharded over the mesh axis.
    Returns planes with the same sharding.  R and C must be divisible by
    the axis size."""
    p = mesh.shape[axis]
    R, C = xr.shape
    row_plan = plans.plan_for((R // p, C))
    col_plan = plans.plan_for((C // p, R))

    def run(plan, br, bi):
        if inverse:
            return plan.execute_inverse(br, bi)
        return plan.execute(br, bi)

    def device_fn(br, bi):  # (R/p, C) planes
        yr, yi = run(row_plan, br, bi)  # row transforms
        # ICI transpose: (R/p, C) -> (R, C/p)
        yr, yi = _a2a(yr, axis, 1, 0), _a2a(yi, axis, 1, 0)
        # column transforms (axis 0 now fully local)
        cr, ci = run(col_plan, jnp.swapaxes(yr, 0, 1),
                     jnp.swapaxes(yi, 0, 1))
        yr, yi = jnp.swapaxes(cr, 0, 1), jnp.swapaxes(ci, 0, 1)
        # transpose back: (R, C/p) -> (R/p, C)
        return _a2a(yr, axis, 0, 1), _a2a(yi, axis, 0, 1)

    fn = shard_map(
        device_fn, mesh=mesh,
        in_specs=(P(axis, None), P(axis, None)),
        out_specs=(P(axis, None), P(axis, None)),
        # check=False (vma checking off): the Pallas HLO interpreter
        # (CPU test path) cannot carry varying-manual-axes through its
        # grid while-loop (jax hlo_interpreter.py; the error text itself
        # prescribes this workaround).  With the checker off HERE, the
        # kernels' vma declarations (_out_struct/_pvary_like in ops) are
        # inert on this entry point — they exist to keep EXTERNAL
        # check_vma=True embeddings of these kernels working, not to
        # protect this path.
        check=False,
    )
    return fn(xr, xi)


def fft2_sharded(x, mesh, axis: str = "p", inverse: bool = False):
    """Complex-API wrapper over fft2_sharded_planes."""
    x = jnp.asarray(x)
    yr, yi = fft2_sharded_planes(
        jnp.real(x).astype(jnp.float32), jnp.imag(x).astype(jnp.float32),
        mesh, axis, inverse,
    )
    return jax_complex(yr, yi)


def fft2_sharded_resilient(x, mesh, axis: str = "p",
                           inverse: bool = False,
                           deadline_s: float | None = None,
                           abort_waits: int | None = None):
    """Self-healing 2-D FFT: the all_to_all path under collective
    supervision, escaping to the communication-free pi-path when the
    transpose wedges or a mesh device is unhealthy
    (docs/MULTICHIP.md).  Returns ``(y, ShardedRunReport)`` — `y` is
    bit-identical either way; the report says whether the run escaped
    (``degraded`` / a ``collective_free`` rung in ``trail``)."""
    from .escape import fft2_collective_free_planes, run_with_escape

    x = jnp.asarray(x)
    xr = jnp.real(x).astype(jnp.float32)
    xi = jnp.imag(x).astype(jnp.float32)
    p = mesh.shape[axis]
    R, C = xr.shape
    # the plans the escape tags with the demotion (the same objects the
    # primary and escape bodies resolve: plan_for memoizes per key)
    tagged = (plans.plan_for((R // p, C)), plans.plan_for((C // p, R)))

    def primary():
        from ..utils.timing import block

        # jitted like the escape body: XLA's per-block arithmetic is
        # bit-stable jit-to-jit, which is what makes the escape's
        # bit-parity contract hold (parallel/escape.py).  block():
        # the supervised region must contain the collective's
        # completion, not just its dispatch.
        return block(jax.jit(
            lambda a, b: fft2_sharded_planes(a, b, mesh, axis, inverse)
        )(xr, xi))

    def escape():
        return fft2_collective_free_planes(xr, xi, mesh, axis, inverse)

    (yr, yi), report = run_with_escape(
        primary, escape, f"fft2d all_to_all ({R}x{C}, p={p})", mesh,
        tagged_plans=tagged, deadline_s=deadline_s,
        abort_waits=abort_waits)
    return jax_complex(yr, yi), report
