"""Distributed 2-D FFT over ICI (BASELINE.json config 4): row FFTs local,
`lax.all_to_all` transpose, column FFTs local, transpose back.

This is the one place the framework genuinely needs communication — the
2-D transform's data dependencies span both axes — and per SURVEY.md §2.3
it uses the XLA collective over ICI (tiled all_to_all), not a
point-to-point port of anything in the reference (which has no multi-node
path at all)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import PartitionSpec as P

from ..models.fft import fft, ifft


def fft2_sharded(x, mesh, axis: str = "p", inverse: bool = False):
    """2-D FFT of complex (R, C), rows sharded over the mesh axis.
    Returns the full 2-D transform, rows still sharded.  R and C must be
    divisible by the axis size."""
    p = mesh.shape[axis]
    f = ifft if inverse else fft

    def device_fn(xb):  # (R/p, C)
        y = f(xb)  # row transforms
        # ICI transpose: (R/p, C) -> (R, C/p)
        y = jax.lax.all_to_all(y, axis, split_axis=1, concat_axis=0,
                               tiled=True)
        # column transforms (axis 0 is now fully local)
        y = jnp.swapaxes(f(jnp.swapaxes(y, 0, 1)), 0, 1)
        # transpose back: (R, C/p) -> (R/p, C)
        return jax.lax.all_to_all(y, axis, split_axis=0, concat_axis=1,
                                  tiled=True)

    fn = shard_map(
        device_fn, mesh=mesh, in_specs=(P(axis, None),),
        out_specs=P(axis, None),
    )
    return fn(x)
