"""Device meshes (the TPU equivalent of the reference's processor-topology
layer: thread→core pinning becomes shard→device placement over ICI).

The reference pins threads to bit-reversed core ids (…pthreads.c:339-344)
to spread funnel siblings; on TPU the funnel needs no placement trick at
all — every device computes its own chain on a replicated copy — so the
mesh here is plain: a 1-D axis for the pi decomposition ("p"), optionally
a leading data axis for batch parallelism.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh


def make_mesh(n_devices: Optional[int] = None, axis: str = "p") -> Mesh:
    """1-D mesh over the first n_devices (default: all)."""
    devs = jax.devices()
    if n_devices is None:
        n_devices = len(devs)
    if n_devices > len(devs):
        raise ValueError(f"need {n_devices} devices, have {len(devs)}")
    return Mesh(np.array(devs[:n_devices]), (axis,))


def make_mesh2d(
    dp: int, p: int, axes: Sequence[str] = ("data", "p")
) -> Mesh:
    """(dp x p) mesh: data-parallel batches x pi-decomposition segments."""
    devs = jax.devices()
    if dp * p > len(devs):
        raise ValueError(f"need {dp * p} devices, have {len(devs)}")
    return Mesh(np.array(devs[: dp * p]).reshape(dp, p), tuple(axes))


def how_many_devices() -> int:
    """Device-capacity probe (parity with the reference probes N4/N5:
    how-many-cpu-cores / how-many-concurrent-blocks)."""
    return len(jax.devices())
