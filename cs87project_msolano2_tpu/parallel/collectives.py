"""The sanctioned collective-call layer (check rule PIF108).

Every inter-chip collective this package dispatches goes through this
module — the ONE funnel point where the supervision discipline
(docs/MULTICHIP.md) attaches.  MULTICHIP_r05 hung an 8-device
``all_to_all`` rendezvous with only a buried C++ log line as evidence;
a collective call site scattered somewhere in parallel/ is a call site
the supervisor cannot see, the escape path cannot re-plan around, and
check rule PIF108 now flags.  Entry points that dispatch a collective
arm supervision OUTSIDE jit (``resilience.supervise_collective`` /
``collective_watchdog``) around the jitted call; the helpers here are
the in-jit dispatch they guard.

This module deliberately contains NO policy: tiled-transpose semantics
only, so the escape path (parallel/escape.py) can reproduce the exact
dataflow without the collective.
"""

from __future__ import annotations

import jax


def all_to_all(v, axis: str, split_axis: int, concat_axis: int):
    """Tiled ``all_to_all`` transpose over a named mesh axis — the
    2-D FFT / Poisson slab transpose primitive (the collective the
    r05 hang wedged)."""
    return jax.lax.all_to_all(v, axis, split_axis=split_axis,
                              concat_axis=concat_axis, tiled=True)
