"""Multi-host scaling (SURVEY.md §5 last row: 'DCN only for the v4-32
slab config').

The reference's answer to multi-node was "the design makes it
unnecessary" — P shared-nothing threads in one address space represent it
fully (SURVEY.md §4).  The same argument holds here across ICI, but a
real v4-32-class slab run spans hosts, so this module wraps the JAX
multi-process runtime: call `init_distributed()` once per process (it
no-ops outside a launcher environment), then `global_mesh()` builds a
mesh over every chip in the job; shard_map code from this package runs on
it unchanged — XLA routes the pi-FFT with zero collectives regardless of
DCN, and the 2-D/3-D transposes ride ICI within a slice and DCN across.

Single-process validation path: the driver's dryrun_multichip and the
test suite use XLA_FLAGS=--xla_force_host_platform_device_count instead.

Rendezvous discipline (resilience subsystem): collective regions run
under :func:`collective_watchdog` — a configurable deadline
(``PIFFT_RENDEZVOUS_DEADLINE_S``) surfaced as a structured
``CollectiveTimeout`` diagnostic instead of the buried rendezvous.cc
"thread may be stuck" C++ line MULTICHIP_r05 recorded.  The watchdog,
the :class:`CollectiveTimeout` type, and the deadline knob are
re-exported here so parallel callers need only this module.
"""

from __future__ import annotations

import itertools
import os
import threading
from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh

from ..resilience import (  # noqa: F401  (re-exports: rendezvous discipline)
    CollectiveAborted,
    CollectiveTimeout,
    HostDesyncError,
    collective_watchdog,
    rendezvous_deadline_s,
    supervise_collective,
)


def init_distributed(coordinator: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None) -> bool:
    """Initialize the JAX distributed runtime if this looks like (or is
    explicitly configured as) a multi-process job.  Returns True if
    initialization happened.

    The rendezvous deadline knob bounds initialization too: a
    coordinator that never forms the job surfaces as a classified
    :class:`CollectiveTimeout` (TRANSIENT — the launcher may retry)
    instead of an open-ended hang."""
    coordinator = coordinator or os.environ.get("PIFFT_COORDINATOR")
    if num_processes is None:
        num_processes = int(os.environ.get("PIFFT_NUM_PROCESSES", "0") or 0)
    if process_id is None:
        pid = os.environ.get("PIFFT_PROCESS_ID")
        process_id = int(pid) if pid is not None else None
    if not coordinator or num_processes <= 1:
        return False
    from ..obs import events, spans

    kwargs = {}
    if os.environ.get("PIFFT_RENDEZVOUS_DEADLINE_S", "").strip():
        # jax.distributed.initialize grew initialization_timeout after
        # 0.4.x-era releases; pass it only when both the knob is set and
        # this jax accepts it.  rendezvous_deadline_s() owns the parse
        # (a malformed value warns and serves the default — it must not
        # crash init when the watchdog tolerates the same knob).
        kwargs["initialization_timeout"] = max(
            int(round(rendezvous_deadline_s())), 1)
    try:
        # the job-formation rendezvous is a collective region like any
        # other: span it so a slow coordinator shows up named in the
        # trace/event stream (docs/OBSERVABILITY.md)
        with spans.span("collective:init_distributed",
                        processes=num_processes):
            jax.distributed.initialize(
                coordinator_address=coordinator,
                num_processes=num_processes,
                process_id=process_id,
                **kwargs,
            )
    except TypeError:
        with spans.span("collective:init_distributed",
                        processes=num_processes, compat="no-timeout"):
            jax.distributed.initialize(
                coordinator_address=coordinator,
                num_processes=num_processes,
                process_id=process_id,
            )
    except Exception as e:
        from ..resilience import FaultKind, classify

        if classify(e) is FaultKind.TRANSIENT:
            raise CollectiveTimeout(
                f"distributed init did not form a {num_processes}-process "
                f"job at {coordinator} ({type(e).__name__}: "
                f"{str(e)[:200]})") from e
        raise
    events.emit("distributed_init", coordinator=coordinator,
                processes=num_processes, process_id=process_id)
    return True


def global_mesh(axis: str = "p") -> Mesh:
    """1-D mesh over every device in the (possibly multi-host) job."""
    return Mesh(np.array(jax.devices()), (axis,))


# ------------------------------------------------- fallback consensus
#
# Degradation must be MULTIHOST-CONSISTENT: if one host escapes to the
# collective-free path while another retries the all_to_all, the
# retrying hosts enter the next rendezvous with a participant that
# will never arrive — the escape itself would manufacture the exact
# r05 wedge it exists to cure.  So before ANY host switches, all hosts
# agree on the fallback epoch through the coordination service's KV
# store + barrier, with its own bounded timeout: either everyone
# switches, or the consensus failure surfaces as a classified
# HostDesyncError (PERMANENT — no local retry can reconcile a split
# brain) instead of a silent split.

_EPOCH_COUNTER = itertools.count(1)
_EPOCH_LOCK = threading.Lock()


def _distributed_client():
    """The process's coordination-service client, or None outside a
    multi-process job.  jax's internal location has been stable across
    the supported releases; treat any import/attr drift as
    single-process (the consensus then short-circuits locally, which
    is correct there)."""
    try:
        from jax._src.distributed import global_state

        return global_state.client
    except Exception:  # pragma: no cover - import drift  # pifft: noqa[PIF501]: optional-dependency import drift probe — absence is the signal, not an error
        return None


def agree_on_fallback(label: str, reason: str = "",
                      deadline_s: Optional[float] = None,
                      client=None, processes: Optional[int] = None) -> int:
    """All-hosts agreement on the next fallback epoch; returns the
    agreed epoch.

    Single-process jobs (and the virtual-mesh test path) agree
    trivially.  In a multi-process job every host publishes its intent
    under ``pifft/fallback/<epoch>/<pid>`` and waits at the
    ``pifft-fallback-<epoch>`` barrier with a bounded timeout (the
    rendezvous deadline): hosts that went through the same sequence of
    escapes hold the same epoch counter, so a barrier that forms means
    every host is switching together — and one that does not raises
    :class:`HostDesyncError` within the deadline instead of stranding
    the fast host.  `client`/`processes` are injectable for tests."""
    from ..obs import events, spans

    with _EPOCH_LOCK:
        epoch = next(_EPOCH_COUNTER)
    deadline = float(deadline_s if deadline_s is not None
                     else rendezvous_deadline_s())
    if client is None:
        client = _distributed_client()
    if processes is None:
        processes = jax.process_count() if client is not None else 1
    with spans.span("collective:fallback_consensus", epoch=epoch,
                    deadline_s=deadline):
        if client is None or processes <= 1:
            events.emit("fallback_consensus", label=label, epoch=epoch,
                        agreed=True, processes=1,
                        reason=str(reason)[:200])
            return epoch
        try:
            client.key_value_set(
                f"pifft/fallback/{epoch}/{jax.process_index()}",
                f"{label}: {reason}"[:512])
            client.wait_at_barrier(f"pifft-fallback-{epoch}",
                                   timeout_in_ms=max(
                                       int(deadline * 1000), 1))
        except Exception as e:
            events.emit("fallback_consensus", label=label, epoch=epoch,
                        agreed=False, processes=processes,
                        error=f"{type(e).__name__}: {str(e)[:200]}")
            raise HostDesyncError(
                f"fallback consensus for epoch {epoch} at {label} did "
                f"not form within {deadline:.0f}s — hosts may be split "
                f"between the all_to_all and collective_free paths "
                f"({type(e).__name__}: {str(e)[:200]})") from e
        events.emit("fallback_consensus", label=label, epoch=epoch,
                    agreed=True, processes=processes,
                    reason=str(reason)[:200])
        return epoch
