"""Multi-host scaling (SURVEY.md §5 last row: 'DCN only for the v4-32
slab config').

The reference's answer to multi-node was "the design makes it
unnecessary" — P shared-nothing threads in one address space represent it
fully (SURVEY.md §4).  The same argument holds here across ICI, but a
real v4-32-class slab run spans hosts, so this module wraps the JAX
multi-process runtime: call `init_distributed()` once per process (it
no-ops outside a launcher environment), then `global_mesh()` builds a
mesh over every chip in the job; shard_map code from this package runs on
it unchanged — XLA routes the pi-FFT with zero collectives regardless of
DCN, and the 2-D/3-D transposes ride ICI within a slice and DCN across.

Single-process validation path: the driver's dryrun_multichip and the
test suite use XLA_FLAGS=--xla_force_host_platform_device_count instead.
"""

from __future__ import annotations

import os
from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh


def init_distributed(coordinator: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None) -> bool:
    """Initialize the JAX distributed runtime if this looks like (or is
    explicitly configured as) a multi-process job.  Returns True if
    initialization happened."""
    coordinator = coordinator or os.environ.get("PIFFT_COORDINATOR")
    if num_processes is None:
        num_processes = int(os.environ.get("PIFFT_NUM_PROCESSES", "0") or 0)
    if process_id is None:
        pid = os.environ.get("PIFFT_PROCESS_ID")
        process_id = int(pid) if pid is not None else None
    if not coordinator or num_processes <= 1:
        return False
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    return True


def global_mesh(axis: str = "p") -> Mesh:
    """1-D mesh over every device in the (possibly multi-host) job."""
    return Mesh(np.array(jax.devices()), (axis,))
