"""Multi-chip pi-FFT: the paper's zero-communication claim, made literal
on a TPU mesh.

Input replicated to every device at initialization (the reference
broadcasts the input into every block's scratchpad, …cuda.cu:307-313);
each device runs its own funnel chain (selected by its mesh index) and
its local tube; the output is sharded along the segment axis.  The
computation body contains NO collectives — tests assert the compiled
HLO is collective-free (test_parallel.py), which is the machine-checked
form of the paper's thesis.
"""

from __future__ import annotations

from functools import partial

import jax
from jax.sharding import PartitionSpec as P

from ..utils.compat import shard_map

from ..models.pi_fft import funnel_single, tube
from ..ops.twiddle import twiddle_tables


def pi_fft_sharded(xr, xi, mesh, axis: str = "p"):
    """pi-FFT over a 1-D mesh axis.  xr/xi: (n,) replicated; returns
    (n,) planes in pi layout, sharded along the mesh axis.
    """
    p = mesh.shape[axis]
    n = xr.shape[-1]
    tables = twiddle_tables(n)

    def device_fn(xr_loc, xi_loc):
        pi = jax.lax.axis_index(axis)
        fr, fi = funnel_single(xr_loc, xi_loc, pi, p, tables)
        tr, ti = tube(fr, fi, n, p, tables)
        return tr, ti  # (n/p,) per device -> (n,) sharded

    fn = shard_map(
        device_fn,
        mesh=mesh,
        in_specs=(P(), P()),  # replicated
        out_specs=(P(axis), P(axis)),  # segment-sharded
    )
    return fn(xr, xi)


def pi_fft_sharded_batched(xr, xi, mesh, data_axis: str = "data",
                           seq_axis: str = "p"):
    """Batched pi-FFT over a 2-D (data x p) mesh: batches sharded over
    `data_axis` (plain DP), each signal decomposed over `seq_axis` (the
    pi analogue of sequence/context parallelism).  xr/xi: (B, n).
    Still zero collectives.
    """
    p = mesh.shape[seq_axis]
    n = xr.shape[-1]
    tables = twiddle_tables(n)

    def device_fn(xr_loc, xi_loc):  # (B/dp, n) replicated along seq axis
        pi = jax.lax.axis_index(seq_axis)
        fr, fi = funnel_single(xr_loc, xi_loc, pi, p, tables)
        tr, ti = tube(fr, fi, n, p, tables)
        b = tr.shape[0]
        return tr.reshape(b, n // p), ti.reshape(b, n // p)

    fn = shard_map(
        device_fn,
        mesh=mesh,
        in_specs=(P(data_axis, None), P(data_axis, None)),
        out_specs=(P(data_axis, seq_axis), P(data_axis, seq_axis)),
    )
    return fn(xr, xi)


def jit_pi_fft_sharded(mesh, axis: str = "p"):
    """jit-wrapped pi_fft_sharded bound to a mesh (convenience for the
    harness and __graft_entry__)."""
    return jax.jit(partial(pi_fft_sharded, mesh=mesh, axis=axis))
