"""Multi-chip pi-FFT: the paper's zero-communication claim, made literal
on a TPU mesh.

Input replicated to every device at initialization (the reference
broadcasts the input into every block's scratchpad, …cuda.cu:307-313);
each device runs its own funnel chain (selected by its mesh index) and
its local tube; the output is sharded along the segment axis.  The
computation body contains NO collectives — tests assert the compiled
HLO is collective-free (test_parallel.py), which is the machine-checked
form of the paper's thesis.

Kernel dispatch: each device's tube is a standalone (n/p)-point
pi-layout transform, resolved through the ONE shared policy
``models.pi_fft.resolve_tube_plan`` — the plan subsystem serves it a
per-SHARD-shape kernel: at segment lengths past 2^20 the single-pass
fourstep pipeline, at row-eligible lengths the rows kernel.  The plan
path auto-engages only above ``PLAN_SEGMENT_MIN`` (where the unrolled
jnp tube hits its compile-time cliff); pass ``plan=`` to force it, or
``plan=False`` to pin the jnp tube.
"""

from __future__ import annotations

from functools import partial

import jax
from jax.sharding import PartitionSpec as P

from ..utils.compat import shard_map

from ..models.pi_fft import funnel_single, resolve_tube_plan, tube
from ..ops.twiddle import twiddle_tables
from ..resilience.inject import maybe_fault

# segment length above which the plan path engages by default: the
# unrolled jnp tube's compile time explodes past one VMEM tile
# (ops.pallas_fft.MAX_ROW_TILE), which is also where the kernel family
# starts to matter
PLAN_SEGMENT_MIN = 1 << 16


def pi_fft_sharded(xr, xi, mesh, axis: str = "p", plan=None):
    """pi-FFT over a 1-D mesh axis.  xr/xi: (n,) replicated; returns
    (n,) planes in pi layout, sharded along the mesh axis.

    `plan` routes each device's tube through the plan subsystem (see
    module docstring); the funnel stays the replicated scalar-select
    chain either way, so the body remains collective-free.
    """
    maybe_fault("shard")  # resilience injection site (docs/RESILIENCE.md)
    p = mesh.shape[axis]
    n = xr.shape[-1]
    tables = twiddle_tables(n)
    seg_plan = resolve_tube_plan((n // p,), plan,
                                 min_segment=PLAN_SEGMENT_MIN)

    def device_fn(xr_loc, xi_loc):
        pi = jax.lax.axis_index(axis)
        fr, fi = funnel_single(xr_loc, xi_loc, pi, p, tables)
        if seg_plan is not None:
            tr, ti = seg_plan.execute(fr, fi)
        else:
            tr, ti = tube(fr, fi, n, p, tables)
        return tr, ti  # (n/p,) per device -> (n,) sharded

    fn = shard_map(
        device_fn,
        mesh=mesh,
        in_specs=(P(), P()),  # replicated
        out_specs=(P(axis), P(axis)),  # segment-sharded
        # vma checking stays on for the pure-jnp body; the kernel path
        # disables it like parallel/batched.py (the Pallas HLO
        # interpreter cannot carry varying-manual-axes through its grid
        # while-loop — the error text itself prescribes this)
        check=seg_plan is None,
    )
    return fn(xr, xi)


def pi_fft_sharded_batched(xr, xi, mesh, data_axis: str = "data",
                           seq_axis: str = "p", plan=None):
    """Batched pi-FFT over a 2-D (data x p) mesh: batches sharded over
    `data_axis` (plain DP), each signal decomposed over `seq_axis` (the
    pi analogue of sequence/context parallelism).  xr/xi: (B, n).
    Still zero collectives; the tube goes through the per-shard-shape
    plan exactly as in :func:`pi_fft_sharded` (keyed on the
    (B/dp, n/p) segment block each device actually transforms).
    """
    maybe_fault("shard")  # resilience injection site (docs/RESILIENCE.md)
    p = mesh.shape[seq_axis]
    n = xr.shape[-1]
    tables = twiddle_tables(n)
    bloc = xr.shape[0] // mesh.shape[data_axis]
    seg_plan = resolve_tube_plan((bloc, n // p), plan,
                                 min_segment=PLAN_SEGMENT_MIN)

    def device_fn(xr_loc, xi_loc):  # (B/dp, n) replicated along seq axis
        pi = jax.lax.axis_index(seq_axis)
        fr, fi = funnel_single(xr_loc, xi_loc, pi, p, tables)
        if seg_plan is not None:
            tr, ti = seg_plan.execute(fr, fi)
        else:
            tr, ti = tube(fr, fi, n, p, tables)
        b = tr.shape[0]
        return tr.reshape(b, n // p), ti.reshape(b, n // p)

    fn = shard_map(
        device_fn,
        mesh=mesh,
        in_specs=(P(data_axis, None), P(data_axis, None)),
        out_specs=(P(data_axis, seq_axis), P(data_axis, seq_axis)),
        check=seg_plan is None,  # see pi_fft_sharded
    )
    return fn(xr, xi)


def jit_pi_fft_sharded(mesh, axis: str = "p"):
    """jit-wrapped pi_fft_sharded bound to a mesh (convenience for the
    harness and __graft_entry__)."""
    return jax.jit(partial(pi_fft_sharded, mesh=mesh, axis=axis))
