"""Backend-dispatch boundary (the `fourier_backend_t` table of the north
star): every backend exposes the same run contract — pi-layout output plus
total/funnel/tube timers — so the harness and analysis layers are
backend-agnostic, exactly what the reference's triplicated design lacked.
"""

from .base import Backend, RunResult  # noqa: F401
from .registry import get_backend, list_backends  # noqa: F401
