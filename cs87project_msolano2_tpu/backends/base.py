"""The backend contract (Python face of native/pifft.h's pif_backend)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Protocol

import numpy as np


@dataclass
class RunResult:
    """One pi-FFT run: output in pi layout (global DIF bit-reversed order,
    processor Pi owning [Pi*n/p, (Pi+1)*n/p)) + phase timers in ms.
    `out` is None when the run was timing-only (fetch=False)."""

    out: Optional[np.ndarray]  # complex64, pi layout
    total_ms: float
    funnel_ms: float
    tube_ms: float
    # True when the timers are dispatch-inclusive wall time rather than
    # honest device time (the loop-slope noise-floor fallback).  The
    # harness marks such TSV rows DEGRADED and the analysis excludes them.
    degraded: bool = False


class Backend(Protocol):
    name: str

    def capacity(self) -> Optional[int]:
        """Max sensible p on this hardware, or None if unlimited."""
        ...

    def run(self, x: np.ndarray, p: int, reps: int = 1,
            fetch: bool = True, timers: bool = True) -> RunResult:
        """pi-DFT of complex64 `x` (power-of-two length) with p virtual
        processors.  `reps`: timed repetitions (best-of); the output is
        from the last rep.

        fetch=False skips materializing the output on the host.  This
        matters for remote-accelerator timing: on the axon TPU tunnel the
        FIRST device->host result transfer permanently degrades the
        process to ~100 ms/dispatch (measured; fresh executables stay
        slow too), so timing sweeps must run entirely fetch-free and
        fetch results only afterwards — the harness does exactly that."""
        ...


def check_run_args(x: np.ndarray, p: int) -> np.ndarray:
    n = x.shape[-1]
    if n & (n - 1) or n <= 0:
        raise ValueError(f"n={n} must be a power of two")
    if p & (p - 1) or p <= 0 or p > n:
        raise ValueError(f"p={p} must be a power of two <= n={n}")
    return np.ascontiguousarray(x, dtype=np.complex64)
