"""Backend registry — name -> Backend, the dispatch boundary's front door.

Imports are lazy so the native-only CLI path never pays the jax import
and the JAX path works where the C toolchain is absent.
"""

from __future__ import annotations

from typing import List

_NAMES = ("serial", "pthreads", "cpu", "jax", "jax-scan",
          "jax-unrolled", "pallas", "einsum")


def list_backends() -> List[str]:
    return list(_NAMES)


def get_backend(name: str):
    if name in ("cpu", "pthreads"):
        from .cpu import NativeBackend

        return NativeBackend("pthreads")
    if name == "serial":
        from .cpu import NativeBackend

        return NativeBackend("serial")
    if name == "jax":
        from .jax_backend import JaxBackend

        return JaxBackend("jnp")
    if name == "jax-unrolled":
        # the unrolled-stage tube pinned at EVERY n (up to the compile
        # ceiling) — the producer of the committed negative-result
        # dataset (its stride-dependent stage costs measurably violate
        # the on-chip law; tests/test_committed_datasets.py asserts the
        # criterion keeps rejecting it).  Plain "jax" auto-selects
        # unrolled below SCAN_MIN_N and scan above.
        from .jax_backend import JaxBackend

        return JaxBackend("unrolled")
    if name == "jax-scan":
        # the jnp pi-FFT with the constant-geometry (Pease) scan tube at
        # EVERY n: each stage runs the identical body, giving the
        # cleanest scaling of the XLA impls — measured to follow the
        # PER-PROCESSOR law on one chip (the VPU absorbs the leading p
        # dimension; see datasets/README.md), where the unrolled tube's
        # stride-dependent stage costs fit no law at all (the committed
        # negative exhibit).
        from .jax_backend import JaxBackend

        return JaxBackend("scan")
    if name == "pallas":
        from .jax_backend import JaxBackend

        return JaxBackend("pallas")
    if name == "einsum":
        from .jax_backend import JaxBackend

        return JaxBackend("einsum")
    raise ValueError(f"unknown backend '{name}' (have: {', '.join(_NAMES)})")
