"""Backend registry — name -> Backend, the dispatch boundary's front door.

Imports are lazy so the native-only CLI path never pays the jax import
and the JAX path works where the C toolchain is absent.
"""

from __future__ import annotations

from typing import List

_NAMES = ("serial", "pthreads", "cpu", "jax", "pallas", "einsum")


def list_backends() -> List[str]:
    return list(_NAMES)


def get_backend(name: str):
    if name in ("cpu", "pthreads"):
        from .cpu import NativeBackend

        return NativeBackend("pthreads")
    if name == "serial":
        from .cpu import NativeBackend

        return NativeBackend("serial")
    if name == "jax":
        from .jax_backend import JaxBackend

        return JaxBackend("jnp")
    if name == "pallas":
        from .jax_backend import JaxBackend

        return JaxBackend("pallas")
    if name == "einsum":
        from .jax_backend import JaxBackend

        return JaxBackend("einsum")
    raise ValueError(f"unknown backend '{name}' (have: {', '.join(_NAMES)})")
