"""The `jax` backend: the funnel/tube pi-FFT compiled with XLA for TPU.

Compilation is cached per (n, p) shape; twiddle tables are baked into the
compiled program as constants (they are the "weights" of this model).
Phase timers follow the reference's contract (funnel / tube / total) but
the TPU way: separately-jitted phases timed with block_until_ready, plus
a fused whole-transform program for the honest total (XLA fuses across
the phase boundary, and the fused number is what bench.py reports).
"""

from __future__ import annotations

from functools import lru_cache, partial
from typing import Optional

import numpy as np

from ..utils.timing import time_ms
from .base import RunResult, check_run_args


@lru_cache(maxsize=32)
def _compiled(n: int, p: int, impl: str):
    import jax

    from ..models.pi_fft import funnel, pi_fft_pi_layout, tube
    from ..ops.twiddle import twiddle_tables

    # keep the tables as NUMPY arrays: jnp.asarray at trace time folds them
    # into the executable as constants.  Pre-converting to device arrays
    # makes them closure-captured runtime buffers, which the axon remote
    # relay re-uploads on EVERY call (~100 ms/call observed at n=2^16).
    tables = twiddle_tables(n)

    if impl == "pallas":
        from ..ops.pallas_fft import pi_fft_pi_layout_pallas

        full = jax.jit(partial(pi_fft_pi_layout_pallas, p=p))
    else:
        full = jax.jit(lambda xr, xi: pi_fft_pi_layout(xr, xi, p, tables))

    funnel_f = jax.jit(lambda xr, xi: funnel(xr, xi, p, tables))
    tube_f = jax.jit(lambda sr, si: tube(sr, si, n, p, tables))
    return funnel_f, tube_f, full


class JaxBackend:
    def __init__(self, impl: str = "jnp"):
        self.name = "jax" if impl == "jnp" else impl
        self._impl = impl

    def capacity(self) -> Optional[int]:
        return None  # virtual processors: any power of two <= n

    def run(self, x: np.ndarray, p: int, reps: int = 1,
            fetch: bool = True) -> RunResult:
        import jax
        import jax.numpy as jnp

        x = check_run_args(x, p)
        n = x.shape[-1]
        funnel_f, tube_f, full_f = _compiled(n, p, self._impl)

        xr = jax.device_put(jnp.asarray(np.real(x), dtype=jnp.float32))
        xi = jax.device_put(jnp.asarray(np.imag(x), dtype=jnp.float32))

        # All timing strictly BEFORE any device->host fetch: on the axon
        # tunnel the first result transfer permanently drops the process
        # into a ~100 ms/dispatch mode (see Backend.run docstring).
        funnel_ms, (fr, fi) = time_ms(funnel_f, xr, xi, reps=reps)
        tube_ms, _ = time_ms(tube_f, fr, fi, reps=reps)
        total_ms, (yr, yi) = time_ms(full_f, xr, xi, reps=reps)

        out = None
        if fetch:
            out = np.asarray(yr).astype(np.complex64)
            out.imag = np.asarray(yi)
        return RunResult(
            out=out, total_ms=total_ms, funnel_ms=funnel_ms, tube_ms=tube_ms
        )
