"""The `jax` backend: the funnel/tube pi-FFT compiled with XLA for TPU.

Compilation is cached per (n, p) shape; twiddle tables are baked into the
compiled program as constants (they are the "weights" of this model).
Phase timers follow the reference's contract (funnel / tube / total).

Timing method depends on the platform: on CPU (tests, local runs)
block_until_ready is a real barrier and phases are timed directly; on
remote accelerators (the axon TPU relay) block_until_ready does NOT wait
for the device, so each phase is measured with the loop-slope method
(utils/timing.py::loop_slope_ms) — K-iteration in-jit loops with a
scalar-fetch barrier, per-op time recovered as the slope between two K
values so the ~100 ms relay overhead cancels exactly.
"""

from __future__ import annotations

from functools import lru_cache, partial
from typing import Optional

import numpy as np

from ..utils.timing import loop_slope_ms, needs_loop_slope, time_ms
from .base import RunResult, check_run_args


@lru_cache(maxsize=32)
def _compiled(n: int, p: int, impl: str):
    import jax

    from ..models.pi_fft import funnel, pi_fft_pi_layout, tube
    from ..ops.twiddle import twiddle_tables

    # keep the tables as NUMPY arrays: jnp.asarray at trace time folds them
    # into the executable as constants.  Pre-converting to device arrays
    # makes them closure-captured runtime buffers, which the axon remote
    # relay re-uploads on EVERY call (~100 ms/call observed at n=2^16).
    tables = twiddle_tables(n)

    if impl == "pallas":
        from ..ops.pallas_fft import pi_fft_pi_layout_pallas

        full = jax.jit(partial(pi_fft_pi_layout_pallas, p=p))
    elif impl == "einsum":
        import jax.numpy as jnp

        from ..models.direct_dft import dft_direct_pi

        def _einsum_full(xr, xi):
            y = dft_direct_pi(xr + 1j * xi.astype(jnp.complex64), p)
            return jnp.real(y), jnp.imag(y)

        full = jax.jit(_einsum_full)
    else:
        full = jax.jit(lambda xr, xi: pi_fft_pi_layout(xr, xi, p, tables))

    funnel_f = jax.jit(lambda xr, xi: funnel(xr, xi, p, tables))
    if impl == "pallas":
        # pallas tube for the phase timer too: the fully-unrolled jnp tube
        # takes minutes of XLA compile at n=2^20; the kernel takes seconds
        from ..ops.pallas_fft import tube_pallas

        tube_raw = partial(tube_pallas, n=n, p=p)
    else:
        tube_raw = lambda sr, si: tube(sr, si, n, p, tables)  # noqa: E731
    tube_f = jax.jit(tube_raw)
    return funnel_f, tube_f, full


@lru_cache(maxsize=32)
def _loop_bodies(n: int, p: int, impl: str):
    """Shape-closed raw bodies for loop-slope timing.

    funnel body folds the (p, n/p) result back to (n,) planes (a free
    reshape) so it can iterate; the tube body iterates on (p, n/p)."""
    from ..models.pi_fft import funnel, pi_fft_pi_layout, tube

    from ..ops.twiddle import twiddle_tables

    tables = twiddle_tables(n)
    # amplitude renormalization so hundreds of loop iterations neither
    # overflow nor denormalize; per application, random data grows by
    # ~sqrt(len) through a full transform but only ~sqrt(p) through the
    # funnel's log2(p) half-stages
    inv_rn = np.float32(1.0 / np.sqrt(n))
    inv_rs = np.float32(1.0 / np.sqrt(n // p))
    inv_rp = np.float32(1.0 / np.sqrt(p))

    def funnel_body(c):
        fr, fi = funnel(c[0], c[1], p, tables)
        return fr.reshape(n) * inv_rp, fi.reshape(n) * inv_rp

    if impl == "pallas":
        from ..ops.pallas_fft import pi_fft_pi_layout_pallas, tube_pallas

        def tube_body(c):
            tr, ti = tube_pallas(c[0], c[1], n, p)
            return tr * inv_rs, ti * inv_rs

        def full_body(c):
            yr, yi = pi_fft_pi_layout_pallas(c[0], c[1], p)
            return yr * inv_rn, yi * inv_rn
    elif impl == "einsum":
        # plane-level einsum: the loop body must stay all-float (the axon
        # relay cannot lower complex inside While bodies)
        from ..models.direct_dft import dft_direct_pi_planes

        def tube_body(c):
            return c

        def full_body(c):
            yr, yi = dft_direct_pi_planes(c[0], c[1], p)
            return yr * inv_rn, yi * inv_rn
    else:
        def tube_body(c):
            tr, ti = tube(c[0], c[1], n, p, tables)
            return tr * inv_rs, ti * inv_rs

        def full_body(c):
            yr, yi = pi_fft_pi_layout(c[0], c[1], p, tables)
            return yr * inv_rn, yi * inv_rn

    return funnel_body, tube_body, full_body


class JaxBackend:
    def __init__(self, impl: str = "jnp"):
        self.name = "jax" if impl == "jnp" else impl
        self._impl = impl

    def capacity(self) -> Optional[int]:
        return None  # virtual processors: any power of two <= n

    def run(self, x: np.ndarray, p: int, reps: int = 1,
            fetch: bool = True) -> RunResult:
        import jax
        import jax.numpy as jnp

        x = check_run_args(x, p)
        n = x.shape[-1]
        funnel_f, tube_f, full_f = _compiled(n, p, self._impl)

        xr = jax.device_put(jnp.asarray(np.real(x), dtype=jnp.float32))
        xi = jax.device_put(jnp.asarray(np.imag(x), dtype=jnp.float32))

        if needs_loop_slope():
            # remote accelerator: loop-slope with scalar-fetch barriers
            # (block_until_ready does not wait on the relay — see module
            # docstring).  Tube iterates on (p, s) planes; its input
            # content is irrelevant to its cost, so reshaped input works.
            funnel_body, tube_body, full_body = _loop_bodies(
                n, p, self._impl
            )
            total_ms = loop_slope_ms(full_body, (xr, xi), reps=reps)
            if self._impl == "einsum":
                funnel_ms, tube_ms = 0.0, total_ms
            else:
                funnel_ms = loop_slope_ms(funnel_body, (xr, xi), reps=reps)
                tube_ms = loop_slope_ms(
                    tube_body,
                    (xr.reshape(p, n // p), xi.reshape(p, n // p)),
                    reps=reps,
                )
            yr, yi = full_f(xr, xi) if fetch else (None, None)
        elif self._impl == "einsum":
            # the direct einsum has no funnel/tube phase split (its law is
            # Theta(n^2/p) per processor, not the butterfly law)
            total_ms, (yr, yi) = time_ms(full_f, xr, xi, reps=reps)
            funnel_ms, tube_ms = 0.0, total_ms
        else:
            funnel_ms, (fr, fi) = time_ms(funnel_f, xr, xi, reps=reps)
            tube_ms, _ = time_ms(tube_f, fr, fi, reps=reps)
            total_ms, (yr, yi) = time_ms(full_f, xr, xi, reps=reps)

        out = None
        if fetch:
            out = np.asarray(yr).astype(np.complex64)
            out.imag = np.asarray(yi)
        return RunResult(
            out=out, total_ms=total_ms, funnel_ms=funnel_ms, tube_ms=tube_ms
        )
