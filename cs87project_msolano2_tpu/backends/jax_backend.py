"""The `jax` backend: the funnel/tube pi-FFT compiled with XLA for TPU.

Compilation is cached per (n, p) shape; twiddle tables are baked into the
compiled program as constants (they are the "weights" of this model).
Phase timers follow the reference's contract (funnel / tube / total).

Timing method depends on the platform: on CPU (tests, local runs)
block_until_ready is a real barrier and phases are timed directly; on
remote accelerators (the axon TPU relay) block_until_ready does NOT wait
for the device, so each phase is measured with the loop-slope method
(utils/timing.py::loop_slope_ms) — K-iteration in-jit loops with a
scalar-fetch barrier, per-op time recovered as the slope between two K
values so the ~100 ms relay overhead cancels exactly.
"""

from __future__ import annotations

from functools import lru_cache, partial
from typing import Optional

import numpy as np

from ..utils.timing import (
    LoopSlopeUnresolved,
    loop_slope_ms,
    needs_loop_slope,
    time_ms,
)
from .base import RunResult, check_run_args

# Above this n the jnp impl switches from the fully-unrolled tube to the
# fori_loop stage scan (models.pi_fft.fft_stages_scan): the unrolled HLO
# graph's XLA compile time grows with log2(n) (measured ~102 s at 2^20
# on the relay compile service; the round-1 full-graph blocker was
# minutes); the scan graph holds one stage body regardless of n.
# 2^21 keeps the ENTIRE default sweep grid (n <= 2^20) on the unrolled
# tube: the scan tube is ~8x slower per unit work (per-stage dynamic
# slicing), and a grid mixing the two regimes puts the slow cells only
# at small p, distorting the on-chip law fit (measured: total R^2 0.27
# on the mixed round-4 sweep) — the same regime-consistency rule the
# sharded harness enforces.  Interactive cost: the first jax-backend
# run at n=2^20 pays the ~2 min compile once per process.
SCAN_MIN_N = 1 << 21


@lru_cache(maxsize=32)
def _compiled(n: int, p: int, impl: str, kblock: int | None = None):
    import jax

    from ..models.pi_fft import (
        funnel,
        pi_fft_pi_layout,
        pi_fft_pi_layout_scan,
        tube,
        tube_scan,
    )
    from ..ops.twiddle import twiddle_tables

    # keep the tables as NUMPY arrays: jnp.asarray at trace time folds them
    # into the executable as constants.  Pre-converting to device arrays
    # makes them closure-captured runtime buffers, which the axon remote
    # relay re-uploads on EVERY call (~100 ms/call observed at n=2^16).
    tables = twiddle_tables(n)

    if impl == "pallas":
        from ..ops.pallas_fft import pi_fft_pi_layout_pallas, tube_pallas

        full = jax.jit(partial(pi_fft_pi_layout_pallas, p=p))
        # pallas tube for the phase timer too: the fully-unrolled jnp tube
        # takes minutes of XLA compile at n=2^20; the kernel takes seconds
        tube_raw = partial(tube_pallas, n=n, p=p)
    elif impl == "einsum":
        # the phased einsum model: funnel = coefficient-tensor einsum,
        # tube = blockwise DIF-matrix einsum (models.direct_dft)
        from ..models.direct_dft import (
            funnel_einsum_planes,
            pi_dft_einsum_planes,
            tube_einsum_block,
            tube_einsum_planes,
            tube_einsum_planes_hostblocked,
        )

        # kblock is part of the cache key: needs_loop_slope() is dynamic
        # (env var / configured platforms), so deriving it HERE would let
        # a mode flip serve a stale single-program tube — the exact
        # >2^14-gather program that crashes the relay worker
        funnel_f = jax.jit(partial(funnel_einsum_planes, p=p))
        if kblock is None:
            full = jax.jit(partial(pi_dft_einsum_planes, p=p))
            tube_f = jax.jit(partial(tube_einsum_planes, n=n, p=p))
        else:
            # capacity-lifted tube: one compiled block program (k0
            # traced), s/kblock host dispatches per application — each
            # within the relay's single-program budget
            block_fn = jax.jit(
                partial(tube_einsum_block, n=n, p=p, kblock=kblock)
            )

            def tube_f(sr, si):
                return tube_einsum_planes_hostblocked(
                    sr, si, n, p, kblock, block_fn=block_fn
                )

            def full(xr, xi):
                fr, fi = funnel_f(xr, xi)
                tr, ti = tube_f(fr, fi)
                return (
                    tr.reshape(*xr.shape[:-1], n),
                    ti.reshape(*xi.shape[:-1], n),
                )

        return funnel_f, tube_f, full
    elif impl == "scan" or (impl != "unrolled" and n >= SCAN_MIN_N):
        # impl == "scan": the jax-scan backend — constant-geometry tube
        # at every n so the sweep is regime-pure and each stage costs
        # the same (the law-obedient variant; see registry).
        # impl == "unrolled" pins the unrolled tube instead (negative-
        # exhibit producer; compile time bounds its n in practice).
        full = jax.jit(lambda xr, xi: pi_fft_pi_layout_scan(xr, xi, p, tables))
        tube_raw = lambda sr, si: tube_scan(sr, si, n, p)  # noqa: E731
    else:
        full = jax.jit(lambda xr, xi: pi_fft_pi_layout(xr, xi, p, tables))
        tube_raw = lambda sr, si: tube(sr, si, n, p, tables)  # noqa: E731

    funnel_f = jax.jit(lambda xr, xi: funnel(xr, xi, p, tables))
    tube_f = jax.jit(tube_raw)
    return funnel_f, tube_f, full


@lru_cache(maxsize=32)
def _loop_bodies(n: int, p: int, impl: str, kblock: int | None = None):
    """Shape-closed raw (funnel_body, tube_body) for loop-slope timing.

    funnel body folds the (p, n/p) result back to (n,) planes (a free
    reshape) so it can iterate; the tube body iterates on (p, n/p).
    Only the two phase bodies exist: run() derives total := funnel +
    tube (the reference's nested-timer contract), so a full-transform
    body would never be timed."""
    from ..models.pi_fft import (
        funnel,
        tube,
        tube_scan,
    )

    from ..ops.twiddle import twiddle_tables

    tables = twiddle_tables(n)
    # amplitude renormalization so hundreds of loop iterations neither
    # overflow nor denormalize; per application, random data grows by
    # ~sqrt(seg) through the tube's segment transform but only ~sqrt(p)
    # through the funnel's log2(p) half-stages
    inv_rs = np.float32(1.0 / np.sqrt(n // p))
    inv_rp = np.float32(1.0 / np.sqrt(p))

    def funnel_body(c):
        fr, fi = funnel(c[0], c[1], p, tables)
        return fr.reshape(n) * inv_rp, fi.reshape(n) * inv_rp

    if impl == "pallas":
        from ..ops.pallas_fft import pi_fft_pi_layout_pallas, tube_pallas

        def tube_body(c):
            tr, ti = tube_pallas(c[0], c[1], n, p)
            return tr * inv_rs, ti * inv_rs
    elif impl == "einsum":
        # phased einsum model, all-float plane ops (the axon relay cannot
        # lower complex inside While bodies)
        import jax

        from ..models.direct_dft import (
            funnel_einsum_planes,
            tube_einsum_block,
            tube_einsum_planes,
        )

        def funnel_body(c):  # noqa: F811 — einsum funnel replaces default
            fr, fi = funnel_einsum_planes(c[0], c[1], p)
            return fr.reshape(n) * inv_rp, fi.reshape(n) * inv_rp

        if kblock is None:
            def tube_body(c):
                tr, ti = tube_einsum_planes(c[0], c[1], n, p)
                return tr * inv_rs, ti * inv_rs
        else:
            # capacity-lifted regime: the timed unit is ONE block
            # program (all s/kblock blocks are shape- and work-
            # identical; run() multiplies the slope back up).  The
            # block result scatters into the carry so shapes close;
            # the O(p*kblock) update is noise next to the
            # Theta(kblock*s) block compute.
            def tube_body(c):
                yr, yi = tube_einsum_block(c[0], c[1], 0, n, p, kblock)
                cr = jax.lax.dynamic_update_slice(
                    c[0], yr * inv_rs, (0,) * c[0].ndim
                )
                ci = jax.lax.dynamic_update_slice(
                    c[1], yi * inv_rs, (0,) * c[1].ndim
                )
                return cr, ci

        return funnel_body, tube_body
    elif impl == "scan" or (impl != "unrolled" and n >= SCAN_MIN_N):
        def tube_body(c):
            tr, ti = tube_scan(c[0], c[1], n, p)
            return tr * inv_rs, ti * inv_rs
    else:
        def tube_body(c):
            tr, ti = tube(c[0], c[1], n, p, tables)
            return tr * inv_rs, ti * inv_rs

    return funnel_body, tube_body


_warned_large_p: set[tuple[int, int]] = set()

# Largest einsum-tube segment the relay can run as ONE program: s=2^14
# measured safe (~2 GB twiddle-gather traffic/application); s=2^15 is
# borderline and s=2^16 crashes the TPU worker (see run()).
EINSUM_TUBE_MAX_S = 1 << 14
# Beyond that the tube splits into host-driven block programs (one
# compiled program, s/kblock dispatches — models.direct_dft.
# tube_einsum_block), each within the single-program budget.  The
# program COUNT caps the lift: 64 dispatches/application keeps one
# application under ~2 min of relay round trips, giving s up to
# sqrt(64) * 2^14 = 2^17.
EINSUM_TUBE_MAX_PROGRAMS = 64
EINSUM_TUBE_ABS_MAX_S = EINSUM_TUBE_MAX_S * 8  # sqrt(64) = 8


def einsum_tube_kblock(s: int) -> int | None:
    """Rows per block program for segment length s; None = single
    program (the scan tube) suffices."""
    if s <= EINSUM_TUBE_MAX_S:
        return None
    # keep per-program gather work ~ EINSUM_TUBE_MAX_S^2 entries
    kblock = max((EINSUM_TUBE_MAX_S * EINSUM_TUBE_MAX_S) // s, 1)
    while s % kblock:
        kblock //= 2
    return kblock


class JaxBackend:
    def __init__(self, impl: str = "jnp"):
        self.name = {"jnp": "jax", "scan": "jax-scan",
                     "unrolled": "jax-unrolled"}.get(impl, impl)
        self._impl = impl
        # golden-test tolerance: butterfly impls are bit-exact on the
        # 8-point golden vector; the einsum impl goes through MXU matmuls
        # whose accumulation order is not (see utils.verify.golden_check_tol)
        self.golden_atol = 1e-4 if impl == "einsum" else 0.0

    def capacity(self) -> Optional[int]:
        return None  # virtual processors: any power of two <= n

    def run(self, x: np.ndarray, p: int, reps: int = 1,
            fetch: bool = True, timers: bool = True) -> RunResult:
        """timers=False skips the phase timing entirely (zeros in the
        RunResult) and just computes + fetches — the verification pass
        needs the OUTPUT, and re-running loop-slope per verified cell
        was measured to dominate a sweep's verify phase on the relay."""
        import jax
        import jax.numpy as jnp

        x = check_run_args(x, p)
        n = x.shape[-1]
        if (self._impl == "einsum" and needs_loop_slope()
                and n // p > EINSUM_TUBE_ABS_MAX_S):
            # The einsum tube is a dense per-segment DFT: Theta(s^2)
            # work AND s^2 on-the-fly twiddle-gather traffic per
            # application (~34 GB at s=2^16).  One application at
            # s >= 2^15 exceeds the relay's ~10 s single-program budget
            # and CRASHES the TPU worker (observed; >1 min restart).
            # s in (2^14, 2^17] is served by the host-blocked tube
            # (einsum_tube_kblock); past that even the blocked split
            # needs > EINSUM_TUBE_MAX_PROGRAMS dispatches/application —
            # a capacity limit of the accelerator path, clipped the way
            # the reference's harness clips infeasible configs
            # (probe-and-clip, run-experiments:42-50).
            raise ValueError(
                f"einsum tube segment s={n // p} exceeds the relay's "
                f"blocked-tube budget (max s={EINSUM_TUBE_ABS_MAX_S}); "
                "use a larger p or the jax/pallas backends"
            )
        if p >= 32 and (n, p) not in _warned_large_p:
            # single-chip backends materialize ALL p virtual processors,
            # so the funnel's redundant work is n(p-1) — at large p it
            # dominates and the run gets SLOWER with p (measured 0.34x
            # at p=64, datasets/README.md).  Real parallelism at large p
            # is the multi-chip path (parallel/pi_shard.py).  Once per
            # (n, p): a harness sweep calls run() reps times per cell.
            import sys

            _warned_large_p.add((n, p))
            print(f"# note: p={p} on a single chip does n(p-1) redundant "
                  "funnel work (the paper's communication/replication "
                  "trade); expect slowdown beyond p~16 — use "
                  "parallel.pi_fft_sharded for real multi-device speedup",
                  file=sys.stderr)
        # compute the einsum tube's blocking ONCE per call from the
        # CURRENT timing mode and thread it into both compile caches
        kblock = (einsum_tube_kblock(n // p)
                  if self._impl == "einsum" and needs_loop_slope()
                  else None)
        funnel_f, tube_f, full_f = _compiled(n, p, self._impl, kblock)

        xr = jax.device_put(jnp.asarray(np.real(x), dtype=jnp.float32))
        xi = jax.device_put(jnp.asarray(np.imag(x), dtype=jnp.float32))

        # Phase timers COMPOSE by construction: total := funnel + tube,
        # exactly the reference's nested-timer semantics (its total timer
        # wraps the two phase timers, …pthreads.c:714-732).  Round 1
        # measured the three as independent fits and got TSV rows with
        # tube > total; deriving total from the phases removes that
        # inconsistency without sacrificing honesty (each phase is still
        # measured on the real compiled phase program).  Tradeoff: the
        # fused full_f program (which produces the returned output) is NOT
        # itself timed here, so cross-phase fusion wins don't show in
        # total_ms; bench.py independently times the real full body, so
        # the headline number is unaffected.
        degraded = False
        if not timers:
            yr, yi = full_f(xr, xi) if fetch else (None, None)
            out = None
            if fetch:
                out = np.asarray(yr).astype(np.complex64)
                out.imag = np.asarray(yi)
            return RunResult(out=out, total_ms=0.0, funnel_ms=0.0,
                             tube_ms=0.0, degraded=False)
        if needs_loop_slope():
            # remote accelerator: loop-slope with scalar-fetch barriers
            # (block_until_ready does not wait on the relay — see module
            # docstring).  Tube iterates on (p, s) planes; its input
            # content is irrelevant to its cost, so reshaped input works.
            funnel_body, tube_body = _loop_bodies(
                n, p, self._impl, kblock
            )
            # The einsum tube does Theta(s^2) work per application; at
            # the capacity limit (s = EINSUM_TUBE_MAX_S, guarded above)
            # the default k1=8 first measurement program is ~8 x ~1 s —
            # within budget but with no headroom, so start the einsum
            # tube at a (1, 4) window; the escalation ladder still grows
            # it if the delta doesn't resolve.
            tube_kw = {}
            tube_mult = 1
            if self._impl == "einsum":
                if n // p >= 1 << 13:
                    tube_kw = dict(k1=1, k2=4)
                if kblock is not None:
                    # blocked tube: the slope times ONE block program;
                    # all s/kblock blocks are identical in shape and
                    # work, so the phase time is the slope scaled up
                    tube_mult = (n // p) // kblock
            try:
                # p == 1: zero funnel iterations (the reference's funnel
                # loop runs log2(p) times, …pthreads.c:419) — the body is
                # an empty program that XLA folds away, which the slope
                # method cannot (and need not) resolve
                # auto_window: sweep cells are visited in magnitude-
                # adjacent order, so seed each fresh body's slope window
                # from the last resolved one (skips most of the
                # escalation ladder's remote recompiles); not used where
                # an explicit window is passed (einsum's tube_kw)
                funnel_ms = 0.0 if p == 1 else loop_slope_ms(
                    funnel_body, (xr, xi), reps=reps, auto_window=True
                )
                tube_ms = tube_mult * loop_slope_ms(
                    tube_body,
                    (xr.reshape(p, n // p), xi.reshape(p, n // p)),
                    reps=reps,
                    auto_window=not tube_kw,
                    **tube_kw,
                )
            except LoopSlopeUnresolved as e:
                # tiny transforms sit below the relay's noise floor at any
                # iteration count (ns-scale op vs ±20 ms jitter); report
                # dispatch-inclusive wall time instead of failing (golden/
                # test mode needs the output, not honest timers)
                import sys

                print(f"# loop-slope unresolved (n={n} p={p}): {e}; "
                      "falling back to dispatch-inclusive timing",
                      file=sys.stderr)
                funnel_ms, (fr, fi) = time_ms(funnel_f, xr, xi, reps=reps)
                tube_ms, _ = time_ms(tube_f, fr, fi, reps=reps)
                degraded = True
            total_ms = funnel_ms + tube_ms
            yr, yi = full_f(xr, xi) if fetch else (None, None)
        else:
            funnel_ms, (fr, fi) = time_ms(funnel_f, xr, xi, reps=reps)
            tube_ms, _ = time_ms(tube_f, fr, fi, reps=reps)
            total_ms = funnel_ms + tube_ms
            yr, yi = full_f(xr, xi) if fetch else (None, None)

        out = None
        if fetch:
            out = np.asarray(yr).astype(np.complex64)
            out.imag = np.asarray(yi)
        return RunResult(
            out=out, total_ms=total_ms, funnel_ms=funnel_ms,
            tube_ms=tube_ms, degraded=degraded,
        )
