"""The `cpu` backend: the native C core via ctypes.

Variants map to the native dispatch table (native/pifft_backends.c):
`serial` runs the P virtual processors sequentially, `pthreads` runs one
pinned OS thread each.  numpy complex64 is layout-identical to the C
pif_c32 {float re, im}, so arrays cross the boundary with zero copies.
"""

from __future__ import annotations

import time
from typing import Optional

import ctypes
import numpy as np

from ..utils.buildlib import load_native
from .base import RunResult, check_run_args


class NativeBackend:
    def __init__(self, variant: str = "pthreads"):
        self.name = variant
        self._variant = variant.encode()

    def capacity(self) -> Optional[int]:
        cap = load_native().pifft_capacity(self._variant)
        if cap < 0:
            raise ValueError(f"unknown native backend '{self.name}'")
        return cap if cap > 0 else None

    def run(self, x: np.ndarray, p: int, reps: int = 1,
            fetch: bool = True, timers: bool = True) -> RunResult:
        # `fetch` is part of the backend contract for remote accelerators;
        # the native output is already host-resident, so it is ignored.
        # `timers` likewise: native phase timers cost nothing extra, so
        # the verification fast path has nothing to skip here.
        del fetch, timers
        x = check_run_args(x, p)
        lib = load_native()
        n = x.shape[-1]
        out = np.empty(n, dtype=np.complex64)
        timers = (ctypes.c_double * 3)()
        best = (float("inf"), 0.0, 0.0)
        # one unmeasured warm-up so first-touch page faults don't count
        # (observed 4x inflation on the first run at n=2^20)
        for rep in range(max(reps, 1) + 1):
            rc = lib.pifft_run(
                self._variant, n, p, x.ctypes.data, out.ctypes.data, timers
            )
            if rc != 0:
                raise RuntimeError(f"native run failed (backend={self.name}, rc={rc})")
            if rep > 0 and timers[0] < best[0]:
                best = (timers[0], timers[1], timers[2])
        return RunResult(out=out, total_ms=best[0], funnel_ms=best[1], tube_ms=best[2])

    def golden_test(self, p: int = 8) -> bool:
        return load_native().pifft_golden_test(self._variant, p) == 0


def num_cores() -> int:
    try:
        return load_native().pifft_num_cores()
    except RuntimeError:
        import os

        return os.cpu_count() or 1


# kept for API symmetry with timing-free callers; a raw clock read, not
# a measurement, so the sanctioned-clock rules are waived here
def wall_ms() -> float:
    return time.perf_counter() * 1e3  # pifft: noqa[PIF102, PIF106]: wall_ms is the backend's documented non-measurement wall stamp, not a timed window
