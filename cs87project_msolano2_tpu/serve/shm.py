"""The same-host shared-memory lane: a slot ring of staging planes.

A sidecar caller on the serving host should not pay the socket for
megabyte planes when the two processes share silicon.  The shm lane
moves only CONTROL over the framed socket: the client writes its
request planes into a slot of a shared-memory ring, sends a binary
REQUEST frame with ``F_SHM`` and the slot index (``payload_len`` 0),
and the server maps the slot as float32 views — the same zero-copy
landing as the inline binary path, minus even the kernel's socket
copy.  Results are written back into the SAME slot and answered with
an ``F_SHM`` RESPONSE; the client owns the slot again once the
response frame arrives.

Lifecycle: the ring is per connection.  The server creates it when a
HELLO carries ``F_WANT_SHM`` and the front was started with ``pifft
serve --shm``; the HELLO_ACK grants the segment name (payload), slot
count (``n``) and slot size (``width``); the client attaches by name.
The server closes AND unlinks the segment when the connection ends —
a vanished client cannot leak host memory.  Slot ownership follows
the request/response frames; the flow-control credit window bounds
in-flight requests, so a well-behaved client never needs more slots
than credits.

The slot write/read-back copies are the TRANSPORT itself (they replace
the socket's kernel copies), not a decode — they are deliberately not
charged to the host-copy meter (serve/wire.py module docstring), and
the wire-smoke asserts the shm round-trip's metered delta is zero.
"""

from __future__ import annotations

import secrets
from multiprocessing import shared_memory
from typing import Optional

import numpy as np


def _untrack(shm: shared_memory.SharedMemory) -> None:
    """Take the segment out of the resource tracker's hands: CPython
    registers ATTACHING handles too (bpo-38119), so a client exit
    would warn about — and may unlink — a segment the server still
    owns, and a same-process attach (tests, the wire smoke) would
    unbalance the tracker's cache.  Lifecycle here is explicit
    instead: the owning server closes AND unlinks on connection end."""
    try:
        from multiprocessing import resource_tracker

        resource_tracker.unregister(shm._name, "shared_memory")
    except Exception:  # pragma: no cover - tracker internals moved  # pifft: noqa[PIF501]: best-effort workaround for a stdlib wart (bpo-38119); attach still works without it
        pass


class ShmRing:
    """``slots`` fixed-size byte slots over one SharedMemory segment.

    Each slot holds two contiguous float32 planes (``xr`` then ``xi``)
    of up to ``slot_bytes // 8`` elements each."""

    def __init__(self, shm: shared_memory.SharedMemory, slots: int,
                 slot_bytes: int, owner: bool):
        self._shm = shm
        self.slots = slots
        self.slot_bytes = slot_bytes
        self.owner = owner

    @property
    def name(self) -> str:
        return self._shm.name

    @classmethod
    def create(cls, slots: int, slot_bytes: int) -> "ShmRing":
        if slots < 1 or slot_bytes < 8:
            raise ValueError(f"shm ring needs >=1 slot of >=8 bytes, "
                             f"got {slots}x{slot_bytes}")
        name = f"pifft-{secrets.token_hex(6)}"
        shm = shared_memory.SharedMemory(
            name=name, create=True, size=slots * slot_bytes)
        _untrack(shm)
        return cls(shm, slots, slot_bytes, owner=True)

    @classmethod
    def attach(cls, name: str, slots: int, slot_bytes: int) -> "ShmRing":
        shm = shared_memory.SharedMemory(name=name)
        if slots < 1 or slot_bytes < 8 or slots * slot_bytes > shm.size:
            # the geometry arrived over the wire (HELLO_ACK); a ring
            # that does not fit the mapped segment would hand out slot
            # views past the end of the buffer
            shm.close()
            raise ValueError(f"ring geometry {slots}x{slot_bytes} does "
                             f"not fit the {shm.size}-byte segment")
        _untrack(shm)
        return cls(shm, slots, slot_bytes, owner=False)

    def _slot_view(self, slot: int) -> memoryview:
        if not 0 <= slot < self.slots:
            raise ValueError(f"slot {slot} out of range "
                             f"(ring has {self.slots})")
        base = slot * self.slot_bytes
        return self._shm.buf[base:base + self.slot_bytes]

    def slot_planes(self, slot: int, width: int,
                    no_xi: bool = False):
        """Zero-copy float32 ``(xr, xi)`` views over one slot — the
        server-side landing, same contract as
        :func:`~.buffers.landing_views`."""
        need = width * 4 * (1 if no_xi else 2)
        if need > self.slot_bytes:
            raise ValueError(f"width {width} needs {need} bytes, slot "
                             f"holds {self.slot_bytes}")
        view = self._slot_view(slot)
        xr = np.frombuffer(view, np.float32, count=width)
        xi = None if no_xi else np.frombuffer(
            view, np.float32, count=width, offset=width * 4)
        return xr, xi

    def write_planes(self, slot: int, xr: np.ndarray,
                     xi: Optional[np.ndarray] = None) -> None:
        """Land request planes in a slot (the client-side transport
        write — it replaces the socket's kernel copy)."""
        width = int(xr.shape[-1])
        dr, di = self.slot_planes(slot, width, no_xi=xi is None)
        np.copyto(dr, xr)
        if xi is not None:
            np.copyto(di, xi)

    def read_planes(self, slot: int, width: int,
                    no_xi: bool = False):
        """Client-side result views after the RESPONSE frame (copy
        them out before reusing the slot)."""
        return self.slot_planes(slot, width, no_xi=no_xi)

    def close(self) -> None:
        try:
            self._shm.close()
        except BufferError:
            # plane views over the segment usually die with their
            # request, but asyncio tasks park exceptions/callbacks in
            # reference cycles — collect and retry before giving up
            # (a still-held client view then keeps its mapping alive
            # until IT dies, which is the right behavior anyway)
            import gc

            gc.collect()
            try:
                self._shm.close()
            except BufferError:  # pragma: no cover - a view outlived us
                pass

    def unlink(self) -> None:
        """Owner-only: release the segment name (idempotent)."""
        if not self.owner:
            return
        # stdlib unlink() unconditionally UNregisters with the
        # tracker; balance the books for the registration _untrack
        # removed, or the tracker daemon logs a KeyError at exit
        try:
            from multiprocessing import resource_tracker

            resource_tracker.register(self._shm._name, "shared_memory")
        except Exception:  # pragma: no cover - tracker internals moved  # pifft: noqa[PIF501]: best-effort bookkeeping around the same stdlib wart as _untrack
            pass
        try:
            self._shm.unlink()
        except FileNotFoundError:  # pragma: no cover - already gone
            pass
