"""Staging-buffer pool: reuse the padded batch planes across requests.

Every coalesced batch stages its requests' float planes into one
``(B_pad, n)`` pair of arrays before the kernel invocation.  Allocating
those per batch at serving rates is pure allocator churn — the arrays
are the same handful of shapes forever (the padded batch buckets of the
served shape set) — so this pool keeps released buffers on a per-shape
free list and hands them back on the next acquire.

The device side of reuse is input donation: the plan executors are
jitted with ``donate_argnums`` via :meth:`plans.core.Plan.executable`,
so XLA may reuse the request planes' device buffers for the outputs.
This pool is the HOST side: the staging arrays a request is copied
into never hit the allocator twice.

Thread-safe (the dispatcher's executor thread releases while the event
loop acquires).  Reuse is observable: ``pifft_serve_buffer_reuse_total``
vs ``pifft_serve_buffer_alloc_total`` counters, and :meth:`stats` for
in-process assertions.
"""

from __future__ import annotations

import threading

import numpy as np


class BufferPool:
    """Per-(shape, dtype) free lists of staging arrays.

    ``max_per_key`` bounds each free list so a burst of odd shapes
    cannot pin memory forever; overflow releases are simply dropped to
    the allocator.
    """

    def __init__(self, max_per_key: int = 8):
        self.max_per_key = max_per_key
        self._free: dict = {}
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0

    def acquire(self, shape, dtype=np.float32) -> np.ndarray:
        """A writable array of `shape` — pooled when one is free, fresh
        otherwise.  Contents are UNDEFINED: the batcher overwrites every
        row it uses and zeroes the padding rows explicitly."""
        from ..obs import metrics

        key = (tuple(shape), np.dtype(dtype).str)
        with self._lock:
            free = self._free.get(key)
            if free:
                self.hits += 1
                buf = free.pop()
            else:
                self.misses += 1
                buf = None
        if buf is not None:
            metrics.inc("pifft_serve_buffer_reuse_total")
            return buf
        metrics.inc("pifft_serve_buffer_alloc_total")
        return np.empty(shape, dtype)

    def release(self, *arrays) -> None:
        """Return staging arrays to their free lists (drop when the
        list is full)."""
        with self._lock:
            for arr in arrays:
                if arr is None:
                    continue
                key = (tuple(arr.shape), arr.dtype.str)
                free = self._free.setdefault(key, [])
                if len(free) < self.max_per_key:
                    free.append(arr)

    def stats(self) -> dict:
        with self._lock:
            pooled = sum(len(v) for v in self._free.values())
            return {"hits": self.hits, "misses": self.misses,
                    "pooled": pooled}

    def pooled_shapes(self) -> set:
        """Shapes with at least one pooled buffer — the staging-side
        warmth signal the mesh router reads (docs/SERVING.md): a
        device whose pool holds a ``(bucket, width)`` pair for a group
        has staged that group before."""
        with self._lock:
            return {shape for (shape, _dt), free in self._free.items()
                    if free}


def landing_views(payload, width: int, *, no_xi: bool = False,
                  dtype: int = 0):
    """Zero-copy ``(xr, xi)`` float32 views over a binary frame's
    payload bytes — the landing half of the zero-copy contract: the
    wire bytes ARE the request planes, and the batcher's staging copy
    into this pool's arrays is the one host memcpy the request ever
    pays.  `dtype` is the wire dtype code (``wire.DTYPE_F32`` /
    ``wire.DTYPE_BF16``); the bf16 path must widen and is charged to
    the host-copy meter (site ``bf16_wire``)."""
    from . import wire

    if dtype == wire.DTYPE_BF16:
        bits = np.frombuffer(payload, np.uint16)
        # widening bf16 -> f32 materializes new planes: a sanctioned,
        # METERED copy (the f32 path stays at exactly zero)
        wire.charge_host_copy(bits.nbytes * 2, site="bf16_wire")
        full = (bits.astype(np.uint32) << 16).view(np.float32)
        xr = full[:width]
        xi = None if no_xi else full[width:2 * width]
        return xr, xi
    xr = np.frombuffer(payload, np.float32, count=width)
    xi = None if no_xi else np.frombuffer(payload, np.float32,
                                          count=width, offset=width * 4)
    return xr, xi
