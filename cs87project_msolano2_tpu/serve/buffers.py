"""Staging-buffer pool: reuse the padded batch planes across requests.

Every coalesced batch stages its requests' float planes into one
``(B_pad, n)`` pair of arrays before the kernel invocation.  Allocating
those per batch at serving rates is pure allocator churn — the arrays
are the same handful of shapes forever (the padded batch buckets of the
served shape set) — so this pool keeps released buffers on a per-shape
free list and hands them back on the next acquire.

The device side of reuse is input donation: the plan executors are
jitted with ``donate_argnums`` via :meth:`plans.core.Plan.executable`,
so XLA may reuse the request planes' device buffers for the outputs.
This pool is the HOST side: the staging arrays a request is copied
into never hit the allocator twice.

Thread-safe (the dispatcher's executor thread releases while the event
loop acquires).  Reuse is observable: ``pifft_serve_buffer_reuse_total``
vs ``pifft_serve_buffer_alloc_total`` counters, and :meth:`stats` for
in-process assertions.
"""

from __future__ import annotations

import threading

import numpy as np


class BufferPool:
    """Per-(shape, dtype) free lists of staging arrays.

    ``max_per_key`` bounds each free list so a burst of odd shapes
    cannot pin memory forever; overflow releases are simply dropped to
    the allocator.
    """

    def __init__(self, max_per_key: int = 8):
        self.max_per_key = max_per_key
        self._free: dict = {}
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0

    def acquire(self, shape, dtype=np.float32) -> np.ndarray:
        """A writable array of `shape` — pooled when one is free, fresh
        otherwise.  Contents are UNDEFINED: the batcher overwrites every
        row it uses and zeroes the padding rows explicitly."""
        from ..obs import metrics

        key = (tuple(shape), np.dtype(dtype).str)
        with self._lock:
            free = self._free.get(key)
            if free:
                self.hits += 1
                buf = free.pop()
            else:
                self.misses += 1
                buf = None
        if buf is not None:
            metrics.inc("pifft_serve_buffer_reuse_total")
            return buf
        metrics.inc("pifft_serve_buffer_alloc_total")
        return np.empty(shape, dtype)

    def release(self, *arrays) -> None:
        """Return staging arrays to their free lists (drop when the
        list is full)."""
        with self._lock:
            for arr in arrays:
                if arr is None:
                    continue
                key = (tuple(arr.shape), arr.dtype.str)
                free = self._free.setdefault(key, [])
                if len(free) < self.max_per_key:
                    free.append(arr)

    def stats(self) -> dict:
        with self._lock:
            pooled = sum(len(v) for v in self._free.values())
            return {"hits": self.hits, "misses": self.misses,
                    "pooled": pooled}

    def pooled_shapes(self) -> set:
        """Shapes with at least one pooled buffer — the staging-side
        warmth signal the mesh router reads (docs/SERVING.md): a
        device whose pool holds a ``(bucket, width)`` pair for a group
        has staged that group before."""
        with self._lock:
            return {shape for (shape, _dt), free in self._free.items()
                    if free}
