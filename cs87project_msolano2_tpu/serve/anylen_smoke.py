"""The any-length serving smoke (docs/PLANS.md, "Arbitrary n"):
prove on THIS machine that the serve front door answers a NON-power-
of-two length with a real plan, not a degrade rung.

Run by ``make bluestein-smoke``:

    python -m cs87project_msolano2_tpu.serve.anylen_smoke

n=1000 c2c and r2c requests travel the real wire (JSON dialect over a
loopback socket) through the real dispatcher — warm path, coalescing
batcher, the lot — and every reply must carry

* numpy parity within the split3 error budget,
* a ``plan_variant`` from the any-length ladder (n=1000 = 8·125
  routes to ``mixedradix``) — NOT ``jnp-fft``/``numpy-ref``,
* ``degraded: false`` with an empty degrade trail.

Exit 0 only when every assertion holds — the serving leg of the
bluestein-smoke CI gate.
"""

from __future__ import annotations

import asyncio
import sys

import numpy as np

#: the served non-pow2 length (= 8 · 125: odd part 125 <= 512, so the
#: static router picks the mixed-radix variant)
N = 1000

#: split3 relative-error budget (utils/errors.py) — the served
#: precision here
TOL = 1e-5

#: the any-length plan variants (ops/anylen.py); a reply naming
#: anything else either fell to a degrade rung or took a path this
#: smoke does not cover
ANYLEN_VARIANTS = ("bluestein", "rader", "mixedradix")


def _relerr(got: np.ndarray, ref: np.ndarray) -> float:
    return float(np.max(np.abs(got - ref)) / np.max(np.abs(ref)))


async def _run(problems: list) -> int:
    from .dispatcher import Dispatcher, ServeConfig
    from .protocol import handle_connection, request_over_socket
    from .shapes import ShapeSpec

    rng = np.random.default_rng(87)
    specs = [ShapeSpec(n=N), ShapeSpec(n=N, domain="r2c")]
    cfg = ServeConfig(max_wait_ms=2.0)
    served = 0
    async with Dispatcher(cfg, specs) as d:
        server = await asyncio.start_server(
            lambda r, w: handle_connection(d, r, w), "127.0.0.1", 0)
        port = server.sockets[0].getsockname()[1]
        try:
            # --- c2c at n=1000: parity vs numpy, a plan not a rung
            xr = rng.standard_normal(N).astype(np.float32)
            xi = rng.standard_normal(N).astype(np.float32)
            reply = await request_over_socket("127.0.0.1", port, xr, xi)
            if not reply.get("ok"):
                problems.append(f"c2c n={N} refused: "
                                f"{reply.get('error')}")
            else:
                served += 1
                ref = np.fft.fft(xr.astype(np.float64)
                                 + 1j * xi.astype(np.float64))
                got = (np.asarray(reply["yr"])
                       + 1j * np.asarray(reply["yi"]))
                err = _relerr(got, ref)
                if err > TOL:
                    problems.append(f"c2c n={N} parity {err:.2e} > "
                                    f"{TOL:.0e}")
                if reply.get("plan_variant") not in ANYLEN_VARIANTS:
                    problems.append(
                        f"c2c n={N} served by "
                        f"{reply.get('plan_variant')!r} — want an "
                        f"any-length plan {ANYLEN_VARIANTS}")
                if reply.get("degraded"):
                    problems.append(f"c2c n={N} tagged degraded "
                                    f"({reply.get('degrade')})")

            # --- r2c at n=1000: the even-n pack trick over the wire
            xr = rng.standard_normal(N).astype(np.float32)
            reply = await request_over_socket("127.0.0.1", port, xr,
                                              domain="r2c")
            if not reply.get("ok"):
                problems.append(f"r2c n={N} refused: "
                                f"{reply.get('error')}")
            else:
                served += 1
                ref = np.fft.rfft(xr.astype(np.float64))
                got = (np.asarray(reply["yr"])
                       + 1j * np.asarray(reply["yi"]))
                if got.shape[-1] != N // 2 + 1:
                    problems.append(f"r2c n={N} returned "
                                    f"{got.shape[-1]} bins, want "
                                    f"{N // 2 + 1}")
                else:
                    err = _relerr(got, ref)
                    if err > TOL:
                        problems.append(f"r2c n={N} parity "
                                        f"{err:.2e} > {TOL:.0e}")
                if reply.get("plan_variant") not in ANYLEN_VARIANTS:
                    problems.append(
                        f"r2c n={N} served by "
                        f"{reply.get('plan_variant')!r} — want an "
                        f"any-length plan {ANYLEN_VARIANTS}")
                if reply.get("degraded"):
                    problems.append(f"r2c n={N} tagged degraded "
                                    f"({reply.get('degrade')})")
        finally:
            server.close()
            await server.wait_closed()
    return served


def main() -> int:
    from .. import obs

    owned = not obs.enabled()
    if owned:
        obs.enable()
    problems: list = []
    try:
        served = asyncio.run(_run(problems))
    finally:
        if owned:
            obs.disable()
    for p in problems:
        print(f"# FAIL: {p}", file=sys.stderr)
    if problems:
        return 1
    print(f"# anylen serve smoke ok: {served} non-pow2 (n={N}) "
          f"requests served over the socket on a mixed-radix plan, "
          f"numpy parity within {TOL:.0e}, zero degrade rungs")
    return 0


if __name__ == "__main__":
    sys.exit(main())
