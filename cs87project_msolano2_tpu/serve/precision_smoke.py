"""The precision degrade-up smoke (docs/PRECISION.md): prove on THIS
machine that an error-budget violation walks a served plan UP the
precision chain to fp32, tagged everywhere the contract demands.

Run under ``PIFFT_PRECISION_BUDGET=0`` (the injection knob — every
sampled batch then violates its budget) by ``make precision-smoke``:

    PIFFT_PRECISION_BUDGET=0 python -m \
        cs87project_msolano2_tpu.serve.precision_smoke

One bf16-storage request is served through the real dispatcher; the
batcher's per-batch sample sees the (injected) violation and must
promote bf16 -> default -> split3 -> fp32, with

* ``degraded: true`` and the ``precision:*`` trail on the RESPONSE,
* ``degraded: true``, ``direction: "up"`` demotion records, and the
  promoted effective precision on the PLAN,
* the ``pifft_precision_rel_err`` gauge published per sampled mode.

Exit 0 only when every assertion holds — the CI gate's third leg.
"""

from __future__ import annotations

import asyncio
import sys

import numpy as np


def main() -> int:
    from .. import obs, plans
    from ..obs import metrics
    from . import Dispatcher, ServeConfig, ShapeSpec

    owned = not obs.enabled()
    if owned:
        obs.enable()
    spec = ShapeSpec(n=1024, precision="bf16")
    rng = np.random.default_rng(0)
    xr = rng.standard_normal(spec.n).astype(np.float32)
    xi = rng.standard_normal(spec.n).astype(np.float32)

    async def serve_one():
        cfg = ServeConfig(max_batch=4, max_wait_ms=1.0)
        async with Dispatcher(cfg, [spec]) as d:
            return await d.submit(xr, xi, precision="bf16")

    resp = asyncio.run(serve_one())

    problems = []
    if not resp.degraded:
        problems.append("response not tagged degraded")
    if "precision:fp32" not in (resp.degrade or []):
        problems.append(f"response trail lacks precision:fp32 "
                        f"({resp.degrade})")
    plan = plans.plan_for((1, spec.n), precision="bf16")
    if not plan.degraded:
        problems.append("plan not tagged degraded")
    if plan.effective_precision() != "fp32":
        problems.append(f"plan did not walk to fp32 "
                        f"(effective {plan.effective_precision()!r})")
    ups = [rec for rec in plan.demotions
           if rec.get("direction") == "up"]
    if not ups or ups[-1]["to"] != "precision:fp32":
        problems.append(f"demotion trail wrong: {plan.demotions}")
    gauges = [k for k in metrics.snapshot()["gauges"]
              if k.startswith("pifft_precision_rel_err")]
    if not gauges:
        problems.append("pifft_precision_rel_err gauge never published")
    if owned:
        obs.disable()
    for p in problems:
        print(f"# FAIL: {p}", file=sys.stderr)
    if problems:
        return 1
    trail = " -> ".join([ups[0]["from"]]
                        + [rec["to"].split(":", 1)[1] for rec in ups])
    print(f"# precision degrade-up ok: injected violation walked "
          f"{trail}, degraded tagged on plan AND response, "
          f"{len(gauges)} rel-err gauge series published")
    return 0


if __name__ == "__main__":
    sys.exit(main())
