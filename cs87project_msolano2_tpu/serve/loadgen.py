"""Open-loop load generator: the SLO measurement side of serving.

Fires requests at a FIXED offered rate (arrivals scheduled at
``t0 + i/rps`` regardless of completions — open-loop, so a slow server
cannot flatter itself by slowing the clients down, the classic
coordinated-omission trap), then reports what the service actually
achieved: completed throughput, client-observed p50/p99, the
queue-wait vs compute split from the responses, and how many arrivals
were rejected (backpressure) or served degraded.

Row schema is STABLE: every latency field is present in every row,
``None`` where the cell has no population to report (a cell where
every arrival was rejected still rolls up — the summary must never
crash on the saturation it exists to measure).

``bench.py --serve-load`` drives this over the served shape set, and
— since the binary front door landed — also replays TRACE-DRIVEN wire
load (:func:`run_wire_load`): synthetic diurnal / bursty / heavy-tail
arrival processes (:func:`arrival_offsets`) over mixed
op/shape/priority/tenant populations, fired through REAL socket
connections per wire dialect so the JSON-vs-binary p99 delta is a
measured fact the per-protocol ``serve_load`` rows carry
(docs/SERVING.md "The wire").  :func:`run_mesh_chaos_load` is the mesh
tier
(``bench.py --serve-mesh`` / ``pifft serve --mesh-smoke``,
docs/SERVING.md): round-robin open-loop load over a shape set spread
across a :class:`~.mesh.MeshDispatcher`, with a MID-RUN DEVICE KILL
through the ``device<K>`` injection site and the pre/post-kill p99
split the ``serve_mesh`` bench rows carry.
"""

from __future__ import annotations

import asyncio
import contextlib
from typing import Optional

import numpy as np

from ..obs.spans import clock
from ..utils.stats import percentile_or_none
from .batcher import GroupKey
from .dispatcher import Dispatcher, QueueFull, ServeError


def shape_label(n: int, layout: str, op: str = "fft") -> str:
    """The serve_load row's shape label: the familiar ``n2^K`` for
    powers of two, the EXACT length (``n1000``) otherwise —
    ``n.bit_length()-1`` silently mislabeled every non-pow2 n as the
    pow2 below it (n=1000 as n2^9), which would have aliased any-length
    rows onto pow2 rows in the analyze loader.  analyze/loader.py
    parses both forms; committed pow2 rounds are unchanged."""
    head = (f"n2^{n.bit_length() - 1}" if n >= 1 and not (n & (n - 1))
            else f"n{n}")
    return f"{head}:{layout}" + (f":{op}" if op != "fft" else "")


def verify_response(n: int, layout: str, domain: str, inverse: bool,
                    precision: str, xr, xi, resp,
                    op: str = "fft") -> Optional[str]:
    """Problem string, or None: one served response checked against
    its domain's ``numpy.fft`` oracle (pi-layout answers are mapped
    back to natural order first; the tolerance is the precision
    mode's error budget, docs/PRECISION.md).  Op-tagged responses
    (docs/APPS.md) verify against the OP's numpy oracle — the fused
    circular conv/corr/solve pipeline.  Shared by the serve smokes
    and the mesh chaos driver — a coalesced, padded, re-routed path
    that returns the wrong rows must FAIL, not just look slow."""
    from ..ops.precision import error_budget
    from ..utils import verify

    got_r = np.asarray(resp.yr, np.float64)
    got_i = np.asarray(resp.yi, np.float64)
    xr64 = np.asarray(xr, np.float64)
    xi64 = np.asarray(xi, np.float64) if xi is not None else None
    if op != "fft":
        from ..apps.spectral import numpy_oracle

        ref = numpy_oracle(op, xr64,
                           xi64 if xi64 is not None
                           else np.zeros_like(xr64), n)
        err = verify.rel_err(got_r, ref)
        tol = max(1e-4, error_budget(precision))
        if err > tol:
            return (f"response {resp.rid} wrong: rel err {err:.3e} > "
                    f"{tol:.0e} vs numpy {op} oracle ({precision} "
                    f"budget)")
        return None
    if domain == "r2c":
        if got_r.shape[-1] != n // 2 + 1:
            return (f"response {resp.rid}: r2c answer is "
                    f"{got_r.shape[-1]} bins, want {n // 2 + 1} "
                    f"(half-spectrum)")
        ref = np.fft.rfft(xr64)
        got = got_r + 1j * got_i
    elif domain == "c2r":
        ref = np.fft.irfft(xr64 + 1j * xi64, n=n)
        got = got_r
    else:
        z = xr64 + 1j * xi64
        ref = np.fft.ifft(z) if inverse else np.fft.fft(z)
        got = got_r + 1j * got_i
        if layout == "pi":
            got = verify.pi_layout_to_natural(got)
    err = verify.rel_err(got, ref)
    tol = max(1e-4, error_budget(precision))
    if err > tol:
        return (f"response {resp.rid} wrong: rel err {err:.3e} > "
                f"{tol:.0e} vs numpy {domain}"
                f"{':inv' if inverse else ''} ({precision} budget)")
    return None


async def run_offered_load(dispatcher: Dispatcher, n: int, rps: float,
                           duration_s: float, layout: str = "natural",
                           precision: Optional[str] = None,
                           seed: int = 0, domain: str = "c2c",
                           inverse: bool = False,
                           priority: str = "normal",
                           tenant: str = "default",
                           op: str = "fft") -> dict:
    """One (shape, offered-rps) cell: fire ``rps * duration_s``
    arrivals on the open-loop schedule, await them all, and roll up
    the SLO row.  Rejections and failures are counted, never raised —
    a load test's job is to record the service's behavior at
    saturation, not to die of it.  `op` drives op-tagged load
    (docs/APPS.md): conv/corr cells send a real signal + kernel
    pair, solve cells a real field — the SLO row carries the op."""
    rng = np.random.default_rng(seed)
    if op in ("conv", "corr"):
        xr = rng.standard_normal(n).astype(np.float32)
        xi = rng.standard_normal(n).astype(np.float32)
    elif op == "solve":
        xr = rng.standard_normal(n).astype(np.float32)
        xi = np.zeros_like(xr)
    elif domain == "c2r":
        spec = np.fft.rfft(rng.standard_normal(n))
        xr = spec.real.astype(np.float32)
        xi = spec.imag.astype(np.float32)
    else:
        xr = rng.standard_normal(n).astype(np.float32)
        xi = np.zeros_like(xr) if domain == "r2c" \
            else rng.standard_normal(n).astype(np.float32)

    ok: list = []          # (client_total_s, response)
    rejected: list = []    # QueueFull errors (structured backpressure)
    failed: list = []      # ServeError beyond backpressure

    t_start = clock()

    async def one():
        t0 = clock()
        try:
            resp = await dispatcher.submit(xr, xi, layout=layout,
                                           precision=precision,
                                           inverse=inverse,
                                           domain=domain,
                                           priority=priority,
                                           tenant=tenant, op=op)
        except QueueFull as e:
            rejected.append(e)
            return
        except ServeError as e:
            failed.append(e)
            return
        ok.append((clock() - t0, resp))

    total = max(1, int(rps * duration_s))
    tasks = []
    for i in range(total):
        delay = (t_start + i / rps) - clock()
        if delay > 0:
            await asyncio.sleep(delay)
        tasks.append(asyncio.ensure_future(one()))
    await asyncio.gather(*tasks)
    elapsed = max(clock() - t_start, 1e-9)

    totals = [t for t, _ in ok]
    queues = [r.queue_wait_ms for _, r in ok]
    computes = [r.compute_ms for _, r in ok]

    def ms(values, q, scale=1.0):
        v = percentile_or_none(values, q)
        return round(v * scale, 4) if v is not None else None

    return {
        "shape": shape_label(n, layout, op),
        "n": n,
        "op": op,
        "offered_rps": round(rps, 1),
        "duration_s": round(elapsed, 4),
        "requests": total,
        "completed": len(ok),
        "rejected": len(rejected),
        "failed": len(failed),
        "achieved_rps": round(len(ok) / elapsed, 1),
        "degraded": sum(1 for _, r in ok if r.degraded),
        # stable schema: every latency field present, None when the
        # population is empty (e.g. every arrival rejected)
        "p50_ms": ms(totals, 50, 1e3),
        "p99_ms": ms(totals, 99, 1e3),
        "queue_p50_ms": ms(queues, 50),
        "queue_p99_ms": ms(queues, 99),
        "compute_p50_ms": ms(computes, 50),
        "compute_p99_ms": ms(computes, 99),
        "retry_after_p50_ms": ms([e.retry_after_ms for e in rejected],
                                 50),
    }


# ------------------------------------------------------- mesh chaos


def _group_for(spec) -> GroupKey:
    return GroupKey(n=spec.n, layout=spec.layout,
                    precision=spec.precision, domain=spec.domain,
                    op=getattr(spec, "op", "fft"))


async def run_mesh_chaos_load(mesh, specs, rps: float,
                              duration_s: float,
                              kill_at_frac: Optional[float] = 0.5,
                              kill_kind: str = "permanent",
                              seed: int = 0,
                              prime: bool = True) -> dict:
    """The mesh acceptance drive (docs/SERVING.md): open-loop arrivals
    round-robin over `specs` against a warmed
    :class:`~.mesh.MeshDispatcher`, with a mid-run device kill.

    At ``kill_at_frac`` of the arrival schedule the CURRENT router
    choice for ``specs[0]``'s group — the device provably about to
    receive traffic — is armed with a one-shot ``device<K>`` fault
    (`kill_kind`), so the kill strikes mid-batch on a loaded device,
    not a conveniently idle one.  Every completed response is verified
    against its numpy oracle, and the client-observed p99 is split at
    the kill time: the ``p99_pre_kill_ms`` / ``p99_post_kill_ms`` pair
    the ``serve_mesh`` bench rows carry.

    Returns the full report; it ASSERTS nothing — the smoke gates and
    tests own the assertions."""
    from ..resilience.inject import inject

    rng = np.random.default_rng(seed)
    inputs = []
    for spec in specs:
        op = getattr(spec, "op", "fft")
        if op in ("conv", "corr"):
            inputs.append((rng.standard_normal(spec.n)
                           .astype(np.float32),
                           rng.standard_normal(spec.n)
                           .astype(np.float32)))
        elif op == "solve":
            inputs.append((rng.standard_normal(spec.n)
                           .astype(np.float32), None))
        elif spec.domain == "c2r":
            sp = np.fft.rfft(rng.standard_normal(spec.n))
            inputs.append((sp.real.astype(np.float32),
                           sp.imag.astype(np.float32)))
        elif spec.domain == "r2c":
            inputs.append((rng.standard_normal(spec.n)
                           .astype(np.float32), None))
        else:
            inputs.append((rng.standard_normal(spec.n)
                           .astype(np.float32),
                           rng.standard_normal(spec.n)
                           .astype(np.float32)))

    if prime:
        # pay each group's trace/compile cost BEFORE the measured
        # schedule opens (the warmup pass every SLO run owes itself):
        # without it the pre-kill window is all compile latency and
        # the pre/post p99 split measures XLA, not the failover
        for si, spec in enumerate(specs):
            xr, xi = inputs[si]
            await mesh.submit(xr, xi, layout=spec.layout,
                              precision=spec.precision,
                              domain=spec.domain,
                              op=getattr(spec, "op", "fft"))

    ok: list = []        # (t_done_rel_s, total_s, spec_idx, resp)
    rejected: list = []
    failed: list = []
    killed = {"device": None, "t_rel_s": None}
    t_start = clock()

    async def one(i: int):
        si = i % len(specs)
        spec = specs[si]
        xr, xi = inputs[si]
        t0 = clock()
        try:
            resp = await mesh.submit(xr, xi, layout=spec.layout,
                                     precision=spec.precision,
                                     domain=spec.domain,
                                     op=getattr(spec, "op", "fft"))
        except QueueFull as e:
            rejected.append(e)
            return
        except ServeError as e:
            failed.append(e)
            return
        ok.append((clock() - t_start, clock() - t0, si, resp))

    total = max(1, int(rps * duration_s))
    kill_i = int(total * kill_at_frac) if kill_at_frac is not None \
        else None
    tasks = []
    with contextlib.ExitStack() as stack:
        for i in range(total):
            delay = (t_start + i / rps) - clock()
            if delay > 0:
                await asyncio.sleep(delay)
            if kill_i is not None and i == kill_i:
                victim = mesh.router.route(_group_for(specs[0]),
                                           record=False)
                stack.enter_context(
                    inject(victim.site, kill_kind, count=1))
                killed["device"] = victim.id
                killed["t_rel_s"] = round(clock() - t_start, 6)
            tasks.append(asyncio.ensure_future(one(i)))
        await asyncio.gather(*tasks)
    elapsed = max(clock() - t_start, 1e-9)

    problems = []
    for _t, _tot, si, resp in ok:
        spec = specs[si]
        xr, xi = inputs[si]
        problem = verify_response(spec.n, spec.layout, spec.domain,
                                  False, spec.precision, xr, xi, resp,
                                  op=getattr(spec, "op", "fft"))
        if problem:
            problems.append(problem)
            if len(problems) >= 5:
                break

    t_kill = killed["t_rel_s"]
    pre = [tot for t, tot, _si, _r in ok
           if t_kill is None or t <= t_kill]
    post = [tot for t, tot, _si, _r in ok
            if t_kill is not None and t > t_kill]
    failover_tagged = sum(
        1 for _t, _tot, _si, r in ok
        if any(str(tag).startswith("failover:") for tag in r.degrade))

    def p99_ms(values):
        v = percentile_or_none(values, 99)
        return round(v * 1e3, 4) if v is not None else None

    return {
        "devices": len(mesh.devices),
        "shapes": [_group_for(s).label() for s in specs],
        "offered_rps": round(rps, 1),
        "duration_s": round(elapsed, 4),
        "requests": total,
        "completed": len(ok),
        "rejected": len(rejected),
        "failed": len(failed),
        "degraded": sum(1 for *_x, r in ok if r.degraded),
        "failover_tagged": failover_tagged,
        "killed_device": killed["device"],
        "t_kill_s": t_kill,
        "p99_pre_kill_ms": p99_ms(pre),
        "p99_post_kill_ms": p99_ms(post),
        "utilization": mesh.utilization(),
        "problems": problems,
    }


# ------------------------------------------- trace-driven wire replay


#: the synthetic arrival processes replay traces are drawn from
#: (docs/SERVING.md): real front doors never see the uniform schedule
#: the classic cells use — diurnal swing, bursts and heavy-tailed
#: think time are what the credit window and the coalescer must absorb
ARRIVAL_PROCESSES = ("uniform", "diurnal", "bursty", "heavytail",
                     "shifted")

#: where the ``shifted`` process flips the population mix, as a
#: fraction of the run (the drift scenario's default step point)
SHIFT_AT_FRAC = 0.5


def arrival_offsets(process: str, rps: float, duration_s: float,
                    rng) -> list:
    """Sorted arrival times in ``[0, duration_s)`` for one replay
    trace, averaging `rps`.  Deterministic given `rng` — a replay is
    only a replay if two runs see the same schedule.

    - ``uniform``: the classic open-loop grid (``i/rps``).
    - ``diurnal``: an inhomogeneous Poisson day compressed into the
      run — rate swings ±80% around `rps` on one sinusoidal period.
    - ``bursty``: on/off source — quiet floor punctuated by bursts at
      4x the mean rate (the coalescer's best case, admission's worst).
    - ``heavytail``: Pareto (alpha=1.5) interarrivals with mean
      ``1/rps`` — long gaps, hot clumps, no second moment to speak of.
    - ``shifted``: the uniform grid — the step change this process
      models lives in the POPULATION MIX, not the rate
      (:func:`population_schedule` flips the draw weights at the
      shift offset; the fleet smoke's drift scenario — docs/FLEET.md).
    """
    total = max(1, int(rps * duration_s))
    if process in ("uniform", "shifted"):
        return [i / rps for i in range(total)]
    if process == "diurnal":
        # invert the cumulative rate Lambda(t) on a grid: arrival i
        # lands where Lambda(t)/Lambda(D) crosses (i+u_i)/total
        grid = np.linspace(0.0, duration_s, 1024)
        lam = 1.0 + 0.8 * np.sin(2 * np.pi * grid / duration_s)
        cum = np.concatenate([[0.0], np.cumsum(
            (lam[1:] + lam[:-1]) * 0.5 * np.diff(grid))])
        cum /= cum[-1]
        u = (np.arange(total) + rng.random(total)) / total
        return sorted(np.interp(u, cum, grid).tolist())
    if process == "bursty":
        out: list = []
        t = 0.0
        burst_rate = 4.0 * rps
        # duty cycle ~25%: mean on-time D/12 at 4x, off-time D/4
        while t < duration_s and len(out) < 4 * total:
            on = rng.exponential(duration_s / 12.0)
            end = min(t + on, duration_s)
            while t < end:
                out.append(t)
                t += rng.exponential(1.0 / burst_rate)
            t += rng.exponential(duration_s / 4.0)
        return out or [0.0]
    if process == "heavytail":
        alpha = 1.5
        scale = (alpha - 1.0) / alpha / rps  # Pareto mean == 1/rps
        gaps = scale * (1.0 + rng.pareto(alpha, size=2 * total))
        times = np.cumsum(gaps)
        out = times[times < duration_s].tolist()
        return out[:2 * total] or [0.0]
    raise ValueError(f"unknown arrival process {process!r} "
                     f"(one of {ARRIVAL_PROCESSES})")


#: population spec defaults: a replay population is a list of
#: ``(weight, spec)`` pairs, each spec a dict with any of these keys
_SPEC_DEFAULTS = {"op": "fft", "domain": "c2c", "layout": "natural",
                  "precision": None, "inverse": False,
                  "priority": "normal", "tenant": "default"}


def population_schedule(process: str, population, rps: float,
                        duration_s: float, rng,
                        shift_frac: float = SHIFT_AT_FRAC) -> tuple:
    """``(offsets, spec_indices)`` for one replay trace: arrival times
    from :func:`arrival_offsets` plus the population draw for each.

    Every process draws the mix i.i.d. from the entries' ``weight`` —
    except ``shifted``, which applies a DETERMINISTIC step-change at
    ``shift_frac * duration_s``: draws before the step use ``weight``,
    draws from the step on use each spec's ``"shifted_weight"`` key
    (default: its ``weight``, i.e. unchanged).  That is how a replay
    trace emits "the shape/op/priority mix moved under the fleet" as a
    normal population, reproducible from the seed (docs/FLEET.md)."""
    if not 0.0 <= shift_frac <= 1.0:
        raise ValueError(f"shift_frac must be in [0, 1], got "
                         f"{shift_frac}")
    weights = np.asarray([float(w) for w, _s in population])
    if weights.sum() <= 0:
        raise ValueError("population weights sum to zero")
    weights = weights / weights.sum()
    offsets = arrival_offsets(process, rps, duration_s, rng)
    if process != "shifted":
        draws = rng.choice(len(population), size=len(offsets),
                           p=weights)
        return offsets, [int(d) for d in draws]
    shifted = np.asarray([float(s.get("shifted_weight", w))
                          for w, s in population])
    if shifted.sum() <= 0:
        raise ValueError("shifted_weight values sum to zero")
    shifted = shifted / shifted.sum()
    t_shift = float(shift_frac) * duration_s
    draws = []
    for off in offsets:
        p = weights if off < t_shift else shifted
        draws.append(int(rng.choice(len(population), p=p)))
    return offsets, draws


def _replay_input(spec: dict, rng):
    n = spec["n"]
    op = spec.get("op", "fft")
    domain = spec.get("domain", "c2c")
    if op in ("conv", "corr"):
        return (rng.standard_normal(n).astype(np.float32),
                rng.standard_normal(n).astype(np.float32))
    if op == "solve":
        return rng.standard_normal(n).astype(np.float32), None
    if domain == "c2r":
        sp = np.fft.rfft(rng.standard_normal(n))
        return (sp.real.astype(np.float32),
                sp.imag.astype(np.float32))
    if domain == "r2c":
        return rng.standard_normal(n).astype(np.float32), None
    return (rng.standard_normal(n).astype(np.float32),
            rng.standard_normal(n).astype(np.float32))


class _JsonLoadClient:
    """Minimal multiplexing JSON-dialect client for the replay driver:
    pipelines requests over ONE connection and matches replies by
    ``id`` — so the JSON cells pay the dialect's true parse cost on a
    persistent connection, not per-request connect overhead."""

    def __init__(self, reader, writer):
        self.reader = reader
        self.writer = writer
        self._pending: dict = {}
        self._rid = 0
        self._lock = asyncio.Lock()
        self._task = asyncio.ensure_future(self._read_loop())

    @classmethod
    async def connect(cls, host: str, port: int) -> "_JsonLoadClient":
        reader, writer = await asyncio.open_connection(host, port)
        return cls(reader, writer)

    async def _read_loop(self):
        from . import protocol

        try:
            while True:
                rec = await protocol.read_frame(self.reader)
                if rec is None:
                    break
                rec.pop("_t_recv", None)
                fut = self._pending.pop(rec.get("id"), None)
                if fut is not None and not fut.done():
                    fut.set_result(rec)
        except (asyncio.IncompleteReadError, ValueError,
                ConnectionResetError, BrokenPipeError) as e:
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(ConnectionError(str(e)))
            self._pending.clear()

    async def request(self, payload: dict) -> dict:
        from . import protocol

        self._rid += 1
        rid = self._rid
        payload = dict(payload, id=rid)
        fut = asyncio.get_running_loop().create_future()
        self._pending[rid] = fut
        try:
            async with self._lock:
                self.writer.write(protocol.encode_frame(payload))
                await self.writer.drain()
            return await fut
        finally:
            self._pending.pop(rid, None)

    async def close(self):
        self._task.cancel()
        try:
            await self._task
        except asyncio.CancelledError:
            pass
        self.writer.close()


async def run_wire_load(host: str, port: int, protocol_name: str,
                        population, rps: float, duration_s: float,
                        process: str = "uniform", seed: int = 0,
                        connections: int = 2,
                        use_shm: bool = False,
                        shift_frac: float = SHIFT_AT_FRAC) -> dict:
    """One replay cell driven over REAL socket connections — the wire
    dialect's full cost (framing, parse, credits) is inside the
    client-observed latency, which is the entire point of the
    per-protocol ``serve_load`` rows (bench.py --serve-load).

    `protocol_name` picks the dialect ("json" or "binary");
    `population` is a list of ``(weight, spec)`` pairs (specs per
    ``_SPEC_DEFAULTS`` + ``n``); arrivals follow `process`
    (:func:`arrival_offsets`).  The row keeps
    :func:`run_offered_load`'s stable schema and adds ``protocol``,
    ``process`` and ``connections``."""
    from . import wire

    rng = np.random.default_rng(seed)
    specs = [dict(_SPEC_DEFAULTS, **s) for _w, s in population]
    inputs = [_replay_input(s, rng) for s in specs]

    if protocol_name == "binary":
        clients = [await wire.WireClient.connect(
            host, port, want_shm=use_shm)
            for _ in range(max(1, connections))]
    else:
        clients = [await _JsonLoadClient.connect(host, port)
                   for _ in range(max(1, connections))]

    ok: list = []          # (client_total_s, record)
    rejected: list = []    # structured backpressure records
    failed: list = []

    async def one(i: int, si: int):
        spec = specs[si]
        xr, xi = inputs[si]
        client = clients[i % len(clients)]
        t0 = clock()
        try:
            if protocol_name == "binary":
                rec = await client.request(
                    xr, xi, op=spec["op"], layout=spec["layout"],
                    precision=spec["precision"],
                    inverse=spec["inverse"], domain=spec["domain"],
                    priority=spec["priority"], tenant=spec["tenant"],
                    use_shm=use_shm and client.shm is not None)
            else:
                payload = {"op": spec["op"],
                           "xr": np.asarray(xr, np.float64).tolist(),
                           "layout": spec["layout"],
                           "precision": spec["precision"],
                           "inverse": spec["inverse"],
                           "domain": spec["domain"],
                           "priority": spec["priority"],
                           "tenant": spec["tenant"]}
                if xi is not None:
                    payload["xi"] = np.asarray(xi, np.float64).tolist()
                rec = await client.request(payload)
        except (ConnectionError, wire.WireError, OSError) as e:
            failed.append({"type": "transport",
                           "message": str(e)[:200]})
            return
        if rec.get("ok"):
            ok.append((clock() - t0, rec))
        elif (rec.get("error") or {}).get("type") == "queue_full":
            rejected.append(rec["error"])
        else:
            failed.append(rec.get("error") or {"type": "unknown"})

    offsets, draws = population_schedule(process, population, rps,
                                         duration_s, rng,
                                         shift_frac=shift_frac)
    t_start = clock()
    tasks = []
    try:
        for i, off in enumerate(offsets):
            delay = (t_start + off) - clock()
            if delay > 0:
                await asyncio.sleep(delay)
            tasks.append(asyncio.ensure_future(one(i, int(draws[i]))))
        await asyncio.gather(*tasks)
    finally:
        for c in clients:
            await c.close()
    elapsed = max(clock() - t_start, 1e-9)

    totals = [t for t, _ in ok]
    queues = [r.get("queue_wait_ms") for _, r in ok
              if r.get("queue_wait_ms") is not None]
    computes = [r.get("compute_ms") for _, r in ok
                if r.get("compute_ms") is not None]

    def ms(values, q, scale=1.0):
        v = percentile_or_none(values, q)
        return round(v * scale, 4) if v is not None else None

    ns = sorted({s["n"] for s in specs})
    shape = ("mixed" if len(specs) > 1 else
             shape_label(specs[0]["n"], specs[0]["layout"],
                         specs[0]["op"]))
    return {
        "shape": shape,
        "n": ns[-1],
        "op": specs[0]["op"] if len(specs) == 1 else "mixed",
        "protocol": protocol_name,
        "process": process,
        "connections": len(clients),
        "offered_rps": round(rps, 1),
        "duration_s": round(elapsed, 4),
        "requests": len(offsets),
        "completed": len(ok),
        "rejected": len(rejected),
        "failed": len(failed),
        "achieved_rps": round(len(ok) / elapsed, 1),
        "degraded": sum(1 for _, r in ok if r.get("degraded")),
        "p50_ms": ms(totals, 50, 1e3),
        "p99_ms": ms(totals, 99, 1e3),
        "queue_p50_ms": ms(queues, 50),
        "queue_p99_ms": ms(queues, 99),
        "compute_p50_ms": ms(computes, 50),
        "compute_p99_ms": ms(computes, 99),
        "retry_after_p50_ms": ms(
            [e.get("retry_after_ms") for e in rejected
             if isinstance(e, dict)
             and e.get("retry_after_ms") is not None], 50),
    }


def mesh_report_rows(report: dict) -> list:
    """The ``serve_mesh`` BENCH row set from one chaos-load report:
    one ``row="device"`` entry per mesh device (utilization balance)
    plus one ``row="kill"`` entry with the pre/post-kill p99 split —
    the shape ``analyze.loader`` parses (docs/ANALYSIS.md)."""
    rows = []
    for dev in report["utilization"].values():
        rows.append({"row": "device", **dev})
    rows.append({
        "row": "kill",
        "killed_device": report["killed_device"],
        "t_kill_s": report["t_kill_s"],
        "p99_pre_kill_ms": report["p99_pre_kill_ms"],
        "p99_post_kill_ms": report["p99_post_kill_ms"],
        "requests": report["requests"],
        "completed": report["completed"],
        "rejected": report["rejected"],
        "failed": report["failed"],
        "failover_tagged": report["failover_tagged"],
    })
    return rows
