"""Open-loop load generator: the SLO measurement side of serving.

Fires requests at a FIXED offered rate (arrivals scheduled at
``t0 + i/rps`` regardless of completions — open-loop, so a slow server
cannot flatter itself by slowing the clients down, the classic
coordinated-omission trap), then reports what the service actually
achieved: completed throughput, client-observed p50/p99, the
queue-wait vs compute split from the responses, and how many arrivals
were rejected (backpressure) or served degraded.

``bench.py --serve-load`` drives this over the served shape set and
emits the rows in the BENCH round record format.
"""

from __future__ import annotations

import asyncio
from typing import Optional

import numpy as np

from ..obs.spans import clock
from .dispatcher import Dispatcher, QueueFull, ServeError
from .slo import percentile


async def run_offered_load(dispatcher: Dispatcher, n: int, rps: float,
                           duration_s: float, layout: str = "natural",
                           precision: Optional[str] = None,
                           seed: int = 0) -> dict:
    """One (shape, offered-rps) cell: fire ``rps * duration_s``
    arrivals on the open-loop schedule, await them all, and roll up
    the SLO row.  Rejections and failures are counted, never raised —
    a load test's job is to record the service's behavior at
    saturation, not to die of it."""
    rng = np.random.default_rng(seed)
    xr = rng.standard_normal(n).astype(np.float32)
    xi = rng.standard_normal(n).astype(np.float32)

    ok: list = []          # (client_total_s, response)
    rejected: list = []    # QueueFull errors (structured backpressure)
    failed: list = []      # ServeError beyond backpressure

    async def one():
        t0 = clock()
        try:
            resp = await dispatcher.submit(xr, xi, layout=layout,
                                           precision=precision)
        except QueueFull as e:
            rejected.append(e)
            return
        except ServeError as e:
            failed.append(e)
            return
        ok.append((clock() - t0, resp))

    total = max(1, int(rps * duration_s))
    t_start = clock()
    tasks = []
    for i in range(total):
        delay = (t_start + i / rps) - clock()
        if delay > 0:
            await asyncio.sleep(delay)
        tasks.append(asyncio.ensure_future(one()))
    await asyncio.gather(*tasks)
    elapsed = max(clock() - t_start, 1e-9)

    row = {
        "shape": f"n2^{n.bit_length() - 1}:{layout}",
        "n": n,
        "offered_rps": round(rps, 1),
        "duration_s": round(elapsed, 4),
        "requests": total,
        "completed": len(ok),
        "rejected": len(rejected),
        "failed": len(failed),
        "achieved_rps": round(len(ok) / elapsed, 1),
        "degraded": sum(1 for _, r in ok if r.degraded),
    }
    if ok:
        totals = [t for t, _ in ok]
        queues = [r.queue_wait_ms for _, r in ok]
        computes = [r.compute_ms for _, r in ok]
        row.update({
            "p50_ms": round(percentile(totals, 50) * 1e3, 4),
            "p99_ms": round(percentile(totals, 99) * 1e3, 4),
            "queue_p50_ms": round(percentile(queues, 50), 4),
            "queue_p99_ms": round(percentile(queues, 99), 4),
            "compute_p50_ms": round(percentile(computes, 50), 4),
            "compute_p99_ms": round(percentile(computes, 99), 4),
        })
    if rejected:
        row["retry_after_p50_ms"] = round(
            percentile([e.retry_after_ms for e in rejected], 50), 3)
    return row
