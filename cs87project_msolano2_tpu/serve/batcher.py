"""The coalescing batcher: many requests in, ONE padded kernel
invocation out.

Requests grouped under one :class:`GroupKey` (same n, layout,
precision, direction) are staged into a pooled ``(B_pad, n)`` plane
pair — ``B_pad`` rounded up to the next power of two so the whole
serving session compiles a handful of batch buckets instead of one
program per observed batch size (a fresh trace per size is the retrace
bug PIF2xx exists for, at serving rates) — and run through the plan
resolved for the PADDED batched shape via ``plans.plan_for``, exactly
the per-shard-shape discipline ``parallel/batched.py`` uses.

Execution is synchronous (the dispatcher calls it from an executor
thread so the event loop keeps admitting requests mid-kernel) and
carries the serving half of the resilience ladder:

* TRANSIENT faults retry in place (``resilience.call_with_retry``,
  fast policy — a serving session cannot sleep 30 s on a blip);
* CAPACITY / PERMANENT faults fall to the degradation rungs
  (``jnp-fft``, then the numpy reference) for THIS batch, tagged in
  every response it carried;
* an explicit ``rung=`` (the dispatcher's overload mode) skips the
  tuned kernel entirely and serves the cheap rung directly.

Inside the tuned path the plan's own executor is already wrapped in
the plan degradation chain (``resilience.degrade``), so kernel faults
demote stickily there too — ``plan.degraded`` is mirrored into the
batch outcome either way.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from .. import plans
from ..obs import metrics
from ..obs.spans import clock, span
from ..resilience import FAST_POLICY, FaultKind, call_with_retry, classify
from ..resilience.degrade import build_rung
from ..resilience.inject import maybe_fault
from .buffers import BufferPool

#: serve-side fallback rungs, weakest-demand last (the batched subset of
#: resilience.degrade.DEGRADE_CHAIN — rql is a 1-D whole-transform path
#: and cannot serve a batched key)
SERVE_FALLBACK_RUNGS = ("jnp-fft", "numpy-ref")


@dataclasses.dataclass(frozen=True)
class GroupKey:
    """The coalescing identity: requests may share a kernel invocation
    iff they share all six fields.  ``domain`` separates the
    half-spectrum real paths (r2c/c2r — docs/REAL.md) from c2c at the
    same n: an r2c group's coalesced invocation runs the HALF-WIDTH
    packed kernel, so mixing the domains would stage the wrong
    planes.  ``op`` is the served OPERATION (docs/APPS.md): "fft" is
    the bare transform; "conv"/"corr"/"solve" groups coalesce into
    one batched FUSED spectral pipeline (apps/spectral.py) — mixing
    ops would multiply the wrong spectra."""

    n: int
    layout: str = "natural"
    precision: str = "split3"
    inverse: bool = False
    domain: str = "c2c"
    op: str = "fft"

    def label(self) -> str:
        d = ":inv" if self.inverse else ""
        d += f":{self.domain}" if self.domain != "c2c" else ""
        d += f":{self.op}" if self.op != "fft" else ""
        return f"{self.n}:{self.layout}:{self.precision}{d}"

    def input_width(self) -> int:
        """Trailing-axis length of this group's staged request planes
        (half-spectrum bins for c2r, the signal length otherwise)."""
        return self.n // 2 + 1 if self.domain == "c2r" else self.n


def batch_bucket(size: int) -> int:
    """The padded batch dim: the next power of two >= size, so the
    session's compiled programs are one per bucket, not one per
    observed batch size."""
    b = 1
    while b < size:
        b *= 2
    return b


@dataclasses.dataclass
class BatchOutcome:
    """One kernel invocation's results, still batch-shaped: the
    dispatcher slices per-request rows out and builds responses."""

    yr: np.ndarray
    yi: np.ndarray
    compute_s: float
    size: int
    bucket: int
    plan_variant: str
    degraded: bool = False
    degrade: list = dataclasses.field(default_factory=list)


class BatchRunner:
    """Stages, pads, and executes one group's batches; caches the
    jitted callable per (group, bucket, rung) so every batch after the
    first reuses the compiled program."""

    def __init__(self, pool: Optional[BufferPool] = None,
                 backend: Optional[str] = None):
        self.pool = pool or BufferPool()
        #: the backend tag every plan key this runner builds carries
        #: (plans.core.BACKENDS — docs/BACKENDS.md); None = discover
        #: per process (plans.make_key's default)
        self.backend = backend
        self._callables: dict = {}

    def cached_groups(self) -> set:
        """GroupKeys with a compiled callable in this runner — the
        hottest warmth signal the mesh router reads (docs/SERVING.md):
        a group cached here serves its next batch with zero trace or
        plan-resolution cost."""
        return {key[0] for key in self._callables}

    def adopt_callables(self, other: "BatchRunner",
                        group: Optional[GroupKey] = None) -> int:
        """Warm-cache handoff (docs/SERVING.md, drain): copy `other`'s
        compiled callables — all of them, or one `group`'s — into this
        runner without displacing anything already here.  The jitted
        executables are process-global, so a drained device's compile
        investment moves to its successor instead of dying with it.
        Returns how many entries were adopted.

        CROSS-BACKEND handoff adopts NOTHING (returns 0): a callable
        compiled for one backend tag embeds that family's lowering —
        serving it under another tag would silently answer gpu traffic
        with a tpu program.  A plan is cold across tags unless
        explicitly cross-warmed (docs/BACKENDS.md)."""
        if other.backend != self.backend:
            return 0
        adopted = 0
        for key, val in list(other._callables.items()):
            if group is not None and key[0] != group:
                continue
            if key not in self._callables:
                self._callables[key] = val
                adopted += 1
        return adopted

    # ---------------------------------------------------- callables

    def _plan_for(self, group: GroupKey, bucket: int):
        return plans.plan_for((bucket, group.n), layout=group.layout,
                              precision=group.precision,
                              domain=group.domain,
                              backend=self.backend)

    def _callable(self, group: GroupKey, bucket: int,
                  rung: Optional[str]):
        """(callable, plan) for the group at this bucket — the tuned
        plan executor, or a degradation rung built for the batched
        key.  Direction is applied OUTSIDE the forward/rung choice: an
        inverse group stays an inverse on every rung (a fallback that
        quietly served the forward transform would be a wrong answer
        tagged merely degraded).

        Op-tagged groups (docs/APPS.md) serve the batched FUSED
        spectral pipeline from apps/spectral.py — and their rungs
        speak the OP natively (a jnp/numpy fallback that served a
        bare transform instead of the convolution would be a wrong
        answer merely tagged degraded), exactly like the inverse
        rule above."""
        import jax

        ck = (group, bucket, rung)
        hit = self._callables.get(ck)
        if hit is not None:
            return hit
        if group.op != "fft":
            from ..apps.spectral import op_executor

            run, plan = op_executor(group.op, (bucket,), group.n,
                                    precision=group.precision,
                                    rung=rung)
            donate = (0, 1) if plans.device_is_tunable() else ()
            fn = jax.jit(run, donate_argnums=donate)
            self._callables[ck] = (fn, plan)
            return fn, plan
        plan = self._plan_for(group, bucket)
        forward = build_rung(plan.key, rung) if rung is not None \
            else plan.fn
        if group.inverse:
            inv_n = np.float32(group.n)
            fwd = forward

            def run(xr, xi):  # the conj trick (plans.core contract)
                yr, yi = fwd(xr, -xi)
                return yr / inv_n, -yi / inv_n
        else:
            run = forward
        # donation lets XLA reuse the staged planes' device buffers for
        # the outputs — meaningful on real devices, a warning on
        # interpret backends, so gate it
        donate = (0, 1) if plans.device_is_tunable() else ()
        fn = jax.jit(run, donate_argnums=donate)
        self._callables[ck] = (fn, plan)
        return fn, plan

    # ----------------------------------------------------- staging

    def _stage(self, group: GroupKey, planes, bucket: int):
        width = group.input_width()
        xr = self.pool.acquire((bucket, width))
        xi = self.pool.acquire((bucket, width))
        for i, (pr, pi) in enumerate(planes):
            xr[i], xi[i] = pr, pi
        if len(planes) < bucket:  # padding rows must be defined
            xr[len(planes):] = 0.0
            xi[len(planes):] = 0.0
        return xr, xi

    # --------------------------------------------------- execution

    def run(self, group: GroupKey, planes,
            rung: Optional[str] = None,
            rung_tag: Optional[str] = None,
            links: Optional[list] = None) -> BatchOutcome:
        """Execute one coalesced batch (list of (xr, xi) float planes of
        shape (n,)).  `rung` forces a degradation rung up front (the
        dispatcher's overload fallback); otherwise the tuned plan runs
        and only a CAPACITY/PERMANENT fault walks the serve fallback
        rungs.  Raises only for faults no rung could absorb.

        `rung_tag` names a forced rung's trigger on the degrade trail
        (default ``overload:<rung>``; the burn-rate monitor passes
        ``slo:<rung>`` — docs/OBSERVABILITY.md).  `links` is the
        trace fan-in edge: the coalesced requests' span ids, recorded
        on the ONE serve_batch span (obs/trace.py)."""
        size = len(planes)
        bucket = batch_bucket(size)
        sxr, sxi = self._stage(group, planes, bucket)
        degrade: list = []
        if rung is not None:
            degrade.append(rung_tag if rung_tag is not None
                           else f"overload:{rung}")
        try:
            with span("serve_batch", cell={"n": group.n, "size": size},
                      bucket=bucket, rung=rung or "plan",
                      op=group.op, links=links) as sp:
                outcome = self._invoke(group, bucket, rung, sxr, sxi,
                                       degrade)
                if rung is None and planes:
                    # the error-budget contract (docs/PRECISION.md):
                    # sample one request row of every served batch
                    # against the float64 reference; a violation walks
                    # the plan UP the precision chain AND recomputes
                    # the batch at the promoted mode, tagged on the
                    # outcome (the fallback rungs are fp32 numpy/jnp —
                    # nothing to sample there)
                    self._enforce_precision(group, bucket, outcome,
                                            planes[0], sxr, sxi)
                sp.set(variant=outcome.plan_variant,
                       degraded=outcome.degraded)
        finally:
            self.pool.release(sxr, sxi)
        outcome.size = size
        # a non-empty degrade trail must never ride degraded=False: the
        # admission rung's "overload:<rung>" tag is attached up here
        # before the runner outcome exists, so reconcile on the way out
        # — the never-silent rule PIF115 machine-checks (the dispatcher
        # computed the same disjunction per response; now the OUTCOME
        # consumers — loadgen rows, tests — see it too)
        outcome.degraded = outcome.degraded or bool(outcome.degrade)
        metrics.inc("pifft_serve_batches_total", shape=group.label())
        metrics.inc("pifft_serve_batched_requests_total", value=size,
                    shape=group.label())
        # per-OP accounting (docs/APPS.md): how much of the served
        # traffic is operations vs bare transforms, and the fused-op
        # traffic the batch moved on the shared meter
        metrics.inc("pifft_serve_ops_total", value=size, op=group.op)
        if group.op != "fft":
            from ..utils.roofline import charge_spectral_traffic

            charge_spectral_traffic(group.op, group.n, count=size)
        metrics.observe("pifft_serve_batch_size", size,
                        shape=group.label())
        return outcome

    # ------------------------------------------- precision contract

    @staticmethod
    def _reference(group: GroupKey, sample):
        """(ref_r, ref_i) float64 oracle planes for one request of this
        group, in the group's own layout — or None for combinations
        with no cheap oracle (inverse real domains).  Op-tagged groups
        (docs/APPS.md) verify against their OP's numpy oracle — the
        circular conv/corr/solve pipeline, not a bare transform."""
        xr = np.asarray(sample[0], dtype=np.float64)
        xi = np.asarray(sample[1], dtype=np.float64)
        if group.op != "fft":
            from ..apps.spectral import numpy_oracle

            y = numpy_oracle(group.op, xr, xi, group.n)
            return y, np.zeros_like(y)
        if group.domain == "r2c":
            if group.inverse:
                return None
            y = np.fft.rfft(xr)
        elif group.domain == "c2r":
            if group.inverse:
                return None
            y = np.fft.irfft(xr + 1j * xi, n=group.n)
            return y, np.zeros_like(y)
        elif group.inverse:
            y = np.fft.ifft(xr + 1j * xi)
        else:
            y = np.fft.fft(xr + 1j * xi)
        ref_r, ref_i = y.real, y.imag
        if group.layout == "pi":
            # pi[i] = natural[bitrev(i)]: put the oracle in the
            # layout the kernel actually answers in
            from ..ops.bits import bit_reverse_indices

            idx = bit_reverse_indices(group.n)
            ref_r, ref_i = ref_r[idx], ref_i[idx]
        return ref_r, ref_i

    def _sample_err(self, plan, group: GroupKey, sample, ref) -> float:
        """Relative error of ONE re-run request row under the plan's
        CURRENT executor — used to re-check after a promotion."""
        from ..ops import precision as prec_mod

        xr = np.asarray(sample[0])[None, :]
        xi = np.asarray(sample[1])[None, :]
        if group.op != "fft":
            # the fused op pipeline at the promoted mode, through the
            # CACHED jitted bucket-1 callable (the promotion loop
            # dropped the stale entry, so this rebuild reads the
            # forward plan's promoted effective precision — and later
            # samples reuse the compiled program)
            fn, _plan = self._callable(group, 1, None)
            yr, yi = fn(xr, xi)
            return prec_mod.rel_err(np.asarray(yr)[0],
                                    np.asarray(yi)[0], ref[0], ref[1])
        if group.inverse:
            yr, yi = plan.fn(xr, -xi)  # the conj trick (plans.core)
            got_r = np.asarray(yr)[0] / np.float32(group.n)
            got_i = -np.asarray(yi)[0] / np.float32(group.n)
        else:
            yr, yi = plan.fn(xr, xi)
            got_r, got_i = np.asarray(yr)[0], np.asarray(yi)[0]
        return prec_mod.rel_err(got_r, got_i, ref[0], ref[1])

    def _enforce_precision(self, group: GroupKey, bucket: int,
                           outcome: BatchOutcome, sample,
                           sxr, sxi) -> None:
        """Sample the served batch's first request against the float64
        reference, publish the ``pifft_precision_rel_err`` gauge, and
        on a budget violation walk the plan UP the precision chain
        (resilience.degrade.promote_precision) — re-checking the
        sample at each promoted mode — until the budget holds or the
        chain tops out at fp32, then RE-RUN the whole staged batch at
        the promoted mode so the responses carry the tightest-mode
        data, not the violating planes.  Every step is tagged on the
        outcome (and so on every response the batch carried): a batch
        that violated its contract is served at the tightest mode
        available, marked degraded, never silently."""
        from ..ops import precision as prec_mod
        from ..resilience.degrade import promote_precision

        ck = (group, bucket, None)
        cached = self._callables.get(ck)
        if cached is None:
            return
        _fn, plan = cached
        if outcome.plan_variant in SERVE_FALLBACK_RUNGS:
            # the batch was served by a fault-fallback rung (jnp-fft /
            # numpy-ref): those run fp32 reference paths — sampling
            # would publish the gauge under the TUNED mode's label
            # while measuring the rung, and a promotion would re-run
            # the very kernel that just faulted
            return
        ref = self._reference(group, sample)
        if ref is None:
            return
        got_r = np.asarray(outcome.yr)[0]
        got_i = np.asarray(outcome.yi)[0]
        err = prec_mod.rel_err(got_r, got_i, ref[0], ref[1])
        mode = plan.effective_precision()
        # an op group's fused pipeline composes TWO transforms
        # (rfft + irfft), so its roundoff is ~2x a bare transform's:
        # the budget scales with the pipeline depth — otherwise a
        # healthy split3 conv flaps at the single-transform bound
        # (docs/APPS.md)
        op_scale = 2.0 if group.op != "fft" else 1.0
        budget = prec_mod.error_budget(mode) * op_scale
        metrics.set_gauge("pifft_precision_rel_err", err,
                          shape=group.label(), mode=mode)
        promoted = False
        while err > budget:
            nxt = promote_precision(plan, err, budget)
            outcome.degraded = True
            if nxt is None:
                break  # top of the chain: serve tagged, nothing tighter
            promoted = True
            outcome.degrade.append(f"precision:{nxt}")
            # the jitted callable bakes the old executor: drop it so
            # the recompute below (and this group's next batch) builds
            # at the promoted mode — the bucket-1 sampling callable
            # included, or _sample_err would measure the stale mode
            self._callables.pop(ck, None)
            self._callables.pop((group, 1, None), None)
            err = self._sample_err(plan, group, sample, ref)
            mode = nxt
            budget = prec_mod.error_budget(mode) * op_scale
            metrics.set_gauge("pifft_precision_rel_err", err,
                              shape=group.label(), mode=mode)
        if promoted:
            # the responses must carry the promoted-mode data — the
            # staged planes are still live (released by run()'s
            # finally AFTER this check), so one re-invocation replaces
            # the violating planes batch-wide.  A fault here must not
            # kill a batch that already holds a (tagged, violating)
            # answer: keep the original planes and say so.
            from ..plans.core import warn

            try:
                fn, _plan = self._callable(group, bucket, None)
                yr, yi = fn(sxr, sxi)
            except Exception as e:
                warn(f"promoted-mode recompute failed for "
                     f"{group.label()} ({type(e).__name__}: "
                     f"{str(e)[:120]}); serving the tagged "
                     f"violating-mode planes")
                return
            outcome.yr = np.asarray(yr)
            outcome.yi = np.asarray(yi)

    def _invoke(self, group, bucket, rung, sxr, sxi,
                degrade) -> BatchOutcome:
        def attempt(use_rung):
            if use_rung is None:
                # injection site: the TUNED serving path only — the
                # fallback rungs stay clean, mirroring the tube site's
                # semantics, so an always-on chaos spec degrades the
                # service instead of killing it
                maybe_fault("serve")
            fn, plan = self._callable(group, bucket, use_rung)
            t0 = clock()
            yr, yi = fn(sxr, sxi)
            yr = np.asarray(yr)
            yi = np.asarray(yi)
            return BatchOutcome(
                yr=yr, yi=yi, compute_s=clock() - t0, size=bucket,
                bucket=bucket,
                plan_variant=use_rung or plan.variant,
                degraded=plan.degraded,
                degrade=degrade + (
                    [f"plan:{rec['to']}" for rec in plan.demotions]
                    if plan.degraded else []))

        try:
            # TRANSIENT faults retry in place on the fast policy — a
            # serving path cannot afford the bench's relay-scale waits
            return call_with_retry(attempt, rung, policy=FAST_POLICY,
                                   label=f"serve {group.label()}")
        except Exception as e:
            kind = classify(e)
            if kind is FaultKind.TRANSIENT:
                raise  # the retry budget is spent; nothing left to try
            exc = e
            start = (SERVE_FALLBACK_RUNGS.index(rung) + 1
                     if rung in SERVE_FALLBACK_RUNGS else 0)
            for fb in SERVE_FALLBACK_RUNGS[start:]:
                try:
                    out = call_with_retry(attempt, fb, policy=FAST_POLICY,
                                          label=f"serve fallback {fb}")
                except Exception as e2:
                    if classify(e2) is FaultKind.TRANSIENT:
                        raise
                    exc = e2
                    continue
                tag = f"fault:{kind.value}:{fb}"
                out.degraded = True
                out.degrade = degrade + [tag]
                from ..obs import events
                from ..plans.core import warn

                metrics.inc("pifft_serve_fallbacks_total", rung=fb)
                events.emit("serve_degrade",
                            cell={"n": group.n, "variant": fb},
                            level=tag, shape=group.label(),
                            reason=f"{type(e).__name__}: {str(e)[:200]}")
                warn(f"serve batch {group.label()} DEGRADED to {fb} "
                     f"({kind.value}: {type(e).__name__}) — results stay "
                     f"correct; performance does not")
                return out
            raise exc
