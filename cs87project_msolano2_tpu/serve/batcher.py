"""The coalescing batcher: many requests in, ONE padded kernel
invocation out.

Requests grouped under one :class:`GroupKey` (same n, layout,
precision, direction) are staged into a pooled ``(B_pad, n)`` plane
pair — ``B_pad`` rounded up to the next power of two so the whole
serving session compiles a handful of batch buckets instead of one
program per observed batch size (a fresh trace per size is the retrace
bug PIF2xx exists for, at serving rates) — and run through the plan
resolved for the PADDED batched shape via ``plans.plan_for``, exactly
the per-shard-shape discipline ``parallel/batched.py`` uses.

Execution is synchronous (the dispatcher calls it from an executor
thread so the event loop keeps admitting requests mid-kernel) and
carries the serving half of the resilience ladder:

* TRANSIENT faults retry in place (``resilience.call_with_retry``,
  fast policy — a serving session cannot sleep 30 s on a blip);
* CAPACITY / PERMANENT faults fall to the degradation rungs
  (``jnp-fft``, then the numpy reference) for THIS batch, tagged in
  every response it carried;
* an explicit ``rung=`` (the dispatcher's overload mode) skips the
  tuned kernel entirely and serves the cheap rung directly.

Inside the tuned path the plan's own executor is already wrapped in
the plan degradation chain (``resilience.degrade``), so kernel faults
demote stickily there too — ``plan.degraded`` is mirrored into the
batch outcome either way.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from .. import plans
from ..obs import metrics
from ..obs.spans import clock, span
from ..resilience import FAST_POLICY, FaultKind, call_with_retry, classify
from ..resilience.degrade import build_rung
from ..resilience.inject import maybe_fault
from .buffers import BufferPool

#: serve-side fallback rungs, weakest-demand last (the batched subset of
#: resilience.degrade.DEGRADE_CHAIN — rql is a 1-D whole-transform path
#: and cannot serve a batched key)
SERVE_FALLBACK_RUNGS = ("jnp-fft", "numpy-ref")


@dataclasses.dataclass(frozen=True)
class GroupKey:
    """The coalescing identity: requests may share a kernel invocation
    iff they share all five fields.  ``domain`` separates the
    half-spectrum real paths (r2c/c2r — docs/REAL.md) from c2c at the
    same n: an r2c group's coalesced invocation runs the HALF-WIDTH
    packed kernel, so mixing the domains would stage the wrong
    planes."""

    n: int
    layout: str = "natural"
    precision: str = "split3"
    inverse: bool = False
    domain: str = "c2c"

    def label(self) -> str:
        d = ":inv" if self.inverse else ""
        d += f":{self.domain}" if self.domain != "c2c" else ""
        return f"{self.n}:{self.layout}:{self.precision}{d}"

    def input_width(self) -> int:
        """Trailing-axis length of this group's staged request planes
        (half-spectrum bins for c2r, the signal length otherwise)."""
        return self.n // 2 + 1 if self.domain == "c2r" else self.n


def batch_bucket(size: int) -> int:
    """The padded batch dim: the next power of two >= size, so the
    session's compiled programs are one per bucket, not one per
    observed batch size."""
    b = 1
    while b < size:
        b *= 2
    return b


@dataclasses.dataclass
class BatchOutcome:
    """One kernel invocation's results, still batch-shaped: the
    dispatcher slices per-request rows out and builds responses."""

    yr: np.ndarray
    yi: np.ndarray
    compute_s: float
    size: int
    bucket: int
    plan_variant: str
    degraded: bool = False
    degrade: list = dataclasses.field(default_factory=list)


class BatchRunner:
    """Stages, pads, and executes one group's batches; caches the
    jitted callable per (group, bucket, rung) so every batch after the
    first reuses the compiled program."""

    def __init__(self, pool: Optional[BufferPool] = None):
        self.pool = pool or BufferPool()
        self._callables: dict = {}

    # ---------------------------------------------------- callables

    def _plan_for(self, group: GroupKey, bucket: int):
        return plans.plan_for((bucket, group.n), layout=group.layout,
                              precision=group.precision,
                              domain=group.domain)

    def _callable(self, group: GroupKey, bucket: int,
                  rung: Optional[str]):
        """(callable, plan) for the group at this bucket — the tuned
        plan executor, or a degradation rung built for the batched
        key.  Direction is applied OUTSIDE the forward/rung choice: an
        inverse group stays an inverse on every rung (a fallback that
        quietly served the forward transform would be a wrong answer
        tagged merely degraded)."""
        import jax

        ck = (group, bucket, rung)
        hit = self._callables.get(ck)
        if hit is not None:
            return hit
        plan = self._plan_for(group, bucket)
        forward = build_rung(plan.key, rung) if rung is not None \
            else plan.fn
        if group.inverse:
            inv_n = np.float32(group.n)
            fwd = forward

            def run(xr, xi):  # the conj trick (plans.core contract)
                yr, yi = fwd(xr, -xi)
                return yr / inv_n, -yi / inv_n
        else:
            run = forward
        # donation lets XLA reuse the staged planes' device buffers for
        # the outputs — meaningful on real devices, a warning on
        # interpret backends, so gate it
        donate = (0, 1) if plans.device_is_tunable() else ()
        fn = jax.jit(run, donate_argnums=donate)
        self._callables[ck] = (fn, plan)
        return fn, plan

    # ----------------------------------------------------- staging

    def _stage(self, group: GroupKey, planes, bucket: int):
        width = group.input_width()
        xr = self.pool.acquire((bucket, width))
        xi = self.pool.acquire((bucket, width))
        for i, (pr, pi) in enumerate(planes):
            xr[i], xi[i] = pr, pi
        if len(planes) < bucket:  # padding rows must be defined
            xr[len(planes):] = 0.0
            xi[len(planes):] = 0.0
        return xr, xi

    # --------------------------------------------------- execution

    def run(self, group: GroupKey, planes,
            rung: Optional[str] = None) -> BatchOutcome:
        """Execute one coalesced batch (list of (xr, xi) float planes of
        shape (n,)).  `rung` forces a degradation rung up front (the
        dispatcher's overload fallback); otherwise the tuned plan runs
        and only a CAPACITY/PERMANENT fault walks the serve fallback
        rungs.  Raises only for faults no rung could absorb."""
        size = len(planes)
        bucket = batch_bucket(size)
        sxr, sxi = self._stage(group, planes, bucket)
        degrade: list = []
        if rung is not None:
            degrade.append(f"overload:{rung}")
        try:
            with span("serve_batch", cell={"n": group.n, "size": size},
                      bucket=bucket, rung=rung or "plan") as sp:
                outcome = self._invoke(group, bucket, rung, sxr, sxi,
                                       degrade)
                sp.set(variant=outcome.plan_variant,
                       degraded=outcome.degraded)
        finally:
            self.pool.release(sxr, sxi)
        outcome.size = size
        metrics.inc("pifft_serve_batches_total", shape=group.label())
        metrics.inc("pifft_serve_batched_requests_total", value=size,
                    shape=group.label())
        metrics.observe("pifft_serve_batch_size", size,
                        shape=group.label())
        return outcome

    def _invoke(self, group, bucket, rung, sxr, sxi,
                degrade) -> BatchOutcome:
        def attempt(use_rung):
            if use_rung is None:
                # injection site: the TUNED serving path only — the
                # fallback rungs stay clean, mirroring the tube site's
                # semantics, so an always-on chaos spec degrades the
                # service instead of killing it
                maybe_fault("serve")
            fn, plan = self._callable(group, bucket, use_rung)
            t0 = clock()
            yr, yi = fn(sxr, sxi)
            yr = np.asarray(yr)
            yi = np.asarray(yi)
            return BatchOutcome(
                yr=yr, yi=yi, compute_s=clock() - t0, size=bucket,
                bucket=bucket,
                plan_variant=use_rung or plan.variant,
                degraded=plan.degraded,
                degrade=degrade + (
                    [f"plan:{rec['to']}" for rec in plan.demotions]
                    if plan.degraded else []))

        try:
            # TRANSIENT faults retry in place on the fast policy — a
            # serving path cannot afford the bench's relay-scale waits
            return call_with_retry(attempt, rung, policy=FAST_POLICY,
                                   label=f"serve {group.label()}")
        except Exception as e:
            kind = classify(e)
            if kind is FaultKind.TRANSIENT:
                raise  # the retry budget is spent; nothing left to try
            exc = e
            start = (SERVE_FALLBACK_RUNGS.index(rung) + 1
                     if rung in SERVE_FALLBACK_RUNGS else 0)
            for fb in SERVE_FALLBACK_RUNGS[start:]:
                try:
                    out = call_with_retry(attempt, fb, policy=FAST_POLICY,
                                          label=f"serve fallback {fb}")
                except Exception as e2:
                    if classify(e2) is FaultKind.TRANSIENT:
                        raise
                    exc = e2
                    continue
                tag = f"fault:{kind.value}:{fb}"
                out.degraded = True
                out.degrade = degrade + [tag]
                from ..obs import events
                from ..plans.core import warn

                metrics.inc("pifft_serve_fallbacks_total", rung=fb)
                events.emit("serve_degrade",
                            cell={"n": group.n, "variant": fb},
                            level=tag, shape=group.label(),
                            reason=f"{type(e).__name__}: {str(e)[:200]}")
                warn(f"serve batch {group.label()} DEGRADED to {fb} "
                     f"({kind.value}: {type(e).__name__}) — results stay "
                     f"correct; performance does not")
                return out
            raise exc
