"""`pifft serve` — run the serving front door, or its offline smoke.

Server mode binds the length-prefixed JSON socket front
(:mod:`.protocol`) on ``--host``/``--port``, warms ``--shapes`` at
startup, and serves until interrupted.

``--smoke`` is the CI gate (``make serve-smoke``): an in-process
dispatcher on this host's backend (CPU in CI) is hit with k concurrent
same-shape requests plus mixed-shape traffic, and the run FAILS unless

* coalescing happened: the k same-shape requests were served by
  strictly fewer kernel invocations than k, read from the
  ``pifft_serve_*`` obs counters (the counters, not a side channel —
  so the observability wiring is re-proven too);
* every response verifies against ``numpy.fft`` (a batched, padded,
  coalesced path that returns the wrong rows would otherwise pass);
* every emitted event validates against the obs schema;
* the per-shape SLO table (p50/p99 queue-wait and compute) is
  reportable.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys

import numpy as np

from .batcher import GroupKey
from .dispatcher import Dispatcher, ServeConfig
from .shapes import ShapeSpec, load_shapes
from .slo import format_summary

#: the smoke's served set: one coalescing-burst shape + mixed traffic
#: (a second n, a pi-layout shape, and a half-spectrum r2c shape —
#: grouping AND the real-input domain path are exercised; the r2c
#: responses are verified against numpy.fft.rfft and asserted
#: half-width, docs/REAL.md)
SMOKE_SPECS = (ShapeSpec(n=4096), ShapeSpec(n=1024),
               ShapeSpec(n=2048, layout="pi"),
               ShapeSpec(n=1024, domain="r2c"))


def _build_config(args) -> ServeConfig:
    cfg = ServeConfig()
    if args.max_batch is not None:
        cfg.max_batch = args.max_batch
    if args.max_wait_ms is not None:
        cfg.max_wait_ms = args.max_wait_ms
    if args.queue_depth is not None:
        cfg.queue_depth = args.queue_depth
    cfg.strict_shapes = bool(args.strict)
    return cfg


def serve_main(argv) -> int:
    ap = argparse.ArgumentParser(
        prog="cs87project_msolano2_tpu serve",
        description="async batched FFT-as-a-service: bounded queues, "
                    "request coalescing, warm plans, graceful "
                    "degradation (docs/SERVING.md)",
    )
    ap.add_argument("--smoke", action="store_true",
                    help="in-process CI smoke: concurrent mixed-shape "
                         "requests, coalescing + schema assertions, "
                         "per-shape p50/p99 report")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8571)
    ap.add_argument("--shapes", default=None, metavar="FILE",
                    help="served shape set (JSONL of {n, batch, "
                         "precision, layout}); warmed at startup")
    ap.add_argument("--strict", action="store_true",
                    help="reject shapes outside the warmed set "
                         "(shape_not_served) instead of serving cold")
    ap.add_argument("--max-batch", type=int, default=None)
    ap.add_argument("--max-wait-ms", type=float, default=None)
    ap.add_argument("--queue-depth", type=int, default=None)
    ap.add_argument("-k", type=int, default=12,
                    help="smoke: concurrent same-shape requests "
                         "(default 12)")
    ap.add_argument("--json", action="store_true",
                    help="smoke: machine-readable report")
    args = ap.parse_args(argv)

    cfg = _build_config(args)
    if args.shapes:
        try:
            specs = load_shapes(args.shapes)
        except (OSError, ValueError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
    else:
        specs = list(SMOKE_SPECS) if args.smoke else []

    if args.smoke:
        return _smoke(cfg, specs, args)

    from .protocol import serve_socket

    dispatcher = Dispatcher(cfg, specs)

    async def main():
        async with dispatcher:
            await serve_socket(dispatcher, args.host, args.port)

    try:
        asyncio.run(main())
    except KeyboardInterrupt:
        print("# serve: interrupted, shutting down", file=sys.stderr)
    return 0


def _smoke(cfg: ServeConfig, specs, args) -> int:
    from .. import obs
    from ..obs import events as obs_events
    from ..obs import metrics
    from ..utils import verify

    owned = not obs.enabled()
    if owned:
        obs.enable()

    # a generous window + the burst being enqueued before the worker
    # first runs makes coalescing deterministic on any host
    if args.max_wait_ms is None:
        cfg.max_wait_ms = 25.0
    k = max(2, args.k)
    burst = specs[0]
    rng = np.random.default_rng(0)

    def planes_for(spec):
        """(xr, xi) request planes for one spec's domain: both planes
        for c2c, a real signal + zeros for r2c, half-spectrum bins
        for c2r (docs/REAL.md)."""
        if spec.domain == "c2r":
            spec_ref = np.fft.rfft(
                rng.standard_normal(spec.n).astype(np.float64))
            return (spec_ref.real.astype(np.float32),
                    spec_ref.imag.astype(np.float32))
        xr = rng.standard_normal(spec.n).astype(np.float32)
        if spec.domain == "r2c":
            return xr, np.zeros_like(xr)
        return xr, rng.standard_normal(spec.n).astype(np.float32)

    def check_response(spec, xr, xi, resp):
        """Problem string, or None: natural-layout responses verify
        against the numpy oracle of their DOMAIN, and half-spectrum
        responses must actually be half-width (a full-width r2c
        answer means the packed path never ran)."""
        if spec.layout != "natural":
            return None
        got = np.asarray(resp.yr) + 1j * np.asarray(resp.yi)
        if spec.domain == "r2c":
            if got.shape[-1] != spec.n // 2 + 1:
                return (f"response {resp.rid}: r2c answer is "
                        f"{got.shape[-1]} bins, want {spec.n // 2 + 1}"
                        f" (half-spectrum)")
            ref = np.fft.rfft(xr.astype(np.float64))
        elif spec.domain == "c2r":
            got = np.asarray(resp.yr)
            ref = np.fft.irfft(xr.astype(np.float64)
                               + 1j * xi.astype(np.float64), n=spec.n)
        else:
            ref = np.fft.fft(xr.astype(np.complex128)
                             + 1j * xi.astype(np.complex128))
        err = verify.rel_err(got, ref)
        # the tolerance is the shape's PRECISION-MODE error budget
        # (docs/PRECISION.md) — a bf16-storage shape legitimately
        # answers at ~1e-2, a split3 one must stay at the classic
        # 1e-4 coalesced-path bound
        from ..ops.precision import error_budget

        tol = max(1e-4, error_budget(spec.precision))
        if err > tol:
            return (f"response {resp.rid} wrong: rel err {err:.3e} > "
                    f"{tol:.0e} vs numpy {spec.domain} "
                    f"({spec.precision} budget)")
        return None

    inputs = [planes_for(burst) for _ in range(k)]
    mixed = [(s, *planes_for(s)) for s in specs[1:] for _ in range(2)]

    async def main():
        async with Dispatcher(cfg, specs) as d:
            calls = [d.submit(xr, xi, layout=burst.layout,
                              precision=burst.precision,
                              domain=burst.domain)
                     for xr, xi in inputs]
            calls += [d.submit(xr, xi, layout=s.layout,
                               precision=s.precision, domain=s.domain)
                      for s, xr, xi in mixed]
            responses = await asyncio.gather(*calls)
            return d, responses

    d, responses = asyncio.run(main())

    problems = []
    # every natural-layout response must verify against numpy: a padded
    # coalesced batch that hands back the wrong rows is the one bug a
    # latency report would never catch — and an r2c response must come
    # back half-width, or the domain plan quietly served full-spectrum
    for (xr, xi), resp in zip(inputs, responses[:k]):
        problem = check_response(burst, xr, xi, resp)
        if problem:
            problems.append(problem)
            break
    for (s, xr, xi), resp in zip(mixed, responses[k:]):
        problem = check_response(s, xr, xi, resp)
        if problem:
            problems.append(problem)
            break

    label = GroupKey(n=burst.n, layout=burst.layout,
                     precision=burst.precision,
                     domain=burst.domain).label()
    reqs = int(metrics.counter_value("pifft_serve_requests_total",
                                     shape=label))
    batches = int(metrics.counter_value("pifft_serve_batches_total",
                                        shape=label))
    if not (0 < batches < k):
        problems.append(
            f"no coalescing: {reqs} concurrent {label} requests were "
            f"served by {batches} kernel invocation(s) (want 0 < "
            f"invocations < {k})")

    bad_events = 0
    snapshot = obs_events.snapshot()
    for rec in snapshot:
        for p in obs_events.validate_event(rec):
            bad_events += 1
            problems.append(f"event seq={rec.get('seq')}: {p}")

    summary = d.stats.summary()
    if owned:
        obs.disable()

    if args.json:
        print(json.dumps({
            "ok": not problems,
            "same_shape_requests": k,
            "same_shape_batches": batches,
            "events": len(snapshot),
            "schema_invalid_events": bad_events,
            "stats": summary,
            "buffers": d.runner.pool.stats(),
            "problems": problems,
        }, indent=1, sort_keys=True))
    else:
        print(format_summary(summary))
        print(f"# serve smoke: {k} concurrent {label} requests -> "
              f"{batches} kernel invocation(s); "
              f"{len(snapshot)} event(s), {bad_events} schema-invalid; "
              f"buffers {d.runner.pool.stats()}")
        for p in problems:
            print(f"# FAIL: {p}", file=sys.stderr)
    if problems:
        return 1
    print("# serve smoke ok", file=sys.stderr)
    return 0
