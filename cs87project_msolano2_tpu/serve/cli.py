"""`pifft serve` — run the serving front door, or its offline smokes.

Server mode binds the length-prefixed JSON socket front
(:mod:`.protocol`) on ``--host``/``--port``, warms ``--shapes`` at
startup, and serves until interrupted.  ``--devices N`` puts the
:class:`~.mesh.MeshDispatcher` behind the same socket: per-device
worker pools, shape-affinity routing, priority admission, and
self-healing failover (docs/SERVING.md, mesh section).
``--telemetry-port`` arms the live plane (streaming /metrics,
/healthz, /slo — docs/OBSERVABILITY.md) and ``--slo-objectives``
the burn-rate monitor whose sustained-burn alerts force
admission-time degradation, tagged ``slo:*``.

``--mesh-smoke`` is the mesh CI gate (``make serve-mesh-smoke``): a
virtual 8-device CPU mesh warmed with an 8-shape set, driven by the
open-loop chaos load with a MID-RUN DEVICE KILL, then a planned
journaled drain — and the run FAILS unless zero requests were
dropped, every response verifies against numpy, the re-routed
requests carry a ``failover:*`` trail, consensus was reached before
the re-route, utilization stayed within the spread bound, the
pre/post-kill p99 pair is recorded, shape affinity held (the
placement counter), and the drained device's successor serves its
groups without re-tuning.

``--smoke`` is the CI gate (``make serve-smoke``): an in-process
dispatcher on this host's backend (CPU in CI) is hit with k concurrent
same-shape requests plus mixed-shape traffic, and the run FAILS unless

* coalescing happened: the k same-shape requests were served by
  strictly fewer kernel invocations than k, read from the
  ``pifft_serve_*`` obs counters (the counters, not a side channel —
  so the observability wiring is re-proven too);
* every response verifies against ``numpy.fft`` (a batched, padded,
  coalesced path that returns the wrong rows would otherwise pass);
* every emitted event validates against the obs schema;
* the per-shape SLO table (p50/p99 queue-wait and compute) is
  reportable.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys

import numpy as np

from .batcher import GroupKey
from .dispatcher import Dispatcher, ServeConfig
from .shapes import ShapeSpec, load_shapes
from .slo import format_summary

#: the smoke's served set: one coalescing-burst shape + mixed traffic
#: (a second n, a pi-layout shape, and a half-spectrum r2c shape —
#: grouping AND the real-input domain path are exercised; the r2c
#: responses are verified against numpy.fft.rfft and asserted
#: half-width, docs/REAL.md)
SMOKE_SPECS = (ShapeSpec(n=4096), ShapeSpec(n=1024),
               ShapeSpec(n=2048, layout="pi"),
               ShapeSpec(n=1024, domain="r2c"))

#: the mesh smoke's served set: 8 equal-cost groups (one warmed per
#: virtual device) so the utilization-spread bound is meaningful —
#: same n, natural/pi layouts crossed with the fp32-storage precision
#: modes (bf16 is excluded here: its looser budget would mask a
#: wrong-rows bug the spread run exists to catch)
MESH_SMOKE_SPECS = tuple(
    ShapeSpec(n=512, layout=lay, precision=p)
    for lay in ("natural", "pi")
    for p in ("split3", "default", "fp32", "highest"))

#: utilization balance bound the mesh smoke asserts: no serving
#: device may be busier than this multiple of the mean (the post-kill
#: survivor legitimately carries the dead device's group, so the
#: bound is loose enough for 2x plus jitter)
MESH_UTIL_SPREAD = 3.0


def _build_config(args) -> ServeConfig:
    cfg = ServeConfig()
    if args.max_batch is not None:
        cfg.max_batch = args.max_batch
    if args.max_wait_ms is not None:
        cfg.max_wait_ms = args.max_wait_ms
    if args.queue_depth is not None:
        cfg.queue_depth = args.queue_depth
    cfg.strict_shapes = bool(args.strict)
    cfg.slo_objectives = getattr(args, "slo_objectives", None)
    return cfg


def serve_main(argv) -> int:
    ap = argparse.ArgumentParser(
        prog="cs87project_msolano2_tpu serve",
        description="async batched FFT-as-a-service: bounded queues, "
                    "request coalescing, warm plans, graceful "
                    "degradation (docs/SERVING.md)",
    )
    ap.add_argument("--smoke", action="store_true",
                    help="in-process CI smoke: concurrent mixed-shape "
                         "requests, coalescing + schema assertions, "
                         "per-shape p50/p99 report")
    ap.add_argument("--mesh-smoke", action="store_true",
                    help="in-process mesh CI gate: virtual device "
                         "mesh under open-loop load with a mid-run "
                         "device kill and a journaled drain "
                         "(make serve-mesh-smoke)")
    ap.add_argument("--wire-smoke", action="store_true",
                    help="wire CI gate (make wire-smoke): both "
                         "dialects over a real socket must return "
                         "byte-identical planes, the binary path must "
                         "charge ZERO metered host-copy bytes, and "
                         "negotiation/fallback, streaming and the shm "
                         "lane must round-trip (docs/SERVING.md)")
    ap.add_argument("--shm", action="store_true",
                    help="server mode: arm the same-host shared-"
                         "memory lane — HELLO frames asking for it "
                         "get a per-connection slot ring "
                         "(serve/shm.py)")
    ap.add_argument("--devices", type=int, default=None,
                    help="serve on a device mesh of this size "
                         "(MeshDispatcher; mesh-smoke default 8)")
    ap.add_argument("--mesh-rps", type=float, default=120.0,
                    help="mesh-smoke: offered load (requests/s)")
    ap.add_argument("--mesh-duration", type=float, default=1.2,
                    help="mesh-smoke: seconds of offered load")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8571)
    ap.add_argument("--telemetry-port", type=int, default=None,
                    metavar="PORT",
                    help="serve the live telemetry plane (/metrics "
                         "/healthz /slo) on this HTTP port "
                         "(docs/OBSERVABILITY.md; 0 = ephemeral); "
                         "arms in-process observability when not "
                         "already enabled")
    ap.add_argument("--slo-objectives", default=None, metavar="FILE",
                    help="burn-rate SLO objectives (YAML/JSON, "
                         "obs/slomon.py): sustained error-budget burn "
                         "forces admission-time degradation, tagged "
                         "slo:*")
    ap.add_argument("--shapes", default=None, metavar="FILE",
                    help="served shape set (JSONL of {n, batch, "
                         "precision, layout}); warmed at startup")
    ap.add_argument("--strict", action="store_true",
                    help="reject shapes outside the warmed set "
                         "(shape_not_served) instead of serving cold")
    ap.add_argument("--max-batch", type=int, default=None)
    ap.add_argument("--max-wait-ms", type=float, default=None)
    ap.add_argument("--queue-depth", type=int, default=None)
    ap.add_argument("-k", type=int, default=12,
                    help="smoke: concurrent same-shape requests "
                         "(default 12)")
    ap.add_argument("--json", action="store_true",
                    help="smoke: machine-readable report")
    args = ap.parse_args(argv)

    cfg = _build_config(args)
    if args.shapes:
        try:
            specs = load_shapes(args.shapes)
        except (OSError, ValueError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
    else:
        specs = list(SMOKE_SPECS) if args.smoke else []

    if args.mesh_smoke:
        return _mesh_smoke(cfg, specs or list(MESH_SMOKE_SPECS), args)
    if args.wire_smoke:
        return _wire_smoke(cfg, args)
    if args.smoke:
        return _smoke(cfg, specs, args)

    from .protocol import serve_socket

    shm_config = None
    if args.shm:
        # slot must hold two float32 planes of the largest served
        # shape (8 MiB floor when serving cold — no warmed set to
        # size from)
        slot_bytes = max([s.n * 8 for s in specs] or [1 << 23])
        shm_config = {"slots": 8, "slot_bytes": slot_bytes}

    if args.devices and args.devices > 1:
        from .mesh import MeshConfig, MeshDispatcher

        mesh_cfg = MeshConfig(**vars(cfg), devices=args.devices)
        dispatcher = MeshDispatcher(mesh_cfg, specs)
    else:
        dispatcher = Dispatcher(cfg, specs)

    telemetry = None
    if args.telemetry_port is not None:
        # the live plane reads the metrics registry and the streaming
        # SLO reservoir: without observability armed both are empty,
        # so a telemetry request implies at least in-process buffering
        from .. import obs
        from ..obs.http import TelemetryServer

        if not obs.enabled():
            obs.enable()
        telemetry = TelemetryServer(dispatcher, host=args.host,
                                    port=args.telemetry_port).start()

    async def main():
        async with dispatcher:
            await serve_socket(dispatcher, args.host, args.port,
                               shm_config=shm_config)

    try:
        asyncio.run(main())
    except KeyboardInterrupt:
        print("# serve: interrupted, shutting down", file=sys.stderr)
    finally:
        if telemetry is not None:
            telemetry.stop()
    return 0


def _smoke(cfg: ServeConfig, specs, args) -> int:
    from .. import obs
    from ..obs import events as obs_events
    from ..obs import metrics
    from ..utils import verify

    owned = not obs.enabled()
    if owned:
        obs.enable()

    # a generous window + the burst being enqueued before the worker
    # first runs makes coalescing deterministic on any host
    if args.max_wait_ms is None:
        cfg.max_wait_ms = 25.0
    k = max(2, args.k)
    burst = specs[0]
    rng = np.random.default_rng(0)

    def planes_for(spec):
        """(xr, xi) request planes for one spec's domain AND op: both
        planes for c2c, a real signal + zeros for r2c, half-spectrum
        bins for c2r (docs/REAL.md); op specs send their operands —
        signal + kernel for conv/corr, the field for solve
        (docs/APPS.md)."""
        if spec.op in ("conv", "corr"):
            return (rng.standard_normal(spec.n).astype(np.float32),
                    rng.standard_normal(spec.n).astype(np.float32))
        if spec.op == "solve":
            xr = rng.standard_normal(spec.n).astype(np.float32)
            return xr, np.zeros_like(xr)
        if spec.domain == "c2r":
            spec_ref = np.fft.rfft(
                rng.standard_normal(spec.n).astype(np.float64))
            return (spec_ref.real.astype(np.float32),
                    spec_ref.imag.astype(np.float32))
        xr = rng.standard_normal(spec.n).astype(np.float32)
        if spec.domain == "r2c":
            return xr, np.zeros_like(xr)
        return xr, rng.standard_normal(spec.n).astype(np.float32)

    def check_response(spec, xr, xi, resp):
        """Problem string, or None: natural-layout responses verify
        against the numpy oracle of their DOMAIN (and OP — an
        op-tagged shape verifies the fused pipeline, docs/APPS.md),
        and half-spectrum responses must actually be half-width (a
        full-width r2c answer means the packed path never ran)."""
        if spec.layout != "natural":
            return None
        if spec.op != "fft":
            from ..apps.spectral import numpy_oracle
            from ..ops.precision import error_budget

            ref = numpy_oracle(spec.op, xr.astype(np.float64),
                               xi.astype(np.float64), spec.n)
            err = verify.rel_err(np.asarray(resp.yr, np.float64), ref)
            tol = max(1e-4, error_budget(spec.precision))
            if err > tol:
                return (f"response {resp.rid} wrong: rel err "
                        f"{err:.3e} > {tol:.0e} vs numpy {spec.op} "
                        f"oracle ({spec.precision} budget)")
            return None
        got = np.asarray(resp.yr) + 1j * np.asarray(resp.yi)
        if spec.domain == "r2c":
            if got.shape[-1] != spec.n // 2 + 1:
                return (f"response {resp.rid}: r2c answer is "
                        f"{got.shape[-1]} bins, want {spec.n // 2 + 1}"
                        f" (half-spectrum)")
            ref = np.fft.rfft(xr.astype(np.float64))
        elif spec.domain == "c2r":
            got = np.asarray(resp.yr)
            ref = np.fft.irfft(xr.astype(np.float64)
                               + 1j * xi.astype(np.float64), n=spec.n)
        else:
            ref = np.fft.fft(xr.astype(np.complex128)
                             + 1j * xi.astype(np.complex128))
        err = verify.rel_err(got, ref)
        # the tolerance is the shape's PRECISION-MODE error budget
        # (docs/PRECISION.md) — a bf16-storage shape legitimately
        # answers at ~1e-2, a split3 one must stay at the classic
        # 1e-4 coalesced-path bound
        from ..ops.precision import error_budget

        tol = max(1e-4, error_budget(spec.precision))
        if err > tol:
            return (f"response {resp.rid} wrong: rel err {err:.3e} > "
                    f"{tol:.0e} vs numpy {spec.domain} "
                    f"({spec.precision} budget)")
        return None

    inputs = [planes_for(burst) for _ in range(k)]
    mixed = [(s, *planes_for(s)) for s in specs[1:] for _ in range(2)]

    async def main():
        async with Dispatcher(cfg, specs) as d:
            calls = [d.submit(xr, xi, layout=burst.layout,
                              precision=burst.precision,
                              domain=burst.domain, op=burst.op)
                     for xr, xi in inputs]
            calls += [d.submit(xr, xi, layout=s.layout,
                               precision=s.precision, domain=s.domain,
                               op=s.op)
                      for s, xr, xi in mixed]
            responses = await asyncio.gather(*calls)
            return d, responses

    d, responses = asyncio.run(main())

    problems = []
    # every natural-layout response must verify against numpy: a padded
    # coalesced batch that hands back the wrong rows is the one bug a
    # latency report would never catch — and an r2c response must come
    # back half-width, or the domain plan quietly served full-spectrum
    for (xr, xi), resp in zip(inputs, responses[:k]):
        problem = check_response(burst, xr, xi, resp)
        if problem:
            problems.append(problem)
            break
    for (s, xr, xi), resp in zip(mixed, responses[k:]):
        problem = check_response(s, xr, xi, resp)
        if problem:
            problems.append(problem)
            break

    label = GroupKey(n=burst.n, layout=burst.layout,
                     precision=burst.precision,
                     domain=burst.domain, op=burst.op).label()
    reqs = int(metrics.counter_value("pifft_serve_requests_total",
                                     shape=label))
    batches = int(metrics.counter_value("pifft_serve_batches_total",
                                        shape=label))
    if not (0 < batches < k):
        problems.append(
            f"no coalescing: {reqs} concurrent {label} requests were "
            f"served by {batches} kernel invocation(s) (want 0 < "
            f"invocations < {k})")

    bad_events = 0
    snapshot = obs_events.snapshot()
    for rec in snapshot:
        for p in obs_events.validate_event(rec):
            bad_events += 1
            problems.append(f"event seq={rec.get('seq')}: {p}")

    summary = d.stats.summary()
    if owned:
        obs.disable()

    if args.json:
        print(json.dumps({
            "ok": not problems,
            "same_shape_requests": k,
            "same_shape_batches": batches,
            "events": len(snapshot),
            "schema_invalid_events": bad_events,
            "stats": summary,
            "buffers": d.buffer_stats(),
            "problems": problems,
        }, indent=1, sort_keys=True))
    else:
        print(format_summary(summary))
        print(f"# serve smoke: {k} concurrent {label} requests -> "
              f"{batches} kernel invocation(s); "
              f"{len(snapshot)} event(s), {bad_events} schema-invalid; "
              f"buffers {d.buffer_stats()}")
        for p in problems:
            print(f"# FAIL: {p}", file=sys.stderr)
    if problems:
        return 1
    print("# serve smoke ok", file=sys.stderr)
    return 0


def _wire_smoke(cfg: ServeConfig, args) -> int:
    """The ``make wire-smoke`` gate: every claim the wire makes,
    asserted over a REAL socket in one process —

    * both dialects return BYTE-IDENTICAL float32 planes for the same
      request (the JSON dialect's float32-faithful serialization);
    * the binary float32 path's metered ``pifft_host_copy_bytes_total``
      delta is exactly ZERO (the JSON path's is not — the meter works);
    * the shm lane round-trips byte-identically, and streaming
      reassembly returns the same bytes as the inline response;
    * an unknown-version HELLO falls back to the JSON dialect with a
      ``serve_wire_fallback`` event; a malformed header closes the
      connection (``serve_conn_lost``), never hangs;
    * every emitted event validates against the obs schema.
    """
    from .. import obs
    from ..obs import events as obs_events
    from ..obs import metrics
    from . import protocol as proto_mod
    from . import wire

    owned = not obs.enabled()
    if owned:
        obs.enable()
    if args.max_wait_ms is None:
        cfg.max_wait_ms = 2.0

    n_small, n_big = 4096, 1 << 16
    specs = [ShapeSpec(n=n_small), ShapeSpec(n=1024, domain="r2c"),
             ShapeSpec(n=n_big)]
    rng = np.random.default_rng(7)
    problems: list = []
    report: dict = {}

    def hc_total() -> float:
        return sum(v for k, v in
                   metrics.snapshot()["counters"].items()
                   if k.startswith("pifft_host_copy_bytes_total"))

    async def main():
        async with Dispatcher(cfg, specs) as d:
            server = await asyncio.start_server(
                lambda r, w: proto_mod.handle_connection(
                    d, r, w,
                    shm_config={"slots": 8, "slot_bytes": n_big * 8}),
                "127.0.0.1", 0)
            port = server.sockets[0].getsockname()[1]
            try:
                xr = rng.standard_normal(n_small).astype(np.float32)
                xi = rng.standard_normal(n_small).astype(np.float32)
                # pay the compile cost outside any metered window
                await d.submit(xr, xi)

                j0 = hc_total()
                rj = await proto_mod.request_over_socket(
                    "127.0.0.1", port, xr, xi)
                report["json_host_copy_delta"] = hc_total() - j0
                if not rj.get("ok"):
                    problems.append(f"JSON dialect refused the "
                                    f"request: {rj}")
                if report["json_host_copy_delta"] <= 0:
                    problems.append(
                        "the JSON dialect charged no host-copy bytes "
                        "— the meter is dead, so the binary zero "
                        "below would be vacuous")

                c = await wire.WireClient.connect(
                    "127.0.0.1", port, want_shm=True)
                report["dialect"] = c.dialect
                report["credits"] = c.window
                report["shm_granted"] = c.shm is not None
                if c.dialect != "binary":
                    problems.append(f"HELLO v{wire.WIRE_VERSION} was "
                                    f"answered in {c.dialect}")
                b0 = hc_total()
                rb = await c.request(xr, xi)
                report["binary_host_copy_delta"] = hc_total() - b0
                if report["binary_host_copy_delta"] != 0:
                    problems.append(
                        f"binary f32 path charged "
                        f"{report['binary_host_copy_delta']} metered "
                        f"host-copy bytes (want exactly 0)")
                if not rb.get("ok"):
                    problems.append(f"binary dialect refused the "
                                    f"request: {rb}")
                elif rj.get("ok"):
                    jr = np.asarray(rj["yr"], np.float64) \
                        .astype(np.float32)
                    ji = np.asarray(rj["yi"], np.float64) \
                        .astype(np.float32)
                    if jr.tobytes() != rb["yr"].tobytes() \
                            or ji.tobytes() != rb["yi"].tobytes():
                        problems.append(
                            "JSON and binary dialects returned "
                            "DIFFERENT plane bytes for the same "
                            "request")

                # the r2c no-xi path: header flag instead of a plane
                xr2 = rng.standard_normal(1024).astype(np.float32)
                rr_b = await c.request(xr2, None, domain="r2c")
                rr_j = await proto_mod.request_over_socket(
                    "127.0.0.1", port, xr2, np.zeros_like(xr2),
                    domain="r2c")
                if rr_b.get("ok") and rr_j.get("ok"):
                    if np.asarray(rr_j["yr"], np.float64) \
                            .astype(np.float32).tobytes() \
                            != rr_b["yr"].tobytes():
                        problems.append("r2c planes differ between "
                                        "dialects")
                else:
                    problems.append(f"r2c request failed: "
                                    f"binary={rr_b.get('ok')} "
                                    f"json={rr_j.get('ok')}")

                # shm round-trip must equal the inline binary answer
                rs = await c.request(xr, xi, use_shm=True)
                if not rs.get("ok"):
                    problems.append(f"shm request failed: {rs}")
                elif rb.get("ok") and rs["yr"].tobytes() \
                        != rb["yr"].tobytes():
                    problems.append("shm lane returned different "
                                    "plane bytes than the inline "
                                    "binary path")

                # streaming reassembly == inline, byte for byte
                big_r = rng.standard_normal(n_big).astype(np.float32)
                big_i = rng.standard_normal(n_big).astype(np.float32)
                await d.submit(big_r, big_i)   # compile outside timing
                r_inline = await c.request(big_r, big_i)
                r_stream = await c.request(big_r, big_i, stream=True)
                if r_inline.get("ok") and r_stream.get("ok"):
                    if r_inline["yr"].tobytes() \
                            != r_stream["yr"].tobytes():
                        problems.append("streamed response reassembled"
                                        " to different bytes")
                else:
                    problems.append(
                        f"streaming cell failed: inline="
                        f"{r_inline.get('ok')} "
                        f"stream={r_stream.get('ok')}")
                await c.close()

                # negotiation: a future version must land on JSON
                cf = await wire.WireClient.connect(
                    "127.0.0.1", port, version=wire.WIRE_VERSION + 7)
                report["fallback_dialect"] = cf.dialect
                if cf.dialect != "json":
                    problems.append(
                        f"unknown-version HELLO negotiated "
                        f"{cf.dialect!r}, want the JSON fallback")
                await cf.close()

                # malformed header: closed with an event, not a hang
                r0, w0 = await asyncio.open_connection(
                    "127.0.0.1", port)
                w0.write(wire.MAGIC + b"\xff" * 60)
                await w0.drain()
                data = await asyncio.wait_for(r0.read(64), timeout=5.0)
                if data:
                    problems.append("malformed header got a reply "
                                    "instead of a close")
                w0.close()
            finally:
                server.close()
                await server.wait_closed()

    asyncio.run(main())

    snapshot = obs_events.snapshot()
    kinds = [e.get("kind") for e in snapshot]
    if "serve_wire_fallback" not in kinds:
        problems.append("no serve_wire_fallback event for the "
                        "unknown-version HELLO")
    if "serve_conn_lost" not in kinds:
        problems.append("no serve_conn_lost event for the malformed "
                        "header")
    bad_events = 0
    for rec in snapshot:
        for p in obs_events.validate_event(rec):
            bad_events += 1
            problems.append(f"event seq={rec.get('seq')}: {p}")
    report["events"] = len(snapshot)
    report["schema_invalid_events"] = bad_events

    if owned:
        obs.disable()

    if args.json:
        print(json.dumps({"ok": not problems, **report,
                          "problems": problems},
                         indent=1, sort_keys=True))
    else:
        print(f"# wire smoke: dialect={report.get('dialect')} "
              f"credits={report.get('credits')} "
              f"shm={report.get('shm_granted')} "
              f"binary host-copy delta="
              f"{report.get('binary_host_copy_delta')} "
              f"json delta={report.get('json_host_copy_delta')}; "
              f"{report['events']} event(s), "
              f"{bad_events} schema-invalid")
        for p in problems:
            print(f"# FAIL: {p}", file=sys.stderr)
    if problems:
        return 1
    print("# wire smoke ok", file=sys.stderr)
    return 0


def _mesh_smoke(cfg: ServeConfig, specs, args) -> int:
    """The ``make serve-mesh-smoke`` gate (module docstring): run the
    chaos load + journaled drain on a virtual mesh and assert the
    whole acceptance list in-process."""
    import os
    import tempfile

    from .. import obs
    from ..obs import events as obs_events
    from ..obs import metrics
    from ..resilience.journal import load_records
    from .loadgen import (
        _group_for,
        run_mesh_chaos_load,
        verify_response,
    )
    from .mesh import MeshConfig, MeshDispatcher

    owned = not obs.enabled()
    if owned:
        obs.enable()

    mesh_cfg = MeshConfig(**vars(cfg),
                          devices=args.devices or 8)
    if args.max_batch is None:
        mesh_cfg.max_batch = 2   # small buckets: few compiled programs
    if args.max_wait_ms is None:
        mesh_cfg.max_wait_ms = 5.0
    journal_fd, journal_path = tempfile.mkstemp(
        prefix="pifft-mesh-drain-", suffix=".jsonl")
    os.close(journal_fd)
    os.unlink(journal_path)  # the drain creates it; start clean

    problems: list = []
    rng = np.random.default_rng(7)

    async def main():
        async with MeshDispatcher(mesh_cfg, specs) as mesh:
            # --- shape affinity: a warmed group's repeat traffic
            # lands on the SAME device (asserted from the placement
            # counter, not a side channel)
            g0 = _group_for(specs[0])
            home = mesh.router.route(g0, record=False)
            xr = rng.standard_normal(specs[0].n).astype(np.float32)
            xi = rng.standard_normal(specs[0].n).astype(np.float32)
            for _ in range(2):
                resp = await mesh.submit(
                    xr, xi, layout=specs[0].layout,
                    precision=specs[0].precision,
                    domain=specs[0].domain, op=specs[0].op)
                if resp.device != home.id:
                    problems.append(
                        f"affinity broken: warmed {g0.label()} served "
                        f"by {resp.device}, warm home is {home.id}")
            affine = metrics.counter_value(
                "pifft_serve_placement_total", device=home.id,
                reason="affinity")
            if affine < 2:
                problems.append(
                    f"placement counter shows {affine} affinity "
                    f"placements on {home.id}, want >= 2")

            # --- the chaos load with the mid-run device kill
            report = await run_mesh_chaos_load(
                mesh, specs, rps=args.mesh_rps,
                duration_s=args.mesh_duration, kill_at_frac=0.5)
            problems.extend(report["problems"])
            if report["failed"]:
                problems.append(
                    f"{report['failed']} request(s) DROPPED (failed "
                    f"beyond backpressure) — the mesh owes zero")
            if report["killed_device"] is None:
                problems.append("the mid-run kill never armed")
            elif mesh.device(report["killed_device"]).state != "dead":
                problems.append(
                    f"killed device {report['killed_device']} is "
                    f"{mesh.device(report['killed_device']).state}, "
                    f"not dead")
            if report["failover_tagged"] < 1:
                problems.append(
                    "no response carries a failover:* degrade trail — "
                    "the re-route was never exercised")
            if report["p99_pre_kill_ms"] is None \
                    or report["p99_post_kill_ms"] is None:
                problems.append(
                    f"pre/post-kill p99 missing: "
                    f"{report['p99_pre_kill_ms']} / "
                    f"{report['p99_post_kill_ms']}")
            served = [d for d in report["utilization"].values()
                      if d["served"] > 0]
            if len(served) < mesh_cfg.devices - 2:
                problems.append(
                    f"only {len(served)}/{mesh_cfg.devices} devices "
                    f"served traffic — the warm spread did not hold")
            busys = [d["busy_s"] for d in served]
            if busys and max(busys) > MESH_UTIL_SPREAD \
                    * (sum(busys) / len(busys)):
                problems.append(
                    f"utilization spread violated: max busy "
                    f"{max(busys):.4f}s > {MESH_UTIL_SPREAD} x mean "
                    f"{sum(busys) / len(busys):.4f}s")

            # --- planned drain with journaled warm-cache handoff
            victim_id = report["killed_device"]
            drain_dev = next(
                (d for d in mesh.devices
                 if d.state == "healthy" and d.warm_groups), None)
            if drain_dev is None:
                # a structured FAIL, not a bare StopIteration (which
                # asyncio would surface as a RuntimeError): with no
                # healthy warmed survivor there is nothing to drain —
                # itself a gate failure on any mesh bigger than 1
                problems.append(
                    "no healthy warmed device left to drain — the "
                    "kill emptied the mesh")
                return report, {"handoffs": [], "journal": None}, \
                    mesh.utilization(), victim_id
            drain_group = sorted(drain_dev.warm_groups,
                                 key=lambda g: g.label())[0]
            drain_report = await mesh.drain_device(
                drain_dev.id, journal_path=journal_path)
            if not drain_report["handoffs"]:
                problems.append(
                    f"drain of {drain_dev.id} handed off nothing")
            successors = {h["group"]: h["successor"]
                          for h in drain_report["handoffs"]}
            spec = next(s for s in specs
                        if _group_for(s) == drain_group)
            dxr = rng.standard_normal(spec.n).astype(np.float32)
            dxi = np.zeros_like(dxr) if spec.op == "solve" \
                else rng.standard_normal(spec.n).astype(np.float32)
            resp = await mesh.submit(dxr, dxi, layout=spec.layout,
                                     precision=spec.precision,
                                     domain=spec.domain, op=spec.op)
            want = successors.get(drain_group.label())
            if resp.device != want:
                problems.append(
                    f"post-drain {drain_group.label()} served by "
                    f"{resp.device}, handoff successor is {want}")
            if resp.degraded:
                problems.append(
                    f"post-drain response degraded ({resp.degrade}) — "
                    f"a planned drain must not cost quality")
            problem = verify_response(spec.n, spec.layout, spec.domain,
                                      False, spec.precision, dxr, dxi,
                                      resp, op=spec.op)
            if problem:
                problems.append(f"post-drain {problem}")
            return report, drain_report, mesh.utilization(), victim_id

    try:
        report, drain_report, util, _victim = asyncio.run(main())

        # --- the journal must carry the drain (kill-mid-drain resume
        # relies on it): handoff cells plus the completion marker
        records, dropped = load_records(journal_path)
        cells = {r.get("cell", "") for r in records}
        if not any(c.startswith("handoff:") for c in cells):
            problems.append(f"drain journal {journal_path} holds no "
                            f"handoff cells ({sorted(cells)})")
        if not any(c.startswith("drained:") for c in cells):
            problems.append("drain journal lacks the drained: "
                            "completion marker")
        if dropped:
            problems.append(f"drain journal has {dropped} corrupt "
                            f"line(s)")
        # --- consensus ran before the re-route, and every event is
        # schema-valid
        snapshot = obs_events.snapshot()
        consensus = [r for r in snapshot
                     if r.get("kind") == "fallback_consensus"
                     and str(r.get("payload", {}).get("label", ""))
                     .startswith("serve-mesh:")]
        if not consensus:
            problems.append("no serve-mesh fallback_consensus event — "
                            "the failover skipped the PR-8 consensus "
                            "path")
        bad_events = 0
        for rec in snapshot:
            for p in obs_events.validate_event(rec):
                bad_events += 1
                problems.append(f"event seq={rec.get('seq')}: {p}")
    finally:
        # the gate must not leak process-global state or tmp files —
        # even when the run itself blew up: the obs disarm and the
        # journal cleanup cannot depend on a clean pass
        if owned:
            obs.disable()
        try:
            os.unlink(journal_path)
        except OSError:
            pass

    out = {
        "ok": not problems,
        "devices": mesh_cfg.devices,
        "report": {k: v for k, v in report.items()
                   if k != "utilization"},
        "utilization": util,
        "drain": drain_report,
        "journal_cells": sorted(cells),
        "consensus_events": len(consensus),
        "events": len(snapshot),
        "schema_invalid_events": bad_events,
        "problems": problems,
    }
    if args.json:
        print(json.dumps(out, indent=1, sort_keys=True))
    else:
        print(f"# serve mesh smoke: {report['requests']} arrivals at "
              f"{report['offered_rps']} rps over "
              f"{mesh_cfg.devices} devices; "
              f"{report['completed']} completed, "
              f"{report['rejected']} rejected, "
              f"{report['failed']} failed; kill at "
              f"t={report['t_kill_s']}s on {report['killed_device']} "
              f"({report['failover_tagged']} failover-tagged); p99 "
              f"{report['p99_pre_kill_ms']} -> "
              f"{report['p99_post_kill_ms']} ms; drain handed "
              f"{len(drain_report['handoffs'])} group(s) "
              f"(journal {drain_report['journal']})")
        for p in problems:
            print(f"# FAIL: {p}", file=sys.stderr)
    if problems:
        return 1
    print("# serve mesh smoke ok", file=sys.stderr)
    return 0
