"""The asyncio dispatcher: the serving front door.

Concurrent callers ``await dispatcher.submit(xr, xi)``; the dispatcher
groups compatible requests (same :class:`~.batcher.GroupKey`) into
bounded per-group queues, and one worker task per group drains them
into coalesced, padded kernel invocations through
:class:`~.batcher.BatchRunner`.  The contract, in order of what a
production front door owes its callers:

* **Backpressure, never unbounded queues.**  Each group's queue is
  bounded (``queue_depth``); an admission past the bound raises
  :class:`QueueFull` — a structured error carrying ``retry_after_ms``
  (an EMA of this group's per-request service time times the depth
  ahead) — immediately.  A saturated server answers "try later",
  it never silently grows a queue or hangs a caller.

* **Coalescing window.**  A worker that finds its queue non-empty
  drains up to ``max_batch`` requests with no wait at all; otherwise
  it holds the batch open for ``max_wait_ms`` (the classic
  latency-for-throughput window).  All serve-side waiting funnels
  through ONE sanctioned helper (:meth:`Dispatcher._wait_for_request`,
  built on ``asyncio.wait_for``) — check rule PIF107 bans blocking
  ``time.sleep``/sync I/O anywhere in serve/ async paths.

* **Admission-time graceful degradation.**  Queue fill decides the
  mode: past ``pressure_watermark`` the batching window collapses to
  zero (ship what's here — ``pressure:window``); past
  ``overload_watermark`` the batch skips the tuned kernel for the
  cheap ``jnp-fft`` rung (``overload:jnp-fft``).  Every demotion —
  these, and the fault-driven rungs inside the runner — is tagged on
  each affected response (``degraded: true`` + the ``degrade`` trail)
  and mirrored into the event stream, the resilience subsystem's
  never-silent rule (docs/RESILIENCE.md).

* **Per-request observability.**  Every response carries its
  queue-wait vs compute split; the same numbers land in
  ``pifft_serve_*`` metrics, ``serve_request`` events, and the
  per-shape :class:`~.slo.LatencyStats` the SLO reports roll up
  (docs/SERVING.md).

Compute runs in a thread-pool executor so the event loop keeps
admitting (and rejecting) requests mid-kernel — which is what makes
backpressure testable and the p99 honest.
"""

from __future__ import annotations

import asyncio
import dataclasses
import functools
import itertools
from typing import Optional

import numpy as np

from ..obs import events, metrics
from ..obs import trace as trace_mod
from ..obs.spans import clock
from ..resilience import classify
from ..utils.roofline import SPECTRAL_OPS as OPS
from . import shapes as shapes_mod
from .batcher import BatchRunner, GroupKey
from .buffers import BufferPool
from .slo import LatencyStats

#: worker-queue shutdown sentinel
_CLOSE = object()

#: admission priority classes, weakest first.  Priorities layer on the
#: bounded-queue backpressure (docs/SERVING.md): each class may fill
#: its group's queue only up to its ceiling fraction of
#: ``queue_depth``, so LOW-priority load sheds FIRST as pressure
#: builds while high-priority traffic keeps its full headroom; and a
#: rejection's ``retry_after_ms`` is scaled per class, so shed
#: low-priority clients back off harder than the high-priority ones
#: the server wants back soonest.
PRIORITIES = ("low", "normal", "high")
PRIORITY_ADMIT_FILL = {"low": 0.5, "normal": 1.0, "high": 1.0}
PRIORITY_RETRY_SCALE = {"low": 4.0, "normal": 1.0, "high": 0.5}


class ServeError(Exception):
    """Base of the structured serving errors: everything a caller (or
    the wire protocol) needs rides :meth:`to_record`, never a bare
    message to parse."""

    code = "serve_error"

    def extras(self) -> dict:
        return {}

    def to_record(self) -> dict:
        return {"type": self.code, "message": str(self), **self.extras()}


class QueueFull(ServeError):
    """Admission rejected: the group's queue is at depth.  Structured
    backpressure — carries when to come back, never hangs."""

    code = "queue_full"

    def __init__(self, msg: str, retry_after_ms: float):
        super().__init__(msg)
        self.retry_after_ms = retry_after_ms

    def extras(self) -> dict:
        return {"retry_after_ms": self.retry_after_ms}


class ShapeNotServed(ServeError):
    """Strict-shape mode: the request's shape is not in the warmed
    set."""

    code = "shape_not_served"


class DispatcherClosed(ServeError):
    code = "dispatcher_closed"


class RequestFailed(ServeError):
    """The batch died of a fault no fallback rung could absorb; the
    classification rides along so the caller's retry policy can
    decide."""

    code = "request_failed"

    def __init__(self, msg: str, kind: str):
        super().__init__(msg)
        self.kind = kind

    def extras(self) -> dict:
        return {"kind": self.kind}


@dataclasses.dataclass
class ServeConfig:
    """Dispatcher knobs (docs/SERVING.md discusses the trade-offs)."""

    max_batch: int = 8           # most requests one invocation carries
    max_wait_ms: float = 2.0     # batching window under normal load
    queue_depth: int = 64        # per-group bound; beyond it: QueueFull
    pressure_watermark: float = 0.5   # fill fraction: window -> 0
    overload_watermark: float = 0.875  # fill fraction: cheap-rung mode
    strict_shapes: bool = False  # only serve the warmed shape set
    #: burn-rate SLO objectives (docs/OBSERVABILITY.md, "The live
    #: plane"): a config-file path for obs.slomon.load_objectives, a
    #: ready list of Objective records, or a built SloMonitor — when
    #: set, sustained error-budget burn forces the admission ladder
    #: (window collapse -> jnp rung) BEFORE the queues saturate,
    #: tagged slo:<level> like every demotion
    slo_objectives: object = None


@dataclasses.dataclass
class Request:
    rid: int
    group: GroupKey
    xr: np.ndarray
    xi: np.ndarray
    t_submit: float
    future: asyncio.Future
    #: admission class (PRIORITIES) and tenant identity — recorded on
    #: every request; the mesh dispatcher's admission acts on them
    priority: str = "normal"
    tenant: str = "default"
    #: per-REQUEST degradation trail (e.g. ``failover:<device>`` when a
    #: mesh re-routes it off a dead device) — merged into the response's
    #: degrade trail on delivery, on top of whatever the batch earned
    trail: list = dataclasses.field(default_factory=list)
    #: trace-plane identity (obs/trace.py): minted at submit or adopted
    #: from the wire; NOOP_TRACE when observability is off
    trace: trace_mod.TraceContext = trace_mod.NOOP_TRACE
    #: stamped by the worker when it pops the request — splits the SLO
    #: row's queue_wait into queue (submit->dequeue) vs window
    #: (dequeue->execution) children in the span tree
    t_dequeue: Optional[float] = None
    #: instant trace marks ((name, t) pairs): failover/handoff re-route
    #: hops land here and become children of the request span tree
    marks: list = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class Response:
    """One served transform, with its latency split and degradation
    trail."""

    rid: int
    yr: np.ndarray
    yi: np.ndarray
    queue_wait_ms: float
    compute_ms: float
    batch_size: int
    plan_variant: str
    degraded: bool = False
    degrade: list = dataclasses.field(default_factory=list)
    #: which mesh device served the batch (None on the single-device
    #: dispatcher — docs/SERVING.md, mesh section)
    device: Optional[str] = None
    #: the request's trace (obs/trace.py): ids always when tracing is
    #: armed, the span tree (queue/window/compute + degrade/failover
    #: children) when the trace was sampled or tail-upgraded —
    #: travels the wire so the CALLER holds its own attribution
    trace: Optional[dict] = None

    def to_record(self, arrays: bool = False) -> dict:
        rec = {
            "id": self.rid, "ok": True,
            "queue_wait_ms": round(self.queue_wait_ms, 4),
            "compute_ms": round(self.compute_ms, 4),
            "batch_size": self.batch_size,
            "plan_variant": self.plan_variant,
            "degraded": self.degraded,
        }
        if self.degrade:
            rec["degrade"] = list(self.degrade)
        if self.device is not None:
            rec["device"] = self.device
        if self.trace is not None:
            rec["trace"] = self.trace
        if arrays:
            # float32-faithful serialization: squeeze through float32
            # FIRST, then widen to float64 for repr — json emits the
            # shortest decimal that round-trips the f64, and an f64
            # holding an exact f32 value recovers that f32 BIT-
            # IDENTICALLY on decode.  Both wire dialects therefore
            # deliver the same plane bytes (tests/test_wire.py).
            rec["yr"] = np.asarray(self.yr, np.float32) \
                .astype(np.float64).tolist()
            rec["yi"] = np.asarray(self.yi, np.float32) \
                .astype(np.float64).tolist()
        return rec


class Dispatcher:
    """See the module docstring; use as an async context manager:

        async with Dispatcher(config, specs) as d:
            resp = await d.submit(xr, xi)
    """

    def __init__(self, config: Optional[ServeConfig] = None,
                 shape_specs=None):
        self.config = config or ServeConfig()
        self.specs = list(shape_specs or [])
        self.runner = BatchRunner(BufferPool())
        self.stats = LatencyStats()
        self._queues: dict = {}
        self._workers: dict = {}
        self._ema_ms: dict = {}
        self._rid = itertools.count()
        self._closing = False
        self._served = {(s.n, s.layout, s.precision, s.domain,
                         getattr(s, "op", "fft"))
                        for s in self.specs}
        self.slomon = self._build_slomon(self.config.slo_objectives)

    @staticmethod
    def _build_slomon(spec):
        """The burn-rate monitor from a config-file path, a list of
        Objective records, or a ready SloMonitor (None disables —
        no per-batch evaluation cost)."""
        if spec is None:
            return None
        from ..obs import slomon as slomon_mod

        if isinstance(spec, slomon_mod.SloMonitor):
            return spec
        if isinstance(spec, str):
            objectives, windows = slomon_mod.load_objectives(spec)
            mon = slomon_mod.SloMonitor(objectives, windows)
            # a file-backed monitor hot-reloads on mtime change, so SLO
            # targets tighten in production without a restart
            # (docs/OBSERVABILITY.md)
            mon.watch(spec)
            return mon
        return slomon_mod.SloMonitor(list(spec))

    # ----------------------------------------------------- lifecycle

    def warm(self, force: bool = False) -> list:
        """Resolve + memoize the plan for every served shape (the
        ``pifft plan warm --shapes`` path) — a warm dispatcher reaches
        its first response on a cache hit."""
        return shapes_mod.warm(self.specs, force=force)

    async def __aenter__(self):
        if self.specs:
            # warming may tune (minutes on real hardware): keep the
            # event loop free while it runs
            await asyncio.get_running_loop().run_in_executor(
                None, self.warm)
        return self

    async def __aexit__(self, *exc):
        await self.close()
        return False

    async def close(self) -> None:
        """Stop accepting, drain every queue, join the workers.
        Requests admitted before close are served (the workers keep
        draining past the shutdown sentinel until their queues are
        empty); later submits raise :class:`DispatcherClosed`.  Any
        request a racing submit still managed to slip behind an
        exiting worker gets a structured :class:`DispatcherClosed`
        rejection — a shutdown must never orphan a future."""
        self._closing = True
        for q in self._queues.values():
            q.put_nowait(_CLOSE)
        if self._workers:
            await asyncio.gather(*self._workers.values(),
                                 return_exceptions=True)
        for q in self._queues.values():
            while True:
                try:
                    item = q.get_nowait()
                except asyncio.QueueEmpty:
                    break
                if item is _CLOSE or item.future.done():
                    continue
                item.future.set_exception(DispatcherClosed(
                    "dispatcher shut down while the request was queued"))

    async def drain(self) -> None:
        """Alias for :meth:`close`: serve everything admitted, then
        stop (the name the ops runbooks use)."""
        await self.close()

    # ----------------------------------------------------- admission

    def _validated(self, xr, xi, layout: str, precision: Optional[str],
                   inverse: bool, domain: str, priority: str,
                   op: str = "fft") -> tuple:
        """Shared request validation (single-device and mesh
        dispatchers): returns ``(xr, xi, group)`` float32 planes plus
        the coalescing key, or raises a structured
        :class:`ServeError`.

        Op-tagged requests (``op`` in "conv"/"corr"/"solve" —
        docs/APPS.md) are REAL-input operations on the half-spectrum
        path: the planes are the op's operands (signal + kernel for
        conv/corr, the field for solve), the group is keyed
        ``domain="r2c"``, and the served semantics are CIRCULAR at
        the group's n (linear semantics pad client-side or through
        apps.fftconv)."""
        from ..plans.core import DOMAINS

        if op not in OPS:
            raise ServeError(f"op={op!r} not in {OPS} (docs/APPS.md)")
        if domain not in DOMAINS:
            raise ServeError(f"domain={domain!r} not in {DOMAINS}")
        if priority not in PRIORITIES:
            raise ServeError(f"priority={priority!r} not in {PRIORITIES}")
        xr = np.asarray(xr, np.float32)
        if op != "fft":
            if inverse:
                raise ServeError(f"op={op!r} has no inverse form; the "
                                 f"op already pairs its transforms")
            if layout != "natural":
                raise ServeError(f"op={op!r} requires natural layout "
                                 f"(the half-spectrum has no pi order)")
            if domain not in ("c2c", "r2c"):
                raise ServeError(f"op={op!r} rides the half-spectrum "
                                 f"forward path; domain={domain!r} "
                                 f"does not apply")
            if op in ("conv", "corr"):
                if xi is None:
                    raise ServeError(f"op={op!r} needs the kernel "
                                     f"plane in xi (signal in xr)")
            elif xi is not None and np.any(np.asarray(xi)):
                raise ServeError("op='solve' takes one real field in "
                                 "xr — a nonzero xi would be silently "
                                 "dropped; send zeros or omit it")
            xi = np.zeros_like(xr) if xi is None \
                else np.asarray(xi, np.float32)
            if xr.ndim != 1 or xr.shape != xi.shape:
                raise ServeError(f"request planes must be matching 1-D "
                                 f"arrays, got {xr.shape} / {xi.shape}")
            n = xr.shape[0]
            if n < 2 or n > shapes_mod.MAX_SERVED_N:
                # ANY length in range is a plan (docs/PLANS.md
                # "Arbitrary n") — refusal is for degenerate or
                # memory-unbounded requests only
                raise ServeError(f"n={n} must be 2 <= n <= "
                                 f"{shapes_mod.MAX_SERVED_N}")
            group = GroupKey(n=n, layout=layout,
                             precision=precision or "split3",
                             inverse=False, domain="r2c", op=op)
            return xr, xi, group
        if xi is None:
            if domain != "r2c":
                raise ServeError(f"domain={domain!r} requests need both "
                                 f"planes; only r2c input is real by "
                                 f"declaration")
            xi = np.zeros_like(xr)
        xi = np.asarray(xi, np.float32)
        if xr.ndim != 1 or xr.shape != xi.shape:
            raise ServeError(f"request planes must be matching 1-D "
                             f"arrays, got {xr.shape} / {xi.shape}")
        if domain == "c2r":
            # the planes carry half-spectrum bins; the group is keyed
            # by the real-side length they decode to
            n = 2 * (xr.shape[0] - 1)
        else:
            n = xr.shape[0]
        if n < 2 or n > shapes_mod.MAX_SERVED_N:
            # any length in range is a plan (docs/PLANS.md "Arbitrary
            # n"); note a c2r request's n is DECODED as 2*(bins-1), so
            # the wire expresses even real lengths only
            raise ServeError(f"n={n} must be 2 <= n <= "
                             f"{shapes_mod.MAX_SERVED_N}"
                             + (" (c2r planes carry n//2+1 bins)"
                                if domain == "c2r" else ""))
        if layout == "pi" and n & (n - 1):
            raise ServeError(f"layout='pi' requires a power-of-two n "
                             f"(bit-reversed order is undefined "
                             f"otherwise), got n={n}")
        if inverse and layout != "natural":
            raise ServeError("inverse requires natural layout (the "
                             "conj-trick contract, plans.core)")
        if domain != "c2c":
            if inverse:
                raise ServeError("inverse is the c2c conj trick; use "
                                 "domain='c2r' for the real inverse")
            if layout != "natural":
                raise ServeError(f"domain={domain!r} requires natural "
                                 f"layout (the half-spectrum has no pi "
                                 f"order)")
            if domain == "r2c" and np.any(xi):
                raise ServeError("r2c request carries a nonzero "
                                 "imaginary plane — the half-spectrum "
                                 "path would silently drop it; send "
                                 "zeros (or omit xi), or use c2c")
        group = GroupKey(n=n, layout=layout,
                         precision=precision or "split3",
                         inverse=inverse, domain=domain)
        return xr, xi, group

    def _check_served(self, group: GroupKey) -> None:
        """Strict-shape refusal (shared with the mesh dispatcher)."""
        if self.config.strict_shapes and \
                (group.n, group.layout, group.precision,
                 group.domain, group.op) not in self._served:
            raise ShapeNotServed(
                f"shape {group.label()} is not in the warmed set "
                f"({len(self.specs)} shape(s)); add it to the shape "
                f"file or serve without strict_shapes")

    def _admit(self, group: GroupKey, q, priority: str) -> None:
        """Class-aware bounded admission: each priority class may fill
        the group's queue only to its ceiling (PRIORITY_ADMIT_FILL ×
        ``queue_depth``), so low-priority load sheds first under
        pressure, with its ``retry_after_ms`` scaled to back off
        harder.  Raises :class:`QueueFull`; never waits."""
        cap = max(1, int(self.config.queue_depth
                         * PRIORITY_ADMIT_FILL[priority]))
        if q.qsize() < cap:
            return
        label = group.label()
        self.stats.record_rejected(label)
        metrics.inc("pifft_serve_rejected_total", shape=label)
        if cap < self.config.queue_depth:
            # shed below the hard bound: the class ceiling did it
            metrics.inc("pifft_serve_shed_total", priority=priority)
        retry_ms = self._retry_after_ms(group, q, priority)
        events.emit("serve_reject", cell={"n": group.n}, shape=label,
                    depth=q.qsize(), retry_after_ms=retry_ms,
                    priority=priority)
        raise QueueFull(
            f"queue for {label} is at the {priority}-class depth "
            f"{cap}/{self.config.queue_depth}; retry in ~{retry_ms} ms",
            retry_after_ms=retry_ms)

    async def submit(self, xr, xi=None, layout: str = "natural",
                     precision: Optional[str] = None,
                     inverse: bool = False,
                     domain: str = "c2c",
                     priority: str = "normal",
                     tenant: str = "default",
                     op: str = "fft",
                     trace=None,
                     t_recv: Optional[float] = None) -> Response:
        """Serve one n-point transform of float planes ``(n,)``.
        Raises a :class:`ServeError` subclass — never hangs — when the
        request cannot be admitted or no rung could serve it.

        `domain` picks the transform family (docs/REAL.md): "c2c"
        (default — both planes required), "r2c" (real forward: `xr` is
        the length-n real signal, `xi` may be omitted and must
        otherwise be zeros — a nonzero imaginary plane on a
        declared-real request would be silently dropped, which is a
        wrong answer, so it is refused instead), or "c2r" (the
        inverse: the planes carry the n//2+1 half-spectrum bins and
        the response is the length-n real signal).

        `op` picks the served OPERATION (docs/APPS.md): "fft" (the
        bare transform, default), or the fused spectral ops "conv" /
        "corr" (`xr` = the real signal, `xi` = the real kernel,
        CIRCULAR semantics at n) and "solve" (`xr` = the real field;
        the 1-D periodic Poisson solve).  Op requests coalesce per
        (op, shape, domain, precision) into one batched fused
        pipeline invocation.

        `priority` is the admission class (PRIORITIES): low-priority
        load sheds first under pressure with a harder retry backoff.
        `tenant` names the quota bucket; the mesh dispatcher enforces
        per-tenant quotas on it (docs/SERVING.md).

        `trace` continues a caller's trace (a wire ``trace`` field or
        an in-process :class:`~..obs.trace.TraceContext`); omitted, a
        fresh trace is MINTED here — obs/trace.py, the no-op
        singleton when observability is off.

        `t_recv` is the wire front's arrival stamp (the clock when the
        request's bytes finished arriving, BEFORE any decode): when
        given, it becomes the submit time, so frame decode cost lands
        in the request's queue phase and tail attribution sees the
        front door (docs/ANALYSIS.md)."""
        if self._closing:
            raise DispatcherClosed("dispatcher is shut down")
        xr, xi, group = self._validated(xr, xi, layout, precision,
                                        inverse, domain, priority, op)
        self._check_served(group)
        ctx = trace_mod.ensure(trace)
        t_submit = t_recv if t_recv is not None else clock()
        q = self._ensure_worker(group)
        try:
            self._admit(group, q, priority)
        except QueueFull:
            # shed requests are in the tracing tail-upgrade class:
            # the rejection leaves a (always-emitted) root span
            trace_mod.shed_record(ctx, label=group.label(),
                                  t_submit=t_submit,
                                  reason="queue_full",
                                  priority=priority)
            raise
        req = Request(rid=next(self._rid), group=group, xr=xr, xi=xi,
                      t_submit=t_submit,
                      future=asyncio.get_running_loop().create_future(),
                      priority=priority, tenant=tenant, trace=ctx)
        metrics.inc("pifft_serve_requests_total", shape=group.label())
        q.put_nowait(req)
        return await req.future

    def _ensure_worker(self, group: GroupKey) -> asyncio.Queue:
        q = self._queues.get(group)
        if q is None:
            # unbounded Queue; the depth bound is enforced at admission
            # so rejection is synchronous (and the shutdown sentinel
            # can always be delivered)
            q = self._queues[group] = asyncio.Queue()
            self._workers[group] = asyncio.get_running_loop() \
                .create_task(self._worker(group, q))
        return q

    def _retry_after_ms(self, group: GroupKey, q,
                        priority: str = "normal") -> float:
        ema = self._ema_ms.get(group, self.config.max_wait_ms)
        scale = PRIORITY_RETRY_SCALE.get(priority, 1.0)
        return round(max(1.0, ema * (q.qsize() + 1) * scale), 3)

    def buffer_stats(self) -> dict:
        """Staging-pool reuse stats (the wire ``stats`` op; the mesh
        dispatcher aggregates its per-device pools here)."""
        return self.runner.pool.stats()

    def _admission(self, group: GroupKey, q) -> tuple:
        """(window_s, forced_rung, level_tag) for the batch about to be
        drained — the admission-time degradation ladder.  Two signals
        feed it: queue FILL (the classic saturation ladder) and the
        burn-rate SLO monitor (obs/slomon.py) — a sustained
        error-budget burn forces the same rungs BEFORE the queues
        fill, tagged ``slo:*`` so the trigger is never ambiguous in
        the trail (queue fill wins the name when both fire)."""
        fill = q.qsize() / self.config.queue_depth
        slo = self.slomon.forced_level() if self.slomon is not None \
            else None
        if fill >= self.config.overload_watermark:
            return 0.0, "jnp-fft", "overload:jnp-fft"
        if slo == "jnp-fft":
            return 0.0, "jnp-fft", "slo:jnp-fft"
        if fill >= self.config.pressure_watermark:
            return 0.0, None, "pressure:window"
        if slo == "window":
            return 0.0, None, "slo:window"
        return self.config.max_wait_ms / 1e3, None, None

    # ------------------------------------------------------- workers

    async def _wait_for_request(self, q, timeout_s: float):
        """THE sanctioned serve-side wait (check rule PIF107): every
        hold in serve/ async code funnels through this one
        asyncio.wait_for — never ``time.sleep``, never sync I/O —
        returning None when the window closes empty."""
        try:
            return await asyncio.wait_for(q.get(), timeout=timeout_s)
        except asyncio.TimeoutError:
            return None

    async def _worker(self, group: GroupKey, q, device=None) -> None:
        closing = False
        while True:
            if closing:
                # past the shutdown sentinel: serve what is already
                # queued (admitted before close), then exit — a
                # request behind the sentinel must complete, never
                # orphan its future
                try:
                    req = q.get_nowait()
                except asyncio.QueueEmpty:
                    break
            else:
                req = await q.get()
            if req is _CLOSE:
                closing = True
                continue
            req.t_dequeue = clock()
            batch = [req]
            window_s, rung, level = self._admission(group, q)
            if closing:
                window_s = 0.0  # shutting down: ship what's here
            deadline = clock() + window_s
            while len(batch) < self.config.max_batch:
                try:
                    nxt = q.get_nowait()
                except asyncio.QueueEmpty:
                    remaining = deadline - clock()
                    if remaining <= 0 or closing:
                        break
                    nxt = await self._wait_for_request(q, remaining)
                    if nxt is None:
                        break
                if nxt is _CLOSE:
                    closing = True
                    continue  # keep collecting what is already queued
                nxt.t_dequeue = clock()
                batch.append(nxt)
            if level is not None:
                metrics.inc("pifft_serve_admission_degrade_total",
                            level=level)
                events.emit("serve_degrade", cell={"n": group.n},
                            shape=group.label(), level=level,
                            depth=q.qsize())
            await self._run_batch(group, batch, rung, level, device)
            # drop the served batch's refs BEFORE parking on the queue
            # again: request planes may be zero-copy views over a
            # client's shm slot ring (serve/shm.py), and a suspended
            # frame still binding them would pin a closed connection's
            # segment mapping open
            req = nxt = batch = None

    def _is_device_failure(self, exc: Exception) -> bool:
        """Hook: exceptions the batch path must NOT absorb into
        per-request failures because they indict the DEVICE, not the
        batch (the mesh dispatcher overrides — docs/SERVING.md,
        failover)."""
        return False

    @staticmethod
    def _batch_links(batch) -> Optional[list]:
        """The fan-in edge: the live request span ids this batch
        serves — recorded on the ONE serve_batch span so Perfetto can
        draw request→batch arrows (obs/trace.py)."""
        links = [r.trace.span_id for r in batch
                 if r.trace.live and r.trace.sampled]
        return links or None

    async def _invoke_batch(self, group: GroupKey, batch, rung,
                            device=None, level=None):
        """One coalesced kernel invocation in the executor (the event
        loop keeps admitting mid-kernel)."""
        return await asyncio.get_running_loop().run_in_executor(
            None,
            functools.partial(self.runner.run, group,
                              [(r.xr, r.xi) for r in batch], rung,
                              rung_tag=level,
                              links=self._batch_links(batch)))

    async def _run_batch(self, group: GroupKey, batch, rung, level,
                         device=None):
        label = group.label()
        t_start = clock()
        try:
            outcome = await self._invoke_batch(group, batch, rung,
                                               device, level)
        except Exception as e:
            if self._is_device_failure(e):
                raise  # the mesh's failover path owns these
            kind = classify(e).value
            events.emit("serve_error", cell={"n": group.n}, shape=label,
                        kind=kind, size=len(batch),
                        error=f"{type(e).__name__}: {str(e)[:200]}")
            metrics.inc("pifft_serve_errors_total", kind=kind)
            for r in batch:
                if not r.future.done():
                    r.future.set_exception(RequestFailed(
                        f"serve batch {label} failed beyond every rung "
                        f"({kind} {type(e).__name__}: {str(e)[:200]})",
                        kind=kind))
            return
        self._deliver(group, batch, outcome, t_start, rung, level,
                      device)

    def _deliver(self, group: GroupKey, batch, outcome, t_start, rung,
                 level, device=None):
        """Build and resolve the per-request responses for one served
        batch (shared by the single-device and mesh dispatchers)."""
        label = group.label()
        self.stats.record_batch(label)
        # EMA of per-request service time feeds QueueFull.retry_after
        batch_ms = (clock() - t_start) * 1e3 / len(batch)
        prev = self._ema_ms.get(group)
        self._ema_ms[group] = batch_ms if prev is None \
            else 0.7 * prev + 0.3 * batch_ms
        # a forced rung's tag is already in outcome.degrade
        # ("overload:<rung>", from the runner) — only the window-collapse
        # level needs adding here
        tags = ([level] if level and rung is None else []) \
            + list(outcome.degrade)
        device_id = getattr(device, "id", None)
        t_done = clock()
        for i, r in enumerate(batch):
            # the batch tags plus this request's OWN trail (failover
            # re-routes tag the request, not the batch it lands in)
            rtags = list(r.trail) + list(tags)
            degraded = outcome.degraded or bool(rtags)
            queue_s = t_start - r.t_submit
            resp = Response(
                rid=r.rid, yr=outcome.yr[i], yi=outcome.yi[i],
                queue_wait_ms=queue_s * 1e3,
                compute_ms=outcome.compute_s * 1e3,
                batch_size=outcome.size,
                plan_variant=outcome.plan_variant,
                degraded=degraded, degrade=rtags, device=device_id)
            if r.trace.live:
                # the request's span tree (obs/trace.py): queue/window/
                # compute children summing exactly to this row's
                # total, degrade tags and re-route hops as instants —
                # emitted when head-sampled, ALWAYS when degraded (the
                # tail upgrade), and returned on the response either
                # way so the caller keeps the correlation ids
                recs = trace_mod.request_span_records(
                    r.trace, label=label, rid=r.rid,
                    t_submit=r.t_submit, t_dequeue=r.t_dequeue,
                    t_exec=t_start, compute_s=outcome.compute_s,
                    t_done=t_done, tags=rtags, marks=r.marks,
                    device=device_id, cell={"n": group.n})
                emitted = trace_mod.emit_request_trace(
                    r.trace, recs, forced=degraded)
                resp.trace = trace_mod.wire_tree(r.trace, recs, emitted)
            self.stats.record(label, queue_s, outcome.compute_s,
                              degraded=degraded, device=device_id)
            metrics.observe("pifft_serve_queue_wait_seconds", queue_s,
                            shape=label)
            if degraded:
                metrics.inc("pifft_serve_degraded_total", shape=label)
            events.emit("serve_request", cell={"n": group.n},
                        rid=r.rid, shape=label,
                        queue_wait_ms=round(queue_s * 1e3, 4),
                        compute_ms=round(outcome.compute_s * 1e3, 4),
                        batch_size=outcome.size, degraded=degraded,
                        **({"degrade": rtags} if rtags else {}),
                        **({"device": device_id} if device_id else {}),
                        **({"trace": r.trace.trace_id}
                           if r.trace.live else {}))
            if self.slomon is not None:
                # the burn monitor judges the FULL server residence
                # time (submit -> delivery): staging, retries and
                # injected stalls all count — the latency the caller
                # actually experienced, not just the split the row
                # itemizes
                self.slomon.observe(
                    group.op, label, (t_done - r.t_submit) * 1e3,
                    t=t_done)
            if not r.future.done():
                r.future.set_result(resp)
        if self.slomon is not None:
            # one evaluation per delivered batch: the burn gauges stay
            # live and the forced level the NEXT admission reads is
            # current — recovery is as automatic as the alert
            self.slomon.evaluate(t=t_done)
        metrics.observe("pifft_serve_compute_seconds", outcome.compute_s,
                        shape=label)
