"""The wire front: length-prefixed JSON frames over a TCP socket.

One frame = a 4-byte big-endian length + a UTF-8 JSON body.  Requests:

    {"op": "fft", "id": 7, "xr": [...], "xi": [...],
     "layout": "natural", "precision": "split3", "inverse": false,
     "domain": "c2c"}
    {"op": "stats"}
    {"op": "ping"}

``domain`` is optional (default "c2c"); ``"r2c"`` requests may omit
``xi`` entirely — the input is real by declaration (docs/REAL.md).

Responses mirror :meth:`~.dispatcher.Response.to_record` (with the
result planes as ``yr``/``yi`` float lists) on success, or

    {"id": 7, "ok": false, "error": {"type": "queue_full",
     "message": "...", "retry_after_ms": 12.5}}

on a structured :class:`~.dispatcher.ServeError` — backpressure and
degradation travel the wire, they are never flattened into a generic
500.  The server is asyncio end to end (``asyncio.start_server``
streams; all awaited — check rule PIF107 keeps blocking socket I/O out
of these paths), with one dispatcher shared by every connection: the
coalescer sees ALL concurrent clients, which is the whole point.

JSON float lists are a deliberately simple encoding — this front is
the protocol seam, not a throughput record; a binary frame body can
replace the JSON without touching the dispatcher.
"""

from __future__ import annotations

import asyncio
import json
import struct
from typing import Optional

import numpy as np

from .dispatcher import Dispatcher, ServeError

#: frame length prefix: 4-byte big-endian unsigned
_LEN = struct.Struct(">I")

#: refuse absurd frames before allocating for them (a 2^27-point
#: request in JSON floats is ~2 GiB of text; cap generously above any
#: sane served shape)
MAX_FRAME_BYTES = 1 << 28


def encode_frame(obj) -> bytes:
    body = json.dumps(obj, separators=(",", ":")).encode("utf-8")
    if len(body) > MAX_FRAME_BYTES:
        raise ValueError(f"frame body {len(body)} bytes exceeds the "
                         f"{MAX_FRAME_BYTES}-byte cap")
    return _LEN.pack(len(body)) + body


async def read_frame(reader) -> Optional[dict]:
    """The next decoded frame, or None on clean EOF."""
    try:
        head = await reader.readexactly(_LEN.size)
    except asyncio.IncompleteReadError as e:
        if not e.partial:
            return None  # clean EOF between frames
        raise ValueError(f"truncated frame header "
                         f"({len(e.partial)}/{_LEN.size} bytes)") from e
    (length,) = _LEN.unpack(head)
    if length > MAX_FRAME_BYTES:
        raise ValueError(f"frame length {length} exceeds the "
                         f"{MAX_FRAME_BYTES}-byte cap")
    body = await reader.readexactly(length)
    return json.loads(body.decode("utf-8"))


async def _handle_one(dispatcher: Dispatcher, msg: dict) -> dict:
    rid = msg.get("id")
    op = msg.get("op")
    if op == "ping":
        return {"id": rid, "ok": True, "pong": True}
    if op == "stats":
        return {"id": rid, "ok": True,
                "stats": dispatcher.stats.summary(),
                "buffers": dispatcher.runner.pool.stats()}
    if op != "fft":
        return {"id": rid, "ok": False,
                "error": {"type": "bad_request",
                          "message": f"unknown op {op!r}"}}
    try:
        xi = msg.get("xi")
        resp = await dispatcher.submit(
            np.asarray(msg.get("xr", ()), np.float32),
            np.asarray(xi, np.float32) if xi is not None else None,
            layout=msg.get("layout", "natural"),
            precision=msg.get("precision"),
            inverse=bool(msg.get("inverse", False)),
            domain=msg.get("domain", "c2c"))
    except ServeError as e:
        return {"id": rid, "ok": False, "error": e.to_record()}
    rec = resp.to_record(arrays=True)
    rec["id"] = rid if rid is not None else rec["id"]
    return rec


async def handle_connection(dispatcher: Dispatcher, reader,
                            writer) -> None:
    """One client connection: frames in, frames out, until EOF.
    Requests on one connection are served CONCURRENTLY (a queue-full
    rejection must not wait behind a coalescing window), with writes
    serialized through a lock."""
    write_lock = asyncio.Lock()
    pending = set()

    async def serve_one(msg):
        try:
            reply = await _handle_one(dispatcher, msg)
        except Exception as e:  # a reply is owed even for the unforeseen
            from ..resilience import classify

            reply = {"id": msg.get("id"), "ok": False,
                     "error": {"type": "internal",
                               "kind": classify(e).value,
                               "message":
                                   f"{type(e).__name__}: {str(e)[:200]}"}}
        async with write_lock:
            writer.write(encode_frame(reply))
            await writer.drain()

    try:
        while True:
            try:
                msg = await read_frame(reader)
            except (ValueError, json.JSONDecodeError) as e:
                async with write_lock:
                    writer.write(encode_frame(
                        {"ok": False,
                         "error": {"type": "bad_frame",
                                   "message": str(e)[:200]}}))
                    await writer.drain()
                break  # framing is lost; the connection cannot recover
            if msg is None:
                break
            task = asyncio.ensure_future(serve_one(msg))
            pending.add(task)
            task.add_done_callback(pending.discard)
        if pending:
            await asyncio.gather(*pending, return_exceptions=True)
    finally:
        writer.close()


async def serve_socket(dispatcher: Dispatcher, host: str = "127.0.0.1",
                       port: int = 8571):
    """Run the socket front until cancelled.  Returns the
    ``asyncio.Server`` via context management inside."""
    server = await asyncio.start_server(
        lambda r, w: handle_connection(dispatcher, r, w), host, port)
    addrs = ", ".join(str(s.getsockname()) for s in server.sockets)
    from ..plans.core import warn

    warn(f"pifft serve listening on {addrs}")
    async with server:
        await server.serve_forever()


async def request_over_socket(host: str, port: int, xr, xi=None,
                              layout: str = "natural",
                              precision: Optional[str] = None,
                              inverse: bool = False,
                              domain: str = "c2c") -> dict:
    """Client helper: one fft request over a fresh connection (tests
    and the CLI demo; a real client keeps the connection open)."""
    reader, writer = await asyncio.open_connection(host, port)
    try:
        frame = {
            "op": "fft", "id": 0,
            "xr": np.asarray(xr, np.float64).tolist(),
            "layout": layout, "precision": precision,
            "inverse": inverse, "domain": domain}
        if xi is not None:
            frame["xi"] = np.asarray(xi, np.float64).tolist()
        writer.write(encode_frame(frame))
        await writer.drain()
        reply = await read_frame(reader)
        if reply is None:
            raise ConnectionError("server closed before replying")
        return reply
    finally:
        writer.close()
