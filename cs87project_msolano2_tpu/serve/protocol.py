"""The wire front: one port, two dialects — framed binary and JSON.

The first four bytes of a connection pick the dialect.  The binary
dialect (:mod:`.wire`, docs/SERVING.md "The wire") opens with the
magic ``b"PIFB"``: a fixed 48-byte little-endian header + a small JSON
metadata blob + the raw float32 planes, landed server-side as
``np.frombuffer`` views with ZERO intermediate copies — no
``json.loads``, no per-element Python floats.  Anything else is the
JSON dialect's 4-byte big-endian length prefix (capped far below the
magic's big-endian value, so the two can never collide): a UTF-8 JSON
body.  Requests:

    {"op": "fft", "id": 7, "xr": [...], "xi": [...],
     "layout": "natural", "precision": "split3", "inverse": false,
     "domain": "c2c", "priority": "normal", "tenant": "acme"}
    {"op": "conv", "id": 8, "xr": [...signal...], "xi": [...kernel...]}
    {"op": "stats"}
    {"op": "ping"}

``op`` names the served operation (docs/APPS.md): "fft" (the bare
transform), or the fused spectral ops — "conv"/"corr" take the real
signal in ``xr`` and the real kernel in ``xi`` (CIRCULAR semantics at
n), "solve" takes the real field in ``xr``.  An op outside the
vocabulary is refused with a structured ``bad_request``, never
silently served as a bare transform.

``domain`` is optional (default "c2c"); ``"r2c"`` requests may omit
``xi`` entirely — the input is real by declaration (docs/REAL.md).
``priority`` (low/normal/high) and ``tenant`` feed the admission
classes and per-tenant quotas (docs/SERVING.md, mesh section); both
default to the unprivileged values when omitted.

``trace`` is the optional trace-context field (docs/OBSERVABILITY.md,
"The live plane"): ``{"trace_id": "...", "span_id": "..."}`` (or the
compact ``"<trace_id>-<span_id>"`` string) continues the CLIENT's
trace — its trace_id round-trips on the response and its span_id
becomes the server-side request span's parent.  Omitted, the
dispatcher mints a fresh trace.  A malformed trace field mints
instead of failing — a bad trace header must never fail the request
it describes.  On the binary dialect, tenant and trace ride the
header's metadata blob.

Responses mirror :meth:`~.dispatcher.Response.to_record` (with the
result planes as ``yr``/``yi`` float lists, serialized
float32-faithfully so both dialects decode bit-identical planes) on
success, or

    {"id": 7, "ok": false, "error": {"type": "queue_full",
     "message": "...", "retry_after_ms": 12.5}}

on a structured :class:`~.dispatcher.ServeError` — backpressure and
degradation travel the wire, they are never flattened into a generic
500.  The server is asyncio end to end (``asyncio.start_server``
streams; all awaited — check rule PIF107 keeps blocking socket I/O out
of these paths), with one dispatcher shared by every connection and
every dialect: the coalescer sees ALL concurrent clients, which is the
whole point.

The JSON dialect's whole-body parse is a sanctioned, METERED host
copy: :func:`read_frame` and :func:`encode_frame` charge the
``pifft_host_copy_bytes_total`` meter (serve/wire.py) — check rule
PIF117 keeps any copying decode in this module legal only beside that
charge.  The binary float32 path charges zero, which is exactly what
``make wire-smoke`` asserts.

Negotiation, flow-control credits, streaming responses and the
same-host shm lane are the binary dialect's contract — serve/wire.py
and serve/shm.py module docstrings, docs/SERVING.md "The wire".
"""

from __future__ import annotations

import asyncio
import json
import struct
from typing import Optional

import numpy as np

from ..obs.spans import clock
from . import wire
from .dispatcher import Dispatcher, ServeError

#: frame length prefix: 4-byte big-endian unsigned
_LEN = struct.Struct(">I")

#: refuse absurd frames before allocating for them (a 2^27-point
#: request in JSON floats is ~2 GiB of text; cap generously above any
#: sane served shape).  Kept strictly below ``b"PIFB"`` read as a
#: big-endian u32 (~1.35e9), so a JSON length can never be mistaken
#: for the binary magic.
MAX_FRAME_BYTES = 1 << 28


def encode_frame(obj) -> bytes:
    body = json.dumps(obj, separators=(",", ":")).encode("utf-8")
    if len(body) > MAX_FRAME_BYTES:
        raise ValueError(f"frame body {len(body)} bytes exceeds the "
                         f"{MAX_FRAME_BYTES}-byte cap")
    # the whole JSON body is materialized host-side — the sanctioned
    # encode copy the host-copy meter charges (docs/OBSERVABILITY.md)
    wire.charge_host_copy(len(body), site="json_encode")
    return _LEN.pack(len(body)) + body


async def read_frame(reader, head: Optional[bytes] = None) -> \
        Optional[dict]:
    """The next decoded frame, or None on clean EOF.  `head` is an
    already-read length-prefix prefix (dialect detection peeks it).
    Decoded request objects carry the reserved ``"_t_recv"`` stamp —
    the arrival clock BEFORE the JSON parse, so the parse cost lands
    in the request's queue phase (tail attribution sees the front
    door, docs/ANALYSIS.md); :func:`request_over_socket` strips it
    client-side."""
    if head is None:
        try:
            head = await reader.readexactly(_LEN.size)
        except asyncio.IncompleteReadError as e:
            if not e.partial:
                return None  # clean EOF between frames
            raise ValueError(f"truncated frame header "
                             f"({len(e.partial)}/{_LEN.size} bytes)") \
                from e
    (length,) = _LEN.unpack(head)
    if length > MAX_FRAME_BYTES:
        raise ValueError(f"frame length {length} exceeds the "
                         f"{MAX_FRAME_BYTES}-byte cap")
    body = await reader.readexactly(length)
    t_recv = clock()
    # the sanctioned decode copy: the whole body becomes Python
    # objects (per-element floats and all) — charged, so the JSON-vs-
    # binary host-copy delta is a measured fact, not a slogan
    wire.charge_host_copy(len(body), site="json_decode")
    obj = json.loads(body.decode("utf-8"))
    if isinstance(obj, dict):
        obj["_t_recv"] = t_recv
    return obj


async def _handle_one(dispatcher: Dispatcher, msg: dict) -> dict:
    from ..utils.roofline import SPECTRAL_OPS

    rid = msg.get("id")
    op = msg.get("op")
    t_recv = msg.pop("_t_recv", None)
    if op == "ping":
        return {"id": rid, "ok": True, "pong": True}
    if op == "stats":
        return {"id": rid, "ok": True,
                "stats": dispatcher.stats.summary(),
                "buffers": dispatcher.buffer_stats()}
    if op not in SPECTRAL_OPS:
        # unknown ops are refused with a structured error — never
        # silently served as a bare transform (docs/APPS.md)
        return {"id": rid, "ok": False,
                "error": {"type": "bad_request",
                          "message": f"unknown op {op!r} (serveable: "
                                     f"{SPECTRAL_OPS + ('ping', 'stats')})"}}
    try:
        xi = msg.get("xi")
        resp = await dispatcher.submit(
            np.asarray(msg.get("xr", ()), np.float32),
            np.asarray(xi, np.float32) if xi is not None else None,
            layout=msg.get("layout", "natural"),
            precision=msg.get("precision"),
            inverse=bool(msg.get("inverse", False)),
            domain=msg.get("domain", "c2c"),
            priority=msg.get("priority") or "normal",
            tenant=msg.get("tenant") or "default",
            op=op,
            trace=msg.get("trace"),
            t_recv=t_recv)
    except ServeError as e:
        return {"id": rid, "ok": False, "error": e.to_record()}
    rec = resp.to_record(arrays=True)
    rec["id"] = rid if rid is not None else rec["id"]
    return rec


#: the client-went-away family: a write/drain dying of one of these is
#: the CLIENT's disconnect, not a server fault — the handler closes
#: that one connection with a warn event and the accept loop (and the
#: sibling connections it serves) never sees it
_DISCONNECTS = (ConnectionResetError, BrokenPipeError,
                ConnectionAbortedError)


class _ConnState:
    """Per-connection write discipline shared by both dialects:
    serialized writes, in-flight reply tasks, and the peer-went-away
    latch with its one ``serve_conn_lost`` event."""

    def __init__(self, writer):
        self.writer = writer
        self.write_lock = asyncio.Lock()
        self.pending: set = set()
        self.lost = asyncio.Event()

    def note_lost(self, e: Exception) -> None:
        if self.lost.is_set():
            return
        self.lost.set()
        from ..obs import events, metrics
        from ..plans.core import warn

        peer = None
        try:
            peer = self.writer.get_extra_info("peername")
        except Exception:  # pragma: no cover - transport gone entirely  # pifft: noqa[PIF501]: transport is gone entirely — there is no peer left to report the error to
            pass
        metrics.inc("pifft_serve_conn_lost_total")
        events.emit("serve_conn_lost", peer=str(peer),
                    error=f"{type(e).__name__}: {str(e)[:200]}")
        warn(f"serve: client {peer} disconnected mid-write "
             f"({type(e).__name__}); closing that connection")

    async def write_bufs(self, bufs) -> bool:
        """Serialized multi-buffer frame write; False once the peer is
        gone.  Buffers are handed to the transport as-is — response
        planes go out as their own memory, no join copy."""
        if self.lost.is_set():
            return False
        async with self.write_lock:
            if self.lost.is_set():
                return False
            try:
                for buf in bufs:
                    self.writer.write(buf)
                await self.writer.drain()
            except _DISCONNECTS as e:
                self.note_lost(e)
                return False
        return True

    async def write_json(self, reply) -> bool:
        return await self.write_bufs([encode_frame(reply)])

    def spawn(self, coro):
        task = asyncio.ensure_future(coro)
        self.pending.add(task)
        task.add_done_callback(self.pending.discard)

    async def drain_pending(self):
        if self.pending:
            await asyncio.gather(*self.pending, return_exceptions=True)


async def handle_connection(dispatcher: Dispatcher, reader, writer,
                            shm_config: Optional[dict] = None) -> None:
    """One client connection: frames in, frames out, until EOF.
    The first four bytes pick the dialect (module docstring).
    Requests on one connection are served CONCURRENTLY (a queue-full
    rejection must not wait behind a coalescing window), with writes
    serialized through a lock.  A client disconnecting mid-write
    (``ConnectionResetError``/``BrokenPipeError`` out of ``drain()``)
    closes THIS connection with a ``serve_conn_lost`` warn event —
    it never propagates into the accept loop."""
    st = _ConnState(writer)
    try:
        try:
            head = await reader.readexactly(4)
        except asyncio.IncompleteReadError:
            return  # nothing (or a sub-prefix fragment) then EOF
        except _DISCONNECTS as e:
            st.note_lost(e)
            return
        if head == wire.MAGIC:
            await _serve_binary(dispatcher, reader, st, head,
                                shm_config)
        else:
            await _serve_json(dispatcher, reader, st, head)
        await st.drain_pending()
    finally:
        try:
            writer.close()
        except _DISCONNECTS as e:  # pragma: no cover - already gone
            st.note_lost(e)


# ------------------------------------------------------- JSON dialect


async def _serve_json(dispatcher: Dispatcher, reader, st: _ConnState,
                      head: Optional[bytes]) -> None:
    async def serve_one(msg):
        try:
            reply = await _handle_one(dispatcher, msg)
        except Exception as e:  # a reply is owed even for the unforeseen
            from ..resilience import classify

            reply = {"id": msg.get("id"), "ok": False,
                     "error": {"type": "internal",
                               "kind": classify(e).value,
                               "message":
                                   f"{type(e).__name__}: {str(e)[:200]}"}}
        await st.write_json(reply)

    while not st.lost.is_set():
        try:
            msg = await read_frame(reader, head=head)
        except _DISCONNECTS as e:
            st.note_lost(e)
            break
        except (ValueError, json.JSONDecodeError) as e:
            await st.write_json(
                {"ok": False,
                 "error": {"type": "bad_frame",
                           "message": str(e)[:200]}})
            break  # framing is lost; the connection cannot recover
        except asyncio.IncompleteReadError as e:
            st.note_lost(e)
            break
        finally:
            head = None
        if msg is None:
            break
        wire.count_frame("json")
        st.spawn(serve_one(msg))


# ----------------------------------------------------- binary dialect


async def _serve_binary(dispatcher: Dispatcher, reader, st: _ConnState,
                        head: bytes, shm_config: Optional[dict]) -> None:
    from ..obs import events
    from .shm import ShmRing

    try:
        hello = await wire.read_wire_frame(reader, head=head)
    except (wire.WireError, asyncio.IncompleteReadError) as e:
        st.note_lost(e)
        return
    if hello is None:
        return
    if hello.msg_type != wire.MSG_HELLO \
            or hello.version > wire.WIRE_VERSION or hello.version < 1:
        # unknown version (or a frame out of handshake order): FALL
        # BACK to the JSON dialect on the same connection, with a
        # structured warning — an old server must stay reachable by a
        # newer client, just slower (docs/SERVING.md)
        from ..plans.core import warn

        events.emit("serve_wire_fallback", offered=hello.version,
                    supported=wire.WIRE_VERSION,
                    msg_type=hello.msg_type)
        warn(f"serve: binary HELLO offered wire version "
             f"{hello.version} (supported: {wire.WIRE_VERSION}); "
             f"falling back to the JSON dialect")
        await st.write_json({"ok": True, "dialect": "json",
                             "wire_version": wire.WIRE_VERSION})
        await _serve_json(dispatcher, reader, st, None)
        return

    window = wire.DEFAULT_CREDITS
    ring = None
    ack_flags = 0
    ack_payload = b""
    slots = slot_bytes = 0
    if (hello.flags & wire.F_WANT_SHM) and shm_config:
        ring = ShmRing.create(shm_config["slots"],
                              shm_config["slot_bytes"])
        ack_flags |= wire.F_SHM
        ack_payload = ring.name.encode("utf-8")
        slots, slot_bytes = ring.slots, ring.slot_bytes
        window = min(window, ring.slots)
    await st.write_bufs(wire.encode_frame(
        wire.MSG_HELLO_ACK, flags=ack_flags, n=slots,
        width=slot_bytes, slot=window, payload=ack_payload))
    events.emit("serve_wire_negotiated", protocol="binary",
                version=min(hello.version, wire.WIRE_VERSION),
                credits=window, shm=ring is not None)

    inflight = 0

    async def serve_one(frame, t_recv):
        nonlocal inflight
        try:
            bufs = await _handle_binary(dispatcher, frame, ring,
                                        t_recv)
        except Exception as e:  # a reply is owed even for the unforeseen
            from ..resilience import classify

            bufs = _error_frame(frame.rid, {
                "type": "internal", "kind": classify(e).value,
                "message": f"{type(e).__name__}: {str(e)[:200]}"})
        finally:
            inflight -= 1
        await st.write_bufs(bufs)

    try:
        while not st.lost.is_set():
            try:
                frame = await wire.read_wire_frame(reader)
            except wire.WireError as e:
                # a malformed header: framing is lost and cannot
                # recover — serve_conn_lost + close, never a hang
                st.note_lost(e)
                break
            except asyncio.IncompleteReadError as e:
                # truncated mid-frame: the client went away; tolerated
                if e.partial:
                    st.note_lost(e)
                break
            except _DISCONNECTS as e:
                st.note_lost(e)
                break
            if frame is None:
                break
            if frame.msg_type == wire.MSG_PING:
                await st.write_bufs(wire.encode_frame(
                    wire.MSG_PONG, rid=frame.rid))
                continue
            if frame.msg_type != wire.MSG_REQUEST:
                await st.write_bufs(_error_frame(frame.rid, {
                    "type": "bad_request",
                    "message": f"unexpected msg_type "
                               f"{frame.msg_type} mid-stream"}))
                continue
            wire.count_frame("binary")
            if inflight >= window:
                # flow-control violation: a structured wire error for
                # THIS rid — the connection (and its other in-flight
                # requests) survives
                await st.write_bufs(_error_frame(frame.rid, {
                    "type": "flow_control",
                    "message": f"credit window exceeded "
                               f"({inflight}/{window} in flight)"}))
                continue
            inflight += 1
            st.spawn(serve_one(frame, clock()))
    finally:
        await st.drain_pending()
        if ring is not None:
            ring.close()
            ring.unlink()


def _error_frame(rid: int, error: dict) -> list:
    return wire.encode_frame(wire.MSG_ERROR, rid=rid,
                             extras={"id": rid, "ok": False,
                                     "error": error})


async def _handle_binary(dispatcher: Dispatcher, frame, ring,
                         t_recv) -> list:
    """One binary REQUEST -> the reply frame's buffer list (a single
    RESPONSE/ERROR frame, or a STREAM_CHUNK sequence + STREAM_END).
    The request planes are ZERO-COPY views — over the receive buffer
    (inline payload) or the shm slot — handed straight to the
    dispatcher; the batcher's staging copy into the pooled planes
    (serve/buffers.py) is the one landing memcpy both dialects
    share."""
    from .buffers import landing_views

    no_xi = bool(frame.flags & wire.F_NO_XI)
    extras = frame.extras or {}
    try:
        if frame.flags & wire.F_SHM:
            if ring is None:
                raise wire.WireError("shm flag on a connection with "
                                     "no shm lane granted")
            xr, xi = ring.slot_planes(frame.slot, frame.width,
                                      no_xi=no_xi)
        else:
            expect = frame.width * wire.wire_dtype_width(frame.dtype) \
                * (1 if no_xi else 2)
            if len(frame.payload) != expect:
                raise wire.WireError(
                    f"payload is {len(frame.payload)} bytes, header "
                    f"promises {expect}")
            xr, xi = landing_views(frame.payload, frame.width,
                                   no_xi=no_xi, dtype=frame.dtype)
    except (wire.WireError, ValueError) as e:
        return _error_frame(frame.rid, {"type": "bad_request",
                                        "message": str(e)[:200]})
    try:
        resp = await dispatcher.submit(
            xr, xi,
            layout="pi" if frame.flags & wire.F_PI else "natural",
            precision=frame.precision,
            inverse=frame.inverse,
            domain=frame.domain,
            priority=frame.priority,
            tenant=extras.get("tenant") or "default",
            op=frame.op,
            trace=extras.get("trace"),
            t_recv=t_recv)
    except ServeError as e:
        return _error_frame(frame.rid, e.to_record())

    meta = resp.to_record(arrays=False)
    meta["id"] = frame.rid
    yr = np.ascontiguousarray(np.asarray(resp.yr, np.float32))
    yi = np.ascontiguousarray(np.asarray(resp.yi, np.float32))
    width = int(yr.shape[-1])
    flags = wire.F_DEGRADED if resp.degraded else 0

    if ring is not None and frame.flags & wire.F_SHM \
            and width * 8 <= ring.slot_bytes:
        # the shm lane answers in place: results land in the request's
        # slot, the RESPONSE frame carries only control
        dr, di = ring.slot_planes(frame.slot, width)
        np.copyto(dr, yr)
        np.copyto(di, yi)
        return wire.encode_frame(
            wire.MSG_RESPONSE, flags=flags | wire.F_SHM,
            rid=frame.rid, n=frame.n, width=width, slot=frame.slot,
            extras=meta)

    payload = [wire.plane_to_wire(yr, frame.dtype),
               wire.plane_to_wire(yi, frame.dtype)]
    total = sum(p.nbytes for p in payload)
    if frame.flags & wire.F_STREAM and total > wire.STREAM_CHUNK_BYTES:
        return _stream_frames(frame, flags, width, payload, meta)
    return wire.encode_frame(
        wire.MSG_RESPONSE, flags=flags, rid=frame.rid,
        dtype=frame.dtype, n=frame.n, width=width, extras=meta,
        payload=payload)


def _stream_frames(frame, flags: int, width: int, payload,
                   meta: dict) -> list:
    """A chunked response: STREAM_CHUNK frames (``slot`` = sequence
    number) then the STREAM_END carrying the metadata — overlap-save
    results stop owing one giant buffer to the transport."""
    raw = b"".join(bytes(p) for p in payload)
    bufs = []
    seq = 0
    for off in range(0, len(raw), wire.STREAM_CHUNK_BYTES):
        bufs.extend(wire.encode_frame(
            wire.MSG_STREAM_CHUNK, rid=frame.rid, dtype=frame.dtype,
            n=frame.n, width=width, slot=seq,
            payload=raw[off:off + wire.STREAM_CHUNK_BYTES]))
        seq += 1
    bufs.extend(wire.encode_frame(
        wire.MSG_STREAM_END, flags=flags, rid=frame.rid,
        dtype=frame.dtype, n=frame.n, width=width, slot=seq,
        extras=meta))
    return bufs


async def serve_socket(dispatcher: Dispatcher, host: str = "127.0.0.1",
                       port: int = 8571,
                       shm_config: Optional[dict] = None):
    """Run the socket front until cancelled.  Returns the
    ``asyncio.Server`` via context management inside.  `shm_config`
    (``{"slots", "slot_bytes"}``) arms the same-host shared-memory
    lane — ``pifft serve --shm``."""
    server = await asyncio.start_server(
        lambda r, w: handle_connection(dispatcher, r, w,
                                       shm_config=shm_config),
        host, port)
    addrs = ", ".join(str(s.getsockname()) for s in server.sockets)
    from ..plans.core import warn

    warn(f"pifft serve listening on {addrs}")
    async with server:
        await server.serve_forever()


async def request_over_socket(host: str, port: int, xr, xi=None,
                              layout: str = "natural",
                              precision: Optional[str] = None,
                              inverse: bool = False,
                              domain: str = "c2c",
                              op: str = "fft",
                              trace=None) -> dict:
    """Client helper: one JSON-dialect request over a fresh connection
    (tests and the CLI demo; a real client keeps the connection open —
    the binary dialect's :class:`~.wire.WireClient` multiplexes).
    `op` rides the frame's op field — "fft" (default) or the spectral
    ops "conv"/"corr"/"solve" (docs/APPS.md); `trace` the optional
    trace-context field (module docstring)."""
    reader, writer = await asyncio.open_connection(host, port)
    try:
        frame = {
            "op": op, "id": 0,
            "xr": np.asarray(xr, np.float64).tolist(),
            "layout": layout, "precision": precision,
            "inverse": inverse, "domain": domain}
        if xi is not None:
            frame["xi"] = np.asarray(xi, np.float64).tolist()
        if trace is not None:
            frame["trace"] = trace
        writer.write(encode_frame(frame))
        await writer.drain()
        reply = await read_frame(reader)
        if reply is None:
            raise ConnectionError("server closed before replying")
        reply.pop("_t_recv", None)
        return reply
    finally:
        writer.close()
