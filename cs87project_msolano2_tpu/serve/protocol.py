"""The wire front: length-prefixed JSON frames over a TCP socket.

One frame = a 4-byte big-endian length + a UTF-8 JSON body.  Requests:

    {"op": "fft", "id": 7, "xr": [...], "xi": [...],
     "layout": "natural", "precision": "split3", "inverse": false,
     "domain": "c2c", "priority": "normal", "tenant": "acme"}
    {"op": "conv", "id": 8, "xr": [...signal...], "xi": [...kernel...]}
    {"op": "stats"}
    {"op": "ping"}

``op`` names the served operation (docs/APPS.md): "fft" (the bare
transform), or the fused spectral ops — "conv"/"corr" take the real
signal in ``xr`` and the real kernel in ``xi`` (CIRCULAR semantics at
n), "solve" takes the real field in ``xr``.  An op outside the
vocabulary is refused with a structured ``bad_request``, never
silently served as a bare transform.

``domain`` is optional (default "c2c"); ``"r2c"`` requests may omit
``xi`` entirely — the input is real by declaration (docs/REAL.md).
``priority`` (low/normal/high) and ``tenant`` feed the admission
classes and per-tenant quotas (docs/SERVING.md, mesh section); both
default to the unprivileged values when omitted.

``trace`` is the optional trace-context field (docs/OBSERVABILITY.md,
"The live plane"): ``{"trace_id": "...", "span_id": "..."}`` (or the
compact ``"<trace_id>-<span_id>"`` string) continues the CLIENT's
trace — its trace_id round-trips on the response and its span_id
becomes the server-side request span's parent.  Omitted, the
dispatcher mints a fresh trace.  Successful responses carry
``trace`` back: the ids always, and the request's span tree
(queue/window/compute children, degrade/failover hops) when the
trace was sampled or tail-upgraded.  A malformed trace field mints
instead of failing — a bad trace header must never fail the request
it describes.

Responses mirror :meth:`~.dispatcher.Response.to_record` (with the
result planes as ``yr``/``yi`` float lists) on success, or

    {"id": 7, "ok": false, "error": {"type": "queue_full",
     "message": "...", "retry_after_ms": 12.5}}

on a structured :class:`~.dispatcher.ServeError` — backpressure and
degradation travel the wire, they are never flattened into a generic
500.  The server is asyncio end to end (``asyncio.start_server``
streams; all awaited — check rule PIF107 keeps blocking socket I/O out
of these paths), with one dispatcher shared by every connection: the
coalescer sees ALL concurrent clients, which is the whole point.

JSON float lists are a deliberately simple encoding — this front is
the protocol seam, not a throughput record; a binary frame body can
replace the JSON without touching the dispatcher.
"""

from __future__ import annotations

import asyncio
import json
import struct
from typing import Optional

import numpy as np

from .dispatcher import Dispatcher, ServeError

#: frame length prefix: 4-byte big-endian unsigned
_LEN = struct.Struct(">I")

#: refuse absurd frames before allocating for them (a 2^27-point
#: request in JSON floats is ~2 GiB of text; cap generously above any
#: sane served shape)
MAX_FRAME_BYTES = 1 << 28


def encode_frame(obj) -> bytes:
    body = json.dumps(obj, separators=(",", ":")).encode("utf-8")
    if len(body) > MAX_FRAME_BYTES:
        raise ValueError(f"frame body {len(body)} bytes exceeds the "
                         f"{MAX_FRAME_BYTES}-byte cap")
    return _LEN.pack(len(body)) + body


async def read_frame(reader) -> Optional[dict]:
    """The next decoded frame, or None on clean EOF."""
    try:
        head = await reader.readexactly(_LEN.size)
    except asyncio.IncompleteReadError as e:
        if not e.partial:
            return None  # clean EOF between frames
        raise ValueError(f"truncated frame header "
                         f"({len(e.partial)}/{_LEN.size} bytes)") from e
    (length,) = _LEN.unpack(head)
    if length > MAX_FRAME_BYTES:
        raise ValueError(f"frame length {length} exceeds the "
                         f"{MAX_FRAME_BYTES}-byte cap")
    body = await reader.readexactly(length)
    return json.loads(body.decode("utf-8"))


async def _handle_one(dispatcher: Dispatcher, msg: dict) -> dict:
    from ..utils.roofline import SPECTRAL_OPS

    rid = msg.get("id")
    op = msg.get("op")
    if op == "ping":
        return {"id": rid, "ok": True, "pong": True}
    if op == "stats":
        return {"id": rid, "ok": True,
                "stats": dispatcher.stats.summary(),
                "buffers": dispatcher.buffer_stats()}
    if op not in SPECTRAL_OPS:
        # unknown ops are refused with a structured error — never
        # silently served as a bare transform (docs/APPS.md)
        return {"id": rid, "ok": False,
                "error": {"type": "bad_request",
                          "message": f"unknown op {op!r} (serveable: "
                                     f"{SPECTRAL_OPS + ('ping', 'stats')})"}}
    try:
        xi = msg.get("xi")
        resp = await dispatcher.submit(
            np.asarray(msg.get("xr", ()), np.float32),
            np.asarray(xi, np.float32) if xi is not None else None,
            layout=msg.get("layout", "natural"),
            precision=msg.get("precision"),
            inverse=bool(msg.get("inverse", False)),
            domain=msg.get("domain", "c2c"),
            priority=msg.get("priority") or "normal",
            tenant=msg.get("tenant") or "default",
            op=op,
            trace=msg.get("trace"))
    except ServeError as e:
        return {"id": rid, "ok": False, "error": e.to_record()}
    rec = resp.to_record(arrays=True)
    rec["id"] = rid if rid is not None else rec["id"]
    return rec


#: the client-went-away family: a write/drain dying of one of these is
#: the CLIENT's disconnect, not a server fault — the handler closes
#: that one connection with a warn event and the accept loop (and the
#: sibling connections it serves) never sees it
_DISCONNECTS = (ConnectionResetError, BrokenPipeError,
                ConnectionAbortedError)


async def handle_connection(dispatcher: Dispatcher, reader,
                            writer) -> None:
    """One client connection: frames in, frames out, until EOF.
    Requests on one connection are served CONCURRENTLY (a queue-full
    rejection must not wait behind a coalescing window), with writes
    serialized through a lock.  A client disconnecting mid-write
    (``ConnectionResetError``/``BrokenPipeError`` out of ``drain()``)
    closes THIS connection with a ``serve_conn_lost`` warn event —
    it never propagates into the accept loop."""
    write_lock = asyncio.Lock()
    pending = set()
    # once the peer is gone every further write on this connection is
    # pointless: remember it so in-flight repliers stop trying
    lost = asyncio.Event()

    def _note_lost(e: Exception) -> None:
        if lost.is_set():
            return
        lost.set()
        from ..obs import events, metrics
        from ..plans.core import warn

        peer = None
        try:
            peer = writer.get_extra_info("peername")
        except Exception:  # pragma: no cover - transport gone entirely  # pifft: noqa[PIF501]: transport is gone entirely — there is no peer left to report the error to
            pass
        metrics.inc("pifft_serve_conn_lost_total")
        events.emit("serve_conn_lost", peer=str(peer),
                    error=f"{type(e).__name__}: {str(e)[:200]}")
        warn(f"serve: client {peer} disconnected mid-write "
             f"({type(e).__name__}); closing that connection")

    async def write_reply(reply) -> bool:
        """Serialized frame write; False once the peer is gone."""
        if lost.is_set():
            return False
        async with write_lock:
            if lost.is_set():
                return False
            try:
                writer.write(encode_frame(reply))
                await writer.drain()
            except _DISCONNECTS as e:
                _note_lost(e)
                return False
        return True

    async def serve_one(msg):
        try:
            reply = await _handle_one(dispatcher, msg)
        except Exception as e:  # a reply is owed even for the unforeseen
            from ..resilience import classify

            reply = {"id": msg.get("id"), "ok": False,
                     "error": {"type": "internal",
                               "kind": classify(e).value,
                               "message":
                                   f"{type(e).__name__}: {str(e)[:200]}"}}
        await write_reply(reply)

    try:
        while not lost.is_set():
            try:
                msg = await read_frame(reader)
            except _DISCONNECTS as e:
                _note_lost(e)
                break
            except (ValueError, json.JSONDecodeError) as e:
                await write_reply(
                    {"ok": False,
                     "error": {"type": "bad_frame",
                               "message": str(e)[:200]}})
                break  # framing is lost; the connection cannot recover
            if msg is None:
                break
            task = asyncio.ensure_future(serve_one(msg))
            pending.add(task)
            task.add_done_callback(pending.discard)
        if pending:
            await asyncio.gather(*pending, return_exceptions=True)
    finally:
        try:
            writer.close()
        except _DISCONNECTS as e:  # pragma: no cover - already gone
            _note_lost(e)


async def serve_socket(dispatcher: Dispatcher, host: str = "127.0.0.1",
                       port: int = 8571):
    """Run the socket front until cancelled.  Returns the
    ``asyncio.Server`` via context management inside."""
    server = await asyncio.start_server(
        lambda r, w: handle_connection(dispatcher, r, w), host, port)
    addrs = ", ".join(str(s.getsockname()) for s in server.sockets)
    from ..plans.core import warn

    warn(f"pifft serve listening on {addrs}")
    async with server:
        await server.serve_forever()


async def request_over_socket(host: str, port: int, xr, xi=None,
                              layout: str = "natural",
                              precision: Optional[str] = None,
                              inverse: bool = False,
                              domain: str = "c2c",
                              op: str = "fft",
                              trace=None) -> dict:
    """Client helper: one request over a fresh connection (tests and
    the CLI demo; a real client keeps the connection open).  `op`
    rides the frame's op field — "fft" (default) or the spectral ops
    "conv"/"corr"/"solve" (docs/APPS.md); `trace` the optional
    trace-context field (module docstring)."""
    reader, writer = await asyncio.open_connection(host, port)
    try:
        frame = {
            "op": op, "id": 0,
            "xr": np.asarray(xr, np.float64).tolist(),
            "layout": layout, "precision": precision,
            "inverse": inverse, "domain": domain}
        if xi is not None:
            frame["xi"] = np.asarray(xi, np.float64).tolist()
        if trace is not None:
            frame["trace"] = trace
        writer.write(encode_frame(frame))
        await writer.drain()
        reply = await read_frame(reader)
        if reply is None:
            raise ConnectionError("server closed before replying")
        return reply
    finally:
        writer.close()
