"""Mesh-scale serving: per-device worker pools behind one front door
(docs/SERVING.md, mesh section).

The paper's thesis — P processors, zero inter-processor communication
— means a device mesh needs no cross-device dataflow to serve FFTs:
each request runs whole on ONE device, so the mesh problem is pure
placement + failure handling.  :class:`MeshDispatcher` keeps the
single-device :class:`~.dispatcher.Dispatcher` contract (same
``submit``, same structured errors, same socket front) and adds:

* **per-device worker pools** — every :class:`MeshDevice` owns its own
  :class:`~.batcher.BatchRunner` + :class:`~.buffers.BufferPool` and
  per-group bounded queues; a batch never spans devices.
* **shape-affinity routing** (:mod:`.router`) — requests land where
  the GroupKey's plan/executor and staging buffers are already warm,
  least-loaded tie-break, every placement counted
  (``pifft_serve_placement_total{device,reason}``).
* **priority admission + tenant quotas** (:mod:`.router`) — the class
  tables shed low-priority load first; per-tenant outstanding-request
  quotas stop one tenant's burst from filling the mesh.
* **self-healing failover** — a device failing (the ``device<K>``
  injection sites — docs/RESILIENCE.md) or stalling (the PR-8
  supervisor, when ``batch_deadline_s`` arms it) mid-batch is marked
  dead through the multihost CONSENSUS path
  (``parallel.multihost.agree_on_fallback`` — every host switches
  together, docs/MULTICHIP.md) and its queued *and* in-flight-unacked
  requests re-route to survivors with ``failover:<device>`` on their
  degrade trail.  Zero dropped requests: every admitted future
  resolves with a response or a structured error.
* **warm-cache handoff on planned drain** — :meth:`drain_device`
  pushes the draining device's compiled executors and warm groups to
  a successor BEFORE the queue moves, journaling each step
  (:class:`~..resilience.journal.Journal`) so a kill mid-drain
  resumes instead of restarting.

The mesh is VIRTUAL on CPU (the tier-1/smoke path: 8 in-process
devices sharing the host backend, exactly like the multichip dryruns'
forced host platform) and maps 1:1 onto real accelerators where
``jax.devices()`` offers them — the placement/failover logic is
device-agnostic by construction.
"""

from __future__ import annotations

import asyncio
import dataclasses
import functools
import threading
from typing import Optional

from ..obs import events, metrics
from ..obs import trace as trace_mod
from ..obs.spans import clock
from ..plans.core import warn
from ..resilience import CollectiveAborted, CollectiveTimeout, classify
from ..resilience.inject import maybe_fault
from ..resilience.journal import Journal
from ..resilience.watchdog import supervise_collective
from .batcher import BatchRunner, GroupKey
from .buffers import BufferPool
from .dispatcher import (
    _CLOSE,
    Dispatcher,
    DispatcherClosed,
    QueueFull,
    Request,
    ServeConfig,
    ServeError,
)
from .router import (
    AdmissionController,
    NoDeviceAvailable,
    QuotaExceeded,
    Router,
)


class DeviceFailure(RuntimeError):
    """A mesh device (not the batch it was running) died: raised out
    of the per-device injection probe so the failover path — not the
    batcher's kernel-fallback rungs — owns it."""

    def __init__(self, device_id: str, cause: Exception):
        super().__init__(
            f"device {device_id} failed ({type(cause).__name__}: "
            f"{str(cause)[:200]})")
        self.device_id = device_id
        self.cause = cause


@dataclasses.dataclass
class MeshConfig(ServeConfig):
    """Mesh knobs on top of the dispatcher's (docs/SERVING.md)."""

    devices: int = 8              # virtual (CPU) or physical device count
    tenant_quota: Optional[int] = None   # max outstanding per tenant
    #: arm the PR-8 collective supervisor around every device batch:
    #: a batch overrunning `batch_deadline_s` × `batch_abort_waits`
    #: is aborted (CollectiveAborted) and handled as a device stall —
    #: None (default) leaves batches unsupervised (no per-batch
    #: supervisor thread on the hot path).  The supervisor cannot
    #: tell a cold compile from a stall, so set the deadline above
    #: worst-case compile time or prime the mesh first (the field is
    #: read per batch, so it can be armed after warmup)
    batch_deadline_s: Optional[float] = None
    batch_abort_waits: int = 1
    #: journal path for warm-handoff drains (drain_device's default)
    handoff_journal: Optional[str] = None
    #: per-device backend tags (plans.core.BACKENDS) for a
    #: HETEROGENEOUS mesh (docs/BACKENDS.md): entry i tags device i,
    #: devices past the tuple's length default to "tpu".  A device's
    #: tag flows into its runner's plan keys, its warmth (plans are
    #: COLD across tags unless explicitly cross-warmed), and the
    #: failover trail (``failover:backend:<tag>`` when a re-route
    #: crosses tags).  Empty (default) = the homogeneous mesh of
    #: PRs 1-19.
    backends: tuple = ()


class MeshDevice:
    """One mesh member: its own runner/pool/queues, health state, and
    occupancy accounting.  States: ``healthy`` (serving) →
    ``draining`` (handoff in progress, router skips it) → ``drained``
    (clean exit), or → ``dead`` (failover evacuated it)."""

    def __init__(self, index: int, prefix: str = "vdev",
                 backend: Optional[str] = None):
        self.index = index
        self.id = f"{prefix}{index}"
        #: fault-injection site (docs/RESILIENCE.md): arm
        #: ``PIFFT_FAULT=device3:permanent`` to kill device 3,
        #: ``device*:...`` to strike any device
        self.site = f"device{index}"
        self.state = "healthy"
        #: the device's backend tag (plans.core.BACKENDS — docs/
        #: BACKENDS.md): flows into every plan key its runner builds,
        #: so a heterogeneous mesh tunes/caches per device family
        self.backend = backend or "tpu"
        self.runner = BatchRunner(BufferPool(), backend=self.backend)
        self.queues: dict = {}     # GroupKey -> asyncio.Queue
        self.workers: dict = {}    # GroupKey -> worker task
        self.inflight: dict = {}   # batch token -> [Request] (un-acked)
        self.warm_groups: set = set()
        self.busy_s = 0.0
        #: busy_s accumulates from executor threads — two groups'
        #: batches can finish on this device simultaneously, and a
        #: lost += would skew the utilization rows the balance gate
        #: reads
        self._busy_lock = threading.Lock()
        self.served = 0
        #: the failover consensus, shared by every batch that dies on
        #: this device: the FIRST failure handler runs it, the rest
        #: await the same future so no re-route happens before the
        #: hosts agreed (set only once state flips to "dead")
        self.consensus: Optional[asyncio.Future] = None

    def load(self) -> int:
        """Placement load: queued + in-flight-unacked requests."""
        queued = sum(q.qsize() for q in self.queues.values())
        return queued + sum(len(b) for b in self.inflight.values())

    def warmth(self, group: GroupKey) -> int:
        """The router's affinity signal, read from the real
        plan-cache/buffer state (docs/SERVING.md): 3 = compiled
        executor cached here (hot), 2 = plan warmed/handed here,
        1 = staging buffers pooled for the group's input width (a
        WEAK signal — the pool is keyed by shape, so same-width
        sibling groups alias; it must never outrank an explicit warm
        assignment), 0 = cold."""
        if group in self.runner.cached_groups():
            return 3
        if group in self.warm_groups:
            return 2
        width = group.input_width()
        if any(len(shape) == 2 and shape[1] == width
               for shape in self.runner.pool.pooled_shapes()):
            return 1
        return 0

    def describe(self) -> dict:
        return {"device": self.id, "state": self.state,
                "backend": self.backend,
                "served": self.served, "load": self.load(),
                "busy_s": round(self.busy_s, 6),
                "warm_groups": sorted(g.label()
                                      for g in self.warm_groups)}


class MeshDispatcher(Dispatcher):
    """The mesh front door: same caller contract as
    :class:`~.dispatcher.Dispatcher`, but admission routes to one of
    ``config.devices`` per-device worker pools (module docstring)."""

    def __init__(self, config: Optional[MeshConfig] = None,
                 shape_specs=None):
        config = config or MeshConfig()
        super().__init__(config, shape_specs)
        count = max(1, int(config.devices))
        tags = tuple(config.backends or ())
        self.devices = [
            MeshDevice(i, backend=tags[i] if i < len(tags) else None)
            for i in range(count)]
        self.router = Router(self.devices)
        self.admission = AdmissionController(quota=config.tenant_quota)
        self.t_open = clock()
        #: the fleet-loop tap (fleet/prewarm.py FleetTap, duck-typed so
        #: serve/ never imports fleet/): when attached, every admitted
        #: request feeds the decayed arrival model + the shadow-traffic
        #: mirror, warm() consults the model's hot groups, and a drain
        #: persists the model beside the plan cache (docs/FLEET.md)
        self.fleet_tap = None

    # ----------------------------------------------------- lifecycle

    def warm(self, force: bool = False) -> list:
        """Warm the served shape set ROUND-ROBIN across the mesh: each
        spec's plan is resolved once (the process-global plan cache)
        and its serving warmth assigned to one device — the initial
        affinity map the router spreads load by."""
        from . import shapes as shapes_mod

        if self.fleet_tap is not None:
            # predictive prewarm (docs/FLEET.md): groups the persisted
            # arrival model expects hot join the served set BEFORE the
            # round-robin, so a restarted mesh serves its first request
            # of every previously-hot GroupKey on a warm plan
            for spec in self.fleet_tap.hot_specs():
                sig = (spec.n, spec.layout, spec.precision, spec.domain,
                       getattr(spec, "op", "fft"))
                if sig in self._served:
                    continue
                self.specs.append(spec)
                self._served.add(sig)
        out = []
        for i, spec in enumerate(self.specs):
            device = self.devices[i % len(self.devices)]
            out.extend(shapes_mod.warm([spec], force=force))
            group = GroupKey(n=spec.n, layout=spec.layout,
                             precision=spec.precision,
                             domain=spec.domain,
                             op=getattr(spec, "op", "fft"))
            device.warm_groups.add(group)
            events.emit("serve_warm_assignment", device=device.id,
                        shape=group.label())
        return out

    def device(self, device_id: str) -> MeshDevice:
        for d in self.devices:
            if d.id == device_id:
                return d
        raise ServeError(f"unknown mesh device {device_id!r} "
                         f"({[d.id for d in self.devices]})")

    def buffer_stats(self) -> dict:
        """Aggregated staging-pool stats across the mesh."""
        agg = {"hits": 0, "misses": 0, "pooled": 0}
        for d in self.devices:
            for key, val in d.runner.pool.stats().items():
                agg[key] += val
        return agg

    def utilization(self) -> dict:
        """Per-device occupancy since the mesh opened: busy compute
        seconds over wall time — the balance row set the mesh smoke
        bounds (docs/SERVING.md)."""
        wall = max(clock() - self.t_open, 1e-9)
        return {
            d.id: {"device": d.id, "state": d.state,
                   "served": d.served, "busy_s": round(d.busy_s, 6),
                   "utilization": round(min(d.busy_s / wall, 1.0), 6)}
            for d in self.devices
        }

    # ----------------------------------------------------- admission

    async def submit(self, xr, xi=None, layout: str = "natural",
                     precision: Optional[str] = None,
                     inverse: bool = False,
                     domain: str = "c2c",
                     priority: str = "normal",
                     tenant: str = "default",
                     op: str = "fft",
                     trace=None,
                     t_recv: Optional[float] = None):
        """:meth:`Dispatcher.submit`, mesh-routed: validation and the
        class-aware bounded admission are the shared base logic; the
        queue is the ROUTED device's, and the tenant-quota layer runs
        before enqueue (released when the response future resolves,
        whatever it resolves to).  Op-tagged requests (docs/APPS.md)
        route exactly like transforms — the GroupKey carries the op,
        so warmth and affinity are op-aware for free.  The trace
        context (obs/trace.py) is minted/adopted exactly like the
        base dispatcher's — placement, re-routes and the device all
        land in the request's span tree."""
        if self._closing:
            raise DispatcherClosed("dispatcher is shut down")
        xr, xi, group = self._validated(xr, xi, layout, precision,
                                        inverse, domain, priority, op)
        self._check_served(group)
        tap = self.fleet_tap
        if tap is not None:
            # one dict/deque update per request (the tap locks its own
            # state): the arrival model learns the live mix, and the
            # mirror keeps the planes the canary race replays
            tap.observe(group, xr, xi)
        ctx = trace_mod.ensure(trace)
        t_submit = t_recv if t_recv is not None else clock()
        # choose first, RECORD only after admission passes: a shed
        # request must not inflate the placement counter the
        # affinity assertions read
        device, why, warmth, load = self.router.choose(group)
        q = self._ensure_device_worker(device, group)
        try:
            self._admit(group, q, priority)
        except QueueFull:
            trace_mod.shed_record(ctx, label=group.label(),
                                  t_submit=t_submit,
                                  reason="queue_full",
                                  priority=priority)
            raise
        try:
            self.admission.charge(
                tenant, self._retry_after_ms(group, q, priority))
        except QuotaExceeded:
            # a quota shed is a rejection like any other: the SLO
            # stats and the rejected counter must agree with what the
            # client saw
            label = group.label()
            self.stats.record_rejected(label)
            metrics.inc("pifft_serve_rejected_total", shape=label)
            trace_mod.shed_record(ctx, label=label, t_submit=t_submit,
                                  reason="tenant_quota",
                                  priority=priority)
            raise
        self.router.record_placement(device, group, why, warmth, load)
        req = Request(rid=next(self._rid), group=group, xr=xr, xi=xi,
                      t_submit=t_submit,
                      future=asyncio.get_running_loop().create_future(),
                      priority=priority, tenant=tenant, trace=ctx)
        req.future.add_done_callback(
            lambda _f, t=tenant: self.admission.release(t))
        metrics.inc("pifft_serve_requests_total", shape=group.label())
        q.put_nowait(req)
        return await req.future

    def _ensure_device_worker(self, device: MeshDevice,
                              group: GroupKey) -> asyncio.Queue:
        q = device.queues.get(group)
        if q is None:
            q = device.queues[group] = asyncio.Queue()
            task = asyncio.get_running_loop().create_task(
                self._worker(group, q, device))
            device.workers[group] = task
            # register under the base maps too, so close()'s
            # sentinel fan-out and the orphan sweep cover the mesh
            self._queues[(device.id, group)] = q
            self._workers[(device.id, group)] = task
        return q

    # ------------------------------------------------------ execution

    def _is_device_failure(self, exc: Exception) -> bool:
        return isinstance(exc, (DeviceFailure, CollectiveAborted,
                                CollectiveTimeout))

    async def _invoke_batch(self, group: GroupKey, batch, rung,
                            device=None, level=None):
        """One batch on `device`: the per-device injection probe fires
        first (a fault there is the DEVICE dying, not the kernel —
        the batcher's fallback rungs never see it), then the device's
        own runner executes.  With ``batch_deadline_s`` set the whole
        call runs under the PR-8 supervisor, so a stalled device is
        ABORTED (CollectiveAborted) instead of wedging its worker —
        the r05 lesson applied to serving (docs/MULTICHIP.md)."""
        planes = [(r.xr, r.xi) for r in batch]
        links = self._batch_links(batch)
        cfg = self.config

        def execute():
            try:
                maybe_fault(device.site)
            except Exception as e:
                # the probe imitates the device dying under the batch:
                # classification happens in the failover handler
                raise DeviceFailure(device.id, e) from e
            t0 = clock()
            try:
                return device.runner.run(group, planes, rung,
                                         rung_tag=level, links=links)
            finally:
                dt = clock() - t0
                with device._busy_lock:
                    device.busy_s += dt

        if cfg.batch_deadline_s:
            def supervised():
                result, _report = supervise_collective(
                    execute, label=f"serve:{device.id}",
                    deadline_s=cfg.batch_deadline_s,
                    abort_waits=cfg.batch_abort_waits)
                return result

            call = supervised
        else:
            call = execute
        return await asyncio.get_running_loop().run_in_executor(
            None, call)

    async def _run_batch(self, group: GroupKey, batch, rung, level,
                         device=None):
        if device.state == "dead":
            # the device died under a sibling group's batch while this
            # one waited its worker's turn: evacuate, don't execute —
            # behind the same consensus the killing handler ran
            if device.consensus is not None:
                await device.consensus
            await self._reroute(list(batch), device, reason="failover")
            return
        token = object()
        device.inflight[token] = list(batch)
        try:
            await super()._run_batch(group, batch, rung, level, device)
        except Exception as e:
            if not self._is_device_failure(e):
                raise
            unacked = device.inflight.pop(token, list(batch))
            await self._handle_device_failure(device, unacked, e)
            return
        finally:
            device.inflight.pop(token, None)
        device.served += len(batch)

    # ------------------------------------------------------- failover

    async def _handle_device_failure(self, device: MeshDevice, batch,
                                     exc: Exception) -> None:
        """The self-healing path: mark the device dead (once), reach
        multihost consensus BEFORE any re-route (all hosts switch
        together — the PR-8 discipline), then move the dead device's
        queued AND in-flight-unacked requests to survivors, failover-
        tagged.  Concurrent failures on the SAME device (two groups'
        batches dying together) share ONE consensus: the first
        handler runs it, the rest await the same future — nobody
        re-routes ahead of the agreement.  Zero dropped requests:
        every evacuated future is re-enqueued or structurally
        failed."""
        loop = asyncio.get_running_loop()
        if device.state == "dead":
            stranded = []
        else:
            stranded = self._mark_dead(device, exc)
            from ..parallel import multihost

            device.consensus = loop.create_future()
            try:
                epoch = await loop.run_in_executor(
                    None,
                    functools.partial(
                        multihost.agree_on_fallback,
                        f"serve-mesh:{device.id}",
                        reason=f"{type(exc).__name__}: "
                               f"{str(exc)[:200]}"))
            except Exception as e:
                # a failed consensus (HostDesyncError) cannot be
                # allowed to strand the requests: re-route locally
                # and SAY so — on a single host there is nothing to
                # split, and a multihost operator sees the
                # fallback_consensus agreed=false event it already
                # emitted
                warn(f"serve-mesh consensus for {device.id} failed "
                     f"({type(e).__name__}: {str(e)[:120]}); "
                     f"re-routing locally")
                epoch = None
            device.consensus.set_result(epoch)
        epoch = await device.consensus if device.consensus is not None \
            else None
        await self._reroute(list(batch) + stranded, device,
                            reason="failover", epoch=epoch)

    def _mark_dead(self, device: MeshDevice, exc: Exception) -> list:
        """Synchronous state flip (atomic on the event loop): mark the
        device dead, strand its queued requests for re-routing, wake
        its workers to exit.  Returns the stranded requests."""
        device.state = "dead"
        kind = classify(exc).value
        metrics.inc("pifft_serve_device_failures_total",
                    device=device.id, kind=kind)
        events.emit("serve_device_failed", device=device.id, kind=kind,
                    error=f"{type(exc).__name__}: {str(exc)[:200]}")
        warn(f"mesh device {device.id} FAILED ({kind} "
             f"{type(exc).__name__}: {str(exc)[:120]}); re-routing its "
             f"queue to survivors")
        # a dead device's live-window keys are retired with it — the
        # /slo table reports survivors, not ghosts
        self.stats.retire(device=device.id)
        return self._evacuate_queues(device)

    @staticmethod
    def _evacuate_queues(device: MeshDevice) -> list:
        """Strand every queued request off `device` and wake its
        workers to exit (one sentinel per queue) — the shared sweep
        behind both the failover and the planned drain."""
        stranded = []
        for q in device.queues.values():
            while True:
                try:
                    item = q.get_nowait()
                except asyncio.QueueEmpty:
                    break
                if item is not _CLOSE:
                    stranded.append(item)
            q.put_nowait(_CLOSE)
        return stranded

    async def _reroute(self, requests, from_device: MeshDevice,
                       reason: str, epoch=None,
                       tag: bool = True) -> None:
        """Move admitted requests off `from_device` onto survivors.
        ``tag=True`` (failover) marks each request's degrade trail;
        a planned drain moves them untagged — the successor serves at
        full quality.  A re-route that CROSSES backend tags (a gpu
        device's queue landing on a cpu-native survivor) appends a
        second trail entry, ``failover:backend:<target tag>``, so the
        response says not just WHERE the request moved but onto WHICH
        hardware family (docs/BACKENDS.md) — appended only after the
        route succeeds, since the tag is the target's.  Admitted
        requests are NOT re-admitted (their slot moves with them);
        with no survivor left the future gets a structured
        :class:`NoDeviceAvailable`."""
        if not requests:
            return
        moved = stranded = crossed = 0
        t_move = clock()
        for req in requests:
            if req.future.done():
                continue
            if tag:
                req.trail.append(f"{reason}:{from_device.id}")
            if req.trace.live:
                # the re-route is an EXPLICIT span in the request's
                # own trace (obs/trace.py): the hop survives the
                # re-enqueue because it rides the Request, and a
                # failover-tagged tree is always emitted (the tail
                # upgrade), so a post-kill p99 outlier shows its hop
                req.marks.append((f"{reason}:{from_device.id}", t_move))
            try:
                target = self.router.route(req.group,
                                           exclude={from_device.id},
                                           reason=reason)
            except NoDeviceAvailable as e:
                req.future.set_exception(e)
                stranded += 1
                continue
            if tag and target.backend != from_device.backend:
                req.trail.append(f"{reason}:backend:{target.backend}")
                if req.trace.live:
                    req.marks.append(
                        (f"{reason}:backend:{target.backend}", t_move))
                crossed += 1
            q = self._ensure_device_worker(target, req.group)
            q.put_nowait(req)
            moved += 1
        if tag and (moved or stranded):
            # count what actually MOVED — already-resolved futures and
            # no-survivor failures must not inflate the failover
            # metric the observability story leans on
            if moved:
                metrics.inc("pifft_serve_failover_total",
                            value=float(moved), device=from_device.id)
            if crossed:
                metrics.inc("pifft_serve_failover_cross_backend_total",
                            value=float(crossed),
                            device=from_device.id)
            events.emit("serve_failover", device=from_device.id,
                        requests=moved,
                        **({"stranded": stranded} if stranded else {}),
                        **({"cross_backend": crossed} if crossed
                           else {}),
                        **({"epoch": epoch} if epoch is not None
                           else {}),
                        reason=reason)

    # ---------------------------------------------------------- drain

    async def drain_device(self, device_id: str,
                           journal_path: Optional[str] = None) -> dict:
        """Planned drain with WARM-CACHE HANDOFF (docs/SERVING.md):

        1. mark the device ``draining`` (the router stops placing);
        2. push every warm group's tuned plan entries — the compiled
           executors and warmth marks — to a successor, journaling
           each handoff BEFORE the queue moves (a kill mid-drain
           resumes: journaled groups are not re-handed);
        3. move the queued requests to the successors (untagged — a
           planned move is not degradation);
        4. let in-flight batches finish and the workers join;
        5. mark ``drained`` and journal completion.

        Returns the drain report.  `journal_path` defaults to
        ``config.handoff_journal``; with neither set the drain runs
        unjournaled (tests and ad-hoc ops)."""
        device = self.device(device_id)
        if device.state not in ("healthy", "draining"):
            raise ServeError(f"device {device_id} is {device.state}; "
                             f"only a healthy/draining device drains")
        loop = asyncio.get_running_loop()
        device.state = "draining"
        path = journal_path or self.config.handoff_journal
        journal = Journal(path) if path else None
        if journal is not None:
            # journal I/O is sync file I/O: keep it off the event loop
            await loop.run_in_executor(None, journal.load)
        report = {"device": device.id, "handoffs": [], "resumed": 0,
                  "moved": 0, "journal": path}
        groups = device.warm_groups | device.runner.cached_groups()
        for group in sorted(groups, key=lambda g: g.label()):
            cell = f"handoff:{device.id}:{group.label()}"
            if journal is not None and journal.has(cell):
                report["resumed"] += 1
                continue
            successor = self.router.route(group,
                                          exclude={device.id},
                                          reason="handoff")
            adopted = successor.runner.adopt_callables(device.runner,
                                                      group)
            successor.warm_groups.add(group)
            metrics.inc("pifft_serve_handoff_total", device=device.id)
            events.emit("serve_handoff", device=device.id,
                        successor=successor.id, shape=group.label(),
                        adopted=adopted)
            if journal is not None:
                await loop.run_in_executor(
                    None, functools.partial(
                        journal.record, cell,
                        {"successor": successor.id,
                         "adopted": adopted}))
            report["handoffs"].append({"group": group.label(),
                                       "successor": successor.id,
                                       "adopted": adopted})
        # the queue moves AFTER the caches: the successor is warm by
        # the time the first moved request reaches it
        moved = self._evacuate_queues(device)
        report["moved"] = len(moved)
        await self._reroute(moved, device, reason="handoff", tag=False)
        if device.workers:
            await asyncio.gather(*device.workers.values(),
                                 return_exceptions=True)
        device.state = "drained"
        # a drained device's live-window keys will never fill again:
        # retire them so the /slo table stops carrying zero-count rows
        self.stats.retire(device=device.id)
        if self.fleet_tap is not None:
            # prewarm-at-handoff (docs/FLEET.md): persist the arrival
            # model beside the plan cache NOW, while the handed-off
            # warmth is fresh — the rolling restart that follows a
            # drain reloads it and warms every previously-hot group
            await loop.run_in_executor(None, self.fleet_tap.save)
        if journal is not None:
            await loop.run_in_executor(
                None, functools.partial(journal.record,
                                        f"drained:{device.id}",
                                        {"moved": len(moved)}))
        events.emit("serve_drain_complete", device=device.id,
                    handoffs=len(report["handoffs"]),
                    resumed=report["resumed"], moved=len(moved))
        warn(f"mesh device {device.id} drained: "
             f"{len(report['handoffs'])} group(s) handed off"
             + (f" ({report['resumed']} resumed from journal)"
                if report["resumed"] else "")
             + f", {len(moved)} queued request(s) moved")
        return report
