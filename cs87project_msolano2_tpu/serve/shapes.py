"""The served shape set: which transform shapes this process answers
for, and the warm startup path that pre-resolves their plans.

A serving session must reach its first response on a warm plan-cache
hit — tuning (or even static-default resolution + first trace) inside
a request's latency budget is exactly the cold-start spike every
inference stack's warmup pass exists to avoid.  The shape set is a
JSONL file, one shape per line:

    {"n": 1048576, "batch": [], "layout": "pi", "precision": "split3"}
    {"n": 4096}                  # defaults: batch=(), natural, split3, c2c
    {"n": 4096, "domain": "r2c"}  # half-spectrum real shape (docs/REAL.md)
    {"n": 4096, "precision": "bf16"}  # bytes-halving bf16 storage
                                      # (docs/PRECISION.md)
    {"n": 4096, "op": "conv"}    # fused spectral conv group — warms
                                 # both half-spectrum plans and the
                                 # fused executor (docs/APPS.md);
                                 # an UNKNOWN op is refused with a
                                 # structured error, never silently
                                 # warmed as a bare FFT

``pifft plan warm --shapes FILE`` warms the whole set in one call
(instead of one ``plan warm`` invocation per shape), and
``Dispatcher.warm()`` runs the same function at serve startup.  The
policy is :func:`plans.tune_or_static`: tune where the hardware can
answer, serve the measured-good static default otherwise — an offline
(CPU) serving session never dies for lack of a tuner.
"""

from __future__ import annotations

import dataclasses
import json

from .. import plans

#: hard admission cap on served transform lengths (front door AND
#: shape files): any n >= 2 below this is a plan — power of two on
#: the kernel ladder, everything else on the any-length ladder
#: (docs/PLANS.md "Arbitrary n").  The cap bounds per-request device
#: memory exactly like the batch buckets bound batch dims; an over-cap
#: n is a structured refusal, never an OOM mid-plan.
MAX_SERVED_N = 1 << 24


@dataclasses.dataclass(frozen=True)
class ShapeSpec:
    """One served transform shape: everything needed to build its
    PlanKey except the device kind (resolved at warm time, so one
    shape file serves every host).  ``domain`` declares the transform
    family: "c2c" (default) or the half-spectrum real paths
    "r2c"/"c2r" — n is the real-side length either way
    (docs/REAL.md).  ``op`` declares the served OPERATION
    (docs/APPS.md): "fft" (default) or the fused spectral ops
    "conv"/"corr"/"solve" — an op shape warms BOTH the forward and
    inverse half-spectrum plans its fused pipeline rides.  An unknown
    op is a structured refusal, never silently warmed as a bare
    FFT."""

    n: int
    batch: tuple = ()
    layout: str = "natural"
    precision: str = "split3"
    domain: str = "c2c"
    op: str = "fft"

    def __post_init__(self):
        if self.n < 2 or self.n > MAX_SERVED_N:
            raise ValueError(f"served n={self.n} must be 2 <= n <= "
                             f"{MAX_SERVED_N} (any length in range is "
                             f"a plan — docs/PLANS.md 'Arbitrary n')")
        if self.layout == "pi" and self.n & (self.n - 1):
            raise ValueError(f"layout='pi' requires a power-of-two n "
                             f"(bit-reversed order is undefined "
                             f"otherwise), got n={self.n}")
        from ..plans.core import DOMAINS
        from ..utils.roofline import SPECTRAL_OPS

        if self.domain not in DOMAINS:
            raise ValueError(f"served domain={self.domain!r} not in "
                             f"{DOMAINS}")
        if self.op not in SPECTRAL_OPS:
            raise ValueError(f"served op={self.op!r} not in "
                             f"{SPECTRAL_OPS} (docs/APPS.md) — an "
                             f"unknown op must be refused, not warmed "
                             f"as a bare FFT")
        if self.domain != "c2c" and self.layout != "natural":
            raise ValueError(f"domain={self.domain!r} requires natural "
                             f"layout (the half-spectrum has no pi "
                             f"order)")
        if self.op != "fft":
            if self.layout != "natural":
                raise ValueError(f"op={self.op!r} requires natural "
                                 f"layout (docs/APPS.md)")
            if self.domain not in ("c2c", "r2c"):
                raise ValueError(f"op={self.op!r} rides the "
                                 f"half-spectrum forward path; "
                                 f"domain={self.domain!r} does not "
                                 f"apply")
            # normalize to the domain the op's GroupKey actually
            # carries, so strict-shape membership and SLO labels agree
            # with the dispatcher's keying
            object.__setattr__(self, "domain", "r2c")

    @classmethod
    def from_record(cls, rec: dict) -> "ShapeSpec":
        if not isinstance(rec, dict) or "n" not in rec:
            raise ValueError(f"shape record needs at least an 'n' field, "
                             f"got {rec!r}")
        return cls(
            n=int(rec["n"]),
            batch=tuple(int(b) for b in rec.get("batch") or ()),
            layout=rec.get("layout", "natural"),
            precision=rec.get("precision") or "split3",
            domain=rec.get("domain") or "c2c",
            op=rec.get("op") or "fft",
        )

    def to_record(self) -> dict:
        return {"n": self.n, "batch": list(self.batch),
                "layout": self.layout, "precision": self.precision,
                "domain": self.domain, "op": self.op}

    def key(self) -> plans.PlanKey:
        """The PlanKey this shape resolves to on the current device
        (an op shape's PRIMARY key — the forward r2c plan its fused
        pipeline enters through; :func:`warm` also resolves the c2r
        side)."""
        domain = "r2c" if self.op != "fft" else self.domain
        return plans.make_key(self.n, self.batch, layout=self.layout,
                              precision=self.precision,
                              domain=domain)

    def label(self) -> str:
        """Stable human/metric label (the per-shape SLO row key).  The
        domain column rides every non-c2c label so a half-spectrum SLO
        row is never mistaken for its full-spectrum sibling at the
        same n; the op column rides every non-fft label the same
        way (matching GroupKey.label for batch-free shapes)."""
        b = "x".join(str(d) for d in self.batch) + "x" if self.batch else ""
        d = f":{self.domain}" if self.domain != "c2c" else ""
        d += f":{self.op}" if self.op != "fft" else ""
        return f"{b}{self.n}:{self.layout}:{self.precision}{d}"


def load_shapes(path: str) -> list:
    """Parse a shape-set JSONL file.  Blank lines and ``#`` comment
    lines are skipped; a malformed line is an error naming its line
    number (a silently dropped shape would serve cold later — the
    failure mode warming exists to prevent)."""
    specs, seen = [], set()
    with open(path, encoding="utf-8") as fh:
        for lineno, line in enumerate(fh, start=1):
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            try:
                spec = ShapeSpec.from_record(json.loads(line))
            except (ValueError, TypeError, json.JSONDecodeError) as e:
                raise ValueError(
                    f"{path}:{lineno}: bad shape record: {e}") from e
            if spec in seen:
                continue  # duplicates warm once
            seen.add(spec)
            specs.append(spec)
    if not specs:
        raise ValueError(f"{path}: no shapes (every line blank/comment)")
    return specs


def warm(specs, force: bool = False, verbose: bool = False) -> list:
    """Resolve (tune where possible, static default otherwise) and
    memoize the plan for every spec — the one-call warm path behind
    ``pifft plan warm --shapes`` and serve startup.  Returns the plans
    in spec order.  Warming also primes each plan's executor, so the
    first real request pays dispatch, not trace."""
    out = []
    for spec in specs:
        plan = plans.tune_or_static(spec.key(), force=force,
                                    verbose=verbose)
        plan.fn  # build (and cache) the executor now, not per-request
        if spec.op != "fft":
            # an op shape's fused pipeline rides BOTH half-spectrum
            # directions: resolve the c2r side too, and build the
            # fused executor so the first request pays dispatch
            inv_plan = plans.tune_or_static(
                plans.make_key(spec.n, spec.batch, layout=spec.layout,
                               precision=spec.precision, domain="c2r"),
                force=force, verbose=verbose)
            inv_plan.fn
            from ..apps.spectral import op_executor

            op_executor(spec.op, spec.batch, spec.n,
                        precision=spec.precision)
        from ..obs import events

        events.emit("serve_warm", cell={"n": spec.n,
                                        "variant": plan.variant},
                    shape=spec.label(), source=plan.source,
                    op=spec.op)
        out.append(plan)
    return out
