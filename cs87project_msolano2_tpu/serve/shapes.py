"""The served shape set: which transform shapes this process answers
for, and the warm startup path that pre-resolves their plans.

A serving session must reach its first response on a warm plan-cache
hit — tuning (or even static-default resolution + first trace) inside
a request's latency budget is exactly the cold-start spike every
inference stack's warmup pass exists to avoid.  The shape set is a
JSONL file, one shape per line:

    {"n": 1048576, "batch": [], "layout": "pi", "precision": "split3"}
    {"n": 4096}                  # defaults: batch=(), natural, split3, c2c
    {"n": 4096, "domain": "r2c"}  # half-spectrum real shape (docs/REAL.md)
    {"n": 4096, "precision": "bf16"}  # bytes-halving bf16 storage
                                      # (docs/PRECISION.md)

``pifft plan warm --shapes FILE`` warms the whole set in one call
(instead of one ``plan warm`` invocation per shape), and
``Dispatcher.warm()`` runs the same function at serve startup.  The
policy is :func:`plans.tune_or_static`: tune where the hardware can
answer, serve the measured-good static default otherwise — an offline
(CPU) serving session never dies for lack of a tuner.
"""

from __future__ import annotations

import dataclasses
import json

from .. import plans


@dataclasses.dataclass(frozen=True)
class ShapeSpec:
    """One served transform shape: everything needed to build its
    PlanKey except the device kind (resolved at warm time, so one
    shape file serves every host).  ``domain`` declares the transform
    family: "c2c" (default) or the half-spectrum real paths
    "r2c"/"c2r" — n is the real-side length either way
    (docs/REAL.md)."""

    n: int
    batch: tuple = ()
    layout: str = "natural"
    precision: str = "split3"
    domain: str = "c2c"

    def __post_init__(self):
        if self.n < 2 or self.n & (self.n - 1):
            raise ValueError(f"served n={self.n} must be a power of two "
                             f">= 2 (the plan ladder's domain)")
        from ..plans.core import DOMAINS

        if self.domain not in DOMAINS:
            raise ValueError(f"served domain={self.domain!r} not in "
                             f"{DOMAINS}")
        if self.domain != "c2c" and self.layout != "natural":
            raise ValueError(f"domain={self.domain!r} requires natural "
                             f"layout (the half-spectrum has no pi "
                             f"order)")

    @classmethod
    def from_record(cls, rec: dict) -> "ShapeSpec":
        if not isinstance(rec, dict) or "n" not in rec:
            raise ValueError(f"shape record needs at least an 'n' field, "
                             f"got {rec!r}")
        return cls(
            n=int(rec["n"]),
            batch=tuple(int(b) for b in rec.get("batch") or ()),
            layout=rec.get("layout", "natural"),
            precision=rec.get("precision") or "split3",
            domain=rec.get("domain") or "c2c",
        )

    def to_record(self) -> dict:
        return {"n": self.n, "batch": list(self.batch),
                "layout": self.layout, "precision": self.precision,
                "domain": self.domain}

    def key(self) -> plans.PlanKey:
        """The PlanKey this shape resolves to on the current device."""
        return plans.make_key(self.n, self.batch, layout=self.layout,
                              precision=self.precision,
                              domain=self.domain)

    def label(self) -> str:
        """Stable human/metric label (the per-shape SLO row key).  The
        domain column rides every non-c2c label so a half-spectrum SLO
        row is never mistaken for its full-spectrum sibling at the
        same n."""
        b = "x".join(str(d) for d in self.batch) + "x" if self.batch else ""
        d = f":{self.domain}" if self.domain != "c2c" else ""
        return f"{b}{self.n}:{self.layout}:{self.precision}{d}"


def load_shapes(path: str) -> list:
    """Parse a shape-set JSONL file.  Blank lines and ``#`` comment
    lines are skipped; a malformed line is an error naming its line
    number (a silently dropped shape would serve cold later — the
    failure mode warming exists to prevent)."""
    specs, seen = [], set()
    with open(path, encoding="utf-8") as fh:
        for lineno, line in enumerate(fh, start=1):
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            try:
                spec = ShapeSpec.from_record(json.loads(line))
            except (ValueError, TypeError, json.JSONDecodeError) as e:
                raise ValueError(
                    f"{path}:{lineno}: bad shape record: {e}") from e
            if spec in seen:
                continue  # duplicates warm once
            seen.add(spec)
            specs.append(spec)
    if not specs:
        raise ValueError(f"{path}: no shapes (every line blank/comment)")
    return specs


def warm(specs, force: bool = False, verbose: bool = False) -> list:
    """Resolve (tune where possible, static default otherwise) and
    memoize the plan for every spec — the one-call warm path behind
    ``pifft plan warm --shapes`` and serve startup.  Returns the plans
    in spec order.  Warming also primes each plan's executor, so the
    first real request pays dispatch, not trace."""
    out = []
    for spec in specs:
        plan = plans.tune_or_static(spec.key(), force=force,
                                    verbose=verbose)
        plan.fn  # build (and cache) the executor now, not per-request
        from ..obs import events

        events.emit("serve_warm", cell={"n": spec.n,
                                        "variant": plan.variant},
                    shape=spec.label(), source=plan.source)
        out.append(plan)
    return out
