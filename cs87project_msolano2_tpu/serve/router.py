"""Mesh routing and admission: where a request lands, and whether it
gets in at all (docs/SERVING.md, mesh section).

**Shape-affinity routing.**  The plan cache is a placement signal: a
device that has already compiled (or been warmed/handed) a GroupKey's
executor serves that group's next batch with zero trace cost, so the
router sends requests where the group is already WARM.  Warmth is read
from the existing per-device plan/executor and buffer state — never a
side channel:

* ``3`` (hot)   — the device's :class:`~.batcher.BatchRunner` holds a
  compiled callable for the group (``cached_groups()``);
* ``2`` (warm)  — the group was warmed onto (or handed to) the device
  (``warm_groups``);
* ``1`` (tepid) — the device's :class:`~.buffers.BufferPool` still
  pools a staging pair of the group's input width (weak: same-width
  sibling groups alias, so this never outranks an explicit warmth);
* ``0`` (cold)  — nothing.

Ties (same warmth) break to the LEAST-LOADED device (queued +
in-flight), then the lowest index for determinism.  Every placement is
emitted as a ``serve_placement`` event and counted in
``pifft_serve_placement_total{device,reason}`` — the counter the mesh
smoke asserts affinity on.

**Priority admission** rides the class tables in
:mod:`.dispatcher` (``PRIORITY_ADMIT_FILL`` / ``PRIORITY_RETRY_SCALE``:
low sheds first, backs off hardest).  This module adds the
**multi-tenant quota** layer: :class:`AdmissionController` bounds each
tenant's OUTSTANDING requests (queued + in-flight, released when the
response future resolves), so one tenant's burst cannot occupy every
queue slot in the mesh — the rejection is a structured
:class:`QuotaExceeded` (a :class:`~.dispatcher.QueueFull` subclass, so
clients treat it as backpressure) naming the tenant and its limit.
"""

from __future__ import annotations

from typing import Optional

from ..obs import events, metrics
from .batcher import GroupKey
from .dispatcher import QueueFull, ServeError


class NoDeviceAvailable(ServeError):
    """Every mesh device is dead or draining: nothing can serve the
    request.  Structured — the caller learns the mesh is gone, it is
    never silently dropped."""

    code = "no_device_available"


class QuotaExceeded(QueueFull):
    """Per-tenant quota admission rejection: the tenant already has its
    quota of outstanding requests in the mesh.  A ``QueueFull``
    subclass — backpressure with a retry hint — that additionally
    names the tenant and limit."""

    code = "tenant_quota"

    def __init__(self, msg: str, retry_after_ms: float, tenant: str,
                 quota: int):
        super().__init__(msg, retry_after_ms)
        self.tenant = tenant
        self.quota = quota

    def extras(self) -> dict:
        return {**super().extras(), "tenant": self.tenant,
                "quota": self.quota}


class Router:
    """Shape-affinity placement over a list of
    :class:`~.mesh.MeshDevice` (docs/SERVING.md)."""

    def __init__(self, devices):
        self.devices = list(devices)
        #: the DESIGNATED CANARY device id, or None: the fleet canary
        #: racer (docs/FLEET.md) sets it so production traffic never
        #: lands there — the mirrored (shadowed, non-served) candidate
        #: re-race owns the device until it is released
        self.canary: Optional[str] = None

    def set_canary(self, device_id: Optional[str]) -> None:
        """Designate (or with None, release) the canary device.
        Designation is a routing statement only — the device stays
        healthy, its queues keep draining; it just receives no NEW
        production placements while the shadow race runs."""
        self.canary = device_id

    def candidates(self, exclude=()) -> list:
        return [d for d in self.devices
                if d.state == "healthy" and d.id not in exclude
                and d.id != self.canary]

    def choose(self, group: GroupKey, exclude=(),
               reason: Optional[str] = None) -> tuple:
        """``(device, why, warmth, load)`` for this group's next batch
        — the decision WITHOUT the recording, so admission can still
        reject the request before a placement is counted.  One pass:
        warmth and load are read once per device (warmth rebuilds the
        runner/pool views and takes the pool lock, so the hot path
        must not evaluate it twice)."""
        pool = self.candidates(exclude)
        if not pool:
            raise NoDeviceAvailable(
                f"no healthy device for {group.label()}: "
                f"{len(self.devices)} device(s), none serving")
        scored = [(-d.warmth(group), d.load(), d.index, d)
                  for d in pool]
        neg_warmth, load, _idx, device = min(scored)
        why = reason or ("affinity" if -neg_warmth > 0
                         else "least_loaded")
        return device, why, -neg_warmth, load

    def record_placement(self, device, group: GroupKey, why: str,
                         warmth: int, load: int) -> None:
        """Count + emit one ADMITTED placement (the counter the mesh
        smoke asserts affinity on — a rejected request must not
        inflate it)."""
        metrics.inc("pifft_serve_placement_total", device=device.id,
                    reason=why)
        events.emit("serve_placement", cell={"n": group.n},
                    device=device.id, shape=group.label(),
                    reason=why, warmth=warmth, load=load)

    def route(self, group: GroupKey, exclude=(),
              reason: Optional[str] = None, record: bool = True):
        """The device this group's next batch should land on.

        `exclude` removes devices by id (the failover path excludes
        the dead device it is evacuating).  `reason` overrides the
        recorded placement reason (``failover`` / ``handoff``);
        otherwise it is ``affinity`` when warmth decided, else
        ``least_loaded``.  ``record=False`` previews the choice
        without emitting the placement event/counter (the chaos
        driver picks its victim that way)."""
        device, why, warmth, load = self.choose(group, exclude, reason)
        if record:
            self.record_placement(device, group, why, warmth, load)
        return device


class AdmissionController:
    """Per-tenant outstanding-request quotas.  ``quota=None`` disables
    enforcement (occupancy is still tracked for the stats surface)."""

    def __init__(self, quota: Optional[int] = None):
        self.quota = quota
        self._outstanding: dict = {}

    def charge(self, tenant: str, retry_after_ms: float) -> None:
        """Admit one request for `tenant` or raise
        :class:`QuotaExceeded`.  The caller MUST pair every successful
        charge with a :meth:`release` (the dispatcher hooks it on the
        response future)."""
        held = self._outstanding.get(tenant, 0)
        if self.quota is not None and held >= self.quota:
            metrics.inc("pifft_serve_quota_rejected_total",
                        tenant=tenant)
            events.emit("serve_quota_reject", tenant=tenant,
                        outstanding=held, quota=self.quota,
                        retry_after_ms=retry_after_ms)
            raise QuotaExceeded(
                f"tenant {tenant!r} holds {held}/{self.quota} "
                f"outstanding requests; retry in ~{retry_after_ms} ms",
                retry_after_ms=retry_after_ms, tenant=tenant,
                quota=self.quota)
        self._outstanding[tenant] = held + 1

    def release(self, tenant: str) -> None:
        held = self._outstanding.get(tenant, 0)
        if held <= 1:
            self._outstanding.pop(tenant, None)
        else:
            self._outstanding[tenant] = held - 1

    def outstanding(self, tenant: Optional[str] = None):
        if tenant is not None:
            return self._outstanding.get(tenant, 0)
        return dict(self._outstanding)
