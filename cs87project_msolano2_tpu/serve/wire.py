"""The binary wire dialect: fixed-header frames, zero-copy payloads.

The JSON front (:mod:`.protocol`) is the protocol seam, not a
throughput record — at 2^20 floats per request, parsing JSON float
lists costs more than the FFT it feeds, and the PR-15 tail attribution
pins the served p99 on the queue/parse phase.  This module is the
replacement hot path: a versioned little-endian header followed by the
raw float planes, laid out so the server can land client bytes
directly as ``np.frombuffer`` views (dlpack-compatible contiguous
float32) with **zero intermediate copies** — no ``json.loads``, no
per-element Python floats.

Frame layout (``HEADER``, 48 bytes, little-endian)::

    offset  size  field        meaning
    0       4     magic        b"PIFB"
    4       2     version      wire version (1)
    6       2     flags        F_* bits below
    8       1     msg_type     MSG_* below
    9       1     op           index into WIRE_OPS
    10      1     domain       index into WIRE_DOMAINS
    11      1     precision    index into WIRE_PRECISIONS (0 = unset)
    12      1     priority     index into WIRE_PRIORITIES
    13      1     inverse      0/1
    14      1     dtype        0 = float32, 1 = bfloat16 (wire storage)
    15      1     (pad)        zero
    16      8     rid          request id (client-chosen, echoed back)
    24      4     n            transform length
    28      4     width        plane width in elements (n//2+1 for c2r)
    32      4     extras_len   UTF-8 JSON metadata blob length
    36      4     slot         shm slot index / stream chunk seq /
                               HELLO_ACK credit window
    40      8     payload_len  raw plane bytes after the extras blob

A frame is ``header + extras + payload``.  ``extras`` is a *small*
JSON metadata blob (tenant, trace context, response latency split) —
variable-length metadata without per-element cost; it is bounded by
``MAX_EXTRAS_BYTES`` and is NOT plane payload, so it is not charged to
the host-copy meter (below).  ``payload`` is the contiguous float
planes: ``xr`` then ``xi`` (``F_NO_XI`` when the imaginary plane is
absent), each ``width`` elements of the wire dtype.

Negotiation: the JSON dialect's length prefix is a 4-byte big-endian
length capped at ``protocol.MAX_FRAME_BYTES`` (2^28); ``b"PIFB"`` read
as a big-endian u32 is ~1.35e9, far above the cap, so the first four
bytes of a connection decide the dialect unambiguously.  A binary
client opens with HELLO (its max version); the server answers
HELLO_ACK with the negotiated version and the flow-control credit
window (``slot``), plus the shm lane grant when negotiated.  A HELLO
with an unsupported version is answered with a JSON frame — the
connection FALLS BACK to the JSON dialect, with a structured
``serve_wire_fallback`` warning event; a malformed binary header
closes the connection with ``serve_conn_lost``; a frame truncated
mid-payload is a tolerated client disconnect, never a hang.

Flow control: the HELLO_ACK's credit window bounds in-flight requests
per connection.  A request consumes one credit; any terminal reply
(RESPONSE, ERROR, STREAM_END) returns it.  A client exceeding the
window gets a structured ``flow_control`` ERROR for the offending rid
— the connection survives, nothing hangs.

The host-copy meter: ``pifft_host_copy_bytes_total{site}`` charges
every sanctioned copy of PLANE PAYLOAD bytes on the serve front —
the JSON dialect's decode/encode (the whole body is parsed into
Python objects), the bfloat16 wire upcast, and streaming-chunk
reassembly.  The binary float32 path charges ZERO: that is the
wire-smoke acceptance, read from the meter, not the code.  Check rule
PIF117 (docs/CHECKS.md) keeps copying decodes out of the hot path
statically: a decode call in serve/protocol.py or serve/buffers.py is
only legal beside a :func:`charge_host_copy` call.
"""

from __future__ import annotations

import asyncio
import json
import struct
from typing import Optional

import numpy as np

MAGIC = b"PIFB"
WIRE_VERSION = 1

#: header: magic, version, flags, msg_type, op, domain, precision,
#: priority, inverse, dtype, pad, rid, n, width, extras_len, slot,
#: payload_len  (module docstring has the offset table)
HEADER = struct.Struct("<4sHHBBBBBBBBQIIIIQ")

#: metadata blob cap: extras are tenant/trace/latency metadata, never
#: plane data — a kilobyte-scale bound keeps a hostile header from
#: turning the metadata lane into an allocation vector
MAX_EXTRAS_BYTES = 1 << 16

#: plane payload cap (matches the JSON front's frame cap rationale)
MAX_PAYLOAD_BYTES = 1 << 30

#: transform-length / plane-width caps: ``n`` and ``width`` are header
#: fields a hostile client picks and downstream code spends as
#: ``frombuffer`` counts and staging sizes, so they are bounds-checked
#: HERE, at the decode boundary, before any size arithmetic sees them
#: (check rule PIF118).  Two float32 planes of ``width`` elements must
#: fit the payload cap; ``n`` bounds the transform any dispatcher
#: would admit.
MAX_WIRE_N = 1 << 28
MAX_WIRE_WIDTH = MAX_PAYLOAD_BYTES // 8

#: shm grant caps: HELLO_ACK reuses ``n``/``width`` as slot count and
#: slot bytes; a client must not size its free-slot list or map a ring
#: from a hostile server's numbers unchecked
MAX_SHM_SLOTS = 4096

#: per-connection flow-control window granted in HELLO_ACK
DEFAULT_CREDITS = 32

#: streaming responses chunk the payload at this size (overlap-save
#: results are long; a chunk bounds client reassembly buffers)
STREAM_CHUNK_BYTES = 1 << 18

# message types
MSG_HELLO = 1
MSG_HELLO_ACK = 2
MSG_REQUEST = 3
MSG_RESPONSE = 4
MSG_ERROR = 5
MSG_STREAM_CHUNK = 6
MSG_STREAM_END = 7
MSG_PING = 8
MSG_PONG = 9

# flags
F_NO_XI = 1 << 0      #: request/response carries only the real plane
F_PI = 1 << 1         #: pi layout (natural otherwise)
F_SHM = 1 << 2        #: payload lives in shm slot ``slot``, not inline
F_STREAM = 1 << 3     #: request: the client accepts chunked responses
F_DEGRADED = 1 << 4   #: response: served degraded (trail in extras)
F_WANT_SHM = 1 << 5   #: HELLO: client asks for the shm lane

# wire dtypes
DTYPE_F32 = 0
DTYPE_BF16 = 1

#: FROZEN wire vocabularies — indexes travel the wire, so these tuples
#: are part of wire version 1 and may only grow, never reorder
WIRE_OPS = ("fft", "conv", "corr", "solve")
WIRE_DOMAINS = ("c2c", "r2c", "c2r")
WIRE_PRECISIONS = ("", "bf16", "default", "split3", "highest", "fp32")
WIRE_PRIORITIES = ("low", "normal", "high")


class WireError(ValueError):
    """A malformed or out-of-contract binary frame."""


def _nbytes(buf) -> int:
    return buf.nbytes if isinstance(buf, memoryview) else len(buf)


def as_bytes_view(arr: np.ndarray) -> memoryview:
    """The array's memory as a flat byte view — what the transport
    writes, with no Python-level copy."""
    return memoryview(arr).cast("B")


def charge_host_copy(nbytes: int, site: str) -> None:
    """Charge one sanctioned host copy of plane-payload bytes to the
    ``pifft_host_copy_bytes_total`` meter.

    Every place the serve front copies request/response PLANE bytes on
    the host (JSON decode/encode, the bfloat16 wire upcast, streaming
    reassembly) charges here, so the meter is the ground truth the
    wire-smoke asserts a zero delta on for the binary float32 path —
    and check rule PIF117 demands this call beside any copying decode
    in the hot-path modules."""
    from ..obs import metrics

    metrics.inc("pifft_host_copy_bytes_total", float(nbytes), site=site)


def count_frame(protocol: str, direction: str = "in") -> None:
    """Per-protocol front-door traffic counter
    (``pifft_serve_wire_frames_total{protocol,direction}``)."""
    from ..obs import metrics

    metrics.inc("pifft_serve_wire_frames_total", protocol=protocol,
                direction=direction)


def _index(value: str, vocab, field: str) -> int:
    try:
        return vocab.index(value)
    except ValueError:
        raise WireError(f"{field}={value!r} is not in the wire "
                        f"vocabulary {vocab}") from None


def _lookup(idx: int, vocab, field: str) -> str:
    if not 0 <= idx < len(vocab):
        raise WireError(f"{field} index {idx} out of range for {vocab}")
    return vocab[idx]


class Frame:
    """One decoded binary frame (header fields + extras + payload)."""

    __slots__ = ("msg_type", "flags", "op", "domain", "precision",
                 "priority", "inverse", "dtype", "rid", "n", "width",
                 "slot", "extras", "payload", "version")

    def __init__(self, msg_type, flags, op, domain, precision,
                 priority, inverse, dtype, rid, n, width, slot,
                 extras, payload, version=WIRE_VERSION):
        self.msg_type = msg_type
        self.flags = flags
        self.op = op
        self.domain = domain
        self.precision = precision
        self.priority = priority
        self.inverse = inverse
        self.dtype = dtype
        self.rid = rid
        self.n = n
        self.width = width
        self.slot = slot
        self.extras = extras
        self.payload = payload
        self.version = version


def encode_frame(msg_type: int, *, flags: int = 0, op: str = "fft",
                 domain: str = "c2c", precision: Optional[str] = None,
                 priority: str = "normal", inverse: bool = False,
                 dtype: int = DTYPE_F32, rid: int = 0, n: int = 0,
                 width: int = 0, slot: int = 0,
                 extras: Optional[dict] = None,
                 payload: bytes = b"",
                 version: int = WIRE_VERSION) -> list:
    """Header + extras + payload as a list of buffers.

    Returned as separate buffers (not concatenated) so callers can
    hand numpy plane memory straight to ``writer.write`` without a
    Python-level join copy."""
    blob = b""
    if extras:
        blob = json.dumps(extras, separators=(",", ":")).encode("utf-8")
        if len(blob) > MAX_EXTRAS_BYTES:
            raise WireError(f"extras blob {len(blob)} bytes exceeds "
                            f"the {MAX_EXTRAS_BYTES}-byte cap")
    payload_len = sum(_nbytes(p) for p in payload) \
        if isinstance(payload, (list, tuple)) else _nbytes(payload)
    if payload_len > MAX_PAYLOAD_BYTES:
        raise WireError(f"payload {payload_len} bytes exceeds the "
                        f"{MAX_PAYLOAD_BYTES}-byte cap")
    head = HEADER.pack(
        MAGIC, version, flags, msg_type,
        _index(op, WIRE_OPS, "op"),
        _index(domain, WIRE_DOMAINS, "domain"),
        _index(precision or "", WIRE_PRECISIONS, "precision"),
        _index(priority, WIRE_PRIORITIES, "priority"),
        1 if inverse else 0, dtype, 0, rid, n, width, len(blob), slot,
        payload_len)
    out = [head]
    if blob:
        out.append(blob)
    if isinstance(payload, (list, tuple)):
        out.extend(p for p in payload if _nbytes(p))
    elif _nbytes(payload):
        out.append(payload)
    return out


def parse_header(head: bytes) -> Frame:
    """A :class:`Frame` from 48 header bytes.  ``extras`` and
    ``payload`` hold the BYTE COUNTS still on the wire (ints) — the
    frame reader replaces them with the decoded blob and raw bytes.
    Raises :class:`WireError` on anything out of contract — the server
    answers that with ``serve_conn_lost`` + close, never a hang."""
    (magic, version, flags, msg_type, op_i, dom_i, prec_i, prio_i,
     inverse, dtype, _pad, rid, n, width, extras_len, slot,
     payload_len) = HEADER.unpack(head)
    if magic != MAGIC:
        raise WireError(f"bad magic {magic!r}")
    if msg_type not in (MSG_HELLO, MSG_HELLO_ACK, MSG_REQUEST,
                        MSG_RESPONSE, MSG_ERROR, MSG_STREAM_CHUNK,
                        MSG_STREAM_END, MSG_PING, MSG_PONG):
        raise WireError(f"unknown msg_type {msg_type}")
    if dtype not in (DTYPE_F32, DTYPE_BF16):
        raise WireError(f"unknown wire dtype {dtype}")
    if extras_len > MAX_EXTRAS_BYTES:
        raise WireError(f"extras_len {extras_len} exceeds the "
                        f"{MAX_EXTRAS_BYTES}-byte cap")
    if payload_len > MAX_PAYLOAD_BYTES:
        raise WireError(f"payload_len {payload_len} exceeds the "
                        f"{MAX_PAYLOAD_BYTES}-byte cap")
    if n > MAX_WIRE_N:
        raise WireError(f"n {n} exceeds the {MAX_WIRE_N} cap")
    if width > MAX_WIRE_WIDTH:
        raise WireError(f"width {width} exceeds the "
                        f"{MAX_WIRE_WIDTH} cap")
    return Frame(
        msg_type, flags,
        _lookup(op_i, WIRE_OPS, "op"),
        _lookup(dom_i, WIRE_DOMAINS, "domain"),
        _lookup(prec_i, WIRE_PRECISIONS, "precision") or None,
        _lookup(prio_i, WIRE_PRIORITIES, "priority"),
        bool(inverse), dtype, rid, n, width, slot, extras_len,
        payload_len, version=version)


def decode_extras(blob: bytes) -> dict:
    """The metadata blob (tenant/trace/latency split) — bounded JSON
    metadata, NOT plane payload, so it rides outside the host-copy
    meter (module docstring)."""
    if not blob:
        return {}
    try:
        out = json.loads(blob.decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise WireError(f"undecodable extras blob: {e}") from None
    if not isinstance(out, dict):
        raise WireError(f"extras blob is {type(out).__name__}, "
                        f"want object")
    return out


async def read_wire_frame(reader, head: Optional[bytes] = None) -> \
        Optional[Frame]:
    """The next binary frame, or None on clean EOF between frames.
    `head` is the already-peeked header prefix (dialect detection).
    A truncation mid-frame raises ``asyncio.IncompleteReadError`` —
    the tolerated client-went-away shape; a malformed header raises
    :class:`WireError`."""
    if head is None:
        try:
            head = await reader.readexactly(HEADER.size)
        except asyncio.IncompleteReadError as e:
            if not e.partial:
                return None
            raise
    elif len(head) < HEADER.size:
        head = head + await reader.readexactly(HEADER.size - len(head))
    frame = parse_header(head)
    extras_len, payload_len = frame.extras, frame.payload
    frame.extras = decode_extras(
        await reader.readexactly(extras_len) if extras_len else b"")
    frame.payload = await reader.readexactly(payload_len) \
        if payload_len else b""
    return frame


# ------------------------------------------------------- plane codecs


def plane_to_wire(arr, dtype: int = DTYPE_F32):
    """One response plane as a write-ready buffer.  float32 planes go
    out as their own memory (no Python-level copy); the bfloat16 wire
    dtype truncates mantissas — a real copy, charged to the meter."""
    a = np.ascontiguousarray(np.asarray(arr, np.float32))
    if dtype == DTYPE_F32:
        return as_bytes_view(a)
    bits = a.view(np.uint32)
    out = ((bits + 0x8000) >> 16).astype(np.uint16)
    charge_host_copy(out.nbytes, site="bf16_wire")
    return as_bytes_view(out)


def wire_dtype_width(dtype: int) -> int:
    return 4 if dtype == DTYPE_F32 else 2


# ------------------------------------------------------------- client


class WireClient:
    """One multiplexed binary connection: HELLO/HELLO_ACK negotiation,
    rid-keyed concurrent requests under the credit window, streaming
    reassembly, and the optional shm lane.

    After :meth:`connect`, ``dialect`` says what the server granted:
    ``"binary"`` — or ``"json"`` when the server refused the offered
    version (the caller then speaks the JSON dialect on the same
    connection; :func:`~.protocol.request_over_socket` style)."""

    def __init__(self):
        self.reader = None
        self.writer = None
        self.dialect = None
        self.credits = 0
        self.window = 0
        self.shm = None          # client-side ShmRing view, when granted
        self._free_slots: list = []
        self._pending: dict = {}     # rid -> Future
        self._chunks: dict = {}      # rid -> list of payload chunks
        self._rid = 0
        self._credit_free = asyncio.Event()
        self._slot_free = asyncio.Event()
        self._reader_task = None
        self._write_lock = asyncio.Lock()
        self._conn_error: Optional[BaseException] = None

    @classmethod
    async def connect(cls, host: str, port: int, *,
                      want_shm: bool = False,
                      version: int = WIRE_VERSION) -> "WireClient":
        self = cls()
        self.reader, self.writer = await asyncio.open_connection(
            host, port)
        flags = F_WANT_SHM if want_shm else 0
        for buf in encode_frame(MSG_HELLO, flags=flags,
                                version=version):
            self.writer.write(buf)
        await self.writer.drain()
        head = await self.reader.readexactly(4)
        if head == MAGIC:
            ack = await read_wire_frame(self.reader, head=head)
            if ack is None or ack.msg_type != MSG_HELLO_ACK:
                raise WireError("server answered HELLO with "
                                f"msg_type {ack and ack.msg_type}")
            self.dialect = "binary"
            self.window = self.credits = max(1, ack.slot)
            self._credit_free.set()
            if ack.flags & F_SHM and ack.payload:
                from .shm import ShmRing

                # the grant numbers come off the wire: a hostile server
                # must not size our free-slot list or the ring mapping
                if not 1 <= ack.n <= MAX_SHM_SLOTS or ack.width < 8:
                    raise WireError(
                        f"shm grant out of contract: {ack.n} slot(s) "
                        f"x {ack.width} byte(s)")
                self.shm = ShmRing.attach(
                    bytes(ack.payload).decode("utf-8"),
                    slots=ack.n, slot_bytes=ack.width)
                self._free_slots = list(range(ack.n))
            self._reader_task = asyncio.ensure_future(self._read_loop())
        else:
            # version fallback: the server answered in the JSON
            # dialect — `head` is the big-endian length prefix of its
            # fallback frame; drain it so the caller starts clean
            (length,) = struct.unpack(">I", head)
            body = await self.reader.readexactly(length)
            self.dialect = "json"
            self.fallback = json.loads(body.decode("utf-8"))
        return self

    async def _read_loop(self):
        try:
            while True:
                frame = await read_wire_frame(self.reader)
                if frame is None:
                    break
                self._dispatch(frame)
        except (asyncio.IncompleteReadError, ConnectionResetError,
                BrokenPipeError, WireError) as e:
            self._conn_error = e
        finally:
            err = self._conn_error or ConnectionError(
                "server closed the connection")
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(err)
            self._pending.clear()
            self._credit_free.set()
            self._slot_free.set()  # wake slot-waiters into the error

    def _dispatch(self, frame: Frame):
        if frame.msg_type == MSG_STREAM_CHUNK:
            # streaming reassembly IS a sanctioned host copy: chunks
            # land in a growing client-side buffer, charged per chunk
            charge_host_copy(len(frame.payload),
                             site="stream_reassemble")
            self._chunks.setdefault(frame.rid, []).append(frame.payload)
            return
        if frame.msg_type == MSG_STREAM_END:
            frame.payload = b"".join(self._chunks.pop(frame.rid, []))
            frame.msg_type = MSG_RESPONSE
        fut = self._pending.pop(frame.rid, None)
        if frame.msg_type in (MSG_RESPONSE, MSG_ERROR):
            # a terminal reply returns its request's credit (PONGs are
            # free: pings never consumed one)
            self.credits = min(self.window, self.credits + 1)
            self._credit_free.set()
        if fut is not None and not fut.done():
            fut.set_result(frame)

    def _next_rid(self) -> int:
        self._rid += 1
        return self._rid

    async def _acquire_credit(self):
        while self.credits <= 0:
            self._credit_free.clear()
            await self._credit_free.wait()
            if self._conn_error is not None:
                raise self._conn_error
        self.credits -= 1

    async def request(self, xr, xi=None, *, op: str = "fft",
                      layout: str = "natural",
                      precision: Optional[str] = None,
                      inverse: bool = False, domain: str = "c2c",
                      priority: str = "normal",
                      tenant: Optional[str] = None,
                      trace=None, stream: bool = False,
                      dtype: int = DTYPE_F32,
                      use_shm: bool = False) -> dict:
        """One request over the multiplexed connection.  Returns the
        response record (``ok``/latency split/``degraded``/``trace``)
        with ``yr``/``yi`` as float32 arrays — zero-copy views over
        the receive buffer on the float32 path."""
        if self.dialect != "binary":
            raise WireError("connection negotiated the JSON dialect")
        xr = np.ascontiguousarray(np.asarray(xr, np.float32))
        xi_arr = None if xi is None \
            else np.ascontiguousarray(np.asarray(xi, np.float32))
        n = int(xr.shape[-1])
        if domain == "c2r":
            n = 2 * (n - 1)
        flags = (F_PI if layout == "pi" else 0) \
            | (F_STREAM if stream else 0) \
            | (0 if xi_arr is not None else F_NO_XI)
        extras = {}
        if tenant:
            extras["tenant"] = tenant
        if trace is not None:
            extras["trace"] = trace
        rid = self._next_rid()
        await self._acquire_credit()
        slot = 0
        if use_shm:
            if self.shm is None:
                raise WireError("shm lane was not granted in HELLO_ACK")
            # a credit does not imply a slot YET: the response frame
            # returns the credit before the awaiting request coroutine
            # resumes and recycles its slot — wait, don't fail
            while not self._free_slots:
                self._slot_free.clear()
                await self._slot_free.wait()
                if self._conn_error is not None:
                    raise self._conn_error
            slot = self._free_slots.pop()
            self.shm.write_planes(slot, xr, xi_arr)
            flags |= F_SHM
            payload = []
        elif dtype == DTYPE_BF16:
            payload = [plane_to_wire(xr, dtype)] \
                + ([plane_to_wire(xi_arr, dtype)]
                   if xi_arr is not None else [])
        else:
            payload = [as_bytes_view(xr)] \
                + ([as_bytes_view(xi_arr)] if xi_arr is not None
                   else [])
        fut = asyncio.get_running_loop().create_future()
        self._pending[rid] = fut
        bufs = encode_frame(
            MSG_REQUEST, flags=flags, op=op, domain=domain,
            precision=precision, priority=priority, inverse=inverse,
            dtype=dtype, rid=rid, n=n, width=int(xr.shape[-1]),
            slot=slot, extras=extras, payload=payload)
        try:
            async with self._write_lock:
                for buf in bufs:
                    self.writer.write(buf)
                await self.writer.drain()
            frame = await fut
            # build the record (copying shm results OUT of the slot)
            # BEFORE the finally recycles the slot — a waiting request
            # must not overwrite planes we haven't read yet
            return self._record(frame)
        finally:
            self._pending.pop(rid, None)
            if use_shm:
                self._free_slots.append(slot)
                self._slot_free.set()

    def _record(self, frame: Frame) -> dict:
        rec = dict(frame.extras or {})
        rec.setdefault("id", frame.rid)
        if frame.msg_type == MSG_ERROR:
            rec.setdefault("ok", False)
            return rec
        rec["ok"] = True
        rec["degraded"] = bool(frame.flags & F_DEGRADED) \
            or bool(rec.get("degraded"))
        if frame.flags & F_SHM and self.shm is not None:
            yr, yi = self.shm.read_planes(
                frame.slot, frame.width,
                no_xi=bool(frame.flags & F_NO_XI))
            # the slot is recycled the moment this response resolves:
            # materialize the result planes out of it (the shm lane's
            # read-back IS the transport — not a metered decode copy,
            # serve/shm.py module docstring)
            yr = np.array(yr)
            yi = np.array(yi) if yi is not None else None
        else:
            elem = wire_dtype_width(frame.dtype)
            plane = frame.width * elem
            raw = frame.payload
            if frame.dtype == DTYPE_BF16:
                bits = np.frombuffer(raw, np.uint16).astype(np.uint32)
                charge_host_copy(bits.nbytes * 2, site="bf16_wire")
                full = (bits << 16).view(np.float32)
                yr = full[:frame.width]
                yi = None if frame.flags & F_NO_XI \
                    else full[frame.width:2 * frame.width]
            else:
                yr = np.frombuffer(raw, np.float32, count=frame.width)
                yi = None if frame.flags & F_NO_XI else np.frombuffer(
                    raw, np.float32, count=frame.width, offset=plane)
        rec["yr"] = yr
        rec["yi"] = yi if yi is not None else np.zeros_like(yr)
        return rec

    async def ping(self) -> bool:
        rid = self._next_rid()
        fut = asyncio.get_running_loop().create_future()
        self._pending[rid] = fut
        async with self._write_lock:
            for buf in encode_frame(MSG_PING, rid=rid):
                self.writer.write(buf)
            await self.writer.drain()
        frame = await fut
        return frame.msg_type == MSG_PONG

    async def close(self):
        if self._reader_task is not None:
            self._reader_task.cancel()
            try:
                await self._reader_task
            except asyncio.CancelledError:
                pass
        if self.shm is not None:
            self.shm.close()
            self.shm = None
        if self.writer is not None:
            self.writer.close()
