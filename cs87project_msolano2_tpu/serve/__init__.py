"""Serving subsystem: async batched FFT-as-a-service
(docs/SERVING.md).

The ROADMAP's north star is a system serving heavy concurrent traffic,
and every subsystem for that exists below this package — plans give
warm tuned kernels, the batched executor gives a collective-free
many-transforms-one-kernel path, resilience gives degradation, obs
gives per-request accounting.  This package is the front door that
turns CONCURRENT REQUESTS into BATCHED KERNEL INVOCATIONS:

* ``dispatcher`` — the asyncio front: bounded per-group queues with
                   structured backpressure (:class:`QueueFull` +
                   ``retry_after_ms``), one coalescing worker per
                   group, admission-time graceful degradation
                   (window collapse, then cheap-rung mode), per-request
                   queue-wait/compute accounting.
* ``batcher``    — requests -> one padded ``(B_pad, n)`` kernel
                   invocation via ``plans.plan_for`` (power-of-two
                   batch buckets so compiled programs are few), with
                   the serve half of the resilience ladder (transient
                   retry in place, capacity/permanent -> fallback
                   rungs, all tagged).
* ``buffers``    — pooled host staging planes (+ device-side donation
                   on real hardware).
* ``shapes``     — the served shape set (JSONL) and the warm startup
                   path shared with ``pifft plan warm --shapes``.
* ``slo``        — per-shape p50/p99 with the queue-wait vs compute
                   split.
* ``loadgen``    — open-loop offered-load driver behind
                   ``bench.py --serve-load``.
* ``protocol``   — the length-prefixed JSON socket front behind
                   ``pifft serve``.
* ``mesh``       — per-device worker pools behind the same front
                   (``MeshDispatcher``): shape-affinity routing,
                   priority admission + tenant quotas, self-healing
                   device failover with consensus re-routing, and
                   warm-cache handoff on planned drain.
* ``router``     — the placement (warmth + least-loaded) and
                   admission (priority classes, per-tenant quota)
                   policies the mesh runs on.
* ``live_smoke`` — the ``make obs-live-smoke`` gate: end-to-end
                   request tracing, the streaming telemetry
                   endpoints, and the burn-rate SLO loop
                   (docs/OBSERVABILITY.md, "The live plane").

Check rule PIF107 (docs/CHECKS.md) polices this package: no blocking
``time.sleep``/sync I/O inside its async paths — all waiting funnels
through the sanctioned dispatcher helper.
"""

from __future__ import annotations

from .batcher import BatchRunner, GroupKey, batch_bucket  # noqa: F401
from .buffers import BufferPool  # noqa: F401
from .dispatcher import (  # noqa: F401
    PRIORITIES,
    Dispatcher,
    DispatcherClosed,
    QueueFull,
    Request,
    RequestFailed,
    Response,
    ServeConfig,
    ServeError,
    ShapeNotServed,
)
from .mesh import (  # noqa: F401
    DeviceFailure,
    MeshConfig,
    MeshDevice,
    MeshDispatcher,
)
from .router import (  # noqa: F401
    AdmissionController,
    NoDeviceAvailable,
    QuotaExceeded,
    Router,
)
from .shapes import ShapeSpec, load_shapes, warm  # noqa: F401
from .slo import (  # noqa: F401
    LatencyStats,
    format_summary,
    percentile,
    percentile_or_none,
)
