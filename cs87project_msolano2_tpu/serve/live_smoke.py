"""``make obs-live-smoke`` — the live-telemetry-plane CI gate
(docs/OBSERVABILITY.md, "The live plane").

One process, four acts, every claim asserted:

0. **The OFF state**: with observability disabled, traced submits mint
   the shared NOOP trace, add ZERO events, and responses carry no
   trace — the no-op-span contract extended to trace mint.
1. **End-to-end tracing over the socket**: a request with no trace
   field gets a MINTED trace whose response span tree has
   queue/window/compute children summing (±5%) to the SLO row's
   total, every child parented on the request root; a client-supplied
   trace id ROUND-TRIPS; the coalescing burst's ``serve_batch`` span
   carries ``links`` whose count equals the coalesced request count;
   ``/metrics`` and ``/healthz`` answer DURING the load and ``/slo``
   reports the sliding-window rows.
2. **Failover under one trace**: a mid-run device kill on a virtual
   mesh re-routes the in-flight request to a survivor and its span
   tree carries the ``failover:<device>`` hop — same trace id,
   explicitly visible re-route.
3. **Burn-rate alerting with teeth**: under injected serve-path
   latency every request blows the declared p99 target, the monitor
   fires a schema'd ``slo_alert`` and the NEXT admission serves the
   cheap rung tagged ``slo:jnp-fft`` with ``degraded: true``; when
   the injection stops the burn drains, the alert RESOLVES, and the
   forced level clears — recovery as automatic as the alarm.

Plus the stream-wide invariant every gate in this project ends on:
zero schema-invalid events.
"""

from __future__ import annotations

import asyncio
import json
import sys

import numpy as np

from .. import obs
from ..obs import events as obs_events
from ..obs import metrics
from ..obs import trace as trace_mod
from ..obs.http import TelemetryServer, fetch_json, fetch_text
from ..obs.slomon import Objective, SloMonitor
from ..resilience.inject import inject
from .batcher import GroupKey
from .dispatcher import Dispatcher, ServeConfig
from .protocol import handle_connection, request_over_socket
from .shapes import ShapeSpec

#: the traced burst: enough concurrency to coalesce deterministically
BURST_K = 8

#: the declared objective for act 3: tight enough that the injected
#: stall (STALL_S) always violates it, loose enough that the healthy
#: CPU path never does
TARGET_MS = 25.0
STALL_S = 0.06
#: act-3 burn windows: CI-sized (seconds) — the production default is
#: 5/60 s (obs/slomon.py)
WINDOWS = (0.4, 1.0)


def _sum_phases(tree: dict) -> float:
    return sum(s["dur_ms"] for s in tree.get("spans", ())
               if s["name"] in ("queue", "window", "compute"))


def _act0_disabled(problems: list) -> None:
    """Observability off: NOOP trace, zero events, no response trace."""
    assert not obs.enabled()
    if trace_mod.mint() is not trace_mod.NOOP_TRACE:
        problems.append("disabled mint() is not the NOOP singleton")

    async def run():
        async with Dispatcher(ServeConfig()) as d:
            xr = np.random.default_rng(0).standard_normal(256) \
                .astype(np.float32)
            return await d.submit(xr, np.zeros_like(xr), domain="r2c")

    resp = asyncio.run(run())
    if resp.trace is not None:
        problems.append(f"disabled-path response carries a trace: "
                        f"{resp.trace}")
    if obs.snapshot():
        problems.append(f"disabled path emitted "
                        f"{len(obs.snapshot())} event(s); want 0")
    snap = metrics.snapshot()
    if snap["counters"] or snap["gauges"]:
        problems.append(f"disabled path touched the metrics registry: "
                        f"{snap}")


async def _act1_socket(problems: list) -> None:
    """Minted + round-tripped traces over the wire, batch links,
    live endpoints under load."""
    rng = np.random.default_rng(1)
    spec = ShapeSpec(n=1024)
    cfg = ServeConfig(max_wait_ms=25.0)
    async with Dispatcher(cfg, [spec]) as d:
        server = await asyncio.start_server(
            lambda r, w: handle_connection(d, r, w), "127.0.0.1", 0)
        port = server.sockets[0].getsockname()[1]
        telemetry = TelemetryServer(d).start()
        try:
            def planes():
                return (rng.standard_normal(spec.n).astype(np.float32),
                        rng.standard_normal(spec.n).astype(np.float32))

            # --- the coalescing burst, no trace field -> minted
            burst = [planes() for _ in range(BURST_K)]
            replies = await asyncio.gather(*[
                request_over_socket("127.0.0.1", port, xr, xi)
                for xr, xi in burst])
            loop = asyncio.get_running_loop()
            # --- /metrics + /healthz DURING load: more traffic in
            # flight while the endpoints answer from another thread
            inflight = asyncio.gather(*[
                request_over_socket("127.0.0.1", port, *planes())
                for _ in range(4)])
            base = telemetry.url()
            prom = await loop.run_in_executor(
                None, fetch_text, f"{base}/metrics")
            health = await loop.run_in_executor(
                None, fetch_json, f"{base}/healthz")
            slo_doc = await loop.run_in_executor(
                None, fetch_json, f"{base}/slo")
            await inflight
            if "pifft_serve_requests_total" not in prom:
                problems.append("/metrics lacks the serve counters "
                                "during load")
            if not health.get("ok"):
                problems.append(f"/healthz not ok during load: "
                                f"{health}")
            if "queues" not in health:
                problems.append(f"/healthz lacks queue depths: "
                                f"{sorted(health)}")
            label = GroupKey(n=spec.n).label()
            if label not in (slo_doc.get("rows") or {}):
                problems.append(f"/slo lacks the served shape "
                                f"{label}: {sorted(slo_doc.get('rows') or {})}")

            # --- minted trace: span tree sums to the SLO row total
            for reply in replies[:1]:
                tree = reply.get("trace")
                if not tree or not tree.get("trace_id"):
                    problems.append(f"no minted trace on the wire "
                                    f"reply: {sorted(reply)}")
                    break
                if not tree.get("spans"):
                    problems.append("minted trace carries no span "
                                    "tree (sampling should be on)")
                    break
                total = reply["queue_wait_ms"] + reply["compute_ms"]
                got = _sum_phases(tree)
                if total > 0 and abs(got - total) > 0.05 * total:
                    problems.append(
                        f"span tree sums to {got:.4f} ms, SLO row "
                        f"total is {total:.4f} ms (>5% apart)")
                root = tree["span_id"]
                for s in tree["spans"]:
                    if s["name"] != "serve_request" \
                            and s.get("parent") != root:
                        problems.append(f"child {s['name']} parented "
                                        f"on {s.get('parent')}, want "
                                        f"root {root}")

            # --- client-supplied trace id round-trips
            supplied = {"trace_id": "feedfacecafebeef0011223344556677",
                        "span_id": "c11e9751"}
            reply = await request_over_socket(
                "127.0.0.1", port, *planes(), trace=supplied)
            tree = reply.get("trace") or {}
            if tree.get("trace_id") != supplied["trace_id"]:
                problems.append(
                    f"client trace id did not round-trip: sent "
                    f"{supplied['trace_id']}, got "
                    f"{tree.get('trace_id')}")

            # --- batch fan-in links == coalesced request count
            batch_spans = [s for s in obs_events.span_snapshot()
                           if s.get("name") == "serve_batch"
                           and (s.get("cell") or {}).get("n") == spec.n]
            if not batch_spans:
                problems.append("no serve_batch spans recorded")
            linked = sum(len(s.get("links") or ()) for s in batch_spans)
            served = sum((s.get("cell") or {}).get("size", 0)
                         for s in batch_spans)
            if linked != served:
                problems.append(
                    f"batch links ({linked}) != coalesced request "
                    f"count ({served}) — the fan-in edge is lossy")
            if not any(len(s.get("links") or ()) > 1
                       for s in batch_spans):
                problems.append("no batch carried >1 link — the burst "
                                "never coalesced; the links assertion "
                                "proved nothing")
        finally:
            telemetry.stop()
            server.close()
            await server.wait_closed()


async def _act2_failover(problems: list) -> None:
    """A mid-run device kill: the re-routed request's span tree shows
    the failover hop, under the SAME trace."""
    from .loadgen import _group_for
    from .mesh import MeshConfig, MeshDispatcher

    rng = np.random.default_rng(2)
    specs = [ShapeSpec(n=512, layout=lay) for lay in ("natural", "pi")]
    cfg = MeshConfig(devices=4, max_wait_ms=2.0)
    async with MeshDispatcher(cfg, specs) as mesh:
        spec = specs[0]
        xr = rng.standard_normal(spec.n).astype(np.float32)
        xi = rng.standard_normal(spec.n).astype(np.float32)
        # prime: pay the compile before the kill
        await mesh.submit(xr, xi, layout=spec.layout)
        victim = mesh.router.route(_group_for(spec), record=False)
        with inject(victim.site, "permanent", count=1):
            resp = await mesh.submit(xr, xi, layout=spec.layout)
        hop = f"failover:{victim.id}"
        if hop not in resp.degrade:
            problems.append(f"kill did not failover-tag the response "
                            f"({resp.degrade})")
        tree = resp.trace or {}
        if not tree.get("spans"):
            problems.append("failover response carries no span tree "
                            "(tail upgrade should force emission)")
            return
        hops = [s for s in tree["spans"] if s["name"] == hop]
        if not hops:
            problems.append(
                f"span tree lacks the {hop} re-route span: "
                f"{[s['name'] for s in tree['spans']]}")
        # the hop rides the request's OWN trace: every emitted record
        # of this tree carries the same trace id
        recs = [s for s in obs_events.span_snapshot()
                if s.get("trace") == tree.get("trace_id")]
        if not any(s.get("name") == hop for s in recs):
            problems.append(f"emitted stream lacks the {hop} span "
                            f"under trace {tree.get('trace_id')}")


async def _act3_burn(problems: list) -> None:
    """Injected latency -> slo_alert fires -> slo:jnp-fft demotion,
    tagged; injection stops -> burn drains -> alert resolves."""
    monitor = SloMonitor(
        [Objective("fft-p99", TARGET_MS, error_budget=0.05,
                   match="fft")],
        windows=WINDOWS)
    rng = np.random.default_rng(3)
    spec = ShapeSpec(n=512)
    cfg = ServeConfig(max_wait_ms=0.5, slo_objectives=monitor)
    async with Dispatcher(cfg, [spec]) as d:
        xr = rng.standard_normal(spec.n).astype(np.float32)
        xi = rng.standard_normal(spec.n).astype(np.float32)
        await d.submit(xr, xi)  # prime the compile outside the clock

        async def drive(count):
            out = []
            for _ in range(count):
                out.append(await d.submit(xr, xi))
            return out

        with inject("serve", "stall", prob=1.0, stall_s=STALL_S):
            # burn both windows: every request blows the target
            await drive(12)
            if not monitor.alerting().get("fft-p99"):
                problems.append("sustained burn never fired the alert")
                return
            demoted = await drive(3)
        tagged = [r for r in demoted
                  if r.degraded and "slo:jnp-fft" in r.degrade]
        if not tagged:
            problems.append(
                f"alert did not demote: post-alert responses carry "
                f"{[r.degrade for r in demoted]} (want slo:jnp-fft, "
                f"degraded true)")
        # the demoted rung skips the injection site, so latency is
        # already healthy; keep serving until the windows drain
        for _ in range(40):
            await drive(2)
            await asyncio.sleep(WINDOWS[0] / 4)
            if not monitor.alerting().get("fft-p99"):
                break
        if monitor.alerting().get("fft-p99"):
            problems.append("alert never resolved after the injection "
                            "stopped")
        if monitor.forced_level() is not None:
            problems.append(f"forced level {monitor.forced_level()!r} "
                            f"outlived the burn")
        recovered = await d.submit(xr, xi)
        if any(str(t).startswith("slo:") for t in recovered.degrade):
            problems.append(f"post-recovery response still slo-tagged: "
                            f"{recovered.degrade}")
    alerts = [e for e in obs.snapshot() if e.get("kind") == "slo_alert"]
    states = [e["payload"]["state"] for e in alerts]
    if "firing" not in states or "resolved" not in states:
        problems.append(f"slo_alert stream incomplete: {states}")


def main(argv=None) -> int:
    problems: list = []
    _act0_disabled(problems)

    owned = not obs.enabled()
    if owned:
        obs.enable()
    try:
        asyncio.run(_act1_socket(problems))
        asyncio.run(_act2_failover(problems))
        asyncio.run(_act3_burn(problems))
        snapshot = obs.snapshot()
        bad = 0
        for rec in snapshot:
            for p in obs_events.validate_event(rec):
                bad += 1
                problems.append(f"event seq={rec.get('seq')}: {p}")
        summary = {
            "ok": not problems,
            "events": len(snapshot),
            "schema_invalid_events": bad,
            "slo_alerts": sum(1 for e in snapshot
                              if e.get("kind") == "slo_alert"),
            "traced_requests": sum(
                1 for s in obs_events.span_snapshot()
                if s.get("name") == "serve_request"),
            "problems": problems,
        }
    finally:
        if owned:
            obs.disable()
    print(json.dumps(summary, indent=1, sort_keys=True))
    for p in problems:
        print(f"# FAIL: {p}", file=sys.stderr)
    if problems:
        return 1
    print("# obs live smoke ok: minted + round-tripped traces, "
          "fan-in links, live endpoints under load, failover hop "
          "under one trace, burn-rate alert fired -> demoted -> "
          "recovered, zero schema-invalid events", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
