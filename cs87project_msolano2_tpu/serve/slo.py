"""SLO accounting: per-shape latency percentiles with the queue-wait /
compute split.

A served request's latency is two different stories glued together:
time spent WAITING (admission + batching window + queue depth — the
dispatcher's doing) and time spent COMPUTING (the kernel invocation its
batch rode — the plan's doing).  Reporting only the total hides which
knob to turn, so every record keeps the split, and the summary reports
p50/p99 of each per shape label — the row format ``pifft serve
--smoke`` prints and ``bench.py --serve-load`` emits in the BENCH
round record.

Percentiles use the nearest-rank method on the recorded population —
no interpolation, so a p99 is always a latency that actually happened.
The estimator itself is the ONE shared implementation in
``utils/stats.py`` (property-tested against numpy's nearest-rank
mode); this module re-exports it so serve-side callers keep their
import path.

Beyond the end-of-run summary, :class:`LatencyStats` now keeps a
**streaming reservoir**: a bounded per-label deque of timestamped
samples over a sliding window, so the live ``/slo`` endpoint
(docs/OBSERVABILITY.md, "The live plane") reports p50/p99 per
(op, shape, domain, precision, device) AS THE MESH RUNS — not only
when a run ends.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Optional

from ..obs.spans import clock
from ..utils.stats import percentile_nearest_rank, percentile_or_none

__all__ = ["LatencyStats", "format_summary", "percentile",
           "percentile_or_none"]

#: re-export: the shared nearest-rank estimator (utils/stats.py)
percentile = percentile_nearest_rank

#: the live window the /slo endpoint reports over (seconds)
DEFAULT_WINDOW_S = 60.0

#: reservoir bound per label: a hot shape cannot grow the live table
#: without limit — the oldest samples age out in O(1)
WINDOW_MAX_SAMPLES = 4096


class LatencyStats:
    """Per-label accumulation of (queue_wait_s, compute_s, total_s)
    samples plus degradation/batching tallies.  Thread-safe: the
    dispatcher records from executor threads while summaries read from
    the event loop."""

    def __init__(self, window_s: float = DEFAULT_WINDOW_S,
                 window_max: int = WINDOW_MAX_SAMPLES):
        self._lock = threading.Lock()
        self._samples: dict = {}    # label -> list of sample dicts
        self._counts: dict = {}     # label -> {"requests", "batches",
        #                                       "degraded", "rejected"}
        #: the streaming reservoir behind the live /slo endpoint:
        #: window key -> deque[(t, queue_s, compute_s, degraded)]
        self.window_s = float(window_s)
        self._window_max = int(window_max)
        self._window: dict = {}

    def _bucket(self, label: str) -> dict:
        c = self._counts.get(label)
        if c is None:
            c = self._counts[label] = {"requests": 0, "batches": 0,
                                       "degraded": 0, "rejected": 0}
            self._samples[label] = []
        return c

    def record(self, label: str, queue_wait_s: float, compute_s: float,
               degraded: bool = False,
               device: Optional[str] = None) -> None:
        """One completed request.  `device` extends the live-window key
        (``label@device``) so the /slo table separates the mesh
        devices serving one shape — the per-(op, shape, domain,
        precision, device) contract (docs/OBSERVABILITY.md)."""
        now = clock()
        wkey = label if device is None else f"{label}@{device}"
        with self._lock:
            c = self._bucket(label)
            c["requests"] += 1
            if degraded:
                c["degraded"] += 1
            self._samples[label].append(
                {"queue": queue_wait_s, "compute": compute_s,
                 "total": queue_wait_s + compute_s})
            dq = self._window.get(wkey)
            if dq is None:
                dq = self._window[wkey] = deque(
                    maxlen=self._window_max)
            dq.append((now, queue_wait_s, compute_s, degraded))

    def record_batch(self, label: str) -> None:
        with self._lock:
            self._bucket(label)["batches"] += 1

    def record_rejected(self, label: str) -> None:
        with self._lock:
            self._bucket(label)["rejected"] += 1

    def summary(self) -> dict:
        """label -> row dict with counts and p50/p99 of queue, compute
        and total (ms).  Labels with zero completed samples report
        counts only."""
        out = {}
        with self._lock:
            for label, counts in self._counts.items():
                row = dict(counts)
                samples = self._samples[label]
                if samples:
                    for part in ("queue", "compute", "total"):
                        vals = [s[part] for s in samples]
                        row[f"{part}_p50_ms"] = round(
                            percentile(vals, 50) * 1e3, 4)
                        row[f"{part}_p99_ms"] = round(
                            percentile(vals, 99) * 1e3, 4)
                out[label] = row
        return out

    def window_summary(self,
                       window_s: Optional[float] = None) -> dict:
        """The LIVE table: per window key (``label`` or
        ``label@device``), counts and p50/p99 of queue/compute/total
        (ms) over the trailing `window_s` (default: the stats'
        configured window).  Keys whose window emptied report a
        zero-count row (the shape was served, just not recently) —
        the /slo endpoint's contract is the same stable schema the
        loadgen rows keep."""
        horizon = clock() - (window_s or self.window_s)
        out = {}
        with self._lock:
            for key, dq in self._window.items():
                # prune in place: aged samples never return
                while dq and dq[0][0] < horizon:
                    dq.popleft()
                live = list(dq)
                row = {"requests": len(live),
                       "degraded": sum(1 for s in live if s[3])}
                for part, idx in (("queue", 1), ("compute", 2)):
                    vals = [s[idx] for s in live]
                    for q in (50, 99):
                        v = percentile_or_none(vals, q)
                        row[f"{part}_p{q}_ms"] = round(v * 1e3, 4) \
                            if v is not None else None
                totals = [s[1] + s[2] for s in live]
                for q in (50, 99):
                    v = percentile_or_none(totals, q)
                    row[f"total_p{q}_ms"] = round(v * 1e3, 4) \
                        if v is not None else None
                out[key] = row
        return out

    def window_totals(self, window_s: Optional[float] = None) -> dict:
        """Raw total-latency populations (seconds, queue + compute) per
        window key over the trailing window — the replicated samples
        the fleet drift detector feeds to the Mann-Whitney machinery
        (docs/FLEET.md): the verdict runs on the latencies requests
        actually saw, not on the summarized percentiles."""
        horizon = clock() - (window_s or self.window_s)
        out = {}
        with self._lock:
            for key, dq in self._window.items():
                while dq and dq[0][0] < horizon:
                    dq.popleft()
                out[key] = [s[1] + s[2] for s in dq]
        return out

    def retire(self, label: Optional[str] = None,
               device: Optional[str] = None) -> list:
        """Drop the live-window keys of a RETIRED group or device, so
        the /slo table stops carrying zero-count rows for shapes (or
        drained/dead mesh devices) that will never serve again.  By
        label, by device, or both; returns the removed keys.  The
        cumulative end-of-run tallies are untouched — retirement is a
        live-table statement, not history rewriting."""
        removed = []
        with self._lock:
            for key in list(self._window):
                klabel, _, kdev = key.partition("@")
                if label is not None and klabel != label:
                    continue
                if device is not None and kdev != device:
                    continue
                if label is None and device is None:
                    continue
                del self._window[key]
                removed.append(key)
        return removed


def format_summary(summary: dict) -> str:
    """The human table ``pifft serve --smoke`` prints."""
    if not summary:
        return "serve: no requests recorded"
    cols = ("reqs", "batches", "rej", "degr", "q_p50", "q_p99",
            "c_p50", "c_p99", "tot_p99")
    lines = ["shape".ljust(28) + "  " + "  ".join(c.rjust(8) for c in cols)]
    for label in sorted(summary):
        row = summary[label]

        def ms(key):
            v = row.get(key)
            return f"{v:.3f}" if v is not None else "-"

        vals = (str(row["requests"]), str(row["batches"]),
                str(row["rejected"]), str(row["degraded"]),
                ms("queue_p50_ms"), ms("queue_p99_ms"),
                ms("compute_p50_ms"), ms("compute_p99_ms"),
                ms("total_p99_ms"))
        lines.append(label.ljust(28) + "  "
                     + "  ".join(v.rjust(8) for v in vals))
    return "\n".join(lines)
