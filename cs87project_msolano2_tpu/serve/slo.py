"""SLO accounting: per-shape latency percentiles with the queue-wait /
compute split.

A served request's latency is two different stories glued together:
time spent WAITING (admission + batching window + queue depth — the
dispatcher's doing) and time spent COMPUTING (the kernel invocation its
batch rode — the plan's doing).  Reporting only the total hides which
knob to turn, so every record keeps the split, and the summary reports
p50/p99 of each per shape label — the row format ``pifft serve
--smoke`` prints and ``bench.py --serve-load`` emits in the BENCH
round record.

Percentiles use the nearest-rank method on the recorded population —
no interpolation, so a p99 is always a latency that actually happened.
"""

from __future__ import annotations

import threading


def percentile(values, q: float) -> float:
    """Nearest-rank percentile (q in [0, 100]) of a non-empty
    sequence."""
    if not values:
        raise ValueError("percentile of an empty population")
    ordered = sorted(values)
    rank = max(1, -(-len(ordered) * q // 100))  # ceil without floats
    return ordered[int(min(rank, len(ordered))) - 1]


def percentile_or_none(values, q: float):
    """:func:`percentile`, or None for an empty population — the
    loadgen row contract: a cell where every arrival was rejected (or
    none were made) keeps its full row schema with null latency
    fields instead of crashing the summary."""
    return percentile(values, q) if values else None


class LatencyStats:
    """Per-label accumulation of (queue_wait_s, compute_s, total_s)
    samples plus degradation/batching tallies.  Thread-safe: the
    dispatcher records from executor threads while summaries read from
    the event loop."""

    def __init__(self):
        self._lock = threading.Lock()
        self._samples: dict = {}    # label -> list of sample dicts
        self._counts: dict = {}     # label -> {"requests", "batches",
        #                                       "degraded", "rejected"}

    def _bucket(self, label: str) -> dict:
        c = self._counts.get(label)
        if c is None:
            c = self._counts[label] = {"requests": 0, "batches": 0,
                                       "degraded": 0, "rejected": 0}
            self._samples[label] = []
        return c

    def record(self, label: str, queue_wait_s: float, compute_s: float,
               degraded: bool = False) -> None:
        with self._lock:
            c = self._bucket(label)
            c["requests"] += 1
            if degraded:
                c["degraded"] += 1
            self._samples[label].append(
                {"queue": queue_wait_s, "compute": compute_s,
                 "total": queue_wait_s + compute_s})

    def record_batch(self, label: str) -> None:
        with self._lock:
            self._bucket(label)["batches"] += 1

    def record_rejected(self, label: str) -> None:
        with self._lock:
            self._bucket(label)["rejected"] += 1

    def summary(self) -> dict:
        """label -> row dict with counts and p50/p99 of queue, compute
        and total (ms).  Labels with zero completed samples report
        counts only."""
        out = {}
        with self._lock:
            for label, counts in self._counts.items():
                row = dict(counts)
                samples = self._samples[label]
                if samples:
                    for part in ("queue", "compute", "total"):
                        vals = [s[part] for s in samples]
                        row[f"{part}_p50_ms"] = round(
                            percentile(vals, 50) * 1e3, 4)
                        row[f"{part}_p99_ms"] = round(
                            percentile(vals, 99) * 1e3, 4)
                out[label] = row
        return out


def format_summary(summary: dict) -> str:
    """The human table ``pifft serve --smoke`` prints."""
    if not summary:
        return "serve: no requests recorded"
    cols = ("reqs", "batches", "rej", "degr", "q_p50", "q_p99",
            "c_p50", "c_p99", "tot_p99")
    lines = ["shape".ljust(28) + "  " + "  ".join(c.rjust(8) for c in cols)]
    for label in sorted(summary):
        row = summary[label]

        def ms(key):
            v = row.get(key)
            return f"{v:.3f}" if v is not None else "-"

        vals = (str(row["requests"]), str(row["batches"]),
                str(row["rejected"]), str(row["degraded"]),
                ms("queue_p50_ms"), ms("queue_p99_ms"),
                ms("compute_p50_ms"), ms("compute_p99_ms"),
                ms("total_p99_ms"))
        lines.append(label.ljust(28) + "  "
                     + "  ".join(v.rjust(8) for v in vals))
    return "\n".join(lines)
