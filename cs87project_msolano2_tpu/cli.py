"""CLI (L3) — flag parity with the reference executables
(…pthreads.c:293-302) plus backend dispatch:

    python -m cs87project_msolano2_tpu { -n <n> -p <p> [-o] [-b <backend>]
                                         [--reps R] | -t [-b <backend>] }
    python -m cs87project_msolano2_tpu plan {show | warm | clear | sweep} [...]
    python -m cs87project_msolano2_tpu check [path ...] [--rule ID]
                                         [--json] [--baseline FILE]
    python -m cs87project_msolano2_tpu faults {list | inject <spec>}
    python -m cs87project_msolano2_tpu obs {summary | export | validate
                                         | top} [--events FILE]
                                         [--format F] [--url URL]
    python -m cs87project_msolano2_tpu analyze {fit | report | gate}
                                         [files ...] [--json]
    python -m cs87project_msolano2_tpu serve [--smoke | --host H --port P]
                                         [--shapes FILE] [...]
    python -m cs87project_msolano2_tpu apps {conv | corr | solve}
                                         [--smoke] [-n N]
    python -m cs87project_msolano2_tpu multichip smoke [-n N]
                                         [--deadline S] [--stall S]
    python -m cs87project_msolano2_tpu hw probe [--json | -v | --cores]

Non-test runs print one TSV row `n p total_ms funnel_ms tube_ms` (header
unless -o) — the exact contract the harness and analysis layers consume
(reference …pthreads.c:487-491).  Test mode runs the reference's 8-point
golden test through the chosen backend and prints pass/fail.

The `plan` subcommand manages the FFT plan cache (the plans/ subsystem):
`show` lists the persistent store for this device kind, `warm` tunes a
key now so serving sessions start on a cache hit, `clear` wipes the
on-disk store, `sweep` tunes a large-n trajectory and reports the
measured fourstep crossover (docs/KERNELS.md).

The `check` subcommand runs the project's static-analysis pass (the
check/ subsystem): AST rules for the timing/retrace/Mosaic/plan-key
invariants, with baseline comparison for CI.  See docs/CHECKS.md.

The `faults` subcommand fronts the resilience subsystem
(docs/RESILIENCE.md): `list` shows the injection sites, fault kinds and
the PIFFT_FAULT syntax; `inject <site>:<kind>[:<prob>[:<count>]]` arms
the spec in-process and drives a small pi-layout transform through the
plan layer, reporting what fired, how it classified, and whether the
retry/degradation policies carried the run — the one-command demo that
the recovery ladder works on THIS machine.

The `obs` subcommand fronts the observability subsystem
(docs/OBSERVABILITY.md): `summary` rolls an event stream (the JSONL
file `bench.py --events` / `PIFFT_OBS_EVENTS` wrote) into a human
table (`--json` for machines), `export --format {chrome,prom}`
converts it to Chrome trace JSON (Perfetto) or the Prometheus textfile
format, `validate` schema-checks every event (the CI obs-smoke
gate), and `top` renders the LIVE /slo + /healthz snapshot of a
running `pifft serve --telemetry-port` as a refreshing terminal
table (docs/OBSERVABILITY.md, "The live plane").

The `analyze` subcommand fronts the statistical verification layer
(docs/ANALYSIS.md): `fit` runs the complexity-law fit (confidence
intervals, per-cell residuals, optional figures) over harness TSVs
and/or the funnel/tube phase spans of an obs event stream, `report`
inventories all three measurement sources with environment
fingerprints and phase-share cross-checks, and `gate` is the
statistical perf-regression gate over the committed BENCH_r\\*.json
trajectory (Mann-Whitney over replications, fingerprint-gated
comparability, the committed perf-baseline.json) — the CI step that
fails on a significant throughput regression with a named metric and a
p-value.

The `serve` subcommand fronts the serving subsystem (docs/SERVING.md):
an asyncio dispatcher that coalesces concurrent requests into padded
batched kernel invocations over bounded backpressured queues, warmed
from a served shape set (`--shapes`, the same JSONL `plan warm
--shapes` takes) — a socket front by default, `--smoke` for the
in-process CI gate (`make serve-smoke`).

The `apps` subcommand fronts the spectral operation suite
(docs/APPS.md): fused spectral convolution/correlation, streaming
overlap-save, and the spectral PDE family, with `--smoke` the
per-op `make apps-smoke` CI gate (oracle parity, the metered fusion
gate, a served op-tagged socket round trip).

The `multichip` subcommand fronts the self-healing multichip layer
(docs/MULTICHIP.md): `smoke` injects a stall into a supervised
all_to_all on a simulated 8-device mesh and asserts the whole recovery
loop — supervised abort, fallback consensus, the communication-free
escape, a bit-identical result, schema-valid events — the second half
of the `make multichip-smoke` CI gate.

The `hw` subcommand fronts the hardware-inventory subsystem
(docs/BACKENDS.md): `probe` reports the host's platform, backend tag,
device kind/count, CPU cores, native per-`p` capacities and the
bandwidth ceiling table — `--json` emits the schema'd DeviceInventory
record the `make backend-smoke` gate validates; the bare form keeps
the legacy `probes` module's human one-liner.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys

import numpy as np

from .backends.registry import get_backend, list_backends
from .utils import verify


def make_input(n: int, seed: int = 0) -> np.ndarray:
    """Deterministic pseudo-random init with amplitude 1/sqrt(n) (the
    reference initializes random +-1/sqrt N, …pthreads.c:244-247)."""
    rng = np.random.default_rng(seed)
    amp = 1.0 / np.sqrt(n)
    x = (rng.uniform(-amp, amp, n) + 1j * rng.uniform(-amp, amp, n))
    return x.astype(np.complex64)


def run_golden(backend_name: str) -> int:
    b = get_backend(backend_name)
    # butterfly backends reproduce the golden DFT bit-exactly (reference
    # semantics, …pthreads.c:689-705) and declare atol=0, where the
    # tolerance check degenerates to exact equality; matmul backends
    # declare a golden tolerance because MXU accumulation order differs
    # (same bound as tests/test_direct_dft.py::test_einsum_backend_golden)
    atol = getattr(b, "golden_atol", 0.0)
    ok_all = True
    for p in (1, 2, 4, 8):
        res = b.run(verify.golden_input(), p)
        nat = verify.pi_layout_to_natural(res.out)
        ok = verify.golden_check_tol(nat, atol)
        print(f"golden test: backend={backend_name} n=8 p={p} ... "
              f"{'PASSED' if ok else 'FAILED'}")
        ok_all &= ok
    return 0 if ok_all else 1


def _parse_n(s: str) -> int:
    """Accept plain ints and the 2^k spelling the bench docs use."""
    if "^" in s:
        base, exp = s.split("^", 1)
        return int(base) ** int(exp)
    return int(s, 0)


def plan_main(argv) -> int:
    """`plan {show|warm|clear|sweep}` — manage the persistent FFT plan
    cache (`sweep` tunes a whole large-n trajectory and reports the
    measured fourstep AND sixstep crossovers — docs/KERNELS.md)."""
    ap = argparse.ArgumentParser(
        prog="cs87project_msolano2_tpu plan",
        description="show / warm / clear / sweep the FFT plan cache "
                    "(tune once, serve forever)",
    )
    ap.add_argument("action", choices=("show", "warm", "clear", "sweep"))
    ap.add_argument("--shapes", default=None, metavar="FILE",
                    help="warm: a served shape set (JSONL of {n, batch, "
                         "precision, layout}) to warm in ONE call — the "
                         "file `pifft serve --shapes` takes "
                         "(docs/SERVING.md)")
    ap.add_argument("-n", type=_parse_n, default=1 << 20,
                    help="transform length for warm (int or 2^k)")
    ap.add_argument("--ns", type=_parse_n, nargs="*",
                    default=[1 << 20, 1 << 22, 1 << 24, 1 << 25, 1 << 26],
                    help="sweep: transform lengths to tune "
                         "(default: the bench trajectory through the "
                         "fourstep AND sixstep crossovers)")
    ap.add_argument("--batch", type=int, nargs="*", default=[],
                    help="leading batch dims for warm (default: none)")
    ap.add_argument("--layout", choices=("natural", "pi"), default="pi",
                    help="output order the plan is tuned for")
    from .ops.precision import PRECISIONS

    ap.add_argument("--precision", choices=PRECISIONS, default=None,
                    help="precision mode to tune for — a TUNED plan "
                         "axis (docs/PRECISION.md): 'bf16' races the "
                         "bytes-halving bfloat16-storage variants "
                         "(fp32 accumulate) against their fp32-storage "
                         "siblings; 'fp32' is the full-precision "
                         "kernel path")
    ap.add_argument("--domain", choices=("c2c", "r2c", "c2r"),
                    default="c2c",
                    help="warm: transform domain — the half-spectrum "
                         "real paths (r2c/c2r) require --layout "
                         "natural and ride the c2c plan at n/2 "
                         "(docs/REAL.md)")
    ap.add_argument("--force", action="store_true",
                    help="warm: re-tune even on a cache hit")
    args = ap.parse_args(argv)

    from . import plans

    if args.action == "clear":
        removed = plans.cache.clear(memory=True, disk=True)
        for path in removed:
            print(f"removed {path}")
        if not removed:
            print("plan cache already empty "
                  f"(dir: {plans.cache.cache_dir() or 'disabled'})")
        return 0

    kind = plans.current_device_kind()
    if args.action == "show":
        path = plans.cache.store_path(kind)
        print(f"device kind:  {kind}")
        print(f"cache dir:    {plans.cache.cache_dir() or 'DISABLED'} "
              f"(PIFFT_PLAN_CACHE overrides)")
        entries = plans.cache.disk_entries(kind)
        if not entries:
            print("store:        empty (plans will come from static "
                  "defaults until warmed)")
            return 0
        print(f"store:        {path} ({len(entries)} plan(s))")
        from .ops.precision import error_budget, storage_dtype

        for token, rec in sorted(entries.items()):
            key = plans.PlanKey.from_token(token)
            ms = rec.get("ms")
            # precision-aware listing (docs/PRECISION.md): the served
            # mode may differ from the key's when the race pinned a
            # tighter-storage sibling — show what actually won, its
            # storage dtype, and the budget the key contracts
            served = (rec.get("params") or {}).get("precision") \
                or key.precision
            prec = key.precision
            if served != key.precision:
                prec = f"{key.precision}->{served}"
            print(f"  n={key.n} domain={key.domain} batch={key.batch} "
                  f"{key.layout} {prec} "
                  f"[{storage_dtype(served)}, budget "
                  f"{error_budget(key.precision):.0e}]: "
                  f"{rec['variant']} {rec['params']}"
                  + (f" ({ms:.4f} ms)" if ms is not None else ""))
        return 0

    if args.action == "sweep":
        try:
            tuned, cross = plans.tune_sweep(
                args.ns, layout=args.layout, precision=args.precision,
                force=args.force)
        except (plans.TuningUnavailable, plans.TuningError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        for p in tuned:
            ms = f" ({p.ms:.4f} ms)" if p.ms is not None else ""
            print(f"  n={p.key.n}: {p.variant} {p.params}{ms}")
        print(f"measured fourstep crossover: "
              f"{cross if cross is not None else 'none (never won)'}")
        cross6 = plans.sixstep_crossover(tuned)
        print(f"measured sixstep crossover: "
              f"{cross6 if cross6 is not None else 'none (never won)'}")
        return 0

    # warm
    if args.shapes:
        # the whole served shape set in one call (serve startup runs
        # the same function): tune where the hardware answers, static
        # default otherwise — a CPU warm never dies for lack of a tuner
        from .serve import shapes as serve_shapes

        try:
            specs = serve_shapes.load_shapes(args.shapes)
        except (OSError, ValueError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        warmed = serve_shapes.warm(specs, force=args.force, verbose=True)
        for spec, p in zip(specs, warmed):
            ms = f" ({p.ms:.4f} ms)" if p.ms is not None else ""
            print(f"warmed {spec.label()}: {p.variant} {p.params} "
                  f"[{p.source}]{ms}")
        print(f"warmed {len(warmed)} shape(s) from {args.shapes}")
        return 0
    try:
        key = plans.make_key(args.n, tuple(args.batch),
                             layout=args.layout,
                             precision=args.precision,
                             domain=args.domain)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    try:
        plan = plans.tune(key, force=args.force)
    except plans.TuningUnavailable as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    except plans.TuningError as e:
        print(f"error: {e}", file=sys.stderr)
        for r in e.results:
            print(f"  {r.variant} {r.params}: {r.reason}", file=sys.stderr)
        return 1
    d = plan.describe()
    print(f"warmed {key.token()}\n  -> {d}")
    return 0


def faults_main(argv) -> int:
    """`faults {list|inject}` — inspect and exercise the resilience
    subsystem's fault-injection layer (docs/RESILIENCE.md)."""
    ap = argparse.ArgumentParser(
        prog="cs87project_msolano2_tpu faults",
        description="list injection sites / inject a fault and watch "
                    "the retry + degradation policies handle it",
    )
    ap.add_argument("action", choices=("list", "inject"))
    ap.add_argument("spec", nargs="?", default=None,
                    help="inject: <site>:<kind>[:<prob>[:<count>]] "
                         "(the PIFFT_FAULT syntax)")
    ap.add_argument("-n", type=_parse_n, default=1 << 10,
                    help="inject: transform length for the demo run "
                         "(int or 2^k; default 2^10)")
    args = ap.parse_args(argv)

    from . import resilience

    if args.action == "list":
        print("fault kinds (PIFFT_FAULT=<site>:<kind>[:<prob>[:<count>]],"
              " comma-separated; site is an fnmatch pattern):")
        for kind in resilience.KINDS:
            print(f"  {kind}")
        print("injection sites:")
        for site, where in sorted(resilience.KNOWN_SITES.items()):
            print(f"  {site:<11} {where}")
        print("recovery: transient -> with_retry backoff; capacity/"
              "permanent -> plan degradation chain "
              f"({' -> '.join(resilience.DEGRADE_CHAIN)}); "
              "see docs/RESILIENCE.md")
        return 0

    if not args.spec:
        print("error: inject needs a <site>:<kind>[:<prob>[:<count>]] "
              "spec", file=sys.stderr)
        return 2
    try:
        spec = resilience.FaultSpec.parse(args.spec)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    from . import plans

    plans.cache.clear(memory=True)  # the demo must trace fresh
    key = plans.make_key(args.n, layout="pi")
    rng = np.random.default_rng(0)
    xr = rng.standard_normal(args.n).astype(np.float32)
    xi = rng.standard_normal(args.n).astype(np.float32)

    with resilience.inject(spec.site, spec.kind, spec.prob, spec.count) \
            as live:
        plan = plans.get_plan(key)

        def run():
            return plan.execute(xr, xi)

        try:
            yr, yi = resilience.call_with_retry(
                run, policy=resilience.FAST_POLICY, label="faults demo")
        except Exception as e:
            kind = resilience.classify(e)
            print(f"run FAILED after policy exhaustion: {kind.value} "
                  f"{type(e).__name__}: {str(e)[:200]}")
            print(f"(fault fired {live.fired} time(s); an uncapped "
                  f"always-on transient spec exhausts the retry budget "
                  f"by design — cap it with :<count>)")
            return 1

    ref = np.fft.fft(xr.astype(np.complex128)
                     + 1j * xi.astype(np.complex128))
    got = verify.pi_layout_to_natural(np.asarray(yr) + 1j * np.asarray(yi))
    err = verify.rel_err(got, ref)
    print(f"fault spec {args.spec!r}: fired {live.fired} time(s)")
    d = plan.describe()
    if plan.degraded:
        trail = " -> ".join([plan.variant]
                            + [rec["to"] for rec in plan.demotions])
        print(f"plan DEGRADED: {trail} (run completed on the weakest "
              f"rung that worked)")
    else:
        print(f"plan healthy: {d['variant']} {d['params']} "
              f"(retry absorbed the fault)" if live.fired
              else f"plan healthy: {d['variant']} {d['params']} "
                   f"(fault never fired)")
    print(f"result vs numpy fft: rel err {err:.3e} "
          f"({'OK' if err < 1e-5 else 'WRONG'})")
    return 0 if err < 1e-5 else 1


def obs_main(argv) -> int:
    """`obs {summary|export|validate|top}` — post-process a structured
    event stream, or watch the LIVE telemetry plane
    (docs/OBSERVABILITY.md)."""
    ap = argparse.ArgumentParser(
        prog="cs87project_msolano2_tpu obs",
        description="summarize / export / validate an observability "
                    "event stream (a JSONL file written by "
                    "bench.py --events or PIFFT_OBS_EVENTS), or render "
                    "the live /slo + /healthz snapshot of a running "
                    "`pifft serve --telemetry-port` as a refreshing "
                    "terminal table (top)",
    )
    ap.add_argument("action", choices=("summary", "export", "validate",
                                       "top"))
    ap.add_argument("--url", default="http://127.0.0.1:8572",
                    metavar="URL",
                    help="top: base URL of the telemetry plane "
                         "(pifft serve --telemetry-port)")
    ap.add_argument("--interval", type=float, default=2.0, metavar="S",
                    help="top: refresh period (seconds)")
    ap.add_argument("--once", action="store_true",
                    help="top: print one frame and exit (scripts/CI)")
    ap.add_argument("--events", default="pifft-events.jsonl",
                    metavar="FILE",
                    help="the event-stream JSONL file (default: "
                         "pifft-events.jsonl)")
    ap.add_argument("--format", choices=("chrome", "prom"),
                    default="chrome",
                    help="export format: Chrome trace JSON (Perfetto) "
                         "or Prometheus textfile")
    ap.add_argument("--out", default=None, metavar="FILE",
                    help="export: write here instead of stdout")
    ap.add_argument("--json", action="store_true",
                    help="summary: machine-readable output")
    args = ap.parse_args(argv)

    import json as _json
    import os

    from .obs import events as obs_events
    from .obs import export as obs_export

    if args.action == "top":
        return _obs_top(args)

    if not os.path.exists(args.events):
        print(f"error: no event stream at {args.events} (run with "
              f"bench.py --events or PIFFT_OBS_EVENTS=<path>)",
              file=sys.stderr)
        return 2
    records, dropped = obs_events.load_events(args.events)

    if args.action == "validate":
        problems = obs_export.validate_stream(records)
        for ident, problem in problems:
            print(f"{args.events}: event {ident}: {problem}",
                  file=sys.stderr)
        tail = (f", {dropped} corrupt line(s) skipped" if dropped else "")
        if problems:
            print(f"obs validate: {len(problems)} schema problem(s) in "
                  f"{len(records)} event(s){tail}", file=sys.stderr)
            return 1
        print(f"obs validate: {len(records)} event(s) OK{tail}")
        return 0

    if args.action == "summary":
        summary = obs_export.summarize(records, dropped)
        print(_json.dumps(summary, indent=1, sort_keys=True)
              if args.json else obs_export.format_summary(summary))
        return 0

    # export
    if args.format == "chrome":
        doc = obs_export.chrome_trace(
            obs_export.spans_from_events(records))
        text = _json.dumps(doc, indent=1, sort_keys=True) + "\n"
    else:
        snap = obs_export.last_metrics_snapshot(records)
        if snap is None:
            print("error: the stream has no metrics snapshot (the run "
                  "died before its final flush)", file=sys.stderr)
            return 1
        text = obs_export.prometheus_text(snap)
    if args.out:
        with open(args.out, "w", encoding="utf-8") as fh:
            fh.write(text)
        print(f"wrote {args.format} export to {args.out}")
    else:
        sys.stdout.write(text)
    return 0


def _obs_top(args) -> int:
    """`obs top` — the live terminal view: poll a running telemetry
    plane's /slo + /healthz and render the refreshing table
    (docs/OBSERVABILITY.md, "The live plane")."""
    import time
    import urllib.error

    from .obs.http import fetch_json, format_top

    base = args.url.rstrip("/")
    interval = max(args.interval, 0.2)
    while True:
        try:
            slo = fetch_json(f"{base}/slo")
            health = fetch_json(f"{base}/healthz")
        except urllib.error.HTTPError as e:
            if e.code != 503:
                print(f"error: {base}: HTTP {e.code}", file=sys.stderr)
                return 1
            # 503 still carries the health body — NOT SERVING is a
            # frame, not a failure of the viewer
            import json as _json

            health = _json.loads(e.read().decode("utf-8"))
            slo = {"rows": {}}
        except (OSError, ValueError) as e:
            print(f"error: no telemetry plane at {base} ({e}) — start "
                  f"one with pifft serve --telemetry-port",
                  file=sys.stderr)
            return 1
        frame = format_top(slo, health)
        if args.once:
            print(frame)
            return 0
        # clear + home, then the frame (the classic top discipline)
        sys.stdout.write("\x1b[2J\x1b[H" + frame + "\n")
        sys.stdout.flush()
        try:
            time.sleep(interval)
        except KeyboardInterrupt:
            return 0


def multichip_main(argv) -> int:
    """`multichip smoke` — the one-command proof the self-healing
    multichip loop works on THIS machine (docs/MULTICHIP.md): an
    injected stall wedges the supervised all_to_all 2-D FFT, the
    supervisor aborts it, all hosts agree on the fallback epoch, the
    communication-free escape completes the run, and the result is
    bit-identical to the healthy path — asserted, with the obs events
    schema-validated.  The CI `make multichip-smoke` gate runs this
    after the four dryruns."""
    ap = argparse.ArgumentParser(
        prog="cs87project_msolano2_tpu multichip",
        description="exercise the collective supervision -> consensus "
                    "-> communication-free escape recovery loop on a "
                    "simulated 8-device mesh",
    )
    ap.add_argument("action", choices=("smoke",))
    ap.add_argument("-n", type=int, default=64,
                    help="2-D transform side (n x n)")
    ap.add_argument("--deadline", type=float, default=0.2, metavar="S",
                    help="supervision deadline for the stalled run")
    ap.add_argument("--stall", type=float, default=1.0, metavar="S",
                    help="injected stall duration")
    args = ap.parse_args(argv)

    import jax

    from . import obs
    from .obs.events import validate_event
    from .parallel import fft2_sharded_resilient, make_mesh
    from .resilience import inject

    if len(jax.devices()) < 8:
        print("error: multichip smoke needs >= 8 devices; on a CPU "
              "host set XLA_FLAGS=--xla_force_host_platform_device_"
              "count=8 and JAX_PLATFORMS=cpu (the make multichip-smoke "
              "target does)", file=sys.stderr)
        return 2
    if not obs.enabled():
        obs.enable()  # in-process buffer; the event asserts below
    mesh = make_mesh(8)
    rng = np.random.default_rng(0)
    x = (rng.standard_normal((args.n, args.n))
         + 1j * rng.standard_normal((args.n, args.n))
         ).astype(np.complex64)

    y_ok, rep_ok = fft2_sharded_resilient(x, mesh)
    if rep_ok.escaped:
        print("error: healthy run escaped — the mesh itself is wedged",
              file=sys.stderr)
        return 1
    print(f"# healthy supervised all_to_all ok "
          f"(waits={rep_ok.waits})")

    with inject("collective", "stall", stall_s=args.stall):
        y_esc, rep = fft2_sharded_resilient(
            x, mesh, deadline_s=args.deadline, abort_waits=2)
    ok = True
    if not rep.escaped or not rep.degraded:
        print(f"error: injected stall did not escape "
              f"(escaped={rep.escaped})", file=sys.stderr)
        ok = False
    rungs = [t.get("to") for t in rep.trail]
    if "collective_free" not in rungs:
        print(f"error: degrade trail lacks the collective_free rung "
              f"({rep.trail})", file=sys.stderr)
        ok = False
    if not np.array_equal(np.asarray(y_ok), np.asarray(y_esc)):
        print("error: escaped result differs from the healthy path",
              file=sys.stderr)
        ok = False
    ref = np.fft.fft2(x.astype(np.complex128))
    err = float(np.max(np.abs(np.asarray(y_esc) - ref))
                / np.max(np.abs(ref)))
    if err > 1e-5:
        print(f"error: escaped result wrong vs numpy (rel err "
              f"{err:.2e})", file=sys.stderr)
        ok = False
    events = obs.snapshot()
    kinds = {r.get("kind") for r in events}
    for wanted in ("collective_heartbeat", "collective_abandoned",
                   "fallback_consensus", "demotion",
                   "collective_escape_completed"):
        if wanted not in kinds:
            print(f"error: event stream lacks {wanted!r}",
                  file=sys.stderr)
            ok = False
    invalid = [p for r in events for p in validate_event(r)]
    if invalid:
        print(f"error: {len(invalid)} schema problem(s) in the event "
              f"stream: {invalid[:3]}", file=sys.stderr)
        ok = False
    if not ok:
        return 1
    epochs = [r["payload"]["epoch"] for r in events
              if r.get("kind") == "fallback_consensus"]
    print(f"# injected stall ({args.stall:.1f}s vs {args.deadline:.1f}s "
          f"deadline) -> supervised abort after {rep.waits} wait(s) -> "
          f"consensus epoch {epochs[-1]} -> collective_free escape: "
          f"result bit-identical, rel err vs numpy {err:.1e}")
    print(f"# multichip smoke ok: degrade trail "
          f"{[t['from'] + '->' + t['to'] for t in rep.trail]}, "
          f"{len(events)} schema-valid events")
    return 0


def wire_main(argv) -> int:
    """``pifft wire`` — inspect the binary wire protocol.

    ``layout`` prints the authoritative frame header table straight
    from the struct (docs/SERVING.md "The wire" quotes it; this is
    the source).  ``probe`` dials a running server, negotiates, and
    reports what the connection actually granted — dialect, credit
    window, shm lane — then round-trips one PING.
    """
    ap = argparse.ArgumentParser(
        prog="cs87project_msolano2_tpu wire",
        description="binary wire protocol tools (docs/SERVING.md)")
    ap.add_argument("cmd", choices=("layout", "probe"))
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8571)
    ap.add_argument("--shm", action="store_true",
                    help="probe: also ask for the shm lane")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)

    from .serve import wire

    if args.cmd == "layout":
        fields = (
            ("magic", "4s", 'b"PIFB"'),
            ("version", "u16", f"wire version (current: "
                               f"{wire.WIRE_VERSION})"),
            ("flags", "u16", "F_NO_XI|F_PI|F_SHM|F_STREAM|"
                             "F_DEGRADED|F_WANT_SHM"),
            ("msg_type", "u8", "HELLO/ACK/REQUEST/RESPONSE/ERROR/"
                               "STREAM_*/PING/PONG"),
            ("op", "u8", f"index into {wire.WIRE_OPS}"),
            ("domain", "u8", f"index into {wire.WIRE_DOMAINS}"),
            ("precision", "u8", "0 = unset, else index into the "
                                "precision modes"),
            ("priority", "u8", f"index into {wire.WIRE_PRIORITIES}"),
            ("inverse", "u8", "0/1"),
            ("dtype", "u8", "0 = float32, 1 = bfloat16"),
            ("pad", "u8", "reserved (zero)"),
            ("rid", "u64", "request id (echoed on the reply)"),
            ("n", "u32", "logical transform length"),
            ("width", "u32", "plane elements in the payload"),
            ("extras_len", "u32", "metadata blob bytes (JSON: "
                                  "tenant/trace/response meta)"),
            ("slot", "u32", "shm slot / stream seq / HELLO_ACK "
                            "credit window"),
            ("payload_len", "u64", "raw plane bytes (xr then xi, "
                                   "dlpack-style contiguous)"),
        )
        if args.json:
            print(json.dumps({
                "magic": "PIFB", "version": wire.WIRE_VERSION,
                "header_bytes": wire.HEADER.size,
                "struct": wire.HEADER.format,
                "fields": [{"name": n, "type": t, "meaning": m}
                           for n, t, m in fields]}, indent=1))
        else:
            print(f"# wire header: {wire.HEADER.size} bytes, "
                  f"little-endian ({wire.HEADER.format})")
            for name, typ, meaning in fields:
                print(f"{name:<12} {typ:<4} {meaning}")
        return 0

    async def probe():
        c = await wire.WireClient.connect(args.host, args.port,
                                          want_shm=args.shm)
        out = {"dialect": c.dialect}
        if c.dialect == "binary":
            out["credits"] = c.window
            out["shm"] = c.shm.name if c.shm is not None else None
            out["pong"] = await c.ping()
        await c.close()
        return out

    try:
        out = asyncio.run(probe())
    except (OSError, wire.WireError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(out, indent=1, sort_keys=True))
    else:
        print(f"# {args.host}:{args.port} -> dialect={out['dialect']}"
              + (f" credits={out['credits']} shm={out['shm']} "
                 f"pong={out['pong']}"
                 if out["dialect"] == "binary" else ""))
    return 0


def hw_main(argv) -> int:
    """``hw probe`` — the device-inventory front (docs/BACKENDS.md).
    Delegates to :func:`hw.inventory.main`, the same entry point
    ``python -m cs87project_msolano2_tpu.hw.inventory`` (and the
    deprecated ``probes`` shim) serve, so the three spellings cannot
    drift apart."""
    if not argv or argv[0] != "probe":
        print("usage: cs87project_msolano2_tpu hw probe "
              "[--json | -v | --cores]", file=sys.stderr)
        return 2
    from .hw.inventory import main as inventory_main

    return inventory_main(argv[1:])


def main(argv=None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == "plan":
        return plan_main(argv[1:])
    if argv and argv[0] == "wire":
        return wire_main(argv[1:])
    if argv and argv[0] == "faults":
        return faults_main(argv[1:])
    if argv and argv[0] == "multichip":
        return multichip_main(argv[1:])
    if argv and argv[0] == "hw":
        return hw_main(argv[1:])
    if argv and argv[0] == "obs":
        return obs_main(argv[1:])
    if argv and argv[0] == "analyze":
        from .analyze.cli import analyze_main

        return analyze_main(argv[1:])
    if argv and argv[0] == "serve":
        from .serve.cli import serve_main

        return serve_main(argv[1:])
    if argv and argv[0] == "apps":
        from .apps.cli import apps_main

        return apps_main(argv[1:])
    if argv and argv[0] == "fleet":
        from .fleet.cli import fleet_main

        return fleet_main(argv[1:])
    if argv and argv[0] == "check":
        from .check.cli import main as check_main

        return check_main(argv[1:])
    ap = argparse.ArgumentParser(
        prog="cs87project_msolano2_tpu",
        description="communication-free pi-FFT over the backend-dispatch boundary",
    )
    ap.add_argument("-n", type=int, help="input length (power of two)")
    ap.add_argument("-p", type=int, help="virtual processors (power of two, <= n)")
    ap.add_argument("-t", action="store_true", help="golden test mode")
    ap.add_argument("-o", action="store_true", help="omit TSV header")
    ap.add_argument("-b", "--backend", default="cpu", choices=list_backends())
    ap.add_argument("--reps", type=int, default=1, help="timed repetitions (best-of)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--verify", action="store_true",
                    help="also check the result against numpy's FFT")
    ap.add_argument("--trace", default=None, metavar="DIR",
                    help="write a jax.profiler trace of the run to DIR")
    args = ap.parse_args(argv)

    if args.t:
        return run_golden(args.backend)

    if not args.n or not args.p:
        ap.print_usage(sys.stderr)
        return 2

    b = get_backend(args.backend)
    cap = b.capacity()
    if cap is not None and args.p > cap:
        print(f"error: p={args.p} exceeds backend '{args.backend}' capacity {cap}",
              file=sys.stderr)
        return 2

    x = make_input(args.n, args.seed)
    try:
        from .obs.profiler import trace

        with trace(args.trace):
            res = b.run(x, args.p, reps=args.reps)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    if args.verify:
        ref = np.fft.fft(x.astype(np.complex128))
        err = verify.rel_err(verify.pi_layout_to_natural(res.out), ref)
        if err > 1e-5:
            print(f"error: verification failed, rel err {err:.3e} > 1e-5",
                  file=sys.stderr)
            return 1
        print(f"# verified vs numpy fft: rel err {err:.3e}", file=sys.stderr)

    if not args.o:
        print("n\tp\ttotal_ms\tfunnel_ms\ttube_ms")
    # degraded timers (loop-slope noise-floor fallback) carry the same
    # marker the harness writes, so redirected CLI output stays honest
    # when fed to the analysis
    mark = "\tDEGRADED" if getattr(res, "degraded", False) else ""
    print(f"{args.n}\t{args.p}\t{res.total_ms:.6f}\t{res.funnel_ms:.6f}\t"
          f"{res.tube_ms:.6f}{mark}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
