"""cs87project_msolano2_tpu — a TPU-native framework with the capabilities of
``elenasolano/CS87Project-msolano2`` ("Parallelizing the Fourier Transform
with no communication").

The reference implements one algorithm — a radix-2 Cooley-Tukey FFT
decomposed into a replicated "funnel" phase and a segment-local "tube"
phase so P processors need zero inter-processor communication — three
times, once per hardware target (pthreads / CUDA / Xeon Phi OpenMP).
This package implements it once, behind a backend-dispatch boundary:

* ``cpu`` / ``serial`` / ``pthreads`` — the native C core
  (``native/libpifft.so``) via ctypes;
* ``jax`` — vectorized butterfly stages under ``jax.jit`` (XLA on TPU);
* ``pallas`` — a hand-written TPU VMEM kernel for the butterfly stages;
* multi-chip — ``parallel/``: ``shard_map`` over a ``jax.sharding.Mesh``
  (zero-collective pi-FFT, DP-batched FFT, all-to-all 2D/3D FFT).

Layer map (mirrors SURVEY.md §1): ``ops/`` = L0/L1 primitives, ``models/``
= L2 transforms, ``backends/`` + ``parallel/`` = L2/L3 runtimes,
``cli`` = L3, ``harness/`` + ``analysis/`` (repo root) = L4/L5.
"""

__version__ = "0.1.0"

from .backends.registry import get_backend, list_backends  # noqa: F401
