"""Atomic per-cell JSONL journal: preemption loses one cell, not the
run.

One record per line, ``{"cell": <id>, ...payload}``, appended with a
single write + flush + fsync so a kill can at worst truncate the LAST
line — and :meth:`Journal.load` tolerates exactly that (a trailing
partial line is skipped with a diagnostic, never an error).  Drives
``bench.py --resume`` and the harness sweeps' completed-cell skipping
(docs/RESILIENCE.md, resume semantics).
"""

from __future__ import annotations

import json
import os
from typing import Optional

# ------------------------------------------------------- shared writer
#
# The atomic-line JSONL discipline is used by more than the journal:
# the observability event sink (obs/events.py) appends the same way, so
# the primitives live here as the single implementation.


def open_append(path: str):
    """An append-mode UTF-8 handle for a JSONL file, parents created."""
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    return open(path, "a", encoding="utf-8")


def write_line(fh, rec: dict, *, fsync: bool = True) -> None:
    """Append one record as a single flushed line.  ``fsync=True`` (the
    journal's checkpoint semantics) adds the durability barrier; the
    event sink passes False and batches its barrier in ``obs.flush``.
    Either way a kill can at worst truncate the LAST line, which
    :func:`load_records` tolerates."""
    fh.write(json.dumps(rec, sort_keys=True) + "\n")
    fh.flush()
    if fsync:
        os.fsync(fh.fileno())


def load_records(path: str) -> tuple:
    """(records, dropped) from a JSONL file: every parseable dict line,
    plus how many corrupt lines (the half-written tail an interrupted
    write leaves) were skipped.  A missing file is (no records, 0
    dropped), never an error."""
    records: list = []
    dropped = 0
    if os.path.exists(path):
        with open(path, encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    dropped += 1
                    continue
                if isinstance(rec, dict):
                    records.append(rec)
                else:
                    dropped += 1
    return records, dropped


class Journal:
    """Append-only JSONL checkpoint keyed by cell id."""

    def __init__(self, path: str):
        self.path = path
        self._cells: Optional[dict] = None

    # ------------------------------------------------------------ read

    def load(self) -> dict:
        """cell id -> last recorded payload.  Corrupt lines (the
        half-written tail a kill leaves) are skipped with a diagnostic;
        a later record for the same cell wins."""
        records, dropped = load_records(self.path)
        cells: dict = {}
        for rec in records:
            if "cell" in rec:
                cells[str(rec["cell"])] = rec
        if dropped:
            from ..plans.core import warn

            warn(f"journal {self.path}: skipped {dropped} "
                 f"corrupt line(s) (interrupted write); the cells "
                 f"they held will re-run")
        self._cells = cells
        return cells

    def _loaded(self) -> dict:
        if self._cells is None:
            self.load()
        return self._cells

    def has(self, cell: str) -> bool:
        return str(cell) in self._loaded()

    def get(self, cell: str) -> Optional[dict]:
        return self._loaded().get(str(cell))

    # ----------------------------------------------------------- write

    def record(self, cell: str, payload: Optional[dict] = None) -> dict:
        """Append one cell record; the line is flushed and fsynced
        before return so a later kill cannot take it back."""
        rec = dict(payload or {})
        rec["cell"] = str(cell)
        with open_append(self.path) as fh:
            write_line(fh, rec, fsync=True)
        self._loaded()[str(cell)] = rec
        return rec

    def reset(self) -> None:
        """Start the journal over (a fresh, non-resumed run must not
        inherit stale cells)."""
        if os.path.exists(self.path):
            os.remove(self.path)
        self._cells = {}

    # ----------------------------------------------------- run config

    def guard_config(self, config: dict, label: str = "run") -> None:
        """Bind the journal to its run configuration (the ``config``
        cell): a resumed journal written by a DIFFERENT configuration
        raises ``ValueError`` — resuming a full-size sweep from a
        smoke journal would splice toy numbers into the record.  Only
        the keys in `config` are compared, so a journal may carry
        extra config fields a newer writer added.  Shared by
        ``bench.py --resume`` and the harness sweeps (`label` names
        the writer in the refusal)."""
        prior = self.get("config")
        if prior is not None:
            prior = {k: prior.get(k) for k in config}
            if prior != config:
                raise ValueError(
                    f"journal {self.path} was written by a different "
                    f"{label} configuration ({prior} != {config}); "
                    f"use a fresh journal or delete it")
        else:
            self.record("config", config)
