"""Fault injection: make every recovery policy testable on CPU tier-1.

Injection sites are one-line ``maybe_fault("<site>")`` probes at the
places real faults strike: kernel entries (ops/pallas_fft.py — site
``tube``, because a kernel IS the tube transform), plan dispatch
(plans/core.py — ``plan``), tube-plan resolution (models/pi_fft.py —
``resolve``), the sharded paths (parallel/pi_shard.py — ``shard``),
the collective watchdog (``collective``), the bench timing loops
(``bench``) and the harness sweep cells (``harness``).

Arming:

* environment — ``PIFFT_FAULT=<site>:<kind>[:<prob>[:<count>]]``,
  comma-separated for multiple specs; ``site`` is an fnmatch pattern,
  ``kind`` one of transient/capacity/permanent/timeout/stall, ``prob``
  defaults to 1.0, ``count`` caps total firings (unlimited when
  omitted).  ``PIFFT_FAULT=tube:capacity:1.0`` is the chaos-smoke CI
  configuration (make bench-chaos).  ``stall`` faults DELAY instead of
  raising — ``stall=<seconds>`` in the kind token sets the duration
  (``PIFFT_FAULT=collective:stall=2.0:1.0:1`` wedges the first
  collective for 2 s, the multichip-smoke recovery configuration) —
  which is how the whole supervised-abort/escape loop is exercised on
  CPU (docs/MULTICHIP.md).
* in-process — the :func:`inject` context manager, which tests use to
  scope a fault to one call.

Injected exceptions carry the REAL signature text of the fault class
they imitate ("RESOURCE_EXHAUSTED", "UNAVAILABLE", Mosaic wording), so
the taxonomy's pattern tables — not a test-only side channel — do the
classification.  When nothing is armed the probe is one dict check.
"""

from __future__ import annotations

import dataclasses
import fnmatch
import os
import random
import time
from contextlib import contextmanager
from typing import Optional

from .taxonomy import CollectiveTimeout

#: site -> where it fires (the `pifft faults list` table)
KNOWN_SITES = {
    "tube": "kernel-variant entry points in ops/pallas_fft.py "
            "(fourstep / rql / fused / two-kernel / mf / rows) — the "
            "segment transform every plan executes",
    "plan": "plans.core.Plan.execute dispatch",
    "resolve": "models.pi_fft.resolve_tube_plan (tube-plan resolution "
               "for the sharded paths)",
    "shard": "parallel.pi_shard sharded pi-FFT entries",
    "collective": "collective supervision: the collective_watchdog arm "
                  "point and the supervise_collective worker entry "
                  "(parallel/multihost.py rendezvous discipline; stall "
                  "faults here wedge the supervised region itself, "
                  "driving the abort/escape recovery loop — "
                  "docs/MULTICHIP.md)",
    "bench": "bench.py measurement loops",
    "harness": "harness/run_experiments.py sweep cells",
    "serve": "serve/batcher.py tuned-kernel batch invocation (the "
             "serving path's fallback rungs stay clean, so chaos "
             "degrades the service instead of killing it — "
             "docs/SERVING.md)",
    "device": "serve/mesh.py per-device batch execution — ONE SITE PER "
              "MESH DEVICE, named device<K> (device0, device1, ...): "
              "PIFFT_FAULT=device3:permanent kills mesh device 3 "
              "mid-batch, device*:... strikes any device, and a stall "
              "spec wedges the device until the batch supervisor "
              "aborts it; either way the mesh marks the device dead "
              "through consensus and re-routes its queued and "
              "in-flight requests to survivors (docs/SERVING.md, "
              "failover)",
    "canary": "fleet/canary.py shadow re-race entry — the mirrored "
              "(non-served) candidate timing loop on the designated "
              "canary device; a fault here aborts the race before any "
              "verdict, leaving the shared plan cache untouched "
              "(docs/FLEET.md)",
    "promote": "fleet/canary.py promotion write — between the journaled "
               "promotion epoch and the shared plan-cache store; a "
               "fault here triggers the automatic rollback path "
               "(byte-identical cache restore + fleet_rollback "
               "demotion event — docs/FLEET.md)",
}

KINDS = ("transient", "capacity", "permanent", "timeout", "stall")

#: default injected-stall duration; long enough that a test-sized
#: supervision deadline (tenths of a second) expires at least once
#: inside it, short enough that tier-1 stays fast
DEFAULT_STALL_S = 1.0


class InjectedFault(RuntimeError):
    """Marker base for injected faults (so logs can tell chaos from
    reality); the message carries the imitated signature, which is what
    :func:`~.taxonomy.classify` keys on."""


# message templates reproduce the real signatures the taxonomy tables
# match (taxonomy.py documents their provenance)
_TEMPLATES = {
    "transient": "UNAVAILABLE: injected transient fault at site {site!r} "
                 "(connection reset by injection)",
    "capacity": "RESOURCE_EXHAUSTED: injected capacity fault at site "
                "{site!r} (attempting to allocate more than the device "
                "has)",
    "permanent": "Mosaic lowering failed: injected permanent fault at "
                 "site {site!r}",
}


@dataclasses.dataclass
class FaultSpec:
    """One armed fault: fnmatch `site` pattern, `kind`, firing
    probability, optional total-firing cap, and the firing counter.
    ``stall`` faults DELAY instead of raising — ``stall_s`` is the
    injected delay (``stall=2.5`` in the kind token overrides the
    default)."""

    site: str
    kind: str
    prob: float = 1.0
    count: Optional[int] = None
    fired: int = 0
    stall_s: float = DEFAULT_STALL_S

    @classmethod
    def parse(cls, text: str) -> "FaultSpec":
        parts = text.strip().split(":")
        if not 2 <= len(parts) <= 4 or not parts[0]:
            raise ValueError(
                f"bad fault spec {text!r} (want site:kind[:prob[:count]])")
        kind = parts[1].lower()
        stall_s = DEFAULT_STALL_S
        if kind.startswith("stall="):
            kind, _, secs = kind.partition("=")
            try:
                stall_s = float(secs)
            except ValueError:
                raise ValueError(f"bad stall duration {secs!r} in "
                                 f"{text!r} (want stall=<seconds>)")
            if not stall_s > 0:
                raise ValueError(f"stall duration must be > 0, got "
                                 f"{stall_s} in {text!r}")
        if kind not in KINDS:
            raise ValueError(f"bad fault kind {parts[1]!r} "
                             f"(want one of {KINDS}, stall takes an "
                             f"optional stall=<seconds>)")
        prob = float(parts[2]) if len(parts) > 2 and parts[2] else 1.0
        count = int(parts[3]) if len(parts) > 3 and parts[3] else None
        return cls(site=parts[0], kind=kind, prob=prob, count=count,
                   stall_s=stall_s)

    def exhausted(self) -> bool:
        return self.count is not None and self.fired >= self.count


def parse_specs(text: str) -> list:
    """Every spec in a comma-separated PIFFT_FAULT value."""
    return [FaultSpec.parse(part)
            for part in text.split(",") if part.strip()]


# env-armed specs, cached on the raw env value so firing counters
# survive across probe calls but a changed env re-parses
_ENV_CACHE: list = [None, []]  # [raw value, parsed specs]
# context-manager-armed specs (stacked; inner scopes fire first)
_SCOPED: list = []
# deterministic by default so chaos runs reproduce;
# PIFFT_FAULT_SEED overrides
_RNG = random.Random(int(os.environ.get("PIFFT_FAULT_SEED", "0") or 0))


def _env_specs() -> list:
    raw = os.environ.get("PIFFT_FAULT", "")
    if raw != _ENV_CACHE[0]:
        try:
            parsed = parse_specs(raw)
        except ValueError as e:
            # a typo'd spec must not silently disable chaos: fail loud —
            # and keep failing (the cache key is only updated on a
            # successful parse, so EVERY probe under the bad value
            # raises instead of silently serving the stale spec list)
            raise ValueError(f"PIFFT_FAULT: {e}") from e
        _ENV_CACHE[0] = raw
        _ENV_CACHE[1] = parsed
    return _ENV_CACHE[1]


def active_specs() -> list:
    """Scoped (innermost first) then env-armed specs."""
    return list(reversed(_SCOPED)) + _env_specs()


def _raise_for(spec: FaultSpec, site: str) -> None:
    spec.fired += 1
    if spec.kind == "timeout":
        raise CollectiveTimeout(
            f"injected collective timeout at site {site!r} (rendezvous "
            f"deadline exceeded)")
    raise InjectedFault(_TEMPLATES[spec.kind].format(site=site))


def maybe_fault(site: str) -> None:
    """The probe: raise (or, for ``stall`` specs, DELAY) the armed
    fault for `site`, if any fires.

    Near-zero cost when nothing is armed.  Probes run at Python call /
    trace time (never inside traced computation), so an injected fault
    propagates exactly like a real compile-time or dispatch failure —
    catchable by the retry and degradation layers under test.  A stall
    sleeps ``spec.stall_s`` and then lets the probe continue: the site
    proceeds late, which is exactly the r05 stuck-then-unstuck shape
    the collective supervisor exists to detect and recover from."""
    if not _SCOPED and not _env_specs():
        return
    for spec in active_specs():
        if spec.exhausted() or not fnmatch.fnmatch(site, spec.site):
            continue
        if spec.prob >= 1.0 or _RNG.random() < spec.prob:
            if spec.kind == "stall":
                spec.fired += 1
                time.sleep(spec.stall_s)
                continue  # a stall delays; it never raises
            _raise_for(spec, site)


@contextmanager
def inject(site: str, kind: str, prob: float = 1.0,
           count: Optional[int] = None,
           stall_s: float = DEFAULT_STALL_S):
    """Scope a fault to a with-block (the test-suite arming path).
    Yields the live :class:`FaultSpec` so callers can assert on
    ``spec.fired``.  ``stall_s`` applies to ``kind="stall"`` only."""
    spec = FaultSpec(site=site, kind=kind, prob=prob, count=count,
                     stall_s=stall_s)
    if kind not in KINDS:
        raise ValueError(f"bad fault kind {kind!r} (want one of {KINDS})")
    if kind == "stall" and not stall_s > 0:
        # mirror FaultSpec.parse: a bad duration must fail HERE, not
        # surface as a time.sleep ValueError disguised as a site fault
        raise ValueError(f"stall duration must be > 0, got {stall_s}")
    _SCOPED.append(spec)
    try:
        yield spec
    finally:
        _SCOPED.remove(spec)
