"""The plan degradation chain: when a kernel plan dies of a CAPACITY or
PERMANENT fault, demote it down Bailey's constraint ladder instead of
killing the run —

    sixstep  ->  fourstep  ->  two-trip rql  ->  jnp.fft.fft
                                             ->  numpy reference
    (fused / rows enter at the fourstep->rql edge: fourstep is a
    sibling single-pass design, not a weaker one, so they skip it)

The order is the recursive four-step constraint order: the hierarchical
sixstep pipeline needs the most machinery (two HBM carries, four DMA
streams), the fourstep one carry, the two-trip rql only scoped column
blocks, ``jnp.fft.fft`` only XLA, and the numpy reference (via
``jax.pure_callback``) only a host — each rung strictly weaker in what
it demands of the backend, strictly equal in what it computes.  A rung
that cannot even serve the key statically (fourstep past its VMEM
feasibility bound, where sixstep exists precisely because fourstep
cannot lower) counts as the rung failing and the walk continues.  Every
demotion is recorded on the plan (``plan.degraded`` /
``plan.demotions``), pushed back through the plan cache, and announced
through ``plans.warn``, so a degraded run is never mistaken for a
healthy one — bench rows carry ``degraded: true`` and the demoted
variant.

TRANSIENT faults are NOT degraded: they re-raise for the retry layer
(``resilience.retry``) — demoting a perfectly good kernel because the
relay blinked would quietly forfeit the measurement.

The wrapper catches at Python/trace time, which is where the faults it
handles actually strike: injection probes, Mosaic lowering rejections,
and scoped-VMEM overflows all surface while the executor traces/lowers.
A runtime HBM OOM inside an already-compiled program propagates to the
jit call site instead, where bench/harness retry-or-reraise policy owns
it.
"""

from __future__ import annotations

from typing import Callable

from .taxonomy import FaultKind, classify

#: the demotion ladder, weakest-demand last (docs/RESILIENCE.md)
DEGRADE_CHAIN = ("fourstep", "rql", "jnp-fft", "numpy-ref")

#: the TRANSPORT demotion rung (docs/MULTICHIP.md): not a kernel in the
#: 1-D chain above but the sharded paths' escape — when a supervised
#: collective is aborted (or a device is reported unhealthy), the
#: all_to_all 2-D FFT / Poisson dataflow re-plans onto the pi-layout
#: funnel-replicated/tube-local decomposition (per-chip local work, one
#: final host-side reorder; parallel/escape.py).  Recorded through
#: :func:`note_collective_escape` with the same record shape, events,
#: and plan tagging as every kernel demotion, so a ``collective_free``
#: rung shows up in the degrade trail exactly like ``rql`` would.
COLLECTIVE_FREE_RUNG = "collective_free"

#: the QUALITY-direction rungs (docs/PRECISION.md): unlike every rung
#: above — which trades performance away to keep serving — a precision
#: promotion trades performance away to keep the ERROR BUDGET: when a
#: served batch's sampled relative error exceeds its mode's contract,
#: the plan promotes UP the mode chain (bf16 -> default -> split3 ->
#: fp32, loosest storage to full precision), recorded exactly like a
#: kernel demotion (``degraded: true``, a demotion record with
#: ``direction: "up"``, the warn line, the event, the counter) — a
#: plan serving tighter-and-slower than it was tuned for is never
#: mistaken for the healthy tuned one.  See promote_precision.
PRECISION_RUNG_PREFIX = "precision:"

#: parameters for the rql rung: auto tile/cb (always lowerable at any
#: feasible n) and the short-tile-safe tail
_RQL_PARAMS = {"tile": None, "cb": None, "tail": 128}

#: parameters for the fourstep rung (sixstep's first demotion): the
#: static-default shape — auto cb, so the rung either lowers or raises
#: the explicit feasibility ValueError and the walk continues
_FOURSTEP_PARAMS = {"tile": None, "cb": None, "tail": 256,
                    "separable": True}


def _rungs_after(variant: str) -> tuple:
    """The chain below `variant` — a ladder variant OR an
    already-landed chain rung.  A plan never demotes sideways or up:
    only sixstep enters at the fourstep rung (the fused/rows designs
    are fourstep's siblings, not its betters — they join at rql)."""
    if variant in DEGRADE_CHAIN:
        return DEGRADE_CHAIN[DEGRADE_CHAIN.index(variant) + 1:]
    if variant == "sixstep":
        return DEGRADE_CHAIN
    if variant == "two-kernel":
        return DEGRADE_CHAIN[2:]
    if variant in ("bluestein", "rader", "mixedradix"):
        # the any-length variants (docs/PLANS.md "Arbitrary n") skip
        # the kernel rungs — fourstep/rql are power-of-two paths and
        # the plan's n is not — and land on the escapes, which speak
        # any n natively (jnp.fft/numpy.fft are mixed-radix engines)
        return DEGRADE_CHAIN[2:]
    if variant == "jnp":
        return DEGRADE_CHAIN[3:]
    return DEGRADE_CHAIN[1:]


def _pi_take(key):
    """Index array mapping natural order -> this key's layout (None when
    no permutation is needed).  pi layout is per-transform bit-reversed:
    pi[i] = natural[bitrev(i)]."""
    if key.layout != "pi":
        return None
    from ..ops.bits import bit_reverse_indices

    return bit_reverse_indices(key.n)


def build_rung(key, rung: str) -> Callable:
    """The executable for one chain rung at `key`'s shape/layout/domain.
    Raises (statically) when the rung cannot serve the key — the chain
    walker treats that exactly like the rung failing and moves on.

    Real-domain keys (r2c/c2r, docs/REAL.md) degrade like everything
    else: the kernel rungs (fourstep/rql) serve the half-length packed
    c2c transform wrapped in the Hermitian passes — built through the
    same ladder executor builder, so the wrapping is identical to the
    healthy path's — and the escape rungs use ``jnp.fft.rfft/irfft``
    and ``numpy.fft.rfft/irfft`` natively (the half-spectrum is their
    home turf; no rung ever silently widens back to full-spectrum
    traffic)."""
    real_domain = getattr(key, "domain", "c2c") != "c2c"
    inner_n = key.n // 2 if real_domain else key.n
    pow2 = key.n >= 1 and not (key.n & (key.n - 1))

    if rung == "fourstep":
        from ..plans import ladder

        if not pow2:
            # per-rung feasibility probe (docs/PLANS.md "Arbitrary
            # n"): the kernel rungs are power-of-two paths — a
            # demoting any-length plan walks past them to the escapes
            raise ValueError(f"fourstep rung requires power-of-two n, "
                             f"got n={key.n}")
        if key.batch != ():
            raise ValueError("fourstep rung is a 1-D whole-transform "
                             "path")
        # build AND probe feasibility statically: past fourstep's VMEM
        # bound (n >= 2^25 — sixstep's whole reason to exist) the
        # auto-cb chooser raises here and the walk moves on to rql.
        # Real domains probe the INNER packed length — the kernel the
        # rung actually runs.
        from ..ops.pallas_fft import MAX_ROW_TILE, fourstep_auto_cb

        if inner_n > MAX_ROW_TILE:
            fourstep_auto_cb(inner_n, MAX_ROW_TILE, 256, True)
        return ladder.build_executor(key, "fourstep",
                                     dict(_FOURSTEP_PARAMS))

    if rung == "rql":
        from ..plans import ladder

        if not pow2:
            raise ValueError(f"rql rung requires power-of-two n, got "
                             f"n={key.n}")
        if key.batch != ():
            raise ValueError("rql rung is a 1-D whole-transform path")
        return ladder.build_executor(key, "rql", dict(_RQL_PARAMS))

    if rung == "jnp-fft":
        import jax.numpy as jnp

        if real_domain and key.domain == "r2c":
            def jnp_rfft_run(xr, xi):
                del xi  # real by declaration (domain="r2c")
                y = jnp.fft.rfft(xr.astype(jnp.float32), axis=-1)
                return (jnp.real(y).astype(jnp.float32),
                        jnp.imag(y).astype(jnp.float32))

            return jnp_rfft_run
        if real_domain:
            n = key.n

            def jnp_irfft_run(xr, xi):
                y = jnp.fft.irfft(xr.astype(jnp.complex64)
                                  + 1j * xi.astype(jnp.complex64),
                                  n=n, axis=-1)
                yr = y.astype(jnp.float32)
                return yr, jnp.zeros_like(yr)

            return jnp_irfft_run

        idx = _pi_take(key)

        def jnp_run(xr, xi):
            y = jnp.fft.fft(xr.astype(jnp.complex64)
                            + 1j * xi.astype(jnp.complex64))
            yr = jnp.real(y).astype(jnp.float32)
            yi = jnp.imag(y).astype(jnp.float32)
            if idx is not None:
                take = jnp.asarray(idx)
                yr = jnp.take(yr, take, axis=-1)
                yi = jnp.take(yi, take, axis=-1)
            return yr, yi

        return jnp_run

    if rung == "numpy-ref":
        import jax
        import numpy as np

        idx = _pi_take(key)
        out_shape = key.batch + (key.output_width(),) if real_domain \
            else key.batch + (key.n,)

        if real_domain and key.domain == "r2c":
            def host_fft(ar, ai):
                del ai  # real by declaration (domain="r2c")
                y = np.fft.rfft(np.asarray(ar).astype(np.float64),
                                axis=-1)
                return (y.real.astype(np.float32),
                        y.imag.astype(np.float32))
        elif real_domain:
            n = key.n

            def host_fft(ar, ai):
                y = np.fft.irfft(
                    np.asarray(ar).astype(np.float64)
                    + 1j * np.asarray(ai).astype(np.float64),
                    n=n, axis=-1)
                return (y.astype(np.float32),
                        np.zeros_like(y, np.float32))
        else:
            def host_fft(ar, ai):
                y = np.fft.fft(np.asarray(ar).astype(np.complex128)
                               + 1j * np.asarray(ai).astype(
                                   np.complex128),
                               axis=-1)
                if idx is not None:
                    y = y[..., idx]
                return (y.real.astype(np.float32),
                        y.imag.astype(np.float32))

        out_struct = (jax.ShapeDtypeStruct(out_shape, np.float32),
                      jax.ShapeDtypeStruct(out_shape, np.float32))

        def numpy_run(xr, xi):
            return jax.pure_callback(host_fft, out_struct, xr, xi)

        return numpy_run

    raise ValueError(f"unknown degradation rung {rung!r}")


def _note_demotion(plan, from_variant: str, rung: str,
                   exc: BaseException, kind: FaultKind,
                   skipped: list) -> None:
    """Record ONE demotion: the rung that actually SERVED, with the
    fault that evicted `from_variant` as the reason and any rungs that
    were tried and failed on the way in `skipped` — the trail never
    claims a rung that never ran."""
    from ..plans import cache
    from ..plans.core import warn

    record = {
        "from": from_variant,
        "to": rung,
        "kind": kind.value,
        "reason": f"{type(exc).__name__}: {str(exc)[:200]}",
    }
    if skipped:
        record["skipped"] = list(skipped)
    plan.degraded = True
    plan.demotions.append(record)
    from ..obs import events, metrics

    metrics.inc("pifft_demotions_total", to=rung)
    events.emit("demotion",
                cell={"n": plan.key.n, "variant": from_variant}, **record)
    warn(f"plan DEGRADED {from_variant} -> {rung} for "
         f"{plan.key.token()} ({kind.value}: {record['reason']})"
         + (f" [also failed: {'; '.join(skipped)}]" if skipped else "")
         + " — results stay correct; performance does not")
    # record the demotion in the IN-PROCESS plan cache only: a demotion
    # is a property of this session's environment, and persisting it
    # would taint every future (possibly healthy) session with
    # degraded=True — and let an injected chaos fault poison the user's
    # real plan store.  The disk record keeps the tuned winner; the
    # session-visible trail lives on the memoized plan, the warn line,
    # and the bench record's degraded tags.
    cache.memoize(plan)


def promote_precision(plan, observed_err: float,
                      budget: float) -> "str | None":
    """Walk the plan ONE rung UP the precision chain — the degrade
    subsystem's first quality-direction rung (docs/PRECISION.md).

    Called when a sampled served batch's relative error `observed_err`
    exceeded `budget` (the plan's current mode's contract,
    ops.precision.error_budget).  The plan's served mode
    (``params["precision"]``, falling back to the key's) moves to the
    next TIGHTER mode (bf16 -> default -> split3 -> fp32); the cached
    executor is dropped so the next ``plan.fn`` rebuilds at the
    promoted mode; and the step is recorded as a demotion — degraded
    stays true, the record carries ``direction: "up"`` and the rung
    name ``precision:<mode>`` — because a plan no longer serving what
    it was tuned as must never read as healthy, even when the move
    bought accuracy rather than survival.  Returns the promoted mode,
    or None when already at the top (fp32/highest: nothing tighter
    exists — the caller serves the result tagged, the honest best).

    Like every demotion the record lands in the IN-PROCESS cache only:
    a budget violation is a property of this session's traffic, and
    persisting it would taint future sessions (see _note_demotion)."""
    from ..ops import precision as prec_mod
    from ..plans import cache
    from ..plans.core import warn

    mode = plan.effective_precision()
    nxt = prec_mod.promote(mode)
    if nxt is None:
        warn(f"precision budget violated at the top of the chain "
             f"({mode}: rel err {observed_err:.3e} > budget "
             f"{budget:.1e}) — nothing tighter to promote to; serving "
             f"tagged degraded")
        return None
    rung = f"{PRECISION_RUNG_PREFIX}{nxt}"
    record = {
        "from": mode,
        "to": rung,
        "kind": "quality",
        "direction": "up",
        "reason": (f"rel err {observed_err:.3e} > budget {budget:.1e} "
                   f"for mode {mode!r}"),
    }
    plan.degraded = True
    plan.demotions.append(record)
    plan.params = dict(plan.params, precision=nxt)
    plan._fn = None  # rebuild the executor at the promoted mode
    from ..obs import events, metrics

    metrics.inc("pifft_demotions_total", to=rung)
    events.emit("demotion",
                cell={"n": plan.key.n, "variant": plan.variant},
                **record)
    warn(f"plan PROMOTED {mode} -> {nxt} (precision, UP) for "
         f"{plan.key.token()} ({record['reason']}) — accuracy is "
         f"restored; the tuned bytes-halving is not")
    cache.memoize(plan)
    return nxt


def note_collective_escape(label: str, exc: BaseException,
                           kind: FaultKind, plans=()) -> dict:
    """Record ONE transport demotion: a supervised collective at `label`
    was abandoned (or its devices reported unhealthy) and the run
    escaped onto the communication-free pi-path.  Returns the demotion
    record (``{"from": "all_to_all", "to": "collective_free", ...}``)
    and tags it onto every plan in `plans` exactly like a kernel
    demotion — a run that escaped is never mistaken for a healthy one.
    """
    from ..plans import cache
    from ..plans.core import warn

    record = {
        "from": "all_to_all",
        "to": COLLECTIVE_FREE_RUNG,
        "kind": kind.value,
        "reason": f"{type(exc).__name__}: {str(exc)[:200]}",
        "site": label,
    }
    for plan in plans:
        plan.degraded = True
        plan.demotions.append(dict(record))
        # in-process cache only, like _note_demotion: an escape is a
        # property of this session's mesh, not of the tuned kernel
        cache.memoize(plan)
    from ..obs import events, metrics

    metrics.inc("pifft_demotions_total", to=COLLECTIVE_FREE_RUNG)
    events.emit("demotion", cell={"site": label}, **record)
    warn(f"collective ESCAPED all_to_all -> {COLLECTIVE_FREE_RUNG} at "
         f"{label} ({kind.value}: {record['reason']}) — per-chip local "
         f"work with one final host-side reorder; results stay "
         f"bit-identical, the ICI transpose does not run")
    return record


def resilient_executor(plan, raw: Callable) -> Callable:
    """Wrap a plan's raw executor with the degradation chain.

    CAPACITY/PERMANENT faults from the current executor walk the chain
    downward (each rung's own such faults continue the walk); TRANSIENT
    faults re-raise untouched for the retry layer.  The walk is
    STICKY: once a rung serves, later calls start there — a dead
    kernel is never re-traced per call, the demotion is recorded once
    (for the rung that served, with the failed intermediates in its
    ``skipped`` list), and the trail only ever moves down.  The last
    rung's failure propagates — when even the numpy reference cannot
    run there is nothing honest left to serve."""
    state = {"fn": raw, "variant": plan.variant}

    def run(xr, xi):
        try:
            return state["fn"](xr, xi)
        except Exception as e:
            kind = classify(e)
            if kind is FaultKind.TRANSIENT:
                raise
            exc, last, skipped = e, kind, []
            for rung in _rungs_after(state["variant"]):
                try:
                    fn = build_rung(plan.key, rung)
                    out = fn(xr, xi)
                except Exception as e2:
                    k2 = classify(e2)
                    if k2 is FaultKind.TRANSIENT:
                        raise
                    skipped.append(f"{rung}: {k2.value} "
                                   f"{type(e2).__name__}: {str(e2)[:80]}")
                    exc, last = e2, k2
                    continue
                _note_demotion(plan, state["variant"], rung, e, kind,
                               skipped)
                state["fn"], state["variant"] = fn, rung
                return out
            raise exc

    return run
