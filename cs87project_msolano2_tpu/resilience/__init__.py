"""Resilience subsystem: classify the fault, retry what is transient,
degrade what is not, and journal so preemption loses one cell, not the
run.

The paper's claim rests on COMPLETED (n, p) sweeps — a single OOM, a
failed Mosaic lowering, or a stuck collective used to abort a sweep or
silently corrupt a row (MULTICHIP_r05 records a real all_to_all
rendezvous hanging 20 s before recovering).  This package is the one
place that discipline lives:

* ``taxonomy`` — :class:`PifftError` subclasses wrapping the backend
                 error zoo (``XlaRuntimeError``, Mosaic lowering,
                 ``RESOURCE_EXHAUSTED``, collective timeout, host
                 desync) and :func:`classify`, which tags any exception
                 TRANSIENT / CAPACITY / PERMANENT.
* ``retry``    — :func:`with_retry` / :func:`call_with_retry`: bounded
                 attempts, exponential backoff + jitter, per-FaultKind
                 policy.  Replaces the harness's old ``run_with_retry``
                 and bench.py's bare excepts.
* ``degrade``  — the plan degradation chain (fourstep -> two-trip rql ->
                 ``jnp.fft.fft`` -> numpy reference) wired into
                 ``plans.core.Plan``; every demotion is recorded on the
                 plan and announced through ``plans.warn`` so a degraded
                 run is never mistaken for a healthy one.
* ``inject``   — fault injection (``PIFFT_FAULT=<site>:<kind>:<prob>``
                 env or the :func:`inject` context manager) with sites
                 in ops/plans/parallel/bench, so every policy above is
                 testable on CPU in tier-1.
* ``watchdog`` — collective supervision (docs/MULTICHIP.md):
                 :func:`collective_watchdog` (warn-only deadline with
                 ``collective_recovered`` accounting) and
                 :func:`supervise_collective` (per-collective
                 heartbeats, straggler notes, and a supervised abort
                 via :class:`CancellationToken` /
                 :class:`CollectiveAborted` that the sharded paths
                 catch to escape onto the communication-free
                 pi-path — the ``collective_free`` degrade rung).
* ``journal``  — atomic per-cell JSONL checkpointing behind
                 ``bench.py --resume`` and the harness sweeps.

See docs/RESILIENCE.md for the full ladder and the chaos-smoke CI gate.
"""

from __future__ import annotations

from .degrade import (  # noqa: F401
    COLLECTIVE_FREE_RUNG,
    DEGRADE_CHAIN,
    note_collective_escape,
    resilient_executor,
)
from .inject import (  # noqa: F401
    KINDS,
    KNOWN_SITES,
    FaultSpec,
    InjectedFault,
    active_specs,
    inject,
    maybe_fault,
)
from .journal import Journal  # noqa: F401
from .retry import (  # noqa: F401
    FAST_POLICY,
    RetryPolicy,
    call_with_retry,
    with_retry,
)
from .taxonomy import (  # noqa: F401
    CapacityError,
    CollectiveAborted,
    CollectiveTimeout,
    FaultKind,
    HostDesyncError,
    LoweringError,
    PifftError,
    TransientBackendError,
    classify,
    wrap,
)
from .watchdog import (  # noqa: F401
    CancellationToken,
    SupervisionReport,
    WatchdogReport,
    collective_watchdog,
    rendezvous_deadline_s,
    supervise_collective,
)
