"""``with_retry``: bounded attempts with exponential backoff + jitter,
policy keyed on the :class:`~.taxonomy.FaultKind` of each failure.

This is the ONE retry loop the project owns — it replaced the harness's
``run_with_retry`` and bench.py's bare excepts.  The defaults encode the
observed failure profile: relay drops ('remote_compile: response body
closed') and worker restarts (UNAVAILABLE for >60 s after a kill) heal
within the 30/60/120 s backoff ladder, so TRANSIENT gets 4 attempts;
CAPACITY and PERMANENT get exactly 1 — an OOM retried is an OOM again,
and the degradation chain (resilience.degrade), not repetition, is the
answer.  ``sleep``/``rng`` are injectable so tests assert the exact
schedule against a mock clock.
"""

from __future__ import annotations

import dataclasses
import functools
import random
import sys
import time
from typing import Callable, Optional

from .taxonomy import FaultKind, classify

#: attempts per kind when the policy does not override them
DEFAULT_ATTEMPTS = {
    FaultKind.TRANSIENT: 4,
    FaultKind.CAPACITY: 1,
    FaultKind.PERMANENT: 1,
}


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """How many attempts each FaultKind earns and how long to wait.

    Backoff before retry ``i`` (1-based) is
    ``base_s * factor**(i-1) * (1 + jitter * u)`` with ``u`` uniform in
    [0, 1), capped at ``max_backoff_s`` — exponential so a restarting
    worker gets its >60 s, jittered so parallel sweep shards do not
    reconnect in lockstep."""

    attempts: dict = dataclasses.field(
        default_factory=lambda: dict(DEFAULT_ATTEMPTS))
    base_s: float = 30.0
    factor: float = 2.0
    jitter: float = 0.25
    max_backoff_s: float = 600.0

    def attempts_for(self, kind: FaultKind) -> int:
        return max(int(self.attempts.get(kind,
                                         DEFAULT_ATTEMPTS[kind])), 1)

    def backoff_s(self, retry_index: int, u: float) -> float:
        """Pause before the `retry_index`-th retry (1-based); `u` is the
        caller's uniform sample so schedules are testable."""
        raw = self.base_s * (self.factor ** (retry_index - 1))
        return min(raw * (1.0 + self.jitter * u), self.max_backoff_s)


#: a policy for interactive/smoke contexts where sleeping 30 s on a
#: blip would cost more than the retry saves
FAST_POLICY = RetryPolicy(base_s=0.05, max_backoff_s=1.0)


def call_with_retry(fn: Callable, *args,
                    policy: Optional[RetryPolicy] = None,
                    on_retry: Optional[Callable] = None,
                    label: str = "",
                    sleep: Callable = time.sleep,
                    rng: Callable = random.random,
                    **kwargs):
    """``fn(*args, **kwargs)`` under `policy`.

    Each failure is classified; kinds whose attempt budget is 1 (the
    CAPACITY/PERMANENT default — ValueError's cell-infeasibility
    contract rides on this) re-raise immediately, TRANSIENT faults are
    retried with exponential backoff + jitter until their budget is
    spent, then re-raised.  ``on_retry(exc, attempt, pause_s)`` runs
    before each pause (the harness resets its timing-program warm state
    there).  The attempt budget is per-kind within one call: a fault of
    a new kind draws from that kind's own budget."""
    policy = policy or RetryPolicy()
    used: dict = {}
    while True:
        try:
            return fn(*args, **kwargs)
        except Exception as e:
            kind = classify(e)
            used[kind] = used.get(kind, 0) + 1
            if used[kind] >= policy.attempts_for(kind):
                from ..obs import events

                events.emit("retry_exhausted", label=label,
                            kind=kind.value, attempts=used[kind],
                            error=f"{type(e).__name__}: {str(e)[:200]}")
                raise
            pause = policy.backoff_s(used[kind], rng())
            from ..obs import events, metrics

            metrics.inc("pifft_retries_total", kind=kind.value)
            events.emit("retry", label=label, kind=kind.value,
                        attempt=used[kind], pause_s=round(pause, 3),
                        error=f"{type(e).__name__}: {str(e)[:200]}")
            if on_retry is not None:
                on_retry(e, used[kind], pause)
            else:
                print(f"# {kind.value} fault"
                      + (f" in {label}" if label else "")
                      + f" ({type(e).__name__}: {str(e)[:120]}); retry "
                        f"{used[kind]}/{policy.attempts_for(kind) - 1} "
                        f"in {pause:.1f}s", file=sys.stderr)
            sleep(pause)


def with_retry(fn: Optional[Callable] = None, *,
               policy: Optional[RetryPolicy] = None,
               on_retry: Optional[Callable] = None,
               label: str = "",
               sleep: Callable = time.sleep,
               rng: Callable = random.random):
    """Decorator form of :func:`call_with_retry`.

    ``@with_retry`` bare or ``@with_retry(policy=..., on_retry=...)``;
    the wrapped callable retries per the policy on every call."""

    def deco(f: Callable) -> Callable:
        @functools.wraps(f)
        def run(*args, **kwargs):
            return call_with_retry(
                f, *args, policy=policy, on_retry=on_retry,
                label=label or getattr(f, "__name__", ""),
                sleep=sleep, rng=rng, **kwargs)

        return run

    return deco if fn is None else deco(fn)
