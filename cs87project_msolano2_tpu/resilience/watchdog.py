"""Collective watchdog: a configurable rendezvous deadline surfaced as
a structured :class:`~.taxonomy.CollectiveTimeout` diagnostic.

MULTICHIP_r05 recorded the raw form of the problem: an all_to_all
rendezvous hung for 20 s, the ONLY signal was a C++ ``rendezvous.cc``
log line ("This thread ... may be stuck"), and eight seconds later a
second line declared it a false positive.  Nothing in the run's own
output said either thing.  The watchdog makes the deadline explicit and
ours: wrap a collective region in :func:`collective_watchdog` and a
stall past the (configurable, logged) deadline emits a structured
``CollectiveTimeout`` warning through ``plans.warn`` while the region
runs — and, in ``strict`` mode, raises :class:`CollectiveTimeout` once
it completes, so the retry layer can classify it (TRANSIENT) instead of
a human grepping C++ logs.

No wall clocks are read (the timing layer owns those — PIF102): the
watchdog thread counts deadline-sized waits on an event, so "recovered
after >= k x deadline" is derived purely from the wait count.
"""

from __future__ import annotations

import os
import threading
from contextlib import contextmanager

from .inject import maybe_fault
from .taxonomy import CollectiveTimeout

#: default rendezvous deadline; the C++ warner fires at a hardcoded
#: 20 s, so a 60 s default stays quiet through the r05-style
#: stuck-then-recovered window and only speaks when something is
#: genuinely wedged
DEFAULT_RENDEZVOUS_DEADLINE_S = 60.0


def rendezvous_deadline_s() -> float:
    """The configured rendezvous deadline
    (``PIFFT_RENDEZVOUS_DEADLINE_S`` overrides the default)."""
    raw = os.environ.get("PIFFT_RENDEZVOUS_DEADLINE_S", "").strip()
    try:
        return float(raw) if raw else DEFAULT_RENDEZVOUS_DEADLINE_S
    except ValueError:
        from ..plans.core import warn

        warn(f"PIFFT_RENDEZVOUS_DEADLINE_S={raw!r} is not a number; "
             f"using {DEFAULT_RENDEZVOUS_DEADLINE_S}")
        return DEFAULT_RENDEZVOUS_DEADLINE_S


class WatchdogReport:
    """What the watchdog saw: ``fired`` deadline expiries (0 = the
    region finished inside its deadline)."""

    def __init__(self, label: str, deadline_s: float):
        self.label = label
        self.deadline_s = deadline_s
        self.fired = 0


@contextmanager
def collective_watchdog(label: str, deadline_s: float | None = None,
                        strict: bool = False):
    """Arm a rendezvous deadline around a collective region.

    While the with-block runs, a daemon thread wakes every `deadline_s`
    (default :func:`rendezvous_deadline_s`) and emits a structured
    ``CollectiveTimeout`` warning naming the region — the in-band
    replacement for rendezvous.cc's buried "may be stuck" line.  On
    exit, a region that overran at least one deadline either raises
    :class:`CollectiveTimeout` (``strict=True``) or warns that it
    recovered (the r05 false-positive case, now visible in OUR output).
    Yields the live :class:`WatchdogReport`."""
    from ..plans.core import warn

    deadline = float(deadline_s if deadline_s is not None
                     else rendezvous_deadline_s())
    maybe_fault("collective")
    report = WatchdogReport(label, deadline)
    done = threading.Event()

    def watch():
        from ..obs import metrics

        while not done.wait(deadline):
            report.fired += 1
            metrics.inc("pifft_watchdog_fires_total", label=label)
            warn(f"CollectiveTimeout: {label} still waiting after "
                 f">= {report.fired * deadline:.0f}s (deadline "
                 f"{deadline:.0f}s; PIFFT_RENDEZVOUS_DEADLINE_S "
                 f"overrides)")

    thread = threading.Thread(target=watch, name=f"pifft-watchdog-{label}",
                              daemon=True)
    thread.start()
    from ..obs import spans

    try:
        # the collective span: the watched region shows up named in the
        # trace/event stream, with how many deadlines it overran
        with spans.span(f"collective:{label}",
                        deadline_s=deadline) as sp:
            yield report
            sp.set(fired=report.fired)
    finally:
        done.set()
        thread.join(timeout=deadline + 1.0)
    if report.fired:
        from ..obs import events

        events.emit("collective_timeout", label=label,
                    fired=report.fired, deadline_s=deadline,
                    recovered=not strict)
        if strict:
            raise CollectiveTimeout(
                f"{label} exceeded its rendezvous deadline "
                f"({report.fired} x {deadline:.0f}s)")
        warn(f"{label} recovered after >= {report.fired * deadline:.0f}s "
             f"(stuck-then-unstuck, the MULTICHIP_r05 pattern; raise "
             f"PIFFT_RENDEZVOUS_DEADLINE_S if this deadline is too "
             f"twitchy)")
