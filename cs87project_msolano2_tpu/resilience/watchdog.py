"""Collective supervision: per-collective heartbeats, recovered-stall
accounting, and a supervised abort that turns a wedged rendezvous into
a recoverable :class:`~.taxonomy.CollectiveAborted` instead of a hang.

MULTICHIP_r05 recorded the raw form of the problem: an all_to_all
rendezvous hung for 20 s, the ONLY signal was a C++ ``rendezvous.cc``
log line ("This thread ... may be stuck"), and eight seconds later a
second line declared it a false positive.  Nothing in the run's own
output said either thing — and nothing in the stack could have done
anything about it had the hang been real.

Two layers now exist:

* :func:`collective_watchdog` (PR 4, kept) — a warn-only deadline: wrap
  a collective region and a stall past the (validated, logged) deadline
  emits a structured ``CollectiveTimeout`` warning while the region
  runs; a region that recovers emits a ``collective_recovered`` event
  carrying the deadline-wait count (the r05 stuck-then-unstuck window,
  now visible in OUR output instead of a rendezvous.cc false-positive
  line).
* :func:`supervise_collective` (this PR) — the supervisor: the region
  runs in a worker thread with a heartbeat armed per deadline; each
  expiry is counted, warned, and emitted (straggler accounting across
  co-armed regions); past ``abort_waits`` expiries the supervisor
  cancels the region's :class:`CancellationToken` and raises
  :class:`CollectiveAborted`, which the resilient sharded entry points
  (parallel/escape.py) catch to re-plan onto the communication-free
  pi-path.  Safe points: the token is checked before the region
  dispatches and may be polled by cooperative callers
  (``token.checkpoint()``); a worker already blocked inside XLA cannot
  be interrupted — it is ABANDONED (daemon thread) and its late
  completion, if any, is emitted as ``collective_late_completion``.

No wall clocks are read (the timing layer owns those — PIF102): the
heartbeat thread counts deadline-sized waits on an event, so
"recovered after >= k x deadline" is derived purely from the wait
count.

Deadline validation (strict-mode contract): ``PIFFT_RENDEZVOUS_
DEADLINE_S`` is parsed ONCE at arm time.  A non-numeric, non-finite,
or non-positive value warns and serves the default — or, under
``strict=True``, raises ``ValueError`` at arm time instead of letting
a bad knob silently disarm the deadline.  The parsed value is carried
in every emitted diagnostic.
"""

from __future__ import annotations

import math
import os
import threading
from contextlib import contextmanager
from typing import Callable, Optional

from .inject import maybe_fault
from .taxonomy import CollectiveAborted, CollectiveTimeout

#: default rendezvous deadline; the C++ warner fires at a hardcoded
#: 20 s, so a 60 s default stays quiet through the r05-style
#: stuck-then-recovered window and only speaks when something is
#: genuinely wedged
DEFAULT_RENDEZVOUS_DEADLINE_S = 60.0

#: default supervised-abort budget: how many whole deadlines a
#: supervised region may overrun before the supervisor abandons it
#: (``PIFFT_COLLECTIVE_ABORT_WAITS`` overrides; the warn-only layers
#: pass None = never abort)
DEFAULT_ABORT_WAITS = 2


def rendezvous_deadline_s(strict: bool = False) -> float:
    """The configured rendezvous deadline, validated ONCE at the call
    (``PIFFT_RENDEZVOUS_DEADLINE_S`` overrides the default).

    A malformed value (non-numeric, non-finite, or <= 0 — a zero
    deadline would busy-spin the heartbeat) warns with the raw AND the
    served value, or raises ``ValueError`` under ``strict=True`` so a
    strict arm point fails at arm time instead of silently running
    with a deadline the operator never asked for."""
    raw = os.environ.get("PIFFT_RENDEZVOUS_DEADLINE_S", "").strip()
    if not raw:
        return DEFAULT_RENDEZVOUS_DEADLINE_S
    try:
        value = float(raw)
    except ValueError:
        value = None
    if value is not None and math.isfinite(value) and value > 0:
        return value
    msg = (f"PIFFT_RENDEZVOUS_DEADLINE_S={raw!r} is not a positive "
           f"finite number of seconds")
    if strict:
        raise ValueError(msg)
    from ..plans.core import warn

    warn(f"{msg}; using the default {DEFAULT_RENDEZVOUS_DEADLINE_S:g}s")
    return DEFAULT_RENDEZVOUS_DEADLINE_S


def abort_waits_default() -> int:
    """The configured supervised-abort budget
    (``PIFFT_COLLECTIVE_ABORT_WAITS`` overrides the default)."""
    raw = os.environ.get("PIFFT_COLLECTIVE_ABORT_WAITS", "").strip()
    try:
        value = int(raw) if raw else DEFAULT_ABORT_WAITS
    except ValueError:
        value = 0
    if value >= 1:
        return value
    from ..plans.core import warn

    warn(f"PIFFT_COLLECTIVE_ABORT_WAITS={raw!r} is not a positive "
         f"integer; using {DEFAULT_ABORT_WAITS}")
    return DEFAULT_ABORT_WAITS


class CancellationToken:
    """Cooperative cancellation for a supervised collective region.

    The supervisor calls :meth:`cancel` when the region overruns its
    abort budget; region code honors it at safe points by calling
    :meth:`checkpoint`, which raises :class:`CollectiveAborted` once
    cancelled.  The built-in safe point is the region's own dispatch
    (``supervise_collective``'s worker checks before calling into the
    region), so a cancellation landing between retries or before the
    collective is entered aborts cleanly without touching XLA."""

    def __init__(self):
        self._event = threading.Event()
        self.reason: Optional[str] = None

    def cancel(self, reason: str) -> None:
        self.reason = reason
        self._event.set()

    def cancelled(self) -> bool:
        return self._event.is_set()

    def checkpoint(self, label: str = "") -> None:
        """Raise :class:`CollectiveAborted` if cancelled — the safe
        point primitive."""
        if self._event.is_set():
            raise CollectiveAborted(
                f"collective region {label or '<unnamed>'} cancelled "
                f"({self.reason})")


class WatchdogReport:
    """What the watchdog saw: ``fired`` deadline expiries (0 = the
    region finished inside its deadline)."""

    def __init__(self, label: str, deadline_s: float):
        self.label = label
        self.deadline_s = deadline_s
        self.fired = 0


class SupervisionReport(WatchdogReport):
    """A supervised region's full accounting: deadline-wait count
    (``fired``), whether the supervisor ``aborted`` it, and whether it
    ``recovered`` (completed after overrunning at least one
    deadline)."""

    def __init__(self, label: str, deadline_s: float,
                 abort_waits: Optional[int]):
        super().__init__(label, deadline_s)
        self.abort_waits = abort_waits
        self.aborted = False
        self.recovered = False


# live supervised/watched regions, label -> report: the straggler view.
# A heartbeat names how many sibling regions armed alongside this one
# have already completed — the one still waiting is the straggler.
_ACTIVE: dict = {}
_ACTIVE_LOCK = threading.Lock()


def _register(report: WatchdogReport) -> None:
    with _ACTIVE_LOCK:
        _ACTIVE[id(report)] = report


def _unregister(report: WatchdogReport) -> None:
    with _ACTIVE_LOCK:
        _ACTIVE.pop(id(report), None)


def _straggler_note(report: WatchdogReport) -> str:
    with _ACTIVE_LOCK:
        waiting = [r.label for r in _ACTIVE.values() if r is not report]
    if not waiting:
        return ""
    return f" (co-armed regions still waiting: {', '.join(waiting)})"


def active_regions() -> list:
    """Labels of the currently armed collective regions (diagnostics)."""
    with _ACTIVE_LOCK:
        return [r.label for r in _ACTIVE.values()]


def _emit_recovered(label: str, fired: int, deadline: float) -> None:
    """The r05 stuck-then-unstuck window, as OUR structured output: a
    ``collective_recovered`` warn event carrying the deadline-wait
    count, instead of a rendezvous.cc false-positive line."""
    from ..obs import events, metrics
    from ..plans.core import warn

    metrics.inc("pifft_collective_recoveries_total", label=label)
    events.emit("collective_recovered", label=label, waits=fired,
                deadline_s=deadline)
    warn(f"collective_recovered: {label} completed after >= "
         f"{fired * deadline:g}s ({fired} x {deadline:g}s deadline "
         f"waits — stuck-then-unstuck, the MULTICHIP_r05 pattern; raise "
         f"PIFFT_RENDEZVOUS_DEADLINE_S if this deadline is too twitchy)")


@contextmanager
def collective_watchdog(label: str, deadline_s: float | None = None,
                        strict: bool = False):
    """Arm a rendezvous deadline around a collective region (warn-only
    layer — :func:`supervise_collective` adds the abort).

    While the with-block runs, a daemon thread wakes every `deadline_s`
    (default :func:`rendezvous_deadline_s`, validated at THIS arm point
    — under ``strict`` a malformed env knob raises here, not never) and
    emits a structured ``CollectiveTimeout`` warning naming the region.
    On exit, a region that overran at least one deadline either raises
    :class:`CollectiveTimeout` (``strict=True``) or emits the
    ``collective_recovered`` event with its wait count.  Yields the
    live :class:`WatchdogReport`."""
    from ..plans.core import warn

    deadline = float(deadline_s if deadline_s is not None
                     else rendezvous_deadline_s(strict=strict))
    maybe_fault("collective")
    report = WatchdogReport(label, deadline)
    done = threading.Event()

    def watch():
        from ..obs import metrics

        while not done.wait(deadline):
            report.fired += 1
            metrics.inc("pifft_watchdog_fires_total", label=label)
            warn(f"CollectiveTimeout: {label} still waiting after "
                 f">= {report.fired * deadline:g}s (deadline "
                 f"{deadline:g}s; PIFFT_RENDEZVOUS_DEADLINE_S "
                 f"overrides){_straggler_note(report)}")

    thread = threading.Thread(target=watch, name=f"pifft-watchdog-{label}",
                              daemon=True)
    _register(report)
    thread.start()
    from ..obs import spans

    try:
        # the collective span: the watched region shows up named in the
        # trace/event stream, with how many deadlines it overran
        with spans.span(f"collective:{label}",
                        deadline_s=deadline) as sp:
            yield report
            sp.set(fired=report.fired)
    finally:
        done.set()
        thread.join(timeout=deadline + 1.0)
        _unregister(report)
    if report.fired:
        if strict:
            from ..obs import events

            events.emit("collective_timeout", label=label,
                        fired=report.fired, deadline_s=deadline,
                        recovered=False)
            raise CollectiveTimeout(
                f"{label} exceeded its rendezvous deadline "
                f"({report.fired} x {deadline:g}s)")
        _emit_recovered(label, report.fired, deadline)


def supervise_collective(fn: Callable, label: str,
                         deadline_s: float | None = None,
                         abort_waits: Optional[int] = None,
                         token: Optional[CancellationToken] = None,
                         strict: bool = False):
    """Run ``fn()`` as a SUPERVISED collective region; returns
    ``(result, SupervisionReport)``.

    The region runs in a daemon worker thread while the supervisor
    counts deadline-sized waits.  Each expiry is a heartbeat: warned,
    counted (``pifft_watchdog_fires_total``), and emitted
    (``collective_heartbeat``), with the straggler note naming any
    co-armed regions still waiting.  After ``abort_waits`` expiries
    (default :func:`abort_waits_default`; the region's cancellation
    `token` is cancelled first, so a cooperative region aborts at its
    next safe point) the supervisor stops waiting and raises
    :class:`CollectiveAborted` — the caller's cue to take the
    communication-free escape path (parallel/escape.py).  A worker
    blocked inside XLA is abandoned; if it completes later its result
    is discarded and a ``collective_late_completion`` event records the
    false-positive window.

    A region that completes after >= 1 wait emits
    ``collective_recovered`` with its wait count; exceptions from the
    region propagate unchanged (classified by the retry layer)."""
    from ..obs import events, metrics, spans
    from ..plans.core import warn

    deadline = float(deadline_s if deadline_s is not None
                     else rendezvous_deadline_s(strict=strict))
    if abort_waits is None:
        abort_waits = abort_waits_default()
    token = token or CancellationToken()
    report = SupervisionReport(label, deadline, abort_waits)
    done = threading.Event()
    box: dict = {}

    def work():
        try:
            # the stall injection site lives INSIDE the supervised
            # region: an injected stall delays here, the heartbeat
            # fires, and the whole recovery loop is exercised on CPU
            maybe_fault("collective")
            # safe point: never dispatch into an already-cancelled
            # region
            token.checkpoint(label)
            box["value"] = fn()
        except BaseException as e:  # noqa: BLE001 — relayed to caller
            box["error"] = e
        finally:
            done.set()
            if "value" in box and token.cancelled():
                # the abandoned worker finished anyway — the r05
                # false-positive shape, recorded instead of lost
                events.emit("collective_late_completion", label=label,
                            deadline_s=deadline)

    worker = threading.Thread(target=work,
                              name=f"pifft-collective-{label}",
                              daemon=True)
    _register(report)
    try:
        with spans.span(f"collective:{label}", deadline_s=deadline,
                        supervised=True) as sp:
            worker.start()
            while not done.wait(deadline):
                report.fired += 1
                metrics.inc("pifft_watchdog_fires_total", label=label)
                events.emit("collective_heartbeat", label=label,
                            waits=report.fired, deadline_s=deadline,
                            abort_waits=abort_waits)
                warn(f"CollectiveTimeout: {label} still waiting after "
                     f">= {report.fired * deadline:g}s (deadline "
                     f"{deadline:g}s, abort after {abort_waits} "
                     f"waits){_straggler_note(report)}")
                if report.fired >= abort_waits:
                    report.aborted = True
                    token.cancel(
                        f"{label} overran {report.fired} x "
                        f"{deadline:g}s deadline waits")
                    metrics.inc("pifft_collective_aborts_total",
                                label=label)
                    events.emit("collective_abandoned", label=label,
                                waits=report.fired, deadline_s=deadline)
                    warn(f"collective ABANDONED: {label} after "
                         f"{report.fired} x {deadline:g}s — "
                         f"supervisor aborting; the wedged worker is "
                         f"left behind (daemon) and a late completion "
                         f"will be recorded")
                    aborted = CollectiveAborted(
                        f"{label} abandoned after {report.fired} x "
                        f"{deadline:g}s deadline waits "
                        f"(abort_waits={abort_waits}; "
                        f"PIFFT_COLLECTIVE_ABORT_WAITS overrides)")
                    # the report rides the exception so the escape
                    # layer can carry the wait count into its trail
                    aborted.report = report
                    raise aborted
            sp.set(fired=report.fired, aborted=report.aborted)
    finally:
        _unregister(report)
    if "error" in box:
        raise box["error"]
    if report.fired:
        report.recovered = True
        _emit_recovered(label, report.fired, deadline)
    return box["value"], report
