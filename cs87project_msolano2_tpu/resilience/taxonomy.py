"""Typed failure taxonomy: every fault the measurement stack can hit,
named, and :func:`classify` mapping any raised exception onto the three
recovery classes the policies key on.

The backend error zoo is stringly typed — ``XlaRuntimeError`` carries
gRPC-style status words ("RESOURCE_EXHAUSTED", "UNAVAILABLE"), Mosaic
lowering failures arrive as RuntimeError text, the axon relay drops
connections with bare socket messages — so classification is by
exception TYPE first (our own :class:`PifftError` subclasses carry their
kind; ConnectionError/MemoryError/ValueError have unambiguous meanings)
and message PATTERN second.  The pattern tables double as documentation
of every failure signature observed in the bench/sweep logs
(BENCH_r*.json, MULTICHIP_r*.json, harness history).
"""

from __future__ import annotations

import enum
import re


class FaultKind(enum.Enum):
    """What a fault means for the recovery policy.

    TRANSIENT — the operation is fine, the moment was not (relay drop,
    worker restart, stuck-then-recovered collective): retry with
    backoff.  CAPACITY — the configuration asks for more memory than
    the device has (HBM OOM, scoped-VMEM overflow): retrying is futile,
    demote to a smaller/leaner plan.  PERMANENT — the program itself is
    wrong for this backend (Mosaic lowering rejection, invalid
    argument, infeasible cell): neither retry nor the same plan again.
    """

    TRANSIENT = "transient"
    CAPACITY = "capacity"
    PERMANENT = "permanent"


class PifftError(RuntimeError):
    """Base of the typed failure taxonomy; ``kind`` drives policy."""

    kind = FaultKind.PERMANENT


class TransientBackendError(PifftError):
    """Infrastructure blinked: relay connection drop, worker restart,
    UNAVAILABLE / DEADLINE_EXCEEDED status — retry with backoff."""

    kind = FaultKind.TRANSIENT


class CapacityError(PifftError):
    """The configuration exceeds device memory (RESOURCE_EXHAUSTED,
    HBM OOM, the 16 MB scoped-VMEM cliff) — demote, don't retry."""

    kind = FaultKind.CAPACITY


class LoweringError(PifftError):
    """The kernel cannot lower on this backend (Mosaic rejection,
    unimplemented op) — permanent for this plan, demote."""

    kind = FaultKind.PERMANENT


class CollectiveTimeout(TransientBackendError):
    """A collective rendezvous exceeded its deadline (the MULTICHIP_r05
    all_to_all hang, surfaced structurally instead of as a buried C++
    log line).  Transient: the r05 hang recovered by itself."""


class CollectiveAborted(CollectiveTimeout):
    """A supervised collective region was ABANDONED: it overran its
    abort budget (``abort_waits`` x deadline) and the supervisor gave
    up waiting and cancelled it (resilience.watchdog.supervise_
    collective).  Still TRANSIENT for the classifier — the operation
    was fine, the rendezvous was not — but callers that can re-plan
    catch it explicitly and take the communication-free escape path
    instead of retrying the same wedge (parallel/escape.py,
    docs/MULTICHIP.md)."""


class HostDesyncError(PifftError):
    """Multi-host processes disagree about the job topology (process
    count / global device mismatch) — no local retry can fix it."""

    kind = FaultKind.PERMANENT


# message signatures, checked in order: CAPACITY before TRANSIENT
# (an OOM report may also mention the op that was being retried), both
# before the PERMANENT default.  Sources: XlaRuntimeError status words,
# Mosaic diagnostics, and the relay/worker failures the harness logs
# (run_with_retry history: 'remote_compile: response body closed',
# UNAVAILABLE for >60 s after a worker kill).
_CAPACITY_PAT = re.compile(
    r"RESOURCE_EXHAUSTED|out of memory|\bOOM\b|attempting to allocate"
    r"|exceeds the limit|ran out of memory|vmem|scoped\s+memory"
    r"|allocation.*fail",
    re.IGNORECASE)
_TRANSIENT_PAT = re.compile(
    r"UNAVAILABLE|DEADLINE_EXCEEDED|\bABORTED\b|\bCANCELLED\b"
    r"|connection (reset|refused|closed|aborted)|response body closed"
    r"|broken pipe|socket|remote_compile|rendezvous|heartbeat"
    r"|coordination service|preempt|worker.*(restart|unreachable)"
    r"|temporarily",
    re.IGNORECASE)
_LOWERING_PAT = re.compile(
    r"mosaic|lowering|UNIMPLEMENTED|unsupported.*(lower|primitive|op)"
    r"|cannot lower",
    re.IGNORECASE)
_DESYNC_PAT = re.compile(
    r"desync|process (id|index|count).*mismatch"
    r"|different number of (processes|devices)|global device",
    re.IGNORECASE)


def _message(exc: BaseException) -> str:
    return f"{type(exc).__name__}: {exc}"


def classify(exc: BaseException) -> FaultKind:
    """Map any exception to the FaultKind the recovery policies key on.

    Our own :class:`PifftError` subclasses carry their kind; unambiguous
    builtin types short-circuit (MemoryError is CAPACITY, connection/
    timeout errors are TRANSIENT, ValueError/TypeError — the "this cell
    is infeasible" contract the harness relies on — are PERMANENT);
    everything else is classified by message signature, defaulting to
    PERMANENT (the safe default: an unknown fault must not be retried
    into a corrupted row)."""
    if isinstance(exc, PifftError):
        return exc.kind
    if isinstance(exc, MemoryError):
        return FaultKind.CAPACITY
    if isinstance(exc, (ConnectionError, TimeoutError, BrokenPipeError,
                        EOFError)):
        return FaultKind.TRANSIENT
    if isinstance(exc, (ValueError, TypeError, NotImplementedError,
                        AssertionError)):
        return FaultKind.PERMANENT
    msg = _message(exc)
    if _CAPACITY_PAT.search(msg):
        return FaultKind.CAPACITY
    if _TRANSIENT_PAT.search(msg):
        return FaultKind.TRANSIENT
    return FaultKind.PERMANENT


_WRAPPERS = {
    FaultKind.TRANSIENT: TransientBackendError,
    FaultKind.CAPACITY: CapacityError,
    FaultKind.PERMANENT: LoweringError,
}


def wrap(exc: BaseException) -> PifftError:
    """The typed form of `exc`: PifftErrors pass through; anything else
    is wrapped in the subclass matching its classification (PERMANENT
    faults get :class:`LoweringError` when the message looks like a
    lowering rejection, :class:`HostDesyncError` on a desync signature,
    plain :class:`PifftError` otherwise), with ``__cause__`` preserved
    so the original traceback survives."""
    if isinstance(exc, PifftError):
        return exc
    kind = classify(exc)
    cls = _WRAPPERS[kind]
    if kind is FaultKind.PERMANENT:
        msg = _message(exc)
        if _DESYNC_PAT.search(msg):
            cls = HostDesyncError
        elif not _LOWERING_PAT.search(msg):
            cls = PifftError
    wrapped = cls(_message(exc))
    wrapped.__cause__ = exc
    return wrapped
